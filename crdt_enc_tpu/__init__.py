"""crdt-enc-tpu: a TPU-native encrypted-CRDT persistence/replication framework.

Capability surface of chpio/crdt-enc (see SURVEY.md), rebuilt JAX-first:
immutable content-addressed op/state files on a passively synced filesystem,
LUKS-style layered key management, and bulk merge/compaction running as
batched tensor folds on TPU.

The primary surface re-exports lazily (PEP 562) so ``import crdt_enc_tpu``
stays light — jax loads only when the accelerator or kernels are touched::

    from crdt_enc_tpu import Core, OpenOptions, orset_adapter
"""

import importlib

__version__ = "0.1.0"

# name -> submodule that defines it (resolved on first attribute access)
_LAZY = {
    "Core": "core",
    "CoreError": "core",
    "OpenOptions": "core",
    "empty_adapter": "core",
    "gcounter_adapter": "core",
    "lwwmap_adapter": "core",
    "mvreg_adapter": "core",
    "orset_adapter": "core",
    "pncounter_adapter": "core",
    "TpuAccelerator": "parallel",
    "canonical_bytes": "models",
}

__all__ = ["__version__", "enable_compilation_cache", *sorted(_LAZY)]


def enable_compilation_cache(path: str | None = None) -> str:
    """Turn on JAX's persistent compilation cache for the fold kernels.

    First compilation of a fold shape costs tens of seconds on TPU; a
    compaction process that exits afterwards pays it again next run.  With
    the cache enabled, recompiles of previously-seen shapes load from disk
    in milliseconds — call this once at process start (before the first
    fold) in any deployment that runs compactions as short-lived jobs.
    Returns the cache directory used.
    """
    import os

    import jax

    if path is None:
        path = os.environ.get(
            "CRDT_ENC_TPU_COMPILE_CACHE",
            os.path.join(
                os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
                "crdt_enc_tpu", "jax_cache",
            ),
        )
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    # jax initializes the cache module lazily at the FIRST compile and
    # then latches: enabling a dir after any compile has happened would
    # silently do nothing.  Reset so the new dir takes effect now.
    try:
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc,
        )

        _cc.reset_cache()
    except Exception:  # pragma: no cover - cache module reshuffles
        pass
    return path


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    return getattr(importlib.import_module(f".{mod}", __name__), name)


def __dir__():
    return sorted(list(globals()) + list(_LAZY))

"""crdt-enc-tpu: a TPU-native encrypted-CRDT persistence/replication framework.

Capability surface of chpio/crdt-enc (see SURVEY.md), rebuilt JAX-first:
immutable content-addressed op/state files on a passively synced filesystem,
LUKS-style layered key management, and bulk merge/compaction running as
batched tensor folds on TPU.
"""

__version__ = "0.1.0"

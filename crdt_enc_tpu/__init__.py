"""crdt-enc-tpu: a TPU-native encrypted-CRDT persistence/replication framework.

Capability surface of chpio/crdt-enc (see SURVEY.md), rebuilt JAX-first:
immutable content-addressed op/state files on a passively synced filesystem,
LUKS-style layered key management, and bulk merge/compaction running as
batched tensor folds on TPU.

The primary surface re-exports lazily (PEP 562) so ``import crdt_enc_tpu``
stays light — jax loads only when the accelerator or kernels are touched::

    from crdt_enc_tpu import Core, OpenOptions, orset_adapter
"""

import importlib

__version__ = "0.1.0"

# name -> submodule that defines it (resolved on first attribute access)
_LAZY = {
    "Core": "core",
    "CoreError": "core",
    "OpenOptions": "core",
    "empty_adapter": "core",
    "gcounter_adapter": "core",
    "lwwmap_adapter": "core",
    "mvreg_adapter": "core",
    "orset_adapter": "core",
    "pncounter_adapter": "core",
    "TpuAccelerator": "parallel",
    "canonical_bytes": "models",
}

__all__ = ["__version__", *sorted(_LAZY)]


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    return getattr(importlib.import_module(f".{mod}", __name__), name)


def __dir__():
    return sorted(list(globals()) + list(_LAZY))

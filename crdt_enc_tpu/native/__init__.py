"""ctypes loader for the native library (builds on demand via make)."""

from __future__ import annotations

import ctypes
import fcntl
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "build", "libcrdtnative.so")
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_error: Exception | None = None  # cached: never retry a failed build per call

u8p = ctypes.POINTER(ctypes.c_uint8)
u64p = ctypes.POINTER(ctypes.c_uint64)


def _build_and_load(target: str, so_path: str, dll_cls, bind_fn):
    """Build one make target under the shared file lock and dlopen it.

    Always invokes make: an incremental no-op when fresh, and source
    edits never silently run stale native code.  The file lock
    serializes concurrent processes (the in-process _lock can't) so one
    never dlopens a half-linked .so.  Building only the requested
    target keeps the libraries independent — e.g. a box without CPython
    dev headers still gets the header-free crypto/codec library even
    though the C-API state library cannot compile there.
    """
    os.makedirs(os.path.join(_HERE, "build"), exist_ok=True)  # lint: effect-ok=blocks (one-shot memoized build; warm() runs it off-loop)
    with open(os.path.join(_HERE, "build", ".lock"), "w") as lk:  # lint: effect-ok=blocks (one-shot memoized build; warm() runs it off-loop)
        fcntl.flock(lk, fcntl.LOCK_EX)
        try:
            subprocess.run(  # lint: effect-ok=blocks (one-shot memoized build; warm() runs it off-loop)
                ["make", "-C", _HERE, target],
                check=True,
                capture_output=True,
                text=True,
            )
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                f"native build failed (exit {e.returncode}):\n"
                f"{e.stdout}\n{e.stderr}"
            ) from e
        lib = dll_cls(so_path)
    bind_fn(lib)
    return lib


def warm() -> None:
    """Build/load both native libraries now, swallowing failures.

    The loaders memoize success *and* failure, so after one ``warm()``
    every later ``load()``/``load_state()`` call is a cached dict hit —
    no ``make`` subprocess, no dlopen.  Event-loop code calls this once
    via ``asyncio.to_thread`` at open (see ``Core.open``) so the
    first-use build never runs on the loop; callers that need the
    library still probe the loaders themselves and fall back to the
    Python paths when the build failed.
    """
    for loader in (load, load_state):
        try:
            loader()
        except Exception:
            pass  # cached by the loader; pure-Python fallbacks take over


def load() -> ctypes.CDLL:
    global _lib, _load_error
    with _lock:
        if _lib is not None:
            return _lib
        if _load_error is not None:
            # a failed build is permanent for this process — callers on hot
            # paths (e.g. the fs op scan) probe per call and must not spawn
            # a failing `make` subprocess every time
            raise _load_error
        try:
            lib = _build_and_load(
                "build/libcrdtnative.so", _SO, ctypes.CDLL, _bind
            )
        except Exception as e:
            # cache ANY load failure (build, dlopen, missing symbol): hot
            # paths probe per call and must never re-spawn make
            _load_error = e
            raise

        _lib = lib
        return lib


_STATE_SO = os.path.join(_HERE, "build", "libcrdtstate.so")
_state_lib: ctypes.PyDLL | None = None
_state_error: Exception | None = None


def load_state() -> ctypes.PyDLL:
    """The C-API state-assembly library (statebuild.cpp).

    Loaded with ``PyDLL`` — calls hold the GIL because the functions
    create Python objects (dicts of a folded state).  Separate from the
    CDLL crypto/codec library, whose calls release the GIL.  Same
    build-on-demand + cached-failure discipline as ``load()``.
    """
    global _state_lib, _state_error
    with _lock:
        if _state_lib is not None:
            return _state_lib
        if _state_error is not None:
            raise _state_error
        try:
            lib = _build_and_load(
                "build/libcrdtstate.so", _STATE_SO, ctypes.PyDLL, _bind_state
            )
        except Exception as e:
            _state_error = e
            raise
        _state_lib = lib
        return lib


def _bind_state(lib) -> None:
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.orset_fresh_fold.argtypes = [
        ctypes.POINTER(ctypes.c_int8), i32p, i32p, i32p, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, i32p,
        ctypes.py_object, ctypes.py_object,
        ctypes.py_object, ctypes.py_object,
    ]
    lib.orset_fresh_fold.restype = ctypes.c_int
    # split fresh fold: rows handle out (counts[2] is the capacity
    # channel for the later take), then a sized copy-out + free
    lib.orset_fold_rows.argtypes = [
        ctypes.POINTER(ctypes.c_int8), i32p, i32p, i32p, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, i32p, i64p,
    ]
    lib.orset_fold_rows.restype = ctypes.c_void_p
    lib.orset_fold_rows_take.argtypes = [
        ctypes.c_void_p, i32p, i32p, i64p, ctypes.c_int64,
        i32p, i32p, i64p, ctypes.c_int64,
    ]
    lib.orset_fold_rows_take.restype = ctypes.c_int
    lib.orset_fold_rows_drop.argtypes = [ctypes.c_void_p]
    lib.orset_fold_rows_drop.restype = None
    lib.dense_clock_dict.argtypes = [i32p, ctypes.c_int64, ctypes.py_object]
    lib.dense_clock_dict.restype = ctypes.py_object
    lib.grouped_rows_dicts.argtypes = [
        i32p, i32p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.py_object, ctypes.py_object, ctypes.py_object,
    ]
    lib.grouped_rows_dicts.restype = ctypes.c_int
    lib.bytes_lens_join.argtypes = [
        ctypes.py_object, u64p, u8p, ctypes.c_int64, ctypes.c_int64
    ]
    lib.bytes_lens_join.restype = ctypes.c_int64
    lib.canon_pack.argtypes = [ctypes.py_object]
    lib.canon_pack.restype = ctypes.py_object


def _bind(lib) -> None:
    lib.hchacha20.argtypes = [u8p, u8p, u8p]
    lib.hchacha20.restype = None
    for name in ("chacha20poly1305_encrypt", "xchacha20poly1305_encrypt"):
        fn = getattr(lib, name)
        fn.argtypes = [
            u8p, u8p, u8p, ctypes.c_uint64, u8p, ctypes.c_uint64, u8p
        ]
        fn.restype = None
    for name in ("chacha20poly1305_decrypt", "xchacha20poly1305_decrypt"):
        fn = getattr(lib, name)
        fn.argtypes = [
            u8p, u8p, u8p, ctypes.c_uint64, u8p, ctypes.c_uint64, u8p
        ]
        fn.restype = ctypes.c_int
    lib.xchacha20poly1305_decrypt_batch.argtypes = [
        u8p, u8p, u8p, u64p, ctypes.c_uint64, u8p, u64p, u8p
    ]
    lib.xchacha20poly1305_decrypt_batch.restype = ctypes.c_int
    lib.xchacha20poly1305_decrypt_batch_mt.argtypes = [
        u8p, u8p, u8p, u64p, ctypes.c_uint64, u8p, u64p, u8p,
        ctypes.c_int,
    ]
    lib.xchacha20poly1305_decrypt_batch_mt.restype = ctypes.c_int
    lib.encbox_parse_batch.argtypes = [
        u8p, u64p, ctypes.c_uint64, u8p, u64p, u64p, u64p
    ]
    lib.encbox_parse_batch.restype = ctypes.c_int64
    lib.encbox_parse_batch_ptrs.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), u64p, ctypes.c_uint64, u8p,
        u64p, u64p, u64p,
    ]
    lib.encbox_parse_batch_ptrs.restype = ctypes.c_int64
    lib.encbox_decrypt_scatter_mt.argtypes = [
        u8p, u8p, u64p, u64p, u64p, ctypes.c_uint64, u8p, u64p, u8p,
        ctypes.c_int,
    ]
    lib.encbox_decrypt_scatter_mt.restype = ctypes.c_int
    # scalar one-shot MAC + the lane-parallel AEAD tag batch (zero AAD):
    # the differential tests pin the vectorized verify pass against both
    # the scalar core and the pure-Python oracle
    lib.poly1305_mac.argtypes = [u8p, u8p, ctypes.c_uint64, u8p]
    lib.poly1305_mac.restype = None
    lib.poly1305_aead_tags.argtypes = [u8p, u8p, u64p, ctypes.c_uint64, u8p]
    lib.poly1305_aead_tags.restype = None

    lib.orset_count_rows.argtypes = [u8p, ctypes.c_uint64]
    lib.orset_count_rows.restype = ctypes.c_int64
    lib.orset_decode.argtypes = [
        u8p, ctypes.c_uint64, u8p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_int8), u64p, u64p,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
    ]
    lib.orset_decode.restype = ctypes.c_int64
    lib.counter_decode.argtypes = [
        u8p, ctypes.c_uint64, u8p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_int8),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
    ]
    lib.counter_decode.restype = ctypes.c_int64

    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.scan_op_sizes.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, i64p
    ]
    lib.scan_op_sizes.restype = ctypes.c_int64
    lib.read_op_files.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, i64p, i64p, u8p
    ]
    lib.read_op_files.restype = ctypes.c_int64
    lib.probe_op_files.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p, u8p
    ]
    lib.probe_op_files.restype = ctypes.c_int64
    # (the two-pass count+decode batch protocol still exists in C —
    # orset_count_rows_batch / orset_decode_batch[_h] — but the Python
    # span decoder moved to the single-pass grow/take protocol below, so
    # only the live entry points are bound)
    lib.actor_hash_build.argtypes = [
        u8p, ctypes.c_uint64, ctypes.POINTER(ctypes.c_int32),
        ctypes.c_uint64,
    ]
    lib.actor_hash_build.restype = None
    lib.orset_decode_batch_grow.argtypes = [
        u8p, u64p, u64p, ctypes.c_uint64, u8p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_uint64, i64p,
    ]
    lib.orset_decode_batch_grow.restype = ctypes.c_void_p
    lib.orset_decode_take.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int8), u64p, u64p,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
    ]
    lib.orset_decode_take.restype = None
    lib.orset_decode_drop.argtypes = [ctypes.c_void_p]
    lib.orset_decode_drop.restype = None
    lib.counter_decode_batch.argtypes = [
        u8p, u64p, u64p, ctypes.c_uint64, u8p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_int8),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
    ]
    lib.counter_decode_batch.restype = ctypes.c_int64
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.orset_host_reduce.argtypes = [
        ctypes.POINTER(ctypes.c_int8), i32p, i32p, i32p, ctypes.c_int64,
        i32p, ctypes.c_int32, ctypes.c_int64, i32p, i32p,
    ]
    lib.orset_host_reduce.restype = ctypes.c_int64
    lib.intern_spans_native.argtypes = [
        u8p, u64p, u64p, ctypes.c_int64, i64p, ctypes.c_int64,
        i32p, u64p, u64p, ctypes.c_int64,
    ]
    lib.intern_spans_native.restype = ctypes.c_int64
    lib.map_count_rows_batch.argtypes = [
        u8p, u64p, u64p, ctypes.c_uint64, i64p
    ]
    lib.map_count_rows_batch.restype = ctypes.c_int64
    lib.map_decode_batch.argtypes = (
        [u8p, u64p, u64p, ctypes.c_uint64, u8p, ctypes.c_uint64]
        + [u64p, u64p, i32p, i32p]
        + [u64p, u64p, u64p, u64p, i32p, i32p]
        + [u64p, u64p, u64p, u64p, i32p, i32p, i32p, i32p]
        + [u64p, u64p, i32p, i32p, i32p]
    )
    lib.map_decode_batch.restype = ctypes.c_int64



def in_ptr(b):
    """Zero-copy input pointer for bytes/bytearray/ndarray.  The caller must
    keep the object alive across the native call (numpy view held by the
    returned tuple)."""
    import numpy as np

    arr = np.frombuffer(b, dtype=np.uint8) if not isinstance(b, np.ndarray) else b
    if arr.size == 0:
        return None, arr
    return arr.ctypes.data_as(u8p), arr


def out_buf(n: int):
    """Writable output buffer of n bytes (numpy-backed)."""
    import numpy as np

    arr = np.empty(n, dtype=np.uint8)
    return (arr.ctypes.data_as(u8p) if n else None), arr

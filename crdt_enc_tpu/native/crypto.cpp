// XChaCha20-Poly1305 AEAD — the native cipher backend.
//
// The reference delegates to the Rust chacha20poly1305 crate
// (crdt-enc-xchacha20poly1305/src/lib.rs:40-102); this environment has no
// Rust toolchain and its Python `cryptography` wheel exposes only the IETF
// 12-byte-nonce ChaCha20Poly1305, so the XChaCha construction (HChaCha20
// subkey derivation + ChaCha20-Poly1305, draft-irtf-cfrg-xchacha) is
// implemented here from RFC 8439 primitives.  The IETF mode is exported too
// so tests can cross-validate this implementation against the cryptography
// wheel as an independent oracle.
//
// Exposed via a plain C ABI for ctypes; every entry point releases no GIL
// concerns (pure C, no Python API).  Batch entry points let the bulk
// decrypt front end amortize FFI overhead across thousands of blobs.

#include <cstdint>
#include <cstring>
#if defined(__x86_64__) || defined(__i386__)
#include <immintrin.h>
#endif
#include <array>
#include <thread>
#include <vector>

namespace {

inline uint32_t rotl32(uint32_t x, int n) { return (x << n) | (x >> (32 - n)); }

inline uint32_t load32_le(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}

inline void store32_le(uint8_t* p, uint32_t v) {
  p[0] = (uint8_t)v;
  p[1] = (uint8_t)(v >> 8);
  p[2] = (uint8_t)(v >> 16);
  p[3] = (uint8_t)(v >> 24);
}

inline void store64_le(uint8_t* p, uint64_t v) {
  store32_le(p, (uint32_t)v);
  store32_le(p + 4, (uint32_t)(v >> 32));
}

#define QR(a, b, c, d)      \
  a += b; d ^= a; d = rotl32(d, 16); \
  c += d; b ^= c; b = rotl32(b, 12); \
  a += b; d ^= a; d = rotl32(d, 8);  \
  c += d; b ^= c; b = rotl32(b, 7);

void chacha20_rounds(uint32_t s[16]) {
  for (int i = 0; i < 10; i++) {
    QR(s[0], s[4], s[8], s[12])
    QR(s[1], s[5], s[9], s[13])
    QR(s[2], s[6], s[10], s[14])
    QR(s[3], s[7], s[11], s[15])
    QR(s[0], s[5], s[10], s[15])
    QR(s[1], s[6], s[11], s[12])
    QR(s[2], s[7], s[8], s[13])
    QR(s[3], s[4], s[9], s[14])
  }
}

const uint32_t SIGMA[4] = {0x61707865, 0x3320646e, 0x79622d32, 0x6b206574};

// RFC 8439 §2.3: one 64-byte keystream block.
void chacha20_block(const uint8_t key[32], uint32_t counter,
                    const uint8_t nonce[12], uint8_t out[64]) {
  uint32_t init[16], s[16];
  for (int i = 0; i < 4; i++) init[i] = SIGMA[i];
  for (int i = 0; i < 8; i++) init[4 + i] = load32_le(key + 4 * i);
  init[12] = counter;
  for (int i = 0; i < 3; i++) init[13 + i] = load32_le(nonce + 4 * i);
  memcpy(s, init, sizeof(s));
  chacha20_rounds(s);
  for (int i = 0; i < 16; i++) store32_le(out + 4 * i, s[i] + init[i]);
}

// 8 independent keystream blocks with the state in GCC vector-extension
// registers (one v8u per ChaCha word, lanes = consecutive block
// counters): every quarter-round statement is a single elementwise
// vector op, which gcc/clang lower to AVX2/AVX-512 under -march=native —
// auto-vectorization of the equivalent scalar lane loops was observed to
// fail (no vector shifts emitted), so the SIMD shape is made explicit.
constexpr int LANES = 8;
typedef uint32_t v8u __attribute__((vector_size(4 * LANES)));

static inline v8u rotlv(v8u x, int n) {
  return (x << n) | (x >> (32 - n));
}

void chacha20_xor_lanes(const uint8_t key[32], uint32_t counter,
                        const uint8_t nonce[12], const uint8_t* in,
                        uint8_t* out) {
  uint32_t init[16];
  for (int i = 0; i < 4; i++) init[i] = SIGMA[i];
  for (int i = 0; i < 8; i++) init[4 + i] = load32_le(key + 4 * i);
  init[12] = counter;
  for (int i = 0; i < 3; i++) init[13 + i] = load32_le(nonce + 4 * i);

  v8u x[16];
  for (int i = 0; i < 16; i++)
    for (int j = 0; j < LANES; j++) x[i][j] = init[i];
  for (int j = 0; j < LANES; j++) x[12][j] = counter + (uint32_t)j;

#define QRV(a, b, c, d)                                      \
  x[a] += x[b]; x[d] ^= x[a]; x[d] = rotlv(x[d], 16);        \
  x[c] += x[d]; x[b] ^= x[c]; x[b] = rotlv(x[b], 12);        \
  x[a] += x[b]; x[d] ^= x[a]; x[d] = rotlv(x[d], 8);         \
  x[c] += x[d]; x[b] ^= x[c]; x[b] = rotlv(x[b], 7);

  for (int r = 0; r < 10; r++) {
    QRV(0, 4, 8, 12)
    QRV(1, 5, 9, 13)
    QRV(2, 6, 10, 14)
    QRV(3, 7, 11, 15)
    QRV(0, 5, 10, 15)
    QRV(1, 6, 11, 12)
    QRV(2, 7, 8, 13)
    QRV(3, 4, 9, 14)
  }
#undef QRV

  for (int j = 0; j < LANES; j++) {
    const uint8_t* src = in + (uint64_t)j * 64;
    uint8_t* dst = out + (uint64_t)j * 64;
    for (int i = 0; i < 16; i++) {
      uint32_t word = x[i][j] + init[i] + (i == 12 ? (uint32_t)j : 0);
      store32_le(dst + 4 * i, load32_le(src + 4 * i) ^ word);
    }
  }
}

// 16 independent keystream blocks in 512-bit vectors (zmm under
// -march=native on this AVX-512 host), with the block-major output
// produced by an in-register 16x16 u32 butterfly transpose instead of
// the 8-lane path's 128 scalar stores per group.  The transpose rule is
// the standard 4-stage interleave; masks were generated and verified by
// simulation (each stage s pairs registers i and i+2^s and interleaves
// 2^s-element chunks).
constexpr int LANES16 = 16;
typedef uint32_t v16u __attribute__((vector_size(4 * LANES16)));

static inline v16u rotlv16(v16u x, int n) {
  return (x << n) | (x >> (32 - n));
}

#if defined(__clang__) || (defined(__GNUC__) && __GNUC__ >= 12)
#define SHUF16(a, b, ...) __builtin_shufflevector(a, b, __VA_ARGS__)
#else
// GCC < 12 has no __builtin_shufflevector; its __builtin_shuffle
// two-vector form has the same concatenated-index semantics with the
// indices packed into an integer mask vector.  Same codegen class
// (vperm*); the AEAD tests cross-check against the `cryptography`
// wheel, so a semantic slip here cannot pass CI.
#define SHUF16(a, b, ...) __builtin_shuffle(a, b, (v16u){__VA_ARGS__})
#endif

static inline void transpose16(v16u x[16]) {
  v16u t[16];
  // stage 0 (step 1)
  for (int i = 0; i < 16; i += 2) {
    v16u a = x[i], b = x[i + 1];
    t[i] = SHUF16(a, b, 0, 16, 2, 18, 4, 20, 6, 22, 8, 24, 10, 26, 12, 28,
                  14, 30);
    t[i + 1] = SHUF16(a, b, 1, 17, 3, 19, 5, 21, 7, 23, 9, 25, 11, 27, 13,
                      29, 15, 31);
  }
  // stage 1 (step 2)
  for (int g = 0; g < 16; g += 4)
    for (int i = g; i < g + 2; i++) {
      v16u a = t[i], b = t[i + 2];
      x[i] = SHUF16(a, b, 0, 1, 16, 17, 4, 5, 20, 21, 8, 9, 24, 25, 12, 13,
                    28, 29);
      x[i + 2] = SHUF16(a, b, 2, 3, 18, 19, 6, 7, 22, 23, 10, 11, 26, 27,
                        14, 15, 30, 31);
    }
  // stage 2 (step 4)
  for (int g = 0; g < 16; g += 8)
    for (int i = g; i < g + 4; i++) {
      v16u a = x[i], b = x[i + 4];
      t[i] = SHUF16(a, b, 0, 1, 2, 3, 16, 17, 18, 19, 8, 9, 10, 11, 24, 25,
                    26, 27);
      t[i + 4] = SHUF16(a, b, 4, 5, 6, 7, 20, 21, 22, 23, 12, 13, 14, 15,
                        28, 29, 30, 31);
    }
  // stage 3 (step 8)
  for (int i = 0; i < 8; i++) {
    v16u a = t[i], b = t[i + 8];
    x[i] = SHUF16(a, b, 0, 1, 2, 3, 4, 5, 6, 7, 16, 17, 18, 19, 20, 21, 22,
                  23);
    x[i + 8] = SHUF16(a, b, 8, 9, 10, 11, 12, 13, 14, 15, 24, 25, 26, 27,
                      28, 29, 30, 31);
  }
}

void chacha20_xor_lanes16(const uint8_t key[32], uint32_t counter,
                          const uint8_t nonce[12], const uint8_t* in,
                          uint8_t* out) {
  uint32_t init[16];
  for (int i = 0; i < 4; i++) init[i] = SIGMA[i];
  for (int i = 0; i < 8; i++) init[4 + i] = load32_le(key + 4 * i);
  init[12] = counter;
  for (int i = 0; i < 3; i++) init[13 + i] = load32_le(nonce + 4 * i);

  v16u x[16], iv[16];
  for (int i = 0; i < 16; i++)
    for (int j = 0; j < LANES16; j++) iv[i][j] = init[i];
  for (int j = 0; j < LANES16; j++) iv[12][j] = counter + (uint32_t)j;
  for (int i = 0; i < 16; i++) x[i] = iv[i];

#define QRV16(a, b, c, d)                                    \
  x[a] += x[b]; x[d] ^= x[a]; x[d] = rotlv16(x[d], 16);      \
  x[c] += x[d]; x[b] ^= x[c]; x[b] = rotlv16(x[b], 12);      \
  x[a] += x[b]; x[d] ^= x[a]; x[d] = rotlv16(x[d], 8);       \
  x[c] += x[d]; x[b] ^= x[c]; x[b] = rotlv16(x[b], 7);

  for (int r = 0; r < 10; r++) {
    QRV16(0, 4, 8, 12)
    QRV16(1, 5, 9, 13)
    QRV16(2, 6, 10, 14)
    QRV16(3, 7, 11, 15)
    QRV16(0, 5, 10, 15)
    QRV16(1, 6, 11, 12)
    QRV16(2, 7, 8, 13)
    QRV16(3, 4, 9, 14)
  }
#undef QRV16

  for (int i = 0; i < 16; i++) x[i] += iv[i];
  transpose16(x);  // x[j] now holds block j's 16 words
  for (int j = 0; j < LANES16; j++) {
    v16u m;
    memcpy(&m, in + (uint64_t)j * 64, 64);
    m ^= x[j];
    memcpy(out + (uint64_t)j * 64, &m, 64);
  }
}

// 4 independent keystream blocks in 128-bit vectors — the guaranteed
// SIMD baseline (SSE2 on any x86-64, NEON q-registers on aarch64): one
// xmm/q register per ChaCha word.  This is the widest shape that never
// needs an ISA the build target might lack, so it is the runtime
// dispatcher's floor before the scalar tail.
constexpr int LANES4 = 4;
typedef uint32_t v4u __attribute__((vector_size(4 * LANES4)));

static inline v4u rotlv4(v4u x, int n) {
  return (x << n) | (x >> (32 - n));
}

void chacha20_xor_lanes4(const uint8_t key[32], uint32_t counter,
                         const uint8_t nonce[12], const uint8_t* in,
                         uint8_t* out) {
  uint32_t init[16];
  for (int i = 0; i < 4; i++) init[i] = SIGMA[i];
  for (int i = 0; i < 8; i++) init[4 + i] = load32_le(key + 4 * i);
  init[12] = counter;
  for (int i = 0; i < 3; i++) init[13 + i] = load32_le(nonce + 4 * i);

  v4u x[16];
  for (int i = 0; i < 16; i++)
    for (int j = 0; j < LANES4; j++) x[i][j] = init[i];
  for (int j = 0; j < LANES4; j++) x[12][j] = counter + (uint32_t)j;

#define QRV4(a, b, c, d)                                     \
  x[a] += x[b]; x[d] ^= x[a]; x[d] = rotlv4(x[d], 16);       \
  x[c] += x[d]; x[b] ^= x[c]; x[b] = rotlv4(x[b], 12);       \
  x[a] += x[b]; x[d] ^= x[a]; x[d] = rotlv4(x[d], 8);        \
  x[c] += x[d]; x[b] ^= x[c]; x[b] = rotlv4(x[b], 7);

  for (int r = 0; r < 10; r++) {
    QRV4(0, 4, 8, 12)
    QRV4(1, 5, 9, 13)
    QRV4(2, 6, 10, 14)
    QRV4(3, 7, 11, 15)
    QRV4(0, 5, 10, 15)
    QRV4(1, 6, 11, 12)
    QRV4(2, 7, 8, 13)
    QRV4(3, 4, 9, 14)
  }
#undef QRV4

  for (int j = 0; j < LANES4; j++) {
    const uint8_t* src = in + (uint64_t)j * 64;
    uint8_t* dst = out + (uint64_t)j * 64;
    for (int i = 0; i < 16; i++) {
      uint32_t word = x[i][j] + init[i] + (i == 12 ? (uint32_t)j : 0);
      store32_le(dst + 4 * i, load32_le(src + 4 * i) ^ word);
    }
  }
}

// Runtime SIMD dispatch: the usable lane width is the MIN of what this
// translation unit was compiled for (wider vector-extension code may
// contain instructions the build ISA allows) and what the running CPU
// actually supports — a build/ copied from an AVX-512 box must degrade
// to the 8/4-lane loops on an AVX2/SSE2 host instead of faulting.  On
// non-x86 the compile-time width is authoritative (vector extensions
// lower to the target baseline, NEON on aarch64).
static int simd_lanes_detect() {
  int compiled = LANES4;
#if defined(__AVX512F__) && defined(__AVX512BW__)
  compiled = LANES16;
#elif defined(__AVX2__)
  compiled = LANES;
#endif
  int runtime = compiled;
#if (defined(__x86_64__) || defined(__i386__)) && defined(__GNUC__)
  __builtin_cpu_init();
  if (__builtin_cpu_supports("avx512f") && __builtin_cpu_supports("avx512bw"))
    runtime = LANES16;
  else if (__builtin_cpu_supports("avx2"))
    runtime = LANES;
  else
    runtime = LANES4;
#endif
  return runtime < compiled ? runtime : compiled;
}

static const int SIMD_LANES = simd_lanes_detect();

void chacha20_xor(const uint8_t key[32], uint32_t counter,
                  const uint8_t nonce[12], const uint8_t* in, uint8_t* out,
                  uint64_t len) {
  while (SIMD_LANES >= LANES16 && len >= 64 * LANES16) {
    chacha20_xor_lanes16(key, counter, nonce, in, out);
    counter += LANES16;
    in += 64 * LANES16;
    out += 64 * LANES16;
    len -= 64 * LANES16;
  }
  while (SIMD_LANES >= LANES && len >= 64 * LANES) {
    chacha20_xor_lanes(key, counter, nonce, in, out);
    counter += LANES;
    in += 64 * LANES;
    out += 64 * LANES;
    len -= 64 * LANES;
  }
  while (len >= 64 * LANES4) {
    chacha20_xor_lanes4(key, counter, nonce, in, out);
    counter += LANES4;
    in += 64 * LANES4;
    out += 64 * LANES4;
    len -= 64 * LANES4;
  }
  uint8_t block[64];
  while (len > 0) {
    chacha20_block(key, counter++, nonce, block);
    uint64_t n = len < 64 ? len : 64;
    for (uint64_t i = 0; i < n; i++) out[i] = in[i] ^ block[i];
    in += n;
    out += n;
    len -= n;
  }
}

// draft-irtf-cfrg-xchacha §2.2: rounds over const|key|nonce16, no final
// add; subkey = words 0..3 and 12..15.
void hchacha20_impl(const uint8_t key[32], const uint8_t nonce16[16],
                    uint8_t out32[32]) {
  uint32_t s[16];
  for (int i = 0; i < 4; i++) s[i] = SIGMA[i];
  for (int i = 0; i < 8; i++) s[4 + i] = load32_le(key + 4 * i);
  for (int i = 0; i < 4; i++) s[12 + i] = load32_le(nonce16 + 4 * i);
  chacha20_rounds(s);
  for (int i = 0; i < 4; i++) store32_le(out32 + 4 * i, s[i]);
  for (int i = 0; i < 4; i++) store32_le(out32 + 16 + 4 * i, s[12 + i]);
}

// ---- Poly1305 (RFC 8439 §2.5), radix-2^44 limbs ------------------------
//
// Three 44/44/42-bit limbs with 64x64->128 products (9 multiplies per
// 16-byte block vs 25 in the 26-bit-limb form this replaced; measured
// ~2x on this core).  Same streaming API: partial tails buffer across
// update() calls like a hash object.

struct Poly1305 {
  uint64_t r0, r1, r2;
  uint64_t h0 = 0, h1 = 0, h2 = 0;
  uint64_t s1, s2;  // 20*r1, 20*r2 (2^130 = 5 mod p, limbs carry 2^132)
  uint64_t pad0, pad1;
  uint8_t buf[16];
  unsigned buflen = 0;

  static inline uint64_t load64(const uint8_t* p) {
    uint64_t v;
    memcpy(&v, p, 8);
    return v;  // little-endian host (x86)
  }

  // r^2 limbs for the two-block interleave: h' = (h+m1)*r^2 + m2*r
  uint64_t q0, q1, q2, qs1, qs2;

  void init(const uint8_t key[32]) {
    const uint64_t m44 = 0xfffffffffffULL, m42 = 0x3ffffffffffULL;
    uint64_t t0 = load64(key), t1 = load64(key + 8);
    // clamp per spec: r &= 0x0ffffffc0ffffffc0ffffffc0fffffff
    t0 &= 0x0ffffffc0fffffffULL;
    t1 &= 0x0ffffffc0ffffffcULL;
    r0 = t0 & m44;
    r1 = ((t0 >> 44) | (t1 << 20)) & m44;
    r2 = t1 >> 24;  // 40 bits
    s1 = r1 * 20;
    s2 = r2 * 20;
    h0 = h1 = h2 = 0;
    pad0 = load64(key + 16);
    pad1 = load64(key + 24);
    buflen = 0;
    // q = r^2 mod p (same reduction as block())
    using u128 = unsigned __int128;
    u128 d0 = (u128)r0 * r0 + (u128)r1 * s2 + (u128)r2 * s1;
    u128 d1 = (u128)r0 * r1 + (u128)r1 * r0 + (u128)r2 * s2;
    u128 d2 = (u128)r0 * r2 + (u128)r1 * r1 + (u128)r2 * r0;
    uint64_t c;
    c = (uint64_t)(d0 >> 44); q0 = (uint64_t)d0 & m44; d1 += c;
    c = (uint64_t)(d1 >> 44); q1 = (uint64_t)d1 & m44; d2 += c;
    c = (uint64_t)(d2 >> 42); q2 = (uint64_t)d2 & m42;
    q0 += c * 5;
    c = q0 >> 44; q0 &= m44; q1 += c;
    qs1 = q1 * 20;
    qs2 = q2 * 20;
  }

  // Two blocks per reduction: h = (h + m1)·r² + m2·r.  The two limb
  // products are independent, so the multiplier pipeline overlaps them
  // and the carry chain runs once per 32 bytes instead of per 16.
  void block2(const uint8_t* m) {
    const uint64_t m44 = 0xfffffffffffULL, m42 = 0x3ffffffffffULL;
    uint64_t a0 = load64(m), a1 = load64(m + 8);
    uint64_t b0 = load64(m + 16), b1 = load64(m + 24);
    uint64_t x0 = h0 + (a0 & m44);
    uint64_t x1 = h1 + (((a0 >> 44) | (a1 << 20)) & m44);
    uint64_t x2 = h2 + (((a1 >> 24) & m42) | (1ULL << 40));
    uint64_t y0 = b0 & m44;
    uint64_t y1 = ((b0 >> 44) | (b1 << 20)) & m44;
    uint64_t y2 = ((b1 >> 24) & m42) | (1ULL << 40);

    using u128 = unsigned __int128;
    u128 d0 = (u128)x0 * q0 + (u128)x1 * qs2 + (u128)x2 * qs1
            + (u128)y0 * r0 + (u128)y1 * s2 + (u128)y2 * s1;
    u128 d1 = (u128)x0 * q1 + (u128)x1 * q0 + (u128)x2 * qs2
            + (u128)y0 * r1 + (u128)y1 * r0 + (u128)y2 * s2;
    u128 d2 = (u128)x0 * q2 + (u128)x1 * q1 + (u128)x2 * q0
            + (u128)y0 * r2 + (u128)y1 * r1 + (u128)y2 * r0;

    uint64_t c;
    c = (uint64_t)(d0 >> 44); h0 = (uint64_t)d0 & m44; d1 += c;
    c = (uint64_t)(d1 >> 44); h1 = (uint64_t)d1 & m44; d2 += c;
    c = (uint64_t)(d2 >> 42); h2 = (uint64_t)d2 & m42;
    h0 += c * 5;
    c = h0 >> 44; h0 &= m44; h1 += c;
  }

  void block(const uint8_t* m, uint64_t hibit /* 1 = full block, 0 = final partial */) {
    const uint64_t m44 = 0xfffffffffffULL, m42 = 0x3ffffffffffULL;
    uint64_t t0 = load64(m), t1 = load64(m + 8);
    h0 += t0 & m44;
    h1 += ((t0 >> 44) | (t1 << 20)) & m44;
    h2 += ((t1 >> 24) & m42) | (hibit << 40);

    using u128 = unsigned __int128;
    u128 d0 = (u128)h0 * r0 + (u128)h1 * s2 + (u128)h2 * s1;
    u128 d1 = (u128)h0 * r1 + (u128)h1 * r0 + (u128)h2 * s2;
    u128 d2 = (u128)h0 * r2 + (u128)h1 * r1 + (u128)h2 * r0;

    uint64_t c;
    c = (uint64_t)(d0 >> 44); h0 = (uint64_t)d0 & m44; d1 += c;
    c = (uint64_t)(d1 >> 44); h1 = (uint64_t)d1 & m44; d2 += c;
    c = (uint64_t)(d2 >> 42); h2 = (uint64_t)d2 & m42;
    h0 += c * 5;
    c = h0 >> 44; h0 &= m44; h1 += c;
  }

  // Streaming update: partial tails are buffered, NOT finalized — multiple
  // update() calls concatenate, exactly like a hash object.
  void update(const uint8_t* m, uint64_t len) {
    if (buflen) {
      uint64_t want = 16 - buflen;
      uint64_t take = len < want ? len : want;
      memcpy(buf + buflen, m, take);
      buflen += (unsigned)take;
      m += take;
      len -= take;
      if (buflen < 16) return;
      block(buf, 1);
      buflen = 0;
    }
    while (len >= 32) {
      block2(m);
      m += 32;
      len -= 32;
    }
    while (len >= 16) {
      block(m, 1);
      m += 16;
      len -= 16;
    }
    if (len) {
      memcpy(buf, m, len);
      buflen = (unsigned)len;
    }
  }

  void finish(uint8_t tag[16]) {
    const uint64_t m44 = 0xfffffffffffULL, m42 = 0x3ffffffffffULL;
    if (buflen) {  // final partial block: append 0x01, zero-fill, no hibit
      buf[buflen] = 1;
      for (unsigned i = buflen + 1; i < 16; i++) buf[i] = 0;
      block(buf, 0);
      buflen = 0;
    }
    // full carry propagation
    uint64_t c;
    c = h1 >> 44; h1 &= m44; h2 += c;
    c = h2 >> 42; h2 &= m42; h0 += c * 5;
    c = h0 >> 44; h0 &= m44; h1 += c;
    c = h1 >> 44; h1 &= m44; h2 += c;
    c = h2 >> 42; h2 &= m42; h0 += c * 5;
    c = h0 >> 44; h0 &= m44; h1 += c;

    // g = h - p = h + 5 - 2^130; select g when h >= p (no borrow out)
    uint64_t g0 = h0 + 5;
    c = g0 >> 44; g0 &= m44;
    uint64_t g1 = h1 + c;
    c = g1 >> 44; g1 &= m44;
    uint64_t g2 = h2 + c - (1ULL << 42);
    uint64_t mask = (g2 >> 63) - 1;  // all-ones iff no borrow (h >= p)
    h0 = (h0 & ~mask) | (g0 & mask);
    h1 = (h1 & ~mask) | (g1 & mask);
    h2 = (h2 & ~mask) | (g2 & m42 & mask);

    // h mod 2^128 + pad (s), 64-bit lanes with carry
    uint64_t f0 = h0 | (h1 << 44);
    uint64_t f1 = (h1 >> 20) | (h2 << 24);
    using u128 = unsigned __int128;
    u128 acc = (u128)f0 + pad0;
    store64_le(tag, (uint64_t)acc);
    acc = (u128)f1 + pad1 + (uint64_t)(acc >> 64);
    store64_le(tag + 8, (uint64_t)acc);
  }
};

// RFC 8439 §2.8 AEAD construction.
void aead_tag(const uint8_t key[32], const uint8_t nonce[12],
              const uint8_t* aad, uint64_t aad_len, const uint8_t* ct,
              uint64_t ct_len, uint8_t tag[16]) {
  uint8_t otk[64];
  chacha20_block(key, 0, nonce, otk);  // one-time poly key = block 0
  Poly1305 p;
  p.init(otk);
  static const uint8_t zeros[16] = {0};
  p.update(aad, aad_len);
  if (aad_len % 16) p.update(zeros, 16 - (aad_len % 16));
  p.update(ct, ct_len);
  if (ct_len % 16) p.update(zeros, 16 - (ct_len % 16));
  uint8_t lens[16];
  store64_le(lens, aad_len);
  store64_le(lens + 8, ct_len);
  p.update(lens, 16);
  p.finish(tag);
}

int ct_compare16(const uint8_t* a, const uint8_t* b) {
  uint8_t d = 0;
  for (int i = 0; i < 16; i++) d |= a[i] ^ b[i];
  return d == 0 ? 0 : -1;
}

void xchacha_derive(const uint8_t key[32], const uint8_t nonce24[24],
                    uint8_t subkey[32], uint8_t nonce12[12]) {
  hchacha20_impl(key, nonce24, subkey);
  memset(nonce12, 0, 4);
  memcpy(nonce12 + 4, nonce24 + 16, 8);
}

}  // namespace

extern "C" {

void hchacha20(const uint8_t* key, const uint8_t* nonce16, uint8_t* out32) {
  hchacha20_impl(key, nonce16, out32);
}

// Raw one-shot Poly1305 (32-byte key, arbitrary message) — exported for
// test-vector validation of the MAC in isolation.
void poly1305_mac(const uint8_t* key, const uint8_t* msg, uint64_t len,
                  uint8_t* tag16) {
  Poly1305 p;
  p.init(key);
  p.update(msg, len);
  p.finish(tag16);
}

// IETF ChaCha20-Poly1305 (12-byte nonce).  out = ct || tag(16).
void chacha20poly1305_encrypt(const uint8_t* key, const uint8_t* nonce,
                              const uint8_t* aad, uint64_t aad_len,
                              const uint8_t* pt, uint64_t pt_len,
                              uint8_t* out) {
  chacha20_xor(key, 1, nonce, pt, out, pt_len);
  aead_tag(key, nonce, aad, aad_len, out, pt_len, out + pt_len);
}

// in = ct || tag.  Returns 0 and writes pt on success, -1 on tag mismatch.
int chacha20poly1305_decrypt(const uint8_t* key, const uint8_t* nonce,
                             const uint8_t* aad, uint64_t aad_len,
                             const uint8_t* in, uint64_t in_len,
                             uint8_t* out) {
  if (in_len < 16) return -1;
  uint64_t ct_len = in_len - 16;
  uint8_t tag[16];
  aead_tag(key, nonce, aad, aad_len, in, ct_len, tag);
  if (ct_compare16(tag, in + ct_len) != 0) return -1;
  chacha20_xor(key, 1, nonce, in, out, ct_len);
  return 0;
}

// XChaCha20-Poly1305 (24-byte nonce), draft-irtf-cfrg-xchacha.
void xchacha20poly1305_encrypt(const uint8_t* key, const uint8_t* nonce24,
                               const uint8_t* aad, uint64_t aad_len,
                               const uint8_t* pt, uint64_t pt_len,
                               uint8_t* out) {
  uint8_t subkey[32], nonce12[12];
  xchacha_derive(key, nonce24, subkey, nonce12);
  chacha20poly1305_encrypt(subkey, nonce12, aad, aad_len, pt, pt_len, out);
}

int xchacha20poly1305_decrypt(const uint8_t* key, const uint8_t* nonce24,
                              const uint8_t* aad, uint64_t aad_len,
                              const uint8_t* in, uint64_t in_len,
                              uint8_t* out) {
  uint8_t subkey[32], nonce12[12];
  xchacha_derive(key, nonce24, subkey, nonce12);
  return chacha20poly1305_decrypt(subkey, nonce12, aad, aad_len, in, in_len,
                                  out);
}

// Defined with the batched engine at the bottom of this file: the
// shared SIMD decrypt core both the EncBox scatter path and the raw
// batch surfaces below route through.
int encbox_decrypt_scatter_mt(const uint8_t* key, const uint8_t* blobs,
                              const uint64_t* nonce_offs,
                              const uint64_t* ct_offs,
                              const uint64_t* ct_lens, uint64_t n,
                              uint8_t* out, const uint64_t* out_offs,
                              uint8_t* ok_flags, int n_threads);

namespace {

// Adapt the flat (nonces n*24, cts + offsets[n+1]) batch layout to the
// batched engine's absolute-address span form (NULL blob base — the
// same convention encbox_parse_batch_ptrs emits), so the raw batch FFI
// surface shares the multi-lane ChaCha phases and the batched Poly1305
// pass with the EncBox path instead of looping the scalar decrypt.
int batch_via_engine(const uint8_t* key, const uint8_t* nonces,
                     const uint8_t* cts, const uint64_t* offsets, uint64_t n,
                     uint8_t* out, const uint64_t* out_offsets,
                     uint8_t* ok_flags, int n_threads) {
  if (n == 0) return 0;
  std::vector<uint64_t> nonce_offs(n), ct_offs(n), ct_lens(n);
  for (uint64_t i = 0; i < n; i++) {
    nonce_offs[i] = (uint64_t)(uintptr_t)(nonces + 24 * i);
    ct_offs[i] = (uint64_t)(uintptr_t)(cts + offsets[i]);
    ct_lens[i] = offsets[i + 1] - offsets[i];
  }
  return encbox_decrypt_scatter_mt(key, nullptr, nonce_offs.data(),
                                   ct_offs.data(), ct_lens.data(), n, out,
                                   out_offsets, ok_flags, n_threads);
}

}  // namespace

// Batch XChaCha decrypt: n blobs, one shared key, per-blob nonce + ct.
// Inputs are flattened: nonces (n*24), cts concatenated with offsets[n+1].
// Outputs into `out` at out_offsets[i] = offsets[i] - 16*i shape (each pt is
// ct_len-16).  Returns the number of failures (0 = all verified).
int xchacha20poly1305_decrypt_batch(const uint8_t* key, const uint8_t* nonces,
                                    const uint8_t* cts,
                                    const uint64_t* offsets, uint64_t n,
                                    uint8_t* out, const uint64_t* out_offsets,
                                    uint8_t* ok_flags) {
  return batch_via_engine(key, nonces, cts, offsets, n, out, out_offsets,
                          ok_flags, 1);
}

// Threaded batch decrypt: blobs are independent (per-blob nonce, disjoint
// output spans), so stripes shard freely across threads.  The Python caller
// releases the GIL for the whole call (ctypes does this automatically).
int xchacha20poly1305_decrypt_batch_mt(const uint8_t* key,
                                       const uint8_t* nonces,
                                       const uint8_t* cts,
                                       const uint64_t* offsets, uint64_t n,
                                       uint8_t* out,
                                       const uint64_t* out_offsets,
                                       uint8_t* ok_flags, int n_threads) {
  return batch_via_engine(key, nonces, cts, offsets, n, out, out_offsets,
                          ok_flags, n_threads);
}

// The resolved SIMD lane width (16 = AVX-512, 8 = AVX2, 4 = SSE2/NEON
// baseline) — exported so tests and diagnostics can see which keystream
// path this process actually runs.
int crdt_simd_lanes(void) { return SIMD_LANES; }

}  // extern "C"

// Forward declaration: the lane-parallel MAC batch lives with the
// batched engine below; this thin FFI wrapper is exported above it.
namespace {
static void poly1305_aead_tags_batch(const uint8_t* const* otks,
                                     const uint8_t* const* msgs,
                                     const uint64_t* lens,
                                     uint8_t (*tags)[16], uint64_t n);
}  // namespace

extern "C" {

// Lane-parallel AEAD tag batch (zero AAD — the op-blob envelope's
// shape): n one-time keys (32B each, concatenated), n messages
// concatenated with offsets[n+1], n 16-byte tags out.  Exported so the
// vectorized MAC is differentially testable against the scalar
// Poly1305 / the pure-Python oracle in isolation, not only through the
// full decrypt surface.
void poly1305_aead_tags(const uint8_t* otks, const uint8_t* msgs,
                        const uint64_t* offsets, uint64_t n, uint8_t* tags) {
  std::vector<const uint8_t*> kp(n), mp(n);
  std::vector<uint64_t> lens(n);
  for (uint64_t i = 0; i < n; i++) {
    kp[i] = otks + 32 * i;
    mp[i] = msgs + offsets[i];
    lens[i] = offsets[i + 1] - offsets[i];
  }
  poly1305_aead_tags_batch(kp.data(), mp.data(), lens.data(),
                           (uint8_t(*)[16])tags, n);
}

}  // extern "C"

// ---- EncBox envelope fast path --------------------------------------------
//
// The wire envelope (backends/xchacha.py, mirroring the reference's EncBox,
// crdt-enc-xchacha20poly1305/src/lib.rs:59-68) is
//   raw VersionBytes:  version(16) ‖ msgpack [ nonce(bin 24), ct(bin N) ]
// At bulk scale (100k+ tiny op files) parsing this in Python costs several
// µs per blob — more than the decrypt itself.  These two calls parse and
// decrypt whole batches straight out of one concatenated buffer.

namespace {
// msgpack bin header at p (limit end): writes payload span, returns 0.
static int parse_bin(const uint8_t* p, const uint8_t* end, const uint8_t** out,
                     uint64_t* out_len, const uint8_t** next) {
  if (p >= end) return -1;
  uint64_t len;
  if (*p == 0xc4) {
    if (end - p < 2) return -1;
    len = p[1];
    p += 2;
  } else if (*p == 0xc5) {
    if (end - p < 3) return -1;
    len = ((uint64_t)p[1] << 8) | p[2];
    p += 3;
  } else if (*p == 0xc6) {
    if (end - p < 5) return -1;
    len = ((uint64_t)p[1] << 24) | ((uint64_t)p[2] << 16) |
          ((uint64_t)p[3] << 8) | p[4];
    p += 5;
  } else {
    return -1;
  }
  if ((uint64_t)(end - p) < len) return -1;
  *out = p;
  *out_len = len;
  *next = p + len;
  return 0;
}
}  // namespace

extern "C" {

// Parse n EncBox blobs concatenated in `blobs` (blob i spans
// [boffs[i], boffs[i+1])).  Each must carry `version` (16 bytes), a 24-byte
// nonce and a ct of ≥ 16 bytes (the tag).  Writes per-blob nonce offsets,
// ct offsets and ct lengths (all relative to `blobs`).  Returns the total
// CLEARTEXT byte count, or -1 if any blob is malformed (caller falls back
// to the per-blob Python path for precise errors).
int64_t encbox_parse_batch(const uint8_t* blobs, const uint64_t* boffs,
                           uint64_t n, const uint8_t* version,
                           uint64_t* nonce_offs, uint64_t* ct_offs,
                           uint64_t* ct_lens) {
  int64_t total = 0;
  for (uint64_t i = 0; i < n; i++) {
    const uint8_t* p = blobs + boffs[i];
    const uint8_t* end = blobs + boffs[i + 1];
    if (end - p < 16 + 1) return -1;
    if (memcmp(p, version, 16) != 0) return -1;
    p += 16;
    if (*p++ != 0x92) return -1;  // fixarray(2)
    const uint8_t *nonce, *ct, *next;
    uint64_t nonce_len, ct_len;
    if (parse_bin(p, end, &nonce, &nonce_len, &next) != 0) return -1;
    if (nonce_len != 24) return -1;
    if (parse_bin(next, end, &ct, &ct_len, &next) != 0) return -1;
    if (ct_len < 16 || next != end) return -1;
    nonce_offs[i] = (uint64_t)(nonce - blobs);
    ct_offs[i] = (uint64_t)(ct - blobs);
    ct_lens[i] = ct_len;
    total += (int64_t)(ct_len - 16);
  }
  return total;
}

// Resolve a blob location from a base pointer plus offset.  The
// pointer-array parse (encbox_parse_batch_ptrs) emits ABSOLUTE
// addresses paired with a NULL base — go through uintptr_t so that
// case is defined behavior, not nullptr arithmetic.
static inline const uint8_t* blob_at(const uint8_t* base, uint64_t off) {
  return (const uint8_t*)((uintptr_t)base + (uintptr_t)off);
}

// Pointer-array variant: blobs live in SEPARATE buffers (the usual case
// — per-file bytes straight from storage), so no caller-side join of
// hundreds of MB is needed.  Emits ABSOLUTE addresses into
// nonce_offs/ct_offs; pair with encbox_decrypt_scatter_mt(blobs=NULL),
// whose `blobs + off` arithmetic then resolves each address unchanged.
int64_t encbox_parse_batch_ptrs(const uint8_t* const* blob_ptrs,
                                const uint64_t* blob_lens, uint64_t n,
                                const uint8_t* version, uint64_t* nonce_offs,
                                uint64_t* ct_offs, uint64_t* ct_lens) {
  int64_t total = 0;
  for (uint64_t i = 0; i < n; i++) {
    const uint8_t* p = blob_ptrs[i];
    const uint8_t* end = p + blob_lens[i];
    if (end - p < 16 + 1) return -1;
    if (memcmp(p, version, 16) != 0) return -1;
    p += 16;
    if (*p++ != 0x92) return -1;  // fixarray(2)
    const uint8_t *nonce, *ct, *next;
    uint64_t nonce_len, ct_len;
    if (parse_bin(p, end, &nonce, &nonce_len, &next) != 0) return -1;
    if (nonce_len != 24) return -1;
    if (parse_bin(next, end, &ct, &ct_len, &next) != 0) return -1;
    if (ct_len < 16 || next != end) return -1;
    nonce_offs[i] = (uint64_t)(uintptr_t)nonce;
    ct_offs[i] = (uint64_t)(uintptr_t)ct;
    ct_lens[i] = ct_len;
    total += (int64_t)(ct_len - 16);
  }
  return total;
}

}  // extern "C" (parse entry points; batched decrypt engine follows)

// ---- batched small-blob decrypt helpers ---------------------------------
//
// The streaming workload (config 5) is ~100k tiny files sealed under ONE
// key: the per-file fixed crypto (HChaCha20 subkey, Poly1305 one-time-key
// block, 2-4 data blocks) dominates.  All of it is ChaCha rounds on
// independent states, so a vector register's worth of files runs per
// pass — only the state *init* differs per lane (nonce / subkey /
// counter), and the QR rounds are elementwise regardless.  The lane
// width follows the runtime dispatch (16 on AVX-512, 8 on AVX2, 4 on
// the SSE2/NEON baseline — C++ templates outside the C-linkage block);
// every width is cross-checked against the pure-Python oracle in
// tests/test_native_crypto.py.

namespace {

template <typename V>
static inline V rotlvN(V x, int n) {
  return (x << n) | (x >> (32 - n));
}

#define QRN(a, b, c, d)                                      \
  x[a] += x[b]; x[d] ^= x[a]; x[d] = rotlvN(x[d], 16);       \
  x[c] += x[d]; x[b] ^= x[c]; x[b] = rotlvN(x[b], 12);       \
  x[a] += x[b]; x[d] ^= x[a]; x[d] = rotlvN(x[d], 8);        \
  x[c] += x[d]; x[b] ^= x[c]; x[b] = rotlvN(x[b], 7);

// L independent HChaCha20 derivations (shared key, per-lane nonce16).
template <typename V, int L>
static void hchacha20_xN(const uint8_t key[32], const uint8_t* const* nonces,
                         uint8_t (*subkeys)[32], int count) {
  uint32_t kw[8];
  for (int i = 0; i < 8; i++) kw[i] = load32_le(key + 4 * i);
  V x[16];
  for (int i = 0; i < 4; i++) x[i] = SIGMA[i] - (V){};
  for (int i = 0; i < 8; i++) x[4 + i] = kw[i] - (V){};
  for (int i = 0; i < 4; i++)
    for (int j = 0; j < L; j++)
      x[12 + i][j] = load32_le(nonces[j < count ? j : 0] + 4 * i);
  for (int r = 0; r < 10; r++) {
    QRN(0, 4, 8, 12) QRN(1, 5, 9, 13) QRN(2, 6, 10, 14) QRN(3, 7, 11, 15)
    QRN(0, 5, 10, 15) QRN(1, 6, 11, 12) QRN(2, 7, 8, 13) QRN(3, 4, 9, 14)
  }
  for (int j = 0; j < count; j++) {
    for (int i = 0; i < 4; i++) store32_le(subkeys[j] + 4 * i, x[i][j]);
    for (int i = 0; i < 4; i++)
      store32_le(subkeys[j] + 16 + 4 * i, x[12 + i][j]);
  }
}

// L independent ChaCha20 blocks, each with its own key/nonce/counter.
template <typename V, int L>
static void chacha20_block_xN(const uint8_t* const* keys,
                              const uint32_t* counters,
                              const uint8_t* const* nonces12,
                              uint8_t (*outs)[64], int count) {
  V x[16], iv[16];
  for (int i = 0; i < 4; i++) iv[i] = SIGMA[i] - (V){};
  for (int i = 0; i < 8; i++)
    for (int j = 0; j < L; j++)
      iv[4 + i][j] = load32_le(keys[j < count ? j : 0] + 4 * i);
  for (int j = 0; j < L; j++) iv[12][j] = counters[j < count ? j : 0];
  for (int i = 0; i < 3; i++)
    for (int j = 0; j < L; j++)
      iv[13 + i][j] = load32_le(nonces12[j < count ? j : 0] + 4 * i);
  for (int i = 0; i < 16; i++) x[i] = iv[i];
  for (int r = 0; r < 10; r++) {
    QRN(0, 4, 8, 12) QRN(1, 5, 9, 13) QRN(2, 6, 10, 14) QRN(3, 7, 11, 15)
    QRN(0, 5, 10, 15) QRN(1, 6, 11, 12) QRN(2, 7, 8, 13) QRN(3, 4, 9, 14)
  }
#undef QRN
  for (int i = 0; i < 16; i++) x[i] += iv[i];
  for (int j = 0; j < count; j++)
    for (int i = 0; i < 16; i++) store32_le(outs[j] + 4 * i, x[i][j]);
}

// 16 independent HChaCha20 derivations (shared key, per-lane nonce16) —
// the AVX-512 shape with the in-register output transpose.
static void hchacha20_x16(const uint8_t key[32],
                          const uint8_t* const nonces[16],
                          uint8_t subkeys[][32], int count) {
  uint32_t kw[8];
  for (int i = 0; i < 8; i++) kw[i] = load32_le(key + 4 * i);
  v16u x[16];
  for (int i = 0; i < 4; i++) x[i] = SIGMA[i] - (v16u){};
  for (int i = 0; i < 8; i++) x[4 + i] = kw[i] - (v16u){};
  for (int i = 0; i < 4; i++)
    for (int j = 0; j < 16; j++)
      x[12 + i][j] = load32_le(nonces[j < count ? j : 0] + 4 * i);
  for (int r = 0; r < 10; r++) {
#define QRX(a, b, c, d)                                      \
  x[a] += x[b]; x[d] ^= x[a]; x[d] = rotlv16(x[d], 16);      \
  x[c] += x[d]; x[b] ^= x[c]; x[b] = rotlv16(x[b], 12);      \
  x[a] += x[b]; x[d] ^= x[a]; x[d] = rotlv16(x[d], 8);       \
  x[c] += x[d]; x[b] ^= x[c]; x[b] = rotlv16(x[b], 7);
    QRX(0, 4, 8, 12) QRX(1, 5, 9, 13) QRX(2, 6, 10, 14) QRX(3, 7, 11, 15)
    QRX(0, 5, 10, 15) QRX(1, 6, 11, 12) QRX(2, 7, 8, 13) QRX(3, 4, 9, 14)
  }
  for (int j = 0; j < count; j++) {
    for (int i = 0; i < 4; i++) store32_le(subkeys[j] + 4 * i, x[i][j]);
    for (int i = 0; i < 4; i++)
      store32_le(subkeys[j] + 16 + 4 * i, x[12 + i][j]);
  }
}

// 16 independent ChaCha20 blocks, each with its own key/nonce/counter
// (the fully general lane shape: Poly1305 one-time keys AND data
// keystream blocks of different files batch together).
static void chacha20_block_x16(const uint8_t* const keys[16],
                               const uint32_t counters[16],
                               const uint8_t* const nonces12[16],
                               uint8_t outs[][64], int count) {
  v16u x[16], iv[16];
  for (int i = 0; i < 4; i++) iv[i] = SIGMA[i] - (v16u){};
  for (int i = 0; i < 8; i++)
    for (int j = 0; j < 16; j++)
      iv[4 + i][j] = load32_le(keys[j < count ? j : 0] + 4 * i);
  for (int j = 0; j < 16; j++) iv[12][j] = counters[j < count ? j : 0];
  for (int i = 0; i < 3; i++)
    for (int j = 0; j < 16; j++)
      iv[13 + i][j] = load32_le(nonces12[j < count ? j : 0] + 4 * i);
  for (int i = 0; i < 16; i++) x[i] = iv[i];
  for (int r = 0; r < 10; r++) {
    QRX(0, 4, 8, 12) QRX(1, 5, 9, 13) QRX(2, 6, 10, 14) QRX(3, 7, 11, 15)
    QRX(0, 5, 10, 15) QRX(1, 6, 11, 12) QRX(2, 7, 8, 13) QRX(3, 4, 9, 14)
  }
#undef QRX
  for (int i = 0; i < 16; i++) x[i] += iv[i];
  transpose16(x);  // x[j] = lane j's 16 words = one 64B block
  for (int j = 0; j < count; j++) memcpy(outs[j], &x[j], 64);
}

// ---- lane-parallel Poly1305 ----------------------------------------------
//
// The batched verify pass was the engine's last scalar phase: every
// file's MAC ran the radix-2^44 core one file at a time while the three
// ChaCha phases ran 4/8/16-wide.  Here the MAC goes lane-parallel the
// same way — one FILE per 64-bit vector lane, radix-2^26 limbs so every
// product fits a 64-bit lane (26+26+log2(5·5) ≈ 57 bits worst case).
// The AEAD construction makes lockstep feasible with no partial-block
// machinery at all: the Poly input is always data zero-padded to a
// 16-byte boundary plus one 16-byte length block, i.e. FULL blocks only
// (hibit always set).  Files of different lengths run lockstep with a
// per-lane active mask; a finished lane's accumulator is carried
// through untouched until every lane drains, then each lane finalizes
// scalar (carry/mod-p/pad — a handful of ops per file).
//
// Lane width is half the u32 ChaCha width (64-bit lanes in the same
// registers): 8 on AVX-512, 4 on AVX2, 2 on the SSE2/NEON baseline.

typedef uint64_t v8q __attribute__((vector_size(64)));
typedef uint64_t v4q __attribute__((vector_size(32)));
typedef uint64_t v2q __attribute__((vector_size(16)));

// 32×32→64 widening multiply per 64-bit lane (every Poly1305 operand is
// < 2^28.4).  GCC does not pattern-match a masked 64-bit vector multiply
// into the 1-µop widening form, and the general vpmullq it emits instead
// is microcoded (3 µops, ~5× the latency) — so on x86 the intrinsic is
// named explicitly; elsewhere the plain lane multiply is already the
// target's native form.  The generic template is the fallback for lane
// shapes wider than the build ISA (never dispatched at runtime there).
template <typename VQ>
static inline VQ mul32(VQ a, VQ b) {
  return a * b;
}
#if defined(__x86_64__) || defined(__i386__)
static inline v2q mul32(v2q a, v2q b) {
  return (v2q)_mm_mul_epu32((__m128i)a, (__m128i)b);
}
#if defined(__AVX2__)
static inline v4q mul32(v4q a, v4q b) {
  return (v4q)_mm256_mul_epu32((__m256i)a, (__m256i)b);
}
#endif
#if defined(__AVX512F__)
static inline v8q mul32(v8q a, v8q b) {
  return (v8q)_mm512_mul_epu32((__m512i)a, (__m512i)b);
}
#endif
#endif

template <typename VQ, int L>
static void poly1305_aead_tags_xN(const uint8_t* const* otks,
                                  const uint8_t* const* msgs,
                                  const uint64_t* lens, uint8_t (*tags)[16],
                                  int count) {
  const uint64_t M26 = 0x3ffffff;
  VQ r0{}, r1{}, r2{}, r3{}, r4{};
  VQ h0{}, h1{}, h2{}, h3{}, h4{};
  // clone lanes (a final partial chunk) mirror lane 0 end to end: they
  // compute lane 0's tag into registers nobody reads, which keeps every
  // lane permanently active — no masking for short batches, no
  // out-of-bounds reads
  const uint8_t* msg_of[L];
  uint64_t len_of[L], nblocks[L];
  uint64_t maxb = 0, min_full = UINT64_MAX, min_nb = UINT64_MAX;
  for (int j = 0; j < L; j++) {
    int ix = j < count ? j : 0;
    const uint8_t* k = otks[ix];
    uint64_t t0 = Poly1305::load64(k), t1 = Poly1305::load64(k + 8);
    t0 &= 0x0ffffffc0fffffffULL;  // clamp per spec
    t1 &= 0x0ffffffc0ffffffcULL;
    r0[j] = t0 & M26;
    r1[j] = (t0 >> 26) & M26;
    r2[j] = ((t0 >> 52) | (t1 << 12)) & M26;
    r3[j] = (t1 >> 14) & M26;
    r4[j] = t1 >> 40;
    msg_of[j] = msgs[ix];
    len_of[j] = lens[ix];
    // blocks = ceil(data/16) data blocks (last zero-padded) + the
    // 16-byte length block
    nblocks[j] = len_of[j] / 16 + (len_of[j] % 16 ? 1 : 0) + 1;
    if (nblocks[j] > maxb) maxb = nblocks[j];
    if (nblocks[j] < min_nb) min_nb = nblocks[j];
    if (len_of[j] / 16 < min_full) min_full = len_of[j] / 16;
  }
  const VQ s1 = r1 * 5, s2 = r2 * 5, s3 = r3 * 5, s4 = r4 * 5;
  const VQ M26v = M26 - (VQ){};
  const VQ HIBIT = (1ULL << 24) - (VQ){};

  // r² limbs for the two-block interleave (h' = (h+m₁)·r² + m₂·r —
  // the scalar core's trick: one carry chain per 32 bytes), computed
  // scalar per lane at init: 26-bit limb products fit u64 with room
  // for the 5-term sums
  VQ q0{}, q1{}, q2{}, q3{}, q4{};
  for (int j = 0; j < L; j++) {
    uint64_t a0 = r0[j], a1 = r1[j], a2 = r2[j], a3 = r3[j], a4 = r4[j];
    uint64_t b1 = a1 * 5, b2 = a2 * 5, b3 = a3 * 5, b4 = a4 * 5;
    uint64_t d0 = a0 * a0 + a1 * b4 + a2 * b3 + a3 * b2 + a4 * b1;
    uint64_t d1 = a0 * a1 + a1 * a0 + a2 * b4 + a3 * b3 + a4 * b2;
    uint64_t d2 = a0 * a2 + a1 * a1 + a2 * a0 + a3 * b4 + a4 * b3;
    uint64_t d3 = a0 * a3 + a1 * a2 + a2 * a1 + a3 * a0 + a4 * b4;
    uint64_t d4 = a0 * a4 + a1 * a3 + a2 * a2 + a3 * a1 + a4 * a0;
    uint64_t c;
    c = d0 >> 26; d0 &= M26; d1 += c;
    c = d1 >> 26; d1 &= M26; d2 += c;
    c = d2 >> 26; d2 &= M26; d3 += c;
    c = d3 >> 26; d3 &= M26; d4 += c;
    c = d4 >> 26; d4 &= M26; d0 += c * 5;
    c = d0 >> 26; d0 &= M26; d1 += c;
    q0[j] = d0; q1[j] = d1; q2[j] = d2; q3[j] = d3; q4[j] = d4;
  }
  const VQ t1 = q1 * 5, t2 = q2 * 5, t3 = q3 * 5, t4 = q4 * 5;

  // one block across all lanes: limb split, multiply, reduce — all in
  // vector registers; only the 2 per-lane 8-byte loads are scalar
  uint64_t w0[L], w1[L];
  auto step = [&](VQ active, bool masked) {
    VQ t0v, t1v;
    memcpy(&t0v, w0, sizeof t0v);
    memcpy(&t1v, w1, sizeof t1v);
    VQ m0 = t0v & M26v;
    VQ m1 = (t0v >> 26) & M26v;
    VQ m2 = ((t0v >> 52) | (t1v << 12)) & M26v;
    VQ m3 = (t1v >> 14) & M26v;
    VQ m4 = (t1v >> 40) | HIBIT;  // hibit: every AEAD block is full
    // h' = (h + m)·r mod p; operands ≤ 2^27, products ≤ 2^53, 5-term
    // sums ≤ 2^55.4 — no 128-bit arithmetic needed in the lanes
    VQ x0 = h0 + m0, x1 = h1 + m1, x2 = h2 + m2, x3 = h3 + m3, x4 = h4 + m4;
    VQ d0 = mul32(x0, r0) + mul32(x1, s4) + mul32(x2, s3) + mul32(x3, s2) +
            mul32(x4, s1);
    VQ d1 = mul32(x0, r1) + mul32(x1, r0) + mul32(x2, s4) + mul32(x3, s3) +
            mul32(x4, s2);
    VQ d2 = mul32(x0, r2) + mul32(x1, r1) + mul32(x2, r0) + mul32(x3, s4) +
            mul32(x4, s3);
    VQ d3 = mul32(x0, r3) + mul32(x1, r2) + mul32(x2, r1) + mul32(x3, r0) +
            mul32(x4, s4);
    VQ d4 = mul32(x0, r4) + mul32(x1, r3) + mul32(x2, r2) + mul32(x3, r1) +
            mul32(x4, r0);
    VQ c;
    c = d0 >> 26; d0 &= M26v; d1 += c;
    c = d1 >> 26; d1 &= M26v; d2 += c;
    c = d2 >> 26; d2 &= M26v; d3 += c;
    c = d3 >> 26; d3 &= M26v; d4 += c;
    c = d4 >> 26; d4 &= M26v; d0 += c * 5;
    c = d0 >> 26; d0 &= M26v; d1 += c;
    if (masked) {  // a drained lane's h carries through untouched
      h0 = (d0 & active) | (h0 & ~active);
      h1 = (d1 & active) | (h1 & ~active);
      h2 = (d2 & active) | (h2 & ~active);
      h3 = (d3 & active) | (h3 & ~active);
      h4 = (d4 & active) | (h4 & ~active);
    } else {
      h0 = d0; h1 = d1; h2 = d2; h3 = d3; h4 = d4;
    }
  };

  // lockstep region: every lane is a plain full data block — straight
  // loads, no branches, no mask (the whole batch for equal-size files).
  // Pairs of blocks run the r² interleave: one carry chain per 32 bytes.
  uint64_t b = 0;
  uint64_t u0[L], u1[L];
  for (; b + 2 <= min_full; b += 2) {
    for (int j = 0; j < L; j++) {
      const uint8_t* p = msg_of[j] + b * 16;
      w0[j] = Poly1305::load64(p);
      w1[j] = Poly1305::load64(p + 8);
      u0[j] = Poly1305::load64(p + 16);
      u1[j] = Poly1305::load64(p + 24);
    }
    VQ a0v, a1v, b0v, b1v;
    memcpy(&a0v, w0, sizeof a0v);
    memcpy(&a1v, w1, sizeof a1v);
    memcpy(&b0v, u0, sizeof b0v);
    memcpy(&b1v, u1, sizeof b1v);
    VQ x0 = h0 + (a0v & M26v);
    VQ x1 = h1 + ((a0v >> 26) & M26v);
    VQ x2 = h2 + (((a0v >> 52) | (a1v << 12)) & M26v);
    VQ x3 = h3 + ((a1v >> 14) & M26v);
    VQ x4 = h4 + ((a1v >> 40) | HIBIT);
    VQ y0 = b0v & M26v;
    VQ y1 = (b0v >> 26) & M26v;
    VQ y2 = ((b0v >> 52) | (b1v << 12)) & M26v;
    VQ y3 = (b1v >> 14) & M26v;
    VQ y4 = (b1v >> 40) | HIBIT;
    // 10-term sums of ≤2^53 products stay under 2^57 — still lane-safe
    VQ d0 = mul32(x0, q0) + mul32(x1, t4) + mul32(x2, t3) + mul32(x3, t2) +
            mul32(x4, t1) + mul32(y0, r0) + mul32(y1, s4) + mul32(y2, s3) +
            mul32(y3, s2) + mul32(y4, s1);
    VQ d1 = mul32(x0, q1) + mul32(x1, q0) + mul32(x2, t4) + mul32(x3, t3) +
            mul32(x4, t2) + mul32(y0, r1) + mul32(y1, r0) + mul32(y2, s4) +
            mul32(y3, s3) + mul32(y4, s2);
    VQ d2 = mul32(x0, q2) + mul32(x1, q1) + mul32(x2, q0) + mul32(x3, t4) +
            mul32(x4, t3) + mul32(y0, r2) + mul32(y1, r1) + mul32(y2, r0) +
            mul32(y3, s4) + mul32(y4, s3);
    VQ d3 = mul32(x0, q3) + mul32(x1, q2) + mul32(x2, q1) + mul32(x3, q0) +
            mul32(x4, t4) + mul32(y0, r3) + mul32(y1, r2) + mul32(y2, r1) +
            mul32(y3, r0) + mul32(y4, s4);
    VQ d4 = mul32(x0, q4) + mul32(x1, q3) + mul32(x2, q2) + mul32(x3, q1) +
            mul32(x4, q0) + mul32(y0, r4) + mul32(y1, r3) + mul32(y2, r2) +
            mul32(y3, r1) + mul32(y4, r0);
    VQ c;
    c = d0 >> 26; d0 &= M26v; d1 += c;
    c = d1 >> 26; d1 &= M26v; d2 += c;
    c = d2 >> 26; d2 &= M26v; d3 += c;
    c = d3 >> 26; d3 &= M26v; d4 += c;
    c = d4 >> 26; d4 &= M26v; d0 += c * 5;
    c = d0 >> 26; d0 &= M26v; d1 += c;
    h0 = d0; h1 = d1; h2 = d2; h3 = d3; h4 = d4;
  }
  for (; b < min_full; b++) {
    for (int j = 0; j < L; j++) {
      const uint8_t* p = msg_of[j] + b * 16;
      w0[j] = Poly1305::load64(p);
      w1[j] = Poly1305::load64(p + 8);
    }
    step(VQ{}, false);
  }
  // ragged tail: per-lane pad/lens-block assembly + drain masking
  for (; b < maxb; b++) {
    VQ active{};
    for (int j = 0; j < L; j++) {
      if (b >= nblocks[j]) { w0[j] = w1[j] = 0; continue; }
      active[j] = ~0ULL;
      uint64_t dlen = len_of[j];
      uint64_t full = dlen / 16;
      if (b + 1 == nblocks[j]) {  // the length block: aad_len(0) ‖ ct_len
        w0[j] = 0;
        w1[j] = dlen;
      } else if (b < full) {
        const uint8_t* p = msg_of[j] + b * 16;
        w0[j] = Poly1305::load64(p);
        w1[j] = Poly1305::load64(p + 8);
      } else {  // final partial data block, zero-padded by the AEAD
        uint8_t blk[16] = {0};
        memcpy(blk, msg_of[j] + full * 16, dlen - full * 16);
        w0[j] = Poly1305::load64(blk);
        w1[j] = Poly1305::load64(blk + 8);
      }
    }
    step(active, b >= min_nb);
  }

  for (int j = 0; j < count; j++) {  // scalar finalize per lane
    uint64_t a0 = h0[j], a1 = h1[j], a2 = h2[j], a3 = h3[j], a4 = h4[j];
    uint64_t c;
    c = a1 >> 26; a1 &= M26; a2 += c;
    c = a2 >> 26; a2 &= M26; a3 += c;
    c = a3 >> 26; a3 &= M26; a4 += c;
    c = a4 >> 26; a4 &= M26; a0 += c * 5;
    c = a0 >> 26; a0 &= M26; a1 += c;
    // g = h - p = h + 5 - 2^130; select g when h >= p (no borrow out)
    uint64_t g0 = a0 + 5;
    c = g0 >> 26; g0 &= M26;
    uint64_t g1 = a1 + c;
    c = g1 >> 26; g1 &= M26;
    uint64_t g2 = a2 + c;
    c = g2 >> 26; g2 &= M26;
    uint64_t g3 = a3 + c;
    c = g3 >> 26; g3 &= M26;
    uint64_t g4 = a4 + c - (1ULL << 26);
    uint64_t mask = (g4 >> 63) - 1;  // all-ones iff no borrow (h >= p)
    a0 = (a0 & ~mask) | (g0 & mask);
    a1 = (a1 & ~mask) | (g1 & mask);
    a2 = (a2 & ~mask) | (g2 & mask);
    a3 = (a3 & ~mask) | (g3 & mask);
    a4 = (a4 & ~mask) | (g4 & M26 & mask);
    uint64_t f0 = a0 | (a1 << 26) | (a2 << 52);
    uint64_t f1 = (a2 >> 12) | (a3 << 14) | (a4 << 40);
    const uint8_t* k = otks[j];
    using u128 = unsigned __int128;
    u128 acc = (u128)f0 + Poly1305::load64(k + 16);
    store64_le(tags[j], (uint64_t)acc);
    acc = (u128)f1 + Poly1305::load64(k + 24) + (uint64_t)(acc >> 64);
    store64_le(tags[j] + 8, (uint64_t)acc);
  }
}

#if defined(__AVX512IFMA__)
// The AVX-512 IFMA shape: radix-2^44 limbs (the scalar core's radix)
// with vpmadd52lo/hi doing the 44×48-bit products directly — 18 madds
// per 16-byte block across 8 files (2.25/file) vs the scalar core's 9
// mulx per file.  Product high halves land at 2^52, i.e. 2^8·2^44, so
// every hi lane is pure carry after an 8-bit shift — no 128-bit
// arithmetic anywhere.
static void poly1305_aead_tags_ifma8(const uint8_t* const* otks,
                                     const uint8_t* const* msgs,
                                     const uint64_t* lens, uint8_t (*tags)[16],
                                     int count) {
  const uint64_t M44 = 0xfffffffffffULL, M42 = 0x3ffffffffffULL;
  typedef v8q VQ;
  VQ r0{}, r1{}, r2{};
  VQ h0{}, h1{}, h2{};
  const uint8_t* msg_of[8];
  uint64_t len_of[8], nblocks[8];
  uint64_t maxb = 0, min_full = UINT64_MAX, min_nb = UINT64_MAX;
  for (int j = 0; j < 8; j++) {
    int ix = j < count ? j : 0;  // clone lanes mirror lane 0 (see xN)
    const uint8_t* k = otks[ix];
    uint64_t t0 = Poly1305::load64(k), t1 = Poly1305::load64(k + 8);
    t0 &= 0x0ffffffc0fffffffULL;
    t1 &= 0x0ffffffc0ffffffcULL;
    r0[j] = t0 & M44;
    r1[j] = ((t0 >> 44) | (t1 << 20)) & M44;
    r2[j] = t1 >> 24;
    msg_of[j] = msgs[ix];
    len_of[j] = lens[ix];
    nblocks[j] = len_of[j] / 16 + (len_of[j] % 16 ? 1 : 0) + 1;
    if (nblocks[j] > maxb) maxb = nblocks[j];
    if (nblocks[j] < min_nb) min_nb = nblocks[j];
    if (len_of[j] / 16 < min_full) min_full = len_of[j] / 16;
  }
  const VQ s1 = r1 * 20, s2 = r2 * 20;  // < 2^48.4: valid madd52 operands
  const VQ M44v = M44 - (VQ){}, M42v = M42 - (VQ){};
  const VQ HIB = (1ULL << 40) - (VQ){};

  auto madlo = [](VQ acc, VQ a, VQ b) {
    return (VQ)_mm512_madd52lo_epu64((__m512i)acc, (__m512i)a, (__m512i)b);
  };
  auto madhi = [](VQ acc, VQ a, VQ b) {
    return (VQ)_mm512_madd52hi_epu64((__m512i)acc, (__m512i)a, (__m512i)b);
  };

  uint64_t w0[8], w1[8];
  auto step = [&](VQ active, bool masked) {
    VQ t0v, t1v;
    memcpy(&t0v, w0, sizeof t0v);
    memcpy(&t1v, w1, sizeof t1v);
    VQ x0 = h0 + (t0v & M44v);
    VQ x1 = h1 + (((t0v >> 44) | (t1v << 20)) & M44v);
    VQ x2 = h2 + (((t1v >> 24) & M42v) | HIB);  // hibit: blocks all full
    VQ lo0{}, hi0{}, lo1{}, hi1{}, lo2{}, hi2{};
    lo0 = madlo(lo0, x0, r0); hi0 = madhi(hi0, x0, r0);
    lo0 = madlo(lo0, x1, s2); hi0 = madhi(hi0, x1, s2);
    lo0 = madlo(lo0, x2, s1); hi0 = madhi(hi0, x2, s1);
    lo1 = madlo(lo1, x0, r1); hi1 = madhi(hi1, x0, r1);
    lo1 = madlo(lo1, x1, r0); hi1 = madhi(hi1, x1, r0);
    lo1 = madlo(lo1, x2, s2); hi1 = madhi(hi1, x2, s2);
    lo2 = madlo(lo2, x0, r2); hi2 = madhi(hi2, x0, r2);
    lo2 = madlo(lo2, x1, r1); hi2 = madhi(hi2, x1, r1);
    lo2 = madlo(lo2, x2, r0); hi2 = madhi(hi2, x2, r0);
    VQ c;
    c = (lo0 >> 44) + (hi0 << 8);
    lo0 &= M44v; lo1 += c;
    c = (lo1 >> 44) + (hi1 << 8);
    lo1 &= M44v; lo2 += c;
    c = (lo2 >> 42) + (hi2 << 10);
    lo2 &= M42v; lo0 += c * 5;
    c = lo0 >> 44; lo0 &= M44v; lo1 += c;
    if (masked) {
      h0 = (lo0 & active) | (h0 & ~active);
      h1 = (lo1 & active) | (h1 & ~active);
      h2 = (lo2 & active) | (h2 & ~active);
    } else {
      h0 = lo0; h1 = lo1; h2 = lo2;
    }
  };

  uint64_t b = 0;
  for (; b < min_full; b++) {  // lockstep: plain full data blocks
    for (int j = 0; j < 8; j++) {
      const uint8_t* p = msg_of[j] + b * 16;
      w0[j] = Poly1305::load64(p);
      w1[j] = Poly1305::load64(p + 8);
    }
    step(VQ{}, false);
  }
  for (; b < maxb; b++) {  // ragged tail: pad/lens blocks + drain mask
    VQ active{};
    for (int j = 0; j < 8; j++) {
      if (b >= nblocks[j]) { w0[j] = w1[j] = 0; continue; }
      active[j] = ~0ULL;
      uint64_t dlen = len_of[j];
      uint64_t full = dlen / 16;
      if (b + 1 == nblocks[j]) {
        w0[j] = 0;
        w1[j] = dlen;
      } else if (b < full) {
        const uint8_t* p = msg_of[j] + b * 16;
        w0[j] = Poly1305::load64(p);
        w1[j] = Poly1305::load64(p + 8);
      } else {
        uint8_t blk[16] = {0};
        memcpy(blk, msg_of[j] + full * 16, dlen - full * 16);
        w0[j] = Poly1305::load64(blk);
        w1[j] = Poly1305::load64(blk + 8);
      }
    }
    step(active, b >= min_nb);
  }

  for (int j = 0; j < count; j++) {  // scalar finalize (Poly1305::finish)
    uint64_t a0 = h0[j], a1 = h1[j], a2 = h2[j];
    uint64_t c;
    c = a1 >> 44; a1 &= M44; a2 += c;
    c = a2 >> 42; a2 &= M42; a0 += c * 5;
    c = a0 >> 44; a0 &= M44; a1 += c;
    c = a1 >> 44; a1 &= M44; a2 += c;
    c = a2 >> 42; a2 &= M42; a0 += c * 5;
    c = a0 >> 44; a0 &= M44; a1 += c;
    uint64_t g0 = a0 + 5;
    c = g0 >> 44; g0 &= M44;
    uint64_t g1 = a1 + c;
    c = g1 >> 44; g1 &= M44;
    uint64_t g2 = a2 + c - (1ULL << 42);
    uint64_t mask = (g2 >> 63) - 1;
    a0 = (a0 & ~mask) | (g0 & mask);
    a1 = (a1 & ~mask) | (g1 & mask);
    a2 = (a2 & ~mask) | (g2 & M42 & mask);
    uint64_t f0 = a0 | (a1 << 44);
    uint64_t f1 = (a1 >> 20) | (a2 << 24);
    const uint8_t* k = otks[j];
    using u128 = unsigned __int128;
    u128 acc = (u128)f0 + Poly1305::load64(k + 16);
    store64_le(tags[j], (uint64_t)acc);
    acc = (u128)f1 + Poly1305::load64(k + 24) + (uint64_t)(acc >> 64);
    store64_le(tags[j] + 8, (uint64_t)acc);
  }
}

// the .so may have been built on an IFMA box and copied — same
// degrade-don't-fault contract as simd_lanes_detect()
static bool ifma_detect() {
#if (defined(__x86_64__) || defined(__i386__)) && defined(__GNUC__)
  __builtin_cpu_init();
  return __builtin_cpu_supports("avx512ifma") != 0;
#else
  return false;
#endif
}
static const bool HAVE_IFMA = ifma_detect();
#endif  // __AVX512IFMA__

// Runtime-dispatched batch front door: AEAD tags (zero AAD — the op-blob
// envelope's shape) for n (one-time key, message, length) triples, in
// lane-width chunks.  Shared by the engine's verify phase and the
// poly1305_aead_tags FFI export the differential tests drive.
static void poly1305_aead_tags_batch(const uint8_t* const* otks,
                                     const uint8_t* const* msgs,
                                     const uint64_t* lens,
                                     uint8_t (*tags)[16], uint64_t n) {
  uint64_t i = 0;
#if defined(__AVX512IFMA__)
  if (HAVE_IFMA && SIMD_LANES >= LANES16) {
    for (; i + 8 <= n; i += 8)
      poly1305_aead_tags_ifma8(otks + i, msgs + i, lens + i, tags + i, 8);
    if (i < n) {
      poly1305_aead_tags_ifma8(otks + i, msgs + i, lens + i, tags + i,
                               (int)(n - i));
      i = n;
    }
    return;
  }
#endif
  if (SIMD_LANES >= LANES16) {
    for (; i + 8 <= n; i += 8)
      poly1305_aead_tags_xN<v8q, 8>(otks + i, msgs + i, lens + i, tags + i, 8);
  } else if (SIMD_LANES >= LANES) {
    for (; i + 4 <= n; i += 4)
      poly1305_aead_tags_xN<v4q, 4>(otks + i, msgs + i, lens + i, tags + i, 4);
  }
  for (; i < n; i += 2) {
    int c = (int)(n - i < 2 ? n - i : 2);
    poly1305_aead_tags_xN<v2q, 2>(otks + i, msgs + i, lens + i, tags + i, c);
  }
}

// Per-lane-width kernel selection for the batched engine: 16 lanes use
// the transpose-optimized AVX-512 shapes above, narrower widths the
// generic templates (scalar lane extraction — 8/4 lanes have too few
// words per register for the butterfly transpose to pay).
template <int L> struct BatchKern;
template <> struct BatchKern<4> {
  static void hch(const uint8_t key[32], const uint8_t* const* nonces,
                  uint8_t (*sk)[32], int c) {
    hchacha20_xN<v4u, 4>(key, nonces, sk, c);
  }
  static void blk(const uint8_t* const* keys, const uint32_t* ctr,
                  const uint8_t* const* n12, uint8_t (*o)[64], int c) {
    chacha20_block_xN<v4u, 4>(keys, ctr, n12, o, c);
  }
};
template <> struct BatchKern<8> {
  static void hch(const uint8_t key[32], const uint8_t* const* nonces,
                  uint8_t (*sk)[32], int c) {
    hchacha20_xN<v8u, 8>(key, nonces, sk, c);
  }
  static void blk(const uint8_t* const* keys, const uint32_t* ctr,
                  const uint8_t* const* n12, uint8_t (*o)[64], int c) {
    chacha20_block_xN<v8u, 8>(keys, ctr, n12, o, c);
  }
};
template <> struct BatchKern<16> {
  static void hch(const uint8_t key[32], const uint8_t* const* nonces,
                  uint8_t (*sk)[32], int c) {
    hchacha20_x16(key, nonces, sk, c);
  }
  static void blk(const uint8_t* const* keys, const uint32_t* ctr,
                  const uint8_t* const* n12, uint8_t (*o)[64], int c) {
    chacha20_block_x16(keys, ctr, n12, o, c);
  }
};

// Batched decrypt of n same-key blobs: three vectorized ChaCha phases
// (subkeys, one-time poly keys, data keystream jobs) + a batched scalar
// Poly1305 verification pass.  Writes cleartext only where the tag
// verifies.  Lane width L follows the runtime dispatch (see the
// non-template front door below).
template <int L>
static int encbox_decrypt_batched_impl(
    const uint8_t* key, const uint8_t* blobs, const uint64_t* nonce_offs,
    const uint64_t* ct_offs, const uint64_t* ct_lens, uint64_t n,
    uint8_t* out, const uint64_t* out_offs, uint8_t* ok_flags) {
  std::vector<std::array<uint8_t, 32>> subkeys(n);
  std::vector<std::array<uint8_t, 12>> n12(n);
  std::vector<std::array<uint8_t, 64>> otk(n);

  // phase 1: subkeys (HChaCha20 over nonce24[0:16))
  for (uint64_t i = 0; i < n; i += L) {
    int c = (int)((n - i) < (uint64_t)L ? (n - i) : (uint64_t)L);
    const uint8_t* np[L];
    uint8_t(*sk)[32] = (uint8_t(*)[32])subkeys[i].data();
    for (int j = 0; j < L; j++)
      np[j] = blob_at(blobs, nonce_offs[i + (j < c ? j : 0)]);
    BatchKern<L>::hch(key, np, sk, c);
  }
  for (uint64_t i = 0; i < n; i++) {
    memset(n12[i].data(), 0, 4);
    memcpy(n12[i].data() + 4, blob_at(blobs, nonce_offs[i]) + 16, 8);
  }
  // phase 2: Poly1305 one-time keys (block 0 of each file's stream)
  for (uint64_t i = 0; i < n; i += L) {
    int c = (int)((n - i) < (uint64_t)L ? (n - i) : (uint64_t)L);
    const uint8_t* kp[L];
    const uint8_t* np[L];
    uint32_t ctr[L] = {0};
    uint8_t(*op)[64] = (uint8_t(*)[64])otk[i].data();
    for (int j = 0; j < L; j++) {
      uint64_t ix = i + (j < c ? j : 0);
      kp[j] = subkeys[ix].data();
      np[j] = n12[ix].data();
    }
    BatchKern<L>::blk(kp, ctr, np, op, c);
  }
  // phase 3: lane-parallel Poly1305 pass — every file's tag computed
  // one-file-per-lane (poly1305_aead_tags_batch) and verified BEFORE
  // any keystream XOR, matching the scalar path's verify-then-decrypt
  // order: a blob whose tag fails must never have plaintext written
  int failures = 0;
  std::vector<const uint8_t*> mac_keys(n);
  std::vector<const uint8_t*> mac_msgs(n);
  std::vector<uint64_t> mac_lens(n);
  std::vector<std::array<uint8_t, 16>> mac_tags(n);
  uint64_t n_mac = 0;
  for (uint64_t i = 0; i < n; i++) {
    if (ct_lens[i] < 16) {
      ok_flags[i] = 0;
      failures++;
      continue;
    }
    ok_flags[i] = 2;  // marks "tag pending" for the verify sweep below
    mac_keys[n_mac] = otk[i].data();
    mac_msgs[n_mac] = blob_at(blobs, ct_offs[i]);
    mac_lens[n_mac] = ct_lens[i] - 16;
    n_mac++;
  }
  poly1305_aead_tags_batch(mac_keys.data(), mac_msgs.data(), mac_lens.data(),
                           (uint8_t(*)[16])mac_tags.data()->data(), n_mac);
  for (uint64_t i = 0, q = 0; i < n; i++) {
    if (ok_flags[i] != 2) continue;
    int rc = ct_compare16(mac_tags[q].data(), mac_msgs[q] + mac_lens[q]);
    ok_flags[i] = rc == 0 ? 1 : 0;
    if (rc != 0) failures++;
    q++;
  }
  // phase 4: data keystream jobs (file, block counter) for VERIFIED
  // files only, 16 at a time, XORed into the scattered output positions
  struct Job { uint64_t file; uint32_t ctr; };
  std::vector<Job> jobs;
  jobs.reserve(n * 3);
  for (uint64_t i = 0; i < n; i++) {
    if (!ok_flags[i]) continue;
    uint64_t data_len = ct_lens[i] - 16;
    for (uint64_t b = 0; b * 64 < data_len; b++)
      jobs.push_back({i, (uint32_t)(b + 1)});
  }
  uint8_t ks[L][64];
  for (size_t q = 0; q < jobs.size(); q += L) {
    int c = (int)((jobs.size() - q) < (size_t)L ? (jobs.size() - q) : (size_t)L);
    const uint8_t* kp[L];
    const uint8_t* np[L];
    uint32_t ctr[L];
    for (int j = 0; j < L; j++) {
      const Job& jb = jobs[q + (j < c ? j : 0)];
      kp[j] = subkeys[jb.file].data();
      np[j] = n12[jb.file].data();
      ctr[j] = jb.ctr;
    }
    BatchKern<L>::blk(kp, ctr, np, ks, c);
    for (int j = 0; j < c; j++) {
      const Job& jb = jobs[q + j];
      uint64_t data_len = ct_lens[jb.file] - 16;
      uint64_t off = (uint64_t)(jb.ctr - 1) * 64;
      uint64_t m = data_len - off < 64 ? data_len - off : 64;
      const uint8_t* src = blob_at(blobs, ct_offs[jb.file]) + off;
      uint8_t* dst = out + out_offs[jb.file] + off;
      for (uint64_t b = 0; b < m; b++) dst[b] = src[b] ^ ks[j][b];
    }
  }
  return failures;
}

// Runtime-dispatched front door: widest lane shape the build AND the
// running CPU both support (SIMD_LANES), so one .so degrades gracefully
// instead of faulting on a narrower host.
static int encbox_decrypt_batched(const uint8_t* key, const uint8_t* blobs,
                                  const uint64_t* nonce_offs,
                                  const uint64_t* ct_offs,
                                  const uint64_t* ct_lens, uint64_t n,
                                  uint8_t* out, const uint64_t* out_offs,
                                  uint8_t* ok_flags) {
  if (SIMD_LANES >= LANES16)
    return encbox_decrypt_batched_impl<16>(key, blobs, nonce_offs, ct_offs,
                                           ct_lens, n, out, out_offs,
                                           ok_flags);
  if (SIMD_LANES >= LANES)
    return encbox_decrypt_batched_impl<8>(key, blobs, nonce_offs, ct_offs,
                                          ct_lens, n, out, out_offs,
                                          ok_flags);
  return encbox_decrypt_batched_impl<4>(key, blobs, nonce_offs, ct_offs,
                                        ct_lens, n, out, out_offs, ok_flags);
}

}  // namespace (batched decrypt engine)

extern "C" {

// Threaded batch decrypt reading nonce/ct in place via the offsets the
// parse produced — zero intermediate copies.  Output spans are disjoint
// (out_offs from an exclusive scan of ct_lens-16).  Returns failure count.
int encbox_decrypt_scatter_mt(const uint8_t* key, const uint8_t* blobs,
                              const uint64_t* nonce_offs,
                              const uint64_t* ct_offs,
                              const uint64_t* ct_lens, uint64_t n,
                              uint8_t* out, const uint64_t* out_offs,
                              uint8_t* ok_flags, int n_threads) {
  if (n_threads <= 0) n_threads = 1;
  if ((uint64_t)n_threads > n) n_threads = (int)(n ? n : 1);
  auto work = [&](uint64_t lo, uint64_t hi, int* fail_out) {
    if (hi - lo >= 32) {  // 16-lane batched kernel per worker range
      *fail_out = encbox_decrypt_batched(
          key, blobs, nonce_offs + lo, ct_offs + lo, ct_lens + lo, hi - lo,
          out, out_offs + lo, ok_flags + lo);
      return;
    }
    int f = 0;
    for (uint64_t i = lo; i < hi; i++) {
      int rc = xchacha20poly1305_decrypt(
          key, blob_at(blobs, nonce_offs[i]), nullptr, 0,
          blob_at(blobs, ct_offs[i]),
          ct_lens[i], out + out_offs[i]);
      ok_flags[i] = rc == 0 ? 1 : 0;
      if (rc != 0) f++;
    }
    *fail_out = f;
  };
  if (n_threads <= 1 || n < 2) {
    if (n >= 32)
      return encbox_decrypt_batched(key, blobs, nonce_offs, ct_offs, ct_lens,
                                    n, out, out_offs, ok_flags);
    int f = 0;
    work(0, n, &f);
    return f;
  }
  std::vector<std::thread> workers;
  std::vector<int> fails((size_t)n_threads, 0);
  uint64_t stride = (n + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; t++) {
    uint64_t lo = t * stride;
    uint64_t hi = lo + stride < n ? lo + stride : n;
    if (lo >= hi) break;
    workers.emplace_back([&, lo, hi, t]() { work(lo, hi, &fails[t]); });
  }
  for (auto& w : workers) w.join();
  int failures = 0;
  for (int f : fails) failures += f;
  return failures;
}

}  // extern "C"

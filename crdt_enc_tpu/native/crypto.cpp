// XChaCha20-Poly1305 AEAD — the native cipher backend.
//
// The reference delegates to the Rust chacha20poly1305 crate
// (crdt-enc-xchacha20poly1305/src/lib.rs:40-102); this environment has no
// Rust toolchain and its Python `cryptography` wheel exposes only the IETF
// 12-byte-nonce ChaCha20Poly1305, so the XChaCha construction (HChaCha20
// subkey derivation + ChaCha20-Poly1305, draft-irtf-cfrg-xchacha) is
// implemented here from RFC 8439 primitives.  The IETF mode is exported too
// so tests can cross-validate this implementation against the cryptography
// wheel as an independent oracle.
//
// Exposed via a plain C ABI for ctypes; every entry point releases no GIL
// concerns (pure C, no Python API).  Batch entry points let the bulk
// decrypt front end amortize FFI overhead across thousands of blobs.

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

inline uint32_t rotl32(uint32_t x, int n) { return (x << n) | (x >> (32 - n)); }

inline uint32_t load32_le(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}

inline void store32_le(uint8_t* p, uint32_t v) {
  p[0] = (uint8_t)v;
  p[1] = (uint8_t)(v >> 8);
  p[2] = (uint8_t)(v >> 16);
  p[3] = (uint8_t)(v >> 24);
}

inline void store64_le(uint8_t* p, uint64_t v) {
  store32_le(p, (uint32_t)v);
  store32_le(p + 4, (uint32_t)(v >> 32));
}

#define QR(a, b, c, d)      \
  a += b; d ^= a; d = rotl32(d, 16); \
  c += d; b ^= c; b = rotl32(b, 12); \
  a += b; d ^= a; d = rotl32(d, 8);  \
  c += d; b ^= c; b = rotl32(b, 7);

void chacha20_rounds(uint32_t s[16]) {
  for (int i = 0; i < 10; i++) {
    QR(s[0], s[4], s[8], s[12])
    QR(s[1], s[5], s[9], s[13])
    QR(s[2], s[6], s[10], s[14])
    QR(s[3], s[7], s[11], s[15])
    QR(s[0], s[5], s[10], s[15])
    QR(s[1], s[6], s[11], s[12])
    QR(s[2], s[7], s[8], s[13])
    QR(s[3], s[4], s[9], s[14])
  }
}

const uint32_t SIGMA[4] = {0x61707865, 0x3320646e, 0x79622d32, 0x6b206574};

// RFC 8439 §2.3: one 64-byte keystream block.
void chacha20_block(const uint8_t key[32], uint32_t counter,
                    const uint8_t nonce[12], uint8_t out[64]) {
  uint32_t init[16], s[16];
  for (int i = 0; i < 4; i++) init[i] = SIGMA[i];
  for (int i = 0; i < 8; i++) init[4 + i] = load32_le(key + 4 * i);
  init[12] = counter;
  for (int i = 0; i < 3; i++) init[13 + i] = load32_le(nonce + 4 * i);
  memcpy(s, init, sizeof(s));
  chacha20_rounds(s);
  for (int i = 0; i < 16; i++) store32_le(out + 4 * i, s[i] + init[i]);
}

// 8 independent keystream blocks with the state in GCC vector-extension
// registers (one v8u per ChaCha word, lanes = consecutive block
// counters): every quarter-round statement is a single elementwise
// vector op, which gcc/clang lower to AVX2/AVX-512 under -march=native —
// auto-vectorization of the equivalent scalar lane loops was observed to
// fail (no vector shifts emitted), so the SIMD shape is made explicit.
constexpr int LANES = 8;
typedef uint32_t v8u __attribute__((vector_size(4 * LANES)));

static inline v8u rotlv(v8u x, int n) {
  return (x << n) | (x >> (32 - n));
}

void chacha20_xor_lanes(const uint8_t key[32], uint32_t counter,
                        const uint8_t nonce[12], const uint8_t* in,
                        uint8_t* out) {
  uint32_t init[16];
  for (int i = 0; i < 4; i++) init[i] = SIGMA[i];
  for (int i = 0; i < 8; i++) init[4 + i] = load32_le(key + 4 * i);
  init[12] = counter;
  for (int i = 0; i < 3; i++) init[13 + i] = load32_le(nonce + 4 * i);

  v8u x[16];
  for (int i = 0; i < 16; i++)
    for (int j = 0; j < LANES; j++) x[i][j] = init[i];
  for (int j = 0; j < LANES; j++) x[12][j] = counter + (uint32_t)j;

#define QRV(a, b, c, d)                                      \
  x[a] += x[b]; x[d] ^= x[a]; x[d] = rotlv(x[d], 16);        \
  x[c] += x[d]; x[b] ^= x[c]; x[b] = rotlv(x[b], 12);        \
  x[a] += x[b]; x[d] ^= x[a]; x[d] = rotlv(x[d], 8);         \
  x[c] += x[d]; x[b] ^= x[c]; x[b] = rotlv(x[b], 7);

  for (int r = 0; r < 10; r++) {
    QRV(0, 4, 8, 12)
    QRV(1, 5, 9, 13)
    QRV(2, 6, 10, 14)
    QRV(3, 7, 11, 15)
    QRV(0, 5, 10, 15)
    QRV(1, 6, 11, 12)
    QRV(2, 7, 8, 13)
    QRV(3, 4, 9, 14)
  }
#undef QRV

  for (int j = 0; j < LANES; j++) {
    const uint8_t* src = in + (uint64_t)j * 64;
    uint8_t* dst = out + (uint64_t)j * 64;
    for (int i = 0; i < 16; i++) {
      uint32_t word = x[i][j] + init[i] + (i == 12 ? (uint32_t)j : 0);
      store32_le(dst + 4 * i, load32_le(src + 4 * i) ^ word);
    }
  }
}

void chacha20_xor(const uint8_t key[32], uint32_t counter,
                  const uint8_t nonce[12], const uint8_t* in, uint8_t* out,
                  uint64_t len) {
  while (len >= 64 * LANES) {
    chacha20_xor_lanes(key, counter, nonce, in, out);
    counter += LANES;
    in += 64 * LANES;
    out += 64 * LANES;
    len -= 64 * LANES;
  }
  uint8_t block[64];
  while (len > 0) {
    chacha20_block(key, counter++, nonce, block);
    uint64_t n = len < 64 ? len : 64;
    for (uint64_t i = 0; i < n; i++) out[i] = in[i] ^ block[i];
    in += n;
    out += n;
    len -= n;
  }
}

// draft-irtf-cfrg-xchacha §2.2: rounds over const|key|nonce16, no final
// add; subkey = words 0..3 and 12..15.
void hchacha20_impl(const uint8_t key[32], const uint8_t nonce16[16],
                    uint8_t out32[32]) {
  uint32_t s[16];
  for (int i = 0; i < 4; i++) s[i] = SIGMA[i];
  for (int i = 0; i < 8; i++) s[4 + i] = load32_le(key + 4 * i);
  for (int i = 0; i < 4; i++) s[12 + i] = load32_le(nonce16 + 4 * i);
  chacha20_rounds(s);
  for (int i = 0; i < 4; i++) store32_le(out32 + 4 * i, s[i]);
  for (int i = 0; i < 4; i++) store32_le(out32 + 16 + 4 * i, s[12 + i]);
}

// ---- Poly1305 (RFC 8439 §2.5), 26-bit limbs -----------------------------

struct Poly1305 {
  uint32_t r[5];
  uint32_t h[5];
  uint32_t pad[4];
  uint8_t buf[16];
  unsigned buflen = 0;

  void init(const uint8_t key[32]) {
    // r clamped per spec
    uint32_t t0 = load32_le(key + 0), t1 = load32_le(key + 4),
             t2 = load32_le(key + 8), t3 = load32_le(key + 12);
    r[0] = t0 & 0x3ffffff;
    r[1] = ((t0 >> 26) | (t1 << 6)) & 0x3ffff03;
    r[2] = ((t1 >> 20) | (t2 << 12)) & 0x3ffc0ff;
    r[3] = ((t2 >> 14) | (t3 << 18)) & 0x3f03fff;
    r[4] = (t3 >> 8) & 0x00fffff;
    memset(h, 0, sizeof(h));
    for (int i = 0; i < 4; i++) pad[i] = load32_le(key + 16 + 4 * i);
  }

  void block(const uint8_t* m, uint32_t hibit /* 1<<24 or 0 */) {
    uint32_t t0 = load32_le(m + 0), t1 = load32_le(m + 4),
             t2 = load32_le(m + 8), t3 = load32_le(m + 12);
    h[0] += t0 & 0x3ffffff;
    h[1] += ((t0 >> 26) | (t1 << 6)) & 0x3ffffff;
    h[2] += ((t1 >> 20) | (t2 << 12)) & 0x3ffffff;
    h[3] += ((t2 >> 14) | (t3 << 18)) & 0x3ffffff;
    h[4] += (t3 >> 8) | hibit;

    uint64_t s1 = r[1] * 5, s2 = r[2] * 5, s3 = r[3] * 5, s4 = r[4] * 5;
    uint64_t d0 = (uint64_t)h[0] * r[0] + (uint64_t)h[1] * s4 +
                  (uint64_t)h[2] * s3 + (uint64_t)h[3] * s2 +
                  (uint64_t)h[4] * s1;
    uint64_t d1 = (uint64_t)h[0] * r[1] + (uint64_t)h[1] * r[0] +
                  (uint64_t)h[2] * s4 + (uint64_t)h[3] * s3 +
                  (uint64_t)h[4] * s2;
    uint64_t d2 = (uint64_t)h[0] * r[2] + (uint64_t)h[1] * r[1] +
                  (uint64_t)h[2] * r[0] + (uint64_t)h[3] * s4 +
                  (uint64_t)h[4] * s3;
    uint64_t d3 = (uint64_t)h[0] * r[3] + (uint64_t)h[1] * r[2] +
                  (uint64_t)h[2] * r[1] + (uint64_t)h[3] * r[0] +
                  (uint64_t)h[4] * s4;
    uint64_t d4 = (uint64_t)h[0] * r[4] + (uint64_t)h[1] * r[3] +
                  (uint64_t)h[2] * r[2] + (uint64_t)h[3] * r[1] +
                  (uint64_t)h[4] * r[0];

    uint64_t c;
    c = d0 >> 26; h[0] = (uint32_t)d0 & 0x3ffffff; d1 += c;
    c = d1 >> 26; h[1] = (uint32_t)d1 & 0x3ffffff; d2 += c;
    c = d2 >> 26; h[2] = (uint32_t)d2 & 0x3ffffff; d3 += c;
    c = d3 >> 26; h[3] = (uint32_t)d3 & 0x3ffffff; d4 += c;
    c = d4 >> 26; h[4] = (uint32_t)d4 & 0x3ffffff;
    h[0] += (uint32_t)(c * 5);
    c = h[0] >> 26; h[0] &= 0x3ffffff; h[1] += (uint32_t)c;
  }

  // Streaming update: partial tails are buffered, NOT finalized — multiple
  // update() calls concatenate, exactly like a hash object.
  void update(const uint8_t* m, uint64_t len) {
    if (buflen) {
      uint64_t want = 16 - buflen;
      uint64_t take = len < want ? len : want;
      memcpy(buf + buflen, m, take);
      buflen += (unsigned)take;
      m += take;
      len -= take;
      if (buflen < 16) return;
      block(buf, 1u << 24);
      buflen = 0;
    }
    while (len >= 16) {
      block(m, 1u << 24);
      m += 16;
      len -= 16;
    }
    if (len) {
      memcpy(buf, m, len);
      buflen = (unsigned)len;
    }
  }

  void finish(uint8_t tag[16]) {
    if (buflen) {  // final partial block: append 0x01, zero-fill, no hibit
      buf[buflen] = 1;
      for (unsigned i = buflen + 1; i < 16; i++) buf[i] = 0;
      block(buf, 0);
      buflen = 0;
    }
    // full carry
    uint32_t c;
    c = h[1] >> 26; h[1] &= 0x3ffffff; h[2] += c;
    c = h[2] >> 26; h[2] &= 0x3ffffff; h[3] += c;
    c = h[3] >> 26; h[3] &= 0x3ffffff; h[4] += c;
    c = h[4] >> 26; h[4] &= 0x3ffffff; h[0] += c * 5;
    c = h[0] >> 26; h[0] &= 0x3ffffff; h[1] += c;

    // g = h + (-p) = h - (2^130 - 5)
    uint32_t g[5];
    uint64_t carry = 5;
    for (int i = 0; i < 5; i++) {
      carry += h[i];
      g[i] = (uint32_t)carry & 0x3ffffff;
      carry >>= 26;
    }
    // select h if h < p else g  (carry-out of the +5 means h >= p... via
    // the top: g4 has bit 26 set iff h + 5 >= 2^130)
    uint32_t mask = (uint32_t)0 - (uint32_t)((g[4] >> 26) & 1);
    for (int i = 0; i < 5; i++) {
      g[i] &= 0x3ffffff;
      h[i] = (h[i] & ~mask) | (g[i] & mask);
    }

    // h mod 2^128 + pad
    uint32_t h0 = h[0] | (h[1] << 26);
    uint32_t h1 = (h[1] >> 6) | (h[2] << 20);
    uint32_t h2 = (h[2] >> 12) | (h[3] << 14);
    uint32_t h3 = (h[3] >> 18) | (h[4] << 8);
    uint64_t f;
    f = (uint64_t)h0 + pad[0];               store32_le(tag + 0, (uint32_t)f);
    f = (uint64_t)h1 + pad[1] + (f >> 32);   store32_le(tag + 4, (uint32_t)f);
    f = (uint64_t)h2 + pad[2] + (f >> 32);   store32_le(tag + 8, (uint32_t)f);
    f = (uint64_t)h3 + pad[3] + (f >> 32);   store32_le(tag + 12, (uint32_t)f);
  }
};

// RFC 8439 §2.8 AEAD construction.
void aead_tag(const uint8_t key[32], const uint8_t nonce[12],
              const uint8_t* aad, uint64_t aad_len, const uint8_t* ct,
              uint64_t ct_len, uint8_t tag[16]) {
  uint8_t otk[64];
  chacha20_block(key, 0, nonce, otk);  // one-time poly key = block 0
  Poly1305 p;
  p.init(otk);
  static const uint8_t zeros[16] = {0};
  p.update(aad, aad_len);
  if (aad_len % 16) p.update(zeros, 16 - (aad_len % 16));
  p.update(ct, ct_len);
  if (ct_len % 16) p.update(zeros, 16 - (ct_len % 16));
  uint8_t lens[16];
  store64_le(lens, aad_len);
  store64_le(lens + 8, ct_len);
  p.update(lens, 16);
  p.finish(tag);
}

int ct_compare16(const uint8_t* a, const uint8_t* b) {
  uint8_t d = 0;
  for (int i = 0; i < 16; i++) d |= a[i] ^ b[i];
  return d == 0 ? 0 : -1;
}

void xchacha_derive(const uint8_t key[32], const uint8_t nonce24[24],
                    uint8_t subkey[32], uint8_t nonce12[12]) {
  hchacha20_impl(key, nonce24, subkey);
  memset(nonce12, 0, 4);
  memcpy(nonce12 + 4, nonce24 + 16, 8);
}

}  // namespace

extern "C" {

void hchacha20(const uint8_t* key, const uint8_t* nonce16, uint8_t* out32) {
  hchacha20_impl(key, nonce16, out32);
}

// Raw one-shot Poly1305 (32-byte key, arbitrary message) — exported for
// test-vector validation of the MAC in isolation.
void poly1305_mac(const uint8_t* key, const uint8_t* msg, uint64_t len,
                  uint8_t* tag16) {
  Poly1305 p;
  p.init(key);
  p.update(msg, len);
  p.finish(tag16);
}

// IETF ChaCha20-Poly1305 (12-byte nonce).  out = ct || tag(16).
void chacha20poly1305_encrypt(const uint8_t* key, const uint8_t* nonce,
                              const uint8_t* aad, uint64_t aad_len,
                              const uint8_t* pt, uint64_t pt_len,
                              uint8_t* out) {
  chacha20_xor(key, 1, nonce, pt, out, pt_len);
  aead_tag(key, nonce, aad, aad_len, out, pt_len, out + pt_len);
}

// in = ct || tag.  Returns 0 and writes pt on success, -1 on tag mismatch.
int chacha20poly1305_decrypt(const uint8_t* key, const uint8_t* nonce,
                             const uint8_t* aad, uint64_t aad_len,
                             const uint8_t* in, uint64_t in_len,
                             uint8_t* out) {
  if (in_len < 16) return -1;
  uint64_t ct_len = in_len - 16;
  uint8_t tag[16];
  aead_tag(key, nonce, aad, aad_len, in, ct_len, tag);
  if (ct_compare16(tag, in + ct_len) != 0) return -1;
  chacha20_xor(key, 1, nonce, in, out, ct_len);
  return 0;
}

// XChaCha20-Poly1305 (24-byte nonce), draft-irtf-cfrg-xchacha.
void xchacha20poly1305_encrypt(const uint8_t* key, const uint8_t* nonce24,
                               const uint8_t* aad, uint64_t aad_len,
                               const uint8_t* pt, uint64_t pt_len,
                               uint8_t* out) {
  uint8_t subkey[32], nonce12[12];
  xchacha_derive(key, nonce24, subkey, nonce12);
  chacha20poly1305_encrypt(subkey, nonce12, aad, aad_len, pt, pt_len, out);
}

int xchacha20poly1305_decrypt(const uint8_t* key, const uint8_t* nonce24,
                              const uint8_t* aad, uint64_t aad_len,
                              const uint8_t* in, uint64_t in_len,
                              uint8_t* out) {
  uint8_t subkey[32], nonce12[12];
  xchacha_derive(key, nonce24, subkey, nonce12);
  return chacha20poly1305_decrypt(subkey, nonce12, aad, aad_len, in, in_len,
                                  out);
}

// Batch XChaCha decrypt: n blobs, one shared key, per-blob nonce + ct.
// Inputs are flattened: nonces (n*24), cts concatenated with offsets[n+1].
// Outputs into `out` at out_offsets[i] = offsets[i] - 16*i shape (each pt is
// ct_len-16).  Returns the number of failures (0 = all verified).
int xchacha20poly1305_decrypt_batch(const uint8_t* key, const uint8_t* nonces,
                                    const uint8_t* cts,
                                    const uint64_t* offsets, uint64_t n,
                                    uint8_t* out, const uint64_t* out_offsets,
                                    uint8_t* ok_flags) {
  int failures = 0;
  for (uint64_t i = 0; i < n; i++) {
    const uint8_t* ct = cts + offsets[i];
    uint64_t ct_len = offsets[i + 1] - offsets[i];
    int rc = xchacha20poly1305_decrypt(key, nonces + 24 * i, nullptr, 0, ct,
                                       ct_len, out + out_offsets[i]);
    ok_flags[i] = rc == 0 ? 1 : 0;
    if (rc != 0) failures++;
  }
  return failures;
}

// Threaded batch decrypt: blobs are independent (per-blob nonce, disjoint
// output spans), so stripes shard freely across threads.  The Python caller
// releases the GIL for the whole call (ctypes does this automatically).
int xchacha20poly1305_decrypt_batch_mt(const uint8_t* key,
                                       const uint8_t* nonces,
                                       const uint8_t* cts,
                                       const uint64_t* offsets, uint64_t n,
                                       uint8_t* out,
                                       const uint64_t* out_offsets,
                                       uint8_t* ok_flags, int n_threads) {
  if (n_threads <= 1 || n < 2)
    return xchacha20poly1305_decrypt_batch(key, nonces, cts, offsets, n, out,
                                           out_offsets, ok_flags);
  if ((uint64_t)n_threads > n) n_threads = (int)n;
  std::vector<std::thread> workers;
  std::vector<int> fails((size_t)n_threads, 0);
  uint64_t stride = (n + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; t++) {
    uint64_t lo = t * stride;
    uint64_t hi = lo + stride < n ? lo + stride : n;
    if (lo >= hi) break;
    workers.emplace_back([=, &fails]() {
      int f = 0;
      for (uint64_t i = lo; i < hi; i++) {
        const uint8_t* ct = cts + offsets[i];
        uint64_t ct_len = offsets[i + 1] - offsets[i];
        int rc = xchacha20poly1305_decrypt(key, nonces + 24 * i, nullptr, 0,
                                           ct, ct_len, out + out_offsets[i]);
        ok_flags[i] = rc == 0 ? 1 : 0;
        if (rc != 0) f++;
      }
      fails[t] = f;
    });
  }
  for (auto& w : workers) w.join();
  int failures = 0;
  for (int f : fails) failures += f;
  return failures;
}

}  // extern "C"

// ---- EncBox envelope fast path --------------------------------------------
//
// The wire envelope (backends/xchacha.py, mirroring the reference's EncBox,
// crdt-enc-xchacha20poly1305/src/lib.rs:59-68) is
//   raw VersionBytes:  version(16) ‖ msgpack [ nonce(bin 24), ct(bin N) ]
// At bulk scale (100k+ tiny op files) parsing this in Python costs several
// µs per blob — more than the decrypt itself.  These two calls parse and
// decrypt whole batches straight out of one concatenated buffer.

namespace {
// msgpack bin header at p (limit end): writes payload span, returns 0.
static int parse_bin(const uint8_t* p, const uint8_t* end, const uint8_t** out,
                     uint64_t* out_len, const uint8_t** next) {
  if (p >= end) return -1;
  uint64_t len;
  if (*p == 0xc4) {
    if (end - p < 2) return -1;
    len = p[1];
    p += 2;
  } else if (*p == 0xc5) {
    if (end - p < 3) return -1;
    len = ((uint64_t)p[1] << 8) | p[2];
    p += 3;
  } else if (*p == 0xc6) {
    if (end - p < 5) return -1;
    len = ((uint64_t)p[1] << 24) | ((uint64_t)p[2] << 16) |
          ((uint64_t)p[3] << 8) | p[4];
    p += 5;
  } else {
    return -1;
  }
  if ((uint64_t)(end - p) < len) return -1;
  *out = p;
  *out_len = len;
  *next = p + len;
  return 0;
}
}  // namespace

extern "C" {

// Parse n EncBox blobs concatenated in `blobs` (blob i spans
// [boffs[i], boffs[i+1])).  Each must carry `version` (16 bytes), a 24-byte
// nonce and a ct of ≥ 16 bytes (the tag).  Writes per-blob nonce offsets,
// ct offsets and ct lengths (all relative to `blobs`).  Returns the total
// CLEARTEXT byte count, or -1 if any blob is malformed (caller falls back
// to the per-blob Python path for precise errors).
int64_t encbox_parse_batch(const uint8_t* blobs, const uint64_t* boffs,
                           uint64_t n, const uint8_t* version,
                           uint64_t* nonce_offs, uint64_t* ct_offs,
                           uint64_t* ct_lens) {
  int64_t total = 0;
  for (uint64_t i = 0; i < n; i++) {
    const uint8_t* p = blobs + boffs[i];
    const uint8_t* end = blobs + boffs[i + 1];
    if (end - p < 16 + 1) return -1;
    if (memcmp(p, version, 16) != 0) return -1;
    p += 16;
    if (*p++ != 0x92) return -1;  // fixarray(2)
    const uint8_t *nonce, *ct, *next;
    uint64_t nonce_len, ct_len;
    if (parse_bin(p, end, &nonce, &nonce_len, &next) != 0) return -1;
    if (nonce_len != 24) return -1;
    if (parse_bin(next, end, &ct, &ct_len, &next) != 0) return -1;
    if (ct_len < 16 || next != end) return -1;
    nonce_offs[i] = (uint64_t)(nonce - blobs);
    ct_offs[i] = (uint64_t)(ct - blobs);
    ct_lens[i] = ct_len;
    total += (int64_t)(ct_len - 16);
  }
  return total;
}

// Threaded batch decrypt reading nonce/ct in place via the offsets the
// parse produced — zero intermediate copies.  Output spans are disjoint
// (out_offs from an exclusive scan of ct_lens-16).  Returns failure count.
int encbox_decrypt_scatter_mt(const uint8_t* key, const uint8_t* blobs,
                              const uint64_t* nonce_offs,
                              const uint64_t* ct_offs,
                              const uint64_t* ct_lens, uint64_t n,
                              uint8_t* out, const uint64_t* out_offs,
                              uint8_t* ok_flags, int n_threads) {
  if (n_threads <= 0) n_threads = 1;
  if ((uint64_t)n_threads > n) n_threads = (int)(n ? n : 1);
  auto work = [&](uint64_t lo, uint64_t hi, int* fail_out) {
    int f = 0;
    for (uint64_t i = lo; i < hi; i++) {
      int rc = xchacha20poly1305_decrypt(
          key, blobs + nonce_offs[i], nullptr, 0, blobs + ct_offs[i],
          ct_lens[i], out + out_offs[i]);
      ok_flags[i] = rc == 0 ? 1 : 0;
      if (rc != 0) f++;
    }
    *fail_out = f;
  };
  if (n_threads <= 1 || n < 2) {
    int f = 0;
    work(0, n, &f);
    return f;
  }
  std::vector<std::thread> workers;
  std::vector<int> fails((size_t)n_threads, 0);
  uint64_t stride = (n + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; t++) {
    uint64_t lo = t * stride;
    uint64_t hi = lo + stride < n ? lo + stride : n;
    if (lo >= hi) break;
    workers.emplace_back([&, lo, hi, t]() { work(lo, hi, &fails[t]); });
  }
  for (auto& w : workers) w.join();
  int failures = 0;
  for (int f : fails) failures += f;
  return failures;
}

}  // extern "C"

// Native sparse-state assembly for the streaming ORSet fold.
//
// The round-3 streaming pipeline (BASELINE config 5) ended in Python:
// numpy lexsort over segment keys (~48ms/200k rows on this host) plus
// per-member dict construction (~105ms) — the last non-columnar link in
// an otherwise native decrypt→decode→fold chain, and the measured wall
// at the 100k-replica scale.  This file moves that tail into C++:
//
//  * a packed-u64 LSD radix sort ((segment_key)·(maxc+1) + counter), so
//    "last of run holds the segment max" falls out of the sort order;
//  * the fresh-state writeback (the streaming shape: one combined fold
//    into an empty state) building the member→{actor: counter} dicts
//    directly through the CPython C-API.
//
// Semantics are exactly ops/columnar.py orset_fold_sparse_host +
// orset_apply_coo's fresh path (strict > horizon for adds, removes kept
// only above the merged clock); byte equality is pinned by the sparse
// fold tests plus bench.py's full-batch check.  Non-fresh states
// (pre-existing entries/deferred) stay on the Python path.
//
// This .so is loaded with ctypes.PyDLL (GIL held) because it creates
// Python objects; the compute sections are a few ms and this box is
// single-core, so holding the GIL costs nothing.
//
// Reference analogue: the consumer path crdt-enc/src/lib.rs:471-547 at
// 100k-replica streaming scale.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

// Presized dict creation skips the grow/rehash cascade while filling
// (the member dicts average ~166 entries at the config-5 shape and the
// clock dict holds one entry per replica).  _PyDict_NewPresized is a
// private-but-exported CPython symbol (msgpack's C extension uses it
// the same way); weak-linked so a build against a Python that drops it
// falls back to PyDict_New.
extern "C" PyObject* _PyDict_NewPresized(Py_ssize_t minused)
    __attribute__((weak));

namespace {

PyObject* new_dict_presized(Py_ssize_t n) {
    if (_PyDict_NewPresized != nullptr && n > 5)
        return _PyDict_NewPresized(n);
    return PyDict_New();
}

// LSD radix sort of uint64 values, 8-bit digits, skipping passes whose
// digit is constant across the array (high zero bytes of small keys).
void radix_sort_u64(std::vector<uint64_t>& a, uint64_t maxval) {
    if (a.size() < 2) return;
    std::vector<uint64_t> tmp(a.size());
    uint64_t* src = a.data();
    uint64_t* dst = tmp.data();
    bool in_tmp = false;
    for (int pass = 0; pass < 8; ++pass) {
        const int shift = pass * 8;
        if ((maxval >> shift) == 0) break;  // no set bits at/after this byte
        size_t hist[256] = {0};
        const size_t n = a.size();
        for (size_t i = 0; i < n; ++i) hist[(src[i] >> shift) & 0xff]++;
        if (hist[(src[0] >> shift) & 0xff] == n) continue;  // constant digit
        size_t sum = 0;
        for (int b = 0; b < 256; ++b) {
            size_t c = hist[b];
            hist[b] = sum;
            sum += c;
        }
        for (size_t i = 0; i < n; ++i)
            dst[hist[(src[i] >> shift) & 0xff]++] = src[i];
        std::swap(src, dst);
        in_tmp = !in_tmp;
    }
    if (in_tmp) std::memcpy(a.data(), src, a.size() * sizeof(uint64_t));
}

// Dedup a sorted packed array (key = p / M, val = p % M) into (seg, val)
// arrays keeping the last (= max val) entry of every key run.
void dedup(const std::vector<uint64_t>& packed, uint64_t M,
           std::vector<int64_t>& seg, std::vector<int64_t>& val) {
    const size_t n = packed.size();
    seg.reserve(n);
    val.reserve(n);
    for (size_t i = 0; i < n; ++i) {
        if (i + 1 < n && packed[i] / M == packed[i + 1] / M) continue;
        seg.push_back((int64_t)(packed[i] / M));
        val.push_back((int64_t)(packed[i] % M));
    }
}

// Emit consecutive same-member groups of (seg, val) rows into
// target[member_obj] = {actor_obj: val}.  Rows are member-major because
// seg = member·R + actor and the arrays are sorted.
// Returns 0 ok, -1 on a Python error (exception set).
int emit_groups(PyObject* target, PyObject* member_objs, PyObject* actor_objs,
                int64_t R, const std::vector<int64_t>& seg,
                const std::vector<int64_t>& val) {
    const size_t n = seg.size();
    size_t s = 0;
    while (s < n) {
        const int64_t m = seg[s] / R;
        size_t e = s + 1;
        while (e < n && seg[e] / R == m) ++e;
        PyObject* d = new_dict_presized((Py_ssize_t)(e - s));
        if (!d) return -1;
        for (size_t i = s; i < e; ++i) {
            PyObject* a = PyList_GET_ITEM(actor_objs, (Py_ssize_t)(seg[i] % R));
            PyObject* c = PyLong_FromLongLong((long long)val[i]);
            if (!c || PyDict_SetItem(d, a, c) < 0) {
                Py_XDECREF(c);
                Py_DECREF(d);
                return -1;
            }
            Py_DECREF(c);
        }
        if (PyDict_SetItem(target, PyList_GET_ITEM(member_objs, (Py_ssize_t)m),
                           d) < 0) {
            Py_DECREF(d);
            return -1;
        }
        Py_DECREF(d);
        s = e;
    }
    return 0;
}

}  // namespace

extern "C" {

// Fold a raw (kind, member, actor, counter) op batch into an EMPTY
// ORSet's entries/deferred dicts + dense clock.
//
//  kind:    (n,) int8   0=add 1=remove (anything else ignored)
//  member:  (n,) int32  vocab index < E
//  actor:   (n,) int32  vocab index; >= R marks a padding row
//  counter: (n,) int32  dot counter / horizon
//  clock:   (R,) int32  in-out: the state's dense clock, merged in place
//  member_objs / actor_objs: vocab object lists (len E / R)
//  entries / deferred: empty dicts to fill (member -> {actor: counter})
//
// Returns 0 on success, -1 if the shape overflows the packed-key sort
// (caller must use the Python path), -2 on a Python error.
int orset_fresh_fold_impl(const int8_t* kind, const int32_t* member,
                          const int32_t* actor, const int32_t* counter,
                          int64_t n, int64_t E, int64_t R, int32_t* clock,
                          PyObject* member_objs, PyObject* actor_objs,
                          PyObject* entries, PyObject* deferred) {
    // pass 0: max counter over participating rows (packing modulus)
    int64_t maxc = 0;
    for (int64_t i = 0; i < n; ++i) {
        if (actor[i] >= R) continue;
        if (counter[i] > maxc) maxc = counter[i];
    }
    const uint64_t M = (uint64_t)maxc + 1;
    const uint64_t segspace = (uint64_t)E * (uint64_t)R;
    // overflow guard: packed = seg·M + c with seg < segspace must fit
    // u64 comfortably (two sides sorted separately, so no 2x factor)
    if (segspace != 0 && M > (((uint64_t)1 << 62) / (segspace + 1))) return -1;

    // pass 1: gate + pack into separate add/remove arrays.  Add rows
    // gate against the ORIGINAL clock (copy) while the merged clock
    // updates in place — same order of effects as the numpy path
    // (np.maximum.at over live adds, then the remove filter sees the
    // merged clock).
    std::vector<int32_t> clock0(clock, clock + (size_t)R);
    std::vector<uint64_t> adds, rms;
    adds.reserve((size_t)n);
    for (int64_t i = 0; i < n; ++i) {
        const int32_t a = actor[i];
        if (a < 0 || a >= R) continue;
        const int64_t c = counter[i];
        if (c < 0) continue;  // defensive: counters are non-negative
        const uint64_t seg = (uint64_t)member[i] * (uint64_t)R + (uint64_t)a;
        if (kind[i] == 0) {
            if (c > clock0[a]) {  // replay gate vs the incoming clock
                adds.push_back(seg * M + (uint64_t)c);
                if (c > clock[a]) clock[a] = (int32_t)c;  // merged clock
            }
        } else if (kind[i] == 1) {
            rms.push_back(seg * M + (uint64_t)c);
        }
    }
    const uint64_t maxpacked = segspace == 0 ? 0 : (segspace - 1) * M + maxc;
    radix_sort_u64(adds, maxpacked);
    radix_sort_u64(rms, maxpacked);

    std::vector<int64_t> aseg, aval, rseg, rval;
    dedup(adds, M, aseg, aval);
    dedup(rms, M, rseg, rval);

    // adds survive a STRICTLY greater horizon on their own segment
    // (equal horizon observed the dot — it dies); merge-join on the
    // sorted segs
    {
        size_t keep = 0, r = 0;
        for (size_t i = 0; i < aseg.size(); ++i) {
            while (r < rseg.size() && rseg[r] < aseg[i]) ++r;
            const int64_t horizon =
                (r < rseg.size() && rseg[r] == aseg[i]) ? rval[r] : 0;
            if (aval[i] > horizon) {
                aseg[keep] = aseg[i];
                aval[keep] = aval[i];
                ++keep;
            }
        }
        aseg.resize(keep);
        aval.resize(keep);
    }
    // removes survive only above the MERGED clock
    {
        size_t keep = 0;
        for (size_t i = 0; i < rseg.size(); ++i) {
            if (rval[i] > clock[rseg[i] % R]) {
                rseg[keep] = rseg[i];
                rval[keep] = rval[i];
                ++keep;
            }
        }
        rseg.resize(keep);
        rval.resize(keep);
    }

    if (emit_groups(entries, member_objs, actor_objs, R, aseg, aval) < 0)
        return -2;
    if (emit_groups(deferred, member_objs, actor_objs, R, rseg, rval) < 0)
        return -2;
    return 0;
}

// ---- split fold: rows out, dicts assembled separately ---------------------
//
// The monolithic orset_fresh_fold above fuses the FOLD (gate + radix
// sort + dedup + survivor filter — pure C, a few ms) with the STATE
// WRITEBACK (CPython dict assembly — the dominant cost at 200k rows).
// The split protocol below returns the surviving rows as plain int
// arrays FIRST — member-contiguous, actor-ascending: exactly the
// orset_pack_checkpoint row layout — so the caller can (a) time fold
// vs writeback honestly (the gap report's fold marginal), (b) hand the
// SAME rows to grouped_rows_dicts for the dict writeback, and (c) seal
// the warm-open checkpoint straight from the rows with no dict walk.

namespace {

struct FoldRows {
    std::vector<int64_t> aseg, aval, rseg, rval;
    int64_t R;
};

}  // namespace

// Fold a raw op batch against an empty state: merged clock in place,
// surviving add/remove rows retained on the returned handle.  Writes
// {n_adds, n_removes} into counts.  Returns NULL when the shape
// overflows the packed-key sort or allocation fails (caller falls back
// to the fused/Python paths; clock may be partially merged — callers
// pass a scratch copy).
void* orset_fold_rows(const int8_t* kind, const int32_t* member,
                      const int32_t* actor, const int32_t* counter,
                      int64_t n, int64_t E, int64_t R, int32_t* clock,
                      int64_t* counts) {
    try {
        int64_t maxc = 0;
        for (int64_t i = 0; i < n; ++i) {
            if (actor[i] >= R) continue;
            if (counter[i] > maxc) maxc = counter[i];
        }
        const uint64_t M = (uint64_t)maxc + 1;
        const uint64_t segspace = (uint64_t)E * (uint64_t)R;
        if (segspace != 0 && M > (((uint64_t)1 << 62) / (segspace + 1)))
            return nullptr;
        std::vector<int32_t> clock0(clock, clock + (size_t)R);
        std::vector<uint64_t> adds, rms;
        adds.reserve((size_t)n);
        for (int64_t i = 0; i < n; ++i) {
            const int32_t a = actor[i];
            if (a < 0 || a >= R) continue;
            const int64_t c = counter[i];
            if (c < 0) continue;
            const uint64_t seg =
                (uint64_t)member[i] * (uint64_t)R + (uint64_t)a;
            if (kind[i] == 0) {
                if (c > clock0[a]) {
                    adds.push_back(seg * M + (uint64_t)c);
                    if (c > clock[a]) clock[a] = (int32_t)c;
                }
            } else if (kind[i] == 1) {
                rms.push_back(seg * M + (uint64_t)c);
            }
        }
        const uint64_t maxpacked =
            segspace == 0 ? 0 : (segspace - 1) * M + maxc;
        radix_sort_u64(adds, maxpacked);
        radix_sort_u64(rms, maxpacked);

        FoldRows* out = new FoldRows;
        out->R = R;
        dedup(adds, M, out->aseg, out->aval);
        dedup(rms, M, out->rseg, out->rval);
        {
            size_t keep = 0, r = 0;
            for (size_t i = 0; i < out->aseg.size(); ++i) {
                while (r < out->rseg.size() && out->rseg[r] < out->aseg[i])
                    ++r;
                const int64_t horizon =
                    (r < out->rseg.size() && out->rseg[r] == out->aseg[i])
                        ? out->rval[r] : 0;
                if (out->aval[i] > horizon) {
                    out->aseg[keep] = out->aseg[i];
                    out->aval[keep] = out->aval[i];
                    ++keep;
                }
            }
            out->aseg.resize(keep);
            out->aval.resize(keep);
        }
        {
            size_t keep = 0;
            for (size_t i = 0; i < out->rseg.size(); ++i) {
                if (out->rval[i] > clock[out->rseg[i] % R]) {
                    out->rseg[keep] = out->rseg[i];
                    out->rval[keep] = out->rval[i];
                    ++keep;
                }
            }
            out->rseg.resize(keep);
            out->rval.resize(keep);
        }
        counts[0] = (int64_t)out->aseg.size();
        counts[1] = (int64_t)out->rseg.size();
        return out;
    } catch (const std::bad_alloc&) {
        return nullptr;
    }
}

// Copy the surviving rows out as (member, actor, counter) columns —
// member-contiguous (sort order), actor ascending within a member, the
// orset_pack_checkpoint group contract — and free the handle.  The
// caller sizes the six arrays from the counts orset_fold_rows wrote and
// passes them back as the write bounds; a mismatch (stale counts, a
// caller bug) writes NOTHING past either capacity and returns -1.
int orset_fold_rows_take(void* handle, int32_t* am, int32_t* aa,
                         int64_t* ac, int64_t a_capacity, int32_t* dm,
                         int32_t* da, int64_t* dc, int64_t d_capacity) {
    FoldRows* rows = (FoldRows*)handle;
    if ((int64_t)rows->aseg.size() != a_capacity ||
        (int64_t)rows->rseg.size() != d_capacity) {
        delete rows;
        return -1;
    }
    const int64_t R = rows->R;
    for (size_t i = 0; i < rows->aseg.size(); ++i) {
        am[i] = (int32_t)(rows->aseg[i] / R);
        aa[i] = (int32_t)(rows->aseg[i] % R);
        ac[i] = rows->aval[i];
    }
    for (size_t i = 0; i < rows->rseg.size(); ++i) {
        dm[i] = (int32_t)(rows->rseg[i] / R);
        da[i] = (int32_t)(rows->rseg[i] % R);
        dc[i] = rows->rval[i];
    }
    delete rows;
    return 0;
}

void orset_fold_rows_drop(void* handle) { delete (FoldRows*)handle; }

int orset_fresh_fold(const int8_t* kind, const int32_t* member,
                     const int32_t* actor, const int32_t* counter, int64_t n,
                     int64_t E, int64_t R, int32_t* clock,
                     PyObject* member_objs, PyObject* actor_objs,
                     PyObject* entries, PyObject* deferred) {
    // a bad_alloc must not unwind into ctypes; -1 = Python-path fallback.
    // Safe to retry in Python: vector allocation happens strictly before
    // any dict mutation (emit_groups allocates through the C-API, whose
    // failures surface as rc=-2 Python errors, not C++ exceptions), and
    // the caller's clock array is a scratch copy it discards on fallback.
    try {
        return orset_fresh_fold_impl(kind, member, actor, counter, n, E, R,
                                     clock, member_objs, actor_objs, entries,
                                     deferred);
    } catch (const std::bad_alloc&) {
        return -1;
    }
}

// ---------------------------------------------------------------------
// Canonical msgpack packer — the native twin of utils/codec.py pack():
// smallest-encoding msgpack with use_bin_type=True semantics and every
// map emitted with keys sorted by their packed bytes.  Sealing a
// compacted state at the 100k-replica scale spent ~400ms in the Python
// _canon + packb walk; this emits the identical bytes in one C pass.
// Unsupported types return 0 and the Python caller falls back.
// ---------------------------------------------------------------------

namespace {

struct Out {
  std::vector<uint8_t> b;
  void u8(uint8_t v) { b.push_back(v); }
  void be16(uint16_t v) { u8(v >> 8); u8(v & 0xff); }
  void be32(uint32_t v) { be16(v >> 16); be16(v & 0xffff); }
  void be64(uint64_t v) { be32(v >> 32); be32(v & 0xffffffffull); }
  void raw(const void* p, size_t n) {
    const uint8_t* c = (const uint8_t*)p;
    b.insert(b.end(), c, c + n);
  }
};

// returns 1 ok, 0 unsupported (no exception), -1 python error (exc set)
int canon_emit(PyObject* obj, Out& out, int depth) {
  if (depth > 200) return 0;
  if (obj == Py_None) { out.u8(0xc0); return 1; }
  if (obj == Py_True) { out.u8(0xc3); return 1; }
  if (obj == Py_False) { out.u8(0xc2); return 1; }
  if (PyLong_CheckExact(obj)) {
    int overflow = 0;
    long long v = PyLong_AsLongLongAndOverflow(obj, &overflow);
    if (overflow > 0) {
      unsigned long long u = PyLong_AsUnsignedLongLong(obj);
      if (u == (unsigned long long)-1 && PyErr_Occurred()) {
        PyErr_Clear();
        return 0;  // > 2^64-1: let the Python packer raise its error
      }
      out.u8(0xcf);
      out.be64(u);
      return 1;
    }
    if (overflow < 0) return 0;  // < -2^63
    if (v == -1 && PyErr_Occurred()) return -1;
    if (v >= 0) {
      unsigned long long u = (unsigned long long)v;
      if (u < 0x80) out.u8((uint8_t)u);
      else if (u <= 0xff) { out.u8(0xcc); out.u8((uint8_t)u); }
      else if (u <= 0xffff) { out.u8(0xcd); out.be16((uint16_t)u); }
      else if (u <= 0xffffffffull) { out.u8(0xce); out.be32((uint32_t)u); }
      else { out.u8(0xcf); out.be64(u); }
    } else {
      if (v >= -32) out.u8((uint8_t)(int8_t)v);
      else if (v >= -128) { out.u8(0xd0); out.u8((uint8_t)(int8_t)v); }
      else if (v >= -32768) { out.u8(0xd1); out.be16((uint16_t)(int16_t)v); }
      else if (v >= -2147483648ll) {
        out.u8(0xd2);
        out.be32((uint32_t)(int32_t)v);
      } else {
        out.u8(0xd3);
        out.be64((uint64_t)v);
      }
    }
    return 1;
  }
  if (PyBytes_CheckExact(obj)) {
    const size_t n = (size_t)PyBytes_GET_SIZE(obj);
    if (n <= 0xff) { out.u8(0xc4); out.u8((uint8_t)n); }
    else if (n <= 0xffff) { out.u8(0xc5); out.be16((uint16_t)n); }
    else if (n <= 0xffffffffull) { out.u8(0xc6); out.be32((uint32_t)n); }
    else return 0;
    out.raw(PyBytes_AS_STRING(obj), n);
    return 1;
  }
  if (PyUnicode_CheckExact(obj)) {
    Py_ssize_t n;
    const char* s = PyUnicode_AsUTF8AndSize(obj, &n);
    if (s == nullptr) return -1;
    if (n < 32) out.u8(0xa0 | (uint8_t)n);
    else if (n <= 0xff) { out.u8(0xd9); out.u8((uint8_t)n); }
    else if (n <= 0xffff) { out.u8(0xda); out.be16((uint16_t)n); }
    else if ((unsigned long long)n <= 0xffffffffull) {
      out.u8(0xdb);
      out.be32((uint32_t)n);
    } else return 0;
    out.raw(s, (size_t)n);
    return 1;
  }
  if (PyFloat_CheckExact(obj)) {
    double d = PyFloat_AS_DOUBLE(obj);
    uint64_t bits;
    memcpy(&bits, &d, 8);
    out.u8(0xcb);
    out.be64(bits);
    return 1;
  }
  if (PyList_CheckExact(obj) || PyTuple_CheckExact(obj)) {
    const int is_list = PyList_CheckExact(obj);
    const Py_ssize_t n =
        is_list ? PyList_GET_SIZE(obj) : PyTuple_GET_SIZE(obj);
    if (n < 16) out.u8(0x90 | (uint8_t)n);
    else if (n <= 0xffff) { out.u8(0xdc); out.be16((uint16_t)n); }
    else if ((unsigned long long)n <= 0xffffffffull) {
      out.u8(0xdd);
      out.be32((uint32_t)n);
    } else return 0;
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject* it =
          is_list ? PyList_GET_ITEM(obj, i) : PyTuple_GET_ITEM(obj, i);
      int rc = canon_emit(it, out, depth + 1);
      if (rc != 1) return rc;
    }
    return 1;
  }
  if (PyDict_CheckExact(obj)) {
    const Py_ssize_t n = PyDict_GET_SIZE(obj);
    if (n < 16) out.u8(0x80 | (uint8_t)n);
    else if (n <= 0xffff) { out.u8(0xde); out.be16((uint16_t)n); }
    else if ((unsigned long long)n <= 0xffffffffull) {
      out.u8(0xdf);
      out.be32((uint32_t)n);
    } else return 0;
    // pack (key bytes, value bytes) pairs, sort by key bytes — the
    // canonical-map ordering codec.pack defines
    struct Pair {
      std::vector<uint8_t> k, v;
    };
    std::vector<Pair> pairs;
    pairs.reserve((size_t)n);
    Py_ssize_t pos = 0;
    PyObject *key, *val;
    while (PyDict_Next(obj, &pos, &key, &val)) {
      Out ko, vo;
      int rc = canon_emit(key, ko, depth + 1);
      if (rc != 1) return rc;
      rc = canon_emit(val, vo, depth + 1);
      if (rc != 1) return rc;
      pairs.push_back(Pair{std::move(ko.b), std::move(vo.b)});
    }
    std::sort(pairs.begin(), pairs.end(),
              [](const Pair& a, const Pair& b) { return a.k < b.k; });
    for (const Pair& p : pairs) {
      out.raw(p.k.data(), p.k.size());
      out.raw(p.v.data(), p.v.size());
    }
    return 1;
  }
  return 0;  // sets, numpy scalars, custom types → Python fallback
}

}  // namespace

extern "C" {

// Canonical-pack ``obj``; returns a bytes object, Py_None when the
// object graph contains a type this packer does not handle (caller
// falls back to the Python path), or NULL on a Python error.
PyObject* canon_pack(PyObject* obj) {
  // bad_alloc from buffer growth must not unwind into ctypes — surface
  // it as a Python MemoryError instead (same convention as the fold and
  // decode entry points)
  try {
    Out out;
    out.b.reserve(256);
    int rc = canon_emit(obj, out, 0);
    if (rc < 0) return nullptr;
    if (rc == 0) Py_RETURN_NONE;
    return PyBytes_FromStringAndSize((const char*)out.b.data(),
                                     (Py_ssize_t)out.b.size());
  } catch (const std::bad_alloc&) {
    return PyErr_NoMemory();
  }
}

}  // extern "C" (canon_pack; the outer linkage block continues below)

// One pass over a list of bytes objects: write each length into
// ``lens`` and (when ``out`` is non-null) memcpy the payloads
// back-to-back into ``out``.  Returns the total byte count, or -1 when
// any element is not exactly ``bytes`` (caller falls back to Python).
// Replaces a np.fromiter(len, ...) + b"".join() pair that cost ~9ms at
// the 83k-tiny-blob config-5 shape (round-5 phase profile).
//
// ``out_capacity`` bounds the join pass and ``expected_n`` bounds BOTH
// buffers: callers size ``lens`` (and, for the join, ``out``) from an
// earlier ``len()`` / lengths-only call, and pure Python runs between
// those and this ctypes call — a list mutated in that window (grown,
// shrunk, or re-totalled) must return -1 BEFORE any write runs past a
// buffer, never overrun the heap (ADVICE r5, medium).  The caller must
// also verify the join's return equals its expected total (a short
// -1-free join is equally stale) and fall back to Python.
int64_t bytes_lens_join(PyObject* seq, uint64_t* lens, uint8_t* out,
                        int64_t out_capacity, int64_t expected_n) {
    if (!PyList_CheckExact(seq)) return -1;
    Py_ssize_t n = PyList_GET_SIZE(seq);
    if (expected_n >= 0 && n != (Py_ssize_t)expected_n) return -1;
    int64_t total = 0;
    for (Py_ssize_t i = 0; i < n; ++i) {
        PyObject* b = PyList_GET_ITEM(seq, i);
        if (!PyBytes_CheckExact(b)) return -1;
        Py_ssize_t ln = PyBytes_GET_SIZE(b);
        lens[i] = (uint64_t)ln;
        if (out) {
            if (total + (int64_t)ln > out_capacity) return -1;
            memcpy(out + total, PyBytes_AS_STRING(b), (size_t)ln);
        }
        total += (int64_t)ln;
    }
    return total;
}

// Build target[members[m]] = {actors[a]: counter} from checkpoint row
// arrays whose member runs are contiguous (ops/columnar.py
// orset_unpack_checkpoint) — the native twin of its per-member dict
// comprehensions, which cost ~0.5s of every 1M-dot warm open.  Returns
// 0, or -1 on any allocation failure / out-of-range index.  Every -1
// path clears the Python error indicator: the caller (a ctypes c_int
// restype, which never checks PyErr) treats -1 as "clear `target` and
// rebuild in Python", and a live indicator would surface later as an
// unrelated SystemError.
int grouped_rows_dicts(const int32_t* m_idx, const int32_t* a_idx,
                       const int64_t* ctr, int64_t n, PyObject* members,
                       PyObject* actors, PyObject* target) {
    if (!PyList_Check(members) || !PyList_Check(actors) ||
        !PyDict_Check(target))
        return -1;
    const Py_ssize_t n_m = PyList_GET_SIZE(members);
    const Py_ssize_t n_a = PyList_GET_SIZE(actors);
    int64_t i = 0;
    while (i < n) {
        const int32_t m = m_idx[i];
        if (m < 0 || (Py_ssize_t)m >= n_m) return -1;
        int64_t j = i;
        while (j < n && m_idx[j] == m) j++;
        PyObject* slot = new_dict_presized((Py_ssize_t)(j - i));
        if (!slot) { PyErr_Clear(); return -1; }
        for (int64_t t = i; t < j; ++t) {
            const int32_t a = a_idx[t];
            if (a < 0 || (Py_ssize_t)a >= n_a) { Py_DECREF(slot); return -1; }
            PyObject* c = PyLong_FromLongLong((long long)ctr[t]);
            if (!c || PyDict_SetItem(
                          slot, PyList_GET_ITEM(actors, (Py_ssize_t)a), c)
                          < 0) {
                Py_XDECREF(c);
                Py_DECREF(slot);
                PyErr_Clear();
                return -1;
            }
            Py_DECREF(c);
        }
        if (PyDict_SetItem(target, PyList_GET_ITEM(members, (Py_ssize_t)m),
                           slot) < 0) {
            Py_DECREF(slot);
            PyErr_Clear();
            return -1;
        }
        Py_DECREF(slot);
        i = j;
    }
    return 0;
}

// Build {actor_obj: counter} for the nonzero entries of a dense clock —
// the native twin of ops/columnar.py dense_to_vclock's dict body.
// Returns a NEW dict, or NULL on error.
PyObject* dense_clock_dict(const int32_t* clock, int64_t R,
                           PyObject* actor_objs) {
    int64_t nz = 0;
    for (int64_t i = 0; i < R; ++i) nz += (clock[i] != 0);
    PyObject* d = new_dict_presized((Py_ssize_t)nz);
    if (!d) return nullptr;
    for (int64_t i = 0; i < R; ++i) {
        if (clock[i] == 0) continue;
        PyObject* c = PyLong_FromLong((long)clock[i]);
        if (!c ||
            PyDict_SetItem(d, PyList_GET_ITEM(actor_objs, (Py_ssize_t)i), c) <
                0) {
            Py_XDECREF(c);
            Py_DECREF(d);
            return nullptr;
        }
        Py_DECREF(c);
    }
    return d;
}

}  // extern "C"

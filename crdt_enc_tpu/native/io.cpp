// Bulk op-file reader: the C++ load path for dense per-actor op logs.
//
// An op-log scan reads remote/ops/<actor>/<N> for N = first, first+1, …
// until the first missing file (the dense-version contract,
// crdt-enc-tokio/src/lib.rs:254-269).  Per-file Python open/read costs
// ~10-20µs of interpreter overhead; at compaction scale (SURVEY.md §2.2:
// "the bulk load path (1M op files) gets a C++ reader") that dwarfs the
// I/O itself.  Two-pass protocol so ctypes needs no growable buffers:
//
//   pass 1  scan_op_sizes(dir, first, max)  → per-file sizes (stat loop)
//   pass 2  read_op_files(dir, first, n, buf, offsets)  → one flat buffer
//
// A file that shrinks/vanishes between passes returns -1 and the caller
// falls back to the per-file Python path (the sync tool may race us; op
// files themselves are immutable once published).

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

int path_join(char* out, size_t cap, const char* dir, int64_t version) {
  int n = snprintf(out, cap, "%s/%lld", dir, (long long)version);
  return (n > 0 && (size_t)n < cap) ? 0 : -1;
}

}  // namespace

extern "C" {

// Pass 1: sizes of the dense run starting at `first`.  Writes up to
// max_files sizes; returns the count of consecutive existing files.
int64_t scan_op_sizes(const char* dir, int64_t first, int64_t max_files,
                      int64_t* sizes_out) {
  char path[4096];
  int64_t n = 0;
  for (; n < max_files; n++) {
    if (path_join(path, sizeof(path), dir, first + n) != 0) return n;
    struct stat st;
    if (stat(path, &st) != 0 || !S_ISREG(st.st_mode)) return n;
    sizes_out[n] = (int64_t)st.st_size;
  }
  return n;
}

// Pass 2: read n_files consecutive files into one flat buffer at the
// given offsets (offsets[i] .. offsets[i] + sizes[i]).  Returns n_files,
// or -1 if any file is missing or its size changed (caller falls back).
int64_t read_op_files(const char* dir, int64_t first, int64_t n_files,
                      const int64_t* offsets, const int64_t* sizes,
                      uint8_t* buf) {
  char path[4096];
  for (int64_t i = 0; i < n_files; i++) {
    if (path_join(path, sizeof(path), dir, first + i) != 0) return -1;
    int fd = open(path, O_RDONLY);
    if (fd < 0) return -1;
    int64_t want = sizes[i];
    uint8_t* dst = buf + offsets[i];
    int64_t got = 0;
    while (got < want) {
      ssize_t r = read(fd, dst + got, (size_t)(want - got));
      if (r < 0 && errno == EINTR) continue;  // signal mid-read: retry
      if (r <= 0) { close(fd); return -1; }
      got += r;
    }
    // file must end exactly where pass 1 said (immutable once published)
    uint8_t extra;
    ssize_t tail;
    do {
      tail = read(fd, &extra, 1);
    } while (tail < 0 && errno == EINTR);
    if (tail != 0) { close(fd); return -1; }
    close(fd);
  }
  return n_files;
}

// Warm-open tail probe: does remote/ops/<actor>/<first> exist, for many
// actors in one call.  `rel_paths` is a flat NUL-separated buffer of n
// entries ("<actor-hex>/<version>"); out_mask[i] = 1 when the file
// exists.  dirfd-relative so each access resolves two path components
// instead of re-walking the whole remote prefix — on containerized
// kernels every syscall costs ~100µs+, so the probe is one syscall per
// actor and zero interpreter overhead.  Returns n, or -1 when base_dir
// cannot be opened (caller falls back to per-actor Python stats).
int64_t probe_op_files(const char* base_dir, int64_t n,
                       const char* rel_paths, uint8_t* out_mask) {
  int dfd = open(base_dir, O_RDONLY | O_DIRECTORY);
  if (dfd < 0) return -1;
  const char* p = rel_paths;
  for (int64_t i = 0; i < n; i++) {
    out_mask[i] = faccessat(dfd, p, F_OK, 0) == 0 ? 1 : 0;
    p += strlen(p) + 1;
  }
  close(dfd);
  return n;
}

}  // extern "C"

// Bulk columnar decoder: msgpack op payloads → flat int arrays.
//
// The 1M-op ingestion path must not build a Python object per op
// (SURVEY.md §2.2: "decode op files directly into pre-allocated arrays
// without Python-object churn").  This decoder walks the framework's own
// canonical op encodings directly:
//
//   ORSet add:  [0, member, [actor16, counter]]
//   ORSet rm:   [1, member, {actor16: counter, ...}]
//   counter op: [dir, [actor16, counter]]   (G-Counter: bare [actor16, c])
//
// Members are interned against a caller-managed table via a callback-free
// two-pass protocol: pass 1 here extracts (kind, actor, counter) and member
// *byte spans*; the Python side interns spans (zero-copy slices) only for
// members, which in benchmarks are small ints/bytes.  For fully native
// speed, fixed-width member encodings (int64) are decoded inline.
//
// Only the msgpack subset the canonical codec emits is implemented:
// positive fixint/uint8/16/32/64, fixarray/array16/32, fixmap/map16/32,
// bin8/16/32, negative ints rejected (canonical ops never hold them).

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool fail = false;

  uint8_t u8() {
    if (p >= end) { fail = true; return 0; }
    return *p++;
  }
  uint64_t be(int n) {
    uint64_t v = 0;
    if (p + n > end) { fail = true; p = end; return 0; }
    for (int i = 0; i < n; i++) v = (v << 8) | *p++;
    return v;
  }
  bool uint(uint64_t* out) {
    uint8_t t = u8();
    if (fail) return false;
    if (t <= 0x7f) { *out = t; return true; }
    if (t == 0xcc) { *out = be(1); return !fail; }
    if (t == 0xcd) { *out = be(2); return !fail; }
    if (t == 0xce) { *out = be(4); return !fail; }
    if (t == 0xcf) { *out = be(8); return !fail; }
    fail = true;
    return false;
  }
  bool arr(uint64_t* len) {
    uint8_t t = u8();
    if (fail) return false;
    if ((t & 0xf0) == 0x90) { *len = t & 0x0f; return true; }
    if (t == 0xdc) { *len = be(2); return !fail; }
    if (t == 0xdd) { *len = be(4); return !fail; }
    fail = true;
    return false;
  }
  bool map(uint64_t* len) {
    uint8_t t = u8();
    if (fail) return false;
    if ((t & 0xf0) == 0x80) { *len = t & 0x0f; return true; }
    if (t == 0xde) { *len = be(2); return !fail; }
    if (t == 0xdf) { *len = be(4); return !fail; }
    fail = true;
    return false;
  }
  // bin: returns span
  bool bin(const uint8_t** data, uint64_t* len) {
    uint8_t t = u8();
    if (fail) return false;
    if (t == 0xc4) *len = be(1);
    else if (t == 0xc5) *len = be(2);
    else if (t == 0xc6) *len = be(4);
    else { fail = true; return false; }
    if (fail || p + *len > end) { fail = true; return false; }
    *data = p;
    p += *len;
    return true;
  }
  // skip any value (for opaque members) returning its span
  bool span(const uint8_t** s, uint64_t* n) {
    const uint8_t* start = p;
    if (!skip()) return false;
    *s = start;
    *n = (uint64_t)(p - start);
    return true;
  }
  bool skip() {
    uint8_t t = u8();
    if (fail) return false;
    if (t <= 0x7f || t >= 0xe0 || t == 0xc0 || t == 0xc2 || t == 0xc3)
      return true;
    if ((t & 0xe0) == 0xa0) { uint64_t n = t & 0x1f; p += n; goto bound; }
    if ((t & 0xf0) == 0x90) { uint64_t n = t & 0x0f; return skip_n(n); }
    if ((t & 0xf0) == 0x80) { uint64_t n = t & 0x0f; return skip_n(2 * n); }
    switch (t) {
      case 0xcc: case 0xd0: p += 1; goto bound;
      case 0xcd: case 0xd1: p += 2; goto bound;
      case 0xce: case 0xd2: case 0xca: p += 4; goto bound;
      case 0xcf: case 0xd3: case 0xcb: p += 8; goto bound;
      case 0xc4: { uint64_t n = be(1); p += n; goto bound; }
      case 0xc5: { uint64_t n = be(2); p += n; goto bound; }
      case 0xc6: { uint64_t n = be(4); p += n; goto bound; }
      case 0xd9: { uint64_t n = be(1); p += n; goto bound; }
      case 0xda: { uint64_t n = be(2); p += n; goto bound; }
      case 0xdb: { uint64_t n = be(4); p += n; goto bound; }
      case 0xdc: { uint64_t n = be(2); return skip_n(n); }
      case 0xdd: { uint64_t n = be(4); return skip_n(n); }
      case 0xde: { uint64_t n = be(2); return skip_n(2 * n); }
      case 0xdf: { uint64_t n = be(4); return skip_n(2 * n); }
      default: fail = true; return false;
    }
  bound:
    if (p > end) { fail = true; return false; }
    return true;
  }
  bool skip_n(uint64_t n) {
    for (uint64_t i = 0; i < n; i++)
      if (!skip()) return false;
    return true;
  }
};

// dense 16-byte actor → index via caller-provided sorted table
int actor_index(const uint8_t* actors, uint64_t n_actors, const uint8_t* a) {
  // binary search over 16-byte keys
  uint64_t lo = 0, hi = n_actors;
  while (lo < hi) {
    uint64_t mid = (lo + hi) / 2;
    int c = memcmp(actors + 16 * mid, a, 16);
    if (c < 0) lo = mid + 1;
    else if (c > 0) hi = mid;
    else return (int)mid;
  }
  return -1;
}

// Optional open-addressing index over the actor table.  A binary search
// over 100k 16-byte keys costs ~17 scattered memcmp probes per op (~38ms
// of the config-5 decode); one hash probe with a single verify runs at
// memory latency.  slots == nullptr falls back to the binary search.
struct ActorLookup {
  const uint8_t* actors;
  uint64_t n;
  const int32_t* slots;  // n_slots entries, -1 = empty
  uint64_t mask;         // n_slots - 1 (n_slots is a power of two)
};

inline uint64_t actor_hash16(const uint8_t* a) {
  uint64_t u0, u1;
  memcpy(&u0, a, 8);
  memcpy(&u1, a + 8, 8);
  uint64_t h = (u0 ^ (u1 * 0x9E3779B97F4A7C15ull)) + (u1 >> 31);
  h ^= h >> 29;
  h *= 0xBF58476D1CE4E5B9ull;
  h ^= h >> 32;
  return h;
}

inline int actor_lookup(const ActorLookup& t, const uint8_t* a) {
  if (t.slots == nullptr) return actor_index(t.actors, t.n, a);
  uint64_t p = actor_hash16(a) & t.mask;
  for (;;) {
    int32_t s = t.slots[p];
    if (s < 0) return -1;
    if (memcmp(t.actors + 16 * (uint64_t)s, a, 16) == 0) return s;
    p = (p + 1) & t.mask;
  }
}

template <typename Sink>
int64_t orset_decode_sink(const uint8_t* buf, uint64_t len,
                          const ActorLookup& look, Sink& sink) {
  Reader r{buf, buf + len};
  uint64_t n_ops;
  if (!r.arr(&n_ops)) return -1;
  int64_t row = 0;
  for (uint64_t i = 0; i < n_ops; i++) {
    // Fast path for the dominant canonical add shape
    //   93 00 <member:fixint|cc|cd> 92 c4 10 <16B actor> <counter:…>
    // — one branch ladder instead of the generic nested walk (~2x on
    // add-heavy payloads; anything unexpected falls to the slow path).
    {
      const uint8_t* p = r.p;
      if ((uint64_t)(r.end - p) >= 24 && p[0] == 0x93 && p[1] == 0x00) {
        uint64_t moff0, mlen0;
        const uint8_t* q = p + 2;
        if (*q <= 0x7f) {
          moff0 = (uint64_t)(q - buf);
          mlen0 = 1;
          q += 1;
        } else if (*q == 0xcc && r.end - q >= 2) {
          moff0 = (uint64_t)(q - buf);
          mlen0 = 2;
          q += 2;
        } else if (*q == 0xcd && r.end - q >= 3) {
          moff0 = (uint64_t)(q - buf);
          mlen0 = 3;
          q += 3;
        } else {
          q = nullptr;
        }
        if (q != nullptr && (uint64_t)(r.end - q) >= 19 && q[0] == 0x92 &&
            q[1] == 0xc4 && q[2] == 0x10) {
          const uint8_t* a = q + 3;
          const uint8_t* c = a + 16;
          uint64_t counter;
          // the 24-byte entry guard covers fixint members only; a
          // uint16 member leaves the counter byte past it — re-bound
          bool okc = c < r.end;
          if (!okc) {
          } else if (*c <= 0x7f) {
            counter = *c;
            c += 1;
          } else if (*c == 0xcc && r.end - c >= 2) {
            counter = c[1];
            c += 2;
          } else if (*c == 0xcd && r.end - c >= 3) {
            counter = ((uint64_t)c[1] << 8) | c[2];
            c += 3;
          } else if (*c == 0xce && r.end - c >= 5) {
            counter = ((uint64_t)c[1] << 24) | ((uint64_t)c[2] << 16) |
                      ((uint64_t)c[3] << 8) | c[4];
            c += 5;
          } else {
            okc = false;
          }
          if (okc) {
            int ai = actor_lookup(look, a);
            if (ai < 0) return -1;
            sink.emit(0, moff0, mlen0, ai, (int32_t)counter);
            row++;
            r.p = c;
            continue;
          }
        }
      }
    }
    uint64_t three, kind;
    if (!r.arr(&three) || three != 3 || !r.uint(&kind)) return -1;
    const uint8_t* mspan;
    uint64_t mlen;
    if (!r.span(&mspan, &mlen)) return -1;
    uint64_t moff = (uint64_t)(mspan - buf);
    if (kind == 0) {
      uint64_t two;
      const uint8_t* a;
      uint64_t alen, counter;
      if (!r.arr(&two) || two != 2 || !r.bin(&a, &alen) || alen != 16 ||
          !r.uint(&counter))
        return -1;
      int ai = actor_lookup(look, a);
      if (ai < 0) return -1;
      sink.emit(0, moff, mlen, ai, (int32_t)counter);
      row++;
    } else if (kind == 1) {
      uint64_t m;
      if (!r.map(&m)) return -1;
      for (uint64_t j = 0; j < m; j++) {
        const uint8_t* a;
        uint64_t alen, counter;
        if (!r.bin(&a, &alen) || alen != 16 || !r.uint(&counter)) return -1;
        int ai = actor_lookup(look, a);
        if (ai < 0) return -1;
        sink.emit(1, moff, mlen, ai, (int32_t)counter);
        row++;
      }
    } else {
      return -1;
    }
  }
  return row;
}

// Fixed-array sink: caller pre-sized the outputs (orset_count_rows).
struct ArraySink {
  int8_t* kind;
  uint64_t* moff;
  uint64_t* mlen;
  int32_t* actor;
  int32_t* counter;
  int64_t row = 0;
  inline void emit(int8_t k, uint64_t mo, uint64_t ml, int32_t a,
                   int32_t c) {
    kind[row] = k;
    moff[row] = mo;
    mlen[row] = ml;
    actor[row] = a;
    counter[row] = c;
    row++;
  }
};

// Growable sink: single-pass decode with no pre-counting walk.
struct GrowSink {
  std::vector<int8_t> kind;
  std::vector<uint64_t> moff, mlen;
  std::vector<int32_t> actor, counter;
  inline void emit(int8_t k, uint64_t mo, uint64_t ml, int32_t a,
                   int32_t c) {
    kind.push_back(k);
    moff.push_back(mo);
    mlen.push_back(ml);
    actor.push_back(a);
    counter.push_back(c);
  }
};

}  // namespace

extern "C" {

// Count the flattened rows of an ORSet op-file payload (array of ops):
// adds contribute 1 row, removes contribute map-size rows.  Returns -1 on
// malformed input.
int64_t orset_count_rows(const uint8_t* buf, uint64_t len) {
  Reader r{buf, buf + len};
  uint64_t n_ops;
  if (!r.arr(&n_ops)) return -1;
  int64_t rows = 0;
  for (uint64_t i = 0; i < n_ops; i++) {
    uint64_t three, kind;
    if (!r.arr(&three) || three != 3 || !r.uint(&kind)) return -1;
    if (!r.skip()) return -1;  // member
    if (kind == 0) {
      uint64_t two;
      if (!r.arr(&two) || two != 2 || !r.skip() || !r.skip()) return -1;
      rows += 1;
    } else if (kind == 1) {
      uint64_t m;
      if (!r.map(&m)) return -1;
      for (uint64_t j = 0; j < m; j++)
        if (!r.skip() || !r.skip()) return -1;
      rows += (int64_t)m;
    } else {
      return -1;
    }
  }
  return rows;
}

// Decode an ORSet op-file payload into flat rows.  Members are reported as
// spans (offset/length into buf) for the caller to intern; actors resolve
// through an ActorLookup (hash slots or sorted-table binary search;
// unknown actors -> row dropped, returns -1).  Arrays must be pre-sized
// via orset_count_rows.  Returns rows written, or -1 on malformed input.
int64_t orset_decode_look(const uint8_t* buf, uint64_t len,
                          const ActorLookup& look, int8_t* kind_out,
                          uint64_t* member_off_out, uint64_t* member_len_out,
                          int32_t* actor_out, int32_t* counter_out) {
  ArraySink sink{kind_out, member_off_out, member_len_out, actor_out,
                 counter_out};
  return orset_decode_sink(buf, len, look, sink);
}

// Sorted-table entry point (legacy signature): binary-search lookup.
int64_t orset_decode(const uint8_t* buf, uint64_t len, const uint8_t* actors,
                     uint64_t n_actors, int8_t* kind_out,
                     uint64_t* member_off_out, uint64_t* member_len_out,
                     int32_t* actor_out, int32_t* counter_out) {
  ActorLookup look{actors, n_actors, nullptr, 0};
  return orset_decode_look(buf, len, look, kind_out, member_off_out,
                           member_len_out, actor_out, counter_out);
}

// Fill a power-of-two open-addressing slot index over the 16-byte actor
// table (pair with orset_decode_batch_h).  n_slots must be a power of
// two > n_actors; pick ~2× for short probe chains.
void actor_hash_build(const uint8_t* actors, uint64_t n_actors,
                      int32_t* slots, uint64_t n_slots) {
  const uint64_t mask = n_slots - 1;
  for (uint64_t i = 0; i < n_slots; i++) slots[i] = -1;
  for (uint64_t i = 0; i < n_actors; i++) {
    uint64_t p = actor_hash16(actors + 16 * i) & mask;
    while (slots[p] >= 0) p = (p + 1) & mask;
    slots[p] = (int32_t)i;
  }
}

// Batch variants: one native call for tens of thousands of payloads.  A
// per-payload ctypes round-trip costs ~25µs of Python overhead, which at
// the 100k-replica streaming scale (config 5: ~2-op files) dwarfs the
// decode itself; looping in C removes it.

// Counts each payload's rows into counts_out; returns the total or -1 on
// the first malformed payload.
int64_t orset_count_rows_batch(const uint8_t* buf, const uint64_t* bases,
                               const uint64_t* lens, uint64_t n_payloads,
                               int64_t* counts_out) {
  int64_t total = 0;
  for (uint64_t i = 0; i < n_payloads; i++) {
    int64_t c = orset_count_rows(buf + bases[i], lens[i]);
    if (c < 0) return -1;
    counts_out[i] = c;
    total += c;
  }
  return total;
}

// Decodes every payload into consecutive row slices; member offsets come
// out relative to the whole buffer.  counts must be the per-payload row
// counts from orset_count_rows_batch (output arrays sized to their sum).
// Returns total rows written or -1.
int64_t orset_decode_batch_h(const uint8_t* buf, const uint64_t* bases,
                             const uint64_t* lens, uint64_t n_payloads,
                             const uint8_t* actors, uint64_t n_actors,
                             const int32_t* slots, uint64_t n_slots,
                             const int64_t* counts, int8_t* kind_out,
                             uint64_t* member_off_out,
                             uint64_t* member_len_out, int32_t* actor_out,
                             int32_t* counter_out) {
  ActorLookup look{actors, n_actors, slots,
                   n_slots ? n_slots - 1 : 0};
  int64_t row = 0;
  for (uint64_t i = 0; i < n_payloads; i++) {
    int64_t got = orset_decode_look(
        buf + bases[i], lens[i], look, kind_out + row, member_off_out + row,
        member_len_out + row, actor_out + row, counter_out + row);
    if (got != counts[i]) return -1;
    for (int64_t j = 0; j < got; j++) member_off_out[row + j] += bases[i];
    row += got;
  }
  return row;
}

int64_t orset_decode_batch(const uint8_t* buf, const uint64_t* bases,
                           const uint64_t* lens, uint64_t n_payloads,
                           const uint8_t* actors, uint64_t n_actors,
                           const int64_t* counts, int8_t* kind_out,
                           uint64_t* member_off_out, uint64_t* member_len_out,
                           int32_t* actor_out, int32_t* counter_out) {
  return orset_decode_batch_h(buf, bases, lens, n_payloads, actors, n_actors,
                              nullptr, 0, counts, kind_out, member_off_out,
                              member_len_out, actor_out, counter_out);
}

// Single-pass growable batch decode: no pre-counting walk (the count
// pass re-parses every payload — ~half the decode cost at the config-5
// shape).  Returns an opaque handle + row count via n_rows_out, or
// nullptr on malformed input / unknown actor.  The caller copies the
// columns out with orset_decode_take (which frees the handle).
void* orset_decode_batch_grow(const uint8_t* buf, const uint64_t* bases,
                              const uint64_t* lens, uint64_t n_payloads,
                              const uint8_t* actors, uint64_t n_actors,
                              const int32_t* slots, uint64_t n_slots,
                              int64_t* n_rows_out) {
  ActorLookup look{actors, n_actors, slots, n_slots ? n_slots - 1 : 0};
  GrowSink* sink = nullptr;
  // bad_alloc from vector growth must not unwind through the extern "C"
  // boundary into ctypes (std::terminate); nullptr = caller falls back
  try {
    sink = new GrowSink();
    sink->kind.reserve(4 * n_payloads);
    for (uint64_t i = 0; i < n_payloads; i++) {
      const size_t before = sink->kind.size();
      int64_t got = orset_decode_sink(buf + bases[i], lens[i], look, *sink);
      if (got < 0) {
        delete sink;
        return nullptr;
      }
      for (size_t j = before; j < sink->kind.size(); j++)
        sink->moff[j] += bases[i];
    }
  } catch (const std::bad_alloc&) {
    delete sink;
    return nullptr;
  }
  *n_rows_out = (int64_t)sink->kind.size();
  return sink;
}

void orset_decode_take(void* h, int8_t* kind_out, uint64_t* member_off_out,
                       uint64_t* member_len_out, int32_t* actor_out,
                       int32_t* counter_out) {
  GrowSink* sink = (GrowSink*)h;
  const size_t n = sink->kind.size();
  if (n) {
    memcpy(kind_out, sink->kind.data(), n * sizeof(int8_t));
    memcpy(member_off_out, sink->moff.data(), n * sizeof(uint64_t));
    memcpy(member_len_out, sink->mlen.data(), n * sizeof(uint64_t));
    memcpy(actor_out, sink->actor.data(), n * sizeof(int32_t));
    memcpy(counter_out, sink->counter.data(), n * sizeof(int32_t));
  }
  delete sink;
}

void orset_decode_drop(void* h) { delete (GrowSink*)h; }

// Decode a counter op-file payload: array of [dir, [actor16, counter]]
// (PN-Counter) or [actor16, counter] (G-Counter).  Returns rows or -1.
int64_t counter_decode(const uint8_t* buf, uint64_t len,
                       const uint8_t* actors, uint64_t n_actors,
                       int8_t* sign_out, int32_t* actor_out,
                       int32_t* counter_out) {
  Reader r{buf, buf + len};
  uint64_t n_ops;
  if (!r.arr(&n_ops)) return -1;
  for (uint64_t i = 0; i < n_ops; i++) {
    uint64_t alen2;
    if (!r.arr(&alen2)) return -1;
    uint64_t dir = 0;
    const uint8_t* a;
    uint64_t alen, counter;
    if (alen2 == 2) {
      // peek: [bin, uint] = G-Counter dot; [uint, [..]] = PN op
      if (r.p < r.end && (*r.p == 0xc4 || *r.p == 0xc5 || *r.p == 0xc6)) {
        if (!r.bin(&a, &alen) || alen != 16 || !r.uint(&counter)) return -1;
      } else {
        uint64_t two;
        if (!r.uint(&dir) || dir > 1 || !r.arr(&two) || two != 2 ||
            !r.bin(&a, &alen) || alen != 16 || !r.uint(&counter))
          return -1;
      }
    } else {
      return -1;
    }
    int ai = actor_index(actors, n_actors, a);
    if (ai < 0) return -1;
    sign_out[i] = (int8_t)dir;
    actor_out[i] = ai;
    counter_out[i] = (int32_t)counter;
  }
  return (int64_t)n_ops;
}

// Batch counter decode into consecutive row slices (outputs must hold at
// least one row per payload byte — a safe upper bound since every op
// costs >1 byte).  Returns total rows or -1.
int64_t counter_decode_batch(const uint8_t* buf, const uint64_t* bases,
                             const uint64_t* lens, uint64_t n_payloads,
                             const uint8_t* actors, uint64_t n_actors,
                             int8_t* sign_out, int32_t* actor_out,
                             int32_t* counter_out) {
  int64_t row = 0;
  for (uint64_t i = 0; i < n_payloads; i++) {
    int64_t got = counter_decode(buf + bases[i], lens[i], actors, n_actors,
                                 sign_out + row, actor_out + row,
                                 counter_out + row);
    if (got < 0) return -1;
    row += got;
  }
  return row;
}

// ---- causal-map (CrdtMap<orset>) op decoding ----------------------------
//
// Wire forms (models/crdtmap.py op_to_obj):
//   Up: [0, [actor16, counter], key, child]
//     child add: [0, member, [actor16, counter]]   (dot must equal map dot)
//     child rm:  [1, member, {actor16: counter, ...}]
//   Rm: [1, {actor16: counter, ...}, [key, ...]]
//
// Emits four row families (the columnar form of the map fold):
//   birth:     (key_span, actor, counter)            one per Up
//   child-add: (key_span, member_span, actor, counter)
//   child-rm:  (key_span, member_span, actor, counter) per ctx entry
//   key-rm:    (key_span, actor, counter)            per ctx entry x key
// Returns -1 on any surprise (unknown actor, child dot != map dot,
// malformed): the caller falls back to the per-op path.

struct MapCounts {
  int64_t birth, cadd, crm, krm;
};

static int map_count_payload(const uint8_t* buf, uint64_t len, MapCounts* mc) {
  Reader r{buf, buf + len};
  uint64_t n_ops;
  if (!r.arr(&n_ops)) return -1;
  for (uint64_t i = 0; i < n_ops; i++) {
    uint64_t alen;
    if (!r.arr(&alen)) return -1;
    uint64_t tag;
    if (!r.uint(&tag)) return -1;
    if (tag == 0) {
      if (alen != 4) return -1;
      uint64_t dlen;
      const uint8_t* a;
      uint64_t abytes, c;
      if (!r.arr(&dlen) || dlen != 2 || !r.bin(&a, &abytes) || abytes != 16 ||
          !r.uint(&c))
        return -1;
      if (!r.skip()) return -1;  // key
      mc->birth++;
      uint64_t clen;
      if (!r.arr(&clen) || clen != 3) return -1;
      uint64_t ckind;
      if (!r.uint(&ckind)) return -1;
      if (!r.skip()) return -1;  // member
      if (ckind == 0) {
        uint64_t d2;
        if (!r.arr(&d2) || d2 != 2 || !r.bin(&a, &abytes) || abytes != 16 ||
            !r.uint(&c))
          return -1;
        mc->cadd++;
      } else if (ckind == 1) {
        uint64_t m;
        if (!r.map(&m)) return -1;
        for (uint64_t j = 0; j < m; j++) {
          if (!r.bin(&a, &abytes) || abytes != 16 || !r.uint(&c)) return -1;
          mc->crm++;
        }
      } else {
        return -1;
      }
    } else if (tag == 1) {
      if (alen != 3) return -1;
      uint64_t m;
      if (!r.map(&m)) return -1;
      const uint8_t* a;
      uint64_t abytes, c;
      for (uint64_t j = 0; j < m; j++) {
        if (!r.bin(&a, &abytes) || abytes != 16 || !r.uint(&c)) return -1;
      }
      uint64_t nk;
      if (!r.arr(&nk)) return -1;
      for (uint64_t k = 0; k < nk; k++)
        if (!r.skip()) return -1;
      mc->krm += (int64_t)(m * nk);
    } else {
      return -1;
    }
  }
  return 0;
}

struct MapOut {
  const uint8_t* base;
  // birth
  uint64_t* b_koff; uint64_t* b_klen; int32_t* b_actor; int32_t* b_ctr;
  int64_t b_row;
  // child add
  uint64_t* a_koff; uint64_t* a_klen; uint64_t* a_moff; uint64_t* a_mlen;
  int32_t* a_actor; int32_t* a_ctr; int64_t a_row;
  // child rm (r_mactor/r_mctr = the Up's MAP dot, for suppression gates)
  uint64_t* r_koff; uint64_t* r_klen; uint64_t* r_moff; uint64_t* r_mlen;
  int32_t* r_actor; int32_t* r_ctr; int32_t* r_mactor; int32_t* r_mctr;
  int64_t r_row;
  // key rm (k_group = index of the originating Rm op, so the fold can
  // evaluate fire-or-defer per WHOLE remove)
  uint64_t* k_koff; uint64_t* k_klen; int32_t* k_actor; int32_t* k_ctr;
  int32_t* k_group; int64_t k_row; int32_t group_no;
};

static int map_decode_payload(const uint8_t* buf, uint64_t len,
                              const uint8_t* actors, uint64_t n_actors,
                              MapOut* o) {
  Reader r{buf, buf + len};
  uint64_t n_ops;
  if (!r.arr(&n_ops)) return -1;
  for (uint64_t i = 0; i < n_ops; i++) {
    uint64_t alen;
    if (!r.arr(&alen)) return -1;
    uint64_t tag;
    if (!r.uint(&tag)) return -1;
    if (tag == 0) {
      uint64_t dlen;
      const uint8_t* a;
      uint64_t abytes, c;
      if (!r.arr(&dlen) || dlen != 2 || !r.bin(&a, &abytes) || abytes != 16 ||
          !r.uint(&c))
        return -1;
      int ai = actor_index(actors, n_actors, a);
      if (ai < 0) return -1;
      const uint8_t* ks;
      uint64_t kn;
      if (!r.span(&ks, &kn)) return -1;
      o->b_koff[o->b_row] = (uint64_t)(ks - o->base);
      o->b_klen[o->b_row] = kn;
      o->b_actor[o->b_row] = ai;
      o->b_ctr[o->b_row] = (int32_t)c;
      o->b_row++;
      uint64_t clen;
      if (!r.arr(&clen) || clen != 3) return -1;
      uint64_t ckind;
      if (!r.uint(&ckind)) return -1;
      const uint8_t* ms;
      uint64_t mn;
      if (!r.span(&ms, &mn)) return -1;
      if (ckind == 0) {
        const uint8_t* ca;
        uint64_t cab, cc;
        uint64_t d2;
        if (!r.arr(&d2) || d2 != 2 || !r.bin(&ca, &cab) || cab != 16 ||
            !r.uint(&cc))
          return -1;
        // the shared-dot discipline the columnar fold relies on
        if (memcmp(ca, a, 16) != 0 || cc != c) return -1;
        o->a_koff[o->a_row] = (uint64_t)(ks - o->base);
        o->a_klen[o->a_row] = kn;
        o->a_moff[o->a_row] = (uint64_t)(ms - o->base);
        o->a_mlen[o->a_row] = mn;
        o->a_actor[o->a_row] = ai;
        o->a_ctr[o->a_row] = (int32_t)c;
        o->a_row++;
      } else {
        uint64_t m;
        if (!r.map(&m)) return -1;
        for (uint64_t j = 0; j < m; j++) {
          const uint8_t* ca;
          uint64_t cab, cc;
          if (!r.bin(&ca, &cab) || cab != 16 || !r.uint(&cc)) return -1;
          int cai = actor_index(actors, n_actors, ca);
          if (cai < 0) return -1;
          o->r_koff[o->r_row] = (uint64_t)(ks - o->base);
          o->r_klen[o->r_row] = kn;
          o->r_moff[o->r_row] = (uint64_t)(ms - o->base);
          o->r_mlen[o->r_row] = mn;
          o->r_actor[o->r_row] = cai;
          o->r_ctr[o->r_row] = (int32_t)cc;
          o->r_mactor[o->r_row] = ai;
          o->r_mctr[o->r_row] = (int32_t)c;
          o->r_row++;
        }
      }
    } else {
      uint64_t m;
      if (!r.map(&m)) return -1;
      // ctx entries first, then the keys they apply to — buffer the ctx
      int32_t ctx_a[64];
      int32_t ctx_c[64];
      if (m > 64) return -1;  // rm_ctx over >64 actors: per-op path
      for (uint64_t j = 0; j < m; j++) {
        const uint8_t* ca;
        uint64_t cab, cc;
        if (!r.bin(&ca, &cab) || cab != 16 || !r.uint(&cc)) return -1;
        int cai = actor_index(actors, n_actors, ca);
        if (cai < 0) return -1;
        ctx_a[j] = cai;
        ctx_c[j] = (int32_t)cc;
      }
      uint64_t nk;
      if (!r.arr(&nk)) return -1;
      for (uint64_t k = 0; k < nk; k++) {
        const uint8_t* ks;
        uint64_t kn;
        if (!r.span(&ks, &kn)) return -1;
        for (uint64_t j = 0; j < m; j++) {
          o->k_koff[o->k_row] = (uint64_t)(ks - o->base);
          o->k_klen[o->k_row] = kn;
          o->k_actor[o->k_row] = ctx_a[j];
          o->k_ctr[o->k_row] = ctx_c[j];
          o->k_group[o->k_row] = o->group_no;
          o->k_row++;
        }
      }
      o->group_no++;
    }
  }
  return 0;
}

extern "C" int64_t map_count_rows_batch(const uint8_t* buf,
                                        const uint64_t* bases,
                                        const uint64_t* lens,
                                        uint64_t n_payloads,
                                        int64_t counts_out[4]) {
  MapCounts mc{0, 0, 0, 0};
  for (uint64_t i = 0; i < n_payloads; i++)
    if (map_count_payload(buf + bases[i], lens[i], &mc) < 0) return -1;
  counts_out[0] = mc.birth;
  counts_out[1] = mc.cadd;
  counts_out[2] = mc.crm;
  counts_out[3] = mc.krm;
  return mc.birth + mc.cadd + mc.crm + mc.krm;
}

extern "C" int64_t map_decode_batch(
    const uint8_t* buf, const uint64_t* bases, const uint64_t* lens,
    uint64_t n_payloads, const uint8_t* actors, uint64_t n_actors,
    uint64_t* b_koff, uint64_t* b_klen, int32_t* b_actor, int32_t* b_ctr,
    uint64_t* a_koff, uint64_t* a_klen, uint64_t* a_moff, uint64_t* a_mlen,
    int32_t* a_actor, int32_t* a_ctr,
    uint64_t* r_koff, uint64_t* r_klen, uint64_t* r_moff, uint64_t* r_mlen,
    int32_t* r_actor, int32_t* r_ctr, int32_t* r_mactor, int32_t* r_mctr,
    uint64_t* k_koff, uint64_t* k_klen, int32_t* k_actor, int32_t* k_ctr,
    int32_t* k_group) {
  MapOut o{};
  o.base = buf;
  o.b_koff = b_koff; o.b_klen = b_klen; o.b_actor = b_actor; o.b_ctr = b_ctr;
  o.a_koff = a_koff; o.a_klen = a_klen; o.a_moff = a_moff; o.a_mlen = a_mlen;
  o.a_actor = a_actor; o.a_ctr = a_ctr;
  o.r_koff = r_koff; o.r_klen = r_klen; o.r_moff = r_moff; o.r_mlen = r_mlen;
  o.r_actor = r_actor; o.r_ctr = r_ctr; o.r_mactor = r_mactor; o.r_mctr = r_mctr;
  o.k_koff = k_koff; o.k_klen = k_klen; o.k_actor = k_actor; o.k_ctr = k_ctr;
  o.k_group = k_group;
  for (uint64_t i = 0; i < n_payloads; i++)
    if (map_decode_payload(buf + bases[i], lens[i], actors, n_actors, &o) < 0)
      return -1;
  return o.b_row + o.a_row + o.r_row + o.k_row;
}

// Masked scatter-max of one op-row chunk into the (E, R) add/rm planes —
// the native twin of the fold session's host reduction (np.maximum.at is
// a buffered ufunc, ~10x slower than this loop at memory bandwidth).
// Semantics identical to orset_fold's scatter phase: padding rows
// (actor >= R) skip, stale adds (counter <= clock0[actor]) skip.
// Returns the number of rows whose member index fell outside [0, E)
// (0 = clean; nonzero means the caller's plane sizing is buggy).
int64_t orset_host_reduce(const int8_t* kind, const int32_t* member,
                          const int32_t* actor, const int32_t* counter,
                          int64_t n, const int32_t* clock0, int32_t R,
                          int64_t E, int32_t* add, int32_t* rm) {
  int64_t oob = 0;
  for (int64_t i = 0; i < n; i++) {
    int32_t a = actor[i];
    if (a < 0 || a >= R) continue;  // sentinel padding column
    int64_t m = member[i];
    if (m < 0 || m >= E) { oob++; continue; }
    int32_t c = counter[i];
    int32_t* cell;
    if (kind[i] == 0) {
      if (c <= clock0[a]) continue;  // stale-add replay
      cell = add + m * R + a;
    } else {
      cell = rm + m * R + a;
    }
    if (c > *cell) *cell = c;
  }
  return oob;
}

// FNV-1a over a byte span
static inline uint64_t span_hash(const uint8_t* p, uint64_t n) {
  uint64_t h = 1469598103934665603ULL;
  for (uint64_t i = 0; i < n; i++) h = (h ^ p[i]) * 1099511628211ULL;
  return h;
}

// Intern member byte spans natively: rows → dense first-appearance ids.
// ``table``/``table_cap`` is caller-allocated scratch (int64, all -1,
// capacity a power of two > 2 * expected uniques).  Unique spans are
// emitted as (offset, length) pairs into uniq_off/uniq_len (capacity
// ``max_uniq``).  Returns the unique count, or -1 when uniq/table
// capacity is exhausted (caller falls back or retries bigger).
int64_t intern_spans_native(const uint8_t* buf, const uint64_t* off,
                            const uint64_t* len, int64_t n,
                            int64_t* table, int64_t table_cap,
                            int32_t* idx_out, uint64_t* uniq_off,
                            uint64_t* uniq_len, int64_t max_uniq) {
  const uint64_t mask = (uint64_t)table_cap - 1;
  int64_t n_uniq = 0;
  for (int64_t i = 0; i < n; i++) {
    const uint8_t* s = buf + off[i];
    const uint64_t L = len[i];
    uint64_t h = span_hash(s, L) & mask;
    for (;;) {
      int64_t slot = table[h];
      if (slot < 0) {
        if (n_uniq >= max_uniq || n_uniq * 2 >= table_cap) return -1;
        table[h] = n_uniq;
        uniq_off[n_uniq] = off[i];
        uniq_len[n_uniq] = L;
        idx_out[i] = (int32_t)n_uniq;
        n_uniq++;
        break;
      }
      if (uniq_len[slot] == L && memcmp(buf + uniq_off[slot], s, L) == 0) {
        idx_out[i] = (int32_t)slot;
        break;
      }
      h = (h + 1) & mask;
    }
  }
  return n_uniq;
}

}  // extern "C"

"""Version-tagged byte blobs — the wire-format substrate.

Every persisted object in the framework (op files, state snapshots, remote
metadata, key material, ciphertext envelopes) is a ``VersionBytes``: a 16-byte
format-version identifier (UUID) followed by an opaque payload.  Formats can
evolve without breaking old replicas because every boundary checks the version
against an explicit supported set before decoding.

Two serializations exist, mirroring the reference's wire surface
(``/root/reference/crdt-enc/src/utils/version_bytes.rs``):

* **raw**: 16-byte big-endian UUID ‖ payload (reference ``serialize``/
  ``deserialize``, version_bytes.rs:186-208).  Used for whole files.
* **msgpack**: a 2-element array ``[version_bytes, payload_bytes]`` (reference
  serde tuple form, version_bytes.rs:32).  Used when a VersionBytes is nested
  inside another msgpack document (e.g. MVReg values, EncBox envelopes).

``VersionBytesBuf`` is the zero-copy chained buffer over (version, content)
with chunk/advance/vectored semantics (reference version_bytes.rs:245-309).
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass
from typing import Iterable

VERSION_LEN = 16


class VersionError(Exception):
    """A version tag did not match the expected / supported set."""

    def __init__(self, got: bytes, expected: Iterable[bytes]):
        self.got = bytes(got)
        self.expected = [bytes(e) for e in expected]
        super().__init__(
            f"unsupported version {uuid.UUID(bytes=self.got)}; expected one of "
            f"{[str(uuid.UUID(bytes=e)) for e in self.expected]}"
        )


class DeserializeError(Exception):
    """Raw buffer too short to contain a version tag."""


def _as_version(v: bytes | uuid.UUID) -> bytes:
    if isinstance(v, uuid.UUID):
        return v.bytes
    v = bytes(v)
    if len(v) != VERSION_LEN:
        raise ValueError(f"version must be {VERSION_LEN} bytes, got {len(v)}")
    return v


@dataclass(frozen=True)
class VersionBytes:
    """An owned version-tagged payload."""

    version: bytes  # 16-byte big-endian UUID
    content: bytes

    def __post_init__(self) -> None:
        object.__setattr__(self, "version", _as_version(self.version))
        object.__setattr__(self, "content", bytes(self.content))

    # -- raw form: 16-byte UUID ‖ payload ---------------------------------
    def serialize(self) -> bytes:
        return self.version + self.content

    @classmethod
    def deserialize(cls, raw: bytes) -> "VersionBytes":
        raw = bytes(raw)
        if len(raw) < VERSION_LEN:
            raise DeserializeError(
                f"buffer of {len(raw)} bytes is too short for a "
                f"{VERSION_LEN}-byte version tag"
            )
        return cls(raw[:VERSION_LEN], raw[VERSION_LEN:])

    # -- msgpack form: 2-element array ------------------------------------
    def to_obj(self) -> list:
        """The msgpack-serializable form (2-element array)."""
        return [self.version, self.content]

    @classmethod
    def from_obj(cls, obj) -> "VersionBytes":
        if not isinstance(obj, (list, tuple)) or len(obj) != 2:
            raise DeserializeError(f"expected [version, content] pair, got {obj!r}")
        version, content = obj
        if not isinstance(version, (bytes, bytearray, memoryview)) or not isinstance(
            content, (bytes, bytearray, memoryview)
        ):
            raise DeserializeError(
                f"expected byte fields in [version, content] pair, got "
                f"[{type(version).__name__}, {type(content).__name__}]"
            )
        if len(bytes(version)) != VERSION_LEN:
            raise DeserializeError(
                f"version tag must be {VERSION_LEN} bytes, got {len(bytes(version))}"
            )
        return cls(bytes(version), bytes(content))

    # -- version checks ----------------------------------------------------
    def ensure_version(self, expected: bytes | uuid.UUID) -> "VersionBytes":
        expected = _as_version(expected)
        if self.version != expected:
            raise VersionError(self.version, [expected])
        return self

    def ensure_versions(self, supported: Iterable[bytes | uuid.UUID]) -> "VersionBytes":
        supported = [_as_version(s) for s in supported]
        if self.version not in supported:
            raise VersionError(self.version, supported)
        return self

    @property
    def uuid(self) -> uuid.UUID:
        return uuid.UUID(bytes=self.version)

    def buf(self) -> "VersionBytesBuf":
        return VersionBytesBuf(self.version, self.content)


class VersionBytesBuf:
    """Zero-copy buffer chaining the version tag and the content.

    Implements the chunked-buffer contract (remaining / chunk / advance /
    chunks_vectored) so writers can emit version‖content without concatenating
    (reference ``VersionBytesBuf``, version_bytes.rs:245-309).
    """

    def __init__(self, version: bytes | uuid.UUID, content: bytes):
        self._version = memoryview(_as_version(version))
        self._content = memoryview(bytes(content))
        self._pos = 0  # absolute cursor over version ‖ content

    def __len__(self) -> int:
        return self.remaining()

    def remaining(self) -> int:
        return (VERSION_LEN + len(self._content)) - self._pos

    def chunk(self) -> memoryview:
        """The current contiguous chunk (never straddles the boundary)."""
        if self._pos < VERSION_LEN:
            return self._version[self._pos :]
        off = self._pos - VERSION_LEN
        return self._content[off:]

    def advance(self, n: int) -> None:
        if n < 0:
            raise IndexError(f"cannot advance by negative amount {n}")
        if n > self.remaining():
            raise IndexError(
                f"cannot advance {n} bytes; only {self.remaining()} remaining"
            )
        self._pos += n

    def chunks_vectored(self, limit: int = 64) -> list[memoryview]:
        """All remaining chunks, for vectored (writev-style) I/O."""
        out: list[memoryview] = []
        if limit <= 0 or self.remaining() == 0:
            return out
        if self._pos < VERSION_LEN:
            out.append(self._version[self._pos :])
            if len(out) < limit and len(self._content) > 0:
                out.append(self._content[:])
        else:
            off = self._pos - VERSION_LEN
            if off < len(self._content):
                out.append(self._content[off:])
        return out

    def read_all(self) -> bytes:
        """Drain the buffer into one bytes object."""
        out = bytearray()
        while self.remaining():
            c = self.chunk()
            out += c
            self.advance(len(c))
        return bytes(out)

"""Format-version registry for crdt-enc-tpu.

The reference's de-facto config system is compile-time version sets checked at
every decode boundary (reference crdt-enc/src/lib.rs:26-31, phf sets;
xchacha lib.rs:11-16).  We mirror that with module-level frozen constants.

All UUIDs below are this framework's own identifiers (generated fresh — this
is a new wire format, not byte-compatible with the reference's Rust UUIDs,
which are private to that implementation).
"""

import uuid

# Outer container-format version stamped on every stored file
# (ops, states, remote metas).  Reference analogue: CURRENT_VERSION lib.rs:26.
CONTAINER_VERSION_1 = uuid.UUID("8f1d0c7e-2f6a-4bd1-9a3e-5c9b1a6e0d01").bytes
CURRENT_CONTAINER_VERSION = CONTAINER_VERSION_1
SUPPORTED_CONTAINER_VERSIONS = frozenset({CONTAINER_VERSION_1})

# Cipher-envelope version stamped by the XChaCha20-Poly1305 cryptor on its
# EncBox payloads.  Reference analogue: DATA_VERSION xchacha lib.rs:11.
XCHACHA_DATA_VERSION_1 = uuid.UUID("3a7c44f2-9e51-4f0b-8d2c-7b61e4a9c102").bytes
# Key-material version stamped on generated keys.  Reference: KEY_VERSION.
XCHACHA_KEY_VERSION_1 = uuid.UUID("b45e19d8-6c3f-4aa7-92e0-1f8d57c3ab03").bytes

# Identity (test) cryptor versions.
IDENTITY_DATA_VERSION_1 = uuid.UUID("5d2f8b1a-0e47-4c69-b3d5-9a64e72f1c04").bytes
IDENTITY_KEY_VERSION_1 = uuid.UUID("e91a3c56-7d20-4b8f-a6e1-48c5d90b2f05").bytes

# Key-cryptor remote-meta format (the Keys CRDT blob in the meta MVReg).
KEYS_META_VERSION_1 = uuid.UUID("27c6e0f9-15ab-4d72-8c43-6e9f01d5ba06").bytes
SUPPORTED_KEYS_META_VERSIONS = frozenset({KEYS_META_VERSION_1})

# Passphrase-wrapped key-cryptor remote-meta format: the Keys blob sealed
# under a scrypt-derived key (salt + KDF params + XChaCha EncBox envelope).
PASSPHRASE_KEYS_META_VERSION_1 = uuid.UUID(
    "9d84f2a1-6b0e-4c57-a3d9-0f72e85c4b08"
).bytes
SUPPORTED_PASSPHRASE_KEYS_META_VERSIONS = frozenset(
    {PASSPHRASE_KEYS_META_VERSION_1}
)

# OpenPGP key-cryptor remote-meta format: the Keys blob is an OpenPGP
# message encrypted to the recipient keyring (the interop the reference's
# gpgme backend declared and never shipped)
GPG_KEYS_META_VERSION_1 = uuid.UUID(
    "7b0e66a1-9c2d-4f5e-b6a7-3d8c1e4f5a62"
).bytes
SUPPORTED_GPG_KEYS_META_VERSIONS = frozenset({GPG_KEYS_META_VERSION_1})

# Recipient-keyed (X25519) key-cryptor remote-meta format: the Keys blob
# sealed to a set of recipient public keys (ephemeral ECDH + HKDF + AEAD).
X25519_KEYS_META_VERSION_1 = uuid.UUID(
    "4fb7a9d2-3c16-4e80-9b5a-217f60d8e3c9"
).bytes
SUPPORTED_X25519_KEYS_META_VERSIONS = frozenset({X25519_KEYS_META_VERSION_1})

# Application-data versions are *not* fixed here: like the reference's
# OpenOptions.supported_data_versions (lib.rs:730-731) they are chosen by the
# application that owns the CRDT state type.  A reasonable default for tests:
DEFAULT_DATA_VERSION_1 = uuid.UUID("c3b80d17-42fe-4e95-b7a8-2d50c61e9f07").bytes

from .version_bytes import (
    VERSION_LEN,
    DeserializeError,
    VersionBytes,
    VersionBytesBuf,
    VersionError,
)
from . import codec, versions

__all__ = [
    "VERSION_LEN",
    "DeserializeError",
    "VersionBytes",
    "VersionBytesBuf",
    "VersionError",
    "codec",
    "versions",
]

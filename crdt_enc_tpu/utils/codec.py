"""Canonical msgpack codec.

All persisted CRDT state must serialize deterministically (byte-identical
across host-reference and TPU paths, and across fold orders), so every map is
emitted with lexicographically sorted keys and every container type is
normalized before packing.  msgpack's C extension does the heavy lifting.
"""

from __future__ import annotations

import logging

import msgpack

logger = logging.getLogger("crdt_enc_tpu.codec")

_native_pack = None  # resolved lazily; False = unavailable for good


def _warn_no_native_pack(exc: Exception) -> None:
    """The canonical-pack fast path disabling must be VISIBLE (EXC001):
    a binding regression would otherwise silently put ~400ms back on
    every canonical_bytes call.  Logged once — the resolution is cached
    for the process, so the fallback decision happens exactly once too."""
    logger.warning(
        "native canon_pack unavailable (%r); using the Python "
        "canonicalization path for all packs", exc
    )


def pack(obj) -> bytes:
    """Deterministic msgpack: sorted map keys, bin type for bytes.

    Hot path (sealing a compacted state, canonical_bytes in every
    equality check): the native canonical packer (statebuild.cpp
    ``canon_pack``) emits the identical bytes in one C pass — the
    Python ``_canon`` walk + ``packb`` cost ~400ms on a 100k-replica
    state.  Objects with types the native packer doesn't know (sets,
    numpy scalars, custom classes) fall through to the Python path, as
    does an environment without the native build."""
    global _native_pack
    if _native_pack is None:
        try:
            from .. import native

            _native_pack = native.load_state().canon_pack
        except Exception as e:
            _warn_no_native_pack(e)
            _native_pack = False
    if _native_pack:
        out = _native_pack(obj)
        if out is not None:
            return out
    return msgpack.packb(_canon(obj), use_bin_type=True)


def unpack(data: bytes):
    """Decode canonical msgpack.  Arrays come back as tuples (use_list=False)
    so that composite map keys — e.g. (replica, counter) dots — stay hashable."""
    return msgpack.unpackb(
        bytes(data), raw=False, strict_map_key=False, use_list=False
    )


def _canon(obj, as_key: bool = False):
    # scalar fast path first: the overwhelming majority of nodes are
    # scalars/bytes and pack() sits on every hot path (seal,
    # canonical_bytes, sort keys), so per-node isinstance chains add up
    t = obj.__class__
    if t is int or t is bytes or t is str or obj is None or t is bool or t is float:
        return obj
    if isinstance(obj, dict):
        # Sort by the packed key bytes so ordering is type-stable.
        items = [(_canon(k, as_key=True), _canon(v)) for k, v in obj.items()]
        items.sort(key=lambda kv: msgpack.packb(kv[0], use_bin_type=True))
        return {k: v for k, v in items}
    if isinstance(obj, (list, tuple)):
        # Map keys must stay hashable; tuples pack identically to lists.
        seq = [_canon(x, as_key=as_key) for x in obj]
        return tuple(seq) if as_key else seq
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return bytes(obj)
    return obj

"""LockBox: the no-await data-lock discipline as a runtime *mechanism*.

The reference's ``LockBox`` (crdt-enc/src/utils/mod.rs:165-195) is a sync
mutex wrapper whose API makes holding the guard across an ``await``
unrepresentable at compile time: the closure passed to ``with_`` is
synchronous, so the borrow cannot outlive the call.  Python cannot forbid
this statically, so this module enforces the same contract at runtime:

* ``LockBox.with_(fn)`` runs a **synchronous** ``fn(value)`` — coroutine
  functions are rejected up front, and a returned awaitable/generator
  (the sneaky way to smuggle the borrow across a suspension point) is
  rejected after the fact.
* ``fn`` receives a revocable **borrow proxy**, not the value itself.  At
  section exit the proxy is revoked; any retained reference that is used
  later — the Python shape of "held the lock across an await" — raises
  ``LockBoxViolation`` at the exact use site instead of racing silently.
* A contextvar tracks section depth so re-entrant sections compose and
  debug assertions (``in_section()``) are available to callers that need
  to require or forbid being inside one.

The proxy layer is active only under ``__debug__`` (i.e. not with
``python -O``), mirroring a debug-mode borrow checker: release builds pay
nothing, test/dev builds turn the convention into a hard error.
"""

from __future__ import annotations

import asyncio
import contextvars
import inspect
from typing import Any, Callable, TypeVar

T = TypeVar("T")

_depth: contextvars.ContextVar[int] = contextvars.ContextVar(
    "lockbox_depth", default=0
)


class LockBoxViolation(RuntimeError):
    """A LockBox borrow escaped its synchronous section and was used."""


class _Borrow:
    """Revocable attribute-forwarding proxy around the guarded value."""

    __slots__ = ("_lockbox_value", "_lockbox_alive")

    def __init__(self, value: Any):
        object.__setattr__(self, "_lockbox_value", value)
        object.__setattr__(self, "_lockbox_alive", True)

    def _check(self) -> Any:
        if not object.__getattribute__(self, "_lockbox_alive"):
            raise LockBoxViolation(
                "LockBox borrow used outside its synchronous section — the "
                "guarded value was retained across a suspension point "
                "(reference utils/mod.rs:165-195 forbids this by type)"
            )
        return object.__getattribute__(self, "_lockbox_value")

    def __getattr__(self, name: str) -> Any:
        return getattr(self._check(), name)

    def __setattr__(self, name: str, value: Any) -> None:
        setattr(self._check(), name, value)

    def __delattr__(self, name: str) -> None:
        delattr(self._check(), name)

    def __repr__(self) -> str:
        return f"<LockBox borrow of {self._check()!r}>"

    # Implicit special-method lookup skips __getattr__, so the protocol
    # operations the CRDT models implement are forwarded explicitly —
    # without these, `s == other` inside a section would silently fall
    # back to object identity and `len(s)` would raise.
    def __eq__(self, other):
        return self._check() == other

    def __ne__(self, other):
        return self._check() != other

    def __hash__(self):
        return hash(self._check())

    def __len__(self):
        return len(self._check())

    def __iter__(self):
        return iter(self._check())

    def __contains__(self, item):
        return item in self._check()

    def __getitem__(self, key):
        return self._check()[key]

    def __setitem__(self, key, value):
        self._check()[key] = value

    def __bool__(self):
        return bool(self._check())


class LockBox:
    """Holds one mutable value; grants access only inside synchronous
    ``with_`` sections.  asyncio's run-to-completion of sync code is the
    mutual exclusion (single event loop); this class enforces that the
    section really is synchronous and that the borrow does not escape."""

    __slots__ = ("_value",)

    def __init__(self, value: T):
        self._value = value

    def with_(self, fn: Callable[[T], Any]) -> Any:
        if asyncio.iscoroutinefunction(fn):
            raise TypeError("LockBox sections must be synchronous callables")
        if not __debug__:
            return fn(self._value)
        borrow = _Borrow(self._value)
        tok = _depth.set(_depth.get() + 1)
        try:
            out = fn(borrow)
        finally:
            _depth.reset(tok)
            object.__setattr__(borrow, "_lockbox_alive", False)
        if inspect.isawaitable(out) or inspect.isgenerator(out):
            raise TypeError(
                "LockBox section returned a suspendable object "
                f"({type(out).__name__}); the borrow must not cross awaits"
            )
        return out

    def replace(self, value: T) -> None:
        """Swap the guarded value (setup/teardown only, not a section)."""
        self._value = value


def in_section() -> bool:
    """True when the caller is (transitively) inside a LockBox section."""
    return _depth.get() > 0


def assert_outside_section(what: str) -> None:
    """Guard for await points: raise if erroneously inside a section."""
    if in_section():
        raise LockBoxViolation(
            f"{what} would suspend inside a LockBox synchronous section"
        )

"""Codecs folding an ``MVReg[VersionBytes]`` into one CRDT value and back.

The remote metadata gives each plugin one MVReg register holding opaque
versioned blobs (reference lib.rs:745-750).  When a plugin's blob is itself
a CRDT (e.g. the Keys CRDT), concurrent register values must be *decoded and
merged*, not tie-broken: version-check each blob, optionally transform
(decrypt), msgpack-decode, then CvRDT-merge all of them (reference
utils/mod.rs:37-126).  Writing back encodes the merged value under the
writer's add-context so it supersedes everything it saw (mod.rs:128-163).
"""

from __future__ import annotations

import inspect
import logging
from typing import Awaitable, Callable, Iterable

from . import codec
from .version_bytes import VersionBytes

logger = logging.getLogger("crdt_enc_tpu.mvreg_codec")


async def _maybe_await(x):
    if inspect.isawaitable(x):
        return await x
    return x


async def decode_version_bytes_mvreg(
    mvreg,
    supported_versions: Iterable[bytes],
    crdt_cls,
    transform: Callable[[VersionBytes], bytes | Awaitable[bytes]] | None = None,
    tolerate: tuple = (),
):
    """Fold all concurrent register values into one ``crdt_cls`` instance.

    ``transform`` maps the version-checked blob to cleartext msgpack (e.g.
    decrypt); default takes the content as-is.  Returns None if the register
    is empty.

    ``tolerate``: exception types from ``transform`` that skip just that
    value (e.g. a concurrent blob sealed to a recipient set this replica
    is not in).  If EVERY value fails, the first error propagates — an
    entirely unreadable register must stay loud.
    """
    values = mvreg.read().values
    if not values:
        return None
    merged = None
    first_err = None
    for obj in values:
        vb = VersionBytes.from_obj(obj).ensure_versions(supported_versions)
        try:
            raw = await _maybe_await(transform(vb)) if transform else vb.content
        except tolerate as e:
            # visible, not fatal: could be a stale concurrent writer — or a
            # forgery attempt by whoever controls the storage
            logger.warning("skipping unreadable register value: %s", e)
            if first_err is None:
                first_err = e
            continue
        value = crdt_cls.from_obj(codec.unpack(raw))
        if merged is None:
            merged = value
        else:
            merged.merge(value)
    if merged is None and first_err is not None:
        raise first_err
    return merged


async def encode_version_bytes_mvreg(
    mvreg,
    value,
    actor: bytes,
    version: bytes,
    transform: Callable[[bytes], bytes | Awaitable[bytes]] | None = None,
) -> None:
    """Write ``value`` (a CRDT) into the register, superseding every value
    the current read observes (derived add-context, mod.rs:128-163)."""
    raw = codec.pack(value.to_obj())
    if transform:
        raw = await _maybe_await(transform(raw))
    vb = VersionBytes(version, raw)
    mvreg.apply(mvreg.write_ctx(actor, vb.to_obj()))

"""Compat shim: the tracing core was promoted into the first-class
observability subsystem at :mod:`crdt_enc_tpu.obs.record` (ISSUE 2) —
timelines live in ``obs.timeline``, JAX runtime signals in
``obs.runtime``, the metrics sink in ``obs.sink``.

Every existing import site (``from crdt_enc_tpu.utils import trace``)
keeps working unchanged: this module replaces itself in ``sys.modules``
with the real registry module, so module-level state — including the
``trace.jax_annotations`` flag — is THE one registry, not a copy (a
re-export shim would silently fork mutable flags set through this name).
"""

import sys

from ..obs import record as _record

sys.modules[__name__] = _record

"""Structured per-phase tracing and counters.

The reference ships no observability at all (SURVEY.md §5: no tracing/log
crates anywhere; anyhow context strings are the only diagnostics).  The
rebuild's contract is per-phase timers around the compaction pipeline —
list/load/decrypt/decode/fold/write — plus counters for the BASELINE
metric (ops merged/sec), with optional ``jax.profiler`` trace annotations
so device-side kernel time lines up with host phases in a profile.

Design: one process-wide registry, monotonic wall-clock spans, plain
dicts under a lock (spans fire at file/batch granularity — hundreds per
compaction — so overhead is irrelevant next to I/O and crypto).  Spans
nest; a span records under its own flat name, so concurrent asyncio tasks
timing the same phase simply accumulate.

Usage::

    from crdt_enc_tpu.utils import trace

    with trace.span("ops.decrypt"):
        ...
    trace.add("ops_folded", len(batch))
    print(trace.report())     # human-readable table
    trace.snapshot()          # {"spans": {...}, "counters": {...}}

Logging: spans emit DEBUG records on the ``crdt_enc_tpu.trace`` logger;
enable with ``logging.getLogger("crdt_enc_tpu").setLevel(logging.DEBUG)``.

Event log: aggregated (count, seconds) slots cannot show *when* phases ran
relative to each other, which is exactly what auditing an overlapped
pipeline needs (did chunk k+1's ingest start before chunk k's fold
finished?).  ``enable_events()`` turns on a per-occurrence log — every span
exit also appends ``{"name", "t0", "t1", "meta"}`` with monotonic
``perf_counter`` timestamps comparable across threads — read it back with
``events()``.  Off by default (spans fire at batch granularity, but callers
like the streaming seam tests want zero surprise cost elsewhere).
"""

from __future__ import annotations

import logging
import sys
import threading
import time
from contextlib import contextmanager

logger = logging.getLogger("crdt_enc_tpu.trace")

# When True and jax is already imported, spans also open a
# jax.profiler.TraceAnnotation so they show up in device traces.
jax_annotations = False

_lock = threading.Lock()
_spans: dict[str, list] = {}  # name -> [count, total_seconds]
_counters: dict[str, int] = {}
_events_enabled = False
_events: list[dict] = []  # per-occurrence: {name, t0, t1, meta}


def enable_events(on: bool = True) -> None:
    """Toggle the per-occurrence event log (see module docs)."""
    global _events_enabled
    with _lock:
        _events_enabled = on


def events() -> list[dict]:
    """A consistent copy of the recorded span occurrences, in completion
    order.  Each entry: name, t0, t1 (``time.perf_counter`` seconds —
    monotonic, cross-thread comparable), meta (the span's ``meta`` arg)."""
    with _lock:
        return [dict(e) for e in _events]


@contextmanager
def span(name: str, meta=None):
    """Time a phase.  Re-entrant and concurrency-tolerant: every exit
    accumulates (count, seconds) under ``name``.  ``meta`` (e.g. a chunk
    index) is recorded only in the event log, never in the aggregate."""
    ann = None
    if jax_annotations and "jax" in sys.modules:
        import jax.profiler

        ann = jax.profiler.TraceAnnotation(name)
        ann.__enter__()
    t0 = time.perf_counter()
    try:
        yield
    finally:
        t1 = time.perf_counter()
        dt = t1 - t0
        if ann is not None:
            ann.__exit__(None, None, None)
        with _lock:
            slot = _spans.setdefault(name, [0, 0.0])
            slot[0] += 1
            slot[1] += dt
            if _events_enabled:
                _events.append({"name": name, "t0": t0, "t1": t1, "meta": meta})
        logger.debug("span %s: %.6fs", name, dt)


def add(name: str, n: int = 1) -> None:
    """Bump a counter (e.g. ops folded, states merged, bytes decrypted)."""
    with _lock:
        _counters[name] = _counters.get(name, 0) + n


def snapshot() -> dict:
    """A consistent copy: {"spans": {name: {"count", "seconds"}},
    "counters": {name: value}}."""
    with _lock:
        return {
            "spans": {
                k: {"count": c, "seconds": s} for k, (c, s) in _spans.items()
            },
            "counters": dict(_counters),
        }


def reset() -> None:
    with _lock:
        _spans.clear()
        _counters.clear()
        _events.clear()


def report() -> str:
    """Human-readable phase table, longest total first."""
    snap = snapshot()
    lines = []
    spans = sorted(
        snap["spans"].items(), key=lambda kv: kv[1]["seconds"], reverse=True
    )
    if spans:
        w = max(len(k) for k, _ in spans)
        for k, v in spans:
            lines.append(
                f"{k:<{w}}  {v['seconds']:>9.4f}s  x{v['count']}"
            )
    for k in sorted(snap["counters"]):
        lines.append(f"{k} = {snap['counters'][k]}")
    return "\n".join(lines) if lines else "(no spans recorded)"


def throughput(span_name: str, counter_name: str) -> float | None:
    """counter / span-seconds, or None if either is missing/zero."""
    snap = snapshot()
    s = snap["spans"].get(span_name)
    c = snap["counters"].get(counter_name)
    if not s or not c or s["seconds"] <= 0:
        return None
    return c / s["seconds"]

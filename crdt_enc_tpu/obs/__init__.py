"""First-class observability subsystem (ISSUE 2).

The reference ships zero observability (SURVEY.md §5); the rebuild's
BASELINE claims — pipeline overlap, byte-identical convergence, the
compaction speedup — are invisible without instrumentation.  This package
is the full layer on top of the span/counter registry PR 1 seeded:

* :mod:`.record`  — the process-wide registry: spans with bounded
  log-scale latency histograms (p50/p95/p99), counters, gauges, and a
  bounded per-occurrence event ring buffer with thread identity.
  ``crdt_enc_tpu.utils.trace`` is a compat shim onto this module.
* :mod:`.timeline` — Chrome-trace/Perfetto JSON export of the event log
  (per-thread lanes, chunk-index args, counter tracks) plus the chunk
  overlap analysis the streaming-pipeline acceptance tests assert on.
* :mod:`.runtime`  — JAX runtime signals: XLA recompile counting via
  ``jax.monitoring``, H2D transfer accounting, device memory gauges
  sampled at fold boundaries.
* :mod:`.sink`     — run-scoped JSONL metrics sink (``CRDT_OBS_SINK``,
  schema-stamped, size-rotated) and Prometheus text exposition with
  registry-derived ``# HELP``/``# TYPE``.
* :mod:`.replication` — per-device replication/convergence status
  (ISSUE 6): causal stability watermark, per-actor op backlog,
  divergence and checkpoint-staleness gauges, sampled by the core on
  every open/read_remote/compact.
* :mod:`.fleet`    — cross-device aggregation of sink files: fleet
  stable watermark, convergence-lag distribution, backlog quantiles,
  and the BENCH_LOCAL perf-trend table with regression flagging.
* :mod:`.live`     — the live telemetry plane (ISSUE 11): an embedded
  HTTP endpoint serving ``/metrics`` (Prometheus exposition from the
  LIVE registry), ``/healthz`` (per-remote watermark/backlog/cycle
  health) and ``/snapshot``; opt-in via ``CRDT_OBS_HTTP`` or
  ``FoldService(live_port=...)``, never on the hot path.
* :mod:`.attribution` — cycle attribution: stage marginals
  (decrypt/decode/h2d/fold/scatter/seal), overlap efficiency,
  critical-path stage, and the e2e-vs-fold-marginal **gap report**
  (``obs_report gap``).
* :mod:`.slo`      — freshness SLOs: staleness-lag-vs-watermark and
  per-tenant seal-latency targets, live ``repl_slo_*`` gauges, and
  window-based burn accounting over sink records (``obs_report slo``).

CLI: ``python -m crdt_enc_tpu.tools.obs_report`` renders phase tables,
exports timelines, diffs runs, aggregates fleets (``fleet``/``trend``),
attributes cycles (``gap``) and accounts SLO burn (``slo``).
Span/metric names are registered in ``docs/observability.md`` and
linted by ``tools/check_span_names.py``.
"""

from . import (
    attribution,
    fleet,
    live,
    record,
    replication,
    runtime,
    sink,
    slo,
    timeline,
)

__all__ = [
    "attribution", "fleet", "live", "record", "replication", "runtime",
    "sink", "slo", "timeline",
]

"""Freshness SLOs: targets, live gauges, and window-based burn accounting.

The stability watermark (``obs.replication``) answers "how stale would a
strong read be *right now*"; an SLO turns that into an operable promise:
"the union clock stays within TARGET versions of the watermark for
OBJECTIVE of samples".  That is exactly the strong-read precondition of
"Linearizable SMR of State-Based CRDTs without Logs" (arXiv 1905.08733)
made continuous — when the freshness SLO burns, the read tier ROADMAP
item 3 builds will be refusing (or delaying) linearizable reads, so burn
here is the measurement substrate that tier gates on.

Two specs ship:

* **freshness** — indicator ``divergence.watermark_lag`` from a
  replication status (total versions the union clock is ahead of the
  causal stability watermark); target ``CRDT_SLO_FRESHNESS_LAG``
  (default 64 versions).
* **seal_latency** — indicator: a tenant's end-to-end completion
  latency in a ``FoldService`` cycle (the serving p99's unit); target
  ``CRDT_SLO_SEAL_LATENCY_S`` (default 2.0 s).

Both carry an objective (``CRDT_SLO_OBJECTIVE``, default 0.99: at most
1% of samples may violate).  Live side: :func:`sample_freshness` runs
inside ``Core._sample_replication`` and publishes the ``repl_slo_*``
gauges (a comparison and two dict stores — nothing on the compaction
hot path); ``FoldService`` attaches per-cycle seal-latency burn to its
cycle sink record and the ``serve_slo_seal_burn`` gauge.  Post-hoc
side: :func:`burn_report` is a pure function over sink records —
samples bucket into fixed windows, each window's **burn rate** is its
violation fraction divided by the error budget (1 − objective), i.e.
burn > 1 means that window alone was eating budget faster than the
objective allows — rendered by ``obs_report slo``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from . import record

ENV_FRESHNESS = "CRDT_SLO_FRESHNESS_LAG"
ENV_SEAL = "CRDT_SLO_SEAL_LATENCY_S"
ENV_OBJECTIVE = "CRDT_SLO_OBJECTIVE"

DEFAULT_FRESHNESS_LAG = 64.0
DEFAULT_SEAL_LATENCY_S = 2.0
DEFAULT_OBJECTIVE = 0.99
DEFAULT_WINDOW_S = 300.0


@dataclass(frozen=True)
class SloSpec:
    """One objective: ``indicator <= target`` for at least ``objective``
    of samples.  ``name`` keys reports; ``indicator`` documents the
    measured value."""

    name: str
    indicator: str
    target: float
    objective: float = DEFAULT_OBJECTIVE

    @property
    def budget(self) -> float:
        """The error budget: the violation fraction the objective
        tolerates (floored so a 1.0 objective cannot zero-divide)."""
        return max(1.0 - self.objective, 1e-9)


def _env_float(var: str, default: float) -> float:
    raw = os.environ.get(var, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def _objective() -> float:
    obj = _env_float(ENV_OBJECTIVE, DEFAULT_OBJECTIVE)
    return obj if 0.0 < obj <= 1.0 else DEFAULT_OBJECTIVE


def freshness_spec() -> SloSpec:
    """Staleness-lag-vs-watermark target (env-tunable, module docs)."""
    return SloSpec(
        name="freshness",
        indicator="replication.divergence.watermark_lag (versions)",
        target=_env_float(ENV_FRESHNESS, DEFAULT_FRESHNESS_LAG),
        objective=_objective(),
    )


def seal_latency_spec() -> SloSpec:
    """Per-tenant seal-latency target for FoldService cycles."""
    return SloSpec(
        name="seal_latency",
        indicator="FoldService per-tenant completion latency (seconds)",
        target=_env_float(ENV_SEAL, DEFAULT_SEAL_LATENCY_S),
        objective=_objective(),
    )


def default_specs() -> list[SloSpec]:
    return [freshness_spec(), seal_latency_spec()]


# ------------------------------------------------------------- live side
def freshness_value(status: dict) -> float:
    """The freshness indicator of one replication status."""
    return float(status["divergence"]["watermark_lag"])


def sample_freshness(status: dict, spec: SloSpec | None = None) -> bool:
    """Publish the freshness-SLO gauges for one replication status —
    called by ``Core._sample_replication`` right after the ``repl_*``
    gauges.  Returns whether the sample met the target.  The target
    gauge rides along so a scraper can alert on
    ``repl_watermark_lag > repl_slo_freshness_target`` without
    duplicating config."""
    if spec is None:
        spec = freshness_spec()
    ok = freshness_value(status) <= spec.target
    record.gauge("repl_slo_freshness_ok", 1.0 if ok else 0.0)
    record.gauge("repl_slo_freshness_target", spec.target)
    return ok


def cycle_burn(results, spec: SloSpec | None = None) -> dict:
    """Seal-latency burn of ONE FoldService cycle: ``results`` are the
    cycle's TenantResult objects.  Sealed tenants' completion latencies
    compare against the target, and a tenant that ERRORED is a
    violation outright — a seal that never happened is infinitely late,
    so a total outage burns at the maximum rate instead of rendering as
    green (zero sealed = zero violations would be the lie).  Tenants
    legitimately skipped (quiet tenant with ``seal_empty`` off) are not
    attempts and stay out of the denominator.  The dict rides into the
    service's cycle sink record (and ``obs_report slo`` aggregates
    it)."""
    if spec is None:
        spec = seal_latency_spec()
    sealed = [r for r in results if getattr(r, "sealed", False)]
    errors = sum(
        1 for r in results if getattr(r, "error", None) is not None
    )
    violations = sum(1 for r in sealed if r.latency_s > spec.target) \
        + errors
    attempts = len(sealed) + errors
    return {
        "target_s": spec.target,
        "objective": spec.objective,
        "tenants": len(results),
        "sealed": len(sealed),
        "errors": errors,
        "attempts": attempts,
        "violations": violations,
        "burn_rate": round(
            (violations / attempts) / spec.budget, 4
        ) if attempts else 0.0,
    }


# --------------------------------------------------------- post-hoc side
def _samples_for(spec: SloSpec, records: list[dict]):
    """(ts, good, bad) sample tuples for one spec over sink records."""
    out = []
    for rec in records:
        ts = rec.get("ts")
        if ts is None:
            continue
        if spec.name == "freshness":
            rep = rec.get("replication")
            if isinstance(rep, dict):
                bad = int(freshness_value(rep) > spec.target)
                out.append((float(ts), 1 - bad, bad))
        elif spec.name == "seal_latency":
            meta = rec.get("meta") or {}
            cyc = meta.get("slo")
            if isinstance(cyc, dict) and "attempts" in cyc:
                # attempts = sealed + errored tenants (errors count as
                # violations — see cycle_burn)
                n, v = int(cyc["attempts"]), int(cyc["violations"])
                out.append((float(ts), n - v, v))
    return out


def burn_report(
    records: list[dict],
    specs: list[SloSpec] | None = None,
    window_s: float = DEFAULT_WINDOW_S,
) -> dict:
    """Window-based burn accounting over sink records (module docs).
    Pure and deterministic: windows are fixed ``window_s`` buckets
    anchored at each spec's earliest sample, burn is violation fraction
    ÷ error budget.  Records the spec has no sample in contribute
    nothing (a fleet that never ran a FoldService has no seal-latency
    series — that is reported as 0 samples, not as compliance)."""
    if specs is None:
        specs = default_specs()
    with record.span("slo.burn"):
        out = {"window_s": window_s, "specs": []}
        for spec in specs:
            samples = _samples_for(spec, records)
            entry = {
                "name": spec.name,
                "indicator": spec.indicator,
                "target": spec.target,
                "objective": spec.objective,
                "samples": sum(g + b for _, g, b in samples),
                "violations": sum(b for _, _, b in samples),
                "windows": [],
            }
            if samples:
                t0 = min(ts for ts, _, _ in samples)
                buckets: dict[int, list[int]] = {}
                for ts, g, b in samples:
                    slot = buckets.setdefault(
                        int((ts - t0) // window_s), [0, 0]
                    )
                    slot[0] += g
                    slot[1] += b
                for idx in sorted(buckets):
                    g, b = buckets[idx]
                    frac = b / (g + b) if (g + b) else 0.0
                    entry["windows"].append({
                        "window": idx,
                        "start_s": round(idx * window_s, 3),
                        "samples": g + b,
                        "violations": b,
                        "burn_rate": round(frac / spec.budget, 4),
                    })
                total = entry["samples"]
                frac = entry["violations"] / total if total else 0.0
                entry["bad_fraction"] = round(frac, 6)
                entry["budget_burn"] = round(frac / spec.budget, 4)
                entry["worst_window_burn"] = max(
                    (w["burn_rate"] for w in entry["windows"]), default=0.0
                )
            out["specs"].append(entry)
        return out


def format_burn(report: dict) -> str:
    """Deterministic human rendering of :func:`burn_report` output."""
    lines = [f"# SLO burn (window {report['window_s']:.0f}s)"]
    for spec in report["specs"]:
        lines.append(
            f"{spec['name']}: target <= {spec['target']:g} "
            f"objective {spec['objective']:g}  "
            f"samples={spec['samples']} violations={spec['violations']}"
        )
        if not spec["windows"]:
            lines.append("  (no samples)")
            continue
        lines.append(
            f"  budget burn {spec['budget_burn']:.2f}x  worst window "
            f"{spec['worst_window_burn']:.2f}x"
        )
        for w in spec["windows"]:
            lines.append(
                f"  window {w['window']:>3} (+{w['start_s']:.0f}s)  "
                f"samples={w['samples']}  violations={w['violations']}  "
                f"burn={w['burn_rate']:.2f}x"
            )
    return "\n".join(lines)

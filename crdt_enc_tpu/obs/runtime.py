"""JAX runtime signals: recompiles, H2D transfers, device memory.

The regressions ADVICE r5 caught by hand — an unbounded-recompile fold
loop, a silent fallback off the device path — are exactly the ones this
module makes mechanical:

* **Recompile counter** (:func:`track_recompiles`): every XLA backend
  compile bumps the ``jax_compiles`` counter and records its duration
  under the ``jax.compile`` span, via the public ``jax.monitoring``
  duration-event stream.  A steady-state fold loop whose ``jax_compiles``
  grows per iteration is recompiling — the bucket-padding contract is
  broken (tests/test_obs.py pins the counter constant across a
  varying-batch fold loop).
* **H2D accounting**: the streaming paths count ``h2d_bytes`` at each
  ``jax.device_put`` issue (ops/stream.py, parallel/session.py); transfer
  issue latency is the ``stream.h2d`` span histogram.
* **Device memory** (:func:`sample_device_memory`): ``bytes_in_use`` /
  ``peak_bytes_in_use`` gauges sampled at fold boundaries — the
  bounded-device-memory claim of the donated-plane streaming fold,
  observable.  A no-op on backends without allocator stats (CPU), probed
  once and cached.

Nothing here imports jax at module load: the registry stays importable in
jax-less tooling contexts, and the listeners attach only when asked.
"""

from __future__ import annotations

import threading

from . import record

_lock = threading.Lock()
_listener_installed = False
_recompiles_enabled = False
_recompiles_explicit = False  # an operator choice must stick

# The one duration event XLA emits exactly once per backend compilation
# (jaxpr tracing and MLIR lowering emit siblings; counting those would
# double-book a single cache miss).  NOTE: with a persistent compilation
# cache configured (CRDT_JIT_CACHE / enable_compilation_cache), jax
# emits this event around the compile-or-retrieve step, so a disk-cache
# RETRIEVAL also counts as a "compile" here — the cache_hits/cache_misses
# events below split the two: ``jax_cache_misses`` is the count of real
# XLA compiles, ``jax_cache_hits`` the count served from disk.
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"

_mem_supported: bool | None = None  # probed once; None = not yet probed


def _on_duration_event(event: str, duration: float, **kwargs) -> None:
    if _recompiles_enabled and event == _COMPILE_EVENT:
        record.add("jax_compiles", 1)
        record.observe("jax.compile", duration)


def _on_event(event: str, **kwargs) -> None:
    if not _recompiles_enabled:
        return
    if event == _CACHE_HIT_EVENT:
        record.add("jax_cache_hits", 1)
    elif event == _CACHE_MISS_EVENT:
        record.add("jax_cache_misses", 1)


def track_recompiles(on: bool = True) -> None:
    """Start (or stop) counting XLA backend compiles into the
    ``jax_compiles`` counter / ``jax.compile`` span.  Idempotent; the
    monitoring listener registers once per process and toggles via a
    flag (jax.monitoring offers no unregister).  Counts are process-wide
    and cleared by ``trace.reset()`` like every other counter.  An
    explicit call here is an OPERATOR choice — the accelerator's
    default-on wiring (:func:`ensure_recompile_tracking`) never
    overrides it."""
    global _recompiles_explicit
    with _lock:
        _recompiles_explicit = True
    _set_recompiles(on)


def ensure_recompile_tracking() -> None:
    """Default-on wiring (TpuAccelerator.__init__): enable tracking
    unless the operator already made an explicit track_recompiles()
    choice — constructing a second accelerator must not silently undo a
    deliberate opt-out."""
    with _lock:
        if _recompiles_explicit:
            return
    _set_recompiles(True)


def _set_recompiles(on: bool) -> None:
    global _listener_installed, _recompiles_enabled
    with _lock:
        _recompiles_enabled = on
        if on and not _listener_installed:
            import jax.monitoring

            jax.monitoring.register_event_duration_secs_listener(
                _on_duration_event
            )
            jax.monitoring.register_event_listener(_on_event)
            _listener_installed = True


def recompile_count() -> int:
    """The current ``jax_compiles`` counter (0 when tracking is off)."""
    return record.snapshot()["counters"].get("jax_compiles", 0)


def sample_device_memory(device=None) -> dict | None:
    """Record ``device_bytes_in_use`` / ``device_peak_bytes`` gauges from
    the backend allocator, returning the raw stats dict.  Returns None —
    and stays cheap, a cached boolean check — on backends without
    allocator stats (the CPU backend) or before jax is imported.

    The capability cache applies only to the DEFAULT device: an
    explicitly passed ``device`` is always probed (a stats-less default
    backend must not disable sampling of a capable one), and a transient
    exception never latches the cache — only a successful probe that
    reports no stats does."""
    global _mem_supported
    default_dev = device is None
    if default_dev and _mem_supported is False:
        return None
    import sys

    if "jax" not in sys.modules:
        return None
    import jax

    try:
        dev = device if device is not None else jax.local_devices()[0]
        stats = dev.memory_stats()
    except Exception:
        return None  # transient failure: do not latch the capability
    if not stats:
        if default_dev:
            _mem_supported = False
        return None
    if default_dev:
        _mem_supported = True
    if "bytes_in_use" in stats:
        record.gauge("device_bytes_in_use", int(stats["bytes_in_use"]))
    if "peak_bytes_in_use" in stats:
        record.gauge("device_peak_bytes", int(stats["peak_bytes_in_use"]))
    return stats

"""Run-scoped metrics sink: JSONL records + Prometheus text exposition.

A compaction's phase table dies with the process unless something writes
it down.  The sink appends ONE self-contained JSON line per labelled
snapshot — the same append-only, crash-tolerant shape as
``BENCH_LOCAL.jsonl`` — so a service operator (or the bench harness) can
diff runs, export timelines, and graph metrics after the fact:

    {"schema": 2, "label": "compact", "ts": <unix seconds>,
     "spans": {...}, "counters": {...}, "gauges": {...},
     "events": [...]?, "meta": {...}?, "replication": {...}?}

``schema`` stamps every record with the sink format version
(:data:`SCHEMA_VERSION`) so downstream consumers (``obs.fleet``,
``obs_report fleet/trend``) can reject records from a future format
loudly (:func:`check_schema`) instead of misparsing them; records
without the field are schema 1 (pre-replication).  ``events`` is
attached only when the event log is enabled and non-empty (timelines
are opt-in; aggregates are always cheap), and the ring buffer is
drained per write — each record carries its own run's timeline.
``replication`` is the per-device convergence status
(``obs.replication``) ``Core.compact`` attaches — the substrate the
fleet aggregator merges.

Wiring: set ``CRDT_OBS_SINK=/path/run.jsonl`` and every ``Core.compact``
(and every ``tools/fsck --obs`` run) appends a snapshot automatically
(:func:`maybe_write`); ``bench.py --e2e-streaming`` embeds the same
snapshot shape in its BENCH_LOCAL record; :func:`configure` sets the
sink programmatically.  ``python -m crdt_enc_tpu.tools.obs_report``
consumes the files.

Rotation: ``CRDT_OBS_SINK_MAX_MB`` (default off) bounds the sink file —
when an append would push it past the limit, the file rotates to
``<path>.1`` (one generation, the previous ``.1`` is dropped), so a
long-lived service cannot grow an unbounded log.

:func:`to_prometheus` renders a snapshot in the Prometheus text format:
every counter/gauge becomes its own metric family with ``# TYPE`` and a
``# HELP`` line taken from the registry descriptions in
``docs/observability.md`` (when the doc ships alongside the package);
span aggregates stay label-keyed families.  Pass ``timestamp=`` to
stamp every sample (millisecond epoch), e.g. with the record's ``ts``.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from pathlib import Path

from . import record

ENV_VAR = "CRDT_OBS_SINK"
ENV_MAX_MB = "CRDT_OBS_SINK_MAX_MB"

#: sink record format version.  2 added ``schema`` itself and the
#: ``replication`` payload; unstamped records are retroactively 1.
SCHEMA_VERSION = 2
SUPPORTED_SCHEMAS = (1, 2)

_configured: "MetricsSink | None | bool" = False  # False = not resolved yet


class SinkSchemaError(ValueError):
    """A record claims a sink schema this build cannot read."""


def _max_sink_bytes() -> int:
    """The rotation bound from ``CRDT_OBS_SINK_MAX_MB`` (0 = off).
    Re-read per write, like the sink path itself."""
    raw = os.environ.get(ENV_MAX_MB, "")
    try:
        mb = float(raw) if raw else 0.0
    except ValueError:
        return 0
    return int(mb * 1e6) if mb > 0 else 0


#: serializes the size-check → rotate → append sequence across threads
#: (a service's per-tenant seals write concurrently): without it two
#: writers could both rotate, dropping a generation, or interleave the
#: check with another's append and overshoot the bound.
_io_lock = threading.Lock()


class MetricsSink:
    """Append-only JSONL sink for labelled registry snapshots."""

    def __init__(self, path: str):
        self.path = path

    def write(self, label: str, *, snapshot: dict | None = None,
              events: list | None = None, meta: dict | None = None,
              replication: dict | None = None) -> dict:
        """Append one record; returns it.  ``snapshot`` defaults to the
        live registry.  ``events`` defaults to DRAINING the live event
        log when recording is enabled — each record carries only the
        timeline since the previous write, so a long-lived service with
        events on does not re-serialize a growing (up to ring-capacity)
        log into every record.  Never raises on I/O failure —
        bookkeeping must not kill a good run (same contract as
        BENCH_LOCAL.jsonl)."""
        snap = record.snapshot() if snapshot is None else snapshot
        rec = {
            "schema": SCHEMA_VERSION,
            "label": label,
            "ts": round(time.time(), 3),
            **snap,
        }
        if events is None:
            evs = record.drain_events() if record.events_enabled() else []
        else:
            evs = events
        if evs:
            rec["events"] = evs
        if meta:
            rec["meta"] = meta
        if replication:
            rec["replication"] = replication
        try:
            line = json.dumps(rec)
            with _io_lock:
                limit = _max_sink_bytes()
                if limit:
                    try:
                        if os.path.getsize(self.path) + len(line) + 1 \
                                > limit:
                            os.replace(self.path, self.path + ".1")
                    except OSError:
                        pass  # no file yet — first append creates it
                with open(self.path, "a") as f:
                    f.write(line + "\n")
        except (OSError, TypeError, ValueError):
            pass
        return rec


def configure(path: str | None) -> "MetricsSink | None":
    """Set (or with None, clear) the process-default sink, overriding the
    ``CRDT_OBS_SINK`` environment variable."""
    global _configured
    _configured = MetricsSink(path) if path else None
    return _configured


def default_sink() -> "MetricsSink | None":
    """The configured sink, else one from ``CRDT_OBS_SINK``, else None.
    The env var is re-read per call so tests (and long-lived services
    re-exec'd with new env) see changes."""
    if _configured is not False:
        return _configured
    path = os.environ.get(ENV_VAR)
    return MetricsSink(path) if path else None


def maybe_write(label: str, meta: dict | None = None,
                replication: dict | None = None) -> dict | None:
    """Append a snapshot to the default sink if one is configured —
    the zero-cost-when-unconfigured hook Core.compact and the tools
    call."""
    sink = default_sink()
    if sink is None:
        return None
    return sink.write(label, meta=meta, replication=replication)


# ------------------------------------------------------------- read side
def read_records(path: str) -> list[dict]:
    """Parse one JSONL file (sink output or BENCH_LOCAL.jsonl) into its
    record dicts, tolerating blank lines and a truncated final append
    from a killed run.  The single reader every consumer (obs_report,
    obs.fleet) shares — the file format has one parse."""
    records = []
    with open(path) as f:
        for ln in f:
            ln = ln.strip()
            if not ln:
                continue
            try:
                rec = json.loads(ln)
            except ValueError:
                continue  # truncated final append from a killed run
            if isinstance(rec, dict):
                records.append(rec)
    return records


def check_schema(records: list[dict], source: str = "<records>") -> None:
    """Reject records stamped with a sink schema this build cannot read
    — loudly, naming the source and record, instead of misparsing a
    future format.  Records without a ``schema`` field are schema 1
    (pre-stamp sink records, BENCH_LOCAL bench records)."""
    for i, rec in enumerate(records, 1):
        s = rec.get("schema", 1)
        # bool is an int subclass and True == 1 — reject it explicitly
        # or a {"schema": true} stamp would silently read as schema 1
        if isinstance(s, bool) or not isinstance(s, int) \
                or s not in SUPPORTED_SCHEMAS:
            raise SinkSchemaError(
                f"{source}: record {i} has sink schema {s!r}; this build "
                f"reads schemas {list(SUPPORTED_SCHEMAS)} — refusing to "
                "misparse a mixed/newer-format input"
            )


# ----------------------------------------------------------- prometheus
_help_cache: dict[str, str] | None = None

_DOC_REL = Path("docs") / "observability.md"
_ROW_RE = re.compile(r"^\|\s*`([^`]+)`\s*\|\s*(?:[^|]*\|)?\s*([^|]+)\|?\s*$")


def registry_help() -> dict[str, str]:
    """name → description from the ``docs/observability.md`` registry
    tables (the SAME tables SPN001 lints call sites against), for
    ``# HELP`` lines.  Empty when the doc is not shipped alongside the
    package (installed wheel) — exposition then degrades to generic
    help text, never fails."""
    global _help_cache
    if _help_cache is not None:
        return _help_cache
    doc = Path(__file__).resolve().parents[2] / _DOC_REL
    out: dict[str, str] = {}
    try:
        text = doc.read_text()
    except OSError:
        _help_cache = out
        return out
    for line in text.splitlines():
        m = _ROW_RE.match(line)
        if not m or m.group(1) in ("span", "name"):
            continue
        # raw text here; escaping for the exposition format happens at
        # render time (_escape_help) so it applies uniformly to registry
        # and fallback help strings alike
        desc = m.group(2).strip().replace("`", "")
        if desc:
            out.setdefault(m.group(1), desc)
    _help_cache = out
    return out


def _metric_name(prefix: str, name: str) -> str:
    return f"{prefix}_" + re.sub(r"[^a-zA-Z0-9_:]", "_", name)


# Prometheus text-format escaping (the exposition spec): label VALUES
# escape backslash, double-quote and newline; HELP text escapes
# backslash and newline.  Metric names need none (sanitized above), but
# span names ride as label values and are dotted free text.
def _escape_label(value: str) -> str:
    return (
        value.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")
    )


def _escape_help(text: str) -> str:
    return text.replace("\\", r"\\").replace("\n", r"\n")


def to_prometheus(snap: dict | None = None, prefix: str = "crdt",
                  timestamp: float | None = None) -> str:
    """Render one snapshot in the Prometheus text exposition format.

    Counters expose as ``<prefix>_<name>_total`` counter families and
    gauges as ``<prefix>_<name>`` gauge families — one family per
    registered name, each with ``# TYPE`` and a ``# HELP`` taken from
    the registry descriptions (:func:`registry_help`).  Span aggregates
    stay label-keyed (``span="..."``) because span names are dotted and
    the set is wide: totals/counts as counters, quantiles as a summary.
    ``timestamp`` (epoch seconds) stamps every sample in milliseconds.
    """
    if snap is None:
        snap = record.snapshot()
    ts = "" if timestamp is None else f" {int(timestamp * 1000)}"
    help_ = registry_help()
    lines: list[str] = []
    if snap.get("spans"):
        lines += [
            f"# HELP {prefix}_span_seconds_total total seconds per span",
            f"# TYPE {prefix}_span_seconds_total counter",
            f"# HELP {prefix}_span_count_total occurrences per span",
            f"# TYPE {prefix}_span_count_total counter",
            f"# HELP {prefix}_span_seconds span latency quantiles",
            f"# TYPE {prefix}_span_seconds summary",
        ]
    for name, v in sorted(snap.get("spans", {}).items()):
        lab = f'{{span="{_escape_label(name)}"}}'
        lines.append(
            f"{prefix}_span_seconds_total{lab} {v['seconds']:.6f}{ts}"
        )
        lines.append(f"{prefix}_span_count_total{lab} {v['count']}{ts}")
        for q in ("p50", "p95", "p99"):
            ms = v.get(f"{q}_ms")
            if ms is not None:
                lines.append(
                    f'{prefix}_span_seconds{{span="{_escape_label(name)}"'
                    f',quantile="0.{q[1:]}"}} {ms / 1e3:.6f}{ts}'
                )
    for name, v in sorted(snap.get("counters", {}).items()):
        fam = _metric_name(prefix, name)
        if not fam.endswith("_total"):
            fam += "_total"
        h = _escape_help(help_.get(name, f"counter {name}"))
        lines.append(f"# HELP {fam} {h}")
        lines.append(f"# TYPE {fam} counter")
        lines.append(f"{fam} {v}{ts}")
    for name, v in sorted(snap.get("gauges", {}).items()):
        fam = _metric_name(prefix, name)
        h = _escape_help(help_.get(name, f"gauge {name}"))
        lines.append(f"# HELP {fam} {h}")
        lines.append(f"# TYPE {fam} gauge")
        lines.append(f"{fam} {v}{ts}")
    return "\n".join(lines) + "\n"

"""Run-scoped metrics sink: JSONL records + Prometheus text exposition.

A compaction's phase table dies with the process unless something writes
it down.  The sink appends ONE self-contained JSON line per labelled
snapshot — the same append-only, crash-tolerant shape as
``BENCH_LOCAL.jsonl`` — so a service operator (or the bench harness) can
diff runs, export timelines, and graph metrics after the fact:

    {"label": "compact", "ts": <unix seconds>, "spans": {...},
     "counters": {...}, "gauges": {...}, "events": [...]?, "meta": {...}?}

``events`` is attached only when the event log is enabled and non-empty
(timelines are opt-in; aggregates are always cheap), and the ring buffer
is drained per write — each record carries its own run's timeline.

Wiring: set ``CRDT_OBS_SINK=/path/run.jsonl`` and every ``Core.compact``
(and every ``tools/fsck --obs`` run) appends a snapshot automatically
(:func:`maybe_write`);
``bench.py --e2e-streaming`` embeds the same snapshot shape in its
BENCH_LOCAL record; :func:`configure` sets the sink programmatically.
``python -m crdt_enc_tpu.tools.obs_report`` consumes the files.

:func:`to_prometheus` renders a snapshot in the Prometheus text format
(counters as ``_total``, span totals/quantiles and gauges as gauges) for
scrape endpoints or textfile collectors.
"""

from __future__ import annotations

import json
import os
import time

from . import record

ENV_VAR = "CRDT_OBS_SINK"

_configured: "MetricsSink | None | bool" = False  # False = not resolved yet


class MetricsSink:
    """Append-only JSONL sink for labelled registry snapshots."""

    def __init__(self, path: str):
        self.path = path

    def write(self, label: str, *, snapshot: dict | None = None,
              events: list | None = None, meta: dict | None = None) -> dict:
        """Append one record; returns it.  ``snapshot`` defaults to the
        live registry.  ``events`` defaults to DRAINING the live event
        log when recording is enabled — each record carries only the
        timeline since the previous write, so a long-lived service with
        events on does not re-serialize a growing (up to ring-capacity)
        log into every record.  Never raises on I/O failure —
        bookkeeping must not kill a good run (same contract as
        BENCH_LOCAL.jsonl)."""
        snap = record.snapshot() if snapshot is None else snapshot
        rec = {"label": label, "ts": round(time.time(), 3), **snap}
        if events is None:
            evs = record.drain_events() if record.events_enabled() else []
        else:
            evs = events
        if evs:
            rec["events"] = evs
        if meta:
            rec["meta"] = meta
        try:
            line = json.dumps(rec)
            with open(self.path, "a") as f:
                f.write(line + "\n")
        except (OSError, TypeError, ValueError):
            pass
        return rec


def configure(path: str | None) -> "MetricsSink | None":
    """Set (or with None, clear) the process-default sink, overriding the
    ``CRDT_OBS_SINK`` environment variable."""
    global _configured
    _configured = MetricsSink(path) if path else None
    return _configured


def default_sink() -> "MetricsSink | None":
    """The configured sink, else one from ``CRDT_OBS_SINK``, else None.
    The env var is re-read per call so tests (and long-lived services
    re-exec'd with new env) see changes."""
    if _configured is not False:
        return _configured
    path = os.environ.get(ENV_VAR)
    return MetricsSink(path) if path else None


def maybe_write(label: str, meta: dict | None = None) -> dict | None:
    """Append a snapshot to the default sink if one is configured —
    the zero-cost-when-unconfigured hook Core.compact and the tools
    call."""
    sink = default_sink()
    if sink is None:
        return None
    return sink.write(label, meta=meta)


def to_prometheus(snap: dict | None = None, prefix: str = "crdt") -> str:
    """Render one snapshot in the Prometheus text exposition format."""
    if snap is None:
        snap = record.snapshot()
    lines = [
        f"# TYPE {prefix}_span_seconds_total counter",
        f"# TYPE {prefix}_span_count_total counter",
        f"# TYPE {prefix}_counter_total counter",
        f"# TYPE {prefix}_gauge gauge",
    ]
    for name, v in sorted(snap.get("spans", {}).items()):
        lab = f'{{span="{name}"}}'
        lines.append(f"{prefix}_span_seconds_total{lab} {v['seconds']:.6f}")
        lines.append(f"{prefix}_span_count_total{lab} {v['count']}")
        for q in ("p50", "p95", "p99"):
            ms = v.get(f"{q}_ms")
            if ms is not None:
                lines.append(
                    f'{prefix}_span_seconds{{span="{name}",quantile='
                    f'"0.{q[1:]}"}} {ms / 1e3:.6f}'
                )
    for name, v in sorted(snap.get("counters", {}).items()):
        lines.append(f'{prefix}_counter_total{{name="{name}"}} {v}')
    for name, v in sorted(snap.get("gauges", {}).items()):
        lines.append(f'{prefix}_gauge{{name="{name}"}} {v}')
    return "\n".join(lines) + "\n"

"""Replication & convergence observability: the per-device status math.

Every signal PR 2 added dies at the process boundary — nothing could
answer "how far behind is this device?", "has the fleet converged?", or
"how stale would a strong read be?".  This module computes, from data
the core already tracks (its ingest cursor ``next_op_versions``, the
remote op listing, and the **cursor matrix** of other replicas' published
ingest cursors — each compacted snapshot carries its sealer's cursor, so
reading a snapshot is learning a replica's progress), the per-device
replication status:

* **causal stability watermark** — the vector-clock frontier EVERY known
  replica has provably reached: ``watermark[a] = min over replicas r of
  cursor_r[a]``.  Ops at or below the watermark are causally stable
  with respect to the KNOWN membership (no replica this one has heard
  of — published cursor or produced ops — can still be missing them);
  a never-heard-from pure consumer is invisible to any
  observation-only frontier, so the strong-read tier of
  "Linearizable SMR of State-Based CRDTs without Logs"
  (arXiv:1905.08733) must additionally pin membership.  A
  replica with no published cursor contributes only its *implied
  self-knowledge* (it has certainly seen its own sealed ops), so one
  silent replica collapses the watermark for every other actor's entries
  — silence is indistinguishable from lag, and the math says so.
* **per-actor op backlog** — sealed-but-unfolded op files past the local
  cursor, in files and bytes (from ``Storage.stat_ops``, which sizes the
  tail without reading it).
* **divergence** — the local clock vs. the union of everything known to
  exist (remote listing ∪ published cursors): actors behind, total
  version lag, and the watermark's lag behind the union.
* **checkpoint staleness** — versions folded since the last sealed
  warm-open checkpoint (how much a crash right now would have to refold).

:func:`compute_status` is a pure function (exactly unit-testable);
``Core.replication_status()`` gathers the inputs and calls it, and
:func:`sample` publishes the scalar summary into registered gauges on
every ``open`` / ``read_remote`` / ``compact`` (opt out with
``CRDT_REPL_SAMPLE=0``).  The full status rides into the metrics sink on
every compaction (``"replication"`` key, sink schema 2) — the substrate
``obs.fleet`` aggregates across devices.

All actor ids in the returned status are lowercase hex strings and every
collection is sorted, so ``json.dumps(status, sort_keys=True)`` is
byte-stable for a given replica state — the differential tests assert
exact output, not shapes.
"""

from __future__ import annotations

from ..models.vclock import Actor, VClock
from . import record


def _hex_clock(clock: VClock) -> dict[str, int]:
    return {a.hex(): c for a, c in sorted(clock.counters.items()) if c > 0}


def stability_watermark(
    actor_id: Actor,
    local_clock: VClock,
    cursor_matrix: dict[Actor, VClock],
    union: VClock,
    replicas=None,
) -> dict[Actor, int]:
    """The causal stability watermark: pointwise min over every known
    replica's cursor (module docs) — factored out of
    :func:`compute_status` so the delta-replication layer can tag each
    sealed delta with the sealer's watermark (docs/delta.md) without
    paying the full status probe.  ``union`` is everything known to
    exist; by default replicas are this one, every published cursor,
    and every actor that ever produced ops.  The strong-read tier
    passes an explicit ``replicas`` denominator instead — its
    membership policy may pin an expected set or quarantine silent
    replicas out of the min (crdt_enc_tpu/read/policy.py); the math
    here stays one implementation either way."""
    if replicas is None:
        replicas = set(cursor_matrix) | set(union.counters) | {actor_id}
    watermark: dict[Actor, int] = {}
    for a in union.counters:
        lo = None
        for r in replicas:
            if r == actor_id:
                k = local_clock.get(a)
            else:
                published = cursor_matrix.get(r)
                k = published.get(a) if published is not None else 0
            if r == a:
                # implied self-knowledge: a replica has certainly seen
                # its own sealed ops, published cursor or not
                k = max(k, union.get(a))
            lo = k if lo is None else min(lo, k)
        if lo:
            watermark[a] = lo
    return watermark


def compute_status(
    actor_id: Actor,
    local_clock: VClock,
    cursor_matrix: dict[Actor, VClock],
    backlog_stats: list[tuple[Actor, int, int]],
    remote_id: bytes,
    checkpoint_cursor: dict[Actor, int] | None,
    checkpoint_enabled: bool,
) -> dict:
    """The replication status dict (see module docs).

    ``backlog_stats`` is ``Storage.stat_ops`` output for versions past
    the local cursor: ``(actor, version, nbytes)`` in version order per
    actor.  ``cursor_matrix`` maps OTHER replicas' actor ids to their
    last published ingest cursor; the local replica's live cursor is
    ``local_clock``.  ``checkpoint_cursor`` is the cursor of the last
    durably sealed checkpoint (None when none was sealed)."""
    # union of everything known to exist: local history ∪ the sealed tail
    # past it ∪ every published cursor (a cursor claims the ops it counts)
    union = local_clock.copy()
    per_actor: dict[Actor, list[int]] = {}
    backlog_files = backlog_bytes = 0
    for actor, version, nbytes in backlog_stats:
        if version > union.get(actor):
            union.counters[actor] = version
        slot = per_actor.setdefault(actor, [0, 0])
        slot[0] += 1
        slot[1] += int(nbytes)
        backlog_files += 1
        backlog_bytes += int(nbytes)
    for clock in cursor_matrix.values():
        union.merge(clock)

    # stability watermark: pointwise min over every known replica's
    # cursor.  Replicas = this one, every published cursor, and every
    # actor that ever produced ops (producers are replicas by
    # construction — op files are written under the writer's actor id).
    replicas = set(cursor_matrix) | set(union.counters) | {actor_id}
    watermark = stability_watermark(actor_id, local_clock, cursor_matrix, union)

    actors_behind = sum(
        1 for a, c in union.counters.items() if c > local_clock.get(a)
    )
    version_lag = sum(
        c - local_clock.get(a) for a, c in union.counters.items()
        if c > local_clock.get(a)
    )
    watermark_lag = sum(
        c - watermark.get(a, 0) for a, c in union.counters.items()
    )

    sealed = checkpoint_cursor is not None
    base = checkpoint_cursor or {}
    staleness = sum(
        c - base.get(a, 0)
        for a, c in local_clock.counters.items()
        if c > base.get(a, 0)
    )

    return {
        "actor": actor_id.hex(),
        "remote_id": remote_id.hex(),
        "local_clock": _hex_clock(local_clock),
        "union_clock": _hex_clock(union),
        "watermark": {a.hex(): c for a, c in sorted(watermark.items())},
        "matrix": {
            r.hex(): _hex_clock(clock)
            for r, clock in sorted(cursor_matrix.items())
        },
        "backlog": {
            "files": backlog_files,
            "bytes": backlog_bytes,
            "per_actor": {
                a.hex(): {"files": f, "bytes": b}
                for a, (f, b) in sorted(per_actor.items())
            },
        },
        "divergence": {
            "actors_behind": actors_behind,
            "version_lag": version_lag,
            "watermark_lag": watermark_lag,
            "known_replicas": len(replicas),
        },
        "checkpoint": {
            "enabled": bool(checkpoint_enabled),
            "sealed": sealed,
            "staleness_versions": staleness,
        },
    }


def sample(status: dict) -> None:
    """Publish one status' scalar summary into the registered gauges —
    the names `docs/observability.md` registers and SPN001 lints."""
    record.gauge("repl_backlog_files", status["backlog"]["files"])
    record.gauge("repl_backlog_bytes", status["backlog"]["bytes"])
    record.gauge("repl_actors_behind", status["divergence"]["actors_behind"])
    record.gauge("repl_version_lag", status["divergence"]["version_lag"])
    record.gauge("repl_watermark_lag", status["divergence"]["watermark_lag"])
    record.gauge(
        "repl_known_replicas", status["divergence"]["known_replicas"]
    )
    record.gauge(
        "checkpoint_staleness_versions",
        status["checkpoint"]["staleness_versions"],
    )
    record.add("repl_samples", 1)

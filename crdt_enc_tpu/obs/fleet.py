"""Fleet-level observability: merge per-device sink files into one view.

One device's replication status (``obs.replication``) answers "how far
behind am *I*?".  Operating a fleet needs the cross-device questions:
has the whole fleet converged, what is the lag *distribution*, which
device is the straggler, and is throughput regressing over time?  This
module answers them from the JSONL files the metrics sink already
writes — no new wire protocol, no coordination; ship the sink files to
one place (they are append-only and schema-stamped) and aggregate:

* :func:`device_summaries` — one summary per device file: the NEWEST
  record carrying a ``"replication"`` payload (sink schema ≥ 2), after
  :func:`obs.sink.check_schema` has rejected unreadable schemas loudly.
* :func:`fleet_report` — devices grouped by the remote they replicate
  (``remote_id`` — the hash of the converged remote metadata, so two
  devices on different remotes never average together): the **fleet
  stable watermark** (pointwise min over devices' local clocks — the
  frontier every *reporting* device has folded), per-device convergence
  lag against the fleet union clock with a min/p50/p99/max
  distribution, and backlog p50/p99 in files and bytes.
* :func:`bench_trend` / :func:`trend_regressions` — the perf trajectory
  per bench config from ``BENCH_LOCAL.jsonl``: every run of the same
  (metric, backend, shape) in file order, latest vs. the best earlier
  run, and the configs whose latest run regressed more than a threshold
  — the ``obs_report trend --fail-on-regression`` CI gate.

Everything is deterministic for a given input (sorted remotes, devices,
configs; no wall-clock reads), so ``obs_report fleet`` output can be
golden-tested and diffed across runs.
"""

from __future__ import annotations

import json
import math

from . import sink, slo


class FleetInputError(ValueError):
    """A device file cannot contribute to a fleet report."""


# ------------------------------------------------------------- devices
def device_summaries(paths: list[str]) -> list[dict]:
    """One summary per device sink file: the newest replication-bearing
    record.  Raises :class:`FleetInputError` when a file has none (the
    device ran with replication sampling off, or the file predates sink
    schema 2) and :class:`obs.sink.SinkSchemaError` on unreadable
    schemas — loudly, instead of silently averaging a partial fleet."""
    out = []
    for path in paths:
        records = sink.read_records(path)
        sink.check_schema(records, source=path)
        rep = ts = None
        counters: dict = {}
        gauges: dict = {}
        wm_change_ts = None  # record ts when the watermark last CHANGED
        prev_wm = None
        for rec in records:
            if isinstance(rec.get("replication"), dict):
                rep, ts = rec["replication"], rec.get("ts")
                counters = rec.get("counters") or {}
                gauges = rec.get("gauges") or {}
                wm = rep.get("watermark")
                if prev_wm is None or wm != prev_wm:
                    wm_change_ts = ts
                    prev_wm = wm
        if rep is None:
            raise FleetInputError(
                f"{path}: no record carries a replication status — the "
                "device must run with replication sampling on (sink "
                "schema >= 2, CRDT_REPL_SAMPLE unset or 1) to join a "
                "fleet report"
            )
        out.append({
            "path": path, "ts": ts, "replication": rep,
            # the same record's registry snapshot, for the quarantine
            # column: ingest_quarantined (damaged synced files, cursor
            # held) and daemon_quarantined (tenants the fleet daemon
            # has parked, serve/daemon.py)
            "counters": counters, "gauges": gauges,
            # watermark AGE, derived purely from sink record timestamps
            # (deterministic — the newest sample's ts anchors "now"):
            # how long the device kept sampling without its stability
            # watermark moving.  A wedged watermark is a growing
            # duration an operator can see without reading gauges.
            "watermark_age_s": (
                round(max(0.0, float(ts) - float(wm_change_ts)), 3)
                if ts is not None and wm_change_ts is not None
                else None
            ),
        })
    return out


def _q(vals: list, q: float):
    """Nearest-rank quantile: the ceil(q·n)-th smallest value."""
    s = sorted(vals)
    rank = max(1, math.ceil(q * len(s)))
    return s[min(rank, len(s)) - 1]


def fleet_report(summaries: list[dict]) -> dict:
    """Aggregate device summaries into the per-remote fleet view (see
    module docs).  Two summaries for the same actor on the same remote
    keep the newer one (by record ``ts``) — re-shipped files are not a
    second device."""
    latest: dict[tuple[str, str], dict] = {}
    for s in summaries:
        rep = s["replication"]
        key = (rep["remote_id"], rep["actor"])
        old = latest.get(key)
        if old is None or (s["ts"] or 0) >= (old["ts"] or 0):
            latest[key] = s

    by_remote: dict[str, list[dict]] = {}
    for (remote_id, _actor), s in sorted(latest.items()):
        by_remote.setdefault(remote_id, []).append(s)

    remotes = []
    for remote_id, devs in sorted(by_remote.items()):
        union: dict[str, int] = {}
        for s in devs:
            for a, c in s["replication"]["union_clock"].items():
                if c > union.get(a, 0):
                    union[a] = c
        watermark = {}
        for a in union:
            lo = min(
                s["replication"]["local_clock"].get(a, 0) for s in devs
            )
            if lo:
                watermark[a] = lo
        freshness = slo.freshness_spec()
        devices = []
        for s in devs:
            rep = s["replication"]
            local = rep["local_clock"]
            lag = sum(c - local.get(a, 0) for a, c in union.items())
            devices.append({
                "actor": rep["actor"],
                "lag": lag,
                "backlog_files": rep["backlog"]["files"],
                "backlog_bytes": rep["backlog"]["bytes"],
                "watermark_lag": rep["divergence"]["watermark_lag"],
                # quarantine column: damaged synced files this device
                # skipped with the cursor held (ingest_quarantined),
                # plus tenants its fleet daemon currently parks
                # (daemon_quarantined gauge, serve/daemon.py)
                "quarantined_files": int(
                    (s.get("counters") or {}).get("ingest_quarantined", 0)
                ),
                "daemon_quarantined": int(
                    (s.get("gauges") or {}).get("daemon_quarantined", 0)
                ),
                # freshness-SLO verdict at the device's last sample:
                # watermark lag within the active target (obs.slo)
                "slo_ok": rep["divergence"]["watermark_lag"]
                <= freshness.target,
                "watermark_age_s": s.get("watermark_age_s"),
                # strong-read membership policy surfacing (present only
                # when the device runs one): replicas quarantined out
                # of the watermark denominator (docs/strong_reads.md)
                "membership_excluded": len(
                    (rep.get("membership") or {}).get("excluded") or []
                ),
            })
        lags = [d["lag"] for d in devices]
        bfiles = [d["backlog_files"] for d in devices]
        bbytes = [d["backlog_bytes"] for d in devices]
        remotes.append({
            "remote_id": remote_id,
            "devices": devices,
            "converged": all(v == 0 for v in lags),
            "slo": {
                "freshness_target": freshness.target,
                "devices_ok": sum(1 for d in devices if d["slo_ok"]),
                "devices_burning": sum(
                    1 for d in devices if not d["slo_ok"]
                ),
            },
            "stable_watermark": dict(sorted(watermark.items())),
            "union_clock": dict(sorted(union.items())),
            "lag": {
                "min": min(lags), "p50": _q(lags, 0.50),
                "p99": _q(lags, 0.99), "max": max(lags),
            },
            "backlog_files": {"p50": _q(bfiles, 0.50), "p99": _q(bfiles, 0.99)},
            "backlog_bytes": {"p50": _q(bbytes, 0.50), "p99": _q(bbytes, 0.99)},
        })
    return {"n_devices": len(latest), "remotes": remotes}


def format_fleet(report: dict) -> str:
    """Deterministic human rendering of :func:`fleet_report` output —
    the shape the committed golden (tests/data/obs_fleet_golden.txt)
    pins."""
    lines = [
        f"# fleet: {report['n_devices']} device(s), "
        f"{len(report['remotes'])} remote(s)"
    ]
    for r in report["remotes"]:
        conv = "yes" if r["converged"] else "no"
        lines.append(
            f"remote {r['remote_id']}  devices={len(r['devices'])}  "
            f"converged={conv}"
        )
        wm = r["stable_watermark"]
        total = sum(wm.values())
        lines.append(
            f"  stable watermark: {len(wm)} actor(s), {total} version(s)"
        )
        for a, c in wm.items():
            lines.append(f"    {a} = {c}")
        lag = r["lag"]
        lines.append(
            f"  lag vs fleet union: min={lag['min']} p50={lag['p50']} "
            f"p99={lag['p99']} max={lag['max']}"
        )
        bf, bb = r["backlog_files"], r["backlog_bytes"]
        lines.append(
            f"  backlog files p50={bf['p50']} p99={bf['p99']}  "
            f"bytes p50={bb['p50']} p99={bb['p99']}"
        )
        s = r["slo"]
        lines.append(
            f"  slo freshness (lag<={s['freshness_target']:g}): "
            f"{s['devices_ok']} ok, {s['devices_burning']} burning"
        )
        for d in r["devices"]:
            quar = d.get("quarantined_files", 0)
            dq = d.get("daemon_quarantined", 0)
            quar_s = f"quar={quar}" + (f"+{dq}t" if dq else "")
            age = d.get("watermark_age_s")
            age_s = f"  wm_age={age:g}s" if age is not None else ""
            excl = d.get("membership_excluded") or 0
            excl_s = f"  excl={excl}" if excl else ""
            lines.append(
                f"  device {d['actor']}  lag={d['lag']}  "
                f"backlog_files={d['backlog_files']}  "
                f"backlog_bytes={d['backlog_bytes']}  "
                f"{quar_s}  "
                f"slo={'ok' if d['slo_ok'] else 'BURN'}"
                f"{age_s}{excl_s}"
            )
    return "\n".join(lines)


# --------------------------------------------------------------- trend
def bench_trend(records: list[dict], metric: str | None = None) -> list[dict]:
    """Per-config perf trajectory from BENCH_LOCAL.jsonl records (file
    order = time order; bench appends).  A config is one (metric,
    backend, shape) triple; records without metric/value (e.g. sink
    records mixed into the file) are skipped, but unknown sink schemas
    still fail loudly via :func:`obs.sink.check_schema` first."""
    configs: dict[tuple, dict] = {}
    for rec in records:
        if "metric" not in rec or "value" not in rec:
            continue
        if metric is not None and rec["metric"] != metric:
            continue
        # shapeless records (the sim bench) fall back to their config
        # string — without it, e.g. a 4r×50s and an 8r×250s sim run
        # would collapse into ONE trajectory and the regression gate
        # would compare apples to oranges
        shape_obj = rec.get("shape")
        if not isinstance(shape_obj, dict) or not shape_obj:
            shape_obj = (
                {"config": rec["config"]} if rec.get("config") else {}
            )
        shape = json.dumps(shape_obj, sort_keys=True)
        key = (rec["metric"], rec.get("backend", "?"), shape)
        cfg = configs.setdefault(key, {
            "metric": rec["metric"],
            "backend": rec.get("backend", "?"),
            "shape": shape_obj,
            "unit": rec.get("unit", ""),
            "runs": [],
        })
        cfg["runs"].append({
            "ts": rec.get("ts", ""),
            "value": float(rec["value"]),
            "variant": rec.get("best_variant", ""),
        })
    out = []
    for key in sorted(configs):
        cfg = configs[key]
        values = [r["value"] for r in cfg["runs"]]
        cfg["latest"] = values[-1]
        cfg["best"] = max(values)
        if len(values) > 1:
            prior_best = max(values[:-1])
            cfg["prior_best"] = prior_best
            cfg["latest_vs_prior_best_pct"] = round(
                100.0 * (values[-1] - prior_best) / prior_best, 2
            )
        out.append(cfg)
    return out


def trend_regressions(trend: list[dict], pct: float) -> list[dict]:
    """Configs whose latest run is more than ``pct`` percent below the
    best earlier run — single-run configs have no trajectory and never
    flag."""
    return [
        cfg for cfg in trend
        if "prior_best" in cfg
        and cfg["latest"] < cfg["prior_best"] * (1.0 - pct / 100.0)
    ]


def format_trend(trend: list[dict], regressed: list[dict] | None = None) -> str:
    """Human trajectory table for :func:`bench_trend` output."""
    flagged = {id(c) for c in (regressed or [])}
    lines = []
    for cfg in trend:
        shape = json.dumps(cfg["shape"], sort_keys=True)
        lines.append(
            f"# {cfg['metric']} [{cfg['backend']}] {shape}  "
            f"unit={cfg['unit']}  runs={len(cfg['runs'])}"
        )
        for run in cfg["runs"]:
            variant = f"  ({run['variant']})" if run["variant"] else ""
            lines.append(f"  {run['ts']}  {run['value']:.1f}{variant}")
        if "prior_best" in cfg:
            mark = "  ** REGRESSION **" if id(cfg) in flagged else ""
            lines.append(
                f"  latest {cfg['latest']:.1f} vs prior best "
                f"{cfg['prior_best']:.1f}: "
                f"{cfg['latest_vs_prior_best_pct']:+.2f}%{mark}"
            )
    return "\n".join(lines) if lines else "(no bench records)"

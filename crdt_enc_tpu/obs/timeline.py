"""Chrome-trace / Perfetto export of the span event log.

Aggregates prove a phase was *fast*; only a timeline proves two phases
*overlapped* — which is the PR-1 streaming pipeline's whole claim (chunk
k+1's decrypt/decode/H2D riding under chunk k's fold).  This module turns
``obs.record`` events into the Chrome trace-event JSON both
``chrome://tracing`` and https://ui.perfetto.dev load directly:

* one **lane per recording thread** (``M``/``thread_name`` metadata
  events), so the producer thread's ``stream.ingest`` visibly overlaps
  the consumer's ``stream.reduce``;
* spans as complete (``ph: "X"``) events with the span ``meta`` (chunk
  index) in ``args``, so overlap is also *programmatically* checkable —
  :func:`chunk_overlaps` is what the acceptance tests assert on;
* counter/gauge updates as counter-track (``ph: "C"``) events, so
  ``h2d_bytes`` or ``device_bytes_in_use`` plot as stepped graphs above
  the lanes.

Timestamps are ``time.perf_counter`` seconds rebased to the earliest
event and scaled to the format's microseconds.  See
``docs/observability.md`` for how to read a compaction timeline.
"""

from __future__ import annotations

import json

from . import record

PID = 1


def to_chrome_trace(events: list | None = None) -> dict:
    """Build the Chrome trace-event JSON object for ``events`` (default:
    the live event log).  Deterministic: events sort by start time and
    thread lanes number in order of first appearance."""
    if events is None:
        events = record.events()
    out: list[dict] = []
    if not events:
        return {"traceEvents": out, "displayTimeUnit": "ms"}
    t_base = min(e["t0"] for e in events)
    lanes: dict = {}
    for e in sorted(events, key=lambda e: (e["t0"], e["t1"])):
        ts = (e["t0"] - t_base) * 1e6
        kind = e.get("kind", "span")
        if kind in ("counter", "gauge"):
            # counter tracks are per-process graphs; no thread lane
            out.append({
                "ph": "C",
                "pid": PID,
                "tid": 0,
                "name": e["name"],
                "ts": ts,
                "args": {"value": e.get("value", 0)},
            })
            continue
        tid = e.get("tid")
        if tid not in lanes:
            lanes[tid] = len(lanes)
            out.append({
                "ph": "M",
                "pid": PID,
                "tid": lanes[tid],
                "name": "thread_name",
                "args": {"name": e.get("thread", f"thread-{tid}")},
            })
        ev = {
            "ph": "X",
            "pid": PID,
            "tid": lanes[tid],
            "name": e["name"],
            "ts": ts,
            "dur": (e["t1"] - e["t0"]) * 1e6,
            "args": {},
        }
        if e.get("meta") is not None:
            ev["args"]["chunk"] = e["meta"]
        out.append(ev)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def export_chrome_trace(path: str, events: list | None = None) -> dict:
    """Write :func:`to_chrome_trace` to ``path``; returns the trace dict."""
    trace_obj = to_chrome_trace(events)
    with open(path, "w") as f:
        json.dump(trace_obj, f)
    return trace_obj


def _spans_by_chunk(trace_obj: dict, name: str) -> dict:
    """chunk index -> (ts, ts+dur) for the ``X`` events named ``name``,
    from the LAST recorded run only.  A stage's chunk indices increase
    strictly within one pipeline run, so a non-increasing index marks a
    new run — without the split, an event log spanning two runs (e.g. a
    warmup pass before the measured one) would pair chunk k of run 1
    with chunk k+1 of run 2 and "prove" an overlap that never happened."""
    runs: list[dict] = [{}]
    rows = sorted(
        (
            e for e in trace_obj.get("traceEvents", ())
            if e.get("ph") == "X" and e.get("name") == name
            and e.get("args", {}).get("chunk") is not None
        ),
        key=lambda e: e["ts"],
    )
    last_k = None
    for e in rows:
        k = e["args"]["chunk"]
        if last_k is not None and k <= last_k:
            runs.append({})
        runs[-1][k] = (e["ts"], e["ts"] + e["dur"])
        last_k = k
    return runs[-1]


def chunk_overlaps(
    trace_obj: dict,
    earlier: str = "stream.ingest",
    later: str = "stream.reduce",
) -> list[int]:
    """The chunk indices ``k`` for which chunk k+1's ``earlier`` stage
    STARTED before chunk k's ``later`` stage FINISHED — the pipeline's
    overlap proof, read from an exported Chrome trace.  Empty list =
    the recorded run was fully serialized (or stages are missing)."""
    a = _spans_by_chunk(trace_obj, earlier)
    b = _spans_by_chunk(trace_obj, later)
    return [
        k for k in sorted(b)
        if (k + 1) in a and a[k + 1][0] < b[k][1]
    ]

"""Structured per-phase tracing and metrics: the process-wide registry.

The reference ships no observability at all (SURVEY.md §5: no tracing/log
crates anywhere; anyhow context strings are the only diagnostics).  The
rebuild's contract is per-phase timers around the compaction pipeline —
list/load/decrypt/decode/fold/write — plus counters for the BASELINE
metric (ops merged/sec), with optional ``jax.profiler`` trace annotations
so device-side kernel time lines up with host phases in a profile.

Design: one process-wide registry, monotonic wall-clock spans, plain
dicts under a lock (spans fire at file/batch granularity — hundreds per
compaction — so overhead is irrelevant next to I/O and crypto).  Spans
nest; a span records under its own flat name, so concurrent asyncio tasks
timing the same phase simply accumulate.

Aggregates are count + total seconds + a **bounded log-scale histogram**
(quarter-octave buckets, so every estimate is within ~±9% of the true
value): ``report()`` and ``snapshot()`` publish p50/p95/p99/max per span.
A phase whose *mean* looks healthy can still hide a 100× tail (one
recompile, one cold dispatch) — the quantiles make that visible.

Usage::

    from crdt_enc_tpu.utils import trace   # compat shim onto this module

    with trace.span("stream.decrypt"):
        ...
    trace.add("ops_folded", len(batch))
    trace.gauge("device_bytes_in_use", stats["bytes_in_use"])
    print(trace.report())     # phase table with quantiles
    trace.snapshot()          # {"spans": ..., "counters": ..., "gauges": ...}

Logging: spans emit DEBUG records on the ``crdt_enc_tpu.trace`` logger;
enable with ``logging.getLogger("crdt_enc_tpu").setLevel(logging.DEBUG)``.

Event log: aggregated slots cannot show *when* phases ran relative to
each other, which is exactly what auditing an overlapped pipeline needs
(did chunk k+1's ingest start before chunk k's fold finished?).
``enable_events()`` turns on a per-occurrence log — every span exit
appends ``{"name", "t0", "t1", "meta", "tid", "thread", "kind"}`` with
monotonic ``perf_counter`` timestamps comparable across threads — read it
back with ``events()`` or export a Chrome-trace timeline with
``obs.timeline``.  The log is a RING BUFFER (``DEFAULT_EVENT_CAPACITY``
occurrences; configure with ``set_events_capacity``): when full, the
oldest event is dropped and the ``events_dropped`` counter bumps, so an
instrumented long-running service can leave events on without unbounded
growth.  Off by default, and ``reset()`` restores the default off state
(seam tests cannot leak event recording into later tests).  Counter and
gauge updates also append (``kind: "counter"/"gauge"``) while events are
on, which is what the timeline's counter tracks are built from.

Span and metric names are REGISTERED in ``docs/observability.md``;
``tools/check_span_names.py`` lints the tree against the registry.
"""

from __future__ import annotations

import contextvars
import logging
import math
import sys
import threading
import time
from collections import deque
from contextlib import contextmanager

logger = logging.getLogger("crdt_enc_tpu.trace")

# When True and jax is already imported, spans also open a
# jax.profiler.TraceAnnotation so they show up in device traces.
jax_annotations = False

DEFAULT_EVENT_CAPACITY = 65536

_lock = threading.Lock()
# name -> [count, total_seconds, max_seconds, {bucket_index: count}]
_spans: dict[str, list] = {}
_counters: dict[str, int] = {}
_gauges: dict[str, float] = {}
_events_enabled = False
_events_capacity = DEFAULT_EVENT_CAPACITY
_events: deque = deque(maxlen=DEFAULT_EVENT_CAPACITY)


# --------------------------------------------------------------- histogram
# Quarter-octave log2 buckets: index = floor(4·log2(dt)).  Bucket width is
# 2^0.25 ≈ 19%, so a quantile read back as the bucket's geometric midpoint
# is within ±9% — plenty for phase timing, at a bounded ~4 bytes/bucket.
# Indices clamp to [≈1ns, ≈5d], so the table size is bounded (~200 slots)
# no matter what durations arrive.
_HIST_SCALE = 4
_HIST_MIN_IDX = _HIST_SCALE * -30  # 2^-30 s ≈ 1 ns
_HIST_MAX_IDX = _HIST_SCALE * 19  # 2^19 s ≈ 6 days


def _hist_index(dt: float) -> int:
    if dt <= 0:
        return _HIST_MIN_IDX
    i = math.floor(_HIST_SCALE * math.log2(dt))
    return max(_HIST_MIN_IDX, min(_HIST_MAX_IDX, i))


def _hist_value(idx: int) -> float:
    return 2.0 ** ((idx + 0.5) / _HIST_SCALE)


def _hist_quantile(hist: dict, count: int, q: float) -> float:
    """Value at quantile ``q`` (geometric bucket midpoint)."""
    rank = max(1, math.ceil(q * count))
    seen = 0
    for idx in sorted(hist):
        seen += hist[idx]
        if seen >= rank:
            return _hist_value(idx)
    return 0.0


def quantiles_ms(hist: dict, count: int) -> dict:
    """p50/p95/p99 in milliseconds from one span's bucket table."""
    if not count:
        return {}
    return {
        f"p{int(q * 100)}_ms": round(_hist_quantile(hist, count, q) * 1e3, 4)
        for q in (0.50, 0.95, 0.99)
    }


# ------------------------------------------------------------ event buffer
def enable_events(on: bool = True) -> None:
    """Toggle the per-occurrence event log (see module docs)."""
    global _events_enabled
    with _lock:
        _events_enabled = on


def set_events_capacity(capacity: int) -> None:
    """Resize the event ring buffer, keeping the newest events; any
    events a shrink discards count into ``events_dropped`` exactly like
    ring overflow (the drop counter is the completeness signal timeline
    consumers rely on)."""
    if capacity < 1:
        raise ValueError(f"event capacity must be >= 1, got {capacity}")
    global _events, _events_capacity
    with _lock:
        overflow = len(_events) - capacity
        if overflow > 0:
            _counters["events_dropped"] = (
                _counters.get("events_dropped", 0) + overflow
            )
        _events_capacity = capacity
        _events = deque(_events, maxlen=capacity)


def events_capacity() -> int:
    return _events_capacity


def events_enabled() -> bool:
    return _events_enabled


def drain_events() -> list[dict]:
    """Like :func:`events`, but CONSUMES the ring buffer: the returned
    occurrences are removed, so successive drains never hand out the
    same event twice (the metrics sink drains, keeping one timeline per
    record instead of a cumulative re-copy)."""
    with _lock:
        out = [dict(e) for e in _events]
        _events.clear()
        return out


def events() -> list[dict]:
    """A consistent copy of the recorded occurrences, in completion order.
    Span entries: name, t0, t1 (``time.perf_counter`` seconds — monotonic,
    cross-thread comparable), meta (the span's ``meta`` arg), tid/thread
    (recording thread), kind ("span").  Counter/gauge entries carry
    ``kind: "counter"/"gauge"`` and the post-update ``value`` at ``t0``."""
    with _lock:
        return [dict(e) for e in _events]


def _append_event_locked(entry: dict) -> None:
    if len(_events) == _events.maxlen:
        _counters["events_dropped"] = _counters.get("events_dropped", 0) + 1
    _events.append(entry)


def _event_base(name: str, kind: str) -> dict:
    t = threading.current_thread()
    return {"name": name, "kind": kind, "tid": t.ident, "thread": t.name}


# ------------------------------------------------------------------- spans
@contextmanager
def span(name: str, meta=None):
    """Time a phase.  Re-entrant and concurrency-tolerant: every exit
    accumulates (count, seconds, histogram) under ``name``.  ``meta``
    (e.g. a chunk index) is recorded only in the event log, never in the
    aggregate."""
    ann = None
    if jax_annotations and "jax" in sys.modules:
        import jax.profiler

        ann = jax.profiler.TraceAnnotation(name)
        ann.__enter__()
    t0 = time.perf_counter()
    try:
        yield
    finally:
        t1 = time.perf_counter()
        if ann is not None:
            ann.__exit__(None, None, None)
        _record_span(name, t0, t1, meta)


def _record_span(name: str, t0: float, t1: float, meta=None) -> None:
    dt = t1 - t0
    with _lock:
        slot = _spans.setdefault(name, [0, 0.0, 0.0, {}])
        slot[0] += 1
        slot[1] += dt
        if dt > slot[2]:
            slot[2] = dt
        idx = _hist_index(dt)
        slot[3][idx] = slot[3].get(idx, 0) + 1
        if _events_enabled:
            e = _event_base(name, "span")
            e["t0"], e["t1"], e["meta"] = t0, t1, meta
            _append_event_locked(e)
    logger.debug("span %s: %.6fs", name, dt)


def observe(name: str, seconds: float, meta=None) -> None:
    """Record one occurrence of ``seconds`` under span ``name`` without a
    context manager — for durations reported by a callback (e.g. the XLA
    compile-time listener in obs.runtime)."""
    t1 = time.perf_counter()
    _record_span(name, t1 - seconds, t1, meta)


# ---------------------------------------------------------------- counters
# Context-local counter taps: the registry's counters are process-wide,
# which is exactly wrong for a caller that needs "increments caused by MY
# work" while other tasks share the process (the population runner's
# lanes each need their own quarantine tally).  A tap is a plain dict
# registered in the calling context; every add() mirrors its increment
# into each tap visible from the caller's context.  asyncio tasks and
# to_thread hops copy the context at creation, so a tap covers the whole
# task tree under the ``with`` — and nothing outside it.
_taps: contextvars.ContextVar[tuple] = contextvars.ContextVar(
    "crdt_trace_counter_taps", default=()
)


@contextmanager
def counter_tap():
    """Yield a dict accumulating every counter increment made from this
    context (and tasks/threads spawned within it) until exit.  Taps
    nest — an inner tap does not steal from an outer one, both see the
    increment.  The global registry is untouched; read the tap."""
    local: dict[str, int] = {}
    token = _taps.set(_taps.get() + (local,))
    try:
        yield local
    finally:
        _taps.reset(token)


def add(name: str, n: int = 1) -> None:
    """Bump a counter (e.g. ops folded, states merged, bytes decrypted)."""
    with _lock:
        value = _counters.get(name, 0) + n
        _counters[name] = value
        for tap in _taps.get():
            tap[name] = tap.get(name, 0) + n
        if _events_enabled:
            e = _event_base(name, "counter")
            e["t0"] = e["t1"] = time.perf_counter()
            e["meta"], e["value"] = None, value
            _append_event_locked(e)


def gauge(name: str, value: float) -> None:
    """Set a point-in-time gauge (e.g. device bytes in use)."""
    with _lock:
        _gauges[name] = value
        if _events_enabled:
            e = _event_base(name, "gauge")
            e["t0"] = e["t1"] = time.perf_counter()
            e["meta"], e["value"] = None, value
            _append_event_locked(e)


# ---------------------------------------------------------------- registry
def snapshot() -> dict:
    """A consistent copy: {"spans": {name: {"count", "seconds", "max_ms",
    "p50_ms", "p95_ms", "p99_ms"}}, "counters": {...}, "gauges": {...}}."""
    with _lock:
        return {
            "spans": {
                k: {
                    "count": c,
                    "seconds": s,
                    "max_ms": round(mx * 1e3, 4),
                    **quantiles_ms(h, c),
                }
                for k, (c, s, mx, h) in _spans.items()
            },
            "counters": dict(_counters),
            "gauges": dict(_gauges),
        }


def reset() -> None:
    """Clear every aggregate and the event log, and restore the event
    defaults (recording OFF, default capacity) — a test or run that
    enabled events cannot leak recording state into the next one."""
    global _events_enabled, _events_capacity, _events
    with _lock:
        _spans.clear()
        _counters.clear()
        _gauges.clear()
        _events_enabled = False
        _events_capacity = DEFAULT_EVENT_CAPACITY
        _events = deque(maxlen=DEFAULT_EVENT_CAPACITY)


def format_snapshot(snap: dict) -> str:
    """Human-readable phase table for one snapshot dict (shared by
    ``report()`` and the obs_report CLI)."""
    lines = []
    spans = sorted(
        snap.get("spans", {}).items(),
        key=lambda kv: kv[1]["seconds"],
        reverse=True,
    )
    if spans:
        w = max(len(k) for k, _ in spans)
        for k, v in spans:
            q = ""
            if "p50_ms" in v:
                q = (
                    f"  p50 {v['p50_ms']:>9.3f}ms  p95 {v['p95_ms']:>9.3f}ms"
                    f"  p99 {v['p99_ms']:>9.3f}ms  max {v['max_ms']:>9.3f}ms"
                )
            lines.append(
                f"{k:<{w}}  {v['seconds']:>9.4f}s  x{v['count']}{q}"
            )
    for k in sorted(snap.get("counters", ())):
        lines.append(f"{k} = {snap['counters'][k]}")
    for k in sorted(snap.get("gauges", ())):
        lines.append(f"{k} = {snap['gauges'][k]} (gauge)")
    return "\n".join(lines) if lines else "(no spans recorded)"


def report() -> str:
    """Human-readable phase table, longest total first, with quantiles."""
    return format_snapshot(snapshot())


def throughput(span_name: str, counter_name: str) -> float | None:
    """counter / span-seconds, or None if either is missing/zero."""
    snap = snapshot()
    s = snap["spans"].get(span_name)
    c = snap["counters"].get(counter_name)
    if not s or not c or s["seconds"] <= 0:
        return None
    return c / s["seconds"]

"""Cycle attribution: stage marginals, critical path, and the gap report.

ROADMAP item 1's claim — "the encrypted front end is ~300× slower than
the fold" — has so far been a human reading BENCH_LOCAL per-stage
marginals.  This module makes it a machine-checked number: a **pure
function** over recorded span/event data (the same inputs
``obs.timeline`` consumes) that decomposes one streaming-compaction or
serve cycle into the canonical stage marginals

    ingest / decrypt / decode / h2d / fold / scatter / seal

computes the **overlap efficiency** (serialized stage sum ÷ wall — >1
means the pipeline genuinely hid work under the fold; chunk-level proof
via :func:`obs.timeline.chunk_overlaps` when an event log is present),
names the **critical-path stage**, and emits the **gap report**:
end-to-end ops/s vs the fold-marginal ops/s (what throughput would be
if only the fold stage existed), with the dominant stage named — the
number ROADMAP item 1 closes, now with a trend trajectory because
``bench.py`` attaches it to every ``--e2e-streaming`` /
``--e2e-multitenant`` record and ``obs_report gap`` reads both sink
files and the committed BENCH_LOCAL records.

Span aggregates nest (``stream.ingest`` wraps ``stream.decrypt`` +
``stream.decode``; ``session.decode`` runs inside ``stream.decode``),
so naive summing double-counts.  Each stage is therefore a tuple of
**groups**; within a group the FIRST span present in the snapshot is
taken (alternatives across pipeline generations), and disjoint groups
sum.  Everything is deterministic for a given snapshot — the CLI output
is golden-tested against the committed BENCH_LOCAL record.
"""

from __future__ import annotations

from . import record, timeline

#: canonical stage order — ties on the critical path resolve to the
#: earliest stage, and reports render in this order.
STAGES = ("ingest", "decrypt", "decode", "h2d", "fold", "scatter", "seal")

# stage -> groups of alternative span names (module docs).  The
# streaming map covers the solo pipeline (ops/stream + session + the
# bulk/legacy core paths); the serve map covers a FoldService cycle.
_STREAM_STAGES: dict[str, tuple[tuple[str, ...], ...]] = {
    "ingest": (("ops.list",), ("ops.load",), ("states.list",),
               ("states.load",)),
    "decrypt": (("stream.decrypt", "ops.bulk_decrypt",
                 "ops.chunk_decrypt"),),
    "decode": (("stream.decode", "session.decode", "fold.decode"),),
    "h2d": (("stream.h2d",),),
    "fold": (("stream.reduce", "ops.bulk_fold", "ops.chunk_fold",
              "session.device_fold", "session.host_reduce",
              "fold.device", "ops.fold"),
             ("session.sparse_fold",)),
    "scatter": (("session.writeback", "stream.finish",
                 "fold.writeback"), ("stream.d2h",)),
    "seal": (("compact.seal",), ("compact.write",), ("compact.gc",),
             ("checkpoint.save",), ("delta.seal",), ("delta.verify",)),
}
_SERVE_STAGES: dict[str, tuple[tuple[str, ...], ...]] = {
    "ingest": (("serve.ingest",), ("serve.plan",)),
    "decrypt": (("serve.decrypt",),),
    "decode": (("serve.decode",),),
    "h2d": (),
    "fold": (("serve.fold", "serve.shard"),),  # shard = mesh mega-fold
    "scatter": (("serve.scatter",),),
    # delta.cut (device-cut delta build, disjoint from serve.scatter)
    # and serve.continue (post-seal warm-entry stamping) are seal-phase
    # work: separate groups because they never nest inside serve.seal
    "seal": (("serve.seal",), ("delta.cut",), ("serve.continue",)),
}


def detect_pipeline(snapshot: dict) -> str:
    """``"serve"`` when the snapshot carries FoldService spans, else
    ``"streaming"`` — the two cycle shapes this profiler decomposes."""
    spans = snapshot.get("spans", {})
    return "serve" if any(n.startswith("serve.") for n in spans) \
        else "streaming"


def _stage_seconds(spans: dict, groups) -> tuple[float, dict[str, float]]:
    total = 0.0
    contributors: dict[str, float] = {}
    for group in groups:
        for name in group:
            v = spans.get(name)
            if v is not None:
                s = float(v.get("seconds", 0.0))
                total += s
                contributors[name] = round(s, 6)
                break  # first present alternative wins (nesting guard)
    return total, contributors


def attribute_cycle(
    snapshot: dict,
    *,
    pipeline: str | None = None,
    wall_s: float | None = None,
    ops: int | None = None,
    events: list | None = None,
) -> dict:
    """Decompose one recorded cycle (module docs).

    ``snapshot`` is a registry snapshot (``record.snapshot()`` /
    a sink record / a bench record's ``obs`` dict).  ``wall_s`` is the
    cycle wall clock when the caller measured it (bench does); else it
    is inferred from the event log's extent, or from the ``serve.cycle``
    span.  ``ops`` enables the throughput half of the gap report.
    ``events`` (the record's event log) additionally yields the
    chunk-level overlap proof."""
    with record.span("attribution.gap"):
        spans = snapshot.get("spans", {})
        # simulator harness spans (sim.run / sim.step / sim.check /
        # sim.population) WRAP the serve spans a sim service cycle
        # records — they are schedule bookkeeping, not cycle stages.
        # Drop them explicitly: left in, they would dominate the
        # event-extent wall inference and report a whole simulation as
        # one impossibly slow cycle.
        spans = {n: v for n, v in spans.items()
                 if not n.startswith("sim.")}
        pipe = pipeline or detect_pipeline(snapshot)
        stage_map = _SERVE_STAGES if pipe == "serve" else _STREAM_STAGES

        stages: dict[str, dict] = {}
        serialized = 0.0
        for stage in STAGES:
            s, contributors = _stage_seconds(spans, stage_map.get(stage, ()))
            stages[stage] = {"seconds": round(s, 6), "spans": contributors}
            serialized += s

        if wall_s is None and events:
            span_events = [e for e in events
                           if e.get("kind", "span") == "span"
                           and not str(e.get("name", "")).startswith("sim.")]
            if span_events:
                wall_s = (max(e["t1"] for e in span_events)
                          - min(e["t0"] for e in span_events))
        if wall_s is None and pipe == "serve":
            cyc = spans.get("serve.cycle")
            if cyc:
                wall_s = float(cyc["seconds"])

        critical = max(
            STAGES, key=lambda st: (stages[st]["seconds"],
                                    -STAGES.index(st))
        )
        report = {
            "pipeline": pipe,
            "stages": stages,
            "serialized_s": round(serialized, 6),
            "wall_s": round(wall_s, 6) if wall_s else None,
            "critical_path": critical,
            "critical_share": round(
                stages[critical]["seconds"] / serialized, 4
            ) if serialized > 0 else None,
        }
        if wall_s:
            report["overlap_x"] = round(serialized / wall_s, 4)
        if events:
            chunks = timeline.chunk_overlaps(
                timeline.to_chrome_trace(events)
            )
            report["overlapped_chunks"] = len(chunks)

        fold_s = stages["fold"]["seconds"]
        if ops and wall_s:
            gap = {
                "ops": int(ops),
                "e2e_ops_per_sec": round(ops / wall_s, 1),
                "dominant_stage": critical,
            }
            if fold_s > 0:
                gap["fold_marginal_ops_per_sec"] = round(ops / fold_s, 1)
                gap["gap_x"] = round(wall_s / fold_s, 2)
            report["gap"] = gap
        return report


def from_record(rec: dict) -> dict:
    """Attribution for one JSONL record of ANY of the shapes the repo
    writes: a bench record (``obs`` + shape/wall fields), or a sink
    record (snapshot at top level).  Pure: only reads the record."""
    if isinstance(rec.get("obs"), dict):
        snapshot = rec["obs"]
        wall = rec.get("e2e_overlapped_s") or rec.get("service_cycle_s")
        shape = rec.get("shape") or {}
        ops = shape.get("total_ops")
    else:
        snapshot = rec
        wall = None
        counters = rec.get("counters", {})
        # best-effort op count for sink records: the batched-tenant and
        # per-op paths count rows; the solo bulk paths count files only
        ops = counters.get("serve_rows_folded") or \
            counters.get("ops_folded") or None
    return attribute_cycle(
        snapshot,
        wall_s=float(wall) if wall else None,
        ops=int(ops) if ops else None,
        events=rec.get("events") or snapshot.get("events"),
    )


def format_attribution(report: dict) -> str:
    """Deterministic human rendering (golden-tested by the CLI test)."""
    lines = [f"# cycle attribution ({report['pipeline']} pipeline)"]
    serialized = report["serialized_s"]
    for stage in STAGES:
        st = report["stages"][stage]
        if not st["spans"]:
            continue
        share = 100.0 * st["seconds"] / serialized if serialized else 0.0
        names = ",".join(sorted(st["spans"]))
        lines.append(
            f"{stage:<8} {st['seconds']:>9.4f}s  {share:>5.1f}%  {names}"
        )
    wall = report.get("wall_s")
    tail = f"  wall {wall:.4f}s" if wall else ""
    if report.get("overlap_x") is not None:
        tail += f"  overlap {report['overlap_x']:.2f}x"
    if report.get("overlapped_chunks") is not None:
        tail += f"  overlapped_chunks={report['overlapped_chunks']}"
    lines.append(f"serialized sum {serialized:.4f}s{tail}")
    crit = report["critical_path"]
    share = report.get("critical_share")
    lines.append(
        f"critical path: {crit}"
        + (f" ({100.0 * share:.1f}% of serialized time)" if share else "")
    )
    gap = report.get("gap")
    if gap:
        if "gap_x" in gap:
            lines.append(
                f"gap: e2e {gap['e2e_ops_per_sec']:,.1f} ops/s vs fold "
                f"marginal {gap['fold_marginal_ops_per_sec']:,.1f} ops/s "
                f"= {gap['gap_x']:.2f}x  (dominant stage: "
                f"{gap['dominant_stage']})"
            )
        else:
            lines.append(
                f"gap: e2e {gap['e2e_ops_per_sec']:,.1f} ops/s; no fold "
                "stage recorded"
            )
    return "\n".join(lines)

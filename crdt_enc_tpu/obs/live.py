"""Live telemetry plane: a scrapeable in-process HTTP endpoint.

Everything PR-2/PR-6 built is post-hoc and file-based — the sink writes
JSONL, ``obs_report`` reads it after the run.  A *running* service
(ROADMAP item 2's always-on daemon) needs the serving shape: a port a
Prometheus scraper (or an operator's ``curl``) can hit while the process
works.  This module is that surface, and nothing else — it computes no
new signals, it *serves* the ones the registry and the replication
sampler already maintain:

* ``GET /metrics``  — the live registry rendered by
  :func:`obs.sink.to_prometheus` (same families, ``# HELP``/``# TYPE``
  and escaping as the file-based ``obs_report prom``), content type
  ``text/plain; version=0.0.4``.
* ``GET /healthz``  — JSON, schema-stamped like a sink record
  (``{"schema": obs.sink.SCHEMA_VERSION, ...}``): per-remote device
  health (the exact stability **watermark**, backlog and divergence
  each ``Core.replication_status()`` computed at its last sample) plus
  the last published service-cycle summaries (``FoldService``).
* ``GET /snapshot`` — the full ``record.snapshot()`` as JSON (the same
  dict a sink record embeds), for ad-hoc debugging.

**Never on the hot path.**  The server runs ``serve_forever`` on one
daemon thread (THR001 allowlisted — it does no ingest work and needs no
backpressure; requests read lock-guarded copies).  Publishing into it is
a dict store under a lock, performed by the replication sampler which
already runs per open/read_remote/compact — when no server is
configured, :func:`publish` is a single global check.  The compaction
pipeline itself is untouched; the enabled-vs-disabled regression test
pins byte-identical folds and an identical storage-probe count.

Opt in with ``CRDT_OBS_HTTP=<port>`` (or ``<host>:<port>``; plain ports
bind 127.0.0.1 — expose deliberately) and the first replication sample
starts the process-default server lazily; or pass
``FoldService(..., live_port=...)`` for a service-owned instance; or
drive :class:`LiveTelemetryServer` directly.  ``port=0`` binds an
ephemeral port (tests); :func:`shutdown` stops the default server and
re-arms env resolution.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import record, sink

logger = logging.getLogger("crdt_enc_tpu.obs.live")

ENV_VAR = "CRDT_OBS_HTTP"

#: /healthz keeps only the bounded summary of a replication status —
#: the cursor matrix grows with (replicas × actors) and belongs in the
#: sink record, not in every scrape response.
_HEALTH_KEYS = (
    "watermark", "backlog", "divergence", "checkpoint", "local_clock",
    # present only when a strong-read membership policy is configured
    # (crdt_enc_tpu/read/policy.py): WHO the watermark denominator
    # excludes must be operator-visible, never a silent drop
    "membership",
)


class _Handler(BaseHTTPRequestHandler):
    server_version = "crdt-obs-live"
    protocol_version = "HTTP/1.1"
    # keep-alive needs an idle bound: without it every half-open or
    # silent connection pins one ThreadingHTTPServer thread FOREVER —
    # unacceptable in the always-on daemon this serves.  On timeout the
    # handler closes the connection and the thread exits.
    timeout = 30.0

    def handle_one_request(self):
        # a scraper dropping its connection (timeout, RST) is routine
        # for a long-lived daemon: both the in-flight response write
        # and the keep-alive loop's next request read die with a pipe
        # error that socketserver would otherwise print as a full
        # stderr traceback per dropped scrape
        try:
            super().handle_one_request()
        except (BrokenPipeError, ConnectionResetError):
            logger.debug("telemetry client disconnected")
            self.close_connection = True

    def do_GET(self):  # noqa: N802 — http.server's fixed method name
        with record.span("obs.live.request", meta=self.path):
            record.add("live_requests", 1)
            try:
                if self.path == "/metrics":
                    body = sink.to_prometheus().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path == "/healthz":
                    body = json.dumps(
                        self.server.telemetry.health(), sort_keys=True
                    ).encode()
                    ctype = "application/json"
                elif self.path == "/snapshot":
                    body = json.dumps(
                        {"schema": sink.SCHEMA_VERSION, **record.snapshot()},
                        sort_keys=True,
                    ).encode()
                    ctype = "application/json"
                else:
                    body = b"not found\n"
                    self._reply(404, "text/plain", body)
                    return
            except Exception as e:  # telemetry must not take itself down
                logger.debug("telemetry request failed", exc_info=True)
                self._reply(500, "text/plain", f"{e!r}\n".encode())
                return
            self._reply(200, ctype, body)

    def _reply(self, code: int, ctype: str, body: bytes) -> None:
        try:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            # a scraper timing out mid-response is routine in a
            # long-lived daemon — not a stderr traceback per scrape
            logger.debug("telemetry client disconnected mid-response")
            self.close_connection = True

    def log_message(self, fmt, *args):
        logger.debug("live: " + fmt, *args)


class LiveTelemetryServer:
    """One embeddable telemetry endpoint (module docs).

    ``start()`` binds and returns the port (use ``port=0`` for an
    ephemeral one); ``stop()`` shuts the listener down gracefully —
    in-flight requests finish, the socket closes, the thread joins.
    ``publish_health``/``publish_cycle`` are the write side the
    replication sampler and the fold service feed; ``health()`` is the
    read side ``/healthz`` renders."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self.host = host
        self.port = int(port)
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        # (remote_id hex, actor hex) -> bounded status summary + ts
        self._devices: dict[tuple[str, str], dict] = {}
        # source name -> last cycle summary (FoldService)
        self._cycles: dict[str, dict] = {}
        # the owning FleetDaemon's control-plane health (serve/daemon.py):
        # uptime, cycles, backoff/quarantine counts, drain state
        self._daemon: dict = {}

    # ---------------------------------------------------------- lifecycle
    @property
    def running(self) -> bool:
        return self._httpd is not None

    def start(self) -> int:
        """Bind + serve on a daemon thread; returns the bound port.
        Idempotent — a running server keeps its port."""
        if self._httpd is not None:
            return self.port
        httpd = ThreadingHTTPServer((self.host, self.port), _Handler)
        httpd.daemon_threads = True
        httpd.telemetry = self
        self._httpd = httpd
        self.port = httpd.server_address[1]
        self._thread = threading.Thread(
            target=httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name=f"crdt-obs-live-{self.port}",
            daemon=True,
        )
        self._thread.start()
        logger.debug("live telemetry serving on %s:%d", self.host, self.port)
        return self.port

    def stop(self) -> None:
        """Graceful shutdown: stop accepting, close the socket, join."""
        httpd, thread = self._httpd, self._thread
        self._httpd = self._thread = None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None:
            thread.join(timeout=5.0)

    # --------------------------------------------------------- write side
    def publish_health(self, status: dict, ts: float | None = None) -> None:
        """Store one device's replication status summary (the dict
        ``Core.replication_status()`` returns).  Bounded: only the
        ``_HEALTH_KEYS`` summary is kept, last write per (remote,
        actor) wins.  The publish time the WATERMARK last changed is
        tracked separately (``watermark_ts``) so ``/healthz`` can
        report watermark AGE — a wedged watermark (fresh samples, stale
        frontier) is an operator-visible duration, not a gauge puzzle
        (docs/strong_reads.md)."""
        key = (status.get("remote_id", "?"), status.get("actor", "?"))
        entry = {k: status[k] for k in _HEALTH_KEYS if k in status}
        entry["ts"] = round(time.time() if ts is None else ts, 3)
        with self._lock:
            old = self._devices.get(key)
            if (
                old is not None
                and old.get("watermark") == entry.get("watermark")
            ):
                entry["watermark_ts"] = old.get("watermark_ts", entry["ts"])
            else:
                entry["watermark_ts"] = entry["ts"]
            self._devices[key] = entry

    def publish_cycle(self, source: str, summary: dict) -> None:
        """Store a service-cycle summary (tenant counts, paths, SLO burn
        — whatever the publisher considers its last-cycle status)."""
        with self._lock:
            self._cycles[source] = dict(summary)

    def publish_daemon(self, info: dict) -> None:
        """Store the fleet daemon's control-plane health (the dict
        :meth:`crdt_enc_tpu.serve.daemon.FleetDaemon.health` builds:
        uptime, cycles, backoff/quarantine counts, degraded flag, drain
        state).  Last write wins — one daemon owns a server."""
        with self._lock:
            self._daemon = dict(info)

    # ---------------------------------------------------------- read side
    def health(self) -> dict:
        """The ``/healthz`` payload: schema-stamped like a sink record,
        devices grouped per remote, plus last-cycle summaries."""
        with self._lock:
            devices = {k: dict(v) for k, v in self._devices.items()}
            cycles = {k: dict(v) for k, v in self._cycles.items()}
            daemon = dict(self._daemon)
        now = time.time()
        remotes: dict[str, dict] = {}
        for (remote_id, actor), entry in sorted(devices.items()):
            # watermark AGE: how long since this device's stability
            # watermark last moved — a wedged watermark shows as a
            # growing duration right in /healthz
            wm_ts = entry.pop("watermark_ts", None)
            if wm_ts is not None:
                entry["watermark_age_s"] = round(max(0.0, now - wm_ts), 3)
            slot = remotes.setdefault(remote_id, {"devices": {}})
            slot["devices"][actor] = entry
            age = entry.get("watermark_age_s")
            if age is not None:
                slot["watermark_age_s"] = max(
                    slot.get("watermark_age_s", 0.0), age
                )
        return {
            "schema": sink.SCHEMA_VERSION,
            "label": "healthz",
            "ts": round(time.time(), 3),
            "remotes": remotes,
            "cycles": cycles,
            # empty until a FleetDaemon publishes — the key is always
            # present so scrapers can probe daemon liveness uniformly
            "daemon": daemon,
        }


# ------------------------------------------------------- process default
_default: LiveTelemetryServer | None = None
_env_resolved = False
_state_lock = threading.Lock()


def configure(port: int | None, host: str = "127.0.0.1") -> "LiveTelemetryServer | None":
    """Start (or with ``None``, stop) the process-default server,
    overriding the ``CRDT_OBS_HTTP`` environment variable."""
    global _default, _env_resolved
    with _state_lock:
        if _default is not None:
            _default.stop()
        _default = None
        _env_resolved = True
        if port is not None:
            _default = LiveTelemetryServer(port=port, host=host)
            _default.start()
        return _default


def default_server() -> "LiveTelemetryServer | None":
    """The configured server, else one lazily started from
    ``CRDT_OBS_HTTP`` (resolved ONCE per process — a server is a bound
    socket, not a re-readable path), else None."""
    global _default, _env_resolved
    if _env_resolved:
        return _default
    with _state_lock:
        if _env_resolved:
            return _default
        import os

        raw = os.environ.get(ENV_VAR, "")
        _env_resolved = True
        if raw:
            host, _, port_s = raw.rpartition(":")
            try:
                srv = LiveTelemetryServer(
                    port=int(port_s), host=host or "127.0.0.1"
                )
                srv.start()
                _default = srv
            except (ValueError, OSError):
                logger.warning(
                    "CRDT_OBS_HTTP=%r: could not start the telemetry "
                    "server; live endpoint disabled", raw,
                )
        return _default


def shutdown() -> None:
    """Stop the default server (if any) — FINAL for this process: env
    resolution stays latched, so the next replication sample does not
    silently rebind the port the embedder just closed.  Re-enable with
    :func:`configure`."""
    global _default, _env_resolved
    with _state_lock:
        if _default is not None:
            _default.stop()
        _default = None
        _env_resolved = True


def _reset() -> None:
    """Test seam: shutdown AND re-arm env resolution, so a test can
    exercise the ``CRDT_OBS_HTTP`` lazy start from a clean slate."""
    global _default, _env_resolved
    with _state_lock:
        if _default is not None:
            _default.stop()
        _default = None
        _env_resolved = False


def publish(status: dict) -> None:
    """Feed one replication status to the default server.  The hook
    ``Core._sample_replication`` calls — a single global check when no
    server is configured, a lock-guarded dict store when one is."""
    srv = default_server()
    if srv is not None:
        srv.publish_health(status)


def publish_cycle(source: str, summary: dict) -> None:
    """Feed one service-cycle summary to the default server."""
    srv = default_server()
    if srv is not None:
        srv.publish_cycle(source, summary)


def publish_daemon(info: dict) -> None:
    """Feed the fleet daemon's control-plane health to the default
    server (the no-server case is one global check, as for publish)."""
    srv = default_server()
    if srv is not None:
        srv.publish_daemon(info)

"""Storage port: abstract persistence over four object families.

Mirrors the reference Storage trait (crdt-enc/src/storage.rs:8-43): local
meta (one mutable blob), remote metas / states (immutable content-addressed
blobs), and per-actor op logs (immutable, densely version-numbered files).

Contracts carried over:
* ``load_ops`` returns each actor's ops **ordered by version** starting at
  the requested first version, with no gaps (storage.rs:36).
* names returned by list/store are opaque strings; stores of metas/states
  are content-addressed so rewrites are idempotent.
* ``remove_ops`` removes **all versions ≤ the given last version** per actor
  — the "everything up to" semantics the reference intended but didn't
  implement (SURVEY.md §3.4 defect 2; storage.rs:42 ``actor_last_verions``).

Missing directories/objects are treated as empty/None, never as errors
(crdt-enc-tokio/src/lib.rs:376-401) — a replica may simply not have synced
yet.
"""

from __future__ import annotations

from abc import ABC, abstractmethod

from ..models.vclock import Actor


class Storage(ABC):
    # -- local meta (mutable, private to this replica) ---------------------
    @abstractmethod
    async def load_local_meta(self) -> bytes | None: ...

    @abstractmethod
    async def store_local_meta(self, data: bytes) -> None: ...

    # -- local fold checkpoint (mutable, private, a pure CACHE) ------------
    # The warm-open resume point (core.py save_checkpoint): one sealed
    # blob per replica holding the materialized state + ingest cursor.
    # Contract: strictly local (never synced, never GC'd by remote
    # compaction), atomic (readers see the old blob or the new one,
    # never a torn mix — fs backends write tmp + fsync + rename), and
    # DISPOSABLE — the core verifies every load and falls back to a cold
    # refold on any mismatch, so a backend may drop the blob at any
    # time.  These defaults implement "no local cache": loads miss,
    # stores are no-ops — a storage backend without durable local
    # scratch simply always opens cold.
    async def load_local_checkpoint(self) -> bytes | None:
        return None

    async def store_local_checkpoint(self, data: bytes) -> None:
        pass

    async def remove_local_checkpoint(self) -> None:
        pass

    # -- remote metas (immutable, content-addressed) -----------------------
    @abstractmethod
    async def list_remote_meta_names(self) -> list[str]: ...

    @abstractmethod
    async def load_remote_metas(self, names: list[str]) -> list[tuple[str, bytes]]:
        """Missing names are silently skipped (concurrent compaction may
        have removed them)."""

    @abstractmethod
    async def store_remote_meta(self, data: bytes) -> str: ...

    @abstractmethod
    async def remove_remote_metas(self, names: list[str]) -> None: ...

    # -- states (immutable full-state snapshots, content-addressed) --------
    @abstractmethod
    async def list_state_names(self) -> list[str]: ...

    @abstractmethod
    async def load_states(self, names: list[str]) -> list[tuple[str, bytes]]: ...

    @abstractmethod
    async def store_state(self, data: bytes) -> str: ...

    @abstractmethod
    async def remove_states(self, names: list[str]) -> None: ...

    # -- op logs (immutable, per-actor, versioned 1,2,3,…) -----------------
    @abstractmethod
    async def list_op_actors(self) -> list[Actor]: ...

    @abstractmethod
    async def load_ops(
        self, actor_first_versions: list[tuple[Actor, int]]
    ) -> list[tuple[Actor, int, bytes]]:
        """For each (actor, first), every stored op file with
        version ≥ first, in version order per actor (scan until the first
        missing version, tolerating none at all)."""

    async def iter_op_chunks(
        self,
        actor_first_versions: list[tuple[Actor, int]],
        max_bytes: int = 64 << 20,
    ):
        """Async-iterate op files in bounded chunks — the feed for the
        core's pipelined bulk ingest (read of chunk i+1 overlaps decrypt +
        fold of chunk i, host memory bounded by ~max_bytes per stage).

        Yields lists of ``(actor, version, raw)``; concatenated, the lists
        must equal ``load_ops`` of the same request (per-actor version
        order holds ACROSS chunks; a chunk may end mid-actor).  This base
        implementation degrades to one ``load_ops`` chunk — backends with
        real IO (fs) override it with incremental scans."""
        chunk = await self.load_ops(actor_first_versions)
        if chunk:
            yield chunk

    async def stat_ops(
        self, actor_first_versions: list[tuple[Actor, int]]
    ) -> list[tuple[Actor, int, int]]:
        """Like ``load_ops`` but returns ``(actor, version, nbytes)`` —
        sizes without content, the replication-status backlog probe
        (obs/replication.py).  Same dense-scan contract as ``load_ops``.
        This base implementation degrades to loading (correct anywhere);
        backends with cheap metadata (fs: stat, memory: dict walk)
        override it so status sampling never reads op payloads."""
        return [
            (actor, version, len(raw))
            for actor, version, raw in await self.load_ops(
                actor_first_versions
            )
        ]

    @abstractmethod
    async def store_ops(self, actor: Actor, version: int, data: bytes) -> None: ...

    @abstractmethod
    async def remove_ops(self, actor_last_versions: list[tuple[Actor, int]]) -> None:
        """Remove every op file with version ≤ last for each actor."""

    # -- delta snapshots (immutable, per-sealer, versioned 1,2,3,…) --------
    # The delta-state replication family (docs/delta.md): each compacting
    # replica keeps a small versioned log of sealed delta snapshots next
    # to its op log.  Contract differences from the op family, both
    # deliberate: ``load_deltas`` returns every version ≥ first that
    # EXISTS, sorted, tolerating leading holes (prefix GC is routine and
    # chain validity is established by the payload's base-name links,
    # not by density); and the whole family is OPTIONAL — these defaults
    # implement "no delta support" (``has_deltas`` False, loads empty,
    # stores/removes no-ops), under which producers seal no deltas and
    # consumers read full snapshots, exactly the pre-delta behavior.
    has_deltas = False

    async def list_delta_actors(self) -> list[Actor]:
        return []

    async def load_deltas(
        self, actor_first_versions: list[tuple[Actor, int]]
    ) -> list[tuple[Actor, int, bytes]]:
        """Every stored delta with version ≥ first, sorted by version
        per actor (leading/interior holes skipped, not scanned-to)."""
        return []

    async def store_delta(self, actor: Actor, version: int, data: bytes) -> None:
        """Publish one immutable delta file.  Must raise
        ``FileExistsError`` on a version collision (the producer probes
        forward, the op-file discipline)."""

    async def remove_deltas(
        self, actor_last_versions: list[tuple[Actor, int]]
    ) -> None:
        """Remove every delta with version ≤ last for each actor."""

    # -- lifecycle ---------------------------------------------------------
    async def init(self, core) -> None:
        """Called once at open with the core handle (plugins may call back,
        cf. CoreSubHandle, reference lib.rs:286-290)."""

    async def set_remote_meta(self, meta) -> None:
        """This plugin's converged config blob changed (an MVReg of opaque
        VersionBytes, reference lib.rs:596-609).

        Delivery-order contract: concurrent ``read_remote`` calls may
        deliver register snapshots out of order.  The register is a CRDT —
        implementations must MERGE it into their own copy (stale snapshots
        then converge to no-ops), never replace state with it."""

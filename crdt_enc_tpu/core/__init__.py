from .adapters import (
    CrdtAdapter,
    HostAccelerator,
    empty_adapter,
    gcounter_adapter,
    lwwmap_adapter,
    mvreg_adapter,
    orset_adapter,
    pncounter_adapter,
)
from .core import (
    Core,
    CoreError,
    Info,
    LocalMeta,
    MissingKeyError,
    OpenOptions,
    OpOrderError,
    RemoteMeta,
    StateWrapper,
)
from .cryptor import Cryptor
from .key_cryptor import DanglingLatestKey, Key, KeyCryptor, Keys
from .storage import Storage

__all__ = [
    "Core",
    "CoreError",
    "CrdtAdapter",
    "Cryptor",
    "DanglingLatestKey",
    "HostAccelerator",
    "Info",
    "Key",
    "KeyCryptor",
    "Keys",
    "LocalMeta",
    "MissingKeyError",
    "OpenOptions",
    "OpOrderError",
    "RemoteMeta",
    "StateWrapper",
    "Storage",
    "empty_adapter",
    "gcounter_adapter",
    "lwwmap_adapter",
    "mvreg_adapter",
    "orset_adapter",
    "pncounter_adapter",
]

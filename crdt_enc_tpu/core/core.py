"""The core runtime: open / apply_ops / read_remote / compact.

Rebuilds the reference Core (crdt-enc/src/lib.rs:189-775) around the same
lifecycle and invariants:

* **three-layer wire format** on every op and state file — inner
  ``VersionBytes(data_version, msgpack payload)``, middle cipher envelope
  from the Cryptor, outer ``VersionBytes(container_version, …)`` (the ops
  path's coherent nesting, lib.rs:670-695).  The reference's compacted
  states used an inconsistent layering and could not be read back
  (SURVEY.md §3.4 defect 1); here states use the exact ops-path scheme.
* **writer serialization**: one async lock around apply_ops
  (lib.rs:196,668), and the LockBox discipline — mutable core data is only
  touched in sync sections, never across an await (utils/mod.rs:165-195).
* **ordered op ingestion** with concurrent-read tolerance: op files apply in
  version order per actor; an already-applied version is skipped, a gap is a
  hard error (lib.rs:519-531).
* **crash safety by ordering**: new content-addressed writes land (fsync'd)
  before old files are removed, in compact and metadata rewrite
  (lib.rs:362-369, 653-661).
* **complete op GC**: compaction removes every op file the snapshot covers
  (≤ last applied version per actor), fixing SURVEY.md §3.4 defect 2.

The hot fold/merge paths go through a pluggable accelerator (host loop or
TPU kernels) — see crdt_enc_tpu/core/adapters.py and parallel/accel.py.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import os
import time
import uuid
from dataclasses import dataclass, field

from ..models import MVReg, ORSet, VClock
from ..utils.lockbox import LockBox
from ..models.vclock import Actor, Dot
from ..utils import VersionBytes, codec, trace
from ..utils.versions import (
    CURRENT_CONTAINER_VERSION,
    SUPPORTED_CONTAINER_VERSIONS,
)
from .adapters import CrdtAdapter, HostAccelerator
from .cryptor import Cryptor
from .key_cryptor import Key, KeyCryptor, Keys
from .storage import Storage

IO_CONCURRENCY = 16  # bounded pipeline width (reference lib.rs:452,512)
BULK_MIN_FILES = 16  # below this the per-file asyncio path is cheaper
BULK_STREAM_CHUNK = 16384  # files per decrypt-lookahead chunk (bulk ingest)

# local fold-checkpoint payload formats (docs/checkpointing.md)
CHECKPOINT_FMT_OBJ = 0  # adapter.state_to_obj (any CRDT type)
CHECKPOINT_FMT_ORSET = 1  # ops/columnar.py orset_pack_checkpoint

logger = logging.getLogger("crdt_enc_tpu.core")


class CoreError(Exception):
    pass


class MissingKeyError(CoreError):
    """No usable data key (key management not initialized)."""


class OpOrderError(CoreError):
    """An op file arrived beyond the expected next version — the storage
    layer violated the gap-free ordering contract (lib.rs:527-531)."""


class IngestDecryptError(CoreError):
    """EVERY blob of a multi-file ingest batch failed to open — that is
    indistinguishable from a dead cryptor backend or damaged key
    material, so instead of quarantining the whole backlog (a replica
    that silently stops converging behind warnings), the read aborts
    loudly with the last underlying error as ``__cause__``.  Nothing
    was ingested and no cursor moved: retry after the repair.  A
    single damaged file still quarantines — per-file damage is exactly
    what the quarantine path exists for."""


class StaleWriterError(CoreError):
    """A reopened producer could not re-learn its own durable history
    (its op files — or a snapshot covering them — have not synced back),
    so writing now would mint event identifiers (Orswot dots) already
    used by pre-crash events.  Two different events with one identity is
    the one thing a CRDT cannot reconcile: replicas diverge permanently
    (simulator-discovered; shrunk repro
    ``tests/data/sim/dot_reuse_crash_reopen.json``).  Retry once the
    remote has synced."""


class _Quarantined:
    """Sentinel standing in a clears/payloads list for a synced file
    whose decrypt or decode failed: the file is SKIPPED (quarantined),
    never folded, and — critically — the ingest cursor is NOT advanced
    past it, so a later repaired sync retries it.  One damaged file
    must not abort a whole read (the passively synced directory tears
    files routinely); an op quarantine also ends its actor's dense run
    for this pass (nothing past the hole may fold).  Unknown sealing
    keys stay LOUD (:class:`MissingKeyError`) — that is a sync-state
    error the caller must see, not file damage."""

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return "<quarantined>"


_QUARANTINED = _Quarantined()


@dataclass
class LocalMeta:
    """Private per-replica identity + durable producer cursor.

    ``last_op_version`` is the highest op-file version this replica has ever
    written.  The reference keeps this cursor only in memory, so a write
    after reopen (before read_remote) silently lands at a version consumers'
    dense scans have already passed — the cursor is persisted here instead
    (reference LocalMeta holds just the actor id, lib.rs:734-737)."""

    local_actor_id: bytes
    last_op_version: int = 0
    # highest delta-snapshot version this replica ever sealed (the delta
    # log is version-addressed like the op log, docs/delta.md); absent
    # in pre-delta metas, so readers default to 0
    last_delta_version: int = 0
    # highest keys-ORSet dot counter this replica ever minted — the
    # durable cursor behind the key-register dot-reuse guard in
    # _install_new_key (simulator-discovered, same class as the op-log
    # dot reuse: tests/data/sim/key_dot_reuse_partial_meta.json)
    last_key_dot: int = 0

    def to_obj(self):
        return {
            b"actor": self.local_actor_id,
            b"last_op": self.last_op_version,
            b"last_delta": self.last_delta_version,
            b"last_key": self.last_key_dot,
        }

    @classmethod
    def from_obj(cls, obj) -> "LocalMeta":
        return cls(
            bytes(obj[b"actor"]),
            int(obj.get(b"last_op", 0)),
            int(obj.get(b"last_delta", 0)),
            int(obj.get(b"last_key", 0)),
        )


@dataclass
class RemoteMeta:
    """CRDT-of-CRDTs: one opaque MVReg config slot per plugin port
    (reference lib.rs:745-764) — the convergent "LUKS header"."""

    storage: MVReg = field(default_factory=MVReg)
    cryptor: MVReg = field(default_factory=MVReg)
    key_cryptor: MVReg = field(default_factory=MVReg)

    def merge(self, other: "RemoteMeta") -> None:
        self.storage.merge(other.storage)
        self.cryptor.merge(other.cryptor)
        self.key_cryptor.merge(other.key_cryptor)

    def to_obj(self):
        return {
            b"s": self.storage.to_obj(),
            b"c": self.cryptor.to_obj(),
            b"k": self.key_cryptor.to_obj(),
        }

    @classmethod
    def from_obj(cls, obj) -> "RemoteMeta":
        return cls(
            MVReg.from_obj(obj.get(b"s")),
            MVReg.from_obj(obj.get(b"c")),
            MVReg.from_obj(obj.get(b"k")),
        )

    def is_empty(self) -> bool:
        return (
            self.storage.is_empty()
            and self.cryptor.is_empty()
            and self.key_cryptor.is_empty()
        )


@dataclass
class StateWrapper:
    """A full-state snapshot: the CRDT value + the op-log cursor (VClock of
    last applied op-file versions — the resume point, lib.rs:740-743).

    On the wire a snapshot payload is ``[state, cursor]`` or (since the
    replication-observability layer) ``[state, cursor, sealer_actor]`` —
    the sealing replica's id, which lets readers attribute the cursor to
    a replica and maintain the cursor matrix behind the causal stability
    watermark (obs/replication.py).  Readers tolerate both lengths, so
    pre-existing remotes stay readable and old *core* readers (which
    index ``[0]``/``[1]``) never notice the extra element — but the
    pre-replication ``tools/fsck`` hard-checks ``len == 2`` and reports
    every 3-element snapshot as corruption, so upgrade fsck installs
    before producers start sealing the 3-form."""

    state: object
    next_op_versions: VClock


def snapshot_sealer(obj) -> bytes | None:
    """The validated sealer id from a decoded snapshot wrapper, or
    ``None`` when absent or malformed — the single encoding of the
    sealer wire rule (16-byte actor id in slot 2).  The type check
    matters: ``bytes(16)`` would coerce an integer into 16 NUL bytes —
    a phantom all-zero replica.  Core ingest silently drops what this
    rejects (observational, never a read failure); fsck reports it."""
    sealer = obj[2] if len(obj) > 2 else None
    if (
        isinstance(sealer, (bytes, bytearray, memoryview))
        and len(sealer) == 16
    ):
        return bytes(sealer)
    return None


@dataclass
class Info:
    """Observability snapshot (reference Info, lib.rs:766-775)."""

    local_actor_id: bytes
    next_op_versions: VClock
    read_states: frozenset
    has_latest_key: bool


@dataclass
class OpenOptions:
    """Configuration-as-code (reference OpenOptions, lib.rs:725-732)."""

    storage: Storage
    cryptor: Cryptor
    key_cryptor: KeyCryptor
    adapter: CrdtAdapter
    supported_data_versions: tuple
    current_data_version: bytes
    create: bool = False
    accelerator: object = field(default_factory=HostAccelerator)
    # local fold checkpoints (docs/checkpointing.md): with ``checkpoint``
    # on, compact() seals a warm-open resume point through the storage
    # port's local-checkpoint slot and open() restores it after
    # verification (falling back to the cold refold on any mismatch).
    # ``checkpoint_on_read`` additionally reseals after every
    # read_remote() — for pure-consumer replicas that never compact.
    checkpoint: bool = True
    checkpoint_on_read: bool = False
    # delta-state replication (docs/delta.md): with ``delta`` on and a
    # storage backend that has the delta family, compact() additionally
    # seals a delta snapshot since this replica's previous snapshot, and
    # read_remote() prefers folding ``known-base + delta chain`` over
    # re-reading full snapshots (automatic traced fallback on any gap,
    # GC'd link, or fingerprint doubt).  ``CRDT_DELTA=0`` force-disables.
    delta: bool = True
    # strong-read membership policy (docs/strong_reads.md): an explicit
    # crdt_enc_tpu.read.MembershipPolicy pinning the watermark
    # denominator (expected replicas, silence decay).  None = the
    # observed-replica denominator, the PR-6 watermark math unchanged.
    membership: object | None = None


async def open_sealed_blob(
    keys: Keys, cryptor: Cryptor, raw: bytes, supported_data_versions=None
):
    """Unwrap one three-layer sealed blob (the single implementation of
    the wire contract — the core and the fsck tool both go through here,
    so the two can never drift).  ``supported_data_versions=None`` skips
    the inner app-version check (diagnostic callers that do not know the
    application's version set)."""
    outer = VersionBytes.deserialize(raw).ensure_versions(
        SUPPORTED_CONTAINER_VERSIONS
    )
    key_id, middle = codec.unpack(outer.content)
    key = keys.get_key(bytes(key_id))
    if key is None:
        raise MissingKeyError(
            f"blob sealed with unknown key {uuid.UUID(bytes=bytes(key_id))}; "
            "key metadata may not have synced yet"
        )
    clear = await cryptor.decrypt(key.material, bytes(middle))
    inner = VersionBytes.deserialize(clear)
    if supported_data_versions is not None:
        inner.ensure_versions(supported_data_versions)
    return codec.unpack(inner.content)


def unpack_checkpoint_state(adapter, fmt: int, st):
    """Decode a checkpoint's state payload — the ONE implementation of
    the format dispatch (the core's warm open and ``tools/fsck
    --verify-checkpoint`` both go through here, so a new format can
    never be readable by one and 'unknown' to the other)."""
    if fmt == CHECKPOINT_FMT_ORSET:
        from ..ops.columnar import orset_unpack_checkpoint

        return orset_unpack_checkpoint(st)
    if fmt == CHECKPOINT_FMT_OBJ:
        return adapter.state_from_obj(st)
    raise CoreError(f"unknown checkpoint format {fmt!r}")


class _MutData:
    """All mutable core state.  LockBox discipline: methods touching this
    must be synchronous (asyncio makes sync sections atomic); the only
    cross-await exclusion is the writer lock in apply_ops."""

    def __init__(self, state):
        self.state = state
        self.next_op_versions = VClock()
        self.read_states: set[str] = set()
        self.read_metas: set[str] = set()
        self.remote_meta = RemoteMeta()
        self.keys = Keys()
        # cursor matrix: other replicas' last PUBLISHED ingest cursors,
        # learned from the sealer id + cursor each compacted snapshot
        # carries (obs/replication.py).  Monotone (clocks only merge) and
        # purely observational — convergence never depends on it.
        self.cursor_matrix: dict[Actor, VClock] = {}
        # delta-chain consumption cursor: per sealer, the highest delta
        # version already scanned (applied OR skipped) — the next read
        # loads only past it, and compaction GCs the consumed prefix
        self.read_deltas: dict[Actor, int] = {}


class Core:
    """One replica's runtime.  Construct via ``Core.open``."""

    def __init__(self, opts: OpenOptions):
        self.storage = opts.storage
        self.cryptor = opts.cryptor
        self.key_cryptor = opts.key_cryptor
        self.adapter = opts.adapter
        self.accel = opts.accelerator
        self.supported_data_versions = tuple(sorted(opts.supported_data_versions))
        self.current_data_version = opts.current_data_version
        self._data = _MutData(opts.adapter.new())
        self._apply_lock = asyncio.Lock()
        self._meta_lock = asyncio.Lock()
        # Serializes every keys read-copy-write against remote-meta
        # ingestion: the key cryptor's register write happens AFTER its
        # (possibly slow, e.g. scrypt) protect step, so without exclusion a
        # Keys value merged during that await would be causally superseded
        # by a write built from a stale snapshot — losing key material.
        # Lock order: _keys_lock → _meta_lock (never the reverse).
        self._keys_lock = asyncio.Lock()
        self._local_meta: LocalMeta | None = None
        self._checkpoint_enabled = opts.checkpoint
        self._checkpoint_on_read = opts.checkpoint_on_read
        self._checkpoint_sig: tuple | None = None  # last sealed resume point
        # warm-open observability: did open() restore a checkpoint, and
        # if not (one existed but was rejected), why
        self.opened_from_checkpoint = False
        self.checkpoint_fallback_reason: str | None = None
        # replication-status sampling (obs/replication.py) runs on every
        # open/read_remote/compact unless opted out; the last computed
        # status is kept for callers that want the full dict
        self._repl_sample = os.environ.get("CRDT_REPL_SAMPLE", "") != "0"
        self.last_replication_status: dict | None = None
        # memoized _remote_id; dropped by every remote-meta merge site
        self._remote_id_cache: bytes | None = None
        # delta-state replication (docs/delta.md): the retained base —
        # the last snapshot THIS replica sealed, as its canonical packed
        # state bytes + name + cursor obj — is what the next compaction
        # diffs against.  Bytes, not a live object: snapshot objs may
        # alias mutable state dicts (the serve path's plane writeback).
        self._delta_enabled = (
            opts.delta and os.environ.get("CRDT_DELTA", "") != "0"
        )
        self._delta_verify = os.environ.get("CRDT_DELTA_VERIFY", "") != "0"
        self._delta_base: dict | None = None
        self.last_delta_fallback_reason: str | None = None
        # seal signature of the last _compact_seal (cursor + read sets +
        # mutation epoch at snapshot time): the serving layer's
        # no-op-cycle detector — when a quiet tenant's signature has not
        # moved, re-sealing would publish the identical snapshot, so the
        # whole seal/GC/checkpoint tail can be skipped honestly
        # (docs/multitenant.md "cycle-cost law")
        self._last_seal_sig: tuple | None = None
        # writer-side dot-reuse guard (_ensure_own_history): the first
        # write of this incarnation probes for un-refolded own history
        self._own_history_checked = False
        # strong-read tier (docs/strong_reads.md): the stable prefix is
        # created lazily on the first linearizable read (or restored
        # from the warm-open checkpoint's observational b"sp" slot), so
        # eventual-only replicas pay nothing for it
        self._membership = opts.membership
        self._stable = None

    # ------------------------------------------------------------------ open
    @classmethod
    async def open(cls, opts: OpenOptions) -> "Core":
        core = cls(opts)
        # warm the native libraries off-loop before the first codec.pack
        # below can reach them: the build-on-demand loader runs `make`
        # once per process, and that subprocess must never run on the
        # event loop (ASY001).  warm() memoizes failure too, so after
        # this every load()/load_state() probe is a cached dict hit.
        from .. import native

        await asyncio.to_thread(native.warm)
        raw = await core.storage.load_local_meta()
        if raw is None:
            if not opts.create:
                raise CoreError(
                    "no local replica metadata; open with create=True to join"
                )
            core._local_meta = LocalMeta(uuid.uuid4().bytes)
            vb = VersionBytes(
                CURRENT_CONTAINER_VERSION, codec.pack(core._local_meta.to_obj())
            )
            await core.storage.store_local_meta(vb.serialize())
        else:
            vb = VersionBytes.deserialize(raw).ensure_versions(
                SUPPORTED_CONTAINER_VERSIONS
            )
            core._local_meta = LocalMeta.from_obj(codec.unpack(vb.content))

        # plugins capture the core handle (CoreSubHandle, lib.rs:286-290)
        await asyncio.gather(
            core.storage.init(core),
            core.cryptor.init(core),
            core.key_cryptor.init(core),
        )
        # pull converged metadata; force-notify so plugins initialize even
        # from an empty remote (lib.rs:292)
        await core._read_remote_meta(force_notify=True)

        # bootstrap the first data key if key management has none yet
        if core._data.keys.latest_key() is None:
            await core._install_new_key()
            if core._data.keys.latest_key() is None:
                raise MissingKeyError(
                    "key cryptor did not install a latest key at open"
                )
        if opts.checkpoint:
            await core._open_from_checkpoint()
        # replication status at open: the backlog gauge here is the
        # answer to "how much will the first read_remote have to fold?"
        await core._sample_replication()
        return core

    # -------------------------------------------------------------- identity
    @property
    def actor_id(self) -> Actor:
        assert self._local_meta is not None
        return self._local_meta.local_actor_id

    def info(self) -> Info:
        d = self._data
        return Info(
            self.actor_id,
            d.next_op_versions.copy(),
            frozenset(d.read_states),
            d.keys.latest_key() is not None,
        )

    def with_state(self, fn):
        """Run ``fn(state)`` synchronously under the data-lock discipline —
        the way applications build ops against current state
        (reference lib.rs:325-330).  The LockBox mechanism
        (utils/lockbox.py) enforces the discipline at runtime: ``fn`` gets
        a revocable borrow, so a retained state reference used after the
        section (the Python shape of holding the lock across an await)
        raises instead of racing; awaitable returns are rejected."""
        return LockBox(self._data.state).with_(fn)

    # ------------------------------------------------------- replication obs
    async def replication_status(self, *, _backlog: list | None = None) -> dict:
        """This replica's replication/convergence status: the causal
        stability watermark, per-actor op backlog (files + bytes past
        the local cursor, sized without reading — ``Storage.stat_ops``),
        divergence vs. everything known to exist, and checkpoint
        staleness.  Pure observation — no state is mutated, no op
        payload is read, and the math lives in
        :func:`crdt_enc_tpu.obs.replication.compute_status` (exactly
        unit-tested); this method only gathers its inputs.  The result
        is byte-stable under ``json.dumps(..., sort_keys=True)`` for a
        given replica state.

        ``_backlog`` is the post-ingest fast path: read_remote just
        folded everything its own listing found, so its sample passes
        ``[]`` instead of paying a second per-actor storage probe on
        the polling hot path (ops sealed concurrently with the fold
        surface in the next sample)."""
        from ..obs import replication

        with trace.span("repl.status"):
            d = self._data
            if _backlog is None:
                actors = await self.storage.list_op_actors()
                wanted = [
                    (a, d.next_op_versions.get(a) + 1) for a in sorted(actors)
                ]
                backlog = (
                    await self.storage.stat_ops(wanted) if wanted else []
                )
            else:
                backlog = _backlog
            # sync section: clocks snapshot + compute, no await between
            ckpt = self._checkpoint_sig
            status = replication.compute_status(
                self.actor_id,
                d.next_op_versions.copy(),
                {a: c.copy() for a, c in d.cursor_matrix.items()},
                backlog,
                self._remote_id(),
                dict(ckpt[0]) if ckpt is not None else None,
                self._checkpoint_enabled,
            )
            if self._membership is not None:
                # the strong-read membership policy's loud surface: who
                # the watermark denominator excludes rides with every
                # status into /healthz and obs_report fleet (the key is
                # absent without a configured policy, so the PR-6
                # byte-stability contract is unchanged for everyone
                # else)
                status["membership"] = self._membership.summary()
        self.last_replication_status = status
        return status

    async def _sample_replication(
        self, *, _backlog: list | None = None
    ) -> dict | None:
        """Status → registered gauges (obs.replication.sample) on every
        open / read_remote / compact; ``CRDT_REPL_SAMPLE=0`` opts out.
        Observability must never kill the run it observes: a failed
        probe logs at debug and samples nothing."""
        if not self._repl_sample:
            return None
        from ..obs import replication

        try:
            status = await self.replication_status(_backlog=_backlog)
        except Exception:
            logger.debug("replication status sampling failed", exc_info=True)
            return None
        replication.sample(status)
        # freshness-SLO gauges + live /healthz publication: both are
        # no-ops-with-one-check unless opted in (CRDT_OBS_HTTP / a
        # configured server), and neither may kill the run it observes
        try:
            from ..obs import live as obs_live
            from ..obs import slo as obs_slo

            obs_slo.sample_freshness(status)
            obs_live.publish(status)
        except Exception:
            logger.debug("slo/live sampling failed", exc_info=True)
        return status

    # ------------------------------------------------------------ strong reads
    def _strong(self):
        """The lazily-created stable prefix (docs/strong_reads.md)."""
        if self._stable is None:
            from ..read.stable import StablePrefix

            self._stable = StablePrefix(self.adapter)
        return self._stable

    async def stable_prefix(self, *, refresh: bool = True):
        """Advance the stable prefix to the current (policy-adjusted)
        stability watermark and return its
        :class:`~crdt_enc_tpu.read.stable.StableView`.  With ``refresh``
        (default), ``read_remote()`` runs first so the watermark
        reflects the latest published cursors; ``refresh=False`` trusts
        current knowledge (the fold service's post-cycle reads, polling
        loops that just ingested).  Monotone: the returned frontier
        never regresses within an incarnation."""
        from ..read.stable import (
            StableView, effective_watermark, find_holdouts,
        )

        if refresh:
            await self.read_remote()
        prefix = self._strong()
        wm, union, replicas, excluded = effective_watermark(
            self, policy=self._membership
        )
        await prefix.advance(self, wm)
        # sync summary section
        lag = sum(
            c - prefix.cursor.get(a)
            for a, c in union.counters.items()
            if c > prefix.cursor.get(a)
        )
        wm_lag = sum(
            c - wm.get(a, 0) for a, c in union.counters.items()
        )
        view = StableView(
            cursor=prefix.cursor.copy(),
            watermark=dict(wm),
            lag=lag,
            watermark_lag=wm_lag,
            excluded=tuple(sorted(a.hex() for a in excluded)),
            holdouts=tuple(find_holdouts(self, wm, union, replicas)),
            wedged={a.hex(): r for a, r in sorted(prefix.wedged.items())},
        )
        trace.gauge("read_stable_lag", lag)
        return view

    async def read(
        self,
        *,
        linearizable: bool = False,
        max_lag: int | None = None,
        min_cursor: VClock | None = None,
        refresh: bool = True,
    ):
        """Read this replica's value.  ``linearizable=False`` (default)
        is the eventual tier: the live state's object form, free, no
        guarantee beyond CRDT convergence.  ``linearizable=True``
        answers from the stable prefix — a fold every denominator
        replica provably holds — refusing honestly
        (:class:`~crdt_enc_tpu.read.StalenessError`) when the caller's
        constraints cannot be met: ``max_lag`` bounds how many versions
        the union may be ahead of the served frontier
        (``lag_exceeded``), ``min_cursor`` demands coverage of a target
        clock, e.g. the caller's own last write (``uncovered_target``).
        There is no silent fallback tier: callers that can accept
        eventual values on refusal catch the error and re-read with
        ``linearizable=False`` — the two consistencies never mix
        implicitly."""
        from ..read.stable import ReadResult, StalenessError

        if not linearizable:
            if max_lag is not None or min_cursor is not None:
                # staleness constraints are strong-read-only; silently
                # dropping one would hand back an eventual value the
                # caller explicitly bounded — the implicit tier mix
                # this API promises never happens
                raise ValueError(
                    "max_lag/min_cursor require linearizable=True"
                )
            d = self._data
            return ReadResult(
                obj=self.adapter.state_to_obj(d.state),
                consistency="eventual",
                cursor=d.next_op_versions.copy(),
            )
        with trace.span("read.strong"):
            trace.add("read_strong_total", 1)
            view = await self.stable_prefix(refresh=refresh)
            status = {
                "watermark": {a.hex(): c for a, c in view.watermark.items()},
                "lag": view.lag,
                "watermark_lag": view.watermark_lag,
                "excluded": list(view.excluded),
                "holdouts": list(view.holdouts),
                "wedged": dict(view.wedged),
            }
            if min_cursor is not None and not view.covers(min_cursor):
                trace.add("read_strong_refusals", 1)
                raise StalenessError(
                    "uncovered_target",
                    "stable prefix does not cover the requested clock "
                    f"(holdouts: {', '.join(view.holdouts) or 'none'}); "
                    "await_stable() or retry later",
                    status=status,
                )
            if max_lag is not None and view.lag > max_lag:
                trace.add("read_strong_refusals", 1)
                raise StalenessError(
                    "lag_exceeded",
                    f"stable prefix lags the union by {view.lag} versions "
                    f"(> max_lag {max_lag}); holdouts: "
                    f"{', '.join(view.holdouts) or 'none'}"
                    + (
                        f"; policy excluded: {', '.join(view.excluded)}"
                        if view.excluded else ""
                    ),
                    status=status,
                )
            prefix = self._strong()
            return ReadResult(
                obj=self.adapter.state_to_obj(prefix.state),
                consistency="strong",
                cursor=view.cursor,
                view=view,
            )

    async def contains(self, member, **kw) -> bool:
        """Linearizable (or eventual) point membership lookup for
        set-shaped states.  Same keywords and refusal taxonomy as
        :meth:`read`; raises ``TypeError`` for states without a
        ``contains`` — honest refusal, not a guess."""
        state = await self._read_state(**kw)
        probe = getattr(state, "contains", None)
        if probe is None:
            raise TypeError(
                f"{type(state).__name__} has no membership lookup"
            )
        return bool(probe(member))

    async def value(self, **kw):
        """Linearizable (or eventual) point value lookup for
        value-shaped states (counters, registers).  Same keywords and
        refusal taxonomy as :meth:`read`."""
        state = await self._read_state(**kw)
        probe = getattr(state, "value", None)
        if probe is None:
            probe = getattr(state, "read", None)  # counters/registers
        if probe is None:
            raise TypeError(f"{type(state).__name__} has no value()")
        return probe() if callable(probe) else probe

    async def _read_state(self, *, linearizable: bool = False, **kw):
        """The live or stable STATE object behind the point lookups —
        read-only by contract."""
        if not linearizable:
            return self._data.state
        await self.read(linearizable=True, **kw)  # advances + enforces
        return self._strong().state

    async def await_stable(
        self,
        target: VClock,
        *,
        timeout_s: float = 30.0,
        poll_interval_s: float = 0.05,
        on_poll=None,
        clock=None,
    ):
        """The freshness-wait protocol: block until the stable prefix
        covers ``target`` (e.g. the caller's own last-write clock —
        read-your-writes made strong), re-reading the remote each poll
        so newly published cursors advance the watermark.  Returns the
        covering :class:`StableView`; raises
        :class:`~crdt_enc_tpu.read.StalenessError` (``timeout``) when
        ``timeout_s`` elapses first.  ``on_poll`` and ``clock`` are the
        determinism seams: the simulator paces with sync ticks and a
        counted clock so waits replay bit-for-bit; production uses the
        defaults (asyncio sleep, monotonic time)."""
        from ..read.stable import StalenessError

        clock = clock if clock is not None else time.monotonic
        t0 = clock()
        trace.add("read_await_total", 1)
        with trace.span("read.await"):
            refresh = False  # first pass reuses current knowledge
            while True:
                view = await self.stable_prefix(refresh=refresh)
                if view.covers(target):
                    return view
                refresh = True
                if clock() - t0 >= timeout_s:
                    trace.add("read_await_timeouts", 1)
                    raise StalenessError(
                        "timeout",
                        f"watermark did not cover the target within "
                        f"{timeout_s}s; holdouts: "
                        f"{', '.join(view.holdouts) or 'none'}",
                        status={"holdouts": list(view.holdouts),
                                "excluded": list(view.excluded)},
                    )
                if on_poll is not None:
                    await on_poll()
                else:
                    await asyncio.sleep(poll_interval_s)

    # ----------------------------------------------------------- key rotation
    async def _install_new_key(self) -> Key:
        """Generate a key, add it to the Keys CRDT as the new latest, and
        push through the key cryptor — the snapshot→write cycle runs under
        ``_keys_lock`` so concurrent meta ingestion cannot be superseded
        by a stale snapshot.

        Key-register dot-reuse guard (simulator-discovered; shrunk repro
        ``tests/data/sim/key_dot_reuse_partial_meta.json``): a reopened
        replica whose own key-register write is not visible (a partially
        synced meta listing) would mint a keys-ORSet dot its pre-crash
        incarnation already spent on a DIFFERENT key — on merge the
        Orswot kills one of the two entries, losing key material, and
        when the latest-register tie-break lands on the killed id every
        subsequent open dies with ``DanglingLatestKey``.  The durable
        ``LocalMeta.last_key_dot`` cursor refuses the mint loudly
        (:class:`MissingKeyError`, retry after sync) whenever the
        observed keys clock trails it — the op-log
        :meth:`_ensure_own_history` discipline applied to the key
        register.  The cursor is persisted BEFORE the remote write, so
        no crash window can mint a colliding dot; the cost is that a
        crash between the two writes leaves a mint the cursor records
        but the remote never saw — that replica refuses further mints
        (rotation/bootstrap) until an operator intervenes, which is the
        safe side: a refused rotation is recoverable, fleet-wide key
        loss is not."""
        for attempt in (0, 1):
            async with self._keys_lock:
                keys = Keys.from_obj(self._data.keys.to_obj())
                expected = keys.keys.clock.get(self.actor_id) + 1
                lm = self._local_meta
                stale = lm is not None and expected <= lm.last_key_dot
                if not stale:
                    material = await self.cryptor.gen_key()
                    key = Key.new(material)
                    keys.insert_latest_key(self.actor_id, key)
                    if lm is not None and expected > lm.last_key_dot:
                        lm.last_key_dot = expected
                        vb = VersionBytes(
                            CURRENT_CONTAINER_VERSION,
                            codec.pack(lm.to_obj()),
                        )
                        await self.storage.store_local_meta(vb.serialize())
                    await self.key_cryptor.set_keys(keys)
            if not stale:
                break
            if attempt == 0:
                # our own register may simply not have been read yet
                # this incarnation — one refresh before refusing
                await self._read_remote_meta()
                continue
            raise MissingKeyError(
                "own key-register history (keys dot "
                f"{self._local_meta.last_key_dot}) is not yet visible on "
                "the remote; minting now would reuse a spent key dot"
            )
        if self._data.keys.get_key(key.id) is None:
            raise MissingKeyError("key cryptor did not install the new key")
        return key

    async def rotate_key(self) -> Key:
        """Generate and install a fresh data key as the new latest.

        The LUKS property the layered design exists for (reference
        README.md:19-25): rotation never re-encrypts data.  Blobs written
        before the rotation stay readable because every blob's outer layer
        records its sealing key id (see ``_seal``) and old keys remain in
        the Keys CRDT; everything written after seals with the new key.
        Converges to other replicas through the remote metadata like any
        key change.  Returns the new key.
        """
        return await self._install_new_key()

    # ------------------------------------------------------ fold checkpoints
    def _checkpoint_fingerprint(self) -> dict:
        """The warm-open validity seal (docs/checkpointing.md): a
        checkpoint is only installable into a replica whose adapter,
        identity, data version, key generation (latest data-key id —
        rotation invalidates) and converged remote metadata all match
        the sealing replica's.  The meta hash is over the canonical
        packed RemoteMeta, so any plugin-config or key-register change
        on the remote (including a wiped-and-recreated remote) forces a
        cold refold."""
        d = self._data
        latest = d.keys.latest_key()
        return {
            b"a": self.adapter.name,
            b"id": self.actor_id,
            b"dv": self.current_data_version,
            b"key": latest.id if latest is not None else b"",
            b"meta": self._remote_id(),
        }

    def _remote_id(self) -> bytes:
        """SHA3 of the canonical converged RemoteMeta — the stable
        identity of the remote this replica is attached to.  Doubles as
        the checkpoint fingerprint's meta hash and the ``remote_id`` the
        replication status / fleet aggregator group devices by.

        Cached: the hash is read several times per compaction (the
        checkpoint fingerprint + every replication sample — at fleet
        scale that is 3+ pack+SHA3 rounds per tenant per service
        cycle) while the RemoteMeta only changes on a meta merge; every
        merge site drops the cache."""
        if self._remote_id_cache is None:
            self._remote_id_cache = hashlib.sha3_256(
                codec.pack(self._data.remote_meta.to_obj())
            ).digest()
        return self._remote_id_cache

    def _pack_checkpoint_state(self):
        """(fmt, obj) for the current state: the packed-columnar ORSet
        encoding when it applies losslessly, else the adapter's generic
        object form (identical to the compacted-snapshot payload).

        A fresh streaming fold stashes its surviving rows on the state
        (``_ckpt_rows``, mut-epoch-guarded — ops/columnar.py
        ``_orset_fresh_fold_native``); when the state provably has not
        mutated since, the checkpoint packs straight from those rows —
        the zero-copy decode→planes tail, no dict walk (the solo twin
        of the fold service's planes-packed ``_packed`` path)."""
        state = self._data.state
        if type(state) is ORSet:
            from ..ops.columnar import (
                orset_pack_checkpoint, orset_pack_checkpoint_rows,
            )

            stash = getattr(state, "_ckpt_rows", None)
            if stash is not None:
                # consume the stash either way: a stale one (mutated
                # since the fold) is dead weight, and a used one has
                # served its purpose — without this the row arrays and
                # both vocab object lists stay pinned to the state for
                # its whole lifetime
                state._ckpt_rows = None
                if stash[0] == getattr(state, "_mut", None):
                    return (
                        CHECKPOINT_FMT_ORSET,
                        orset_pack_checkpoint_rows(*stash[1]),
                    )
            obj = orset_pack_checkpoint(state)
            if obj is not None:
                return CHECKPOINT_FMT_ORSET, obj
        return CHECKPOINT_FMT_OBJ, self.adapter.state_to_obj(state)

    def _unpack_checkpoint_state(self, fmt: int, st):
        return unpack_checkpoint_state(self.adapter, fmt, st)

    async def save_checkpoint(
        self, *, _packed: tuple | None = None, _snap: tuple | None = None
    ) -> bool:
        """Seal the materialized state + ingest cursor + read-states set
        as this replica's local warm-open checkpoint (sealed with the
        normal data-key cryptor, stored through the storage port's
        atomic local-checkpoint slot).  A later ``open`` restores it and
        ingests only op tails past the cursor — state-based CRDTs need
        no op log to resume (arXiv:1905.08733), so the persisted state +
        cursor is a complete, safe resume point.  Returns False when
        checkpointing is disabled on this core.

        ``_packed`` is the fold service's pre-packed state payload,
        ``(fmt, obj, mut_epoch)``: the service packs from the dense
        planes it already holds (no sparse walk), and the epoch guards
        staleness — if the state mutated since packing (a concurrent
        apply), the live state is re-packed here instead, so the sealed
        (state, cursor) pair can never tear.

        ``_snap`` is ``(snapshot_name, mut_epoch)`` from the compaction
        seal tail: when the live state PROVABLY still equals the just-
        sealed snapshot (same mutation epoch), the checkpoint records
        the snapshot's name (``b"snap"``), so a warm reopen can restore
        the delta-sealing base and keep its delta chain unbroken
        (docs/delta.md).  States without a mutation epoch never record
        it — a wrong base would seal wrong deltas, a missing one only
        costs consumers one full snapshot read."""
        if not self._checkpoint_enabled:
            return False
        with trace.span("checkpoint.save"):
            # sync section: every mutable input is materialized before
            # the first await, so a concurrent apply cannot tear the
            # (state, cursor) pair
            d = self._data
            if (
                _packed is not None
                and _packed[2] == getattr(d.state, "_mut", None)
            ):
                fmt, st = _packed[0], _packed[1]
            else:
                fmt, st = self._pack_checkpoint_state()
            sig = (
                dict(d.next_op_versions.counters), frozenset(d.read_states)
            )
            payload = {
                b"fmt": fmt,
                b"state": st,
                b"cursor": d.next_op_versions.to_obj(),
                b"rs": sorted(d.read_states),
                b"fp": self._checkpoint_fingerprint(),
                # the cursor matrix rides along so a warm open keeps its
                # replication view (stability watermark continuity);
                # observational only — never part of the fingerprint
                b"cm": {
                    a: c.to_obj() for a, c in sorted(d.cursor_matrix.items())
                },
                # delta-chain continuity (both observational): the
                # per-sealer delta consumption cursor, and — only when
                # the epoch proves state == sealed snapshot — its name
                b"rd": dict(sorted(d.read_deltas.items())),
            }
            if (
                self._stable is not None
                and self._stable.cursor.counters
            ):
                # the stable prefix only grows, so it is checkpointable
                # as-is (docs/strong_reads.md): a warm reopen resumes
                # the exposed strong-read frontier instead of
                # restarting the session guarantee from bottom.
                # Observational — never fingerprinted; a malformed slot
                # costs a cold prefix rebuild, never a wrong read.
                payload[b"sp"] = self._stable.to_obj()
            if (
                _snap is not None
                and _snap[1] is not None
                and _snap[1] == getattr(d.state, "_mut", None)
            ):
                payload[b"snap"] = _snap[0].encode()
            blob = await self._seal(payload)
            await self.storage.store_local_checkpoint(blob)
            self._checkpoint_sig = sig  # only a DURABLE seal gates skips
            trace.add("checkpoint_bytes", len(blob))
        return True

    async def _checkpoint_fallback(self, reason: str) -> bool:
        """Record WHY a present checkpoint was rejected (traced counter +
        reason attribute), drop the rejected blob (a cache that failed
        verification is dead weight every future open would re-parse —
        the next save reseals a valid one), and signal the cold path."""
        self.checkpoint_fallback_reason = reason
        trace.add("checkpoint_fallbacks", 1)
        logger.info(
            "local checkpoint rejected (%s); opening cold", reason
        )
        await self.storage.remove_local_checkpoint()
        return False

    @staticmethod
    def _fp_bytes(v) -> bytes | None:
        return bytes(v) if isinstance(v, (bytes, bytearray, memoryview)) else None

    async def _open_from_checkpoint(self) -> bool:
        """Restore the local fold checkpoint if one exists and verifies:
        decrypts under a known key, fingerprint current (adapter /
        actor / data version / key generation / remote-meta hash), and
        the cursor still traceable against the remote listing.  Any
        torn file, decrypt failure, or mismatch falls back to the cold
        refold with the reason traced — a checkpoint is a cache, never
        a source of truth."""
        raw = await self.storage.load_local_checkpoint()
        if raw is None:
            return False
        with trace.span("checkpoint.load"):
            try:
                obj = await self._open_sealed(raw)
            except Exception:
                logger.debug("checkpoint undecryptable", exc_info=True)
                return await self._checkpoint_fallback("unreadable")
            with trace.span("checkpoint.verify"):
                try:
                    fp = dict(obj[b"fp"])
                    fmt = int(obj[b"fmt"])
                    cursor = VClock.from_obj(obj[b"cursor"])
                    read_states = {str(n) for n in obj[b"rs"]}
                    cursor_matrix = {
                        bytes(a): VClock.from_obj(c)
                        for a, c in (obj.get(b"cm") or {}).items()
                    }
                    read_deltas = {
                        bytes(a): int(v)
                        for a, v in (obj.get(b"rd") or {}).items()
                    }
                except Exception:
                    logger.debug("checkpoint malformed", exc_info=True)
                    return await self._checkpoint_fallback("malformed")
                expected = self._checkpoint_fingerprint()
                for field_key, reason in (
                    (b"a", "adapter"),
                    (b"id", "actor"),
                    (b"dv", "data_version"),
                    (b"key", "key_rotation"),
                    (b"meta", "remote_meta"),
                ):
                    if self._fp_bytes(fp.get(field_key)) != expected[field_key]:
                        return await self._checkpoint_fallback(reason)
                # cursor ⊆ remote listing: every actor the checkpoint
                # claims folded must still have its op log listed, OR a
                # state snapshot must exist (compaction legitimately GCs
                # op logs into snapshots — whether it is one this
                # checkpoint folded or a superseding unread one, the
                # CvRDT merge of read_remote converges either way).  A
                # remote with neither — no cursor actors, no snapshots —
                # is not the remote this checkpoint came from.
                if cursor.counters:
                    op_actors = set(await self.storage.list_op_actors())
                    covered = set(cursor.counters) <= op_actors or bool(
                        await self.storage.list_state_names()
                    )
                    if not covered:
                        return await self._checkpoint_fallback("cursor")
                try:
                    state = self._unpack_checkpoint_state(fmt, obj[b"state"])
                except Exception:
                    logger.debug(
                        "checkpoint state undecodable", exc_info=True
                    )
                    return await self._checkpoint_fallback("malformed")
            # sync install section: the resume point becomes the live
            # replica state; read_remote ingests only past the cursor
            d = self._data
            d.state = state
            d.next_op_versions = cursor
            d.read_states = read_states
            d.cursor_matrix = cursor_matrix
            d.read_deltas = read_deltas
            # the installed resume point IS the last sealed one: a quiet
            # first poll under checkpoint_on_read must not reseal it
            self._checkpoint_sig = (
                dict(cursor.counters), frozenset(read_states)
            )
            # delta-base continuity: when the checkpoint proves it was
            # sealed WITH the snapshot (state == snapshot, name known),
            # the next compaction keeps extending the delta chain
            # instead of breaking it with a delta-less seal
            sp = obj.get(b"sp")
            if sp is not None:
                try:
                    from ..read.stable import StablePrefix

                    self._stable = StablePrefix.from_obj(self.adapter, sp)
                except Exception:
                    # observational slot: a malformed prefix rebuilds
                    # cold, it never fails the checkpoint
                    logger.debug(
                        "checkpoint stable-prefix slot undecodable; "
                        "strong reads rebuild cold", exc_info=True,
                    )
                    self._stable = None
            snap = obj.get(b"snap")
            if (
                self._delta_enabled
                and isinstance(snap, (bytes, bytearray, memoryview))
            ):
                snap_name = bytes(snap).decode()
                if snap_name in read_states:
                    self._set_delta_base(
                        snap_name,
                        codec.pack(self.adapter.state_to_obj(state)),
                        cursor.to_obj(),
                    )
        self.opened_from_checkpoint = True
        return True

    # ------------------------------------------------------- wire (3 layers)
    def _latest_key(self) -> Key:
        key = self._data.keys.latest_key()
        if key is None:
            raise MissingKeyError("no latest data key")
        return key

    async def _seal(self, payload_obj) -> bytes:
        """inner(data version) → cipher middle → outer(container), with the
        sealing key's id recorded in the outer layer so readers can select
        the right key after rotation or concurrent bootstrap (the reference
        decrypts everything with the current latest key, lib.rs:437-441,
        which loses data once two keys exist — deliberately fixed here)."""
        inner = VersionBytes(self.current_data_version, codec.pack(payload_obj))
        key = self._latest_key()
        middle = await self.cryptor.encrypt(key.material, inner.serialize())
        return VersionBytes(
            CURRENT_CONTAINER_VERSION, codec.pack([key.id, middle])
        ).serialize()

    async def _open_sealed(self, raw: bytes):
        return await open_sealed_blob(
            self._data.keys, self.cryptor, raw, self.supported_data_versions
        )

    def _note_quarantine(self, family: str, ident: str, exc: Exception) -> None:
        """Bookkeeping for one quarantined synced file (see
        :class:`_Quarantined`): counted under ``ingest_quarantined``
        and one warning naming the damaged object — the signal an
        operator greps for before reaching for ``tools/fsck``."""
        trace.add("ingest_quarantined", 1)
        logger.warning(
            "quarantining %s %s: %r (cursor held; retried on repaired sync)",
            family, ident, exc,
        )

    async def _decrypt_tolerant(self, key: Key, files: list, middles: list) -> list:
        """Batched AEAD open with per-file quarantine: the batch fast
        path first, and on failure a per-file pass that replaces each
        undecryptable blob with the :class:`_Quarantined` sentinel
        instead of aborting the whole ingest.

        Escalation rule: when EVERY file of a multi-file batch fails,
        the failure is indistinguishable from a dead cryptor or damaged
        key material — quarantining it all would silently stop
        convergence behind warnings — so :class:`IngestDecryptError`
        propagates loudly instead (nothing consumed, cursors held).  A
        single-file batch still quarantines (one torn file IS the
        per-file damage case this exists for)."""
        try:
            return await self.cryptor.decrypt_batch(key.material, middles)
        except Exception:
            logger.debug(
                "batch decrypt failed; isolating per file", exc_info=True
            )
        outs, failed = [], []
        for (actor, version, _), middle in zip(files, middles):
            try:
                outs.append(await self.cryptor.decrypt(key.material, middle))
            except Exception as e:
                outs.append(_QUARANTINED)
                failed.append((actor, version, e))
        if len(files) > 1 and len(failed) == len(files):
            raise IngestDecryptError(
                f"all {len(files)} op files in the batch failed to open"
            ) from failed[-1][2]
        for actor, version, e in failed:
            self._note_quarantine("op", f"{actor.hex()}:v{version}", e)
        return outs

    # ------------------------------------------------------------- apply_ops
    async def _ensure_own_history(self) -> None:
        """Dot-reuse guard, run under the writer lock before any op is
        BUILT: a producer whose in-memory clock trails its own durable
        history would mint event identifiers (Orswot dots) that its
        pre-crash incarnation already spent on *different* events —
        after which replicas diverge permanently, because a CRDT merge
        has no way to tell two events with one identity apart
        (simulator-discovered: a 4-step no-fault schedule
        ``add → crash → reopen → add`` reproduces it;
        ``tests/data/sim/dot_reuse_crash_reopen.json``).

        Cheap when in sync: two integer reads per write, plus ONE
        own-tail storage probe on the first write of each incarnation
        (a crash between ``store_ops`` and the local-meta update leaves
        an op file the durable cursor does not know about — only
        storage can reveal it).  The probe has a peer-GC blind spot
        (simulator-discovered under the daemon vocabulary:
        ``tests/data/sim/dot_reuse_gc_orphan.json``): a peer's
        compaction may fold the orphan op file into a snapshot and GC
        it before this incarnation's first write, destroying the tail
        evidence — the covering snapshot is then the only carrier of
        the spent dots.  So when the tail probe of a replica WITH prior
        history comes up empty, the snapshot listing is checked too:
        any unread snapshot forces a full re-read before the write, and
        a listing where EVERY snapshot this replica merged vanished
        with no unread replacement (a peer GC whose covering snapshot
        is not yet visible) refuses the write loudly.  When behind,
        the remote is re-read (own op
        tail, or the snapshot a peer compacted it into); a remote that
        STILL does not show the recorded history refuses the write
        loudly (:class:`StaleWriterError`) rather than corrupting every
        replica quietly."""
        actor = self.actor_id
        assert self._local_meta is not None
        behind = (
            self._data.next_op_versions.get(actor)
            < self._local_meta.last_op_version
        )
        probe_ok = True
        if not behind and not self._own_history_checked:
            try:
                tail = await self.storage.stat_ops(
                    [(actor, self._data.next_op_versions.get(actor) + 1)]
                )
                if not tail and self._local_meta.last_op_version > 0:
                    # peer-GC blind spot (docstring): only replicas that
                    # have EVER written can have a crash orphan, so the
                    # extra listing is skipped for fresh joiners.  Op
                    # files only vanish when a covering snapshot became
                    # durable first (write-new-then-delete-old), so a
                    # replica with durable history facing an empty op
                    # tail must see EITHER only snapshots it already
                    # merged (in sync) or an unread one (re-read first);
                    # a view where known snapshots vanished — or where
                    # nothing is visible at all — is inconsistent, and
                    # writing into it could re-mint dots a peer already
                    # folded.  (Assumes removes never become visible
                    # before the snapshot that justified them — the GC
                    # ordering the whole sync model rests on.)
                    names = set(await self.storage.list_state_names())
                    unread = names - self._data.read_states
                    if unread:
                        tail = True  # re-read the covering snapshots
                    elif self._data.read_states and not (
                        self._data.read_states & names
                    ):
                        # EVERY snapshot this replica merged vanished
                        # and nothing unread replaced it: the covering
                        # snapshot of that GC is not visible yet.  (A
                        # ghost name from a stale checkpoint next to a
                        # listed snapshot we also read is benign — the
                        # current listing's snapshots collectively
                        # carry all GC coverage once fully read.)
                        raise StaleWriterError(
                            "snapshots this replica merged were "
                            "garbage-collected but no replacement is "
                            "visible; writing now could reuse dots the "
                            "collecting peer's snapshot already folded"
                        )
                    elif not names and not await self.storage.stat_ops(
                        [(actor, 1)]
                    ):
                        # zero snapshots anywhere AND the own op log is
                        # gone below the cursor too: the history went
                        # SOMEWHERE (a not-yet-visible snapshot) — an
                        # intact own log (the never-compacted remote)
                        # passes this probe and writes normally
                        raise StaleWriterError(
                            "own durable op history vanished with no "
                            "covering snapshot visible; writing now "
                            "could reuse dots it carried"
                        )
            except StaleWriterError:
                raise
            except Exception:
                # a safety guard must not fail OPEN permanently: the
                # recorded-cursor check above still fails closed, and
                # leaving the checked flag unset re-probes for the
                # unrecorded-orphan corner on the next write
                logger.warning(
                    "own-tail probe failed; re-probing on the next write",
                    exc_info=True,
                )
                tail = []
                probe_ok = False
            behind = bool(tail)
        if behind:
            await self.read_remote(_sample=False)
            if (
                self._data.next_op_versions.get(actor)
                < self._local_meta.last_op_version
            ):
                raise StaleWriterError(
                    "own durable history (op files through "
                    f"v{self._local_meta.last_op_version}) is not yet "
                    "visible on the remote; writing now would reuse "
                    "pre-crash event ids"
                )
        if probe_ok:
            self._own_history_checked = True

    async def apply_ops(self, ops: list) -> None:
        """Persist a batch of local ops as one immutable op file, then fold
        it into memory (producer path, lib.rs:666-722).

        Ops must have been built against the *current* state (with_state).
        When multiple tasks write concurrently, use ``update`` instead — it
        derives the ops under the writer lock, so dots can't collide."""
        if not ops:
            return
        async with self._apply_lock:
            await self._ensure_own_history()
            await self._apply_ops_locked(ops)

    async def update(self, build) -> list:
        """Build-and-apply under the writer lock: ``build(state)`` (sync,
        LockBox discipline) returns one op or a list of ops derived from the
        live state; they are persisted and folded atomically with respect to
        other writers.  Returns the ops."""
        async with self._apply_lock:
            await self._ensure_own_history()
            ops = LockBox(self._data.state).with_(build)
            if ops is None:
                return []
            if not isinstance(ops, list):
                ops = [ops]
            if ops:
                await self._apply_ops_locked(ops)
            return ops

    async def _apply_ops_locked(self, ops: list) -> None:
        payload = [self.adapter.op_to_obj(op) for op in ops]
        blob = await self._seal(payload)
        actor = self.actor_id
        assert self._local_meta is not None
        # The true next version is past everything this replica has ever
        # written (durable cursor) and everything it has folded (memory
        # cursor); a collision with a file a previous crash left behind
        # probes forward rather than clobbering.
        version = (
            max(
                self._data.next_op_versions.get(actor),
                self._local_meta.last_op_version,
            )
            + 1
        )
        while True:
            try:
                await self.storage.store_ops(actor, version, blob)
                break
            except FileExistsError:
                version += 1
        self._local_meta.last_op_version = version
        vb = VersionBytes(
            CURRENT_CONTAINER_VERSION, codec.pack(self._local_meta.to_obj())
        )
        await self.storage.store_local_meta(vb.serialize())
        # sync section: fold into memory
        self.accel.fold_ops(self._data.state, ops)
        self._data.next_op_versions.apply(Dot(actor, version))

    # ----------------------------------------------------------- read_remote
    async def read_remote(self, *, _sample: bool = True) -> None:
        """Ingest everything new: snapshots first, then op tails
        (consumer path, lib.rs:390-399).  ``_sample=False`` is compact's
        internal call — it samples once itself, post-GC, so the inner
        ingest must not pay a second status probe."""
        await self._read_remote_meta()
        await self._read_remote_states()
        await self._read_remote_ops()
        if self._checkpoint_on_read and self._checkpoint_enabled:
            # pure-consumer replicas (no compaction rights) reseal their
            # resume point after every ingest — but not after a no-op
            # poll (same cursor + read-states as the last seal): a quiet
            # remote must not cost a multi-MB re-pack + fsync per poll
            d = self._data
            sig = (
                dict(d.next_op_versions.counters), frozenset(d.read_states)
            )
            if sig != self._checkpoint_sig:
                await self.save_checkpoint()
        if _sample:
            # the ingest above folded everything its own listing found,
            # so the backlog is empty as-of that listing — don't pay a
            # second per-actor storage probe on the polling hot path
            await self._sample_replication(_backlog=[])

    async def _read_remote_states(self) -> None:
        with trace.span("states.list"):
            names = await self.storage.list_state_names()
        new = [n for n in names if n not in self._data.read_states]
        if not new:
            # a quiet poll pays NO delta machinery: deltas are sealed
            # with their snapshots, so no unread snapshot ⇒ no new delta
            return
        if self._delta_enabled and getattr(self.storage, "has_deltas", False):
            # delta-first: chains that anchor at an already-merged base
            # snapshot fold without downloading the full snapshot; any
            # snapshot a chain cannot reach (gap, GC'd link, fingerprint
            # doubt, no codec) is full-loaded below — the delta layer
            # can save bytes but never lose data (docs/delta.md)
            if await self._read_remote_deltas():
                new = [n for n in new if n not in self._data.read_states]
                if not new:
                    return
        with trace.span("states.load"):
            loaded = await self.storage.load_states(new)
        sem = asyncio.Semaphore(IO_CONCURRENCY)

        state_failures: list[tuple[str, Exception]] = []

        async def decode(name: str, raw: bytes):
            async with sem:
                try:
                    obj = await self._open_sealed(raw)
                    # [state, cursor] or [state, cursor, sealer] — see
                    # StateWrapper's wire note; a malformed sealer id is
                    # ignored (observational), never a read failure
                    sealer = snapshot_sealer(obj)
                    return name, sealer, StateWrapper(
                        self.adapter.state_from_obj(obj[0]),
                        VClock.from_obj(obj[1]),
                    )
                except MissingKeyError:
                    raise  # key metadata not synced: loud, not damage
                except Exception as e:
                    # torn/tampered snapshot: quarantine it — the name
                    # stays OUT of read_states, so a repaired sync is
                    # retried on the next listing
                    state_failures.append((name, e))
                    return None

        with trace.span("states.decrypt_decode"):
            decoded = [
                d
                for d in await asyncio.gather(
                    *(decode(n, raw) for n, raw in loaded)
                )
                if d is not None
            ]
        if len(loaded) > 1 and len(state_failures) == len(loaded):
            # every snapshot failing = dead cryptor / damaged keys, not
            # file damage: escalate (the _decrypt_tolerant rule)
            raise IngestDecryptError(
                f"all {len(loaded)} state snapshots failed to open"
            ) from state_failures[-1][1]
        for name, e in state_failures:
            self._note_quarantine("state", name, e)
        if not decoded:
            return
        # sync section: CvRDT merge (HOT LOOP #1 → accelerator)
        wrappers = [sw for _, _, sw in decoded]
        with trace.span("states.merge"):
            self.accel.merge_states(
                self._data.state, [sw.state for sw in wrappers]
            )
        trace.add("states_merged", len(wrappers))
        for _, sealer, sw in decoded:
            self._data.next_op_versions.merge(sw.next_op_versions)
            if sealer is not None and sealer != self.actor_id:
                # learn the sealing replica's published ingest cursor —
                # the matrix row the stability watermark mins over
                self._data.cursor_matrix.setdefault(
                    sealer, VClock()
                ).merge(sw.next_op_versions)
        self._data.read_states.update(name for name, _, _ in decoded)

    # ------------------------------------------------------- delta chains
    def _delta_fallback(self, actor: Actor, version: int, reason: str) -> None:
        """One unusable delta link: counted (``delta_fallbacks``) and
        attributed, never silent — the snapshot path picks the slack up
        in the same pass, so this is an efficiency signal, not an
        error.  The last reason is kept for tests/operators."""
        trace.add("delta_fallbacks", 1)
        self.last_delta_fallback_reason = reason
        logger.debug(
            "delta chain fallback at %s:v%d (%s); using the snapshot path",
            actor.hex(), version, reason,
        )

    async def _read_remote_deltas(self) -> int:
        """Walk every sealer's delta log past the consumed cursor and
        apply each link whose base snapshot this replica has already
        merged (base NAME ∈ ``read_states`` — the content address is
        the fingerprint, so an unknown or renamed base is doubt and
        falls back).  Applying a link is byte-equal to merging its
        target snapshot (delta/codec.py contract), so the target name
        is marked read, its cursor merged, and the sealer's
        cursor-matrix row advanced — exactly the full-snapshot
        bookkeeping.  Returns the number of links applied."""
        from ..delta import codec_for, wire

        d = self._data
        codec_cls = codec_for(self.adapter.name)
        with trace.span("delta.read"):
            actors = await self.storage.list_delta_actors()
            wanted = [
                (a, d.read_deltas.get(a, 0) + 1) for a in sorted(actors)
            ]
            if not wanted:
                return 0
            files = await self.storage.load_deltas(wanted)
            if not files:
                return 0
            trace.add("delta_bytes_read", sum(len(raw) for _, _, raw in files))
            applied = 0
            chain = 0  # longest contiguous applied run this pass
            run: dict[Actor, int] = {}
            for actor, version, raw in files:
                # scanned-is-consumed: whatever this link's fate, the next
                # poll starts past it (its target is reachable through the
                # snapshot listing regardless — see the caller's note)
                if version > d.read_deltas.get(actor, 0):
                    d.read_deltas[actor] = version
                try:
                    obj = await self._open_sealed(raw)
                    rec = wire.parse_delta_obj(obj)
                except MissingKeyError:
                    # unlike op ingest this is NOT loud: the full
                    # snapshot (sealed with the same key register) will
                    # raise it if the key truly has not synced
                    self._delta_fallback(actor, version, "unknown_key")
                    continue
                except Exception:
                    logger.debug("delta undecodable", exc_info=True)
                    self._delta_fallback(actor, version, "unreadable")
                    continue
                if rec.adapter != self.adapter.name:
                    self._delta_fallback(actor, version, "adapter")
                    continue
                if rec.new_name in d.read_states:
                    continue  # already merged (idempotent re-delivery)
                if codec_cls is None:
                    self._delta_fallback(actor, version, "no_codec")
                    continue
                if not rec.base_name or rec.base_name not in d.read_states:
                    self._delta_fallback(actor, version, "base_missing")
                    continue
                # sync section: fold the link + full snapshot bookkeeping
                codec_cls.apply(d.state, rec.delta_obj)
                d.next_op_versions.merge(rec.new_cursor)
                d.read_states.add(rec.new_name)
                if rec.sealer != self.actor_id:
                    d.cursor_matrix.setdefault(
                        rec.sealer, VClock()
                    ).merge(rec.new_cursor)
                applied += 1
                run[actor] = run.get(actor, 0) + 1
                chain = max(chain, run[actor])
            if applied:
                trace.add("delta_applied", applied)
                trace.gauge("delta_chain_length", chain)
        return applied

    async def _read_remote_ops(self) -> None:
        with trace.span("ops.list"):
            actors = await self.storage.list_op_actors()
        wanted = [
            (a, self._data.next_op_versions.get(a) + 1) for a in sorted(actors)
        ]
        if not wanted:
            return
        if await self._read_remote_ops_pipelined(wanted, actors):
            return
        # legacy whole-batch flow (no fold session, or the pipeline hit a
        # structural surprise): cursors already reflect everything the
        # pipeline folded, so recompute and load only the remainder
        wanted = [
            (a, self._data.next_op_versions.get(a) + 1) for a in sorted(actors)
        ]
        with trace.span("ops.load"):
            files = await self.storage.load_ops(wanted)
        trace.add("op_files_loaded", len(files))
        if not files:
            return
        if len(files) >= BULK_MIN_FILES:
            # streaming front end: batched native decrypt + columnar decode
            # (SURVEY.md §7 step 6); falls through on structural surprises
            if await self._read_remote_ops_bulk(files, actors):
                return
        sem = asyncio.Semaphore(IO_CONCURRENCY)

        failures: list[tuple[Actor, int, Exception]] = []

        async def decode(actor: Actor, version: int, raw: bytes):
            async with sem:
                try:
                    return actor, version, await self._open_sealed(raw)
                except MissingKeyError:
                    raise  # key metadata not synced: loud, not damage
                except Exception as e:
                    failures.append((actor, version, e))
                    return actor, version, _QUARANTINED

        # concurrent decode, ORDER PRESERVED (the reference's `buffered`
        # not `buffer_unordered` — ordering is load-bearing, lib.rs:497-514)
        with trace.span("ops.decrypt_decode"):
            decoded = await asyncio.gather(
                *(decode(a, v, raw) for a, v, raw in files)
            )
        if len(files) > 1 and len(failures) == len(files):
            # the _decrypt_tolerant escalation rule, per-file-path twin
            raise IngestDecryptError(
                f"all {len(files)} op files failed to open"
            ) from failures[-1][2]
        for actor, version, e in failures:
            self._note_quarantine("op", f"{actor.hex()}:v{version}", e)

        # sync section: version bookkeeping + batched fold (HOT LOOP #2)
        batch = []
        blocked: set[Actor] = set()  # actors cut at a quarantined file
        for actor, version, payload in decoded:
            if actor in blocked:
                continue
            expected = self._data.next_op_versions.get(actor) + 1
            if version < expected:
                continue  # concurrent-read tolerance (lib.rs:521-525)
            if payload is _QUARANTINED:
                # the hole ends this actor's dense run for this pass;
                # the cursor stays put so the file is retried later
                blocked.add(actor)
                continue
            if version > expected:
                raise OpOrderError(
                    f"op file v{version} for {uuid.UUID(bytes=actor)} arrived "
                    f"beyond expected v{expected}"
                )
            batch.extend(self.adapter.op_from_obj(o) for o in payload)
            self._data.next_op_versions.apply(Dot(actor, version))
        if batch:
            with trace.span("ops.fold"):
                self.accel.fold_ops(self._data.state, batch)
            trace.add("ops_folded", len(batch))

    # ------------------------------------------------- pipelined bulk ingest
    def _validate_chunk(self, files: list, clears: list, overlay=None,
                        blocked: set | None = None):
        """Sync section: ordered version bookkeeping for one chunk WITHOUT
        advancing the global cursors (the caller advances only after the
        chunk's fold is accepted — a declined or failed chunk stays
        re-readable).  ``overlay`` carries validated-but-not-yet-advanced
        versions across chunks when several are in flight; ``blocked``
        likewise carries quarantine cuts (an actor whose run hit a
        damaged file — see :class:`_Quarantined` — folds nothing past
        the hole, and the cursor holds there).  Returns
        ``(payloads, metas)``; skew tolerance and gap errors exactly as
        lib.rs:519-531."""
        payloads, metas = [], []
        local: dict[Actor, int] = overlay if overlay is not None else {}
        cut: set = blocked if blocked is not None else set()
        for (actor, version, _), clear in zip(files, clears):
            if actor in cut:
                continue
            expected = (
                max(self._data.next_op_versions.get(actor), local.get(actor, 0))
                + 1
            )
            if version < expected:
                continue  # concurrent-read tolerance (lib.rs:521-525)
            if clear is _QUARANTINED:
                cut.add(actor)  # already counted at the decrypt site
                continue
            if version > expected:
                raise OpOrderError(
                    f"op file v{version} for {uuid.UUID(bytes=actor)} arrived "
                    f"beyond expected v{expected}"
                )
            try:
                inner = VersionBytes.deserialize(clear).ensure_versions(
                    self.supported_data_versions
                )
            except Exception as e:
                # decrypted fine but the cleartext framing is damaged
                # (or a data version this build cannot read): same
                # quarantine discipline — skip, cut the actor, hold
                self._note_quarantine("op", f"{actor.hex()}:v{version}", e)
                cut.add(actor)
                continue
            payloads.append(inner.content)
            metas.append((actor, version))
            local[actor] = version
        return payloads, metas

    def _advance_cursors(self, metas: list) -> None:
        for actor, version in metas:
            self._data.next_op_versions.apply(Dot(actor, version))

    async def _fold_chunk_python(self, files: list, clears: list,
                                 blocked: set | None = None) -> None:
        """Per-op fallback fold of one decrypted chunk (non-columnar CRDT
        or a session decline) — bounded by the chunk size."""
        payloads, metas = self._validate_chunk(files, clears, blocked=blocked)
        if not payloads:
            return
        batch = []
        for p in payloads:
            batch.extend(self.adapter.op_from_obj(o) for o in codec.unpack(p))
        if batch:
            with trace.span("ops.fold"):
                self.accel.fold_ops(self._data.state, batch)
            trace.add("ops_folded", len(batch))
        self._advance_cursors(metas)

    async def _read_remote_ops_pipelined(self, wanted, actors) -> bool:
        """Bounded-memory overlapped ingest: the reader+decryptor task
        streams chunks (storage.iter_op_chunks → outer unwrap → batched
        native decrypt) through a small queue while this task validates,
        decodes, and folds them through a fold session — read of chunk
        i+1 overlaps decrypt of chunk i and fold of chunk i-1, and host
        memory is bounded by chunk size × queue depth (SURVEY.md §7 hard
        part 3; restructures ref lib.rs:471-547).

        Returns True when the stream was fully consumed; False hands the
        remainder to the legacy path (an outer-envelope surprise there
        produces the precise per-file error)."""
        open_session = getattr(self.accel, "open_fold_session", None)
        if open_session is None:
            return False
        # cheap type gate BEFORE any pipeline machinery: a session-less
        # CRDT type must not pay the producer's storage scan (incl. the
        # per-actor tail probe) only to cancel it and re-read legacily
        can_open = getattr(self.accel, "can_open_fold_session", None)
        if can_open is not None and not can_open(self._data.state):
            return False

        q: asyncio.Queue = asyncio.Queue(maxsize=2)

        async def produce():
            ci = 0  # chunk index: span meta, so overlap is event-auditable
            cut: set = set()  # actors ended by an unwrap quarantine
            try:
                async for files in self.storage.iter_op_chunks(wanted):
                    with trace.span("ops.chunk_unwrap", meta=ci):
                        kept, key_ids, middles = [], [], []
                        for f in files:
                            actor, version, raw = f
                            if actor in cut:
                                continue
                            try:
                                outer = VersionBytes.deserialize(
                                    raw
                                ).ensure_versions(SUPPORTED_CONTAINER_VERSIONS)
                                kid, middle = codec.unpack(outer.content)
                            except Exception as e:
                                # torn outer envelope: quarantine the
                                # file + end this actor's dense run
                                # (the cursor holds at the hole)
                                self._note_quarantine(
                                    "op", f"{actor.hex()}:v{version}", e
                                )
                                cut.add(actor)
                                continue
                            kept.append(f)
                            key_ids.append(bytes(kid))
                            middles.append(bytes(middle))
                    files = kept
                    groups: dict[bytes, list[int]] = {}
                    for i, kid in enumerate(key_ids):
                        groups.setdefault(kid, []).append(i)
                    clears: list = [None] * len(files)
                    with trace.span("ops.chunk_decrypt", meta=ci):
                        for kid, idxs in groups.items():
                            key = self._data.keys.get_key(kid)
                            if key is None:
                                raise MissingKeyError(
                                    "ops sealed with unknown key "
                                    f"{uuid.UUID(bytes=kid)}; key metadata "
                                    "may not have synced yet"
                                )
                            outs = await self._decrypt_tolerant(
                                key,
                                [files[i] for i in idxs],
                                [middles[i] for i in idxs],
                            )
                            for i, clear in zip(idxs, outs):
                                clears[i] = clear
                    trace.add("bytes_decrypted", sum(len(m) for m in middles))
                    if files:
                        await q.put(("chunk", files, clears))
                        ci += 1
                await q.put(("end",))
            except Exception as e:
                await q.put(("error", e))

        from ..parallel.session import SessionDeclined

        producer = asyncio.create_task(produce())
        # one tick steps the producer into its first storage scan (a
        # worker thread), so the session's sync state-vocabulary walk
        # below — the other big fixed cost of a tail ingest — runs
        # CONCURRENTLY with the per-actor tail probe instead of after it
        await asyncio.sleep(0)
        try:
            session = open_session(self._data.state, actors_hint=actors)
        except BaseException:
            producer.cancel()
            raise
        if session is None:
            # no chunked path for this CRDT type: the legacy flow
            # re-lists and re-loads (reads are idempotent)
            producer.cancel()
            try:
                await producer
            except (asyncio.CancelledError, Exception):
                pass
            return False
        session_done = False
        python_mode = False
        pending: list[tuple[list, list]] = []  # buffered below BULK_MIN_FILES
        pending_files = 0
        session_started = False
        fed_files = 0
        overlay: dict[Actor, int] = {}  # validated-but-unadvanced versions
        blocked: set[Actor] = set()  # actors cut at a quarantined file
        # decode runs in parallel threads (pure, GIL-released ctypes);
        # reduces drain strictly FIFO so per-actor cursor advancement stays
        # in version order even under a mid-stream failure.  The in-flight
        # width is the asyncio twin of the thread pipeline's producer
        # count (ops/stream.py stream_producer_count): the accelerator's
        # configured fan-out, else the cpu-count auto-tune.
        from ..ops.stream import stream_producer_count

        inflight: list[tuple] = []  # (decode_task, metas, files, clears)
        n_producers = stream_producer_count(
            getattr(self.accel, "stream_producers", 0)
        )
        # the gauge records the resolved fan-out width; the in-flight
        # decode bound keeps its historical floor of 2 (one decode of
        # lookahead even at width 1 — that lookahead IS the pipeline)
        MAX_DECODES = max(2, n_producers)
        trace.gauge("stream_producers", n_producers)

        async def finish_session():
            # state mutates ONLY here; must precede any python-mode fold
            # (the session's plane capture would clobber a direct fold).
            # Deliberately SYNCHRONOUS: finish reads the state, combines,
            # and writes it back — in a worker thread an update() landing
            # between its read and writeback would be silently clobbered.
            # One event-loop stall (≈combine+writeback) buys atomicity.
            nonlocal session_done
            if not session_done:
                session_done = True
                with trace.span("ops.session_finish"):
                    session.finish()

        async def drain_one() -> None:
            """Complete the oldest in-flight chunk: await its decode,
            reduce it (serialized), advance its cursors.  A decline flips
            to per-op python folds for it and everything after."""
            nonlocal python_mode, fed_files
            task, metas, files, clears = inflight.pop(0)
            try:
                decoded = await task
                if python_mode:
                    raise SessionDeclined("session already degraded")
                with trace.span("ops.chunk_fold"):
                    await asyncio.to_thread(session.reduce_chunk, decoded)
            except SessionDeclined:
                if not python_mode:
                    await finish_session()
                    python_mode = True
                await self._fold_chunk_python(files, clears, blocked)
                # later chunks already in flight were validated ahead of
                # this one — fold them NOW, in order, or a newer chunk
                # would fold first and trip the version-gap check
                while inflight:
                    t2, _m2, f2, c2 = inflight.pop(0)
                    t2.cancel()
                    try:
                        await t2
                    except (asyncio.CancelledError, Exception):
                        pass
                    await self._fold_chunk_python(f2, c2, blocked)
                return
            self._advance_cursors(metas)
            fed_files += len(files)

        async def dispatch(files, clears) -> None:
            nonlocal python_mode
            if python_mode:
                await self._fold_chunk_python(files, clears, blocked)
                return
            payloads, metas = self._validate_chunk(
                files, clears, overlay, blocked
            )
            if not payloads:
                return
            task = asyncio.create_task(
                asyncio.to_thread(session.decode_chunk, payloads)
            )
            inflight.append((task, metas, files, clears))
            if len(inflight) >= MAX_DECODES:
                await drain_one()

        try:
            while True:
                item = await q.get()
                tag = item[0]
                if tag == "end":
                    break
                if tag == "error":
                    raise item[1]
                _, files, clears = item
                if not session_started and not python_mode:
                    pending.append((files, clears))
                    pending_files += len(files)
                    if pending_files < BULK_MIN_FILES:
                        continue
                    session_started = True
                    backlog, pending = pending, []
                    for f, c in backlog:
                        await dispatch(f, c)
                    continue
                await dispatch(files, clears)
            # stream fully consumed; a never-promoted tiny ingest folds
            # per-op, the same shape as the legacy small path (decrypt
            # already happened, batched)
            while inflight:
                await drain_one()
            await finish_session()
            for files, clears in pending:
                await self._fold_chunk_python(files, clears, blocked)
            pending = []
            return True
        finally:
            producer.cancel()
            for task, *_ in inflight:
                task.cancel()
            # fold whatever was fed — chunks whose cursors advanced must
            # land in the state even on an exceptional exit
            await finish_session()
            if fed_files:
                trace.add("op_files_bulk_folded", fed_files)

    async def _read_remote_ops_bulk(self, files: list, actors) -> bool:
        """Bulk ingestion: unwrap all outer envelopes, one batched decrypt
        per sealing key, then hand raw payloads to the accelerator's
        columnar decode+fold.  Damaged files quarantine per-file (see
        :class:`_Quarantined`) instead of surprising the ingest; key-auth
        and op-order violations raise exactly as the per-file path would
        (lib.rs:519-531 semantics preserved)."""
        files, groups = self._unwrap_op_files(files)
        if not files:
            return True  # every file quarantined: consumed, cursors held

        # Single sealing key (the overwhelmingly common case) + a stream-
        # capable accelerator: chunked decrypt with one-chunk lookahead —
        # the worker thread decrypts chunk i+1 (native, GIL released)
        # while this thread validates and span-decodes chunk i; one
        # combined fold at the end.  The same pipeline benchmarks/suite.py
        # config 5 measures.
        open_stream = getattr(self.accel, "open_payload_stream", None)
        stream = (
            open_stream(self._data.state, actors_hint=actors)
            if open_stream is not None and len(groups) == 1
            else None
        )
        payload_chunks: list[list] = []
        metas: list = []
        overlay: dict[Actor, int] = {}
        barred: set[Actor] = set()  # actors cut at a quarantined file
        streamed_ok = stream is not None
        with trace.span("ops.bulk_decrypt"):
            if stream is not None:
                (key, idxs, mids), = groups
                CH = BULK_STREAM_CHUNK
                slices = [idxs[i : i + CH] for i in range(0, len(idxs), CH)]
                mid_slices = [
                    mids[i : i + CH] for i in range(0, len(mids), CH)
                ]

                async def decrypt_chunk(si):
                    # per-chunk producer stage, span-tagged with the chunk
                    # index so the overlap with the consumer's decode below
                    # is auditable from the trace event log (the same
                    # stream.* stage names the ops/stream.py pipeline and
                    # bench.py --e2e-streaming use)
                    with trace.span("stream.decrypt", meta=si):
                        return await self._decrypt_tolerant(
                            key,
                            [files[i] for i in slices[si]],
                            mid_slices[si],
                        )

                nxt = asyncio.create_task(decrypt_chunk(0))
                try:
                    for si, sl in enumerate(slices):
                        clears = await nxt
                        nxt = (
                            asyncio.create_task(decrypt_chunk(si + 1))
                            if si + 1 < len(slices)
                            else None
                        )
                        if nxt is not None:
                            # a created task has not executed yet: one tick
                            # steps it into its to_thread so the worker
                            # decrypts WHILE this thread validates+decodes
                            # (without this the "lookahead" is serialized)
                            await asyncio.sleep(0)
                        # sync: inner version checks WITHOUT cursor advance
                        # — cursors move only after the fold lands (same
                        # discipline as the pipelined path; an OpOrderError
                        # mid-batch must not strand validated-but-unfolded
                        # ops behind advanced cursors)
                        with trace.span("stream.validate", meta=si):
                            p, m = self._validate_chunk(
                                [files[i] for i in sl], clears, overlay,
                                barred,
                            )
                        metas.extend(m)
                        payload_chunks.append(p)
                        if streamed_ok:
                            with trace.span("stream.decode", meta=si):
                                streamed_ok = stream.feed(p)
                finally:
                    if nxt is not None:
                        nxt.cancel()
                        try:
                            await nxt
                        except (asyncio.CancelledError, Exception):
                            pass
            else:
                clears: list = [None] * len(files)
                for key, idxs, mids in groups:
                    outs = await self._decrypt_tolerant(
                        key, [files[i] for i in idxs], mids
                    )
                    for i, clear in zip(idxs, outs):
                        clears[i] = clear
                p, m = self._validate_chunk(files, clears, overlay, barred)
                metas.extend(m)
                payload_chunks.append(p)
        trace.add(
            "bytes_decrypted",
            sum(len(m) for _, _, mids in groups for m in mids),
        )

        payloads = [p for chunk in payload_chunks for p in chunk]
        if not payloads:
            return True
        with trace.span("ops.bulk_fold"):
            if streamed_ok and stream.finish():
                self._advance_cursors(metas)
                trace.add("op_files_bulk_folded", len(payloads))
                return True
            if stream is None and self.accel.fold_payloads(
                self._data.state, payloads, actors_hint=actors
            ):
                self._advance_cursors(metas)
                trace.add("op_files_bulk_folded", len(payloads))
                return True
            # accelerator declined (non-columnar CRDT, vocab collision):
            # decode per-op in Python but still fold as one batch
            batch = []
            for p in payloads:
                batch.extend(
                    self.adapter.op_from_obj(o) for o in codec.unpack(p)
                )
            self.accel.fold_ops(self._data.state, batch)
            self._advance_cursors(metas)
            trace.add("ops_folded", len(batch))
        return True

    # -------------------------------------------------- serving front end
    def _unwrap_op_files(self, files: list):
        """Outer-envelope unwrap of loaded op files, grouped by sealing
        key: ``(kept, [(key, idxs, middles)])`` — ONE implementation of
        the unwrap → group → key-resolve sequence shared by the
        whole-batch bulk ingest and the serving front end (a wire or
        error-message change must have one home).  A file whose outer
        framing does not parse is QUARANTINED (counter + warning, the
        actor's dense run ends there, cursor held — see
        :class:`_Quarantined`), so ``kept`` may be shorter than
        ``files``; ``idxs`` index into ``kept``.  An unsynced sealing
        key raises :class:`MissingKeyError` — loud, not damage."""
        with trace.span("ops.bulk_unwrap"):
            kept, key_ids, middles = [], [], []
            cut: set = set()
            for f in files:
                actor, version, raw = f
                if actor in cut:
                    continue
                try:
                    outer = VersionBytes.deserialize(raw).ensure_versions(
                        SUPPORTED_CONTAINER_VERSIONS
                    )
                    kid, middle = codec.unpack(outer.content)
                except Exception as e:
                    self._note_quarantine(
                        "op", f"{actor.hex()}:v{version}", e
                    )
                    cut.add(actor)
                    continue
                kept.append(f)
                key_ids.append(bytes(kid))
                middles.append(bytes(middle))
        by_kid: dict[bytes, list[int]] = {}
        for i, kid in enumerate(key_ids):
            by_kid.setdefault(kid, []).append(i)
        groups = []
        for kid, idxs in by_kid.items():
            key = self._data.keys.get_key(kid)
            if key is None:
                raise MissingKeyError(
                    f"ops sealed with unknown key {uuid.UUID(bytes=kid)}; "
                    "key metadata may not have synced yet"
                )
            groups.append((key, idxs, [middles[i] for i in idxs]))
        return kept, groups

    async def load_sealed_ops(self):
        """The multi-tenant serving layer's ingest front end
        (crdt_enc_tpu/serve/service.py): list + load + outer-unwrap
        every op file past the local cursor, grouping ciphertexts by
        sealing key WITHOUT decrypting, validating, folding, or
        advancing any cursor.  Returns ``(actors, files, groups)``
        where ``groups`` is ``[(key, idxs, middles)]`` — the fold
        service executes many tenants' decrypt plans inside one
        worker-thread hop (``Cryptor.decrypt_batch_fn``) instead of
        paying a per-tenant ``asyncio.to_thread`` round-trip, then
        validates through :meth:`_validate_chunk` and advances cursors
        only after its fold lands — the solo bulk-ingest discipline,
        factored so the two cannot drift.  No ``bytes_decrypted``
        counting here: nothing is decrypted yet — the caller counts
        after its decrypt phase actually succeeds."""
        with trace.span("ops.list"):
            actors = await self.storage.list_op_actors()
        wanted = [
            (a, self._data.next_op_versions.get(a) + 1) for a in sorted(actors)
        ]
        if not wanted:
            return [], [], []
        with trace.span("ops.load"):
            files = await self.storage.load_ops(wanted)
        trace.add("op_files_loaded", len(files))
        if not files:
            return actors, [], []
        files, groups = self._unwrap_op_files(files)
        return actors, files, groups

    # --------------------------------------------------------- delta sealing
    @property
    def delta_base_name(self) -> str | None:
        """Content-addressed name of the retained diff base (the last
        snapshot this replica sealed), or None.  The serving layer
        matches it against a warm entry's ``seal_name`` to decide
        whether a device-cut delta is possible this cycle."""
        base = self._delta_base
        return base["name"] if base is not None else None

    def _seal_signature(self, _mut=None) -> tuple:
        """Everything a re-seal of the current state would depend on:
        the op cursor, the read snapshot/delta sets, and the state's
        mutation epoch.  Two equal signatures ⇒ ``_compact_seal`` would
        publish the identical snapshot + GC set, so the serving layer
        may skip it.  ``_mut`` overrides the live epoch (callers pass
        the SNAPSHOT-time epoch so a mutation landing mid-seal can
        never alias the next cycle's comparison)."""
        d = self._data
        return (
            tuple(sorted(d.next_op_versions.counters.items())),
            frozenset(d.read_states),
            tuple(sorted(d.read_deltas.items())),
            getattr(d.state, "_mut", None) if _mut is None else _mut,
        )

    def _plan_delta_seal(self, state_obj, cursor_obj, _cut=None):
        """Sync section of the delta seal (docs/delta.md): diff the
        about-to-be-sealed state against the retained base (this
        replica's previous snapshot), self-verify, and hand the await
        half (:meth:`_seal_delta`) an immutable plan.  Runs BEFORE the
        first await of the seal tail so a concurrent apply cannot tear
        the (base, new, delta) triple.

        The plan always carries ``new_bytes`` — the canonical packed
        state — which becomes the NEXT base even when no delta can be
        cut this round (first seal, no codec, divergent or oversize
        diff); ``dobj`` is None in those cases and consumers fall back
        to the full snapshot for this link only."""
        if not self._delta_enabled or not getattr(
            self.storage, "has_deltas", False
        ):
            return None
        from ..delta import codec_for

        codec_cls = codec_for(self.adapter.name)
        if codec_cls is None:
            return None
        d = self._data
        new_bytes = codec.pack(state_obj)
        plan = {
            "new_bytes": new_bytes,
            "cursor": cursor_obj,
            "dobj": None,
            "codec": codec_cls,
            "base_state": None,
            "base_name": "",
            "base_cursor": None,
        }
        base = self._delta_base
        if base is None:
            return plan
        if (
            _cut is not None
            and _cut.get("base_name") == base["name"]
            and _cut.get("mut") == getattr(d.state, "_mut", None)
        ):
            # device-cut fast path (docs/delta.md "device-cut deltas"):
            # the serving layer already compared base vs post-fold
            # planes ON DEVICE and built the wire object from just the
            # diff rows — no host dict walk, no need for host-resident
            # base bytes.  The base planes ride in the plan so the
            # seal-time self-verify can still rebuild the base and
            # refold the delta against it.
            plan["dobj"] = _cut["dobj"]
            plan["base_planes"] = _cut.get("base_planes")
            plan["base_name"] = base["name"]
            plan["base_cursor"] = base["cursor"]
            plan["device_cut"] = True
            trace.add("delta_device_cuts", 1)
            return plan
        if base["bytes"] is None:
            # the bytes were dropped by a prior device-cut seal and this
            # cycle's cut does not line up (warm-tier eviction or a
            # mut-epoch bump mid-continuation): seal one snapshot-only
            # link — it re-anchors the chain AND re-retains the bytes,
            # so the fallback is self-healing
            trace.add("delta_cut_fallbacks", 1)
            trace.add("delta_seal_skipped", 1)
            return plan
        try:
            base_state = self.adapter.state_from_obj(
                codec.unpack(base["bytes"])
            )
            dobj = codec_cls.diff(base_state, d.state)
        except Exception:
            logger.warning(
                "delta diff failed; sealing snapshot only", exc_info=True
            )
            trace.add("delta_seal_skipped", 1)
            return plan
        if dobj is None:
            trace.add("delta_seal_skipped", 1)
            return plan
        # the size guard and self-verify run in _seal_delta's await half
        # (everything they read is an immutable plan-owned copy) — only
        # the diff against the LIVE state needed this sync section
        plan["dobj"] = dobj
        plan["base_state"] = base_state
        plan["base_name"] = base["name"]
        plan["base_cursor"] = base["cursor"]
        return plan

    def _set_delta_base(
        self, name: str, state_bytes: bytes | None, cursor_obj
    ) -> None:
        """Retain the just-sealed snapshot as the next diff base.
        ``state_bytes`` is a resident O(state) canonical copy per Core —
        deliberate (the alternative is re-decrypting the sealed snapshot
        every compact) but not free at fleet scale, so the cost is
        published (``delta_base_bytes``, last-writer-wins across cores)
        and the whole subsystem is opt-out (``OpenOptions.delta`` /
        ``CRDT_DELTA=0``).  A plane-resident tenant (one whose seal just
        rode the device-cut path) passes ``state_bytes=None``: the warm
        tier's device planes ARE the base, so no host copy is retained —
        ``delta_base_bytes`` drops to ~0 and the next cycle either cuts
        on device again or seals one snapshot-only link
        (``delta_cut_fallbacks``) that re-retains the bytes."""
        self._delta_base = {
            "name": name, "bytes": state_bytes, "cursor": cursor_obj,
        }
        trace.gauge(
            "delta_base_bytes",
            0 if state_bytes is None else len(state_bytes),
        )

    def _verify_delta_plan(self, plan) -> bool:
        """The refusal-to-publish guard (worker thread — the plan owns
        every input, so nothing races the live state): apply the delta
        to the base copy and require byte-identity with the sealed
        state.  A codec bug must surface HERE, on the sealer, not as
        divergence scattered across the fleet (``CRDT_DELTA_VERIFY=0``
        opts out)."""
        with trace.span("delta.verify"):
            try:
                base_state = plan["base_state"]
                if base_state is None:
                    # device-cut plan: the host base copy was never
                    # built — rebuild it from the plan-owned base
                    # planes (normalized by the fold kernel's output
                    # law; zero padding reconstructs to nothing)
                    clock, add, rm, members, replicas = plan[
                        "base_planes"
                    ]
                    import numpy as np

                    from ..ops import orset_planes_to_state

                    base_state = orset_planes_to_state(
                        np.asarray(clock), np.asarray(add),
                        np.asarray(rm), members, replicas,
                    )
                plan["codec"].apply(base_state, plan["dobj"])
                return (
                    codec.pack(self.adapter.state_to_obj(base_state))
                    == plan["new_bytes"]
                )
            except Exception:
                logger.warning("delta verify crashed", exc_info=True)
                return False

    async def _seal_delta(self, plan, name: str) -> None:
        """Await half of the delta seal: wire-build, seal with the data
        key, publish at the next own-log version (FileExistsError
        probes forward — the op-file discipline), persist the bumped
        local-meta cursor, and retain the new base.  A delta-less round
        (``dobj`` None) wipes the own log instead: a chain that cannot
        extend to the new snapshot is dead weight every consumer would
        scan and fall back on."""
        from ..delta import wire
        from ..obs.replication import stability_watermark

        d = self._data
        assert self._local_meta is not None
        if name == plan["base_name"]:
            return  # idempotent re-seal of the identical snapshot
        if plan["dobj"] is not None:
            if len(codec.pack(plan["dobj"])) >= len(plan["new_bytes"]):
                # a delta no smaller than the state saves nothing
                trace.add("delta_seal_skipped", 1)
                plan["dobj"] = None
            elif self._delta_verify and not await asyncio.to_thread(
                self._verify_delta_plan, plan
            ):
                logger.warning(
                    "delta diff does not refold to the sealed state; "
                    "refusing to publish it (snapshot only)"
                )
                trace.add("delta_seal_divergence", 1)
                plan["dobj"] = None
        if plan["dobj"] is None:
            self._set_delta_base(name, plan["new_bytes"], plan["cursor"])
            last = self._local_meta.last_delta_version
            if last:
                trace.add("delta_pruned", 1)
                await self.storage.remove_deltas([(self.actor_id, last)])
            return
        with trace.span("delta.seal"):
            union = d.next_op_versions.copy()
            for clock in d.cursor_matrix.values():
                union.merge(clock)
            rec = wire.DeltaRecord(
                base_name=plan["base_name"],
                new_name=name,
                base_cursor=VClock.from_obj(plan["base_cursor"]),
                new_cursor=VClock.from_obj(plan["cursor"]),
                sealer=self.actor_id,
                adapter=self.adapter.name,
                watermark=stability_watermark(
                    self.actor_id, d.next_op_versions, d.cursor_matrix, union
                ),
                delta_obj=plan["dobj"],
            )
            blob = await self._seal(wire.build_delta_obj(rec))
            version = self._local_meta.last_delta_version + 1
            while True:
                try:
                    await self.storage.store_delta(
                        self.actor_id, version, blob
                    )
                    break
                except FileExistsError:
                    version += 1
            self._local_meta.last_delta_version = version
            vb = VersionBytes(
                CURRENT_CONTAINER_VERSION,
                codec.pack(self._local_meta.to_obj()),
            )
            await self.storage.store_local_meta(vb.serialize())
            trace.add("delta_files_sealed", 1)
            trace.add("delta_bytes_sealed", len(blob))
            # own-log bound: consumers further than MAX_CHAIN behind
            # re-read the full snapshot once and rejoin the chain
            from ..delta import MAX_CHAIN

            if version > MAX_CHAIN:
                trace.add("delta_pruned", 1)
                await self.storage.remove_deltas(
                    [(self.actor_id, version - MAX_CHAIN)]
                )
        # a published device-cut proves the warm planes ARE this
        # snapshot: drop the host base copy (the planes take over as
        # the base; _plan_delta_seal's bytes-None branch covers any
        # future cycle where they no longer line up)
        self._set_delta_base(
            name,
            None if plan.get("device_cut") else plan["new_bytes"],
            plan["cursor"],
        )

    # --------------------------------------------------------------- compact
    async def compact(self) -> None:
        """Fold everything, snapshot, write-new-then-delete-old
        (north-star path, lib.rs:332-380, with both WIP defects fixed).

        The ingest below runs the overlapped streaming pipeline when the
        storage/accelerator support it (_read_remote_ops_pipelined /
        _read_remote_ops_bulk): decrypt+decode of chunk k+1 proceeds
        while chunk k folds, with per-stage ``stream.*`` trace spans —
        see docs/streaming_pipeline.md for how to read them."""
        with trace.span("compact.ingest"):
            await self.read_remote(_sample=False)
        await self._compact_seal()

    async def _compact_seal(
        self, *, _backlog: list | None = None,
        _packed_state: tuple | None = None,
        _state_obj: tuple | None = None,
        _delta_cut: dict | None = None,
    ) -> None:
        """The seal tail of :meth:`compact`: snapshot the CURRENT state +
        cursor, write-new-then-delete-old, reseal the warm-open
        checkpoint, sample replication, and append the sink record.

        Factored out so the multi-tenant serving layer
        (crdt_enc_tpu/serve/) can install a batch-folded state and then
        run the EXACT solo sealing path — one implementation of the
        snapshot wire form, the GC ordering, and the checkpoint reseal,
        so a service-compacted remote can never drift from a solo
        ``compact()``.  ``_backlog`` is forwarded to the replication
        sample: the service passes ``[]`` because its own ingest just
        folded everything its listing found (same contract as
        ``read_remote``'s post-ingest sample) — a batch of N tenants
        must not pay N per-actor storage probes per dispatch.
        ``_packed_state`` forwards to :meth:`save_checkpoint` (the
        service's planes-packed checkpoint payload); ``_state_obj`` is
        ``(obj, mut_epoch)`` — a pre-built snapshot state object (the
        service derives it from the canonical fold writeback instead of
        re-walking the state), used only when the state's mutation
        epoch still matches, else the live state is serialized here.
        The canonical packer re-sorts maps, so an equivalent obj seals
        byte-identical payloads."""
        # lint: sync-section-begin (ASY001: the snapshot/cursor/delta-plan
        # cut below must come from ONE loop slice — an await here lets an
        # ingest interleave and seal a torn (state, cursor, delta) triple)
        d = self._data
        if _state_obj is not None and _state_obj[1] == getattr(
            d.state, "_mut", None
        ):
            state_obj = _state_obj[0]
        else:
            state_obj = self.adapter.state_to_obj(d.state)
        cursor_obj = d.next_op_versions.to_obj()
        snap_mut = getattr(d.state, "_mut", None)
        # delta plan (diff + self-verify) in the SAME sync section: the
        # (base, new, delta) triple must be cut from one stable state.
        # ``_delta_cut`` is the serving layer's device-cut candidate —
        # validated (base name + mut epoch) inside the plan, never
        # trusted blindly
        delta_plan = self._plan_delta_seal(
            state_obj, cursor_obj, _cut=_delta_cut
        )
        payload = [
            state_obj,
            cursor_obj,
            # sealer id: readers attribute the cursor to this replica in
            # their cursor matrix (StateWrapper's wire note) — old
            # readers index [0]/[1] and never see it
            self.actor_id,
        ]
        states_to_remove = sorted(d.read_states)
        ops_to_remove = sorted(d.next_op_versions.counters.items())
        prior_names = frozenset(d.read_states)
        # consumed-prefix GC covers FOREIGN logs only: the own log is
        # governed by _seal_delta's MAX_CHAIN bound — a stale reopen
        # that re-scanned its own chain must not wipe links steady
        # consumers are still walking
        deltas_to_remove = sorted(
            (a, v) for a, v in d.read_deltas.items() if a != self.actor_id
        )
        # lint: sync-section-end
        with trace.span("compact.seal"):
            blob = await self._seal(payload)
        # crash safety: the new snapshot is durable before anything vanishes
        with trace.span("compact.write"):
            name = await self.storage.store_state(blob)
        if delta_plan is not None:
            # the delta lands AFTER its target snapshot is durable (a
            # crash between the two leaves a snapshot consumers simply
            # full-read) and BEFORE the GC below
            await self._seal_delta(delta_plan, name)
        # snapshot-GC guard: foreign snapshots may only be removed when
        # the justifying snapshot ``name`` has never been published
        # before.  A re-seal of unchanged state reproduces its previous
        # content-addressed name — a name concurrent peers may already
        # have read, making it a legal target of THEIR GC; when every
        # member of a batch re-seals unchanged state, the union of
        # removes can wipe every snapshot (each remove justified by a
        # snapshot that is itself another sealer's remove target), and a
        # crashed replica reopening cold finds an empty remote it can
        # never converge from.  A never-before-published name cannot be
        # a concurrent remove target (removing requires having read it,
        # which orders the remover strictly after this store), so its
        # removes always stay covered by a durable snapshot — the GC
        # ordering _ensure_own_history's cross-check assumes.  Deferred
        # names stay in read_states and are GC'd by the next
        # genuinely-new seal.
        if name in prior_names:
            stale_states: list[str] = []
            trace.add("seal_gc_deferred", 1)
        else:
            stale_states = states_to_remove
        with trace.span("compact.gc"):
            if deltas_to_remove and self._delta_enabled:
                # consumed delta prefixes go FIRST: the new snapshot
                # covers them, and removing them before their target
                # snapshots keeps any crash window free of dangling
                # chain heads (docs/delta.md GC ordering)
                await self.storage.remove_deltas(deltas_to_remove)
            await asyncio.gather(
                self.storage.remove_states(stale_states),
                self.storage.remove_ops(ops_to_remove),
            )
        # sync bookkeeping section
        d.read_states.difference_update(stale_states)
        d.read_states.add(name)
        # record what this seal depended on, AT the snapshot epoch: the
        # serving layer skips the next seal iff the signature has not
        # moved (a mutation landing mid-seal keeps the epochs apart, so
        # the skip can never alias it away)
        self._last_seal_sig = self._seal_signature(_mut=snap_mut)
        if self._checkpoint_enabled:
            # the freshly compacted state is the ideal warm-open resume
            # point: everything folded, op logs GC'd to the cursor
            await self.save_checkpoint(
                _packed=_packed_state, _snap=(name, snap_mut)
            )
        # local ops are now folded into the snapshot; reset the producer
        # cursor bookkeeping is unnecessary — versions only grow.
        # replication status AFTER the GC + checkpoint seal (backlog is
        # zero by construction, staleness zero): the post-compaction
        # fixed point is what rides into the sink record below — the
        # per-device line the fleet aggregator reads.
        status = await self._sample_replication(_backlog=_backlog)
        # run-scoped metrics sink (CRDT_OBS_SINK / obs.sink.configure):
        # every compaction appends its phase table + counters, so the
        # streaming pipeline is auditable after the process is gone.
        # Off the event loop: with events enabled the record can carry a
        # full ring of timeline events, and json.dumps + the file append
        # must not stall concurrent ingests (the registry is lock-backed,
        # so snapshot/drain from a worker thread is safe).
        from ..obs import sink as obs_sink

        if obs_sink.default_sink() is not None:
            # ops_to_remove is (actor, covered-version-cursor) pairs —
            # the GC prefix per actor, not a file count
            await asyncio.to_thread(
                obs_sink.maybe_write,
                "compact",
                {"gc_op_actors": len(ops_to_remove),
                 "gc_states": len(states_to_remove)},
                status,
            )

    # ------------------------------------------------- remote meta lifecycle
    async def _read_remote_meta(self, force_notify: bool = False) -> None:
        names = await self.storage.list_remote_meta_names()
        new = [n for n in names if n not in self._data.read_metas]
        loaded = await self.storage.load_remote_metas(new) if new else []
        # The merge and the KEY-cryptor fan-out hold the keys lock: a
        # key-register merge landing inside _install_new_key's
        # snapshot→write window would be silently superseded (lock order:
        # _keys_lock → _meta_lock).  The storage/cryptor notifications
        # don't touch the keys register, so they run outside the lock —
        # rotation never waits on their (possibly fsync-heavy) callbacks.
        storage_reg = cryptor_reg = None
        async with self._keys_lock:
            for name, raw in loaded:
                vb = VersionBytes.deserialize(raw).ensure_versions(
                    SUPPORTED_CONTAINER_VERSIONS
                )
                self._data.remote_meta.merge(
                    RemoteMeta.from_obj(codec.unpack(vb.content))
                )
                self._remote_id_cache = None
                self._data.read_metas.add(name)
            if loaded or force_notify:
                rm = self._data.remote_meta
                storage_reg = MVReg.from_obj(rm.storage.to_obj())
                cryptor_reg = MVReg.from_obj(rm.cryptor.to_obj())
                await self.key_cryptor.set_remote_meta(
                    MVReg.from_obj(rm.key_cryptor.to_obj())
                )
        if storage_reg is not None:
            await asyncio.gather(
                self.storage.set_remote_meta(storage_reg),
                self.cryptor.set_remote_meta(cryptor_reg),
            )

    async def _store_remote_meta(self) -> None:
        """Persist converged metadata: content-addressed write, then remove
        superseded meta files (store-then-delete, lib.rs:647-664)."""
        vb = VersionBytes(
            CURRENT_CONTAINER_VERSION, codec.pack(self._data.remote_meta.to_obj())
        )
        old = set(self._data.read_metas)
        name = await self.storage.store_remote_meta(vb.serialize())
        await self.storage.remove_remote_metas([n for n in old if n != name])
        self._data.read_metas.difference_update(old)
        self._data.read_metas.add(name)

    # --------------------------------------- plugin callbacks (CoreSubHandle)
    def set_keys(self, keys: Keys) -> None:
        """Key cryptor installed a decoded key set (lib.rs:382-388)."""
        self._data.keys = keys

    async def set_remote_meta_storage(self, reg: MVReg) -> None:
        async with self._meta_lock:
            self._data.remote_meta.storage.merge(reg)
            self._remote_id_cache = None
            await self._store_remote_meta()

    async def set_remote_meta_cryptor(self, reg: MVReg) -> None:
        async with self._meta_lock:
            self._data.remote_meta.cryptor.merge(reg)
            self._remote_id_cache = None
            await self._store_remote_meta()

    async def set_remote_meta_key_cryptor(self, reg: MVReg) -> None:
        async with self._meta_lock:
            self._data.remote_meta.key_cryptor.merge(reg)
            self._remote_id_cache = None
            await self._store_remote_meta()

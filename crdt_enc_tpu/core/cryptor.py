"""Cryptor port: abstract AEAD over opaque byte blobs.

Mirrors the reference Cryptor trait (crdt-enc/src/cryptor.rs:11-27): key
generation plus encrypt/decrypt, where keys and ciphertexts are VersionBytes
so cipher formats can rotate independently of everything else.
"""

from __future__ import annotations

from abc import ABC, abstractmethod

from ..utils import VersionBytes


class Cryptor(ABC):
    @abstractmethod
    async def gen_key(self) -> VersionBytes:
        """Fresh random key material, tagged with the cipher's key version."""

    @abstractmethod
    async def encrypt(self, key: VersionBytes, data: bytes) -> bytes:
        """Seal ``data``; returns the raw-serialized cipher envelope (a
        VersionBytes tagged with the cipher's data version)."""

    @abstractmethod
    async def decrypt(self, key: VersionBytes, data: bytes) -> bytes:
        """Open a cipher envelope produced by ``encrypt``."""

    async def decrypt_batch(self, key: VersionBytes, blobs: list) -> list:
        """Open many envelopes sealed with one key.  Default: sequential
        loop; bulk backends override with a parallel native path (the
        decrypt front end of streaming compaction, SURVEY.md §7 step 6)."""
        return [await self.decrypt(key, b) for b in blobs]

    def decrypt_batch_fn(self, key: VersionBytes):
        """Optional SYNC twin of :meth:`decrypt_batch`: a plain callable
        ``(blobs) -> clears`` bound to ``key``, or None when this cipher
        has no GIL-releasing sync path.  The multi-tenant fold service
        uses it to run MANY tenants' decrypts inside ONE worker-thread
        hop — per-tenant ``asyncio.to_thread`` round-trips (~1ms each on
        a busy box) otherwise dominate a cycle over thousands of small
        tenants.  Must be semantically identical to ``decrypt_batch``;
        backends that override one must keep the other in step."""
        return None

    async def init(self, core) -> None: ...

    async def set_remote_meta(self, meta) -> None:
        """Converged config register changed.  Concurrent ``read_remote``
        calls may deliver snapshots out of order — MERGE the register
        (it is a CRDT), never replace local state with it."""

"""KeyCryptor port and the Keys CRDT — the "LUKS header" of the system.

Mirrors the reference key_cryptor.rs: data is encrypted with random data
keys; the keys themselves converge as a CRDT (an MVReg naming the latest key
id + an OR-Set of key material) that the KeyCryptor backend may additionally
encrypt (e.g. with PGP) inside the remote metadata.  Passwords/recipients can
change without re-encrypting data (reference README.md:19-25).

``Keys.latest_key`` resolves concurrent latest-id writes deterministically by
taking the minimum key id (reference key_cryptor.rs:59-70) and raises on a
dangling id (the reference panics).
"""

from __future__ import annotations

import uuid
from abc import ABC, abstractmethod
from dataclasses import dataclass, field

from ..models import MVReg, ORSet
from ..models.vclock import Actor
from ..utils import VersionBytes, codec


@dataclass(frozen=True)
class Key:
    """UUID-identified key material.  Identity is the id alone (reference
    key_cryptor.rs:85-139: Borrow/Hash/Eq/Ord by id); material for a given
    id is immutable once generated."""

    id: bytes  # 16-byte UUID
    material: VersionBytes

    @classmethod
    def new(cls, material: VersionBytes) -> "Key":
        return cls(uuid.uuid4().bytes, material)

    def member_obj(self):
        """The ORSet member encoding: nested tuples keep it hashable and
        msgpack-canonical."""
        return (self.id, (self.material.version, self.material.content))

    @classmethod
    def from_member_obj(cls, obj) -> "Key":
        kid, (version, content) = obj
        return cls(bytes(kid), VersionBytes(bytes(version), bytes(content)))


class DanglingLatestKey(Exception):
    """The latest-key register names an id absent from the key set."""


@dataclass
class Keys:
    """MVReg of the latest key id + OR-Set of keys (key_cryptor.rs:35-52)."""

    latest: MVReg = field(default_factory=MVReg)
    keys: ORSet = field(default_factory=ORSet)
    # id → Key lookup index, built lazily and invalidated by every mutation
    # that goes through this class.  ``get_key`` is called per key-group per
    # bulk ingest and per sealed blob open; without the index each call
    # re-sorts the whole rotation history (O(K log K · msgpack)).
    _index: dict | None = field(
        default=None, repr=False, compare=False, init=False
    )

    def _key_index(self) -> dict:
        if self._index is None:
            by_id: dict[bytes, tuple] = {}
            for m in self.keys.entries:
                kid = bytes(m[0])
                prev = by_id.get(kid)
                # ids are unique in practice (material is immutable per id,
                # reference key_cryptor.rs:85-139); if storage ever presents
                # duplicates, keep the canonical-order winner deterministically
                if prev is None or codec.pack(m) < codec.pack(prev):
                    by_id[kid] = m
            self._index = {
                kid: Key.from_member_obj(m) for kid, m in by_id.items()
            }
        return self._index

    def get_key(self, kid: bytes) -> Key | None:
        return self._key_index().get(bytes(kid))

    def latest_key(self) -> Key | None:
        """Deterministic resolution of concurrent latest-id writes: the
        minimum id wins the tie-break (key_cryptor.rs:59-70)."""
        ids = self.latest.read().values
        if not ids:
            return None
        kid = min(bytes(i) for i in ids)
        key = self.get_key(kid)
        if key is None:
            raise DanglingLatestKey(uuid.UUID(bytes=kid).hex)
        return key

    def insert_latest_key(self, actor: Actor, key: Key) -> None:
        """Add the key and point the latest-register at it
        (key_cryptor.rs:72-82: Orswot add + MVReg write under add-ctx)."""
        self.keys.apply(self.keys.add_ctx(actor, key.member_obj()))
        self.latest.apply(self.latest.write_ctx(actor, key.id))
        self._index = None

    def merge(self, other: "Keys") -> None:
        self.latest.merge(other.latest)
        self.keys.merge(other.keys)
        self._index = None

    def to_obj(self):
        return {b"l": self.latest.to_obj(), b"k": self.keys.to_obj()}

    @classmethod
    def from_obj(cls, obj) -> "Keys":
        if obj is None:
            return cls()
        return cls(MVReg.from_obj(obj.get(b"l")), ORSet.from_obj(obj.get(b"k")))

    def is_empty(self) -> bool:
        return self.latest.is_empty() and not self.keys.entries


class KeyCryptor(ABC):
    """Key-management port (key_cryptor.rs:18-33).  Owns how the Keys CRDT
    is protected inside the remote metadata (identity for tests, PGP-style
    asymmetric wrap for real deployments)."""

    @abstractmethod
    async def set_keys(self, keys: Keys) -> None:
        """The core (or the backend itself) updated the key set: encode it
        into this plugin's remote-meta register and push it to the core for
        persistence + convergence (reference gpgme lib.rs:107-129)."""

    async def init(self, core) -> None: ...

    async def set_remote_meta(self, meta) -> None:
        """Converged config register changed.  Concurrent ``read_remote``
        calls may deliver snapshots out of order — MERGE the register
        (it is a CRDT), never replace local state with it."""

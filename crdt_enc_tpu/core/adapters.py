"""CRDT-type adapters: how the core (de)serializes a state type and its ops.

The reference core is generic over ``S: CmRDT + CvRDT + Serialize`` with op
encoding via serde (lib.rs:189-197); here an adapter bundles the same
knowledge for dynamically chosen state types, plus the *accelerator* —
the pluggable execution backend for the two hot paths (per-op fold and
state merge).  ``HostAccelerator`` is the plain loop; the TPU accelerator
(crdt_enc_tpu/parallel/accel.py) batches onto the device kernels.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

from ..models import (
    CrdtMap,
    EmptyCrdt,
    GCounter,
    GSet,
    LWWMap,
    LWWOp,
    LWWReg,
    LWWRegOp,
    MerkleNode,
    MerkleReg,
    MVReg,
    MVRegOp,
    ORSet,
    PNCounter,
    SeqList,
    VClock,
)
from ..models.orset import op_from_obj as orset_op_from_obj
from ..models.seqlist import op_from_obj as seqlist_op_from_obj
from ..models.vclock import Dot


class HostAccelerator:
    """Reference execution: sequential host loops (the thing the TPU path
    replaces — HOT LOOPS #1/#2, reference lib.rs:458-466, 533-539)."""

    def fold_ops(self, state, ops: list):
        for op in ops:
            state.apply(op)
        return state

    def merge_states(self, state, others: list):
        for other in others:
            state.merge(other)
        return state

    def fold_payloads(self, state, payloads: list, actors_hint=()) -> bool:
        """Fold raw decrypted op-file payloads (msgpack op arrays) without
        per-op Python objects.  Returns True if handled; False tells the
        caller to decode and use ``fold_ops`` (this host reference always
        declines — the bulk path lives in the TPU accelerator)."""
        return False


@dataclass
class CrdtAdapter:
    name: bytes
    new: Callable[[], object]
    state_to_obj: Callable = field(default=lambda s: s.to_obj())
    state_from_obj: Callable = None  # type: ignore[assignment]
    op_to_obj: Callable = field(default=lambda op: op.to_obj())
    op_from_obj: Callable = field(default=lambda obj: obj)


def gcounter_adapter() -> CrdtAdapter:
    return CrdtAdapter(
        name=b"gcounter",
        new=GCounter,
        state_from_obj=GCounter.from_obj,
        op_from_obj=Dot.from_obj,
    )


def pncounter_adapter() -> CrdtAdapter:
    return CrdtAdapter(
        name=b"pncounter",
        new=PNCounter,
        state_from_obj=PNCounter.from_obj,
        op_to_obj=lambda op: [op[0], op[1].to_obj()],
        op_from_obj=lambda obj: (int(obj[0]), Dot.from_obj(obj[1])),
    )


def orset_adapter() -> CrdtAdapter:
    return CrdtAdapter(
        name=b"orset",
        new=ORSet,
        state_from_obj=ORSet.from_obj,
        op_from_obj=orset_op_from_obj,
    )


def lwwmap_adapter() -> CrdtAdapter:
    return CrdtAdapter(
        name=b"lwwmap",
        new=LWWMap,
        state_from_obj=LWWMap.from_obj,
        op_from_obj=LWWOp.from_obj,
    )


def mvreg_adapter() -> CrdtAdapter:
    return CrdtAdapter(
        name=b"mvreg",
        new=MVReg,
        state_from_obj=MVReg.from_obj,
        op_to_obj=lambda op: [op.clock.to_obj(), op.value],
        op_from_obj=lambda obj: MVRegOp(VClock.from_obj(obj[0]), obj[1]),
    )


def gset_adapter() -> CrdtAdapter:
    return CrdtAdapter(
        name=b"gset",
        new=GSet,
        state_from_obj=GSet.from_obj,
        op_to_obj=lambda op: op,  # the op IS the member
        op_from_obj=lambda obj: obj,
    )


def lwwreg_adapter() -> CrdtAdapter:
    return CrdtAdapter(
        name=b"lwwreg",
        new=LWWReg,
        state_from_obj=LWWReg.from_obj,
        op_from_obj=LWWRegOp.from_obj,
    )


def merklereg_adapter() -> CrdtAdapter:
    return CrdtAdapter(
        name=b"merklereg",
        new=MerkleReg,
        state_from_obj=MerkleReg.from_obj,
        op_from_obj=MerkleNode.from_obj,
    )


def list_adapter() -> CrdtAdapter:
    return CrdtAdapter(
        name=b"list",
        new=SeqList,
        state_from_obj=SeqList.from_obj,
        op_from_obj=seqlist_op_from_obj,
    )


def map_adapter(child: bytes = b"orset") -> CrdtAdapter:
    """Causal reset-remove map with nested CRDT values of type ``child``
    (one of crdtmap.CHILD_TYPES)."""
    proto = CrdtMap(child=child)  # op codec needs only the child type
    return CrdtAdapter(
        name=b"map+" + child,
        new=lambda: CrdtMap(child=child),
        state_from_obj=CrdtMap.from_obj,
        op_to_obj=proto.op_to_obj,
        op_from_obj=proto.op_from_obj,
    )


def empty_adapter() -> CrdtAdapter:
    return CrdtAdapter(
        name=b"empty",
        new=EmptyCrdt,
        state_from_obj=EmptyCrdt.from_obj,
        op_to_obj=lambda op: None,
        op_from_obj=lambda obj: None,
    )

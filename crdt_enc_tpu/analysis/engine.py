"""The analysis engine: one parse pass, rules fan out over the shared tree.

``Project`` walks the scan roots once, parsing every file into a
:class:`ModuleInfo` (AST + source lines + ``# lint: disable=RULE``
pragmas + a parent map + per-node enclosing-function qualnames).  Rules
are plain callables registered via :func:`rule`; each receives the whole
:class:`Project` and yields :class:`Finding`s, so cross-module rules
(FFI bindings vs. call sites, span registry vs. call sites) see the same
parsed trees as the per-function ones — nothing re-reads or re-parses a
file.

Suppression has exactly two channels, both carrying provenance:

* inline pragmas — ``# lint: disable=RULE[,RULE...]`` on the flagged
  line (or the line directly above it, comment-only), for point
  exceptions whose justification fits in the neighbouring comment;
* the committed baseline (``tools/analysis_baseline.toml``, see
  :mod:`crdt_enc_tpu.analysis.baseline`) for deliberate exceptions that
  need a recorded reason and a pinned match count.

A suppressed finding is not dropped — it is tagged with its channel so
``--json`` and ``--diff-baseline`` can audit the suppression inventory.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
import re
from typing import Callable, Iterable, Iterator

SEV_ERROR = "error"
SEV_WARNING = "warning"

#: roots scanned relative to the repo root, mirroring the historical
#: lints (tools/check_span_names.py).  ``tests/`` is deliberately absent:
#: test code seeds violations on purpose (fixtures) and uses scratch
#: span names.  ``tools/`` hosts the lint shims themselves.
SCAN_GLOBS: tuple[tuple[str, str], ...] = (
    ("crdt_enc_tpu", "**/*.py"),
    ("benchmarks", "**/*.py"),
    ("examples", "**/*.py"),
    (".", "bench.py"),
)

_PRAGMA_RE = re.compile(r"#\s*lint:\s*disable=([A-Z]{3}\d{3}(?:\s*,\s*[A-Z]{3}\d{3})*)")


@dataclasses.dataclass
class Finding:
    """One rule violation (or advisory) at a concrete source location."""

    rule: str
    severity: str  # SEV_ERROR | SEV_WARNING
    path: str  # repo-relative posix path
    line: int  # 1-based
    message: str
    context: str = "<module>"  # enclosing function qualname
    suppressed: str | None = None  # None | "pragma" | "baseline"
    #: effect provenance — the call path that introduced the effect,
    #: caller-first (populated by the interprocedural rules; None for
    #: the per-node pattern rules)
    chain: list[str] | None = None

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        tag = f" [{self.suppressed}]" if self.suppressed else ""
        head = (
            f"{self.severity.upper()} {self.rule} {self.path}:{self.line} "
            f"({self.context}): {self.message}{tag}"
        )
        if self.chain:
            head += "".join(f"\n    via {link}" for link in self.chain)
        return head


class ModuleInfo:
    """One parsed source file plus the per-file indexes every rule needs."""

    def __init__(self, root: pathlib.Path, path: pathlib.Path):
        self.path = path
        self.rel = path.relative_to(root).as_posix()
        self.source = path.read_text()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=self.rel)
        self.pragmas = self._collect_pragmas(self.lines)
        self.parents: dict[ast.AST, ast.AST] = {}
        self.qualname: dict[ast.AST, str] = {}
        self._all_nodes: list[ast.AST] | None = None  # walk() cache
        self._index(self.tree, None, ())

    @staticmethod
    def _collect_pragmas(lines: list[str]) -> dict[int, set[str]]:
        out: dict[int, set[str]] = {}
        for i, line in enumerate(lines, start=1):
            m = _PRAGMA_RE.search(line)
            if m:
                out[i] = {r.strip() for r in m.group(1).split(",")}
        return out

    def _index(self, node: ast.AST, parent: ast.AST | None, stack: tuple) -> None:
        if parent is not None:
            self.parents[node] = parent
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            stack = stack + (node.name,)
        self.qualname[node] = ".".join(stack) if stack else "<module>"
        for child in ast.iter_child_nodes(node):
            self._index(child, node, stack)

    def suppressed_by_pragma(self, rule: str, line: int) -> bool:
        """Pragma on the flagged line, or comment-only pragma directly above."""
        if rule in self.pragmas.get(line, ()):
            return True
        above = self.pragmas.get(line - 1)
        if above and rule in above:
            text = self.lines[line - 2].strip() if line >= 2 else ""
            return text.startswith("#")
        return False

    def context_of(self, node: ast.AST) -> str:
        return self.qualname.get(node, "<module>")

    def walk(self, *types) -> Iterator[ast.AST]:
        # every rule re-walks every module; one cached flat list turns
        # the repeated traversals into plain list scans
        nodes = self._all_nodes
        if nodes is None:
            nodes = self._all_nodes = list(ast.walk(self.tree))
        if not types:
            return iter(nodes)
        return (n for n in nodes if isinstance(n, types))


class Project:
    """All scanned modules, parsed exactly once and shared by every rule."""

    def __init__(
        self,
        root: pathlib.Path,
        paths: Iterable[pathlib.Path] | None = None,
    ):
        self.root = pathlib.Path(root)
        self.modules: list[ModuleInfo] = []
        self.parse_errors: list[Finding] = []
        #: an explicit-paths run sees only a slice of the tree — rules
        #: with project-global negatives (SPN001 stale registry rows)
        #: and baseline staleness cannot be judged from it
        self.partial = paths is not None
        for path in sorted(set(paths if paths is not None else self._scan())):
            try:
                self.modules.append(ModuleInfo(self.root, path))
            except SyntaxError as e:
                self.parse_errors.append(
                    Finding(
                        rule="ENG000",
                        severity=SEV_ERROR,
                        path=path.relative_to(self.root).as_posix(),
                        line=e.lineno or 1,
                        message=f"file does not parse: {e.msg}",
                    )
                )
            except UnicodeDecodeError as e:
                # one bad file must degrade to a finding, not abort the
                # run — every other file still gets analyzed
                self.parse_errors.append(
                    Finding(
                        rule="ENG000",
                        severity=SEV_ERROR,
                        path=path.relative_to(self.root).as_posix(),
                        line=1,
                        message=(
                            f"file is not valid UTF-8: {e.reason} "
                            f"at byte {e.start}"
                        ),
                    )
                )

    def _scan(self) -> Iterator[pathlib.Path]:
        for base, pattern in SCAN_GLOBS:
            for path in (self.root / base).glob(pattern):
                if path.is_file() and "__pycache__" not in path.parts:
                    yield path

    @staticmethod
    def in_scan_scope(root: pathlib.Path, path: pathlib.Path) -> bool:
        """Would the default scan visit ``path``?  Explicit-path runs
        use this to honour the tests/-exempt contract: out-of-scope
        paths are skipped, not linted with library-invariant rules.
        Raises ValueError if ``path`` is outside ``root``."""
        rel = path.relative_to(root)
        if "__pycache__" in rel.parts:
            return False
        for base, pattern in SCAN_GLOBS:
            if base == ".":
                if rel.as_posix() == pattern:
                    return True
            elif rel.parts and rel.parts[0] == base and rel.suffix == ".py":
                return True
        return False

    def module(self, rel: str) -> ModuleInfo | None:
        for mod in self.modules:
            if mod.rel == rel:
                return mod
        return None


# --------------------------------------------------------------- registry

#: name -> (callable(Project) -> Iterable[Finding], default severity, doc)
_RULES: dict[str, tuple[Callable, str, str]] = {}


def rule(name: str, severity: str = SEV_ERROR):
    """Register a rule.  The decorated callable takes a :class:`Project`
    and yields :class:`Finding`s; ``severity`` is its default (a rule may
    still emit individual findings at another severity)."""

    def deco(fn: Callable):
        _RULES[name] = (fn, severity, (fn.__doc__ or "").strip())
        fn.rule_name = name
        return fn

    return deco


def all_rules() -> dict[str, tuple[Callable, str, str]]:
    from . import rules as _  # noqa: F401 — importing registers the rules

    return dict(_RULES)


def run(
    project: Project,
    rule_names: Iterable[str] | None = None,
    baseline=None,
) -> list[Finding]:
    """Run the selected rules over the shared trees and apply suppression.

    Returns every finding (suppressed ones tagged, not dropped), sorted
    by (path, line, rule).  ``baseline`` is a
    :class:`crdt_enc_tpu.analysis.baseline.Baseline` or None.
    """
    registry = all_rules()
    names = list(rule_names) if rule_names is not None else sorted(registry)
    unknown = [n for n in names if n not in registry]
    if unknown:
        raise KeyError(f"unknown rule(s): {', '.join(unknown)}")
    findings: list[Finding] = list(project.parse_errors)
    for name in names:
        fn, _sev, _doc = registry[name]
        findings.extend(fn(project))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    by_rel = {mod.rel: mod for mod in project.modules}
    for f in findings:
        mod = by_rel.get(f.path)
        if mod is not None and mod.suppressed_by_pragma(f.rule, f.line):
            f.suppressed = "pragma"
    if baseline is not None:
        baseline.apply(findings)
    return findings


def unsuppressed_errors(findings: list[Finding]) -> list[Finding]:
    return [
        f for f in findings if f.severity == SEV_ERROR and f.suppressed is None
    ]

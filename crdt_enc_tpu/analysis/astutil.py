"""Small shared AST helpers for the analysis rules."""

from __future__ import annotations

import ast
from typing import Iterator


def dotted(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> str | None:
    return dotted(call.func)


def const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def func_params(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> list[str]:
    a = fn.args
    return [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]


def walk_in(node: ast.AST, *types) -> Iterator[ast.AST]:
    for n in ast.walk(node):
        if not types or isinstance(n, types):
            yield n


def enclosing(
    mod, node: ast.AST, *types
) -> ast.AST | None:
    """Nearest ancestor of ``node`` (via the module parent map) of the
    given types."""
    cur = mod.parents.get(node)
    while cur is not None:
        if isinstance(cur, types):
            return cur
        cur = mod.parents.get(cur)
    return None


def functions(mod) -> Iterator[ast.FunctionDef | ast.AsyncFunctionDef]:
    yield from mod.walk(ast.FunctionDef, ast.AsyncFunctionDef)


def assigned_names(target: ast.AST) -> Iterator[str]:
    """Plain names bound by an assignment target (tuples unpacked)."""
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from assigned_names(elt)

"""Interprocedural effect inference over the shared :class:`Project` ASTs.

One pass builds a project-wide call graph keyed per *definition node*
(the JIT002 idiom: a bare name resolves to the local def that shadows
it, else the module-level def, else a project-unique global; two or
more same-named candidates are never guessed between — the call is
recorded as *unresolved* and reported honestly, not silently dropped).
A fixpoint over that graph then propagates per-function effect sets:

``blocks``
    file/socket I/O, ``time.sleep``, ``subprocess``, native FFI calls
    through the known lib-handle spellings, ``lock.acquire()`` on a
    known ``threading`` lock, blocking ``queue.Queue`` get/put, and
    jit dispatch synchronisation (``block_until_ready``/``device_put``).
``wall_clock``
    ``time.time``/``monotonic``/``perf_counter``, ``datetime.now``.
``rng``
    module-level ``random.*``, zero-arg ``random.Random()``,
    ``uuid4``/``uuid1``, ``os.urandom``, ``secrets.*``, ``np.random.*``.
``awaits``
    the function body contains an ``await`` (not propagated: awaiting a
    coroutine is the caller's own, lexical property).
``mutates``
    stores to ``self.<attr>``/``cls.<attr>``; propagated only across
    same-instance (``self.``/``cls.``) call edges so a method inherits
    the write set of the helpers it drives on the *same* object.

Every propagated effect carries provenance: the first call edge that
introduced it, linked transitively so :meth:`EffectIndex.chain` can
print the concrete call path from any function down to the direct
origin.  Laundering seams are modelled on the edge, not the node:
``asyncio.to_thread(fn, ...)`` / ``loop.run_in_executor(ex, fn, ...)``
and the ingest producer-pool entry points drop the ``blocks`` effect
across that edge (the work happens off-loop) while still propagating
``wall_clock``/``rng`` — moving a clock read to a worker thread does
not make it deterministic.

Deliberate modelling decisions (kept honest in ``--effects`` output):

* ``with lock:`` is **not** a blocks effect — bounded critical sections
  (telemetry counters, registry guards) would otherwise poison every
  caller.  A bare ``.acquire()`` on a known threading lock *is*;
  ``await`` while holding a lock is LCK001's job.
* Unresolved calls (dynamic, or ambiguous between 2+ same-named defs)
  do **not** widen to all-effects; they are recorded per function and
  surfaced by ``--effects`` and the JSON dump so reviewers can see
  exactly where the analysis is blind.
* A seed line may carry ``# lint: effect-ok=<kind>[,<kind>] (reason)``
  to sanction the *origin* — for amortized one-shot sites (the memoized
  native ``make`` build) where baselining every transitive caller would
  bury the signal.  Sanctioned origins are recorded on the function and
  shown by ``--effects``, never silently dropped.
"""

from __future__ import annotations

import ast
import dataclasses
import re

from .astutil import call_name, dotted
from .engine import Project, ModuleInfo

KIND_BLOCKS = "blocks"
KIND_WALL = "wall_clock"
KIND_RNG = "rng"
KIND_AWAITS = "awaits"
KIND_MUTATES = "mutates"

ALL_KINDS = (KIND_BLOCKS, KIND_WALL, KIND_RNG, KIND_AWAITS, KIND_MUTATES)

#: native FFI handle spellings (mirrors rules/ffi.py's receiver set)
_LIB_NAMES = {"lib", "slib", "state_lib", "_state_lib", "_lib"}

#: full dotted-name seeds
_BLOCKS_EXACT = {
    "time.sleep": "time.sleep",
    "os.system": "os.system",
    "open": "open",
    "socket.create_connection": "socket.create_connection",
    "urllib.request.urlopen": "urllib.request.urlopen",
    "os.remove": "os file op",
    "os.rename": "os file op",
    "os.replace": "os file op",
    "os.unlink": "os file op",
    "os.makedirs": "os file op",
    "os.rmdir": "os file op",
    "os.listdir": "os file op",
    "os.scandir": "os file op",
    "os.stat": "os file op",
    "os.fsync": "os file op",
    "os.fdopen": "os file op",
}
_BLOCKS_PREFIXES = ("subprocess.", "shutil.")
#: attribute-tail seeds: pathlib I/O and jax host/device sync points
_BLOCKS_TAILS = {
    "block_until_ready": "jax block_until_ready (D2H sync)",
    "device_put": "jax device_put (dispatch)",
    "read_text": "pathlib read",
    "write_text": "pathlib write",
    "read_bytes": "pathlib read",
    "write_bytes": "pathlib write",
}

_WALL_EXACT = {
    "time.time",
    "time.monotonic",
    "time.perf_counter",
    "time.time_ns",
    "time.monotonic_ns",
    "time.perf_counter_ns",
}
_WALL_TAILS = {
    # datetime.datetime.now / from datetime import datetime; datetime.now()
    "datetime.now",
    "datetime.utcnow",
    "datetime.today",
    "date.today",
}

_RNG_EXACT = {
    "os.urandom",
    "uuid.uuid4",
    "uuid.uuid1",
}
_RNG_PREFIXES = ("secrets.", "random.", "np.random.", "numpy.random.")
_RNG_TAILS = {"uuid4", "uuid1"}

#: launder seams: calls that run their callable argument off the event
#: loop.  Maps dotted-name tail -> positional index of the callable.
_LAUNDER_ARG = {"to_thread": 0, "run_in_executor": 1}
#: named seams whose *implementation* is the sanctioned producer pool —
#: blocks effects do not propagate across a call to them (the blocking
#: work runs on pool threads; the entry point itself stays loop-safe).
_LAUNDER_CALLEES = {"run_ingest_pipeline", "run_striped_ingest_pipeline"}

_LOCK_CTORS = {
    "threading.Lock": "threading",
    "threading.RLock": "threading",
    "threading.Semaphore": "threading",
    "threading.BoundedSemaphore": "threading",
    "threading.Condition": "threading",
    "asyncio.Lock": "asyncio",
    "asyncio.Semaphore": "asyncio",
    "asyncio.Condition": "asyncio",
    "queue.Queue": "queue",
    "queue.SimpleQueue": "queue",
    "queue.LifoQueue": "queue",
    "queue.PriorityQueue": "queue",
}

_MAX_UNRESOLVED = 32  # per function, keeps the dump bounded

_EFFECT_OK_RE = re.compile(r"#\s*lint:\s*effect-ok=([a-z_]+(?:\s*,\s*[a-z_]+)*)")


def _effect_ok_lines(mod: "ModuleInfo") -> dict[int, set[str]]:
    """line -> sanctioned effect kinds (``# lint: effect-ok=blocks``)."""
    out: dict[int, set[str]] = {}
    for i, line in enumerate(mod.lines, start=1):
        m = _EFFECT_OK_RE.search(line)
        if m:
            out[i] = {k.strip() for k in m.group(1).split(",")}
    return out


def lock_ctor_kind(call: ast.Call) -> str | None:
    """``"threading"`` / ``"asyncio"`` / ``"queue"`` for a known lock or
    queue constructor call, else None.  Exact dotted spellings only —
    the repo idiom is always module-qualified."""
    name = call_name(call)
    return _LOCK_CTORS.get(name) if name else None


@dataclasses.dataclass
class Prov:
    """One provenance link: where an effect entered this function."""

    rel: str
    line: int
    desc: str  # human description of this link (direct origin or call)
    via: str | None = None  # callee FuncInfo key when propagated
    laundered: bool = False  # edge crossed a to_thread-style seam


@dataclasses.dataclass
class Unresolved:
    """A call edge the resolver declined to guess at (reported, not
    silently dropped)."""

    rel: str
    line: int
    desc: str


class FuncInfo:
    """Per-definition effect record (key = ``rel::qualname``)."""

    def __init__(self, mod: ModuleInfo, node, cls_name: str | None):
        self.mod = mod
        self.node = node
        self.qualname = mod.qualname[node]
        self.key = f"{mod.rel}::{self.qualname}"
        self.name = node.name
        self.cls = cls_name
        self.is_async = isinstance(node, ast.AsyncFunctionDef)
        #: (kind, origin) -> first-won provenance
        self.effects: dict[tuple[str, str], Prov] = {}
        self.unresolved: list[Unresolved] = []
        #: (kind, line, desc) seeds waived by an effect-ok pragma
        self.sanctioned: list[tuple[str, int, str]] = []
        #: outgoing edges: (callee_key, line, self_edge, laundered)
        self.calls: list[tuple[str, int, bool, bool]] = []
        #: nested defs by bare name (for local-shadow resolution)
        self.nested: dict[str, "FuncInfo"] = {}

    def effect_kinds(self) -> set[str]:
        return {k for (k, _o) in self.effects}

    def origins(self, kind: str) -> list[str]:
        return sorted(o for (k, o) in self.effects if k == kind)


def _seed(name: str | None) -> tuple[str, str] | None:
    """(kind, origin) when the dotted call name is a direct effect seed."""
    if name is None:
        return None
    tail = name.rsplit(".", 1)[-1]
    if name in _BLOCKS_EXACT:
        return (KIND_BLOCKS, name)
    if any(name.startswith(p) for p in _BLOCKS_PREFIXES):
        return (KIND_BLOCKS, name)
    if tail in _BLOCKS_TAILS and name != tail:
        return (KIND_BLOCKS, tail)
    if name in _WALL_EXACT:
        return (KIND_WALL, name)
    if any(name.endswith(t) for t in _WALL_TAILS):
        return (KIND_WALL, name)
    if name in _RNG_EXACT or tail in _RNG_TAILS:
        return (KIND_RNG, f"{tail}" if tail in _RNG_TAILS else name)
    if any(name.startswith(p) for p in _RNG_PREFIXES):
        # random.Random(seed) is a *seeded* constructor, handled by the
        # caller (zero-arg check); everything else under random./secrets.
        return (KIND_RNG, name)
    return None


class _ModIndex:
    """Per-module name-resolution context, built once."""

    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self.dotted = _module_dotted(mod.rel)
        self.pkg = self.dotted.rsplit(".", 1)[0] if "." in self.dotted else ""
        #: local alias -> ("from", src_module, src_name) | ("mod", module)
        self.imports: dict[str, tuple] = {}
        self.top_defs: dict[str, FuncInfo] = {}
        #: class name -> method name -> FuncInfo
        self.classes: dict[str, dict[str, FuncInfo]] = {}
        self.mod_locks: dict[str, str] = {}  # global name -> lock kind
        #: class name -> attr -> lock kind (self.X = threading.Lock())
        self.class_locks: dict[str, dict[str, str]] = {}

    def resolve_import_module(self, node: ast.ImportFrom) -> str:
        if node.level == 0:
            return node.module or ""
        parts = self.dotted.split(".")
        # level=1 strips the module's own name, each extra level one pkg
        base = parts[: len(parts) - node.level]
        if node.module:
            base = base + node.module.split(".")
        return ".".join(base)


def _module_dotted(rel: str) -> str:
    p = rel[:-3] if rel.endswith(".py") else rel
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


class EffectIndex:
    """Project-wide call graph + per-function propagated effect sets."""

    def __init__(self, project: Project):
        self.project = project
        self.funcs: dict[str, FuncInfo] = {}
        self.mods: dict[str, _ModIndex] = {}  # rel -> index
        self.by_dotted: dict[str, _ModIndex] = {}
        self.by_name: dict[str, list[FuncInfo]] = {}
        for mod in project.modules:
            self._index_module(mod)
        for mi in self.mods.values():
            self._scan_module(mi)
        self._propagate()

    # ------------------------------------------------------ construction

    def _index_module(self, mod: ModuleInfo) -> None:
        mi = _ModIndex(mod)
        self.mods[mod.rel] = mi
        self.by_dotted[mi.dotted] = mi
        for node in mod.tree.body:
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    mi.imports[local] = ("mod", alias.name if alias.asname else alias.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                src = mi.resolve_import_module(node)
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    mi.imports[alias.asname or alias.name] = ("from", src, alias.name)
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                value = node.value
                if isinstance(value, ast.Call):
                    kind = lock_ctor_kind(value)
                    if kind:
                        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                        for t in targets:
                            if isinstance(t, ast.Name):
                                mi.mod_locks[t.id] = kind
        # every def in the file, nested included, gets a FuncInfo
        for node in mod.walk(ast.FunctionDef, ast.AsyncFunctionDef):
            from .astutil import enclosing

            cls_node = enclosing(mod, node, ast.ClassDef)
            # only treat it as a method when the class is the *direct*
            # def parent (a def nested inside a method is not a method)
            direct = mod.parents.get(node)
            cls_name = cls_node.name if (cls_node is not None and direct is cls_node) else None
            fi = FuncInfo(mod, node, cls_name)
            self.funcs[fi.key] = fi
            self.by_name.setdefault(fi.name, []).append(fi)
            parent_fn = enclosing(mod, node, ast.FunctionDef, ast.AsyncFunctionDef)
            if parent_fn is not None:
                pkey = f"{mod.rel}::{mod.qualname[parent_fn]}"
                pfi = self.funcs.get(pkey)
                if pfi is not None:
                    pfi.nested[fi.name] = fi
            if cls_name is not None:
                mi.classes.setdefault(cls_name, {})[fi.name] = fi
            elif direct is mod.tree:
                mi.top_defs[fi.name] = fi
        # class lock attrs: self.X = threading.Lock() anywhere in a method
        for cls_name, methods in mi.classes.items():
            attrs: dict[str, str] = {}
            for fi in methods.values():
                for n in ast.walk(fi.node):
                    if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                        kind = lock_ctor_kind(n.value)
                        if not kind:
                            continue
                        for t in n.targets:
                            if (
                                isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id in ("self", "cls")
                            ):
                                attrs[t.attr] = kind
            if attrs:
                mi.class_locks[cls_name] = attrs

    # ---------------------------------------------------------- scanning

    def _scan_module(self, mi: _ModIndex) -> None:
        ok_lines = _effect_ok_lines(mi.mod)
        buckets = self._bucket_nodes(mi.mod.tree)
        for fi in self.funcs.values():
            if fi.mod is mi.mod:
                self._scan_func(mi, fi, ok_lines, buckets.get(fi.node, ()))

    @staticmethod
    def _bucket_nodes(tree) -> dict:
        """One DFS assigning every node to its innermost enclosing def
        (excluding nested def/class subtrees, which open their own
        buckets; lambdas stay in-line).  Replaces a per-function body
        walk — the module tree is traversed exactly once."""
        buckets: dict[ast.AST, list] = {}
        defs = (ast.FunctionDef, ast.AsyncFunctionDef)
        stack: list[tuple[ast.AST, ast.AST | None]] = [(tree, None)]
        while stack:
            node, fn = stack.pop()
            if isinstance(node, defs):
                buckets[node] = []
                for c in node.body:
                    stack.append((c, node))
                continue
            if isinstance(node, ast.ClassDef):
                for c in node.body:
                    stack.append((c, None))
                continue
            if fn is not None:
                buckets[fn].append(node)
            for c in ast.iter_child_nodes(node):
                stack.append((c, fn))
        return buckets

    def _scan_func(
        self, mi: _ModIndex, fi: FuncInfo, ok_lines: dict[int, set[str]],
        nodes,
    ) -> None:
        rel = mi.mod.rel
        local_locks: dict[str, str] = {}
        cls_locks = mi.class_locks.get(fi.cls, {}) if fi.cls else {}

        def add(kind: str, origin: str, line: int, desc: str) -> None:
            if kind in ok_lines.get(line, ()):
                fi.sanctioned.append((kind, line, desc))
                return
            fi.effects.setdefault((kind, origin), Prov(rel, line, desc))

        for n in nodes:
            if isinstance(n, ast.Await):
                add(KIND_AWAITS, "await", n.lineno, "await expression")
            elif isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign, ast.Delete)):
                targets = (
                    n.targets
                    if isinstance(n, (ast.Assign, ast.Delete))
                    else [n.target]
                )
                for t in targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id in ("self", "cls")
                    ):
                        add(KIND_MUTATES, t.attr, t.lineno, f"writes self.{t.attr}")
                value = getattr(n, "value", None)
                if isinstance(value, ast.Call):
                    kind = lock_ctor_kind(value)
                    if kind:
                        for t in targets:
                            if isinstance(t, ast.Name):
                                local_locks[t.id] = kind
            if not isinstance(n, ast.Call):
                continue
            name = call_name(n)
            # launder seams: resolve the callable argument as an edge
            # that drops blocks but still carries wall_clock/rng
            tail = name.rsplit(".", 1)[-1] if name else None
            if tail in _LAUNDER_ARG:
                idx = _LAUNDER_ARG[tail]
                if len(n.args) > idx:
                    target = n.args[idx]
                    if isinstance(target, ast.Call):  # partial(fn, ...)
                        tname = call_name(target)
                        if tname and tname.rsplit(".", 1)[-1] == "partial" and target.args:
                            target = target.args[0]
                    tdot = dotted(target)
                    if tdot is not None:
                        self._resolve_edge(mi, fi, tdot, n.lineno, laundered=True)
                continue
            if name is None:
                fi.unresolved.append(
                    Unresolved(rel, n.lineno, "dynamic call (non-name callee)")
                )
                continue
            seed = _seed(name)
            if seed is None and "." not in name:
                imp = mi.imports.get(name)
                if imp is not None and imp[0] == "from" and imp[1]:
                    # canonicalise `from time import monotonic` so bare
                    # spellings hit the same seed tables
                    seed = _seed(f"{imp[1]}.{imp[2]}")
            if seed is not None:
                kind, origin = seed
                # random.Random(seed) is seeded — only the zero-arg
                # constructor draws entropy from the OS
                if origin.endswith("random.Random") and (n.args or n.keywords):
                    continue
                add(kind, origin, n.lineno, f"call to {name}")
                continue
            parts = name.split(".")
            base = ".".join(parts[:-1])
            if len(parts) >= 2 and parts[-2] in _LIB_NAMES:
                add(KIND_BLOCKS, f"ffi:{name}", n.lineno, f"native FFI call {name}()")
                continue
            if parts[-1] == "acquire" and len(parts) >= 2:
                kind = self._lock_kind_of(mi, fi, base, local_locks, cls_locks)
                if kind == "threading":
                    add(KIND_BLOCKS, f"acquire:{base}", n.lineno, f"{base}.acquire()")
                continue
            if parts[-1] in ("get", "put") and len(parts) >= 2:
                kind = self._lock_kind_of(mi, fi, base, local_locks, cls_locks)
                if kind == "queue":
                    add(
                        KIND_BLOCKS,
                        f"queue:{base}.{parts[-1]}",
                        n.lineno,
                        f"blocking {base}.{parts[-1]}()",
                    )
                    continue
            self._resolve_edge(mi, fi, name, n.lineno, laundered=False)

    def _lock_kind_of(
        self,
        mi: _ModIndex,
        fi: FuncInfo,
        base: str,
        local_locks: dict[str, str],
        cls_locks: dict[str, str],
    ) -> str | None:
        if base in local_locks:
            return local_locks[base]
        if base in mi.mod_locks:
            return mi.mod_locks[base]
        if base.startswith(("self.", "cls.")):
            attr = base.split(".", 1)[1]
            if "." not in attr and attr in cls_locks:
                return cls_locks[attr]
        return None

    # -------------------------------------------------------- resolution

    def _resolve_edge(
        self, mi: _ModIndex, fi: FuncInfo, name: str, line: int, *, laundered: bool
    ) -> None:
        rel = mi.mod.rel
        parts = name.split(".")
        if len(parts) == 1:
            n = parts[0]
            if n in _LAUNDER_CALLEES:
                laundered = True
            target = fi.nested.get(n) or mi.top_defs.get(n)
            if target is None and n in mi.imports:
                target = self._resolve_import(mi.imports[n])
                if target is None and mi.imports[n][0] == "from":
                    # imported class: constructor edge to its __init__
                    target = self._resolve_class_method(mi.imports[n], "__init__")
            if target is None and n in mi.classes:
                target = mi.classes[n].get("__init__")
            if target is None:
                cands = self.by_name.get(n, [])
                if len(cands) == 1:
                    target = cands[0]
                elif len(cands) >= 2:
                    if len(fi.unresolved) < _MAX_UNRESOLVED:
                        fi.unresolved.append(
                            Unresolved(
                                rel,
                                line,
                                f"ambiguous: {len(cands)} defs named '{n}'",
                            )
                        )
                    return
                else:
                    return  # external (builtin/stdlib): silent by design
            fi.calls.append((target.key, line, False, laundered))
            return
        # attribute call
        head, tail = parts[0], parts[-1]
        if tail in _LAUNDER_CALLEES:
            laundered = True
        if head in ("self", "cls") and len(parts) == 2 and fi.cls:
            methods = mi.classes.get(fi.cls, {})
            target = methods.get(tail)
            if target is not None:
                fi.calls.append((target.key, line, True, laundered))
                return
            # fall through: inherited / mixin method -> tail fallback
        if len(parts) == 2 and head in mi.classes:
            target = mi.classes[head].get(tail)
            if target is not None:
                fi.calls.append((target.key, line, False, laundered))
                return
        imp = mi.imports.get(head)
        if imp is not None:
            if imp[0] == "mod":
                tgt_mi = self.by_dotted.get(imp[1])
                if tgt_mi is not None:
                    if len(parts) == 2:
                        target = tgt_mi.top_defs.get(tail)
                        if target is not None:
                            fi.calls.append((target.key, line, False, laundered))
                            return
                    elif len(parts) == 3 and parts[1] in tgt_mi.classes:
                        target = tgt_mi.classes[parts[1]].get(tail)
                        if target is not None:
                            fi.calls.append((target.key, line, False, laundered))
                            return
            elif imp[0] == "from" and len(parts) == 2:
                target = self._resolve_class_method((imp[0], imp[1], imp[2]), tail)
                if target is not None:
                    fi.calls.append((target.key, line, False, laundered))
                    return
        # bounded dynamic dispatch: a method name unique project-wide
        # resolves (the JIT002 keying idiom); 2+ candidates widen
        # honestly into the unresolved list
        cands = self.by_name.get(tail, [])
        if len(cands) == 1:
            fi.calls.append((cands[0].key, line, False, laundered))
        elif len(cands) >= 2:
            if len(fi.unresolved) < _MAX_UNRESOLVED:
                fi.unresolved.append(
                    Unresolved(
                        rel,
                        line,
                        f"ambiguous: {len(cands)} defs named '{tail}' "
                        f"(call spelled {name})",
                    )
                )
        # 0 candidates: external attribute (dict.get, list.append, ...)

    def _resolve_import(self, imp: tuple) -> FuncInfo | None:
        if imp[0] != "from":
            return None
        src_mi = self.by_dotted.get(imp[1])
        if src_mi is None:
            return None
        return src_mi.top_defs.get(imp[2])

    def _resolve_class_method(self, imp: tuple, method: str) -> FuncInfo | None:
        if imp[0] != "from":
            return None
        src_mi = self.by_dotted.get(imp[1])
        if src_mi is None:
            return None
        methods = src_mi.classes.get(imp[2])
        return methods.get(method) if methods else None

    # ------------------------------------------------------- propagation

    def _propagate(self) -> None:
        callers: dict[str, list[tuple[str, int, bool, bool]]] = {}
        for fi in self.funcs.values():
            for callee_key, line, self_edge, laundered in fi.calls:
                callers.setdefault(callee_key, []).append(
                    (fi.key, line, self_edge, laundered)
                )
        work = [fi.key for fi in self.funcs.values() if fi.effects]
        while work:
            key = work.pop()
            callee = self.funcs[key]
            for caller_key, line, self_edge, laundered in callers.get(key, ()):
                caller = self.funcs[caller_key]
                changed = False
                for (kind, origin), _prov in callee.effects.items():
                    if kind == KIND_AWAITS:
                        continue
                    if kind == KIND_BLOCKS and laundered:
                        continue
                    if kind == KIND_MUTATES and not self_edge:
                        continue
                    ek = (kind, origin)
                    if ek not in caller.effects:
                        caller.effects[ek] = Prov(
                            caller.mod.rel,
                            line,
                            f"call to {callee.qualname}",
                            via=key,
                            laundered=laundered,
                        )
                        changed = True
                if changed:
                    work.append(caller_key)

    # ------------------------------------------------------------ lookup

    def func_for_node(self, mod: ModuleInfo, node) -> FuncInfo | None:
        return self.funcs.get(f"{mod.rel}::{mod.qualname[node]}")

    def lookup(self, qualname: str) -> list[FuncInfo]:
        """All functions whose key ends with ``qualname`` (so both
        ``ORSet.apply`` and ``models/orset.py::ORSet.apply`` match)."""
        exact = [fi for fi in self.funcs.values() if fi.key == qualname]
        if exact:
            return exact
        out = []
        for fi in self.funcs.values():
            if fi.qualname == qualname or fi.key.endswith(qualname):
                out.append(fi)
        return sorted(out, key=lambda f: f.key)

    def chain(self, key: str, kind: str, origin: str) -> list[str]:
        """The provenance call path for one effect, caller-first, ending
        at the direct origin line."""
        out: list[str] = []
        seen: set[str] = set()
        k: str | None = key
        while k and k not in seen:
            seen.add(k)
            fi = self.funcs.get(k)
            if fi is None:
                break
            prov = fi.effects.get((kind, origin))
            if prov is None:
                break
            if prov.via:
                seam = " [off-loop seam]" if prov.laundered else ""
                out.append(f"{prov.rel}:{prov.line} {fi.qualname} -> {prov.desc[8:]}{seam}")
                k = prov.via
            else:
                out.append(f"{prov.rel}:{prov.line} {fi.qualname}: {prov.desc}")
                k = None
        return out

    def class_threading_locks(self, mod: ModuleInfo, cls_name: str) -> dict[str, str]:
        mi = self.mods.get(mod.rel)
        if mi is None:
            return {}
        return {
            a: k
            for a, k in mi.class_locks.get(cls_name, {}).items()
            if k == "threading"
        }


def effect_index(project: Project) -> EffectIndex:
    """Build (once) and cache the effect index on the project."""
    idx = getattr(project, "_effect_index", None)
    if idx is None:
        idx = EffectIndex(project)
        project._effect_index = idx
    return idx

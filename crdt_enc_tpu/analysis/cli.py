"""CLI for the static-analysis engine.

``python -m crdt_enc_tpu.tools.analyze [--json] [--diff-baseline]
[--rule RULE ...] [--list-rules] [--root DIR] [paths...]``

Exit codes: 0 = no unsuppressed error-severity findings (and, under
``--diff-baseline``, no stale baseline entries either); 1 = violations;
2 = usage/configuration error.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

from .baseline import Baseline
from .engine import (
    SEV_ERROR,
    Project,
    all_rules,
    run,
    unsuppressed_errors,
)

BASELINE_REL = "tools/analysis_baseline.toml"
JSON_SCHEMA_VERSION = 1


def default_root() -> pathlib.Path:
    # crdt_enc_tpu/analysis/cli.py -> repo root
    return pathlib.Path(__file__).resolve().parents[2]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m crdt_enc_tpu.tools.analyze",
        description="Project-invariant static analysis (docs/static_analysis.md)",
    )
    p.add_argument("paths", nargs="*", help="restrict to these files")
    p.add_argument("--json", action="store_true", help="machine-readable output")
    p.add_argument(
        "--diff-baseline", action="store_true",
        help="also fail on stale baseline entries (the committed baseline "
        "must exactly cover the deliberate exceptions)",
    )
    p.add_argument(
        "--rule", action="append", dest="rules", metavar="RULE",
        help="run only this rule (repeatable)",
    )
    p.add_argument("--list-rules", action="store_true")
    p.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the committed baseline (show everything)",
    )
    p.add_argument("--root", default=None, help="repo root (default: auto)")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    root = pathlib.Path(args.root).resolve() if args.root else default_root()

    if args.list_rules:
        for name, (_fn, sev, doc) in sorted(all_rules().items()):
            head = doc.splitlines()[0] if doc else ""
            print(f"{name}  [{sev}]  {head}")
        return 0

    if not (root / "crdt_enc_tpu").is_dir() or not (root / "docs").is_dir():
        # an installed (site-packages) cli.py cannot infer the checkout
        print(
            f"{root} is not a repo checkout (no crdt_enc_tpu/ + docs/); "
            "run from the repository or pass --root",
            file=sys.stderr,
        )
        return 2

    try:
        rules = args.rules
        if rules:
            unknown = set(rules) - set(all_rules())
            if unknown:
                print(f"unknown rule(s): {sorted(unknown)}", file=sys.stderr)
                return 2
        baseline = (
            None if args.no_baseline else Baseline.load(root / BASELINE_REL)
        )
    except ValueError as e:
        print(f"baseline error: {e}", file=sys.stderr)
        return 2

    t0 = time.monotonic()
    try:
        raw = []
        for arg in args.paths:
            p = pathlib.Path(arg).resolve()
            if p.is_dir():
                # a directory argument means "every in-scope file under
                # it" — without the expansion `crdt-analyze foo/` would
                # analyze zero files yet exit 0
                found = sorted(
                    f for f in p.rglob("*.py")
                    if Project.in_scan_scope(root, f)
                )
                if not found:
                    print(
                        f"note: {p.relative_to(root).as_posix()} "
                        "contains no in-scope files, skipped",
                        file=sys.stderr,
                    )
                raw.extend(found)
            else:
                raw.append(p)
        skipped = [p for p in raw if not Project.in_scan_scope(root, p)]
        for p in skipped:
            # tests/, tools/, docs/ are exempt by contract (SCAN_GLOBS):
            # a hook feeding changed files must not get spurious errors
            print(
                f"note: {p.relative_to(root).as_posix()} is outside the "
                "analysis scope, skipped",
                file=sys.stderr,
            )
        paths = [p for p in raw if p not in skipped] if args.paths else None
        project = Project(root, paths)
    except (ValueError, OSError) as e:
        # an explicit path outside the root, or unreadable
        print(f"path error: {e}", file=sys.stderr)
        return 2
    findings = run(project, rules, baseline)
    elapsed = time.monotonic() - t0

    stale = baseline.stale_entries() if baseline is not None else []
    if rules:  # a subset run can't judge other rules' entries
        stale = [e for e in stale if e.rule in rules]
    if project.partial:  # nor can a path-subset run judge any of them
        stale = []
    errors = unsuppressed_errors(findings)
    visible = [f for f in findings if f.suppressed is None]
    suppressed = [f for f in findings if f.suppressed is not None]

    if args.json:
        out = {
            "version": JSON_SCHEMA_VERSION,
            "root": str(root),
            "elapsed_s": round(elapsed, 3),
            "rules": sorted(rules) if rules else sorted(all_rules()),
            "findings": [f.to_json() for f in findings],
            "stale_baseline": [
                {"rule": e.rule, "path": e.path, "context": e.context,
                 "reason": e.reason}
                for e in stale
            ],
            "summary": {
                "files": len(project.modules),
                "errors": len(errors),
                "warnings": len(
                    [f for f in visible if f.severity != SEV_ERROR]
                ),
                "suppressed": len(suppressed),
            },
        }
        print(json.dumps(out, indent=2, sort_keys=True))
    else:
        for f in visible:
            print(f.render())
        for e in stale:
            print(
                f"STALE baseline entry {e.rule} {e.path}"
                + (f" ({e.context})" if e.context else "")
                + f" matched nothing — delete it (reason was: {e.reason})"
            )
        n_warn = len([f for f in visible if f.severity != SEV_ERROR])
        print(
            f"{len(project.modules)} files, {len(errors)} error(s), "
            f"{n_warn} warning(s), {len(suppressed)} suppressed, "
            f"{len(stale)} stale baseline entr(y/ies) in {elapsed:.2f}s"
        )

    if errors:
        return 1
    if args.diff_baseline and stale:
        return 1
    return 0

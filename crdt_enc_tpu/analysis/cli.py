"""CLI for the static-analysis engine.

``python -m crdt_enc_tpu.tools.analyze [--json] [--diff-baseline]
[--rule RULE ...] [--effects QUALNAME] [--expect-json-version N]
[--list-rules] [--root DIR] [paths...]``

Exit codes: 0 = no unsuppressed error-severity findings (and, under
``--diff-baseline``, no stale baseline entries either); 1 = violations;
2 = usage/configuration error (including an ``--expect-json-version``
mismatch, and an ``--effects`` qualname that matches nothing).
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

from .baseline import Baseline
from .engine import (
    SEV_ERROR,
    Project,
    all_rules,
    run,
    unsuppressed_errors,
)

BASELINE_REL = "tools/analysis_baseline.toml"
# v2 (interprocedural effects): findings gained `chain` (the provenance
# call path, caller-first), and `--effects` emits the per-function
# effect dump.  Consumers pinned to a version pass --expect-json-version
# and get a loud exit-2 reject instead of silently mis-parsing.
JSON_SCHEMA_VERSION = 2


def default_root() -> pathlib.Path:
    # crdt_enc_tpu/analysis/cli.py -> repo root
    return pathlib.Path(__file__).resolve().parents[2]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m crdt_enc_tpu.tools.analyze",
        description="Project-invariant static analysis (docs/static_analysis.md)",
    )
    p.add_argument("paths", nargs="*", help="restrict to these files")
    p.add_argument("--json", action="store_true", help="machine-readable output")
    p.add_argument(
        "--diff-baseline", action="store_true",
        help="also fail on stale baseline entries (the committed baseline "
        "must exactly cover the deliberate exceptions)",
    )
    p.add_argument(
        "--rule", action="append", dest="rules", metavar="RULE",
        help="run only this rule (repeatable)",
    )
    p.add_argument("--list-rules", action="store_true")
    p.add_argument(
        "--effects", metavar="QUALNAME",
        help="dump the inferred effect set + provenance chains for a "
        "function (e.g. Core.open, or serve/service.py::FoldService.run_cycle)",
    )
    p.add_argument(
        "--expect-json-version", type=int, default=None, metavar="N",
        help="fail loudly (exit 2) unless the --json schema version is "
        "exactly N — pin your consumer instead of silently mis-parsing",
    )
    p.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the committed baseline (show everything)",
    )
    p.add_argument("--root", default=None, help="repo root (default: auto)")
    return p


def _effects_json(idx, fi) -> dict:
    return {
        "key": fi.key,
        "qualname": fi.qualname,
        "async": fi.is_async,
        "effects": [
            {"kind": kind, "origin": origin,
             "chain": idx.chain(fi.key, kind, origin)}
            for (kind, origin) in sorted(fi.effects)
        ],
        "unresolved": [
            {"path": u.rel, "line": u.line, "desc": u.desc}
            for u in fi.unresolved
        ],
        "sanctioned": [
            {"kind": kind, "line": line, "desc": desc}
            for kind, line, desc in fi.sanctioned
        ],
    }


def _print_effects(idx, fi) -> None:
    head = "async def" if fi.is_async else "def"
    print(f"{head} {fi.qualname}  [{fi.mod.rel}]")
    if not fi.effects:
        print("  effects: none")
    for (kind, origin) in sorted(fi.effects):
        print(f"  {kind}: {origin}")
        for link in idx.chain(fi.key, kind, origin):
            print(f"    via {link}")
    for u in fi.unresolved:
        print(f"  unresolved call at {u.rel}:{u.line}: {u.desc}")
    for kind, line, desc in fi.sanctioned:
        print(f"  sanctioned [{kind}] at {fi.mod.rel}:{line}: {desc}")


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    root = pathlib.Path(args.root).resolve() if args.root else default_root()

    if args.expect_json_version is not None and (
        args.expect_json_version != JSON_SCHEMA_VERSION
    ):
        print(
            f"JSON schema version mismatch: this analyzer emits v"
            f"{JSON_SCHEMA_VERSION}, consumer expects v"
            f"{args.expect_json_version} — update the consumer "
            "(v2 added per-finding `chain` provenance; see "
            "docs/static_analysis.md)",
            file=sys.stderr,
        )
        return 2

    if args.list_rules:
        for name, (_fn, sev, doc) in sorted(all_rules().items()):
            head = doc.splitlines()[0] if doc else ""
            print(f"{name}  [{sev}]  {head}")
        return 0

    if not (root / "crdt_enc_tpu").is_dir() or not (root / "docs").is_dir():
        # an installed (site-packages) cli.py cannot infer the checkout
        print(
            f"{root} is not a repo checkout (no crdt_enc_tpu/ + docs/); "
            "run from the repository or pass --root",
            file=sys.stderr,
        )
        return 2

    if args.effects:
        from .effects import effect_index

        idx = effect_index(Project(root, None))
        matches = idx.lookup(args.effects)
        if not matches:
            print(
                f"no function matching {args.effects!r} — use a dotted "
                "qualname (Core.open) or a key "
                "(crdt_enc_tpu/core/core.py::Core.open)",
                file=sys.stderr,
            )
            return 2
        if args.json:
            print(json.dumps(
                {"version": JSON_SCHEMA_VERSION,
                 "functions": [_effects_json(idx, fi) for fi in matches]},
                indent=2, sort_keys=True,
            ))
        else:
            for fi in matches:
                _print_effects(idx, fi)
        return 0

    try:
        rules = args.rules
        if rules:
            unknown = set(rules) - set(all_rules())
            if unknown:
                print(f"unknown rule(s): {sorted(unknown)}", file=sys.stderr)
                return 2
        baseline = (
            None if args.no_baseline else Baseline.load(root / BASELINE_REL)
        )
    except ValueError as e:
        print(f"baseline error: {e}", file=sys.stderr)
        return 2

    t0 = time.monotonic()
    try:
        raw = []
        for arg in args.paths:
            p = pathlib.Path(arg).resolve()
            if p.is_dir():
                # a directory argument means "every in-scope file under
                # it" — without the expansion `crdt-analyze foo/` would
                # analyze zero files yet exit 0
                found = sorted(
                    f for f in p.rglob("*.py")
                    if Project.in_scan_scope(root, f)
                )
                if not found:
                    print(
                        f"note: {p.relative_to(root).as_posix()} "
                        "contains no in-scope files, skipped",
                        file=sys.stderr,
                    )
                raw.extend(found)
            else:
                raw.append(p)
        skipped = [p for p in raw if not Project.in_scan_scope(root, p)]
        for p in skipped:
            # tests/, tools/, docs/ are exempt by contract (SCAN_GLOBS):
            # a hook feeding changed files must not get spurious errors
            print(
                f"note: {p.relative_to(root).as_posix()} is outside the "
                "analysis scope, skipped",
                file=sys.stderr,
            )
        paths = [p for p in raw if p not in skipped] if args.paths else None
        project = Project(root, paths)
    except (ValueError, OSError) as e:
        # an explicit path outside the root, or unreadable
        print(f"path error: {e}", file=sys.stderr)
        return 2
    findings = run(project, rules, baseline)
    elapsed = time.monotonic() - t0

    stale = baseline.stale_entries() if baseline is not None else []
    if rules:  # a subset run can't judge other rules' entries
        stale = [e for e in stale if e.rule in rules]
    if project.partial:  # nor can a path-subset run judge any of them
        stale = []
    errors = unsuppressed_errors(findings)
    visible = [f for f in findings if f.suppressed is None]
    suppressed = [f for f in findings if f.suppressed is not None]

    if args.json:
        out = {
            "version": JSON_SCHEMA_VERSION,
            "root": str(root),
            "elapsed_s": round(elapsed, 3),
            "rules": sorted(rules) if rules else sorted(all_rules()),
            "findings": [f.to_json() for f in findings],
            "stale_baseline": [
                {"rule": e.rule, "path": e.path, "context": e.context,
                 "reason": e.reason}
                for e in stale
            ],
            "summary": {
                "files": len(project.modules),
                "errors": len(errors),
                "warnings": len(
                    [f for f in visible if f.severity != SEV_ERROR]
                ),
                "suppressed": len(suppressed),
            },
        }
        print(json.dumps(out, indent=2, sort_keys=True))
    else:
        for f in visible:
            print(f.render())
        for e in stale:
            print(
                f"STALE baseline entry {e.rule} {e.path}"
                + (f" ({e.context})" if e.context else "")
                + f" matched nothing — delete it (reason was: {e.reason})"
            )
        n_warn = len([f for f in visible if f.severity != SEV_ERROR])
        print(
            f"{len(project.modules)} files, {len(errors)} error(s), "
            f"{n_warn} warning(s), {len(suppressed)} suppressed, "
            f"{len(stale)} stale baseline entr(y/ies) in {elapsed:.2f}s"
        )

    if errors:
        return 1
    if args.diff_baseline and stale:
        return 1
    return 0

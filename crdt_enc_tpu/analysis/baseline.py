"""Committed suppression baseline for the static-analysis engine.

``tools/analysis_baseline.toml`` records the *deliberate* exceptions to
the project invariants — each entry names the rule, the file, usually
the enclosing function, a human reason, and a pinned ``max`` match
count.  The pin is what keeps the baseline honest: a NEW violation in an
already-baselined function exceeds the pin and surfaces instead of
riding the old exception (the thread-discipline lint's per-file site
counts, generalized).

The file is a deliberately small TOML subset so the engine stays stdlib
on Python 3.10 (no ``tomllib``): comments, ``[[suppress]]`` array
headers, and ``key = "string" | integer`` pairs.  :func:`parse_toml`
rejects anything else loudly rather than guessing.

Matching: a finding matches an entry when the rule and path are equal
and the entry's ``context`` (if present) equals the finding's enclosing
function qualname.  Entries suppress at most ``max`` findings (default
1), in source order; ``reason`` is mandatory — an unexplained exception
is indistinguishable from a rubber stamp.
"""

from __future__ import annotations

import dataclasses
import pathlib
import re

from .engine import Finding

_HEADER_RE = re.compile(r"^\[\[(\w+)\]\]$")
_PAIR_RE = re.compile(r"^(\w+)\s*=\s*(\"(?:[^\"\\]|\\.)*\"|\d+)$")


def _strip_comment(raw: str) -> str:
    """Drop a trailing ``#`` comment — but not a ``#`` inside a quoted
    value (reasons legitimately reference issue numbers)."""
    out = []
    in_str = False
    i = 0
    while i < len(raw):
        c = raw[i]
        if in_str:
            if c == "\\" and i + 1 < len(raw):
                out.append(raw[i : i + 2])
                i += 2
                continue
            if c == '"':
                in_str = False
        elif c == '"':
            in_str = True
        elif c == "#":
            break
        out.append(c)
        i += 1
    return "".join(out).strip()


def parse_toml(text: str) -> list[dict]:
    """Parse the ``[[suppress]]`` TOML subset (see module docs)."""
    entries: list[dict] = []
    current: dict | None = None
    for lineno, raw in enumerate(text.splitlines(), start=1):
        line = _strip_comment(raw)
        if not line:
            continue
        m = _HEADER_RE.match(line)
        if m:
            if m.group(1) != "suppress":
                raise ValueError(
                    f"baseline line {lineno}: unknown table [[{m.group(1)}]]"
                )
            current = {}
            entries.append(current)
            continue
        m = _PAIR_RE.match(line)
        if m and current is not None:
            key, val = m.group(1), m.group(2)
            if val.startswith('"'):
                current[key] = val[1:-1].replace('\\"', '"').replace("\\\\", "\\")
            else:
                current[key] = int(val)
            continue
        raise ValueError(f"baseline line {lineno}: cannot parse {raw!r}")
    return entries


@dataclasses.dataclass
class Entry:
    rule: str
    path: str
    reason: str
    context: str | None = None
    contains: str | None = None  # message substring, for co-located findings
    max: int = 1
    matched: int = 0

    def matches(self, f: Finding) -> bool:
        if self.rule != f.rule or self.path != f.path:
            return False
        if self.context is not None and self.context != f.context:
            return False
        if self.contains is not None and self.contains not in f.message:
            return False
        return True


class Baseline:
    def __init__(self, entries: list[Entry]):
        self.entries = entries

    @classmethod
    def load(cls, path: pathlib.Path | str) -> "Baseline":
        path = pathlib.Path(path)
        if not path.exists():
            return cls([])
        entries = []
        allowed = {"rule", "path", "reason", "context", "contains", "max"}
        for i, raw in enumerate(parse_toml(path.read_text())):
            missing = {"rule", "path", "reason"} - set(raw)
            if missing:
                raise ValueError(
                    f"baseline entry #{i + 1} missing {sorted(missing)}"
                )
            unknown = set(raw) - allowed
            if unknown:
                # a typo'd narrowing key (`contain`, `contxt`) must not
                # silently WIDEN the suppression
                raise ValueError(
                    f"baseline entry #{i + 1} has unknown "
                    f"key(s) {sorted(unknown)}"
                )
            entries.append(
                Entry(
                    rule=raw["rule"],
                    path=raw["path"],
                    reason=raw["reason"],
                    context=raw.get("context"),
                    contains=raw.get("contains"),
                    max=int(raw.get("max", 1)),
                )
            )
        return cls(entries)

    def apply(self, findings: list[Finding]) -> None:
        """Tag baseline-covered findings, in source order, up to each
        entry's ``max`` pin.  Pragma-suppressed findings don't consume
        baseline slots."""
        for entry in self.entries:
            entry.matched = 0
        for f in findings:
            if f.suppressed is not None:
                continue
            for entry in self.entries:
                if entry.matched < entry.max and entry.matches(f):
                    f.suppressed = "baseline"
                    entry.matched += 1
                    break

    def stale_entries(self) -> list[Entry]:
        """Entries that matched nothing in the last :meth:`apply` — the
        exception they document no longer exists and should be deleted."""
        return [e for e in self.entries if e.matched == 0]

"""SEC001 — no key material in spans, logs, or exception messages.

The paper's whole premise is that the storage/observability boundary
never sees plaintext or key bytes; one ``logger.warning("bad key %r",
key)`` undoes it.  The rule taints names whose tokens say they hold
secrets (``key``, ``passphrase``, ``plaintext``, ``secret``, ...),
propagates taint through straight-line assignments within a function,
and flags tainted values reaching an observability/log/exception sink:
``trace.span/add/gauge/observe`` args (incl. ``meta=``), ``logger.*``
and ``warnings.warn`` args, ``print``, and the arguments of a raised
exception.

Public *facts about* secrets are fine and excluded: ``len(key)``,
``type(key)``, ``key.key_id`` and other identifier-ish attributes, and
any name whose tokens include a public-fact marker (``id``,
``version``, ``len``, ``path``, ...) — ``key_id``/``key_path`` name
metadata, not material.  ``x.hex()`` on a tainted value is NOT exempt:
hex-encoding a key is still the key.
"""

from __future__ import annotations

import ast

from ..astutil import call_name, functions, walk_in
from ..engine import SEV_ERROR, Finding, Project, rule
from .exc import _LOG_ATTRS
from .spans import _is_obs_call

_SECRET_TOKENS = {
    "key", "keys", "passphrase", "password", "secret", "plaintext",
    "material", "privkey", "seckey",
}
#: a name containing any of these tokens is metadata about a secret,
#: not the secret itself (key_id, key_version, key_path, keylen...)
_PUBLIC_TOKENS = {
    "id", "ids", "version", "versions", "len", "length", "count", "num",
    "n", "name", "names", "path", "paths", "file", "files", "dir",
    "fmt", "type", "kind", "error", "err", "exc", "meta", "index", "idx",
    "ring", "cls", "backend", "cryptor", "store", "storage", "manager",
    "registry", "cache", "hash", "digest", "fingerprint", "public", "pub",
    "size", "sizes", "offset", "offsets",
}
_PUBLIC_ATTRS = {
    "key_id", "id", "version", "key_version", "name", "kind", "hex_id",
    # facts about an array, not its contents
    "shape", "ndim", "dtype", "size", "nbytes", "itemsize",
}
_SAFE_WRAPPERS = {"len", "type", "bool", "sorted", "list", "set"}
#: calls whose result is still the secret (taint flows through);
#: everything else blocks propagation — a status code or row count
#: computed FROM a key is not the key
_IDENTITY_CALLS = {
    "bytes", "bytearray", "memoryview", "hex", "frombuffer", "asarray",
    "ascontiguousarray", "in_ptr", "data_as", "tobytes", "decode",
    "encode", "join", "derive", "copy",
}

# sink identification is shared with EXC001 (_LOG_ATTRS) and SPN001
# (_KINDS/_RECEIVERS) — one definition per sink family


def _is_secret_name(name: str) -> bool:
    tokens = set(name.lower().strip("_").split("_"))
    if not tokens & _SECRET_TOKENS:
        return False
    return not tokens & _PUBLIC_TOKENS


def _names_in(expr: ast.AST):
    for n in walk_in(expr, ast.Name):
        if isinstance(n.ctx, ast.Load):
            yield n


def _tainted_refs(mod, expr: ast.AST, tainted: set[str]):
    """Tainted Name nodes in ``expr`` that are not behind a public-fact
    wrapper (len/type/...) or a public attribute."""
    for name in _names_in(expr):
        if name.id not in tainted:
            continue
        allowed = False
        cur, parent = name, mod.parents.get(name)
        while parent is not None and cur is not expr:
            if isinstance(parent, ast.Attribute) and parent.attr in _PUBLIC_ATTRS:
                allowed = True
                break
            if isinstance(parent, ast.Call):
                cn = (call_name(parent) or "").rsplit(".", 1)[-1]
                if cn in _SAFE_WRAPPERS and cur in parent.args:
                    allowed = True
                    break
            cur, parent = parent, mod.parents.get(parent)
        if not allowed:
            yield name


def _blocks_propagation(mod, name: ast.Name, rhs: ast.AST) -> bool:
    """Taint does NOT flow out of a call unless the call is
    identity-ish (``bytes(key)`` is still the key; ``decrypt(key, b)``'s
    status/count is not)."""
    cur, parent = name, mod.parents.get(name)
    while parent is not None and cur is not rhs:
        if isinstance(parent, ast.Call):
            cn = (call_name(parent) or "").rsplit(".", 1)[-1]
            # a method ON the tainted value (key.hex()) keeps taint
            on_tainted = (
                isinstance(parent.func, ast.Attribute)
                and parent.func.value is cur
            )
            if cn not in _IDENTITY_CALLS and not on_tainted:
                return True
        cur, parent = parent, mod.parents.get(parent)
    return False


def _target_names(target: ast.AST):
    if isinstance(target, ast.Name):
        yield target
    elif isinstance(target, ast.Starred):
        yield from _target_names(target.value)
    elif isinstance(target, (ast.Tuple, ast.List)):
        for e in target.elts:
            yield from _target_names(e)


def _bindings(fn):
    """(target Name nodes, bound expr or None) for EVERY binding form —
    plain/annotated/augmented assignment, for targets, with-as, walrus,
    and comprehension targets all bind names a secret can arrive
    through, not just ``ast.Assign``."""
    for a in walk_in(fn, ast.Assign):
        names = [n for t in a.targets for n in _target_names(t)]
        yield names, a.value
    for a in walk_in(fn, ast.AnnAssign, ast.AugAssign):
        yield list(_target_names(a.target)), a.value
    for loop in walk_in(fn, ast.For, ast.AsyncFor):
        yield list(_target_names(loop.target)), loop.iter
    for w in walk_in(fn, ast.With, ast.AsyncWith):
        for item in w.items:
            if item.optional_vars is not None:
                yield (
                    list(_target_names(item.optional_vars)),
                    item.context_expr,
                )
    for comp in walk_in(fn, ast.comprehension):
        yield list(_target_names(comp.target)), comp.iter
    for ne in walk_in(fn, ast.NamedExpr):
        yield list(_target_names(ne.target)), ne.value


def _function_taint(mod, fn) -> set[str]:
    from ..astutil import func_params

    tainted = {p for p in func_params(fn) if _is_secret_name(p)}
    bindings = list(_bindings(fn))
    # secret-named binding targets are sources by convention
    # (`key = storage.load_key(...)`) — naming IS the project contract
    for names, _ in bindings:
        for n in names:
            if _is_secret_name(n.id):
                tainted.add(n.id)
    changed = True
    while changed:  # fixpoint: chains may taint against source order
        changed = False
        for names, value in bindings:
            if value is None:
                continue
            rhs_tainted = any(
                not _blocks_propagation(mod, n, value)
                for n in _tainted_refs(mod, value, tainted)
            )
            if not rhs_tainted:
                continue
            for n in names:
                if n.id not in tainted:
                    tainted.add(n.id)
                    changed = True
    return tainted


def _sink_exprs(mod, fn):
    """Yield (kind, line, context_node, [exprs]) for every sink in fn."""
    for node in walk_in(fn, ast.Call):
        func = node.func
        if isinstance(func, ast.Attribute):
            if _is_obs_call(func):
                exprs = list(node.args) + [kw.value for kw in node.keywords]
                yield "trace meta", node, exprs
                continue
            if func.attr in _LOG_ATTRS:
                yield "log call", node, list(node.args) + [
                    kw.value for kw in node.keywords if kw.arg != "exc_info"
                ]
                continue
        cn = call_name(node) or ""
        if cn in ("warnings.warn", "print"):
            yield "log call", node, list(node.args)
    for node in walk_in(fn, ast.Raise):
        if isinstance(node.exc, ast.Call):
            yield "exception message", node, list(node.exc.args) + [
                kw.value for kw in node.exc.keywords
            ]


@rule("SEC001", SEV_ERROR)
def no_secrets_in_telemetry(project: Project):
    """Key material / plaintext must not reach spans, logs, or exception
    messages."""
    for mod in project.modules:
        # examples print the user's own decrypted data by design, and
        # benchmarks log synthetic corpora — the boundary this rule
        # guards is the LIBRARY's
        if not mod.rel.startswith("crdt_enc_tpu/"):
            continue
        for fn in functions(mod):
            tainted = _function_taint(mod, fn)
            if not tainted:
                continue
            for kind, node, exprs in _sink_exprs(mod, fn):
                hits: list[str] = []
                for expr in exprs:
                    hits.extend(
                        n.id for n in _tainted_refs(mod, expr, tainted)
                    )
                if hits:
                    uniq = ", ".join(sorted(set(hits)))
                    yield Finding(
                        rule="SEC001", severity=SEV_ERROR, path=mod.rel,
                        line=node.lineno, context=mod.context_of(node),
                        message=(
                            f"secret-tainted value(s) `{uniq}` reach a "
                            f"{kind} — key material must never cross the "
                            "observability/log boundary (lengths and "
                            "key_ids are fine)"
                        ),
                    )

"""FFI001 — every ctypes foreign call fully declared, checked, and bounded.

The invariant this encodes was bought with real bugs (ADVICE r5's
unbounded ``bytes_lens_join`` out-buffer): a ctypes call with no
``argtypes``/``restype`` declaration silently marshals through default
int conversions, an unchecked status return hides partial native fills,
and an out-buffer with no capacity argument is an overflow waiting for a
larger batch.  Concretely:

* every foreign function bound anywhere in the tree must declare BOTH
  ``argtypes`` and ``restype`` (a partial binding is worse than none —
  it looks audited);
* a declaration whose ``argtypes`` include raw pointer types must also
  carry at least one integer scalar (the capacity/length channel);
  fixed-width primitives (e.g. ``hchacha20``'s 32/16-byte blocks) are
  deliberate exceptions and live in the baseline with that reason;
* a call site invoking a bound function with an integer ``restype``
  must not discard the result (an expression statement) — that status
  is the only overflow/race signal the native side has;
* a call through a native library handle (a local assigned from
  ``native.load()`` / ``native.load_state()``) to a name with no
  declaration anywhere in the tree is an undeclared foreign call.
"""

from __future__ import annotations

import ast

from ..astutil import assigned_names, call_name, dotted, enclosing, walk_in
from ..engine import SEV_ERROR, Finding, Project, rule

_INT_CTYPES = {
    "c_int", "c_uint", "c_long", "c_ulong", "c_int32", "c_uint32",
    "c_int64", "c_uint64", "c_size_t", "c_ssize_t", "c_longlong",
    "c_ulonglong",
}
# receiver spellings that are a native library handle even without a
# visible `= native.load()` assignment in the same function
_LIB_NAMES = {"lib", "slib", "state_lib", "_state_lib", "_lib"}


class _Decl:
    __slots__ = ("argtypes", "restype", "argtypes_line", "restype_line", "rel")

    def __init__(self):
        self.argtypes = None
        self.restype = "<unset>"
        self.argtypes_line = 0
        self.restype_line = 0
        self.rel = ""


def _pointer_aliases(mod) -> set[str]:
    """Local/module names bound to ``ctypes.POINTER(...)`` (u8p, i32p...)."""
    out = set()
    for node in mod.walk(ast.Assign):
        if (
            isinstance(node.value, ast.Call)
            and call_name(node.value) in ("ctypes.POINTER", "POINTER")
        ):
            for t in node.targets:
                out.update(assigned_names(t))
    return out


def _classify_argtype(node: ast.AST, ptr_aliases: set[str]) -> str:
    """'ptr' | 'int' | 'other' for one element of an argtypes list."""
    name = dotted(node)
    if name is not None:
        base = name.rsplit(".", 1)[-1]
        if name in ptr_aliases or base in ptr_aliases:
            return "ptr"
        if base in _INT_CTYPES:
            return "int"
        return "other"  # py_object, c_char_p, c_void_p, ...
    if isinstance(node, ast.Call) and call_name(node) in (
        "ctypes.POINTER", "POINTER"
    ):
        return "ptr"
    return "other"


def _is_int_restype(expr) -> bool:
    if not isinstance(expr, ast.AST):
        return False
    name = dotted(expr)
    return name is not None and name.rsplit(".", 1)[-1] in _INT_CTYPES


def _record(decls: dict[str, _Decl], name: str, attr: str, node, mod):
    d = decls.setdefault(name, _Decl())
    d.rel = d.rel or mod.rel
    if attr == "argtypes":
        d.argtypes = node.value
        d.argtypes_line = node.lineno
    else:
        d.restype = node.value
        d.restype_line = node.lineno


def _loop_const_names(loop: ast.For) -> list[str]:
    """String constants iterated by ``for name in ("a", "b"):``."""
    if isinstance(loop.iter, (ast.Tuple, ast.List)):
        return [
            e.value
            for e in loop.iter.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        ]
    return []


def _collect_declarations(project: Project) -> dict[str, _Decl]:
    decls: dict[str, _Decl] = {}
    for mod in project.modules:
        for node in mod.walk(ast.Assign):
            for target in node.targets:
                if not (
                    isinstance(target, ast.Attribute)
                    and target.attr in ("argtypes", "restype")
                ):
                    continue
                recv = target.value
                # direct form: lib.NAME.argtypes = [...]
                if isinstance(recv, ast.Attribute):
                    _record(decls, recv.attr, target.attr, node, mod)
                    continue
                # loop form: for name in ("a","b"): fn = getattr(lib, name);
                #            fn.argtypes = [...]
                if isinstance(recv, ast.Name):
                    loop = enclosing(mod, node, ast.For)
                    if loop is None:
                        continue
                    bound = False
                    for a in walk_in(loop, ast.Assign):
                        if (
                            isinstance(a.value, ast.Call)
                            and call_name(a.value) == "getattr"
                            and any(
                                n == recv.id for t in a.targets
                                for n in assigned_names(t)
                            )
                        ):
                            bound = True
                    if bound:
                        for cname in _loop_const_names(loop):
                            _record(decls, cname, target.attr, node, mod)
    return decls


def _lib_locals(fn_node) -> set[str]:
    """Names assigned from native.load()/load_state() within a function."""
    out = set(_LIB_NAMES)
    for a in walk_in(fn_node, ast.Assign):
        if isinstance(a.value, ast.Call):
            cn = call_name(a.value) or ""
            if cn.endswith(("native.load", "native.load_state")) or cn in (
                "load", "load_state"
            ):
                for t in a.targets:
                    out.update(assigned_names(t))
    return out


@rule("FFI001", SEV_ERROR)
def ffi_contract(project: Project):
    """ctypes bindings: argtypes+restype declared in pairs, pointer args
    carry a capacity channel, int status returns are consumed, and no
    call through a native handle hits an undeclared name."""
    decls = _collect_declarations(project)

    for name, d in sorted(decls.items()):
        if d.argtypes is None or d.restype == "<unset>":
            missing = "restype" if d.restype == "<unset>" else "argtypes"
            line = d.argtypes_line or d.restype_line
            yield Finding(
                rule="FFI001", severity=SEV_ERROR, path=d.rel, line=line,
                message=(
                    f"foreign function `{name}` declares "
                    f"{'argtypes' if missing == 'restype' else 'restype'} "
                    f"but not {missing} — partial bindings marshal through "
                    "default int conversion"
                ),
            )
            continue
        mod = project.module(d.rel)
        ptr_aliases = _pointer_aliases(mod) if mod else set()
        if isinstance(d.argtypes, (ast.List, ast.Tuple)):
            kinds = [
                _classify_argtype(e, ptr_aliases) for e in d.argtypes.elts
            ]
            if "ptr" in kinds and "int" not in kinds:
                ctx = mod.context_of(d.argtypes) if mod else "<module>"
                yield Finding(
                    rule="FFI001", severity=SEV_ERROR, path=d.rel,
                    line=d.argtypes_line, context=ctx,
                    message=(
                        f"foreign function `{name}` takes pointer arguments "
                        "but no integer capacity/length argument — an "
                        "out-buffer pass with no bound (bytes_lens_join bug "
                        "class)"
                    ),
                )

    # call-site checks
    for mod in project.modules:
        lib_locals_cache: dict[ast.AST, set[str]] = {}
        for call in mod.walk(ast.Call):
            func = call.func
            if not (
                isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
            ):
                continue
            recv, fname = func.value.id, func.attr
            fn_node = enclosing(mod, call, ast.FunctionDef, ast.AsyncFunctionDef)
            if fn_node is not None and fn_node not in lib_locals_cache:
                lib_locals_cache[fn_node] = _lib_locals(fn_node)
            handles = lib_locals_cache.get(fn_node, _LIB_NAMES)
            if recv not in handles:
                continue
            ctx = mod.context_of(call)
            if fname not in decls:
                if fname in ("argtypes", "restype"):
                    continue
                if project.partial:
                    # declarations are cross-file (native/load.py binds
                    # what ops/ calls); a path-subset run can't judge
                    # them — same contract as the stale-span skip
                    continue
                yield Finding(
                    rule="FFI001", severity=SEV_ERROR, path=mod.rel,
                    line=call.lineno, context=ctx,
                    message=(
                        f"call `{recv}.{fname}(...)` has no argtypes/restype "
                        "declaration anywhere in the tree — undeclared "
                        "foreign call"
                    ),
                )
                continue
            d = decls[fname]
            if _is_int_restype(d.restype):
                parent = mod.parents.get(call)
                if isinstance(parent, ast.Expr):
                    yield Finding(
                        rule="FFI001", severity=SEV_ERROR, path=mod.rel,
                        line=call.lineno, context=ctx,
                        message=(
                            f"`{recv}.{fname}(...)` returns an integer "
                            "status but the result is discarded — overflow/"
                            "race signals vanish"
                        ),
                    )

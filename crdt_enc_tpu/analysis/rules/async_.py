"""ASY001 — the event loop never blocks, and sync sections never yield.

Two obligations, both on the cooperative-scheduling contract that the
population runner (PR 18) and the serve tier depend on:

* no ``blocks`` effect (file/socket I/O, ``time.sleep``, subprocess,
  native FFI, ``lock.acquire()``, jit D2H sync) may be reachable from
  an ``async def`` body in ``serve/``/``sim/``/``core/`` except through
  a sanctioned off-loop seam — ``asyncio.to_thread``/``run_in_executor``
  and the ingest producer pool are modelled as laundering edges by the
  effect engine, everything else needs a baseline entry with a reason;
* no ``await`` inside a declared *sync section* — a region bracketed by
  ``# lint: sync-section-begin`` / ``# lint: sync-section-end`` whose
  correctness depends on not yielding to the loop (the compaction
  snapshot/cursor/delta-plan cut in ``core._compact_seal``).

Findings carry the provenance chain: the call path from the async body
down to the line that actually blocks.  When the effect arrives *via*
another in-scope async function, the finding is reported there (once),
not at every transitive caller.
"""

from __future__ import annotations

import ast
import re

from ..effects import KIND_BLOCKS, effect_index
from ..engine import SEV_ERROR, Finding, Project, rule

_SCOPE_PREFIXES = (
    "crdt_enc_tpu/serve/",
    "crdt_enc_tpu/sim/",
    "crdt_enc_tpu/core/",
)

_BEGIN_RE = re.compile(r"#\s*lint:\s*sync-section-begin\b")
_END_RE = re.compile(r"#\s*lint:\s*sync-section-end\b")


def _in_scope(rel: str) -> bool:
    return rel.startswith(_SCOPE_PREFIXES)


def _sync_sections(mod):
    """(begin_line, end_line) regions; unterminated regions yield
    (begin_line, None)."""
    begin = None
    for i, line in enumerate(mod.lines, start=1):
        # markers are standalone comment lines — a mention inside a
        # docstring or trailing a statement is not a declaration
        if not line.lstrip().startswith("#"):
            continue
        if _BEGIN_RE.search(line):
            if begin is not None:
                yield (begin, None)  # previous region never closed
            begin = i
        elif _END_RE.search(line):
            if begin is not None:
                yield (begin, i)
                begin = None
    if begin is not None:
        yield (begin, None)


@rule("ASY001", SEV_ERROR)
def no_blocking_in_async(project: Project):
    """Async bodies in serve/sim/core must not reach a blocks effect
    except through sanctioned off-loop seams; declared sync sections
    must not await."""
    idx = effect_index(project)
    for fi in idx.funcs.values():
        if not fi.is_async or not _in_scope(fi.mod.rel):
            continue
        for (kind, origin), prov in sorted(fi.effects.items()):
            if kind != KIND_BLOCKS:
                continue
            if prov.via:
                callee = idx.funcs.get(prov.via)
                if callee is not None and callee.is_async and _in_scope(callee.mod.rel):
                    continue  # reported at the inner async boundary
            chain = idx.chain(fi.key, kind, origin)
            yield Finding(
                rule="ASY001",
                severity=SEV_ERROR,
                path=fi.mod.rel,
                line=prov.line,
                context=fi.qualname,
                message=(
                    f"async def reaches blocking effect `{origin}` — "
                    "move it behind asyncio.to_thread / the producer "
                    "pool, or baseline with a reason"
                ),
                chain=chain,
            )
    for mod in project.modules:
        sections = list(_sync_sections(mod))
        if not sections:
            continue
        for begin, end in sections:
            if end is None:
                yield Finding(
                    rule="ASY001",
                    severity=SEV_ERROR,
                    path=mod.rel,
                    line=begin,
                    message=(
                        "sync-section-begin without a matching "
                        "sync-section-end — the region must be closed "
                        "explicitly"
                    ),
                )
        closed = [(b, e) for b, e in sections if e is not None]
        if not closed:
            continue
        for node in mod.walk(ast.Await):
            for b, e in closed:
                if b < node.lineno < e:
                    yield Finding(
                        rule="ASY001",
                        severity=SEV_ERROR,
                        path=mod.rel,
                        line=node.lineno,
                        context=mod.context_of(node),
                        message=(
                            f"await inside the sync section declared at "
                            f"line {b} — the region's snapshot/cursor cut "
                            "must not yield to the event loop"
                        ),
                    )
                    break

"""EXC001 — no silent broad except around native/crypto fast paths.

A ``except Exception: <fall back>`` around a ``native.*`` or xchacha
fast path is how the project has repeatedly lost its native
optimizations without noticing (ADVICE r5: a binding regression made
``bytes_lens_join`` raise, the broad except ate it, and every bulk
decrypt silently ran the slow Python path for a round).  The fix
pattern is established (``_warn_no_native_lens``): fall back, but LOG
once.  This rule enforces it: a broad handler (bare ``except``,
``Exception``, ``BaseException``) whose try body touches a native
fast-path root must either re-raise or call something that visibly
logs (``logger.warning/...``, a ``*warn*`` helper, ``warnings.warn``).
"""

from __future__ import annotations

import ast
import re

from ..astutil import call_name, walk_in
from ..engine import SEV_ERROR, Finding, Project, rule
from .ffi import _LIB_NAMES

_BROAD = {"Exception", "BaseException"}
_LOG_ATTRS = {"warning", "error", "exception", "info", "debug", "critical"}
_WARN_NAME_RE = re.compile(r"warn", re.IGNORECASE)


def _fast_path_roots(mod) -> set[str]:
    """Module-level names that are native fast-path entry points: the
    ``native`` package itself and the xchacha backend, however imported."""
    roots = set()
    for node in mod.walk(ast.Import, ast.ImportFrom):
        if isinstance(node, ast.ImportFrom):
            module = node.module or ""
            for alias in node.names:
                name = alias.asname or alias.name
                if alias.name in ("native", "xchacha") or module.endswith(
                    ("native", "xchacha")
                ):
                    roots.add(name)
        else:
            for alias in node.names:
                if alias.name.endswith(("native", "xchacha")):
                    roots.add(alias.asname or alias.name.split(".")[0])
    return roots


def _touches_fast_path(body: list[ast.stmt], roots: set[str]) -> bool:
    for stmt in body:
        for node in walk_in(stmt):
            if isinstance(node, ast.Attribute) and isinstance(
                node.value, ast.Name
            ):
                if node.value.id in roots:
                    return True
            if isinstance(node, ast.Call):
                cn = call_name(node) or ""
                recv = cn.rsplit(".", 1)[0] if "." in cn else ""
                if recv in roots or cn.split(".")[0] in roots:
                    return True
                # calls through a native handle are native calls (the
                # receiver spellings are FFI001's, kept in one place)
                if recv in _LIB_NAMES:
                    return True
    return False


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    for node in ([t] if not isinstance(t, ast.Tuple) else t.elts):
        if isinstance(node, ast.Name):
            names.append(node.id)
        elif isinstance(node, ast.Attribute):
            names.append(node.attr)
    return any(n in _BROAD for n in names)


def _handler_is_loud(handler: ast.ExceptHandler) -> bool:
    for node in walk_in(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            cn = call_name(node) or ""
            last = cn.rsplit(".", 1)[-1]
            if last in _LOG_ATTRS:
                return True
            if _WARN_NAME_RE.search(last):
                return True
    return False


@rule("EXC001", SEV_ERROR)
def silent_native_fallback(project: Project):
    """Broad except around a native/xchacha fast path must re-raise or
    log the fallback (one-shot helpers count)."""
    for mod in project.modules:
        roots = _fast_path_roots(mod)
        for node in mod.walk(ast.Try):
            if not _touches_fast_path(node.body, roots):
                continue
            for handler in node.handlers:
                if not _is_broad(handler):
                    continue
                if _handler_is_loud(handler):
                    continue
                yield Finding(
                    rule="EXC001", severity=SEV_ERROR, path=mod.rel,
                    line=handler.lineno, context=mod.context_of(handler),
                    message=(
                        "broad except swallows a native fast-path failure "
                        "with no logged fallback — the optimization can "
                        "silently disable (bytes_lens_join regression "
                        "class); log once (e.g. a _warn_* helper) or "
                        "re-raise"
                    ),
                )

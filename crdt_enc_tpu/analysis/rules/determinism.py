"""DET001 — sim-replayable surfaces stay bit-deterministic, statically.

The PR-18 population runner's contract is that a schedule replays to a
bit-identical fingerprint.  That holds only while every entropy source
on a replayable surface goes through a seeded seam.  This rule checks
the propagated ``wall_clock``/``rng`` effect sets of every function in
``sim/`` and the schedule-driven daemon module, with the seams the
runtime actually provides carved out:

* ``uuid4``/``uuid1`` origins — the sim installs a refcounted,
  ContextVar-dispatched ``uuid.uuid4`` patch (``sim/runner.py``), so a
  uuid draw on a replayable surface IS seeded at replay time;
* wall-clock reads whose *direct origin* lives in ``crdt_enc_tpu/obs/``
  — telemetry timestamps annotate spans and live dashboards and never
  enter fingerprints or schedule decisions;
* seeded constructions are invisible by design: ``random.Random(seed)``
  is not an rng effect, ``clock=``/``on_poll=`` parameters resolve to
  injected callables (honestly reported as unresolved, not guessed),
  and SHA-256 fault rolls are hashes, not entropy.

The runtime half (the simulator actually replaying and comparing
fingerprints) still exists — this rule is the cheap static half that
fails a violating call chain in seconds instead of needing an all-fault
schedule to fire.  Effects arriving *via* another on-surface function
are reported there, once.
"""

from __future__ import annotations

from ..effects import KIND_RNG, KIND_WALL, effect_index
from ..engine import SEV_ERROR, Finding, Project, rule

_SURFACE_PREFIXES = ("crdt_enc_tpu/sim/",)
_SURFACE_FILES = ("crdt_enc_tpu/serve/daemon.py",)

#: uuid draws go through the sim's ContextVar dispatch seam
_SANCTIONED_ORIGINS = ("uuid4", "uuid1", "uuid.uuid4", "uuid.uuid1")
#: wall-clock reads rooted in obs/ are telemetry, never replay inputs
_TELEMETRY_PREFIX = "crdt_enc_tpu/obs/"


def _on_surface(rel: str) -> bool:
    return rel.startswith(_SURFACE_PREFIXES) or rel in _SURFACE_FILES


def _direct_origin_rel(idx, key: str, kind: str, origin: str) -> str | None:
    """Follow via-links to the file containing the direct origin."""
    seen: set[str] = set()
    k: str | None = key
    while k and k not in seen:
        seen.add(k)
        fi = idx.funcs.get(k)
        if fi is None:
            return None
        prov = fi.effects.get((kind, origin))
        if prov is None:
            return None
        if prov.via is None:
            return prov.rel
        k = prov.via
    return None


@rule("DET001", SEV_ERROR)
def determinism_on_sim_surfaces(project: Project):
    """No wall_clock/rng effect may reach a sim-replayable surface
    except via the seeded seams (ContextVar uuid dispatch, obs-rooted
    telemetry clocks, seeded constructors)."""
    idx = effect_index(project)
    for fi in idx.funcs.values():
        if not _on_surface(fi.mod.rel):
            continue
        for (kind, origin), prov in sorted(fi.effects.items()):
            if kind not in (KIND_WALL, KIND_RNG):
                continue
            if origin in _SANCTIONED_ORIGINS or origin.rsplit(".", 1)[-1] in (
                "uuid4",
                "uuid1",
            ):
                continue
            if prov.via:
                callee = idx.funcs.get(prov.via)
                if callee is not None and _on_surface(callee.mod.rel):
                    continue  # reported at the inner surface boundary
            if kind == KIND_WALL:
                root = _direct_origin_rel(idx, fi.key, kind, origin)
                if root is not None and root.startswith(_TELEMETRY_PREFIX):
                    continue
            chain = idx.chain(fi.key, kind, origin)
            yield Finding(
                rule="DET001",
                severity=SEV_ERROR,
                path=fi.mod.rel,
                line=prov.line,
                context=fi.qualname,
                message=(
                    f"replayable surface reaches {kind} effect `{origin}` "
                    "— route it through a seeded seam (clock= param, "
                    "ContextVar uuid dispatch, SHA-256 roll) or baseline "
                    "with a reason"
                ),
                chain=chain,
            )

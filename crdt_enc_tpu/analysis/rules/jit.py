"""JIT001/JIT002 — traced-value control flow and bounded static args.

JIT001: a Python ``if``/``while`` on a traced value inside a
``@jax.jit`` body raises ``TracerBoolConversionError`` at best and, when
it happens to trace (e.g. a weak-typed scalar), silently bakes one
branch into the compiled program.  Shape/dtype/None tests are static and
allowed (``x.shape``, ``x.ndim``, ``x.dtype``, ``x is None``,
``len(x)``, ``isinstance(x, ...)``).

JIT002: every value passed for a ``static_argnums``/``static_argnames``
parameter keys a separate compilation.  The per-batch Pallas recompile
bug (ADVICE r5: ``lww_limbs`` computed from raw column values) is this
rule's reason to exist: a static arg must be *provably bounded* at the
call site — a literal, a module/instance constant, a shape, or the
result of an allowlisted quantizer (``_bucket``, ``fold_cap``,
``lww_limbs`` & co., which round data-dependent values onto a finite
lattice).  ``len(...)`` and other raw data-dependent expressions are
exactly the unbounded case.
"""

from __future__ import annotations

import ast

from ..astutil import (
    call_name,
    dotted,
    enclosing,
    func_params,
    functions,
    walk_in,
)
from ..engine import SEV_ERROR, Finding, Project, rule

#: call names (last dotted segment) that quantize their input onto a
#: finite lattice — the sanctioned ways to bound a static argument
QUANTIZERS = {
    "_bucket", "_round_to", "fold_cap", "sharded_fold_cap", "lww_tile_cap",
    "lww_limbs", "lww_limbs_from_maxima", "stream_sharding",
}
#: builtins that preserve boundedness of already-bounded operands
_BOUNDED_WRAPPERS = {"min", "max", "int", "bool", "abs", "range"}

_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "nbytes", "itemsize"}


def _jit_decorator_info(fn: ast.FunctionDef | ast.AsyncFunctionDef):
    """(is_jitted, static_names) from the decorator list, resolving
    ``@jax.jit``, ``@jit``, ``@jax.jit(...)`` and
    ``@partial(jax.jit, static_arg...=...)`` forms."""
    for dec in fn.decorator_list:
        call_kw = []
        target = dec
        if isinstance(dec, ast.Call):
            name = call_name(dec) or ""
            if name.rsplit(".", 1)[-1] == "partial" and dec.args:
                target = dec.args[0]
            else:
                target = dec.func  # direct form: @jax.jit(static_...=...)
            call_kw = dec.keywords
        name = dotted(target) or ""
        if name not in ("jit", "jax.jit"):
            continue
        statics: set[str] = set()
        params = func_params(fn)
        for kw in call_kw:
            if kw.arg == "static_argnames":
                for s in walk_in(kw.value, ast.Constant):
                    if isinstance(s.value, str):
                        statics.add(s.value)
            elif kw.arg == "static_argnums":
                for s in walk_in(kw.value, ast.Constant):
                    if isinstance(s.value, int) and s.value < len(params):
                        statics.add(params[s.value])
        return True, statics
    return False, set()


def _allowed_traced_use(mod, name_node: ast.Name, test: ast.AST) -> bool:
    """Is this traced-param reference inside the test static-safe?"""
    cur = name_node
    parent = mod.parents.get(cur)
    while parent is not None and cur is not test:
        if isinstance(parent, ast.Attribute) and parent.attr in _STATIC_ATTRS:
            return True
        if isinstance(parent, ast.Call):
            cn = (call_name(parent) or "").rsplit(".", 1)[-1]
            if cn in ("len", "isinstance", "getattr", "hasattr", "type"):
                return True
        if isinstance(parent, ast.Compare) and any(
            isinstance(op, (ast.Is, ast.IsNot)) for op in parent.ops
        ):
            return True
        cur, parent = parent, mod.parents.get(parent)
    return False


@rule("JIT001", SEV_ERROR)
def jit_traced_branch(project: Project):
    """No Python ``if``/``while`` on traced values inside ``@jit`` bodies."""
    for mod in project.modules:
        for fn in functions(mod):
            jitted, statics = _jit_decorator_info(fn)
            if not jitted:
                continue
            traced = set(func_params(fn)) - statics - {"self"}
            for node in walk_in(fn, ast.If, ast.While):
                test = node.test
                for name in walk_in(test, ast.Name):
                    if not isinstance(name.ctx, ast.Load):
                        continue
                    if name.id not in traced:
                        continue
                    if _allowed_traced_use(mod, name, test):
                        continue
                    yield Finding(
                        rule="JIT001", severity=SEV_ERROR, path=mod.rel,
                        line=node.lineno, context=mod.context_of(node),
                        message=(
                            f"Python branch on traced value `{name.id}` "
                            f"inside @jit `{fn.name}` — use jnp.where/"
                            "lax.cond, or declare the arg static"
                        ),
                    )
                    break  # one finding per branch statement


def _collect_jitted_callees(
    project: Project,
) -> dict[str, dict[int, tuple[set[str], list[str]]]]:
    """name -> {function node id -> (static param names, positional
    param order)} for every jit-decorated function in the tree — keyed
    per definition so same-named jitted functions in different modules
    keep their own signatures instead of merging."""
    out: dict[str, dict[int, tuple[set[str], list[str]]]] = {}
    for mod in project.modules:
        for fn in functions(mod):
            jitted, statics = _jit_decorator_info(fn)
            if jitted and statics:
                out.setdefault(fn.name, {})[id(fn)] = (
                    statics, func_params(fn)
                )
    return out


def _module_consts(mod) -> set[str]:
    out = set()
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and isinstance(
            node.value, (ast.Constant, ast.BinOp)
        ):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


class _Provenance:
    """Bounded-value provenance within an enclosing function chain.

    Closures see their enclosing functions' params and locals, so the
    chain from the call site outward is the resolution scope.  Params
    read pass-through are recorded in ``passthrough`` — they are only
    sound to treat as bounded because :func:`jit_static_args_bounded`
    registers the owning function as a forwarding target and checks
    ITS call sites too (the fixpoint below)."""

    def __init__(self, mod, fn_chain: list):
        self.mod = mod
        self.params: set[str] = set()
        self.consts = _module_consts(mod)
        self.assigns: dict[str, list[ast.AST]] = {}
        self.passthrough: set[str] = set()
        self._class = (
            enclosing(mod, fn_chain[0], ast.ClassDef) if fn_chain else None
        )
        self._attr_visiting: set[str] = set()
        self._name_visiting: set[str] = set()
        for fn_node in fn_chain:
            self.params.update(func_params(fn_node))
            for a in walk_in(fn_node, ast.Assign):
                for t in a.targets:
                    self._record_target(t, a.value)
            for loop in walk_in(fn_node, ast.For):
                self._record_target(loop.target, loop.iter)

    def _record_target(self, target: ast.AST, value: ast.AST) -> None:
        if isinstance(target, ast.Name):
            self.assigns.setdefault(target.id, []).append(value)
        elif isinstance(target, (ast.Tuple, ast.List)):
            elts = (
                value.elts
                if isinstance(value, (ast.Tuple, ast.List))
                and len(value.elts) == len(target.elts)
                else None
            )
            for i, t in enumerate(target.elts):
                if isinstance(t, ast.Name):
                    # element-wise when shapes line up, else the whole RHS
                    # stands in (its boundedness bounds every element)
                    self.assigns.setdefault(t.id, []).append(
                        elts[i] if elts is not None else value
                    )

    def bounded(self, node: ast.AST, depth: int = 0) -> bool:
        if depth > 8:
            return False
        if isinstance(node, ast.Constant):
            return True
        if isinstance(node, ast.Name):
            if node.id in self.assigns:
                if node.id in self._name_visiting:
                    # self-referential rebind (`E = round_up(E)`): the
                    # cycle itself adds no unboundedness — the non-cyclic
                    # initializers decide
                    return True
                self._name_visiting.add(node.id)
                try:
                    return all(
                        self.bounded(v, depth + 1)
                        for v in self.assigns[node.id]
                    )
                finally:
                    self._name_visiting.discard(node.id)
            if node.id in self.params:
                # pass-through: sound only because the rule registers the
                # owning function as a forwarding target (fixpoint) and
                # checks its call sites with the same provenance machinery
                self.passthrough.add(node.id)
                return True
            return node.id in self.consts
        if isinstance(node, ast.Attribute):
            base = node.value
            if isinstance(base, ast.Name) and base.id in ("self", "cls"):
                # instance statics: bounded iff every in-class assignment
                # to the attribute is itself bounded (unassigned attrs are
                # external configuration — permissive)
                return self._self_attr_bounded(node.attr, depth)
            # module.CONST / deeper object attrs: fixed per process
            return True
        if isinstance(node, ast.Subscript):
            base = node.value
            if isinstance(base, ast.Attribute) and base.attr == "shape":
                return True
            return self.bounded(base, depth + 1)
        if isinstance(node, ast.Call):
            full = call_name(node) or ""
            cn = full.rsplit(".", 1)[-1]
            if cn in QUANTIZERS:
                return True
            # process-constant configuration reads: one value per run
            if full.endswith(("environ.get", "os.getenv")) or full == "getenv":
                return True
            # len() is shape-like: one compile per (bucketed) extent —
            # the recompile bug class is VALUE-derived statics
            # (`col.max()`), which stay unresolved here
            if isinstance(node.func, ast.Name) and node.func.id == "len":
                return True
            if (
                isinstance(node.func, ast.Name)
                and node.func.id in _BOUNDED_WRAPPERS
            ):
                return all(self.bounded(a, depth + 1) for a in node.args)
            return False
        if isinstance(node, ast.BinOp):
            return self.bounded(node.left, depth + 1) and self.bounded(
                node.right, depth + 1
            )
        if isinstance(node, ast.UnaryOp):
            return self.bounded(node.operand, depth + 1)
        if isinstance(node, (ast.Tuple, ast.List)):
            return all(self.bounded(e, depth + 1) for e in node.elts)
        if isinstance(node, ast.IfExp):
            return self.bounded(node.body, depth + 1) and self.bounded(
                node.orelse, depth + 1
            )
        if isinstance(node, ast.Compare):
            return True  # booleans are a 2-point lattice
        if isinstance(node, ast.Starred):
            return self.bounded(node.value, depth + 1)
        return False

    def _self_attr_bounded(self, attr: str, depth: int) -> bool:
        cls = self._class
        if cls is None or attr in self._attr_visiting:
            return True
        self._attr_visiting.add(attr)
        try:
            sites: list[tuple[ast.AST, ast.AST]] = []  # (method, value)
            for m in walk_in(cls, ast.FunctionDef, ast.AsyncFunctionDef):
                for a in walk_in(m, ast.Assign):
                    for t in a.targets:
                        if _is_self_attr(t, attr):
                            sites.append((m, a.value))
                for a in walk_in(m, ast.AnnAssign, ast.AugAssign):
                    if a.value is not None and _is_self_attr(a.target, attr):
                        sites.append((m, a.value))
            if not sites:
                return True
            for m, value in sites:
                sub = _Provenance(self.mod, [m])
                sub._attr_visiting = self._attr_visiting
                if not sub.bounded(value, depth + 1):
                    return False
            return True
        finally:
            self._attr_visiting.discard(attr)


def _is_self_attr(target: ast.AST, attr: str) -> bool:
    return (
        isinstance(target, ast.Attribute)
        and target.attr == attr
        and isinstance(target.value, ast.Name)
        and target.value.id in ("self", "cls")
    )


def _static_bound_args(call: ast.Call, statics: set[str], param_order: list):
    """(param name, value node) for every arg bound to a static param."""
    out: list[tuple[str, ast.AST]] = []
    for i, a in enumerate(call.args):
        if isinstance(a, ast.Starred):
            # *-unpacking: every later position binds an unknowable
            # parameter — mapping by index past this point would check
            # the wrong name (flag a bounded call, or admit the real
            # static).  Keyword-bound statics below are still checked.
            break
        if i < len(param_order) and param_order[i] in statics:
            out.append((param_order[i], a))
    for kw in call.keywords:
        if kw.arg in statics:
            out.append((kw.arg, kw.value))
    return out


@rule("JIT002", SEV_ERROR)
def jit_static_args_bounded(project: Project):
    """Static args at jitted-call sites must be provably bounded
    (literal, constant, shape, or allowlisted quantizer).

    Parameter pass-through is resolved by a forwarding fixpoint: when a
    non-jitted wrapper's param flows into a static arg, the wrapper
    becomes a checked target itself, so ``helper(int(col.max()))`` is
    flagged at the OUTER call site instead of escaping through one
    level of indirection."""
    jitted = _collect_jitted_callees(project)
    # name -> {owner node id -> (forwarded params, positional order)};
    # keyed per OWNER so same-named wrappers in different modules keep
    # their own param orders, and kept separate from ``jitted`` so a
    # name collision with a real jitted function can't widen that
    # function's static set
    forward: dict[str, dict[int, tuple[set[str], list[str]]]] = {}
    top_level: dict[int, dict[str, ast.AST]] = {}

    def local_def(mod, full: str, cn: str):
        """The module's own top-level function a bare call resolves to."""
        if "." in full:
            return None  # qualified: K.fold / module.fold
        defs = top_level.get(id(mod))
        if defs is None:
            defs = top_level[id(mod)] = {
                n.name: n
                for n in mod.tree.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
        return defs.get(cn)
    # the provenance index depends only on (module, innermost function),
    # not on the fixpoint state — build each once, not per (call, arg)
    chains: dict[int, list] = {}
    provs: dict[tuple[int, int | None], _Provenance] = {}

    def provenance(mod, fn_chain) -> _Provenance:
        key = (id(mod), id(fn_chain[0]) if fn_chain else None)
        prov = provs.get(key)
        if prov is None:
            prov = provs[key] = _Provenance(mod, fn_chain)
        prov.passthrough = set()  # per-evaluation output channel
        return prov

    def resolve(mod, full: str, cn: str):
        local = local_def(mod, full, cn)
        if local is not None:
            if _jit_decorator_info(local)[0]:
                # the module's own jitted def: check against ITS
                # signature only (None when it declares no statics)
                return jitted.get(cn, {}).get(id(local))
            # the call resolves to this module's own plain function:
            # only check it against THAT function's forwarding entry
            return forward.get(cn, {}).get(id(local))
        jentries = jitted.get(cn, {})
        if len(jentries) == 1:
            return next(iter(jentries.values()))
        if jentries:
            # 2+ same-named jitted defs and no local one to pick by:
            # guessing a signature would mis-map args — skip
            return None
        entries = forward.get(cn, {})
        if len(entries) == 1:
            return next(iter(entries.values()))
        return None

    def call_sites():
        for mod in project.modules:
            for call in mod.walk(ast.Call):
                full = call_name(call) or ""
                cn = full.rsplit(".", 1)[-1]
                info = resolve(mod, full, cn)
                if info is None:
                    continue
                fn_chain = chains.get(id(call))
                if fn_chain is None:
                    fn_chain = []
                    cur = call
                    while True:
                        fn_node = enclosing(
                            mod, cur, ast.FunctionDef, ast.AsyncFunctionDef
                        )
                        if fn_node is None:
                            break
                        fn_chain.append(fn_node)
                        cur = fn_node
                    chains[id(call)] = fn_chain
                if any(_jit_decorator_info(fn)[0] for fn in fn_chain):
                    # calls INSIDE another jit body are all traced-time
                    continue
                yield mod, call, cn, info, fn_chain

    changed = True
    while changed:
        changed = False
        for mod, call, cn, info, fn_chain in call_sites():
            for pname, value in _static_bound_args(call, *info):
                prov = provenance(mod, fn_chain)
                if not prov.bounded(value):
                    continue  # reported in the final pass
                for used in prov.passthrough:
                    owner = next(
                        (f for f in fn_chain if used in func_params(f)), None
                    )
                    if owner is None:
                        continue
                    statics, order = forward.setdefault(
                        owner.name, {}
                    ).setdefault(id(owner), (set(), func_params(owner)))
                    if used not in statics:
                        statics.add(used)
                        changed = True

    for mod, call, cn, info, fn_chain in call_sites():
        for pname, value in _static_bound_args(call, *info):
            prov = provenance(mod, fn_chain)
            if prov.bounded(value):
                continue
            role = (
                f"static arg `{pname}` of jitted `{cn}`"
                if cn in jitted
                else f"arg `{pname}` of `{cn}` (flows into a jitted static)"
            )
            yield Finding(
                rule="JIT002", severity=SEV_ERROR, path=mod.rel,
                line=value.lineno, context=mod.context_of(call),
                message=(
                    f"{role} is not provably bounded — every distinct "
                    "value compiles a new program; quantize via "
                    "_bucket/fold_cap/lww_limbs or pass a constant"
                ),
            )

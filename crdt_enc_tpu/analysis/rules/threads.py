"""THR001 — no bare ``threading.Thread`` outside the ingest pipeline.

The AST re-implementation of ``tools/check_thread_discipline.py`` (the
old script is now a shim over this rule).  Ad-hoc threads bypass
everything ``ops/stream.py run_ingest_pipeline`` guarantees:
backpressure (the BoundedSemaphore memory bound), ordered sequencing,
fault propagation (first failure cancels peers, threads are joined) and
per-lane observability.  The sanctioned exceptions — the pipeline's own
producer pool, the gpg stderr drain, bench.py's watchdog — live in
``tools/analysis_baseline.toml`` with ``max = 1`` pins, preserving the
old allowlist's per-file site counts: a NEW bare thread in an
allowlisted file exceeds the pin and still fails.
"""

from __future__ import annotations

import ast

from ..engine import SEV_ERROR, Finding, Project, rule


def _thread_aliases(mod) -> tuple[set[str], set[str]]:
    """(direct Thread names, threading-module names): covers
    ``from threading import Thread [as T]`` and
    ``import threading [as thr]``."""
    direct: set[str] = set()
    modules = {"threading"}
    for node in mod.walk(ast.ImportFrom):
        if node.module == "threading":
            for alias in node.names:
                if alias.name == "Thread":
                    direct.add(alias.asname or alias.name)
    for node in mod.walk(ast.Import):
        for alias in node.names:
            if alias.name == "threading":
                modules.add(alias.asname or alias.name)
    return direct, modules


@rule("THR001", SEV_ERROR)
def thread_discipline(project: Project):
    """Bare Thread construction outside run_ingest_pipeline."""
    for mod in project.modules:
        direct, modules = _thread_aliases(mod)
        for call in mod.walk(ast.Call):
            func = call.func
            bare = (
                isinstance(func, ast.Attribute)
                and func.attr == "Thread"
                and isinstance(func.value, ast.Name)
                and func.value.id in modules
            ) or (isinstance(func, ast.Name) and func.id in direct)
            if not bare:
                continue
            yield Finding(
                rule="THR001", severity=SEV_ERROR, path=mod.rel,
                line=call.lineno, context=mod.context_of(call),
                message=(
                    "bare threading.Thread outside run_ingest_pipeline — "
                    "route parallel ingest through ops/stream.py (or add "
                    "a baseline entry with a reason)"
                ),
            )

"""Rule plugins.  Importing this package registers every rule with the
engine registry (``crdt_enc_tpu.analysis.engine.rule``); adding a rule
is: write a module here, decorate the entry point, import it below, and
document it in docs/static_analysis.md."""

from . import exc, ffi, jit, obs, sec, spans, threads  # noqa: F401

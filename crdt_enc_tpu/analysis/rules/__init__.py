"""Rule plugins.  Importing this package registers every rule with the
engine registry (``crdt_enc_tpu.analysis.engine.rule``); adding a rule
is: write a module here, decorate the entry point, import it below, and
document it in docs/static_analysis.md."""

from . import (  # noqa: F401
    async_,
    determinism,
    exc,
    ffi,
    jit,
    locks,
    mutation,
    obs,
    sec,
    spans,
    threads,
)

"""SPN001 — every span/metric name registered; proof spans must emit.

The AST re-implementation of ``tools/check_span_names.py`` (now a shim
over this rule).  The observability registry is the two tables in
``docs/observability.md``; library code may only emit literal names
that appear there (aggregation keys must stay low-cardinality), and a
REGISTERED ``stream.*`` name with no call site is an error — those
spans back the machine-checked overlap/backpressure proofs
(``chunk_overlaps``, ``obs_report --check-overlap``), which would
silently read an empty timeline.

Severities: unregistered literal name → error; f-string / identifier
name → warning (identifiers are fine when the VALUES are registered
literals defined nearby); stale non-stream registry row → warning.
"""

from __future__ import annotations

import ast
import re

from ..astutil import const_str
from ..engine import SEV_ERROR, SEV_WARNING, Finding, Project, rule

_RECEIVERS = {"trace", "record", "_record"}
_KINDS = {"span", "add", "gauge", "observe"}
_TABLE_ROW_RE = re.compile(r"^\|\s*`([^`]+)`\s*\|")
_REGISTRY_SECTIONS = ("## Span registry", "## Counter & gauge registry")
#: names maintained inside obs.record itself (no trace.* call site)
_INTERNAL = {"events_dropped"}
_PROOF_PREFIXES = ("stream.",)

DOC_REL = "docs/observability.md"


def _is_obs_call(func: ast.AST) -> bool:
    """``trace.add(...)`` — and the qualified spelling
    ``obs.record.add(...)``, where the receiver is the final attribute
    before the kind (the old regex lint matched both)."""
    if not (isinstance(func, ast.Attribute) and func.attr in _KINDS):
        return False
    base = func.value
    if isinstance(base, ast.Name):
        return base.id in _RECEIVERS
    if isinstance(base, ast.Attribute):
        return base.attr in _RECEIVERS
    return False


def registry_names(project: Project) -> dict[str, int] | None:
    """name -> doc line for the registry tables; None if the doc is
    missing/empty."""
    doc = project.root / DOC_REL
    if not doc.exists():
        return None
    names: dict[str, int] = {}
    in_registry = False
    for lineno, line in enumerate(doc.read_text().splitlines(), start=1):
        if line.startswith("## "):
            in_registry = line.strip() in _REGISTRY_SECTIONS
            continue
        if not in_registry:
            continue
        m = _TABLE_ROW_RE.match(line)
        if m:
            names.setdefault(m.group(1), lineno)
    return names or None


@rule("SPN001", SEV_ERROR)
def span_names_registered(project: Project):
    """trace/record span+metric names vs the observability registry."""
    registered = registry_names(project)
    if registered is None:
        yield Finding(
            rule="SPN001", severity=SEV_ERROR, path=DOC_REL, line=1,
            message="observability registry doc missing or has no "
            "registry tables",
        )
        return
    used: set[str] = set()
    for mod in project.modules:
        for call in mod.walk(ast.Call):
            func = call.func
            if not _is_obs_call(func):
                continue
            if not call.args:
                continue
            arg = call.args[0]
            kind = func.attr
            name = const_str(arg)
            if name is not None:
                used.add(name)
                if name not in registered:
                    yield Finding(
                        rule="SPN001", severity=SEV_ERROR, path=mod.rel,
                        line=call.lineno, context=mod.context_of(call),
                        message=(
                            f'{kind}("{name}") is not in the '
                            f"{DOC_REL} registry"
                        ),
                    )
            elif isinstance(arg, ast.JoinedStr):
                yield Finding(
                    rule="SPN001", severity=SEV_WARNING, path=mod.rel,
                    line=call.lineno, context=mod.context_of(call),
                    message=(
                        f"f-string {kind} name — dynamic cardinality "
                        "breaks the aggregate table"
                    ),
                )
            else:
                yield Finding(
                    rule="SPN001", severity=SEV_WARNING, path=mod.rel,
                    line=call.lineno, context=mod.context_of(call),
                    message=f"non-literal {kind} name",
                )
    if project.partial:
        # a path-subset run can't prove a registered name is unemitted
        return
    for stale in sorted(set(registered) - used - _INTERNAL):
        if stale.startswith(_PROOF_PREFIXES):
            yield Finding(
                rule="SPN001", severity=SEV_ERROR, path=DOC_REL,
                line=registered[stale],
                message=(
                    f"registry entry `{stale}` (stream.* proof family) has "
                    "no literal call site — the overlap proofs would read "
                    "an empty timeline"
                ),
            )
        else:
            yield Finding(
                rule="SPN001", severity=SEV_WARNING, path=DOC_REL,
                line=registered[stale],
                message=f"registry entry `{stale}` has no literal call site",
            )

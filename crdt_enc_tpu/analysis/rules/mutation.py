"""MUT001 — every tracked-state write bumps the ``_mut`` epoch first.

The plane cache (``parallel/accel.py``), the warm tier
(``serve/warm.py``), and the continuation stamps all validate cached
planes with ``entry.token != state._mut`` — a state mutation that does
not bump the epoch silently revalidates stale planes.  This rule makes
the invalidation law static:

* a class is *tracked* when it declares ``_mut`` (class body or
  ``__init__``); its tracked attrs are its other declared fields;
* any method writing a tracked attr — directly (``self.entries[...] =``,
  ``self.clock = ...``), through a mutator call (``self.entries.pop()``,
  ``self.deferred.setdefault(...)``), or through a one-level local alias
  (``e = self.entries; e.add(...)``, incl. aliases obtained via
  ``self.A[...]``/``.get()``/``.setdefault()``) — must be *dominated* by
  an unconditional ``self._mut`` bump: a top-level bump statement before
  the first write on every path.  A bump that only happens on one
  branch is flagged as such;
* private helpers may rely on their callers: a writing helper is clean
  when every intra-class call site is itself bump-dominated (fixpoint
  over the intra-class call graph); public mutators must self-protect;
* ``__init__``/``__post_init__``/``__setstate__`` construct, they don't
  mutate published state — exempt.  Fresh locals built from the class
  constructor (``s = ORSet()``, ``cls()``) are exempt receivers:
  nothing can hold a stale plane for an object that didn't exist;
* module-level functions (the columnar fold/writeback paths) that write
  tracked attrs on a parameter must bump ``<recv>._mut`` in the same
  function.
"""

from __future__ import annotations

import ast

from ..astutil import dotted, functions
from ..engine import SEV_ERROR, Finding, Project, rule

#: method tails that mutate their receiver in place
_MUTATOR_TAILS = {
    "add", "append", "extend", "insert", "remove", "discard", "pop",
    "popitem", "clear", "update", "setdefault", "apply", "merge",
    "reset_remove",
}
#: calls whose result aliases INTO the receiver's contents
_ALIAS_TAILS = {"get", "setdefault"}
_EXEMPT_METHODS = {"__init__", "__post_init__", "__new__", "__setstate__"}


def _class_methods(mod, cls: ast.ClassDef):
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _declares_mut(mod, cls: ast.ClassDef) -> bool:
    for node in cls.body:
        if isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            if node.target.id == "_mut":
                return True
        elif isinstance(node, ast.Assign):
            if any(isinstance(t, ast.Name) and t.id == "_mut" for t in node.targets):
                return True
    for m in _class_methods(mod, cls):
        if m.name in ("__init__", "__post_init__"):
            for n in ast.walk(m):
                if (
                    isinstance(n, ast.Attribute)
                    and isinstance(n.ctx, (ast.Store,))
                    and n.attr == "_mut"
                    and isinstance(n.value, ast.Name)
                    and n.value.id == "self"
                ):
                    return True
    return False


def _tracked_attrs(mod, cls: ast.ClassDef) -> set[str]:
    attrs: set[str] = set()
    for node in cls.body:
        if isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            attrs.add(node.target.id)
    for m in _class_methods(mod, cls):
        if m.name in ("__init__", "__post_init__"):
            for n in ast.walk(m):
                if (
                    isinstance(n, ast.Attribute)
                    and isinstance(n.ctx, ast.Store)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == "self"
                ):
                    attrs.add(n.attr)
    attrs.discard("_mut")
    return {a for a in attrs if not a.startswith("__")}


class _Event:
    """One bump / write / helper-call inside a method, positioned by its
    top-level statement index and whether any enclosing statement can
    branch (If/For/While/Try) — With doesn't branch and doesn't count."""

    __slots__ = ("kind", "line", "index", "conditional", "detail")

    def __init__(self, kind, line, index, conditional, detail=""):
        self.kind = kind
        self.line = line
        self.index = index
        self.conditional = conditional
        self.detail = detail


def _attr_write_name(target: ast.AST, recv: str) -> str | None:
    """The attr of ``<recv>.A`` / ``<recv>.A[...]`` stores, else None."""
    node = target
    while isinstance(node, (ast.Subscript, ast.Slice)):
        node = node.value if isinstance(node, ast.Subscript) else node
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == recv
    ):
        return node.attr
    return None


def _method_events(method, tracked: set[str], method_names: set[str]):
    """Scan one method body for bump/write/helper-call events."""
    events: list[_Event] = []
    aliases: dict[str, str] = {}  # local name -> tracked attr it aliases

    def scan(stmts, index_base, conditional):
        for i, stmt in enumerate(stmts):
            idx = index_base if index_base is not None else i
            scan_stmt(stmt, idx, conditional)

    def scan_stmt(stmt, idx, conditional):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign, ast.Delete)):
            targets = (
                stmt.targets
                if isinstance(stmt, (ast.Assign, ast.Delete))
                else [stmt.target]
            )
            for t in targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                    and t.attr == "_mut"
                ):
                    events.append(_Event("bump", stmt.lineno, idx, conditional))
                    continue
                a = _attr_write_name(t, "self")
                if a in tracked:
                    events.append(
                        _Event("write", stmt.lineno, idx, conditional, f"self.{a}")
                    )
                    continue
                if isinstance(t, ast.Name) and t.id in aliases:
                    # plain rebinding of the alias name isn't a write,
                    # but subscript stores through it are
                    pass
                sub = _subscript_base_name(t)
                if sub in aliases:
                    events.append(
                        _Event(
                            "write", stmt.lineno, idx, conditional,
                            f"self.{aliases[sub]} (via alias {sub})",
                        )
                    )
            # alias creation: x = self.A / self.A[...] / self.A.get(...)
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                t0 = stmt.targets[0]
                if isinstance(t0, ast.Name):
                    a = _alias_source(stmt.value, tracked, aliases)
                    if a is not None:
                        aliases[t0.id] = a
                    elif t0.id in aliases:
                        del aliases[t0.id]
        for call in _own_calls(stmt):
            name = dotted(call.func)
            if name is None:
                continue
            parts = name.split(".")
            if parts[0] == "self" and len(parts) == 2 and parts[1] in method_names:
                events.append(
                    _Event("helper", call.lineno, idx, conditional, parts[1])
                )
            elif parts[-1] in _MUTATOR_TAILS:
                base = parts[:-1]
                if len(base) >= 2 and base[0] == "self" and base[1] in tracked:
                    events.append(
                        _Event(
                            "write", call.lineno, idx, conditional,
                            f"self.{base[1]}.{parts[-1]}()",
                        )
                    )
                elif len(base) == 1 and base[0] in aliases:
                    events.append(
                        _Event(
                            "write", call.lineno, idx, conditional,
                            f"self.{aliases[base[0]]}.{parts[-1]}() "
                            f"(via alias {base[0]})",
                        )
                    )
        for child, cond in _sub_blocks(stmt):
            scan(child, idx, conditional or cond)

    scan(method.body, None, False)
    return events


def _subscript_base_name(target: ast.AST) -> str | None:
    if isinstance(target, ast.Subscript) and isinstance(target.value, ast.Name):
        return target.value.id
    return None


def _alias_source(value: ast.AST, tracked: set[str], aliases: dict) -> str | None:
    node = value
    if isinstance(node, ast.Call):
        name = dotted(node.func)
        if name:
            parts = name.split(".")
            if (
                len(parts) == 3
                and parts[0] == "self"
                and parts[1] in tracked
                and parts[2] in _ALIAS_TAILS
            ):
                return parts[1]
        return None
    if isinstance(node, ast.Subscript):
        node = node.value
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
        and node.attr in tracked
    ):
        return node.attr
    return None


def _own_calls(stmt):
    """Calls in the statement's OWN expressions — nested block bodies
    are scanned separately (with their branch flag) via _sub_blocks."""
    if isinstance(stmt, (ast.If, ast.While)):
        exprs = [stmt.test]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        exprs = [stmt.iter]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        exprs = [i.context_expr for i in stmt.items]
    elif isinstance(stmt, ast.Try):
        exprs = []
    elif isinstance(stmt, ast.Match):
        exprs = [stmt.subject]
    else:
        exprs = [stmt]
    for e in exprs:
        for n in ast.walk(e):
            if isinstance(n, ast.Call):
                yield n


def _sub_blocks(stmt):
    """(child statement list, introduces_branch) pairs for compound
    statements."""
    if isinstance(stmt, ast.If):
        yield stmt.body, True
        yield stmt.orelse, True
    elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
        yield stmt.body, True
        yield stmt.orelse, True
    elif isinstance(stmt, ast.Try):
        yield stmt.body, True
        for h in stmt.handlers:
            yield h.body, True
        yield stmt.orelse, True
        yield stmt.finalbody, False
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        yield stmt.body, False
    elif isinstance(stmt, ast.Match):
        for case in stmt.cases:
            yield case.body, True


def _first_unconditional_bump(events) -> int | None:
    """Line of the earliest bump on the unconditional path.  Both a
    qualifying bump and any write are on straight-line segments, so
    textual order IS execution order between them."""
    lines = [e.line for e in events if e.kind == "bump" and not e.conditional]
    return min(lines) if lines else None


def _has_any_bump(events) -> bool:
    return any(e.kind == "bump" for e in events)


@rule("MUT001", SEV_ERROR)
def mut_epoch_bumped(project: Project):
    """Methods writing tracked CRDT state attrs must bump the `_mut`
    epoch unconditionally before the first write; columnar writeback
    functions must bump `<recv>._mut` for non-fresh receivers."""
    all_tracked_attrs: set[str] = set()
    tracked_class_names: set[str] = set()
    per_class: list[tuple] = []
    for mod in project.modules:
        for cls in mod.walk(ast.ClassDef):
            if not _declares_mut(mod, cls):
                continue
            tracked = _tracked_attrs(mod, cls)
            if not tracked:
                continue
            tracked_class_names.add(cls.name)
            all_tracked_attrs |= tracked
            per_class.append((mod, cls, tracked))

    for mod, cls, tracked in per_class:
        methods = list(_class_methods(mod, cls))
        method_names = {m.name for m in methods}
        events_by_method = {
            m.name: _method_events(m, tracked, method_names)
            for m in methods
            if m.name not in _EXEMPT_METHODS
        }
        # fixpoint: a method "writes" when it has a direct write or an
        # un-dominated call to a writing method
        writing: set[str] = set()
        changed = True
        while changed:
            changed = False
            for name, events in events_by_method.items():
                if name in writing:
                    continue
                bump_line = _first_unconditional_bump(events)
                for e in events:
                    is_write = e.kind == "write" or (
                        e.kind == "helper" and e.detail in writing
                    )
                    if not is_write:
                        continue
                    if bump_line is None or bump_line >= e.line:
                        writing.add(name)
                        changed = True
                        break
        for m in methods:
            if m.name in _EXEMPT_METHODS or m.name not in writing:
                continue
            if m.name.startswith("_"):
                # a private writing helper is its callers' obligation;
                # each un-dominated intra-class call site is already
                # flagged at the caller (which joined `writing`)
                callers = [
                    n
                    for n, evs in events_by_method.items()
                    if any(e.kind == "helper" and e.detail == m.name for e in evs)
                ]
                if callers:
                    continue
            events = events_by_method[m.name]
            first = next(
                (
                    e
                    for e in events
                    if e.kind == "write"
                    or (e.kind == "helper" and e.detail in writing)
                ),
                None,
            )
            if first is None:
                continue
            if _has_any_bump(events):
                how = (
                    "bumps `_mut` on one branch only / after the write — "
                    "the bump must dominate every write"
                )
            else:
                how = "never bumps `_mut`"
            yield Finding(
                rule="MUT001",
                severity=SEV_ERROR,
                path=mod.rel,
                line=first.line,
                context=f"{cls.name}.{m.name}",
                message=(
                    f"writes tracked state ({first.detail or 'tracked attr'}) "
                    f"but {how}; stale planes in the warm tier / plane "
                    "cache would revalidate"
                ),
            )

    if not all_tracked_attrs:
        return
    # module-level writeback paths: <recv>.<tracked attr> stores need a
    # <recv>._mut bump in the same function unless <recv> is fresh
    for mod in project.modules:
        for fn in functions(mod):
            cls_parent = mod.parents.get(fn)
            if isinstance(cls_parent, ast.ClassDef):
                continue  # methods handled (or untracked classes exempt)
            fresh: set[str] = set()
            bumped: set[str] = set()
            writes: list[tuple[str, str, int]] = []
            for n in ast.walk(fn):
                if isinstance(n, ast.Assign):
                    if (
                        len(n.targets) == 1
                        and isinstance(n.targets[0], ast.Name)
                        and isinstance(n.value, ast.Call)
                    ):
                        cname = dotted(n.value.func) or ""
                        tail = cname.rsplit(".", 1)[-1]
                        if tail in tracked_class_names or cname == "cls":
                            fresh.add(n.targets[0].id)
                if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                    targets = n.targets if isinstance(n, ast.Assign) else [n.target]
                    for t in targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id not in ("self", "cls")
                        ):
                            if t.attr == "_mut":
                                bumped.add(t.value.id)
                            elif t.attr in all_tracked_attrs:
                                writes.append((t.value.id, t.attr, t.lineno))
            for recv, attr, line in writes:
                if recv in fresh or recv in bumped:
                    continue
                yield Finding(
                    rule="MUT001",
                    severity=SEV_ERROR,
                    path=mod.rel,
                    line=line,
                    context=mod.context_of(fn),
                    message=(
                        f"writeback to `{recv}.{attr}` without bumping "
                        f"`{recv}._mut` — the warm tier / plane cache "
                        "key on the epoch and would serve stale planes"
                    ),
                )

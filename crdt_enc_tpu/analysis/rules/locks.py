"""LCK001 — lock-consistency for thread-shared class state.

Two checks, both on classes/functions that hold real ``threading``
locks (asyncio locks are cooperative and excluded — awaiting under
``async with`` is normal):

* **guarded-field consistency**: an attribute *written* under
  ``with self._lock:`` somewhere in a class is part of that lock's
  protected invariant — every other access (read or write) of it in
  any method must also hold the lock.  ``__init__``/``__post_init__``
  are exempt (construction happens-before publication), as are
  accesses inside the lock's own ``with`` regions;
* **await-under-lock**: an ``await`` anywhere inside a ``with`` on a
  known threading lock parks the event loop while holding a lock
  worker threads contend on — a deadlock-by-design.  Known locks are
  class attrs (``self._lock = threading.Lock()``), module globals, and
  function locals, classified by constructor spelling.
"""

from __future__ import annotations

import ast

from ..astutil import dotted, functions, walk_in
from ..effects import effect_index, lock_ctor_kind
from ..engine import SEV_ERROR, Finding, Project, rule
from .mutation import _MUTATOR_TAILS

_EXEMPT = {"__init__", "__post_init__", "__new__", "__setstate__"}


def _with_lock_regions(fn, lock_names: set[str]):
    """With/AsyncWith nodes whose context expr is one of lock_names
    (dotted spellings, e.g. ``self._lock`` or ``_PATCH_LOCK``)."""
    for w in walk_in(fn, ast.With, ast.AsyncWith):
        for item in w.items:
            expr = item.context_expr
            # `with lock:` or `with lock.acquire_timeout(..)` styles —
            # only the bare-name/attr form is a lock region
            name = dotted(expr)
            if name in lock_names:
                yield w, name


def _under(mod, node, region) -> bool:
    cur = mod.parents.get(node)
    while cur is not None:
        if cur is region:
            return True
        cur = mod.parents.get(cur)
    return False


@rule("LCK001", SEV_ERROR)
def lock_consistency(project: Project):
    """Fields written under a class's threading lock must be accessed
    under it everywhere; never await while holding a threading lock."""
    idx = effect_index(project)
    for mod in project.modules:
        mi = idx.mods.get(mod.rel)
        if mi is None:
            continue
        module_locks = {n for n, k in mi.mod_locks.items() if k == "threading"}
        for cls in mod.walk(ast.ClassDef):
            lock_attrs = {
                a
                for a, k in mi.class_locks.get(cls.name, {}).items()
                if k == "threading"
            }
            if not lock_attrs:
                continue
            methods = [
                n
                for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            ]
            for lock in sorted(lock_attrs):
                lname = f"self.{lock}"
                # pass 1: the lock's protected field set = attrs written
                # under any `with self.<lock>:` region
                guarded: set[str] = set()
                regions_by_method: dict[str, list] = {}
                for m in methods:
                    regions = [w for w, _ in _with_lock_regions(m, {lname})]
                    regions_by_method[m.name] = regions
                    for region in regions:
                        for a in walk_in(region, ast.Attribute):
                            if (
                                isinstance(a.ctx, (ast.Store, ast.Del))
                                and isinstance(a.value, ast.Name)
                                and a.value.id == "self"
                                and a.attr != lock
                            ):
                                guarded.add(a.attr)
                        # in-place mutation counts as a write too:
                        # `self.items.append(x)` under the lock makes
                        # `items` part of the protected invariant
                        for c in walk_in(region, ast.Call):
                            name = dotted(c.func) or ""
                            parts = name.split(".")
                            if (
                                len(parts) == 3
                                and parts[0] == "self"
                                and parts[1] != lock
                                and parts[2] in _MUTATOR_TAILS
                            ):
                                guarded.add(parts[1])
                if not guarded:
                    continue
                # pass 2: every other access of a guarded field must
                # hold the lock
                for m in methods:
                    if m.name in _EXEMPT:
                        continue
                    regions = regions_by_method.get(m.name, [])
                    reported: set[str] = set()
                    for a in walk_in(m, ast.Attribute):
                        if (
                            not isinstance(a.value, ast.Name)
                            or a.value.id != "self"
                            or a.attr not in guarded
                            or a.attr in reported
                        ):
                            continue
                        if any(_under(mod, a, r) for r in regions):
                            continue
                        reported.add(a.attr)
                        yield Finding(
                            rule="LCK001",
                            severity=SEV_ERROR,
                            path=mod.rel,
                            line=a.lineno,
                            context=f"{cls.name}.{m.name}",
                            message=(
                                f"`self.{a.attr}` is written under "
                                f"`{lname}` elsewhere but accessed here "
                                "without it — a thread-reachable path "
                                "sees torn state"
                            ),
                        )
        # await-under-lock: any function, any known threading lock
        for fn in functions(mod):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            cls_parent = mod.parents.get(fn)
            cls_lock_names = set()
            if isinstance(cls_parent, ast.ClassDef):
                cls_lock_names = {
                    f"self.{a}"
                    for a, k in mi.class_locks.get(cls_parent.name, {}).items()
                    if k == "threading"
                }
            local_locks = set()
            for n in walk_in(fn, ast.Assign):
                if isinstance(n.value, ast.Call) and lock_ctor_kind(n.value) == "threading":
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            local_locks.add(t.id)
            known = module_locks | cls_lock_names | local_locks
            if not known:
                continue
            for region, name in _with_lock_regions(fn, known):
                if isinstance(region, ast.AsyncWith):
                    continue  # async with => asyncio lock, not these
                for aw in walk_in(region, ast.Await):
                    yield Finding(
                        rule="LCK001",
                        severity=SEV_ERROR,
                        path=mod.rel,
                        line=aw.lineno,
                        context=mod.context_of(aw),
                        message=(
                            f"await while holding threading lock "
                            f"`{name}` — parks the event loop with the "
                            "lock held; worker threads deadlock on it"
                        ),
                    )
                    break

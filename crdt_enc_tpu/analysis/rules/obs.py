"""OBS001 — every explicit H2D transfer flows through h2d_bytes accounting.

The observability subsystem's transfer story (docs/observability.md,
``h2d_bytes``) is only as trustworthy as its coverage: one
``jax.device_put`` that bypasses the counter and the warm-open /
plane-reuse proofs (PR 4) under-report transfers.  This rule pins the
invariant: every explicit placement call in library code —
``jax.device_put(...)``,
``jax.make_array_from_process_local_data(...)`` (the multi-host
spelling of the same transfer), or ``jnp.asarray(...)`` outside a jit
body (on host data it IS an upload; inside jit it is a traced no-op)
— must sit in a function that also issues
``trace.add("h2d_bytes", ...)`` (or ``record.add``) — accounting at
issue, the convention the streaming pipeline established.  Sites
whose bytes are counted by a downstream aggregator (e.g. a ``put=``
closure handed to ``fold_chunks_overlapped``, which accounts every
chunk it issues) are point exceptions: pragma them with the
accounting site named in the comment.

Scope: ``crdt_enc_tpu/`` only — benchmarks measure, they don't serve.
"""

from __future__ import annotations

import ast

from ..astutil import call_name, const_str, enclosing, walk_in
from ..engine import SEV_ERROR, Finding, Project, rule
from .jit import _jit_decorator_info

#: full dotted spellings of the host→device array coercion; bare-name
#: matching would also catch np.asarray, which never leaves the host
_ASARRAY = {"jnp.asarray", "jax.numpy.asarray"}


def _accounts_h2d(scope: ast.AST, *, module_level: bool = False) -> bool:
    """Does ``scope`` issue ``*.add("h2d_bytes", ...)``?  For a module
    scope only module-level statements count — accounting inside some
    unrelated function must not excuse a module-level transfer."""
    if module_level:
        stack = list(getattr(scope, "body", []))
        calls = []
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(node, ast.Call):
                calls.append(node)
            stack.extend(ast.iter_child_nodes(node))
    else:
        calls = walk_in(scope, ast.Call)
    for call in calls:
        cn = call_name(call) or ""
        if cn.rsplit(".", 1)[-1] == "add" and call.args:
            if const_str(call.args[0]) == "h2d_bytes":
                return True
    return False


@rule("OBS001", SEV_ERROR)
def device_put_accounted(project: Project):
    """jax.device_put in library code must be h2d_bytes-accounted in the
    same function."""
    for mod in project.modules:
        if not mod.rel.startswith("crdt_enc_tpu/"):
            continue
        checked: dict[ast.AST, bool] = {}
        for call in mod.walk(ast.Call):
            full = call_name(call) or ""
            cn = full.rsplit(".", 1)[-1]
            is_asarray = full in _ASARRAY
            if not is_asarray and cn not in (
                "device_put", "make_array_from_process_local_data"
            ):
                continue
            scope = enclosing(mod, call, ast.FunctionDef, ast.AsyncFunctionDef)
            if is_asarray and scope is not None:
                # traced: no runtime transfer at this site.  The jit
                # decorator may sit on an OUTER def (a scan/cond body
                # closure is traced too), so walk the whole chain.
                fn, traced = scope, False
                while fn is not None and not traced:
                    traced = _jit_decorator_info(fn)[0]
                    fn = enclosing(
                        mod, fn, ast.FunctionDef, ast.AsyncFunctionDef
                    )
                if traced:
                    continue
            key = scope if scope is not None else mod.tree
            if key not in checked:
                checked[key] = _accounts_h2d(
                    key, module_level=scope is None
                )
            if checked[key]:
                continue
            yield Finding(
                rule="OBS001", severity=SEV_ERROR, path=mod.rel,
                line=call.lineno, context=mod.context_of(call),
                message=(
                    f"{full or cn} without h2d_bytes accounting in the "
                    "same function — the transfer is invisible to the "
                    "observability counters (docs/observability.md); "
                    'trace.add("h2d_bytes", x.nbytes) at issue, or pragma '
                    "with the downstream accounting site named"
                ),
            )

"""crdt_enc_tpu.analysis — the project-invariant static-analysis engine.

One AST parse pass over the package, a plugin rule registry encoding the
invariants this codebase has been burned by (FFI contracts, jit
recompile bounds, silent native fallbacks, thread discipline, span
registry, H2D accounting, key-material taint), inline pragmas plus a
committed baseline for deliberate exceptions, and a CLI
(``python -m crdt_enc_tpu.tools.analyze``).  See docs/static_analysis.md.
"""

from .baseline import Baseline
from .engine import (
    SEV_ERROR,
    SEV_WARNING,
    Finding,
    ModuleInfo,
    Project,
    all_rules,
    run,
    rule,
    unsuppressed_errors,
)

__all__ = [
    "Baseline",
    "Finding",
    "ModuleInfo",
    "Project",
    "SEV_ERROR",
    "SEV_WARNING",
    "all_rules",
    "rule",
    "run",
    "unsuppressed_errors",
]

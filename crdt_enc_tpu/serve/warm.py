"""Tenant-keyed warm tier: an LRU of per-tenant fold planes.

The single-tenant accelerator keeps ONE set of device-resident result
planes (``parallel/accel._OrsetPlaneCache``) so the next fold on an
un-mutated state skips the sparse state walk and the full-plane upload.
A fold service cycling over thousands of tenants needs the same trick
*per tenant*, under an explicit memory budget: this tier holds each
tenant's last fold output — the ``(clock, add, rm)`` planes exactly as
the batched kernel produced them (device-resident arrays; on the CPU
backend that is host memory), plus the vocabularies they are indexed by
— keyed by the tenant state's identity and validated by the same
``_mut`` mutation-epoch token the accelerator cache uses, so ANY host
mutation (an apply, a snapshot merge, another path's writeback) silently
expires the entry.

Budget and visibility: ``byte_budget`` bounds the summed plane bytes;
inserting past it evicts least-recently-used entries first (the newest
entry itself is never evicted at insert — a single over-budget tenant
still gets exactly one cycle of reuse and then ages out normally).
``serve_warm_hits`` / ``serve_warm_misses`` / ``serve_warm_evictions``
counters and the ``serve_warm_bytes`` gauge (docs/observability.md) make
the tier's behavior auditable per cycle.

Entries expose the same ``members / replicas / canon / planes``
attributes as the accelerator's plane cache, so the service reuses the
accelerator's remap and pad helpers (``TpuAccelerator._remap_to_cache``,
``_cached_planes_padded``) — one implementation of the vocab-collision
guard, not two.
"""

from __future__ import annotations

import weakref
from collections import OrderedDict

from ..utils import trace

DEFAULT_BYTE_BUDGET = 256 << 20  # summed plane bytes across tenants


class WarmEntry:
    """One tenant's cached fold planes (see module docs)."""

    __slots__ = ("ref", "token", "members", "replicas", "planes", "canon",
                 "nbytes", "seal_name")

    def __init__(self, ref, token, members, replicas, planes, canon):
        self.ref = ref
        self.token = token
        self.members = members
        self.replicas = replicas
        self.planes = planes  # (clock, add, rm) arrays, padded shapes
        self.canon = canon  # member slot -> canonical packed bytes
        self.nbytes = sum(int(getattr(p, "nbytes", 0)) for p in planes)
        # content-addressed name of the sealed snapshot these planes ARE
        # (stamped after a successful seal by PlaneWarmTier.stamp_seal);
        # None until then.  When it matches the core's delta-base name,
        # the next cycle can cut the tenant's delta on device from these
        # planes and the core need not retain the host-resident base
        # bytes at all (docs/delta.md "device-cut deltas").
        self.seal_name = None


class PlaneWarmTier:
    """LRU of :class:`WarmEntry` keyed by tenant state identity.

    ``mesh_key`` pins the tier to one device-mesh identity: a tier built
    for a mesh holds device-SHARDED plane slices (the sharded mega-fold's
    outputs), which are only addressable under that same mesh — a
    service must never hand a foreign tier its entries.  The key is
    compared by identity in :meth:`compatible_with`; ``None`` = the
    single-chip tier (host/device-0 planes, the historical behavior)."""

    def __init__(self, byte_budget: int = DEFAULT_BYTE_BUDGET,
                 mesh_key=None):
        if byte_budget < 1:
            raise ValueError("byte_budget must be positive")
        self.byte_budget = int(byte_budget)
        self.mesh_key = mesh_key
        self._entries: OrderedDict[int, WarmEntry] = OrderedDict()
        self._bytes = 0

    def compatible_with(self, mesh_key) -> bool:
        """True when entries stored by this tier are addressable under
        ``mesh_key`` (identity match — mesh equality is identity in
        jax)."""
        return self.mesh_key is mesh_key

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def bytes_held(self) -> int:
        return self._bytes

    def _drop(self, key: int) -> None:
        entry = self._entries.pop(key, None)
        if entry is not None:
            self._bytes -= entry.nbytes
            trace.gauge("serve_warm_bytes", self._bytes)

    def lookup(self, state) -> WarmEntry | None:
        """The live entry for ``state``, or None (no entry, entry for a
        dead/foreign object, or the state mutated since it was stored —
        stale entries are dropped on sight, they can never be right
        again).  A hit refreshes the entry's LRU position."""
        key = id(state)
        entry = self._entries.get(key)
        if entry is None:
            trace.add("serve_warm_misses", 1)
            return None
        if entry.ref() is not state or entry.token != getattr(
            state, "_mut", None
        ):
            self._drop(key)
            trace.add("serve_warm_misses", 1)
            # refine the reason: an entry EXISTED but the state mutated
            # under it (or the id was recycled) — the mut-epoch expiry
            # the continuation fallback tests count, vs. a plain
            # never-stored / LRU-evicted miss
            trace.add("serve_warm_expired", 1)
            return None
        self._entries.move_to_end(key)
        trace.add("serve_warm_hits", 1)
        return entry

    def store(self, state, members, replicas, planes, canon=None) -> WarmEntry:
        """Record ``state``'s post-fold planes as its warm entry (token =
        the state's CURRENT ``_mut`` — call after the writeback bump),
        then evict LRU entries past the byte budget.  The weakref
        finalizer drops the entry the moment the state dies, so plane
        buffers never outlive the tenant they cache."""
        key = id(state)
        self._drop(key)

        tier_ref = weakref.ref(self)

        def _finalize(dead_ref, _key=key):
            tier = tier_ref()
            if tier is not None:
                e = tier._entries.get(_key)
                if e is not None and e.ref is dead_ref:
                    tier._drop(_key)

        entry = WarmEntry(
            weakref.ref(state, _finalize), getattr(state, "_mut", None),
            members, replicas, planes, canon if canon is not None else {},
        )
        self._entries[key] = entry
        self._bytes += entry.nbytes
        while self._bytes > self.byte_budget and len(self._entries) > 1:
            oldest = next(iter(self._entries))
            if oldest == key:
                break  # never evict the entry being inserted
            self._drop(oldest)
            trace.add("serve_warm_evictions", 1)
        trace.gauge("serve_warm_bytes", self._bytes)
        return entry

    def stamp_seal(self, state, seal_name) -> bool:
        """Mark ``state``'s live warm entry as byte-identical to the
        sealed snapshot ``seal_name`` — called by the service AFTER a
        successful seal, iff the state has not mutated since the planes
        were stored.  Deliberately not a :meth:`lookup` (no hit/miss
        accounting, no LRU refresh): this is a seal-time annotation, not
        a use.  Returns False (and stamps nothing) on any doubt."""
        entry = self._entries.get(id(state))
        if (
            entry is None
            or entry.ref() is not state
            or entry.token != getattr(state, "_mut", None)
        ):
            return False
        entry.seal_name = seal_name
        return True

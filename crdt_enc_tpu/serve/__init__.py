"""Multi-tenant serving: batch many tenants' compactions into shared
device dispatches (docs/multitenant.md).

* :mod:`.service` — :class:`FoldService`: ingest → cross-tenant decode
  fan-out → bucketed mega-folds → per-tenant sealed snapshots.
* :mod:`.bucketing` — pure ragged-shape planner (quantized size
  classes, spill rules; bounded ``jax_compiles`` across tenant mixes).
* :mod:`.warm` — tenant-keyed LRU of fold planes under a byte budget.
* :mod:`.daemon` — :class:`FleetDaemon`: the always-on control plane
  (staleness scheduling, backoff/quarantine, admission, drain) over a
  service; ``python -m crdt_enc_tpu.tools.daemon`` runs it.
"""

from .bucketing import Bucket, TenantShape, plan_buckets
from .daemon import AdmissionError, DaemonConfig, FleetDaemon, TenantEntry
from .service import FoldService, ServeConfig, TenantResult
from .warm import PlaneWarmTier, WarmEntry

__all__ = [
    "AdmissionError",
    "Bucket",
    "DaemonConfig",
    "FleetDaemon",
    "FoldService",
    "PlaneWarmTier",
    "ServeConfig",
    "TenantEntry",
    "TenantResult",
    "TenantShape",
    "WarmEntry",
    "plan_buckets",
]

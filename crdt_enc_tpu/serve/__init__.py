"""Multi-tenant serving: batch many tenants' compactions into shared
device dispatches (docs/multitenant.md).

* :mod:`.service` — :class:`FoldService`: ingest → cross-tenant decode
  fan-out → bucketed mega-folds → per-tenant sealed snapshots.
* :mod:`.bucketing` — pure ragged-shape planner (quantized size
  classes, spill rules; bounded ``jax_compiles`` across tenant mixes).
* :mod:`.warm` — tenant-keyed LRU of fold planes under a byte budget.
"""

from .bucketing import Bucket, TenantShape, plan_buckets
from .service import FoldService, ServeConfig, TenantResult
from .warm import PlaneWarmTier, WarmEntry

__all__ = [
    "Bucket",
    "FoldService",
    "PlaneWarmTier",
    "ServeConfig",
    "TenantResult",
    "TenantShape",
    "WarmEntry",
    "plan_buckets",
]

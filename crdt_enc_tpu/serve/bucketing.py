"""Ragged tenant bucketing: quantized size classes for the mega-fold.

The fold service batches many tenants' op columns into one device
dispatch (``ops.orset.orset_fold_tenants``), which needs every tenant in
a batch to share one padded shape — and the set of *compiled* shapes must
stay bounded however tenant mixes vary, or the service re-pays XLA
compilation per mix (the ADVICE-r5 unbounded-recompile bug class, here at
fleet scale).  This module owns that trade as a pure, unit-testable
planning function:

* every tenant's ragged ``(rows, members, replicas)`` quantizes to a
  power-of-two **size class** via the same ``_bucket`` quantizer the
  accelerator and the fold sessions use (floor 8 — tiny tenants share
  one class instead of compiling per size 1..8);
* tenants of one size class and CRDT kind group into **buckets**; a
  bucket's tenant count pads to a power of two too (floor 1), so the
  vmapped kernel's leading axis is also drawn from a bounded set — and
  with an active device mesh the slot classes become dp-multiples and
  ORSet member classes mp-multiples, so every dispatch divides the mesh
  axes without adding compile classes (see :func:`plan_buckets`);
* a tenant too big for batching — rows past ``rows_cap`` or dense
  planes past ``cells_cap`` — **spills to the solo path** (the existing
  single-tenant accelerator fold, which has sparse/streaming regimes for
  exactly those shapes); a size-class group larger than ``tenants_cap``
  splits into several buckets of the same class (bounded stacked-plane
  memory, zero extra compiles).

The planner never looks at tenant *contents*, only shapes — two shuffled
mixes of the same size classes produce the same compiled-shape set, which
``tests/test_serve.py`` pins by asserting ``jax_compiles`` is constant
across them.
"""

from __future__ import annotations

from dataclasses import dataclass

# A "small remote" by the survey's production-CRDT sizing; past this the
# solo accelerator's streaming/sparse regimes are the right machinery.
DEFAULT_ROWS_CAP = 1 << 15
# Dense per-tenant plane bound inside a bucket (cells = members·replicas;
# 1M cells = 4MB/plane/tenant): past it the solo fold's sparse regime
# (ops/columnar.orset_fold_sparse_host) wins anyway.
DEFAULT_CELLS_CAP = 1 << 20
# Tenants per bucket: bounds the stacked planes' host+device footprint
# without adding compile classes (split buckets share their shape).
DEFAULT_TENANTS_CAP = 1 << 10


def _bucket(n: int, floor: int = 8) -> int:
    """The repo's shape quantizer (same law as parallel/accel.py): the
    smallest power-of-two ≥ ``n``, floored."""
    b = floor
    while b < n:
        b *= 2
    return b


@dataclass(frozen=True)
class TenantShape:
    """One tenant's ragged fold shape, as measured after decode:
    ``key`` is the service's tenant handle (opaque to the planner);
    ``members`` is 0 for plane-less kinds (counters)."""

    key: object
    kind: str  # "orset" | "gcounter"
    rows: int
    members: int
    replicas: int


@dataclass
class Bucket:
    """One batched dispatch: ``tenants`` (≤ ``slots``) share the padded
    shape ``(slots, rows, members, replicas)``; slots beyond the tenant
    list are dummy all-sentinel lanes over zero planes."""

    kind: str
    rows: int
    members: int
    replicas: int
    tenants: list
    slots: int


def plan_buckets(
    shapes: list[TenantShape],
    *,
    rows_cap: int = DEFAULT_ROWS_CAP,
    cells_cap: int = DEFAULT_CELLS_CAP,
    tenants_cap: int = DEFAULT_TENANTS_CAP,
    dp: int = 1,
    mp: int = 1,
) -> tuple[list[Bucket], list]:
    """Plan one service cycle's batched dispatches.

    Returns ``(buckets, solo)``: the buckets in deterministic
    (kind, shape) order, and the keys of tenants that spill to the solo
    path.  Pure — no state, no randomness — so the same shapes always
    produce the same plan.

    ``dp``/``mp`` make the plan mesh-aware (the sharded mega-folds of
    ``parallel.mesh``): bucket slot counts quantize to **multiples of
    dp** — the classes become {dp, 2·dp, 4·dp, …}, still a bounded set,
    so tenant join/evict churn never changes the compiled-shape set and
    every dispatch's tenant axis divides the mesh — and ORSet member
    classes lift to **multiples of mp** so each tenant's plane slice
    divides the model axis.  ``dp=mp=1`` (the default) is exactly the
    single-chip plan.
    """
    if rows_cap < 1 or cells_cap < 1 or tenants_cap < 1:
        raise ValueError("bucket caps must be positive")
    if dp < 1 or mp < 1:
        raise ValueError("mesh axes must be positive")
    groups: dict[tuple, list] = {}
    solo: list = []
    for s in shapes:
        if s.rows <= 0:
            continue  # nothing to fold — the caller's empty path
        rows_b = _bucket(s.rows)
        e_b = _bucket(s.members) if s.kind == "orset" else 0
        if e_b and e_b % mp:
            # lift to the next mp multiple: the class set stays bounded
            # (a pure function of the power-of-two classes), and a
            # non-power-of-two mp terminates — doubling would not
            e_b = -(-e_b // mp) * mp
        r_b = _bucket(s.replicas)
        if s.rows > rows_cap or (s.kind == "orset" and e_b * r_b > cells_cap):
            solo.append(s.key)
            continue
        groups.setdefault((s.kind, rows_b, e_b, r_b), []).append(s.key)
    buckets: list[Bucket] = []
    for (kind, rows_b, e_b, r_b), keys in sorted(
        groups.items(), key=lambda kv: (kv[0][0], kv[0][1], kv[0][2], kv[0][3])
    ):
        for lo in range(0, len(keys), tenants_cap):
            chunk = keys[lo : lo + tenants_cap]
            slots = dp * _bucket(-(-len(chunk) // dp), floor=1)
            buckets.append(Bucket(kind, rows_b, e_b, r_b, chunk, slots))
    return buckets, solo

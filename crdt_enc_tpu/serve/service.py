"""Multi-tenant fold service: thousands of small remotes, one dispatch.

The paper's design is one device folding one remote; the north star is
millions of *users* — millions of small encrypted remotes, where every
solo ``Core.compact()`` pays full dispatch, session, and probe overhead
per tenant (ROADMAP item 1).  :class:`FoldService` amortizes all of it
across a fleet of open cores:

1. **ingest** — per tenant, the service reads remote meta + snapshots
   through the tenant's normal paths and pulls the pending op tail
   through ``Core.load_sealed_ops`` (list → load → outer unwrap,
   ciphertexts grouped by sealing key, decrypt deferred to the
   cycle-wide phase below), then validates versions with the core's
   own ``_validate_chunk`` — cursors do NOT advance until the fold
   lands, exactly the solo bulk-ingest discipline.  Tenants ingest
   concurrently under a bounded semaphore.
2. **decode** — the PR-3 producer pool (``ops.stream
   .run_ingest_pipeline``) fans the native columnar decode out ACROSS
   TENANTS instead of across one tenant's chunks: worker threads decode
   different tenants' payloads in parallel (the native calls release
   the GIL) while the sequencer collects results in tenant order.
3. **plan + fold** — decoded tenants quantize into bucketed size
   classes (``serve.bucketing``) and every bucket collapses in ONE
   vmapped device dispatch (``ops.orset.orset_fold_tenants`` /
   ``ops.counters.gcounter_fold_tenants``): the tenant batch is just
   another fold axis over the existing columnar kernels.  Oversized
   tenants spill to the existing solo accelerator paths
   (``fold_payloads`` — sparse/streaming regimes); tenants the decoder
   declines fold per-op through ``Core._fold_chunk_python``.  The whole
   fold phase — plane capture, kernel, writeback, cursor advance — is
   one synchronous section, so concurrent applies can never interleave
   a torn (planes, state) pair (the same stall ``finish_session`` buys
   in the solo pipeline).
4. **scatter + seal** — per-tenant result planes write back through
   ``orset_planes_to_state`` into each tenant's live state, and each
   tenant seals through its normal encrypted snapshot path
   (``Core._compact_seal``): the same snapshot wire form, GC ordering,
   checkpoint reseal, and sink record as a solo compact — byte-identical
   states by construction, pinned end-to-end by the differential tests.

**Warm tier** (``serve.warm``): each tenant's post-fold planes are kept
under a byte-budgeted LRU keyed by state identity × mutation epoch, so
the next cycle on an un-mutated tenant skips the sparse state walk and
the full-plane re-upload — the multi-tenant generalization of the PR-4
device-resident plane cache.

**Replication probes**: a solo compact pays one per-actor ``stat_ops``
probe per tenant when it samples replication status.  The service's
ingest just folded everything its own listing found, so every tenant's
sample reuses that listing (``_compact_seal(_backlog=[])`` — the same
contract as ``read_remote``'s post-ingest sample): a batch of N tenants
pays ZERO extra storage probes per cycle, regression-pinned in
tests/test_serve.py.

Every phase emits ``serve.*`` spans and the per-tenant end-to-end
latency lands in the ``serve.tenant`` histogram (p50/p95/p99 via the
obs registry) — ``bench.py --e2e-multitenant`` publishes them.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field

import numpy as np

from .. import ops as K
from ..models import GCounter, ORSet
from ..models.counters import POS
from ..utils import codec, trace
from . import bucketing
from .bucketing import TenantShape, _bucket, plan_buckets
from .warm import DEFAULT_BYTE_BUDGET, PlaneWarmTier

logger = logging.getLogger("crdt_enc_tpu.serve")


@dataclass
class ServeConfig:
    """Service knobs; the defaults serve the many-small-tenants shape."""

    rows_cap: int = bucketing.DEFAULT_ROWS_CAP
    cells_cap: int = bucketing.DEFAULT_CELLS_CAP
    tenants_cap: int = bucketing.DEFAULT_TENANTS_CAP
    # decode fan-out width: 0 = auto (ops.stream.stream_producer_count)
    producers: int = 0
    # concurrent tenant ingests/seals (bounded asyncio semaphore)
    io_width: int = 16
    # warm plane tier (serve.warm); budget in summed plane bytes
    warm: bool = True
    warm_bytes: int = DEFAULT_BYTE_BUDGET
    # seal a snapshot for tenants with no new ops (solo-compact parity);
    # off = a quiet tenant costs nothing per cycle
    seal_empty: bool = True
    # skip the whole seal/GC/checkpoint tail for a tenant whose seal
    # SIGNATURE has not moved since its last seal (cursor, read sets,
    # mutation epoch — Core._seal_signature): re-sealing would publish
    # the identical snapshot, so the cycle honestly no-ops it
    # (``serve_noop_cycles``).  Off = every cycle re-seals, the
    # O(state) steady state (the bench's comparison arm).
    noop_skip: bool = True


@dataclass
class TenantResult:
    """One tenant's outcome for one service cycle.  ``path`` is how its
    ops folded: ``batched`` (the mega-fold), ``solo`` (spilled to the
    single-tenant accelerator bulk path), ``perop`` (decoder declined —
    python per-op fold), ``empty`` (no new ops), or ``error``."""

    path: str = "empty"
    rows: int = 0
    latency_s: float = 0.0
    sealed: bool = False
    error: str | None = None


@dataclass
class _TenantWork:
    idx: int
    core: object
    actors: list = field(default_factory=list)
    files: list = field(default_factory=list)
    groups: list = field(default_factory=list)  # (key, idxs, middles)
    clears: list = field(default_factory=list)
    payloads: list = field(default_factory=list)
    metas: list = field(default_factory=list)
    actors_sorted: list = field(default_factory=list)
    kind: str | None = None  # "orset" | "gcounter" | None (solo type)
    cols: tuple | None = None  # decoded columns + vocabs
    prepared: tuple | None = None  # fold-phase planes/vocabs
    packed: tuple | None = None  # planes-packed checkpoint payload
    state_obj: tuple | None = None  # pre-built snapshot state obj
    delta_cut: dict | None = None  # device-cut delta candidate
    result: TenantResult = field(default_factory=TenantResult)

    @property
    def ok(self) -> bool:
        return self.result.error is None


def _actor_table(state, actors) -> list:
    """Sorted actor table for the native decoders: the storage listing
    plus every actor the state mentions (the serving twin of
    ``TpuAccelerator._orset_actor_table``, without the fast-path
    micro-optimizations — tenant tables are small by definition)."""
    actor_set = set(actors)
    if isinstance(state, ORSet):
        actor_set.update(state.clock.counters)
        for entry in state.entries.values():
            actor_set.update(entry)
        for dfr in state.deferred.values():
            actor_set.update(dfr)
    elif isinstance(state, GCounter):
        actor_set.update(state.clock.counters)
    return sorted(actor_set)


def _decode_orset_columns(adapter, payloads, actors_sorted):
    """One tenant's payloads → ``(kind, member, actor, counter, members,
    replicas)`` columns.  Native span decode first; the Python
    columnarizer takes over when the native decoder declines OR a
    member value collision (1 == True, 0.0 == -0.0) makes the native
    per-bytes vocab unrepresentable as dense planes — the Python path
    interns by value, which IS the host dict semantics."""
    from ..ops.native_decode import decode_orset_payload_batch

    try:
        decoded = decode_orset_payload_batch(payloads, actors_sorted)
    except RuntimeError:  # native lib unavailable on this box
        decoded = None
    if decoded is not None:
        kind, member_idx, actor_idx, counter, member_objs = decoded
        members = K.Vocab(member_objs)
        if len(members) == len(member_objs):
            replicas = K.Vocab.presorted_unique(list(actors_sorted))
            return kind, member_idx, actor_idx, counter, members, replicas
    ops = [
        adapter.op_from_obj(o) for p in payloads for o in codec.unpack(p)
    ]
    members, replicas = K.Vocab(), K.Vocab(list(actors_sorted))
    cols = K.orset_ops_to_columns(ops, members, replicas)
    return cols.kind, cols.member, cols.actor, cols.counter, members, replicas


def _decode_gcounter_columns(adapter, payloads, actors_sorted):
    """One tenant's payloads → ``(actor, counter, replicas)`` columns,
    or None when the rows are not plain G-Counter increments (the
    per-op path then decides, exactly as the solo bulk path would)."""
    from ..ops.native_decode import decode_counter_payload_batch

    try:
        decoded = decode_counter_payload_batch(payloads, actors_sorted)
    except RuntimeError:  # native lib unavailable on this box
        decoded = None
    if decoded is not None:
        sign, actor_idx, counter = decoded
        if len(sign) and bool(np.any(sign != POS)):
            return None
        return actor_idx, counter, K.Vocab.presorted_unique(
            list(actors_sorted)
        )
    ops = [
        adapter.op_from_obj(o) for p in payloads for o in codec.unpack(p)
    ]
    cols = K.counter_ops_to_columns(ops, K.Vocab(list(actors_sorted)))
    if len(cols.sign) and bool(np.any(cols.sign != POS)):
        return None
    return cols.actor, cols.counter, cols.replicas


class FoldService:
    """Batch many tenants' compactions into shared device dispatches.

    ``tenants`` are OPEN :class:`~crdt_enc_tpu.core.Core` handles, each
    attached to its own remote; the service takes over their compaction
    cadence (``run_cycle`` ≈ one ``compact()`` for every tenant).  The
    service owns the write side of its tenants while a cycle runs the
    same way a solo compact does — concurrent local ``apply_ops`` are
    honored (the fold phase is one sync section), but a second
    concurrent compactor on the same tenant is the caller's bug, as it
    always was.
    """

    def __init__(self, tenants, config: ServeConfig | None = None,
                 live_port: int | None = None, mesh=None):
        self.tenants = list(tenants)
        self.config = config if config is not None else ServeConfig()
        # device mesh (parallel.mesh.make_mesh): with more than one
        # device the bucketed mega-folds run the SPMD tenant kernels —
        # tenant lanes over dp, member planes over mp — and oversize
        # spills route through a service-owned mesh accelerator's
        # orset_fold_sharded path instead of the tenant's solo chip.
        # The planner quantizes bucket classes to the mesh axes, so the
        # zero-steady-state-recompile contract survives sharding.
        self.mesh = mesh
        self._mesh_active = mesh is not None and mesh.size > 1
        self._mesh_accel = None
        if self._mesh_active:
            from ..parallel.accel import TpuAccelerator

            self._mesh_accel = TpuAccelerator(min_device_batch=1, mesh=mesh)
            trace.gauge("serve_mesh_devices", mesh.size)
        self.warm = (
            PlaneWarmTier(
                self.config.warm_bytes,
                mesh_key=mesh if self._mesh_active else None,
            )
            if self.config.warm
            else None
        )
        # the mesh-identity guard, enforced where entries are consumed:
        # a tier built for another device layout holds plane slices this
        # service cannot address (today the service builds its own tier,
        # so this can only fire if tier injection is ever added — which
        # is exactly when it must)
        if self.warm is not None and not self.warm.compatible_with(
            mesh if self._mesh_active else None
        ):
            raise ValueError(
                "warm tier belongs to a different mesh identity"
            )
        # service-owned live telemetry endpoint (obs/live.py): /metrics,
        # /healthz (per-tenant watermarks + the last cycle summary),
        # /snapshot.  live_port=0 binds an ephemeral port (see
        # self.live.port); None = no server (the process-default
        # CRDT_OBS_HTTP server, if any, still receives publications).
        self.live = None
        if live_port is not None:
            from ..obs.live import LiveTelemetryServer

            self.live = LiveTelemetryServer(port=live_port)
            self.live.start()
        # last cycle's summary (tenant paths, wall, SLO burn) — what
        # /healthz shows and the cycle sink record carries
        self.last_cycle_summary: dict | None = None
        # lifecycle guards: a second close() is a logged no-op, a cycle
        # on a closed service (or overlapping a running one) is a loud
        # error — never a hang or an interleaved fold
        self._closed = False
        self._cycle_running = False
        # shared-owner serialization (run_cycle_shared): lazily built per
        # event loop so a service outliving one asyncio.run() can be
        # shared again under the next loop
        self._owner_lock: asyncio.Lock | None = None
        self._owner_loop = None

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        """Graceful shutdown of service-owned resources (the live
        telemetry listener; tenants stay open — they are the caller's).
        Idempotent: a second close is a logged no-op, never a hang."""
        if self._closed:
            logger.warning("FoldService.close(): already closed (no-op)")
            return
        self._closed = True
        if self.live is not None:
            self.live.stop()

    # ------------------------------------------------------------- cycle
    async def run_cycle(self, tenants=None) -> list[TenantResult]:
        """One service cycle: ingest → decode → bucketed mega-folds →
        per-tenant seal.  ``tenants`` overrides the fleet for THIS cycle
        (the daemon's staleness scheduler compacts subsets); default is
        ``self.tenants``.  Returns one :class:`TenantResult` per tenant
        (index-aligned with the cycled list).  Tenant failures are
        isolated: an erroring tenant reports ``path="error"`` and the
        rest of the fleet still compacts.

        NOT reentrant: the fold phase assumes exclusive ownership of the
        cycle's tenants, so an overlapping ``run_cycle`` (or one on a
        closed service) raises ``RuntimeError`` immediately instead of
        silently interleaving two fleets' folds."""
        if self._closed:
            raise RuntimeError("FoldService is closed; run_cycle refused")
        if self._cycle_running:
            raise RuntimeError(
                "FoldService.run_cycle is not reentrant: a cycle is "
                "already in flight on this service"
            )
        self._cycle_running = True
        try:
            return await self._run_cycle(
                self.tenants if tenants is None else list(tenants)
            )
        finally:
            self._cycle_running = False

    async def run_cycle_shared(self, tenants=None) -> list[TenantResult]:
        """Subset-cycle entry for MULTIPLE concurrent owners sharing one
        service (the population runner's lanes, docs/simulation.md
        "Population runs"): overlapping calls QUEUE on an internal
        asyncio lock and run one full cycle at a time, instead of
        tripping :meth:`run_cycle`'s non-reentrancy error.  Each queued
        cycle is exactly the cycle its owner would have run on a private
        service — the fold phase still has exclusive ownership of its
        tenants for the duration, the shared warm tier is keyed by
        tenant-state identity so owners never alias — which is what
        keeps a lane's results bit-identical to its serial twin while P
        lanes amortize one set of jitted programs.  Single-owner callers
        should keep using :meth:`run_cycle`: the loud overlap error
        there is a real bug-catcher, not a nuisance."""
        loop = asyncio.get_running_loop()
        if self._owner_lock is None or self._owner_loop is not loop:
            self._owner_lock = asyncio.Lock()
            self._owner_loop = loop
        async with self._owner_lock:
            return await self.run_cycle(tenants)

    async def _run_cycle(self, tenants) -> list[TenantResult]:
        t0 = time.perf_counter()
        works = [_TenantWork(i, core) for i, core in enumerate(tenants)]
        with trace.span("serve.cycle"):
            await self._ingest_all(works)
            await self._decrypt_all(works)
            decodable = [w for w in works if w.ok and w.kind and w.payloads]
            if decodable:
                await asyncio.to_thread(self._decode_all, decodable)
            self._fold_batched(works)
            await self._fold_fallbacks(works)
            await self._seal_all(works, t0)
            self._stamp_continuations(works)
        trace.add("serve_cycles", 1)
        trace.add("serve_tenants", len(works))
        results = [w.result for w in works]
        await self._publish_cycle(tenants, results, time.perf_counter() - t0)
        return results

    async def _publish_cycle(self, tenants, results, wall_s: float) -> None:
        """Post-cycle telemetry: the cycle summary (tenant paths, wall,
        per-tenant seal-latency SLO burn) goes to the live /healthz
        endpoint and — when a sink is configured — into one
        ``serve_cycle`` sink record; each sealed tenant's replication
        status (sampled by its own ``_compact_seal``) feeds the live
        health map.  Strictly after the fold/seal work, never on the
        hot path, and never fatal to the cycle it describes."""
        from ..obs import live as obs_live
        from ..obs import sink as obs_sink
        from ..obs import slo as obs_slo

        try:
            burn = obs_slo.cycle_burn(results)
            paths: dict[str, int] = {}
            for r in results:
                paths[r.path] = paths.get(r.path, 0) + 1
            summary = {
                "tenants": len(results),
                "sealed": sum(1 for r in results if r.sealed),
                "errors": sum(1 for r in results if r.error is not None),
                "paths": paths,
                "wall_s": round(wall_s, 4),
                "slo": burn,
            }
            self.last_cycle_summary = summary
            trace.gauge("serve_slo_seal_burn", burn["burn_rate"])
            target = self.live if self.live is not None \
                else obs_live.default_server()
            if target is not None:
                target.publish_cycle("fold_service", summary)
                # only tenants that SEALED this cycle republished a
                # fresh replication sample (_compact_seal's sampler) —
                # republishing a quiet/errored tenant's old status
                # would stamp stale watermark data with a current ts,
                # hiding exactly the wedged-replica staleness /healthz
                # exists to expose
                for core, r in zip(tenants, results):
                    status = getattr(core, "last_replication_status", None)
                    if r.sealed and status is not None:
                        target.publish_health(status)
            if obs_sink.default_sink() is not None:
                await asyncio.to_thread(
                    obs_sink.maybe_write, "serve_cycle", summary
                )
        except Exception:  # telemetry must not fail the fleet cycle
            logger.debug("cycle telemetry publication failed",
                         exc_info=True)

    # ------------------------------------------------------- strong reads
    async def read_strong(self, core, *, max_lag=None, min_cursor=None,
                          refresh: bool = True):
        """Per-tenant strong read through the serving layer
        (docs/strong_reads.md): the same stable-prefix guarantee as
        ``Core.read(linearizable=True)`` — served tenants do not trade
        consistency for batching.  ``refresh=False`` skips the
        per-read ``read_remote`` when the caller knows the tenant just
        cycled (the daemon's post-cycle waiter resolution); the
        default refreshes, so a standalone endpoint call observes the
        latest published cursors.  Refusals raise
        :class:`~crdt_enc_tpu.read.StalenessError` unchanged."""
        if self._closed:
            raise RuntimeError("FoldService is closed; read_strong refused")
        with trace.span("serve.read_strong"):
            trace.add("serve_strong_reads", 1)
            return await core.read(
                linearizable=True, max_lag=max_lag,
                min_cursor=min_cursor, refresh=refresh,
            )

    # ------------------------------------------------------------ ingest
    async def _ingest_all(self, works) -> None:
        sem = asyncio.Semaphore(max(1, self.config.io_width))

        async def one(w: _TenantWork):
            async with sem:
                try:
                    with trace.span("serve.ingest", meta=w.idx):
                        core = w.core
                        await core._read_remote_meta()
                        await core._read_remote_states()
                        # decrypt-deferred ops load: ciphertexts grouped
                        # by sealing key; the cycle-wide decrypt phase
                        # below opens every tenant's in ONE thread hop
                        w.actors, w.files, w.groups = (
                            await core.load_sealed_ops()
                        )
                except Exception as e:  # tenant isolation, never fleet-fatal
                    w.result.error = repr(e)
                    w.result.path = "error"

        await asyncio.gather(*(one(w) for w in works))

    # ----------------------------------------------------------- decrypt
    async def _decrypt_all(self, works) -> None:
        """Open every tenant's ciphertexts, then validate versions.

        Tenants whose cryptor exposes the sync bulk hook
        (``Cryptor.decrypt_batch_fn``) all decrypt inside ONE
        ``asyncio.to_thread`` hop — per-tenant thread round-trips
        (~1ms each) would otherwise dominate a many-small-tenant cycle;
        the rest fall back to the normal async ``decrypt_batch``.  The
        version checks (``_validate_chunk``) run back on the event
        loop: they read live cursors, which must not race a concurrent
        apply."""
        sync_plans: list[tuple[_TenantWork, list]] = []
        async_works: list[_TenantWork] = []
        for w in works:
            if not w.ok or not w.files:
                continue
            try:
                plans = []
                for key, idxs, mids in w.groups:
                    fn = w.core.cryptor.decrypt_batch_fn(key.material)
                    if fn is None:
                        plans = None
                        break
                    plans.append((fn, idxs, mids))
            except Exception as e:  # e.g. foreign key version — tenant-local
                w.result.error = repr(e)
                w.result.path = "error"
                continue
            if plans is None:
                async_works.append(w)
            else:
                sync_plans.append((w, plans))

        def run_sync_plans():
            from ..core.core import _QUARANTINED

            for w, plans in sync_plans:
                try:
                    clears: list = [None] * len(w.files)
                    for fn, idxs, mids in plans:
                        try:
                            outs = fn(mids)
                        except Exception:
                            # a damaged blob in the batch: isolate it
                            # per file — the core's quarantine
                            # discipline (skip + counter + held
                            # cursor), not a whole-tenant error.  But
                            # the WHOLE batch failing is a dead
                            # cryptor / damaged key, not file damage:
                            # re-raise into the tenant error (the
                            # core's _decrypt_tolerant escalation rule)
                            outs, failed = [], []
                            for i, m in zip(idxs, mids):
                                try:
                                    outs.append(fn([m])[0])
                                except Exception as e:
                                    outs.append(_QUARANTINED)
                                    failed.append((i, e))
                            if len(mids) > 1 and len(failed) == len(mids):
                                from ..core.core import IngestDecryptError

                                raise IngestDecryptError(
                                    f"all {len(mids)} op files in the "
                                    "tenant batch failed to open"
                                ) from failed[-1][1]
                            for i, e in failed:
                                actor, version, _ = w.files[i]
                                w.core._note_quarantine(
                                    "op",
                                    f"{actor.hex()}:v{version}", e,
                                )
                        for i, clear in zip(idxs, outs):
                            clears[i] = clear
                    w.clears = clears
                    trace.add(
                        "bytes_decrypted",
                        sum(len(m) for _, _, mids in plans for m in mids),
                    )
                except Exception as e:  # tenant-local (plan-level surprise)
                    w.result.error = repr(e)
                    w.result.path = "error"

        if sync_plans:
            with trace.span("serve.decrypt", meta=len(sync_plans)):
                await asyncio.to_thread(run_sync_plans)
        for w in async_works:
            try:
                with trace.span("serve.decrypt", meta=w.idx):
                    clears = [None] * len(w.files)
                    for key, idxs, mids in w.groups:
                        # per-file quarantine on damage, exactly the
                        # solo bulk path's discipline
                        outs = await w.core._decrypt_tolerant(
                            key, [w.files[i] for i in idxs], mids
                        )
                        for i, clear in zip(idxs, outs):
                            clears[i] = clear
                    w.clears = clears
                    trace.add(
                        "bytes_decrypted",
                        sum(len(m) for _, _, mids in w.groups for m in mids),
                    )
            except Exception as e:
                w.result.error = repr(e)
                w.result.path = "error"
        # sync section: inner version checks WITHOUT cursor advance —
        # cursors move only after the fold lands
        for w in works:
            if not w.ok or not w.files:
                continue
            try:
                w.payloads, w.metas = w.core._validate_chunk(
                    w.files, w.clears
                )
                state = w.core._data.state
                if isinstance(state, ORSet):
                    w.kind = "orset"
                elif isinstance(state, GCounter):
                    w.kind = "gcounter"
                if w.payloads:
                    w.actors_sorted = _actor_table(state, w.actors)
            except Exception as e:
                w.result.error = repr(e)
                w.result.path = "error"

    # ------------------------------------------------------------ decode
    def _decode_all(self, works) -> None:
        """Cross-tenant decode fan-out: the PR-3 producer pool with
        TENANTS as the work items.  Runs off the event loop (the native
        decode calls release the GIL, so the workers genuinely overlap);
        results land on each work item in tenant order."""
        from ..ops.stream import run_ingest_pipeline, stream_producer_count

        producers = stream_producer_count(self.config.producers)
        # a few work items per producer: per-item queue/span overhead is
        # ~1ms, so thousands of tiny tenants ride in tenant GROUPS
        group = max(1, -(-len(works) // max(producers * 4, 1)))
        chunks = [
            works[i : i + group] for i in range(0, len(works), group)
        ]

        def decode_one(w: _TenantWork):
            with trace.span("serve.decode", meta=w.idx):
                if w.kind == "orset":
                    return _decode_orset_columns(
                        w.core.adapter, w.payloads, w.actors_sorted
                    )
                return _decode_gcounter_columns(
                    w.core.adapter, w.payloads, w.actors_sorted
                )

        def ingest(chunk: list, k: int):
            out = []
            for w in chunk:
                try:
                    out.append(decode_one(w))
                except Exception as e:  # tenant isolation
                    out.append(("error", e))
            return out

        def reduce(decoded_list, k: int):
            for w, decoded in zip(chunks[k], decoded_list):
                if isinstance(decoded, tuple) and len(decoded) == 2 and \
                        decoded[0] == "error":
                    w.result.error = repr(decoded[1])
                    w.result.path = "error"
                else:
                    w.cols = decoded  # None = per-op fallback

        run_ingest_pipeline(
            chunks, ingest, reduce, producers=producers,
            thread_prefix="crdt-serve-producer",
        )

    # -------------------------------------------------------------- fold
    def _fold_batched(self, works) -> None:
        """Plan and run the bucketed mega-folds.  One synchronous
        section per cycle: plane capture, kernel dispatch, writeback and
        cursor advance never interleave with concurrent applies."""
        by_idx: dict[int, _TenantWork] = {}
        shapes: list[TenantShape] = []
        with trace.span("serve.plan"):
            for w in works:
                if not (w.ok and w.kind and w.payloads):
                    continue
                if w.cols is None:
                    w.result.path = "perop"
                    continue
                if len(w.cols[0]) == 0:
                    # validated files that decode to ZERO rows (e.g. an
                    # empty-ctx remove, or an empty op list a foreign
                    # writer sealed): the fold is a no-op but the
                    # cursors MUST advance exactly as the solo path's
                    # — or the sealed snapshot carries a stale cursor
                    # and the covered files are re-read forever
                    w.core._advance_cursors(w.metas)
                    w.result.path = "batched"
                    continue
                prepared = self._prepare_tenant(w)
                if prepared is None:
                    w.result.path = "solo"
                    continue
                shape = prepared[0]
                w.prepared = prepared[1]
                by_idx[w.idx] = w
                shapes.append(shape)
            buckets, solo = plan_buckets(
                shapes,
                rows_cap=self.config.rows_cap,
                cells_cap=self.config.cells_cap,
                tenants_cap=self.config.tenants_cap,
                dp=self.mesh.shape["dp"] if self._mesh_active else 1,
                mp=self.mesh.shape["mp"] if self._mesh_active else 1,
            )
            for key in solo:
                by_idx[key].result.path = "solo"
                trace.add("serve_solo_spills", 1)
                del by_idx[key]
        trace.gauge("serve_buckets", len(buckets))
        for bi, bucket in enumerate(buckets):
            try:
                if bucket.kind == "orset":
                    self._fold_orset_bucket(bi, bucket, by_idx)
                else:
                    self._fold_gcounter_bucket(bi, bucket, by_idx)
            except Exception as e:  # e.g. device OOM stacking a bucket
                # tenant isolation at bucket granularity: tenants whose
                # scatter already landed (path "batched", cursors
                # advanced) go on to seal; the rest of the bucket
                # reports the error and the OTHER buckets still fold
                for key in bucket.tenants:
                    w = by_idx[key]
                    if w.result.path != "batched":
                        w.result.error = repr(e)
                        w.result.path = "error"

    def _prepare_tenant(self, w: _TenantWork):
        """Fold-phase prep for one decoded tenant: resolve vocabularies
        (warm-tier remap or state scan) and pin its ragged shape.
        Returns ``(TenantShape, prepared)`` or None to route the tenant
        to the solo path (wide clocks the int32 planes cannot hold)."""
        state = w.core._data.state
        if w.kind == "orset":
            from ..parallel.accel import TpuAccelerator

            kind, member, actor, counter, members, replicas = w.cols
            entry = self.warm.lookup(state) if self.warm is not None else None
            if entry is not None:
                remapped = TpuAccelerator._remap_to_cache(
                    entry, member, actor, members, replicas
                )
                if remapped is None:
                    entry = None
                else:
                    member, actor = remapped
                    members, replicas = entry.members, entry.replicas
            if entry is None:
                K.orset_scan_vocab(state, members, replicas)
            shape = TenantShape(
                w.idx, "orset", len(kind), len(members), len(replicas)
            )
            return shape, (kind, member, actor, counter, members, replicas,
                           entry)
        actor_idx, counter, replicas = w.cols
        clock0 = K.vclock_to_dense(state.clock, replicas)
        if clock0.dtype != np.int32:
            return None  # >int32 counters: the solo sparse path's regime
        shape = TenantShape(
            w.idx, "gcounter", len(actor_idx), 0, len(replicas)
        )
        return shape, (actor_idx, counter, replicas, clock0)

    def _fold_orset_bucket(self, bi: int, bucket, by_idx) -> None:
        import jax
        import jax.numpy as jnp

        from ..core.core import CHECKPOINT_FMT_ORSET
        from ..parallel.accel import TpuAccelerator

        cpu_backend = jax.default_backend() == "cpu"

        # re-quantize at the call site (idempotent — the planner already
        # bucketed) so the jitted statics' boundedness is provenance-
        # checkable (JIT002) right where they are passed
        N_b = _bucket(bucket.rows)
        E_b = _bucket(bucket.members)
        R_b = _bucket(bucket.replicas)
        T = bucket.slots
        kind = np.zeros((T, N_b), np.int8)
        member = np.zeros((T, N_b), np.int32)
        actor = np.full((T, N_b), R_b, np.int32)  # dummy lanes: all-pad
        counter = np.zeros((T, N_b), np.int32)
        clock_rows, add_rows, rm_rows = [], [], []
        # slots whose pre-fold planes ARE the tenant's current delta
        # base (a live warm entry stamped with the base's seal name):
        # after the fold these tenants can cut their delta on device
        # from planes already in hand — no host dict walk, no retained
        # base bytes (docs/delta.md "device-cut deltas")
        cut_slots: list[tuple[int, object]] = []
        for slot, key in enumerate(bucket.tenants):
            w = by_idx[key]
            k, m, a, c, members, replicas, entry = w.prepared
            if (
                entry is not None
                and entry.seal_name is not None
                and entry.seal_name == w.core.delta_base_name
                and w.core._delta_enabled
                and getattr(w.core.storage, "has_deltas", False)
            ):
                cut_slots.append((slot, key))
            n = len(k)
            kind[slot, :n] = k
            member[slot, :n] = m
            actor[slot, :n] = a
            counter[slot, :n] = c
            E, R = len(members), len(replicas)
            if entry is not None:
                clock0, add0, rm0 = TpuAccelerator._cached_planes_padded(
                    entry, E_b, R_b
                )
            else:
                clock0, add0, rm0 = K.orset_state_to_planes(
                    w.core._data.state, members, replicas, scanned=True
                )
                pads = ((0, E_b - E), (0, R_b - R))
                add0 = np.pad(add0, pads)
                rm0 = np.pad(rm0, pads)
                clock0 = np.pad(clock0, (0, R_b - R))
            clock_rows.append(clock0)
            add_rows.append(add0)
            rm_rows.append(rm0)
        for _ in range(T - len(bucket.tenants)):
            clock_rows.append(jnp.zeros(R_b, jnp.int32))
            add_rows.append(jnp.zeros((E_b, R_b), jnp.int32))
            rm_rows.append(jnp.zeros((E_b, R_b), jnp.int32))
        # every HOST-sourced plane row uploads here (cold scans always;
        # warm-tier rows too on the CPU backend, where the tier stores
        # host views) plus the op columns; device-resident rows re-wrap
        # for free
        trace.add(
            "h2d_bytes",
            sum(
                x.nbytes
                for rows in (clock_rows, add_rows, rm_rows)
                for x in rows
                if isinstance(x, np.ndarray)
            )
            + kind.nbytes + member.nbytes + actor.nbytes + counter.nbytes,
        )
        # stack the pre-fold planes ONCE: the fold consumes them and —
        # when any slot is cut-eligible — the plane diff reuses the very
        # same device stacks as its base side
        clock_s = jnp.stack(clock_rows)
        add_s = jnp.stack(add_rows)
        rm_s = jnp.stack(rm_rows)
        if self._mesh_active:
            # SPMD mega-fold: tenant lanes over dp, member planes over
            # mp (parallel.mesh.orset_fold_tenants_sharded) — slot and
            # member classes already divide the mesh by planner law
            from ..parallel import mesh as pmesh

            orset_step, _ = pmesh.tenant_fold_steps(self.mesh)
            with trace.span("serve.shard", meta=bi):
                out = orset_step(
                    clock_s, add_s, rm_s, kind, member, actor, counter,
                )
            trace.add("serve_sharded_folds", 1)
            trace.add("serve_sharded_tenants", len(bucket.tenants))
        else:
            with trace.span("serve.fold", meta=bi):
                out = K.orset_fold_tenants(
                    clock_s, add_s, rm_s, kind, member, actor, counter,
                    num_members=E_b, num_replicas=R_b,
                )
        with trace.span("serve.scatter", meta=bi):
            clock_all = np.asarray(out[0])
            add_all = np.asarray(out[1])
            rm_all = np.asarray(out[2])
            for slot, key in enumerate(bucket.tenants):
                w = by_idx[key]
                _, _, _, _, members, replicas, entry = w.prepared
                E, R = len(members), len(replicas)
                state = w.core._data.state
                folded = K.orset_planes_to_state(
                    clock_all[slot][:R], add_all[slot][:E, :R],
                    rm_all[slot][:E, :R], members, replicas,
                )
                state.clock = folded.clock
                state.entries = folded.entries
                state.deferred = folded.deferred
                note = getattr(w.core.accel, "_note_orset_writeback", None)
                if note is not None:
                    note(state)
                else:
                    state._mut += 1
                w.core._advance_cursors(w.metas)
                # the warm-open checkpoint payload, packed VECTORIZED
                # from the planes just written back (the sparse pack
                # walk was the seal phase's biggest CPU item at fleet
                # scale); the recorded epoch lets save_checkpoint
                # reject it if a concurrent apply lands before the seal
                w.packed = (
                    CHECKPOINT_FMT_ORSET,
                    K.orset_pack_checkpoint_planes(
                        clock_all[slot], add_all[slot], rm_all[slot],
                        members, replicas,
                    ),
                    state._mut,
                )
                # snapshot payload obj without a second state walk: the
                # dicts just written back ARE plane-canonical (entries
                # non-empty, retired horizons already dropped), so
                # wrapping them is exactly ORSet.to_obj's output; the
                # epoch guard keeps the alias safe (any mutation makes
                # _compact_seal re-serialize the live state) and the
                # canonical packer re-sorts, so the sealed bytes equal
                # a solo compact's
                w.state_obj = (
                    {
                        b"c": state.clock.to_obj(),
                        b"e": state.entries,
                        b"d": state.deferred,
                    },
                    state._mut,
                )
                n_rows = len(w.prepared[0])
                w.result.path = "batched"
                w.result.rows = n_rows
                trace.add("serve_rows_folded", n_rows)
                if self.warm is not None:
                    # the tenant's next-cycle resume planes, epoch-
                    # stamped post-writeback.  On an accelerator the
                    # DEVICE slices are kept (no re-upload next cycle);
                    # the CPU backend keeps host copies — "device" and
                    # host are the same silicon there, and small owned
                    # copies beat pinning the whole bucket stack alive
                    if cpu_backend:
                        planes = (
                            clock_all[slot].copy(),
                            add_all[slot].copy(),
                            rm_all[slot].copy(),
                        )
                    else:
                        planes = (out[0][slot], out[1][slot], out[2][slot])
                    self.warm.store(
                        state, members, replicas, planes,
                        canon=entry.canon if entry is not None else None,
                    )
        if cut_slots:
            # device-cut delta sealing (docs/delta.md): diff the bucket's
            # pre-fold stacks (for eligible slots, byte-identical to the
            # tenants' sealed diff bases) against the post-fold planes in
            # ONE dispatch, then D2H only the diff rows per eligible
            # tenant and build the Orswot wire form from them.  Slots
            # that are not cut-eligible ride the same dispatch for free
            # and their code rows are simply never read.  A separate
            # span, deliberately outside serve.scatter: attribution
            # groups both under the seal stage without double-counting.
            from ..delta.codec import orset_delta_from_rows

            with trace.span("delta.cut", meta=bi):
                if self._mesh_active:
                    from ..parallel import mesh as pmesh

                    code, counts = pmesh.tenant_diff_step(self.mesh)(
                        clock_s, add_s, rm_s, out[0], out[1], out[2]
                    )
                else:
                    code, counts = K.orset_plane_diff_tenants(
                        clock_s, add_s, rm_s, out[0], out[1], out[2]
                    )
                counts = np.asarray(counts)  # one (T,) D2H per bucket
                cells = E_b * R_b
                for slot, key in cut_slots:
                    w = by_idx[key]
                    _, _, _, _, members, replicas, entry = w.prepared
                    state = w.core._data.state
                    n_diff = int(counts[slot])
                    if n_diff:
                        size = min(_bucket(n_diff), cells)
                        rows = K.orset_plane_diff_rows(
                            code[slot], add_s[slot], out[1][slot],
                            out[2][slot], size=size,
                        )
                        # the ONLY per-tenant D2H of the cut: O(diff
                        # rows), not O(state)
                        rows = tuple(np.asarray(r) for r in rows)
                    else:
                        empty = np.zeros(0, np.int64)
                        rows = (empty, empty, empty, empty, empty)
                    dobj = orset_delta_from_rows(
                        rows,
                        members=members.items,
                        replicas=replicas.items,
                        row_width=R_b,
                        base_clock=np.asarray(clock_rows[slot]),
                        new_clock=clock_all[slot],
                    )
                    # epoch-guarded candidate: _plan_delta_seal only
                    # accepts it while the base name AND the mutation
                    # epoch still match at seal time
                    w.delta_cut = {
                        "dobj": dobj,
                        "base_name": entry.seal_name,
                        "mut": state._mut,
                        "base_planes": (
                            clock_rows[slot], add_rows[slot],
                            rm_rows[slot], members, replicas,
                        ),
                    }

    def _fold_gcounter_bucket(self, bi: int, bucket, by_idx) -> None:
        N_b = _bucket(bucket.rows)
        R_b = _bucket(bucket.replicas)
        T = bucket.slots
        actor = np.full((T, N_b), R_b, np.int32)
        counter = np.zeros((T, N_b), np.int32)
        clock0 = np.zeros((T, R_b), np.int32)
        for slot, key in enumerate(bucket.tenants):
            w = by_idx[key]
            a, c, replicas, dense = w.prepared
            n = len(a)
            actor[slot, :n] = a
            counter[slot, :n] = c
            clock0[slot, : len(dense)] = dense
        trace.add(
            "h2d_bytes", clock0.nbytes + actor.nbytes + counter.nbytes
        )
        if self._mesh_active:
            from ..parallel import mesh as pmesh

            _, gcounter_step = pmesh.tenant_fold_steps(self.mesh)
            with trace.span("serve.shard", meta=bi):
                out = gcounter_step(clock0, actor, counter)
            trace.add("serve_sharded_folds", 1)
            trace.add("serve_sharded_tenants", len(bucket.tenants))
        else:
            with trace.span("serve.fold", meta=bi):
                out = K.gcounter_fold_tenants(
                    clock0, actor, counter, num_replicas=R_b
                )
        with trace.span("serve.scatter", meta=bi):
            out_all = np.asarray(out)
            for slot, key in enumerate(bucket.tenants):
                w = by_idx[key]
                a, _, replicas, _ = w.prepared
                state = w.core._data.state
                state.clock = K.dense_to_vclock(
                    out_all[slot][: len(replicas)], replicas
                )
                w.core._advance_cursors(w.metas)
                w.result.path = "batched"
                w.result.rows = len(a)
                trace.add("serve_rows_folded", len(a))

    @staticmethod
    def _fallback_rows(w: _TenantWork) -> int:
        """Op-ROW count for a fallback tenant, same units as the batched
        path's ``rows``: the decoded columns when the tenant was decoded
        (solo spills), else a payload unpack count (rare paths only —
        decoder declines and non-columnar types)."""
        if w.cols is not None:
            return len(w.cols[0])
        return sum(len(codec.unpack(p)) for p in w.payloads)

    # -------------------------------------------------------- fallbacks
    async def _fold_fallbacks(self, works) -> None:
        """Tenants outside the mega-fold: solo spills run the existing
        single-tenant bulk accelerator path on the already-decrypted
        payloads; decoder-declined tenants fold per-op — both the exact
        machinery a solo compact would have used."""
        for w in works:
            if not w.ok or not w.payloads:
                continue
            core = w.core
            # with an active mesh, a columnar oversize spill folds
            # through the service-owned mesh accelerator — the existing
            # solo orset_fold_sharded / gcounter_fold_sharded SPMD path
            # (one huge tenant uses the whole pod) — instead of the
            # tenant's own single-chip accelerator.  The writeback bumps
            # the state's _mut epoch, so any planes the tenant's own
            # accel cached for it expire by token, never go stale.
            spill_accel = (
                self._mesh_accel
                if self._mesh_accel is not None
                and w.kind in ("orset", "gcounter")
                else core.accel
            )
            try:
                if w.result.path == "solo":
                    ok = spill_accel.fold_payloads(
                        core._data.state, list(w.payloads),
                        actors_hint=w.actors_sorted,
                    )
                    if ok:
                        core._advance_cursors(w.metas)
                    else:
                        # the spilled tenant's bulk path declined too:
                        # report the machinery that actually folded it
                        await core._fold_chunk_python(w.files, w.clears)
                        w.result.path = "perop"
                        trace.add("serve_python_fallbacks", 1)
                    w.result.rows = self._fallback_rows(w)
                elif w.kind is None or w.result.path == "perop":
                    # no columnar kind (solo type) or decoder declined
                    ok = core.accel.fold_payloads(
                        core._data.state, list(w.payloads),
                        actors_hint=w.actors_sorted,
                    ) if w.kind is None else False
                    if ok:
                        core._advance_cursors(w.metas)
                        w.result.path = "solo"
                    else:
                        await core._fold_chunk_python(w.files, w.clears)
                        w.result.path = "perop"
                        trace.add("serve_python_fallbacks", 1)
                    w.result.rows = self._fallback_rows(w)
            except Exception as e:
                w.result.error = repr(e)
                w.result.path = "error"

    def _stamp_continuations(self, works) -> None:
        """Post-seal half of the persistent fold continuation: for every
        tenant that sealed this cycle and whose warm planes still match
        its live state, stamp the entry with the sealed snapshot's name
        (= the tenant's new delta base).  Next cycle those planes serve
        double duty — fold base for the tenant's new rows AND diff base
        for the device-cut delta — so the steady-state cycle touches
        only the tail.  Any doubt (mutation since the fold, no delta
        base, fallback-path seal) just leaves the entry unstamped: the
        next seal walks the host path, byte-identically."""
        if self.warm is None:
            return
        with trace.span("serve.continue"):
            stamped = 0
            for w in works:
                if not (w.ok and w.result.sealed):
                    continue
                name = w.core.delta_base_name
                if name is None:
                    continue
                if self.warm.stamp_seal(w.core._data.state, name):
                    stamped += 1
            if stamped:
                trace.add("serve_continuations", stamped)

    # -------------------------------------------------------------- seal
    async def _seal_all(self, works, t0: float) -> None:
        sem = asyncio.Semaphore(max(1, self.config.io_width))

        async def one(w: _TenantWork):
            async with sem:
                if not w.ok:
                    trace.add("serve_tenant_errors", 1)
                    w.result.latency_s = time.perf_counter() - t0
                    return
                if (
                    w.result.path == "empty"
                    and self.config.noop_skip
                    and w.core._last_seal_sig is not None
                    and w.core._seal_signature() == w.core._last_seal_sig
                ):
                    # quiet tenant, nothing moved since its last seal
                    # (cursor, read sets, mutation epoch all equal):
                    # re-sealing would publish the identical snapshot.
                    # Skip the seal, GC, checkpoint AND replication
                    # sample — the honest O(tail) no-op
                    # (docs/multitenant.md "cycle-cost law")
                    trace.add("serve_noop_cycles", 1)
                    w.result.latency_s = time.perf_counter() - t0
                    return
                if w.result.path == "empty" and not self.config.seal_empty:
                    w.result.latency_s = time.perf_counter() - t0
                    return
                try:
                    with trace.span("serve.seal", meta=w.idx):
                        # _backlog=[]: the cycle's ingest folded
                        # everything its own listing found — no second
                        # per-actor storage probe per tenant (the PR-6
                        # probe-cost fix, regression-pinned)
                        await w.core._compact_seal(
                            _backlog=[], _packed_state=w.packed,
                            _state_obj=w.state_obj,
                            _delta_cut=w.delta_cut,
                        )
                    w.result.sealed = True
                except Exception as e:
                    w.result.error = repr(e)
                    w.result.path = "error"
                    trace.add("serve_tenant_errors", 1)
                dt = time.perf_counter() - t0
                w.result.latency_s = dt
                if w.result.sealed:
                    # the registry documents this histogram as seal
                    # COMPLETIONS — failed seals carry their latency on
                    # the TenantResult but stay out of the percentiles
                    trace.observe("serve.tenant", dt)

        await asyncio.gather(*(one(w) for w in works))

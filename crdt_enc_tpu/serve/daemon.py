"""Always-on fleet daemon: a self-healing control plane over FoldService.

:class:`FoldService` is one *cycle*; production is a *process*.  The
:class:`FleetDaemon` owns a service and runs the supervised forever-loop
ROADMAP item 2 asks for, with the failure behavior a long-lived control
plane needs:

* **staleness-driven scheduling** — each supervised cycle compacts the
  tenants that *need* it, not the whole fleet round-robin.  Due-ness and
  priority derive from the measurement substrate PRs 6/11 built: the
  tenant's last ``replication_status`` (op backlog files/bytes past the
  cursor, ``watermark_lag`` — how far the union clock is ahead of the
  causal stability watermark of arXiv 1905.08733) plus freshness-SLO
  pressure (``obs.slo``): lag past the SLO target scores hardest, so
  laggards jump the queue.  Tenants not selected are *polled* — a
  stat-only ``replication_status`` probe refreshes their score without
  paying decrypt/decode.  Tenants opened with delta-state replication on
  consume PR-10 delta chains inside the cycle's ingest before falling
  back to full snapshots (``Core._read_remote_states`` is delta-first).
* **per-tenant retry / backoff / quarantine** — a failing tenant never
  poisons the cycle (the service already isolates it); the daemon adds
  the *temporal* half: consecutive failures back the tenant off with
  capped exponential delay plus seeded jitter (in units of cycles, so
  schedules replay deterministically), a re-probe path returns it to
  service when the delay expires, and repeat offenders park in a
  quarantine ring (``daemon_quarantined`` gauge) re-probed on a slow
  cadence.  Transient error classes (``IngestDecryptError`` — blobs not
  yet synced intact, ``StaleWriterError`` on reopen — own history not
  yet visible, storage hiccups) are exactly what the backoff exists
  for; they clear themselves on a later probe.
* **circuit breaker** — consecutive *whole-cycle* failures (every
  attempted tenant errored: a dead remote, a dead key service) trip the
  breaker into degraded mode: the daemon seals nothing and sheds all
  decrypt/decode load, keeps polling stat-only, and reports honestly
  (``daemon_degraded`` gauge, drain state in ``/healthz``).  A half-open
  probe every ``breaker_probe_every`` cycles attempts ONE tenant; a
  successful seal closes the breaker.
* **admission / eviction while running** — :meth:`admit` gates new
  tenants against the warm plane tier's byte budget (observed
  bytes-per-tenant, falling back to a configured estimate) and
  :meth:`evict` checkpoints a tenant and hands its core back, both
  serialized against in-flight cycles by the daemon lock — the fleet
  mutates between cycles, never during one.
* **graceful drain and crash/reopen** — :meth:`drain` (SIGTERM in the
  CLI) finishes the in-flight cycle, seals a warm-open checkpoint for
  every tenant, publishes the final health, and stops the live server.
  A SIGKILL'd daemon loses nothing durable: every seal went through the
  core's write-new-then-delete-old compaction and every cycle resealed
  checkpoints, so reopening the tenants (``Core.open(create=False)``)
  restores warm state and the first write re-runs the PR-9
  ``_ensure_own_history`` probe — dots are never reused and a remote
  that hides the pre-crash history refuses the write loudly
  (``StaleWriterError``) instead of diverging.

The daemon is pure asyncio over the existing machinery: no thread of
its own (the live endpoint keeps its one THR001-allowlisted server
thread), no new wire format, no storage writes beyond what compaction
and checkpoints already do.  ``python -m crdt_enc_tpu.tools.daemon``
wraps it as a process (docs/GUIDE.md "Running the daemon").
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from dataclasses import dataclass, field

from ..utils import trace
from .service import FoldService, ServeConfig
from .warm import DEFAULT_BYTE_BUDGET

logger = logging.getLogger("crdt_enc_tpu.serve.daemon")

#: tenant states of the backoff/quarantine machine (docs/multitenant.md)
ACTIVE = "active"
BACKOFF = "backoff"
QUARANTINED = "quarantined"

#: error classes the backoff path treats as self-clearing (substring
#: match on the ``TenantResult.error`` repr — the service reports errors
#: as reprs so tenant isolation never re-raises across the fleet)
TRANSIENT_ERRORS = (
    "IngestDecryptError",
    "StaleWriterError",
    "MissingKeyError",
    "OSError",
    "ConnectionError",
    "TimeoutError",
)


class AdmissionError(RuntimeError):
    """A tenant was refused admission (fleet or byte budget full)."""


@dataclass
class DaemonConfig:
    """Control-plane knobs.  Backoff and cadence are in units of
    *cycles*, not seconds — the daemon's behavior is then a pure
    function of its inputs (the simulator runs it inside deterministic
    schedules); ``interval_s`` only paces :meth:`FleetDaemon.run_forever`
    between cycles."""

    interval_s: float = 1.0
    # wall-clock-aware pacing (docs/strong_reads.md "Scheduling for
    # freshness"): with interval_auto on, run_forever paces by
    # next_interval() — real-time freshness-SLO burn over the last
    # burn_window_s (obs/slo.py window accounting applied live) drives
    # the interval geometrically between interval_max_s (no burn) and
    # interval_min_s (burn ≥ 1: the fleet is eating budget, laggards
    # blocking the watermark get re-scheduled sooner).  Timestamps come
    # from the daemon's clock seam, so the sim stays replayable.
    interval_auto: bool = False
    interval_min_s: float = 0.05
    interval_max_s: float = 8.0
    burn_window_s: float = 30.0
    # scheduler: compact when backlog ≥ min_backlog_files or watermark
    # lag exceeds the freshness-SLO target, and at least every
    # max_idle_cycles regardless; at most `batch` tenants per cycle
    batch: int = 256
    min_backlog_files: int = 1
    max_idle_cycles: int = 8
    # backoff: delay = min(cap, base·2^(failures-1)) cycles ± jitter
    backoff_base: float = 1.0
    backoff_cap: float = 32.0
    backoff_jitter: float = 0.25
    # quarantine ring: park after N consecutive failures, re-probe one
    # parked tenant every M cycles
    quarantine_after: int = 4
    quarantine_probe_every: int = 16
    # circuit breaker: trip after N consecutive whole-cycle failures,
    # half-open probe every M cycles while degraded
    breaker_after: int = 3
    breaker_probe_every: int = 4
    # admission: refuse tenants past this many, or past the byte budget
    # (admission_bytes; defaults to the serve warm budget) at the
    # observed-or-estimated per-tenant resident cost
    max_tenants: int = 100_000
    admission_bytes: int = 0  # 0 = serve.warm_bytes
    tenant_cost_bytes: int = 1 << 20
    serve: ServeConfig = field(
        default_factory=lambda: ServeConfig(seal_empty=False)
    )


@dataclass
class TenantEntry:
    """One admitted tenant's control-plane state."""

    tid: str
    core: object
    state: str = ACTIVE
    failures: int = 0  # consecutive; resets on success
    eligible_at: int = 0  # first cycle a backoff re-probe may run
    # cycle of the last successful service visit (a seal, or an "empty"
    # pass over a quiet tenant — both restart the idle cadence)
    last_sealed: int = -1
    quarantined_at: int | None = None
    last_error: str | None = None

    def status(self) -> dict | None:
        return getattr(self.core, "last_replication_status", None)


class FleetDaemon:
    """The supervised forever-loop over a :class:`FoldService` (module
    docs).  ``tenants`` seed the fleet (tids ``t0..tN``); admit/evict
    mutate it while running.  ``seed`` fixes the jitter stream so a
    seeded simulator schedule replays bit-for-bit."""

    def __init__(self, tenants=(), config: DaemonConfig | None = None,
                 live_port: int | None = None, seed: int = 0, mesh=None,
                 clock=None):
        self.config = config if config is not None else DaemonConfig()
        # mesh passed at construction, straight through to the service:
        # the daemon's scheduling, backoff, and drain are device-layout
        # oblivious — only the fold dispatches change shape
        self.service = FoldService(
            [], self.config.serve, live_port=live_port, mesh=mesh
        )
        self._entries: dict[str, TenantEntry] = {}
        self._rng = random.Random(f"crdt-daemon-{seed}")
        self._cycle = 0
        # the deterministic-clock seam: every wall-time read (uptime,
        # SLO burn window, auto interval) goes through here, so the
        # simulator can inject a counted clock and replay bit-for-bit
        self._clock = clock if clock is not None else time.monotonic
        self._started = self._clock()
        # freshness-wait protocol (docs/strong_reads.md): per-tenant
        # waiters blocked until the tenant's stable prefix covers a
        # target clock; a waiting tenant jumps the cadence queue
        self._waiters: dict[str, list] = {}
        # live freshness-burn samples (clock_t, bad, good) for the
        # wall-clock-aware interval (pruned to burn_window_s)
        self._burn_window: list = []
        # serializes cycles against admit/evict/drain: the fleet mutates
        # BETWEEN cycles, never during one
        self._lock = asyncio.Lock()
        self._drain_requested = asyncio.Event()
        self.state = "running"  # running | draining | drained
        self.degraded = False
        self._consec_cycle_failures = 0
        self.last_cycle_report: dict | None = None
        for i, core in enumerate(tenants):
            self._admit_locked(core, f"t{i}")

    # ------------------------------------------------------------ fleet
    @property
    def cycle(self) -> int:
        return self._cycle

    @property
    def tenant_ids(self) -> list[str]:
        return list(self._entries)

    def entry(self, tid: str) -> TenantEntry | None:
        return self._entries.get(tid)

    def _admission_cost(self) -> int:
        """Per-tenant resident-bytes estimate for the admission gate:
        the warm tier's OBSERVED mean entry size once it has data, the
        configured estimate before that."""
        warm = self.service.warm
        if warm is not None and len(warm):
            return max(1, warm.bytes_held // len(warm))
        return self.config.tenant_cost_bytes

    def _admit_locked(self, core, tid: str) -> TenantEntry:
        if self.state != "running":
            raise AdmissionError(f"daemon is {self.state}")
        if tid in self._entries:
            raise AdmissionError(f"tenant {tid!r} already admitted")
        if len(self._entries) >= self.config.max_tenants:
            raise AdmissionError(
                f"fleet full ({len(self._entries)} tenants)"
            )
        budget = self.config.admission_bytes or self.config.serve.warm_bytes
        projected = (len(self._entries) + 1) * self._admission_cost()
        if projected > budget:
            raise AdmissionError(
                f"byte budget: {len(self._entries) + 1} tenants × "
                f"{self._admission_cost()}B/tenant > {budget}B warm budget"
            )
        entry = TenantEntry(tid, core)
        self._entries[tid] = entry
        trace.add("daemon_admitted", 1)
        return entry

    async def admit(self, core, tid: str | None = None) -> str:
        """Admit an OPEN core as a tenant while running.  Raises
        :class:`AdmissionError` when the fleet or the warm-tier byte
        budget is full — admission is the backpressure surface, never a
        silent drop.  Returns the tenant id."""
        async with self._lock:
            if tid is None:
                tid = f"t{len(self._entries)}"
                while tid in self._entries:
                    tid = f"{tid}x"
            self._admit_locked(core, tid)
        self._publish()
        return tid

    def _fail_waiters(self, tid: str, why: str) -> None:
        """Fail a departed tenant's pending freshness waiters LOUDLY —
        no cycle can ever resolve them, so letting them ride out their
        timeouts against a gone tenant would be a silent hang."""
        pending = self._waiters.pop(tid, None)
        if not pending:
            return
        from ..read.stable import StalenessError

        for _target, fut in pending:
            if not fut.done():
                fut.set_exception(
                    StalenessError(
                        "timeout",
                        f"tenant {tid}: {why} before the watermark "
                        "covered the target",
                    )
                )

    async def evict(self, tid: str, *, checkpoint: bool = True):
        """Remove a tenant while running: waits out any in-flight cycle,
        seals a final warm-open checkpoint (so the next open of that
        tenant is warm), fails its pending freshness waiters loudly,
        and hands the core back to the caller."""
        async with self._lock:
            entry = self._entries.pop(tid, None)
            if entry is None:
                raise KeyError(f"unknown tenant {tid!r}")
            self._fail_waiters(tid, "evicted")
            if checkpoint:
                try:
                    await entry.core.save_checkpoint()
                except Exception:
                    logger.warning(
                        "evict(%s): final checkpoint failed", tid,
                        exc_info=True,
                    )
            trace.add("daemon_evicted", 1)
        self._publish()
        return entry.core

    async def discard(self, tid: str) -> None:
        """Drop a tenant whose core is GONE (crashed process in the
        simulator, caller-closed handle): no checkpoint, no core
        returned.  Unknown tids are ignored — discard is the cleanup
        path and must be safe to repeat.  Pending freshness waiters
        fail loudly, exactly as on evict."""
        async with self._lock:
            if self._entries.pop(tid, None) is not None:
                self._fail_waiters(tid, "discarded")
                trace.add("daemon_evicted", 1)

    # -------------------------------------------------------- scheduling
    def _slo_target(self) -> float:
        """The freshness-SLO target, resolved ONCE per cycle — the spec
        re-reads env vars, which must not run twice per tenant in the
        always-on loop."""
        from ..obs import slo as obs_slo

        return obs_slo.freshness_spec().target

    def _score(self, entry: TenantEntry, target: float):
        """Staleness priority, as a sort KEY: a pending freshness
        waiter is a separate tier above every score (compacting THIS
        tenant publishes the cursor its watermark is waiting on — the
        laggard jumps the queue outright; an additive boost would let
        a large-enough laggard crowd the waiter out of a full batch),
        then SLO-lag pressure, backlog files/bytes, and idle age.  A
        tenant with no status yet (never sampled) sorts first within
        its tier — unknown staleness is assumed worst."""
        waiting = 1 if self._waiters.get(entry.tid) else 0
        status = entry.status()
        if status is None:
            return (waiting, float("inf"))
        lag = float(status["divergence"]["watermark_lag"])
        backlog = status["backlog"]
        idle = self._cycle - max(entry.last_sealed, 0)
        return (
            waiting,
            (lag / max(target, 1.0)) * 16.0
            + float(backlog["files"])
            + float(backlog["bytes"]) / 65536.0
            + idle / max(self.config.max_idle_cycles, 1),
        )

    def _due(self, entry: TenantEntry, target: float) -> bool:
        if self._waiters.get(entry.tid):
            return True  # a freshness waiter is blocked on this tenant
        status = entry.status()
        if status is None or entry.last_sealed < 0:
            return True
        if status["backlog"]["files"] >= self.config.min_backlog_files:
            return True
        if float(status["divergence"]["watermark_lag"]) > target:
            return True
        return (
            self._cycle - entry.last_sealed >= self.config.max_idle_cycles
        )

    # ------------------------------------------------------------ cycles
    async def run_cycle(self) -> dict:
        """One supervised control-plane cycle (module docs).  Returns
        the cycle report: per-tenant outcomes keyed by tid —
        ``sealed`` / ``empty`` / ``error`` / ``polled`` / ``backoff`` /
        ``quarantined`` — plus the breaker and selection summary."""
        async with self._lock:
            if self.state != "running":
                raise RuntimeError(
                    f"daemon is {self.state}; run_cycle refused"
                )
            self._cycle += 1
            trace.add("daemon_cycles", 1)
            with trace.span("daemon.cycle", meta=self._cycle):
                report = await self._cycle_locked()
        self.last_cycle_report = report
        self._publish()
        return report

    async def _cycle_locked(self) -> dict:
        cfg = self.config
        cycle = self._cycle
        report: dict = {
            "cycle": cycle,
            "degraded": self.degraded,
            "selected": [],
            "results": {},
        }

        # ---- state-machine transitions into this cycle
        probes: list[TenantEntry] = []
        for entry in self._entries.values():
            if entry.state == BACKOFF and cycle >= entry.eligible_at:
                entry.state = ACTIVE  # re-probe path
            elif entry.state == QUARANTINED:
                parked = cycle - (entry.quarantined_at or cycle)
                if parked and parked % cfg.quarantine_probe_every == 0:
                    probes.append(entry)

        candidates = [
            e for e in self._entries.values() if e.state == ACTIVE
        ]
        target = self._slo_target()

        if self.degraded:
            # breaker open: shed decrypt/decode — poll only, except the
            # half-open single-tenant probe on its cadence.  The probe
            # pool falls back to backoff/quarantined tenants when no
            # active one is left — a fully-parked degraded fleet must
            # still be able to close the breaker after the outage ends
            if cycle % cfg.breaker_probe_every == 0 and self._entries:
                pool = candidates or list(self._entries.values())
                probe = max(pool, key=lambda e: self._score(e, target))
                trace.add("daemon_probes", 1)
                await self._compact([probe], report, half_open=True)
                candidates = [c for c in candidates if c is not probe]
            await self._poll(candidates, report)
        else:
            due = sorted(
                (e for e in candidates if self._due(e, target)),
                key=lambda e: self._score(e, target), reverse=True,
            )
            selected = due[: max(1, cfg.batch)]
            if probes:
                # one quarantined re-probe per cycle, APPENDED past the
                # batch cap and outside the due filter — the ring's
                # cadence is a guarantee, not a suggestion (and the
                # counter only ticks for probes that actually run)
                selected.append(probes[0])
                trace.add("daemon_probes", 1)
            chosen = {id(e) for e in selected}
            rest = [e for e in candidates if id(e) not in chosen]
            await self._compact(selected, report)
            await self._poll(rest, report)

        # ---- freshness-wait resolution + live SLO burn sample
        await self._resolve_waiters(report)
        self._note_burn(target)

        # ---- gauges + outcome bookkeeping
        counts = {ACTIVE: 0, BACKOFF: 0, QUARANTINED: 0}
        for entry in self._entries.values():
            counts[entry.state] += 1
        trace.gauge("daemon_tenants", len(self._entries))
        trace.gauge("daemon_quarantined", counts[QUARANTINED])
        trace.gauge("daemon_degraded", 1.0 if self.degraded else 0.0)
        report["degraded"] = self.degraded
        report["states"] = counts
        return report

    # -------------------------------------------------- freshness waits
    async def await_stable(self, tid: str, target, *, timeout_s: float = 30.0):
        """The freshness-wait protocol at the control plane: block until
        tenant ``tid``'s stable prefix covers ``target`` (a VClock, e.g.
        the caller's own last-write clock — read-your-writes through a
        daemon-served tenant).  Registering a waiter boosts the tenant
        to the front of the cadence queue, so the scheduler actively
        chases the cursors the waiter needs instead of waiting for
        backlog pressure.  Resolution happens at the end of each cycle;
        raises :class:`~crdt_enc_tpu.read.StalenessError` (``timeout``)
        when ``timeout_s`` of *wall* time elapses first (the daemon
        clock seam), and ``KeyError`` for unknown tenants."""
        from ..read.stable import StalenessError

        entry = self._entries.get(tid)
        if entry is None:
            raise KeyError(f"unknown tenant {tid!r}")
        fut = asyncio.get_running_loop().create_future()
        waiter = (target, fut)
        self._waiters.setdefault(tid, []).append(waiter)
        trace.add("daemon_waiters", 1)
        try:
            return await asyncio.wait_for(fut, timeout=timeout_s)
        except asyncio.TimeoutError:
            raise StalenessError(
                "timeout",
                f"tenant {tid}: watermark did not cover the target "
                f"within {timeout_s}s of daemon cycles",
            ) from None
        finally:
            pending = self._waiters.get(tid, [])
            if waiter in pending:
                pending.remove(waiter)
            if not pending:
                self._waiters.pop(tid, None)

    async def _resolve_waiters(self, report: dict) -> None:
        """End-of-cycle half of :meth:`await_stable`: advance the
        stable prefix of every tenant with pending waiters (knowledge
        is fresh — the cycle just ingested or polled it) and resolve
        the futures whose target the frontier now covers."""
        for tid in list(self._waiters):
            entry = self._entries.get(tid)
            pending = self._waiters.get(tid, [])
            if entry is None or not pending:
                continue
            try:
                view = await entry.core.stable_prefix(refresh=False)
            except Exception as e:
                logger.debug(
                    "waiter advance for %s failed: %r", tid, e
                )
                continue
            for target, fut in list(pending):
                if not fut.done() and view.covers(target):
                    fut.set_result(view)
            report.setdefault("waiters", {})[tid] = len(
                [w for w in pending if not w[1].done()]
            )

    def _note_burn(self, target: float) -> None:
        """One live freshness-burn sample per cycle: the fraction of
        tenants whose watermark lag exceeds the SLO target, window-
        bucketed by the daemon clock — obs/slo.py's burn accounting
        applied to the running fleet instead of sink records."""
        bad = good = 0
        for entry in self._entries.values():
            status = entry.status()
            if status is None:
                continue
            if float(status["divergence"]["watermark_lag"]) > target:
                bad += 1
            else:
                good += 1
        now = self._clock()
        self._burn_window.append((now, bad, good))
        horizon = now - max(self.config.burn_window_s, 1e-9)
        while self._burn_window and self._burn_window[0][0] < horizon:
            self._burn_window.pop(0)

    def next_interval(self) -> float:
        """The pacing for run_forever's next sleep.  Fixed
        ``interval_s`` unless ``interval_auto``; with it, the freshness
        burn rate over the live window drives the interval
        geometrically from ``interval_max_s`` (no burn) down to
        ``interval_min_s`` (burn ≥ 1 — budget is being eaten in real
        time, so laggards holding the watermark back get visited
        sooner).  Published as the ``daemon_interval_s`` gauge either
        way."""
        cfg = self.config
        if not cfg.interval_auto:
            trace.gauge("daemon_interval_s", cfg.interval_s)
            return cfg.interval_s
        from ..obs import slo as obs_slo

        spec = obs_slo.freshness_spec()
        bad = sum(b for _, b, _ in self._burn_window)
        total = bad + sum(g for _, _, g in self._burn_window)
        frac = bad / total if total else 0.0
        burn = min(1.0, frac / spec.budget)
        lo = max(cfg.interval_min_s, 1e-3)
        hi = max(cfg.interval_max_s, lo)
        interval = hi * (lo / hi) ** burn
        trace.gauge("daemon_interval_s", interval)
        return interval

    async def _compact(self, entries, report, *, half_open: bool = False):
        """Run one FoldService cycle over ``entries`` and feed the
        outcomes through the backoff machine; maintains the breaker."""
        if not entries:
            return
        report["selected"] = [e.tid for e in entries]
        results = await self.service.run_cycle([e.core for e in entries])
        any_ok = False
        all_failed = True
        for entry, res in zip(entries, results):
            if res.error is not None:
                self._note_failure(entry, res.error)
                report["results"][entry.tid] = {
                    "outcome": "error", "error": res.error,
                    "state": entry.state, "path": res.path,
                }
                continue
            all_failed = False
            any_ok = any_ok or res.sealed
            self._note_success(entry)
            report["results"][entry.tid] = {
                "outcome": "sealed" if res.sealed else res.path,
                "error": None, "state": entry.state, "path": res.path,
                "latency_s": res.latency_s,
            }
        if all_failed:
            self._consec_cycle_failures += 1
            if (
                not self.degraded
                and self._consec_cycle_failures >= self.config.breaker_after
            ):
                self.degraded = True
                trace.add("daemon_breaker_trips", 1)
                logger.warning(
                    "circuit breaker OPEN after %d consecutive "
                    "whole-cycle failures: degraded mode (seal nothing, "
                    "poll only)", self._consec_cycle_failures,
                )
        else:
            self._consec_cycle_failures = 0
            if self.degraded and (any_ok or half_open):
                self.degraded = False
                logger.info(
                    "circuit breaker CLOSED: half-open probe succeeded"
                )

    async def _poll(self, entries, report) -> None:
        """Stat-only freshness refresh for tenants not compacted this
        cycle: updates each tenant's staleness inputs (and the live
        ``repl_*`` gauges) without any decrypt/decode work — fanned out
        under the service's io_width bound so a large quiet fleet does
        not pay one sequential storage round-trip per tenant.  Poll
        failures ride the same backoff machine — an unreachable remote
        backs its tenant off whether it surfaced in a seal or a poll."""
        entries = [e for e in entries if e.state == ACTIVE]
        if not entries:
            return
        sem = asyncio.Semaphore(max(1, self.config.serve.io_width))

        async def one(entry: TenantEntry):
            async with sem:
                try:
                    await entry.core.replication_status()
                except Exception as e:
                    self._note_failure(entry, repr(e))
                    report["results"][entry.tid] = {
                        "outcome": "error", "error": repr(e),
                        "state": entry.state, "path": "poll",
                    }
                else:
                    report["results"].setdefault(
                        entry.tid,
                        {"outcome": "polled", "error": None,
                         "state": entry.state},
                    )

        with trace.span("daemon.poll", meta=len(entries)):
            await asyncio.gather(*(one(e) for e in entries))

    # ----------------------------------------------------- state machine
    def _note_success(self, entry: TenantEntry) -> None:
        if entry.state == QUARANTINED:
            logger.info("tenant %s left quarantine", entry.tid)
        entry.state = ACTIVE
        entry.failures = 0
        entry.last_error = None
        entry.quarantined_at = None
        entry.last_sealed = self._cycle

    def _note_failure(self, entry: TenantEntry, error: str) -> None:
        entry.failures += 1
        entry.last_error = error
        transient = any(t in error for t in TRANSIENT_ERRORS)
        if entry.state == QUARANTINED:
            # a failed re-probe re-parks; the modulo cadence restarts
            entry.quarantined_at = self._cycle
            return
        if entry.failures >= self.config.quarantine_after:
            entry.state = QUARANTINED
            entry.quarantined_at = self._cycle
            trace.add("daemon_quarantines", 1)
            logger.warning(
                "tenant %s quarantined after %d consecutive failures "
                "(last: %s)", entry.tid, entry.failures, error,
            )
            return
        cfg = self.config
        delay = min(
            cfg.backoff_cap, cfg.backoff_base * 2.0 ** (entry.failures - 1)
        )
        delay *= 1.0 + self._rng.uniform(
            -cfg.backoff_jitter, cfg.backoff_jitter
        )
        entry.state = BACKOFF
        entry.eligible_at = self._cycle + max(1, round(delay))
        trace.add("daemon_backoffs", 1)
        logger.info(
            "tenant %s backing off until cycle %d (%s failure %d: %s)",
            entry.tid, entry.eligible_at,
            "transient" if transient else "unclassified",
            entry.failures, error,
        )

    # ------------------------------------------------------------- drain
    def request_drain(self) -> None:
        """Signal-handler-safe drain request: the forever-loop finishes
        its in-flight cycle and drains.  Idempotent."""
        self._drain_requested.set()

    async def drain(self) -> dict:
        """Graceful shutdown: wait out the in-flight cycle, seal a
        warm-open checkpoint for every tenant, publish the final health,
        stop the live server.  Tenant cores stay open (they are the
        caller's); a second drain is a no-op.  Returns the tenants whose
        final checkpoint failed, as ``{tid: error_repr}`` — a failed
        drain checkpoint only costs that tenant a cold next open, so it
        is reported, not raised."""
        if self.state == "drained":
            return {}
        self.state = "draining"
        # pending freshness waiters cannot resolve once cycles stop:
        # fail them loudly now instead of letting them ride out their
        # timeouts against a drained daemon
        from ..read.stable import StalenessError

        for tid, pending in list(self._waiters.items()):
            for _target, fut in pending:
                if not fut.done():
                    fut.set_exception(
                        StalenessError(
                            "timeout",
                            f"tenant {tid}: daemon drained before the "
                            "watermark covered the target",
                        )
                    )
        self._waiters.clear()
        self._publish()
        errors: dict[str, str] = {}
        async with self._lock:
            with trace.span("daemon.drain", meta=len(self._entries)):
                for entry in self._entries.values():
                    try:
                        await entry.core.save_checkpoint()
                    except Exception as e:
                        errors[entry.tid] = repr(e)
                        logger.warning(
                            "drain: checkpoint for %s failed: %r",
                            entry.tid, e,
                        )
            self.state = "drained"
        self._publish()
        self.service.close()
        return errors

    async def run_forever(self, *, max_cycles: int = 0) -> None:
        """The supervised loop: cycle, pace by ``interval_s``, drain on
        request (or after ``max_cycles`` > 0 — the bounded CI smoke).
        A cycle that raises unexpectedly is logged and the loop keeps
        going — the daemon only stops on drain."""
        try:
            while not self._drain_requested.is_set():
                try:
                    await self.run_cycle()
                except RuntimeError:
                    raise  # drained under us: stop, don't spin
                except Exception:
                    logger.exception(
                        "supervised cycle %d failed; continuing",
                        self._cycle,
                    )
                if max_cycles and self._cycle >= max_cycles:
                    break
                try:
                    await asyncio.wait_for(
                        self._drain_requested.wait(),
                        timeout=self.next_interval(),
                    )
                except asyncio.TimeoutError:
                    pass
        finally:
            await self.drain()

    # ------------------------------------------------------------ health
    def health(self) -> dict:
        """The control-plane section of ``/healthz`` (obs/live.py):
        uptime, cycles, per-state tenant counts, breaker and drain
        state, and the last cycle's selection summary."""
        counts = {ACTIVE: 0, BACKOFF: 0, QUARANTINED: 0}
        for entry in self._entries.values():
            counts[entry.state] += 1
        last = self.last_cycle_report or {}
        return {
            "state": self.state,
            "uptime_s": round(self._clock() - self._started, 3),
            "cycles": self._cycle,
            "tenants": len(self._entries),
            "active": counts[ACTIVE],
            "backoff": counts[BACKOFF],
            "quarantined": counts[QUARANTINED],
            "degraded": self.degraded,
            "consecutive_cycle_failures": self._consec_cycle_failures,
            "waiters": sum(len(v) for v in self._waiters.values()),
            "last_cycle": {
                "cycle": last.get("cycle", 0),
                "selected": len(last.get("selected", [])),
                "errors": sum(
                    1 for r in last.get("results", {}).values()
                    if r.get("error")
                ),
            },
        }

    def _publish(self) -> None:
        """Health → the live endpoint (service-owned, else the process
        default).  Telemetry must never kill the loop it observes."""
        try:
            from ..obs import live as obs_live

            target = (
                self.service.live if self.service.live is not None
                else obs_live.default_server()
            )
            if target is not None:
                target.publish_daemon(self.health())
        except Exception:
            logger.debug("daemon health publication failed", exc_info=True)

"""Sequence CRDT: an ordered list with dense position identifiers.

The external engine's ``list`` capability (the reference is generic over
any ``crdts`` state type, lib.rs:189-197): concurrent inserts at the
same position converge to one total order without coordination.  Logoot
style: every element owns an identifier ``(path, actor, seq)`` where

* ``path`` is a tuple of integer digits in ``[0, BASE)`` — a point in a
  dense order (between any two paths another fits, growing one digit
  level when the gap closes),
* ``(actor, seq)`` breaks ties between concurrent allocations of the
  same path AND makes identifiers globally unique (``seq`` is the
  actor's insert counter, so no identifier is ever minted twice — a
  tombstone can never swallow a later unrelated insert).

Deletes tombstone the identifier (grow-only tombstone set); merge is
union-of-elements minus union-of-tombstones.  Ordering is identifier
order, so apply/merge are order-independent and the canonical encoding
is deterministic — the property tests pin convergence under adversarial
interleavings like every other model here.

The op-log analogue of long sequences (SURVEY.md §2.3): a list's history
chunks and folds like any op stream; the accelerator's columnar paths
decline this type and the core folds per-op on host.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..utils import codec
from .vclock import Actor

BASE = 1 << 31


def path_between(lo: tuple, hi: tuple | None) -> tuple:
    """A digit path strictly between ``lo`` and ``hi`` (``hi=None`` = +∞).

    Walks levels keeping the invariant "out is lo's prefix (0-padded) or
    already diverged below hi"; the first level with a gap > 1 fits a new
    digit.  Terminates because past both lengths the gap is ``BASE``.
    """
    out = []
    i = 0
    while True:
        a = lo[i] if i < len(lo) else 0
        b = hi[i] if hi is not None and i < len(hi) else BASE
        if b - a > 1:
            out.append(a + 1)
            return tuple(out)
        out.append(a)
        i += 1


@dataclass(frozen=True)
class InsOp:
    path: tuple
    actor: Actor
    seq: int
    value: object

    @property
    def ident(self):
        return (self.path, self.actor, self.seq)

    def to_obj(self):
        return [0, list(self.path), self.actor, self.seq, self.value]


@dataclass(frozen=True)
class DelOp:
    path: tuple
    actor: Actor
    seq: int

    @property
    def ident(self):
        return (self.path, self.actor, self.seq)

    def to_obj(self):
        return [1, list(self.path), self.actor, self.seq]


def op_from_obj(obj):
    if isinstance(obj, (InsOp, DelOp)):
        return obj
    kind = obj[0]
    path = tuple(int(d) for d in obj[1])
    actor, seq = bytes(obj[2]), int(obj[3])
    if kind == 0:
        return InsOp(path, actor, seq, obj[4])
    if kind == 1:
        return DelOp(path, actor, seq)
    raise ValueError(f"bad list op kind {kind!r}")


@dataclass
class SeqList:
    elems: dict = field(default_factory=dict)  # ident -> value (visible)
    tombs: set = field(default_factory=set)  # deleted idents
    _seq_seen: dict = field(default_factory=dict)  # actor -> max seq seen

    # -- op derivation (ctx style: derive against current state, apply) ---
    def insert_ctx(self, actor: Actor, index: int, value) -> InsOp:
        """An insert placing ``value`` at ``index`` of the visible list.

        Placement caveat shared with the Logoot family: elements whose
        paths collide (only possible via *concurrent* same-position
        inserts) order by ``(actor, seq)``, and a later insert aimed
        between such twins lands adjacent to the cluster instead of
        inside it — identically on every replica, so convergence and
        determinism hold; only the index intuition bends, and only
        around concurrency.
        """
        order = self._order()
        if not 0 <= index <= len(order):
            raise IndexError(f"insert index {index} out of range")
        lo = order[index - 1][0] if index > 0 else ()
        hi = order[index][0] if index < len(order) else None
        actor = bytes(actor)
        seq = self._seq_seen.get(actor, 0) + 1
        return InsOp(path_between(lo, hi), actor, seq, value)

    def append_ctx(self, actor: Actor, value) -> InsOp:
        return self.insert_ctx(actor, len(self.elems), value)

    def delete_ctx(self, index: int) -> DelOp:
        order = self._order()
        if not 0 <= index < len(order):
            # no negative indexing: a caller's off-by-one would silently
            # tombstone the LAST element, irreversibly, on every replica
            raise IndexError(f"delete index {index} out of range")
        path, actor, seq = order[index]
        return DelOp(path, actor, seq)

    # -- CmRDT -------------------------------------------------------------
    def apply(self, op) -> None:
        op = op_from_obj(op) if isinstance(op, (list, tuple)) else op
        ident = op.ident
        seen = self._seq_seen.get(op.actor, 0)
        if op.seq > seen:
            self._seq_seen[op.actor] = op.seq
        if isinstance(op, InsOp):
            if ident not in self.tombs:
                self.elems[ident] = op.value
        else:
            self.elems.pop(ident, None)
            self.tombs.add(ident)

    # -- CvRDT -------------------------------------------------------------
    def merge(self, other: "SeqList") -> None:
        self.tombs |= other.tombs
        for ident, value in other.elems.items():
            if ident not in self.tombs:
                self.elems[ident] = value
        for ident in [i for i in self.elems if i in self.tombs]:
            del self.elems[ident]
        for actor, seq in other._seq_seen.items():
            if seq > self._seq_seen.get(actor, 0):
                self._seq_seen[actor] = seq

    # -- reads -------------------------------------------------------------
    def _order(self) -> list:
        return sorted(self.elems)

    def read(self) -> list:
        return [self.elems[i] for i in self._order()]

    def __len__(self) -> int:
        return len(self.elems)

    # -- canonical serialization ------------------------------------------
    @staticmethod
    def _ident_obj(ident):
        path, actor, seq = ident
        return [list(path), actor, seq]

    def to_obj(self):
        return [
            [self._ident_obj(i), self.elems[i]] for i in self._order()
        ] + [[self._ident_obj(i)] for i in sorted(self.tombs)]

    @classmethod
    def from_obj(cls, obj) -> "SeqList":
        lst = cls()
        for entry in obj or []:
            ident_obj = entry[0]
            ident = (
                tuple(int(d) for d in ident_obj[0]),
                bytes(ident_obj[1]),
                int(ident_obj[2]),
            )
            seen = lst._seq_seen.get(ident[1], 0)
            if ident[2] > seen:
                lst._seq_seen[ident[1]] = ident[2]
            if len(entry) == 2:
                lst.elems[ident] = entry[1]
            else:
                lst.tombs.add(ident)
        return lst

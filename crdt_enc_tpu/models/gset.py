"""Grow-only set.

The simplest member of the external engine's catalogue (the reference is
generic over any ``crdts`` state type, lib.rs:189-197; the crate ships
``gset`` alongside the types the reference example uses).  An op IS the
member; merge is set union — no clocks, no contexts, removal impossible
by construction.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..utils import codec


@dataclass
class GSet:
    members: set = field(default_factory=set)

    # ops are members themselves (crdts gset::Op::Insert { member })
    def insert_ctx(self, member):
        return member

    def apply(self, op) -> None:
        self.members.add(self._freeze(op))

    def merge(self, other: "GSet") -> None:
        self.members |= other.members

    def contains(self, member) -> bool:
        return self._freeze(member) in self.members

    def read(self) -> list:
        return sorted(self.members, key=codec.pack)

    @staticmethod
    def _freeze(member):
        # msgpack round-trip would thaw bytes-like views; store hashables
        if isinstance(member, (bytearray, memoryview)):
            return bytes(member)
        if isinstance(member, list):
            return tuple(member)
        return member

    def to_obj(self):
        return [m for m in self.read()]

    @classmethod
    def from_obj(cls, obj) -> "GSet":
        s = cls()
        for m in obj or []:
            s.apply(m)
        return s

"""Merkle-DAG register: a register whose write history is a content-
addressed DAG.

The external engine's ``merkle_reg`` (the reference is generic over any
``crdts`` state type, lib.rs:189-197): each write names the hashes of
the writes it supersedes, so the "current" value(s) are the DAG's heads
— nodes no other node claims as a parent.  Concurrent writes coexist as
multiple heads until a later write cites them all.  Content addressing
(SHA3-256 over the canonical node encoding, the same hash family the
storage backends use for file names) makes apply/merge idempotent by
construction: a node IS its bytes.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from ..utils import codec


def node_hash(parents, value) -> bytes:
    return hashlib.sha3_256(
        codec.pack([sorted(bytes(p) for p in parents), value])
    ).digest()


@dataclass(frozen=True)
class MerkleNode:
    parents: tuple  # tuple[bytes, ...], sorted
    value: object

    @property
    def hash(self) -> bytes:
        return node_hash(self.parents, self.value)

    def to_obj(self):
        return [list(self.parents), self.value]

    @classmethod
    def from_obj(cls, obj) -> "MerkleNode":
        parents, value = obj
        return cls(tuple(sorted(bytes(p) for p in parents)), value)


@dataclass
class MerkleReg:
    nodes: dict = field(default_factory=dict)  # hash -> MerkleNode

    def write_ctx(self, value) -> MerkleNode:
        """A write superseding the current heads (cite them as parents)."""
        return MerkleNode(tuple(sorted(self.heads())), value)

    def heads(self) -> list:
        """Hashes of nodes no stored node cites as a parent."""
        cited = {p for n in self.nodes.values() for p in n.parents}
        return sorted(h for h in self.nodes if h not in cited)

    def read(self) -> list:
        """Values at the heads, in canonical order."""
        return [self.nodes[h].value for h in self.heads()]

    def apply(self, op) -> None:
        if isinstance(op, (list, tuple)):
            op = MerkleNode.from_obj(op)
        self.nodes[op.hash] = op

    def merge(self, other: "MerkleReg") -> None:
        self.nodes.update(other.nodes)

    def to_obj(self):
        return [self.nodes[h].to_obj() for h in sorted(self.nodes)]

    @classmethod
    def from_obj(cls, obj) -> "MerkleReg":
        reg = cls()
        for node in obj or []:
            reg.apply(MerkleNode.from_obj(node))
        return reg

"""Observed-remove set (Orswot-style, tombstone-free), dense-semantics.

Replaces the ``crdts`` crate's Orswot (reference usage: the Keys CRDT at
crdt-enc/src/key_cryptor.rs:41, merged at lib.rs:460-466).  The semantics are
*designed for tensorization* (SURVEY.md §7 hard part 1): state is exactly
three planes that map 1:1 onto dense arrays —

* ``clock[r]``      — global per-replica max counter seen (VClock),
* ``entries[e][r]`` — the single latest surviving add-dot counter of member
                      ``e`` from replica ``r`` (0 = none),
* ``deferred[e][r]``— pending remove horizon: a remove observed dots up to
                      this counter that we have not seen yet (kept only while
                      it exceeds ``clock[r]``).

Presence: ``e ∈ set  ⟺  ∃r: entries[e][r] > 0``.

Merge is pure elementwise arithmetic (the TPU kernel in
``crdt_enc_tpu.ops.orset`` runs the same formulas over (E, R) matrices):

* ``clock' = max(clockA, clockB)``
* a dot ``a`` from one side survives iff the other side hasn't seen it
  (``a > other.clock[r]``) or holds the same dot (``a == b``); the merged
  entry is the max surviving dot,
* ``rm' = max(deferredA, deferredB)``; any surviving entry ``≤ rm'`` is
  killed (the remove it predicted has caught up),
* ``deferred'`` keeps only ``rm' > clock'``.

This reproduces observed-remove/add-wins behavior without per-dot tombstone
sets: the global clock is the tombstone (a dot another replica has already
seen but no longer holds is dead on merge).

Causal-delivery contract: per-replica op streams are applied in dot order
(the core's op-file version ordering guarantees this, reference
lib.rs:497-531); cross-replica interleaving is unconstrained.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..utils import codec
from .vclock import Actor, Dot, VClock

Member = object  # any msgpack-able hashable: bytes, int, str, tuple


@dataclass(frozen=True)
class AddOp:
    member: Member
    dot: Dot

    def to_obj(self):
        return [0, self.member, self.dot.to_obj()]


@dataclass(frozen=True)
class RmOp:
    member: Member
    ctx: VClock  # the add-dots this remove observed (per-member read ctx)

    def to_obj(self):
        return [1, self.member, self.ctx.to_obj()]


def op_from_obj(obj):
    kind, member, payload = obj
    if kind == 0:
        return AddOp(member, Dot.from_obj(payload))
    if kind == 1:
        return RmOp(member, VClock.from_obj(payload))
    raise ValueError(f"bad ORSet op kind {kind!r}")


@dataclass
class ORSet:
    clock: VClock = field(default_factory=VClock)
    entries: dict = field(default_factory=dict)  # member -> {actor: counter}
    deferred: dict = field(default_factory=dict)  # member -> {actor: counter}
    # mutation epoch: bumped by every mutating method (and by the
    # accelerator's plane writebacks) so device-resident plane caches can
    # key their validity on it (parallel/accel.py) — a cache entry whose
    # recorded epoch no longer matches has missed a host mutation
    _mut: int = field(default=0, compare=False, repr=False)

    # -- op construction (local replica) -----------------------------------
    def add_ctx(self, actor: Actor, member: Member) -> AddOp:
        return AddOp(member, self.clock.inc(actor))

    def rm_ctx(self, member: Member) -> RmOp:
        """Remove everything currently observed for ``member``."""
        return RmOp(member, VClock(dict(self.entries.get(member, {}))))

    # -- CmRDT apply -------------------------------------------------------
    def apply(self, op) -> None:
        self._mut += 1
        if isinstance(op, (list, tuple)):
            op = op_from_obj(op)
        if isinstance(op, AddOp):
            self._apply_add(op.member, op.dot)
        elif isinstance(op, RmOp):
            self._apply_rm(op.member, op.ctx)
        else:
            raise TypeError(f"bad ORSet op {op!r}")

    def _apply_add(self, member: Member, dot: Dot) -> None:
        r, c = dot.actor, dot.counter
        if c <= self.clock.get(r):
            return  # already seen (duplicate/stale op replay)
        self.clock.counters[r] = c
        if self.deferred.get(member, {}).get(r, 0) >= c:
            # a remove already observed this dot: born dead
            self._normalize_member(member)
            return
        self.entries.setdefault(member, {})[r] = c
        self._normalize_member(member)

    def _apply_rm(self, member: Member, ctx: VClock) -> None:
        entry = self.entries.get(member)
        dfr = None
        for r, c in ctx.counters.items():
            if entry is not None and entry.get(r, 0) <= c:
                entry.pop(r, None)
            if c > self.clock.get(r):
                if dfr is None:
                    dfr = self.deferred.setdefault(member, {})
                if c > dfr.get(r, 0):
                    dfr[r] = c
        self._normalize_member(member)

    # -- CvRDT merge -------------------------------------------------------
    def merge(self, other: "ORSet") -> None:
        self._mut += 1
        members = set(self.entries) | set(other.entries)
        new_entries: dict = {}
        for e in members:
            ea = self.entries.get(e, {})
            eb = other.entries.get(e, {})
            merged: dict = {}
            for r in set(ea) | set(eb):
                a, b = ea.get(r, 0), eb.get(r, 0)
                surv_a = a if (a == b or a > other.clock.get(r)) else 0
                surv_b = b if (a == b or b > self.clock.get(r)) else 0
                c = max(surv_a, surv_b)
                if c:
                    merged[r] = c
            if merged:
                new_entries[e] = merged

        # remove horizons combine by max; they kill any entry they cover
        new_deferred: dict = {}
        for e in set(self.deferred) | set(other.deferred):
            da = self.deferred.get(e, {})
            db = other.deferred.get(e, {})
            merged_rm = {r: max(da.get(r, 0), db.get(r, 0)) for r in set(da) | set(db)}
            if merged_rm:
                new_deferred[e] = merged_rm

        self.clock.merge(other.clock)
        self.entries = new_entries
        self.deferred = new_deferred
        for e in list(members | set(new_deferred)):
            self._normalize_member(e)

    def reset_remove(self, ctx: VClock) -> None:
        """ResetRemove (for causal-Map children): forget every dot and
        horizon the removed context observed — entries, deferred removes,
        and the clock itself all drop state ≤ ctx per actor."""
        self._mut += 1
        for m in list(self.entries):
            entry = self.entries[m]
            for r in [r for r, c in entry.items() if c <= ctx.get(r)]:
                del entry[r]
            if not entry:
                del self.entries[m]
        for m in list(self.deferred):
            dfr = self.deferred[m]
            for r in [r for r, c in dfr.items() if c <= ctx.get(r)]:
                del dfr[r]
            if not dfr:
                del self.deferred[m]
        self.clock.reset_remove(ctx)

    def _normalize_member(self, member: Member) -> None:
        entry = self.entries.get(member)
        dfr = self.deferred.get(member)
        if entry is not None and dfr:
            for r in list(entry):
                if entry[r] <= dfr.get(r, 0):
                    del entry[r]
        if dfr:
            # a horizon the clock has caught up with has fully applied
            for r in list(dfr):
                if dfr[r] <= self.clock.get(r):
                    del dfr[r]
            if not dfr:
                self.deferred.pop(member, None)
        if entry is not None and not entry:
            self.entries.pop(member, None)

    # -- reads -------------------------------------------------------------
    def contains(self, member: Member) -> bool:
        return member in self.entries

    def members(self) -> list:
        return sorted(self.entries, key=lambda m: codec.pack(m))

    # -- canonical serialization ------------------------------------------
    def to_obj(self):
        """Canonical form.  The per-op apply path normalizes lazily (only
        the touched member), so a remove horizon another member's adds have
        retired (``≤ clock``) can linger in ``deferred`` — semantically
        inert, but it would break byte equality against the batched folds,
        which normalize globally.  Serialization is where canonical means
        canonical: inert horizons are filtered here."""
        dfr = {
            m: {r: c for r, c in v.items() if c > self.clock.get(r)}
            for m, v in self.deferred.items()
        }
        return {
            b"c": self.clock.to_obj(),
            b"e": {m: dict(v) for m, v in self.entries.items() if v},
            b"d": {m: v for m, v in dfr.items() if v},
        }

    @classmethod
    def from_obj(cls, obj) -> "ORSet":
        s = cls()
        if obj is None:
            return s
        s.clock = VClock.from_obj(obj.get(b"c"))
        s.entries = {
            m: {bytes(r): int(c) for r, c in v.items()}
            for m, v in (obj.get(b"e") or {}).items()
            if v
        }
        s.deferred = {
            m: {bytes(r): int(c) for r, c in v.items()}
            for m, v in (obj.get(b"d") or {}).items()
            if v
        }
        return s

    def __eq__(self, other) -> bool:
        if not isinstance(other, ORSet):
            return NotImplemented
        return codec.pack(self.to_obj()) == codec.pack(other.to_obj())

"""Single last-writer-wins register.

The one-slot sibling of :mod:`lwwmap` (the external engine's ``lwwreg``;
the reference is generic over any of its state types, lib.rs:189-197).
The ``(timestamp, actor)`` marker totally orders writes; where the crate
*panics* on equal markers with different values, this converges
deterministically with the same value-bytes tie-break the map uses.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .lwwmap import _wins
from .vclock import Actor


@dataclass(frozen=True)
class LWWRegOp:
    ts: int
    actor: Actor
    value: object

    def to_obj(self):
        return [self.ts, self.actor, self.value]

    @classmethod
    def from_obj(cls, obj) -> "LWWRegOp":
        ts, actor, value = obj
        return cls(int(ts), bytes(actor), value)


@dataclass
class LWWReg:
    # [ts, actor, value] of the winning write, or None before any write
    slot: list | None = field(default=None)

    def write(self, ts: int, actor: Actor, value) -> LWWRegOp:
        return LWWRegOp(ts, actor, value)

    def read(self):
        return None if self.slot is None else self.slot[2]

    def apply(self, op) -> None:
        if isinstance(op, (list, tuple)):
            op = LWWRegOp.from_obj(op)
        self._take(op.ts, bytes(op.actor), op.value)

    def merge(self, other: "LWWReg") -> None:
        if other.slot is not None:
            ts, actor, value = other.slot
            self._take(int(ts), bytes(actor), value)

    def _take(self, ts: int, actor: bytes, value) -> None:
        if self.slot is None or _wins(
            ts, actor, value, False,
            int(self.slot[0]), bytes(self.slot[1]), self.slot[2], False,
        ):
            self.slot = [ts, actor, value]

    def to_obj(self):
        return None if self.slot is None else list(self.slot)

    @classmethod
    def from_obj(cls, obj) -> "LWWReg":
        reg = cls()
        if obj is not None:
            reg.slot = [int(obj[0]), bytes(obj[1]), obj[2]]
        return reg

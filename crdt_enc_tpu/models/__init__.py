from .base import Crdt, EmptyCrdt, canonical_bytes
from .counters import GCounter, PNCounter, NEG, POS
from .lwwmap import LWWMap, LWWOp
from .mvreg import MVReg, MVRegOp, ReadCtx
from .orset import AddOp, ORSet, RmOp
from .vclock import Actor, Dot, VClock

# Registry used by state decoders that need to resolve a CRDT type by name.
REGISTRY = {
    b"empty": EmptyCrdt,
    b"gcounter": GCounter,
    b"pncounter": PNCounter,
    b"mvreg": MVReg,
    b"orset": ORSet,
    b"lwwmap": LWWMap,
}

__all__ = [
    "Actor",
    "AddOp",
    "Crdt",
    "Dot",
    "EmptyCrdt",
    "GCounter",
    "LWWMap",
    "LWWOp",
    "MVReg",
    "MVRegOp",
    "NEG",
    "ORSet",
    "POS",
    "PNCounter",
    "ReadCtx",
    "REGISTRY",
    "RmOp",
    "VClock",
    "canonical_bytes",
]

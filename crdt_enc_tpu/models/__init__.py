from .base import Crdt, EmptyCrdt, canonical_bytes
from .counters import GCounter, PNCounter, NEG, POS
from .crdtmap import CrdtMap, RmOp as MapRmOp, UpOp as MapUpOp
from .gset import GSet
from .lwwmap import LWWMap, LWWOp
from .lwwreg import LWWReg, LWWRegOp
from .merkle_reg import MerkleNode, MerkleReg
from .mvreg import MVReg, MVRegOp, ReadCtx
from .orset import AddOp, ORSet, RmOp
from .seqlist import DelOp, InsOp, SeqList
from .vclock import Actor, Dot, VClock

# Registry used by state decoders that need to resolve a CRDT type by name.
REGISTRY = {
    b"empty": EmptyCrdt,
    b"gcounter": GCounter,
    b"pncounter": PNCounter,
    b"mvreg": MVReg,
    b"orset": ORSet,
    b"lwwmap": LWWMap,
    b"gset": GSet,
    b"lwwreg": LWWReg,
    b"merklereg": MerkleReg,
    b"list": SeqList,
    b"map": CrdtMap,
}

__all__ = [
    "Actor",
    "AddOp",
    "Crdt",
    "CrdtMap",
    "DelOp",
    "Dot",
    "EmptyCrdt",
    "GCounter",
    "GSet",
    "InsOp",
    "LWWMap",
    "LWWOp",
    "LWWReg",
    "MapRmOp",
    "MapUpOp",
    "LWWRegOp",
    "MerkleNode",
    "MerkleReg",
    "MVReg",
    "MVRegOp",
    "NEG",
    "ORSet",
    "POS",
    "PNCounter",
    "ReadCtx",
    "REGISTRY",
    "RmOp",
    "SeqList",
    "VClock",
    "canonical_bytes",
]

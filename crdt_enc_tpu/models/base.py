"""CRDT protocol: op-based (CmRDT) apply + state-based (CvRDT) merge.

This package is the host-reference CRDT engine, replacing the reference's
external ``crdts`` crate dependency (SURVEY.md §2 row 14; usage at
/root/reference/crdt-enc/src/lib.rs:14,460-466,533-539).  Semantics here are
the framework's ground truth: the TPU kernels in ``crdt_enc_tpu.ops`` must
produce byte-identical canonical state.

Design rule for every state type: ``to_obj()`` emits only msgpack-able
structures in a *canonical* form (sorted, normalized, no redundant entries),
so ``canonical_bytes()`` is deterministic regardless of op arrival order —
that's what makes "byte-identical TPU result" a meaningful test.
"""

from __future__ import annotations

from typing import Any, Protocol, runtime_checkable

from ..utils import codec


@runtime_checkable
class Crdt(Protocol):
    def apply(self, op: Any) -> None:  # CmRDT
        ...

    def merge(self, other: "Crdt") -> None:  # CvRDT
        ...

    def to_obj(self) -> Any: ...

    @classmethod
    def from_obj(cls, obj: Any) -> "Crdt": ...


def canonical_bytes(state) -> bytes:
    return codec.pack(state.to_obj())


class EmptyCrdt:
    """No-op state type (reference utils/mod.rs:12-35): useful when a Core is
    opened purely for key/metadata management."""

    def apply(self, op) -> None:
        pass

    def merge(self, other) -> None:
        pass

    def to_obj(self):
        return None

    @classmethod
    def from_obj(cls, obj) -> "EmptyCrdt":
        return cls()

    def __eq__(self, other) -> bool:
        return isinstance(other, EmptyCrdt)

"""Last-writer-wins map with per-key registers and delete tombstones.

The ``(timestamp, actor)`` pair totally orders writes (actor bytes break
timestamp ties deterministically); deletes are tombstoned writes so they win
over concurrent older puts and survive merges.  The TPU analogue is a
segment-argmax over packed (ts, actor-rank) keys (``crdt_enc_tpu.ops.lww``).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..utils import codec
from .vclock import Actor


@dataclass(frozen=True)
class LWWOp:
    key: object
    ts: int
    actor: Actor
    value: object  # ignored when tombstone
    tombstone: bool = False

    def to_obj(self):
        return [self.key, self.ts, self.actor, self.value, self.tombstone]

    @classmethod
    def from_obj(cls, obj) -> "LWWOp":
        key, ts, actor, value, tombstone = obj
        return cls(key, int(ts), bytes(actor), value, bool(tombstone))


def _wins(a_ts, a_actor, a_val, a_tomb, b_ts, b_actor, b_val, b_tomb) -> bool:
    """True if write A beats write B.  Total order: ts, then actor bytes,
    then canonical value bytes, then tombstone (delete wins a full tie) —
    every duplicate-write pathology converges deterministically."""
    if a_ts != b_ts:
        return a_ts > b_ts
    if a_actor != b_actor:
        return a_actor > b_actor
    pa, pb = codec.pack(a_val), codec.pack(b_val)
    if pa != pb:
        return pa > pb
    return a_tomb > b_tomb


@dataclass
class LWWMap:
    # key -> [ts, actor, value, tombstone]
    entries: dict = field(default_factory=dict)
    # mutation epoch: bumped by every mutating method (and by the
    # accelerator's writebacks) — same cache-validity law as ORSet._mut
    # (MUT001 enforces it statically); excluded from the semantic
    # __eq__ below
    _mut: int = field(default=0, compare=False, repr=False)

    def put(self, key, ts: int, actor: Actor, value) -> LWWOp:
        return LWWOp(key, ts, actor, value)

    def delete(self, key, ts: int, actor: Actor) -> LWWOp:
        return LWWOp(key, ts, actor, None, tombstone=True)

    def apply(self, op) -> None:
        self._mut += 1
        if isinstance(op, (list, tuple)):
            op = LWWOp.from_obj(op)
        cur = self.entries.get(op.key)
        new = [op.ts, op.actor, None if op.tombstone else op.value, op.tombstone]
        if cur is None or _wins(*new, *cur):
            self.entries[op.key] = new

    def merge(self, other: "LWWMap") -> None:
        self._mut += 1
        for key, theirs in other.entries.items():
            cur = self.entries.get(key)
            if cur is None or _wins(*theirs, *cur):
                self.entries[key] = list(theirs)

    def get(self, key):
        e = self.entries.get(key)
        if e is None or e[3]:
            return None
        return e[2]

    def keys(self) -> list:
        return sorted(
            (k for k, e in self.entries.items() if not e[3]),
            key=lambda k: codec.pack(k),
        )

    def to_obj(self):
        return {
            k: [ts, actor, value, bool(tomb)]
            for k, (ts, actor, value, tomb) in self.entries.items()
        }

    @classmethod
    def from_obj(cls, obj) -> "LWWMap":
        m = cls()
        if obj is None:
            return m
        m.entries = {
            k: [int(ts), bytes(actor), value, bool(tomb)]
            for k, (ts, actor, value, tomb) in obj.items()
        }
        return m

    def __eq__(self, other) -> bool:
        if not isinstance(other, LWWMap):
            return NotImplemented
        return codec.pack(self.to_obj()) == codec.pack(other.to_obj())

"""Multi-value register with causal read/write contexts.

Replaces the ``crdts`` crate's MVReg (reference usage: the Keys CRDT at
crdt-enc/src/key_cryptor.rs:35-52 and the RemoteMeta plugin-blob registers at
lib.rs:745-750).  A write supersedes everything it causally saw; concurrent
writes survive side by side until a later write (or an application-level
tie-break, cf. ``latest_key``) resolves them.

Values are opaque msgpack-able objects (in this framework almost always the
msgpack form of a VersionBytes — versioned opaque blobs, as in the reference).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..utils import codec
from .vclock import Actor, VClock


@dataclass(frozen=True)
class MVRegOp:
    clock: VClock
    value: object


@dataclass
class ReadCtx:
    """A read plus the causal context it was taken under (crdts ReadCtx)."""

    clock: VClock
    values: list

    def derive_write(self, actor: Actor, value) -> MVRegOp:
        """Build a write op that supersedes everything this read saw."""
        clock = self.clock.copy()
        clock.apply(clock.inc(actor))
        return MVRegOp(clock, value)


@dataclass
class MVReg:
    # parallel lists of (clock, value) pairs, none dominated by another
    vals: list = field(default_factory=list)  # list[tuple[VClock, object]]

    def read(self) -> ReadCtx:
        clock = VClock()
        for c, _ in self.vals:
            clock.merge(c)
        return ReadCtx(clock, [v for _, v in self.vals])

    def write_ctx(self, actor: Actor, value) -> MVRegOp:
        return self.read().derive_write(actor, value)

    def apply(self, op: MVRegOp) -> None:
        # Drop pairs the op STRICTLY supersedes; keep the op unless itself
        # strictly superseded.  Equal-clock pairs with distinct values
        # coexist (ordinary ctx-derived writes never produce them — each
        # write carries a fresh dot — but a causal-Map reset can shrink
        # two different writes onto one clock, and preferring one by
        # serialization order would diverge; exact duplicates are deduped
        # by _canonicalize).
        kept = [(c, v) for c, v in self.vals if not op.clock.dominates(c)]
        if not any(c.dominates(op.clock) for c, _ in kept):
            kept.append((op.clock.copy(), op.value))
        self.vals = kept
        self._canonicalize()

    def merge(self, other: "MVReg") -> None:
        mine = [(c, v) for c, v in self.vals if self._survives(c, v, other.vals)]
        theirs = [(c, v) for c, v in other.vals if self._survives(c, v, self.vals)]
        merged = mine + [(c.copy(), v) for c, v in theirs]
        self.vals = merged
        self._canonicalize()

    def reset_remove(self, ctx: VClock) -> None:
        """ResetRemove (for causal-Map children): each surviving value
        forgets the removed context's dots; values whose entire causal
        basis was observed-removed vanish."""
        kept = []
        for c, v in self.vals:
            c.reset_remove(ctx)
            if not c.is_empty():
                kept.append((c, v))
        self.vals = kept
        self._canonicalize()

    @staticmethod
    def _survives(clock: VClock, value, opposing: list) -> bool:
        """A pair survives unless some opposing pair strictly dominates it."""
        for oc, _ in opposing:
            if oc.dominates(clock):
                return False
        return True

    def _canonicalize(self) -> None:
        # dedupe identical (clock, value) pairs, sort by canonical bytes
        seen = {}
        for c, v in self.vals:
            seen[codec.pack([c.to_obj(), v])] = (c, v)
        self.vals = [seen[k] for k in sorted(seen)]

    def is_empty(self) -> bool:
        return not self.vals

    def to_obj(self):
        return [[c.to_obj(), v] for c, v in self.vals]

    @classmethod
    def from_obj(cls, obj) -> "MVReg":
        reg = cls()
        if obj is None:
            return reg
        reg.vals = [(VClock.from_obj(c), v) for c, v in obj]
        reg._canonicalize()
        return reg

    def __eq__(self, other) -> bool:
        if not isinstance(other, MVReg):
            return NotImplemented
        return codec.pack(self.to_obj()) == codec.pack(other.to_obj())

"""G-Counter and PN-Counter.

Replaces the ``crdts`` crate's counters (SURVEY.md §2 row 14).  A G-Counter is
a VClock whose value is the sum of per-actor counters; an increment op is the
actor's next dot and apply is a max (so replayed/duplicated op files are
idempotent).  The TPU analogue is a segment-max over (actor → counter) columns
(``crdt_enc_tpu.ops.counters``).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .vclock import Actor, Dot, VClock

POS, NEG = 0, 1


@dataclass
class GCounter:
    clock: VClock = field(default_factory=VClock)

    def inc(self, actor: Actor, steps: int = 1) -> Dot:
        """Build the op advancing this actor's counter by ``steps``."""
        if steps < 1:
            raise ValueError("steps must be >= 1")
        return Dot(actor, self.clock.get(actor) + steps)

    def apply(self, op: Dot) -> None:
        self.clock.apply(op)

    def merge(self, other: "GCounter") -> None:
        self.clock.merge(other.clock)

    def read(self) -> int:
        return sum(self.clock.counters.values())

    def reset_remove(self, ctx) -> None:
        """ResetRemove (for causal-Map children): forget increments the
        removed context observed."""
        self.clock.reset_remove(ctx)

    def to_obj(self):
        return self.clock.to_obj()

    @classmethod
    def from_obj(cls, obj) -> "GCounter":
        return cls(VClock.from_obj(obj))

    def __eq__(self, other) -> bool:
        return isinstance(other, GCounter) and self.clock == other.clock


@dataclass
class PNCounter:
    """Increment/decrement counter: two G-Counter planes."""

    p: GCounter = field(default_factory=GCounter)
    n: GCounter = field(default_factory=GCounter)

    def inc(self, actor: Actor, steps: int = 1):
        return (POS, self.p.inc(actor, steps))

    def dec(self, actor: Actor, steps: int = 1):
        return (NEG, self.n.inc(actor, steps))

    def apply(self, op) -> None:
        direction, dot = op
        if not isinstance(dot, Dot):
            dot = Dot.from_obj(dot)
        if direction == POS:
            self.p.apply(dot)
        elif direction == NEG:
            self.n.apply(dot)
        else:
            raise ValueError(f"bad PNCounter op direction {direction!r}")

    def merge(self, other: "PNCounter") -> None:
        self.p.merge(other.p)
        self.n.merge(other.n)

    def read(self) -> int:
        return self.p.read() - self.n.read()

    def reset_remove(self, ctx) -> None:
        """ResetRemove (for causal-Map children): both planes forget the
        removed context."""
        self.p.reset_remove(ctx)
        self.n.reset_remove(ctx)

    def to_obj(self):
        return [self.p.to_obj(), self.n.to_obj()]

    @classmethod
    def from_obj(cls, obj) -> "PNCounter":
        p, n = obj
        return cls(GCounter.from_obj(p), GCounter.from_obj(n))

    def __eq__(self, other) -> bool:
        return isinstance(other, PNCounter) and self.p == other.p and self.n == other.n

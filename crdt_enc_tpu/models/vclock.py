"""Vector clocks and dots — the causality substrate for every CRDT here.

Replaces the ``crdts`` crate's VClock/Dot (SURVEY.md §2 row 14).  Actors are
16-byte UUIDs (bytes).  A ``Dot`` is one event ``(actor, counter)``; a
``VClock`` summarizes a causal history as the per-actor max counter.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator

Actor = bytes  # 16-byte UUID


@dataclass(frozen=True, order=True)
class Dot:
    actor: Actor
    counter: int

    def to_obj(self):
        return [self.actor, self.counter]

    @classmethod
    def from_obj(cls, obj) -> "Dot":
        actor, counter = obj
        return cls(bytes(actor), int(counter))


@dataclass
class VClock:
    counters: dict[Actor, int] = field(default_factory=dict)

    def get(self, actor: Actor) -> int:
        return self.counters.get(actor, 0)

    def inc(self, actor: Actor) -> Dot:
        """The next dot this actor would produce (does not mutate — apply the
        returned dot to commit it, mirroring the crdts inc/apply protocol)."""
        return Dot(actor, self.get(actor) + 1)

    def apply(self, dot: Dot) -> None:
        if dot.counter > self.get(dot.actor):
            self.counters[dot.actor] = dot.counter

    def merge(self, other: "VClock") -> None:
        for a, c in other.counters.items():
            if c > self.get(a):
                self.counters[a] = c

    def contains(self, dot: Dot) -> bool:
        """Has this history seen the event?  (counter ≤ clock[actor])"""
        return dot.counter <= self.get(dot.actor)

    def dominates(self, other: "VClock") -> bool:
        """self ≥ other pointwise and self ≠ other."""
        return self.descends(other) and self.counters != other.counters

    def descends(self, other: "VClock") -> bool:
        """self ≥ other pointwise (other's history ⊆ ours)."""
        return all(self.get(a) >= c for a, c in other.counters.items())

    def concurrent(self, other: "VClock") -> bool:
        return not self.descends(other) and not other.descends(self)

    def actors(self) -> Iterator[Actor]:
        return iter(self.counters)

    def copy(self) -> "VClock":
        return VClock(dict(self.counters))

    def is_empty(self) -> bool:
        return not self.counters

    def reset_remove(self, ctx: "VClock") -> None:
        """Forget every event the removed context ``ctx`` observed: drop
        per-actor counters ≤ ctx's (the ResetRemove protocol the causal
        Map applies to its children — crdt_enc_tpu/models/crdtmap.py)."""
        for a in [a for a, c in self.counters.items() if c <= ctx.get(a)]:
            del self.counters[a]

    # canonical form: map actor → counter, zero entries dropped
    def to_obj(self):
        return {a: c for a, c in self.counters.items() if c > 0}

    @classmethod
    def from_obj(cls, obj) -> "VClock":
        if obj is None:
            return cls()
        return cls({bytes(a): int(c) for a, c in obj.items() if int(c) > 0})

    def __eq__(self, other) -> bool:
        if not isinstance(other, VClock):
            return NotImplemented
        return {a: c for a, c in self.counters.items() if c} == {
            a: c for a, c in other.counters.items() if c
        }

"""Causal reset-remove map: keys to nested CRDT values.

The external engine's ``map`` capability (the reference is generic over
any ``crdts`` state type, lib.rs:189-197): a map whose values are
themselves CRDTs, where removing a key deletes exactly the causal
history the remover had *observed* — updates concurrent with the remove
survive (observed-remove, the same add-wins discipline as the ORSet),
and the nested value forgets only the removed context
(``reset_remove``, implemented by every causal child type here).

Dot discipline (mirrors the crate's ctx protocol): ONE dot per update
authorizes both the map entry (the key's "birth" dots) and the child
mutation — the child op builder receives that dot, so map-level replay
protection and removal cover the child coherently.

Structure parallels the tombstone-free ORSet (models/orset.py): per-key
birth dots as dense per-actor maxima, deferred remove horizons for
contexts beyond the local clock, one global clock.  The CvRDT merge uses
the same clock-filter survivor rule; CmRDT/CvRDT agreement is pinned by
the property tests against oracle-folded histories.

Child types must provide ``apply``, ``merge``, ``reset_remove``,
``to_obj``/``from_obj`` and an op decoder — see ``CHILD_TYPES``.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..utils import codec
from .counters import GCounter, PNCounter
from .mvreg import MVReg, MVRegOp
from .orset import ORSet
from .orset import op_from_obj as orset_op_from_obj
from .vclock import Actor, Dot, VClock


def _pn_op_from_obj(obj):
    return (int(obj[0]), Dot.from_obj(obj[1]))


def _pn_op_to_obj(op):
    return [op[0], op[1].to_obj()]


# child registry: name -> (type, op_from_obj, op_to_obj)
CHILD_TYPES = {
    b"orset": (ORSet, orset_op_from_obj, lambda op: op.to_obj()),
    b"mvreg": (
        MVReg,
        lambda obj: MVRegOp(VClock.from_obj(obj[0]), obj[1]),
        lambda op: [op.clock.to_obj(), op.value],
    ),
    b"gcounter": (GCounter, Dot.from_obj, lambda op: op.to_obj()),
    b"pncounter": (PNCounter, _pn_op_from_obj, _pn_op_to_obj),
}


@dataclass(frozen=True)
class UpOp:
    """One update: the dot births the key and authorizes ``child_op``."""

    dot: Dot
    key: object
    child_op: object

    def to_obj(self, child_op_to_obj):
        return [0, self.dot.to_obj(), self.key, child_op_to_obj(self.child_op)]


@dataclass(frozen=True)
class RmOp:
    """Observed-remove of ``keys`` under the read context ``ctx``."""

    ctx: VClock
    keys: tuple

    def to_obj(self, _child_op_to_obj=None):
        return [1, self.ctx.to_obj(), list(self.keys)]


@dataclass
class CrdtMap:
    """``CrdtMap(child=b"orset")`` — the child type is fixed per map."""

    child: bytes = b"orset"
    clock: VClock = field(default_factory=VClock)
    # key -> {actor: max birth counter}
    births: dict = field(default_factory=dict)
    # key -> child CRDT state
    vals: dict = field(default_factory=dict)
    # key -> {actor: remove horizon beyond the clock}
    deferred: dict = field(default_factory=dict)

    def __post_init__(self):
        if self.child not in CHILD_TYPES:
            raise ValueError(f"unknown child CRDT type {self.child!r}")

    def _child_type(self):
        return CHILD_TYPES[self.child]

    # -- op derivation -----------------------------------------------------
    def update_ctx(self, actor: Actor, key, build_child_op) -> UpOp:
        """Derive an update: ``build_child_op(child_state, dot)`` returns
        the child op the shared dot authorizes (the child it receives is
        the current value or a fresh empty one — never mutated here)."""
        dot = self.clock.inc(actor)
        cls = self._child_type()[0]
        child = self.vals.get(key)
        child = child if child is not None else cls()
        return UpOp(dot, key, build_child_op(child, dot))

    def rm_ctx(self, *keys) -> RmOp:
        """Remove keys as observed: the context is the keys' birth dots
        (everything this replica has seen of them)."""
        ctx = VClock()
        for key in keys:
            for a, c in self.births.get(key, {}).items():
                if c > ctx.get(a):
                    ctx.counters[a] = c
        return RmOp(ctx, tuple(keys))

    # -- CmRDT -------------------------------------------------------------
    def apply(self, op) -> None:
        if isinstance(op, (list, tuple)):
            op = self.op_from_obj(op)
        if isinstance(op, UpOp):
            self._apply_up(op)
        elif isinstance(op, RmOp):
            self._apply_rm(op)
        else:
            raise TypeError(f"bad CrdtMap op {op!r}")

    def _apply_up(self, op: UpOp) -> None:
        if self.clock.contains(op.dot):
            return  # replay
        # a deferred horizon that observed this dot kills it on arrival
        if op.dot.counter <= self.deferred.get(op.key, {}).get(op.dot.actor, 0):
            self.clock.apply(op.dot)
            self._normalize_key(op.key)
            return
        birth = self.births.setdefault(op.key, {})
        if op.dot.counter > birth.get(op.dot.actor, 0):
            birth[op.dot.actor] = op.dot.counter
        cls = self._child_type()[0]
        child = self.vals.get(op.key)
        if child is None:
            child = self.vals[op.key] = cls()
        child.apply(op.child_op)
        self.clock.apply(op.dot)
        self._normalize_key(op.key)

    def _apply_rm(self, op: RmOp) -> None:
        for key in op.keys:
            birth = self.births.get(key)
            if birth is not None:
                for a in [
                    a for a, c in birth.items() if c <= op.ctx.get(a)
                ]:
                    del birth[a]
                child = self.vals.get(key)
                if child is not None:
                    child.reset_remove(op.ctx)
                if not birth:
                    self.births.pop(key, None)
                    self.vals.pop(key, None)
            # horizons beyond the clock defer (out-of-order cross-actor
            # delivery: the remove observed dots we have not seen yet)
            for a, c in op.ctx.counters.items():
                if c > self.clock.get(a):
                    dfr = self.deferred.setdefault(key, {})
                    if c > dfr.get(a, 0):
                        dfr[a] = c
            self._normalize_key(key)

    def _normalize_key(self, key) -> None:
        dfr = self.deferred.get(key)
        if dfr:
            for a in [a for a, c in dfr.items() if c <= self.clock.get(a)]:
                del dfr[a]
            if not dfr:
                del self.deferred[key]

    # -- CvRDT -------------------------------------------------------------
    #
    # The survivor rule everywhere below relies on global dot uniqueness:
    # a dot (actor, counter) names ONE map update, which targeted ONE key
    # — so "dot covered by the other side's MAP clock, yet absent from
    # the other side's state" can only mean observed-removed.  Child
    # state therefore merges against the MAP clocks, not the children's
    # own clocks (a remover's child forgot the removed dots via
    # reset_remove, so its own clock cannot testify about them).
    def merge(self, other: "CrdtMap") -> None:
        if self.child != other.child:
            raise ValueError("cannot merge maps with different child types")
        keys = set(self.births) | set(other.births)
        cls = self._child_type()[0]
        new_births: dict = {}
        new_vals: dict = {}
        for key in keys:
            ba = self.births.get(key, {})
            bb = other.births.get(key, {})
            # each side's removal knowledge for this key = its map clock
            # extended by its deferred horizon (a remove OBSERVED those
            # dots even when the clock has not caught up to them yet);
            # copy only when a horizon exists — the common case reuses
            # the clocks as-is
            ca_eff, cb_eff = self.clock, other.clock
            dfr = self.deferred.get(key)
            if dfr:
                ca_eff = ca_eff.copy()
                for a, c in dfr.items():
                    if c > ca_eff.get(a):
                        ca_eff.counters[a] = c
            dfr = other.deferred.get(key)
            if dfr:
                cb_eff = cb_eff.copy()
                for a, c in dfr.items():
                    if c > cb_eff.get(a):
                        cb_eff.counters[a] = c
            merged: dict = {}
            for a in set(ba) | set(bb):
                c = self._surv2(
                    ba.get(a, 0), bb.get(a, 0),
                    ca_eff.get(a), cb_eff.get(a),
                )
                if c:
                    merged[a] = c
            if not merged:
                continue
            va = self.vals.get(key)
            vb = other.vals.get(key)
            new_births[key] = merged
            new_vals[key] = self._merge_child_ctx(
                va if va is not None else cls(),
                vb if vb is not None else cls(),
                ca_eff, cb_eff,
            )

        # deferred horizons union by max
        for key, dfr in other.deferred.items():
            mine = self.deferred.setdefault(key, {})
            for a, c in dfr.items():
                if c > mine.get(a, 0):
                    mine[a] = c

        self.clock.merge(other.clock)
        self.births = new_births
        self.vals = new_vals
        # retire satisfied horizons; apply surviving ones to merged state
        for key in list(self.deferred):
            dfr = self.deferred[key]
            ctx = VClock({a: c for a, c in dfr.items()})
            birth = self.births.get(key)
            if birth is not None:
                for a in [a for a, c in birth.items() if c <= ctx.get(a)]:
                    del birth[a]
                child = self.vals.get(key)
                if child is not None:
                    child.reset_remove(ctx)
                if not birth:
                    self.births.pop(key, None)
                    self.vals.pop(key, None)
            self._normalize_key(key)

    @staticmethod
    def _surv2(xa: int, xb: int, ca_r: int, cb_r: int) -> int:
        """Per-actor survivor max: a side's value stands if both agree or
        it is beyond the other side's map clock (else observed-removed)."""
        surv_a = xa if (xa == xb or xa > cb_r) else 0
        surv_b = xb if (xa == xb or xb > ca_r) else 0
        return max(surv_a, surv_b)

    def _merge_child_ctx(self, va, vb, ca: VClock, cb: VClock):
        """Merge two child states under the MAP clocks (see merge())."""
        if self.child == b"orset":
            return self._merge_orset_ctx(va, vb, ca, cb)
        if self.child == b"mvreg":
            return self._merge_mvreg_ctx(va, vb, ca, cb)
        if self.child == b"gcounter":
            out = GCounter()
            out.clock = self._merge_clock_ctx(va.clock, vb.clock, ca, cb)
            return out
        if self.child == b"pncounter":
            out = PNCounter()
            out.p.clock = self._merge_clock_ctx(va.p.clock, vb.p.clock, ca, cb)
            out.n.clock = self._merge_clock_ctx(va.n.clock, vb.n.clock, ca, cb)
            return out
        raise ValueError(f"unknown child CRDT type {self.child!r}")

    @classmethod
    def _merge_clock_ctx(cls, a: VClock, b: VClock, ca: VClock, cb: VClock) -> VClock:
        out = VClock()
        for r in set(a.counters) | set(b.counters):
            c = cls._surv2(a.get(r), b.get(r), ca.get(r), cb.get(r))
            if c:
                out.counters[r] = c
        return out

    @classmethod
    def _merge_orset_ctx(cls, va: ORSet, vb: ORSet, ca: VClock, cb: VClock) -> ORSet:
        out = ORSet()
        for m in set(va.entries) | set(vb.entries):
            ea, eb = va.entries.get(m, {}), vb.entries.get(m, {})
            merged = {}
            for r in set(ea) | set(eb):
                c = cls._surv2(ea.get(r, 0), eb.get(r, 0), ca.get(r), cb.get(r))
                if c:
                    merged[r] = c
            if merged:
                out.entries[m] = merged
        # remove horizons union by max…
        for src in (va.deferred, vb.deferred):
            for m, d in src.items():
                slot = out.deferred.setdefault(m, {})
                for r, c in d.items():
                    if c > slot.get(r, 0):
                        slot[r] = c
        out.clock = cls._merge_clock_ctx(va.clock, vb.clock, ca, cb)
        for m in list(set(out.entries) | set(out.deferred)):
            out._normalize_member(m)
        # …then retire any the merged MAP knowledge covers: a dot ≤ both
        # effective clocks can never re-enter this child (the map-level
        # survivor filter and replay gate both block it), and the fold
        # side retired the same horizons through the child clock the
        # map-level reset has since forgotten
        mapk = ca.copy()
        mapk.merge(cb)
        for m in list(out.deferred):
            d = out.deferred[m]
            for r in [r for r, c in d.items() if c <= mapk.get(r)]:
                del d[r]
            if not d:
                del out.deferred[m]
        return out

    @classmethod
    def _merge_mvreg_ctx(cls, va: MVReg, vb: MVReg, ca: VClock, cb: VClock) -> MVReg:
        def survivors(mine: MVReg, theirs: MVReg, their_map_clock: VClock):
            out = []
            for c, v in mine.vals:
                if any(c == oc for oc, _ in theirs.vals):
                    out.append((c.copy(), v))
                    continue
                dominated = any(oc.dominates(c) for oc, _ in theirs.vals)
                if not dominated and not their_map_clock.descends(c):
                    out.append((c.copy(), v))
            return out

        out = MVReg()
        out.vals = survivors(va, vb, cb) + survivors(vb, va, ca)
        out._canonicalize()
        return out

    # -- reads -------------------------------------------------------------
    def get(self, key):
        return self.vals.get(key)

    def keys(self) -> list:
        return sorted(self.births, key=codec.pack)

    def contains(self, key) -> bool:
        return key in self.births

    # -- wire --------------------------------------------------------------
    def op_to_obj(self, op):
        return op.to_obj(self._child_type()[2])

    def op_from_obj(self, obj):
        if isinstance(obj, (UpOp, RmOp)):
            return obj
        kind = obj[0]
        if kind == 0:
            return UpOp(
                Dot.from_obj(obj[1]), self._thaw_key(obj[2]),
                self._child_type()[1](obj[3]),
            )
        if kind == 1:
            return RmOp(
                VClock.from_obj(obj[1]),
                tuple(self._thaw_key(k) for k in obj[2]),
            )
        raise ValueError(f"bad CrdtMap op kind {kind!r}")

    @staticmethod
    def _thaw_key(key):
        if isinstance(key, (bytearray, memoryview)):
            return bytes(key)
        if isinstance(key, list):
            return tuple(key)
        return key

    def to_obj(self):
        keys = self.keys()
        cls = self._child_type()[0]
        return [
            self.child,
            self.clock.to_obj(),
            [
                [
                    k,
                    {a: c for a, c in sorted(self.births[k].items())},
                    self.vals[k].to_obj() if k in self.vals else cls().to_obj(),
                ]
                for k in keys
            ],
            [
                [k, {a: c for a, c in sorted(d.items())}]
                for k, d in sorted(
                    self.deferred.items(), key=lambda kv: codec.pack(kv[0])
                )
            ],
        ]

    @classmethod
    def from_obj(cls, obj) -> "CrdtMap":
        child, clock, entries, deferred = obj
        m = cls(child=bytes(child))
        m.clock = VClock.from_obj(clock)
        ctype = m._child_type()[0]
        for k, birth, val in entries:
            k = cls._thaw_key(k)
            m.births[k] = {bytes(a): int(c) for a, c in birth.items()}
            m.vals[k] = ctype.from_obj(val)
        for k, d in deferred:
            m.deferred[cls._thaw_key(k)] = {
                bytes(a): int(c) for a, c in d.items()
            }
        return m

"""Causal reset-remove map: keys to nested CRDT values.

The external engine's ``map`` capability (the reference is generic over
any ``crdts`` state type, lib.rs:189-197): a map whose values are
themselves CRDTs, where removing a key deletes exactly the causal
history the remover had *observed* — updates concurrent with the remove
survive (observed-remove, the same add-wins discipline as the ORSet),
and the nested value forgets only the removed context
(``reset_remove``).

Dot discipline (mirrors the crate's ctx protocol): ONE dot per update
authorizes both the map entry (the key's "birth" dots) and the child
mutation — the child op builder receives that dot, so map-level replay
protection and removal cover the child coherently.  See ``CHILD_TYPES``
for why the ORSet is the one child this stays coherent for.

Structure parallels the tombstone-free ORSet (models/orset.py): per-key
birth dots as dense per-actor maxima, one global clock — but removes
whose context cites unseen dots defer as WHOLE ops, not per-actor
horizons, and a child's remove-horizons retire against the MAP clock.
Both rules exist because the transport is per-actor FIFO, *not* causal:
each was driven by a concrete divergence found under true-concurrency
fuzzing (ops derived from divergent replicas, gossiped out of causal
order) — the oracle-based law tests alone cannot reach those states.
CmRDT/CvRDT agreement, adversarial interleavings, and the
true-concurrency class are all pinned in tests/test_crdtmap.py.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..utils import codec
from .orset import ORSet
from .orset import op_from_obj as orset_op_from_obj
from .vclock import Actor, Dot, VClock


# child registry: name -> (type, op_from_obj, op_to_obj)
#
# The ORSet is the one child whose dot discipline is coherent under the
# map (the crate's canonical Orswot-in-map usage): a child add's dot IS
# the map dot, so map-level replay gates, resets, and the merge's
# clock-coverage arguments all see one consistent dot space.  Two
# families are deliberately absent, each verified non-convergent by
# fuzzing before exclusion:
#
# * MVReg — its unit of state is a (context-clock, value) pair; a
#   key-remove's reset shrinks pair clocks, two distinct writes can
#   collapse onto one clock, and no merge rule can then tell their
#   histories apart (re-merges resurrect dead dots).  The external
#   crate's MVReg-in-map shares these corners under the non-causal
#   delivery this framework's file-sync transport provides.
# * Counters — shared map dots corrupt counts (max-dot ≠ op count when
#   an actor alternates inc/dec), and child-local dots break the shared
#   dot space the reset rules need.
#
# A register- or counter-per-key is served by LWWMap or separate Cores.
CHILD_TYPES = {
    b"orset": (ORSet, orset_op_from_obj, lambda op: op.to_obj()),
}


@dataclass(frozen=True)
class UpOp:
    """One update: the dot births the key and authorizes ``child_op``."""

    dot: Dot
    key: object
    child_op: object

    def to_obj(self, child_op_to_obj):
        return [0, self.dot.to_obj(), self.key, child_op_to_obj(self.child_op)]


@dataclass(frozen=True)
class RmOp:
    """Observed-remove of ``keys`` under the read context ``ctx``."""

    ctx: VClock
    keys: tuple

    def to_obj(self, _child_op_to_obj=None):
        return [1, self.ctx.to_obj(), list(self.keys)]


@dataclass
class CrdtMap:
    """``CrdtMap(child=b"orset")`` — the child type is fixed per map."""

    child: bytes = b"orset"
    clock: VClock = field(default_factory=VClock)
    # key -> {actor: max birth counter}
    births: dict = field(default_factory=dict)
    # key -> child CRDT state
    vals: dict = field(default_factory=dict)
    # pending whole removes whose context cites dots beyond the clock:
    # canonical-ctx-bytes -> (VClock, set of keys).  Deferring the WHOLE
    # op (the crdts-crate discipline) — not per-actor horizons — is what
    # keeps non-causal delivery convergent: a remove fires only once
    # every update it observed has arrived, so the updates' child
    # sub-ops (e.g. a child remove citing an actor the remover never
    # saw) are never lost to suppression.
    deferred: dict = field(default_factory=dict)
    # mutation epoch: bumped by every mutating method (and by the
    # accelerator's fold writebacks, ops/map_columnar.py) so caches and
    # checkpoint stashes can key their validity on it — same law as
    # ORSet._mut (MUT001 enforces it statically)
    _mut: int = field(default=0, compare=False, repr=False)

    def __post_init__(self):
        if self.child not in CHILD_TYPES:
            raise ValueError(f"unknown child CRDT type {self.child!r}")

    def _child_type(self):
        return CHILD_TYPES[self.child]

    # -- op derivation -----------------------------------------------------
    def update_ctx(self, actor: Actor, key, build_child_op) -> UpOp:
        """Derive an update: ``build_child_op(child_state, dot)`` returns
        the child op the shared dot authorizes (the child it receives is
        the current value or a fresh empty one — never mutated here)."""
        dot = self.clock.inc(actor)
        cls = self._child_type()[0]
        child = self.vals.get(key)
        child = child if child is not None else cls()
        return UpOp(dot, key, build_child_op(child, dot))

    def rm_ctx(self, *keys) -> RmOp:
        """Remove keys as observed: the context is the keys' birth dots
        (everything this replica has seen of them)."""
        ctx = VClock()
        for key in keys:
            for a, c in self.births.get(key, {}).items():
                if c > ctx.get(a):
                    ctx.counters[a] = c
        return RmOp(ctx, tuple(keys))

    # -- CmRDT -------------------------------------------------------------
    def apply(self, op) -> None:
        self._mut += 1
        if isinstance(op, (list, tuple)):
            op = self.op_from_obj(op)
        if isinstance(op, UpOp):
            self._apply_up(op)
        elif isinstance(op, RmOp):
            self._apply_rm(op)
        else:
            raise TypeError(f"bad CrdtMap op {op!r}")

    def _apply_up(self, op: UpOp) -> None:
        if self.clock.contains(op.dot):
            return  # replay
        birth = self.births.setdefault(op.key, {})
        if op.dot.counter > birth.get(op.dot.actor, 0):
            birth[op.dot.actor] = op.dot.counter
        cls = self._child_type()[0]
        child = self.vals.get(op.key)
        if child is None:
            child = self.vals[op.key] = cls()
        child.apply(op.child_op)
        self.clock.apply(op.dot)
        # retire child remove-horizons the MAP clock covers: child dots
        # are key-bound, so a cited dot ≤ the map clock either reached
        # this child incarnation (its own normalize handles it) or
        # belonged to a previous incarnation a key-remove consumed —
        # either way it can never arrive again (per-actor FIFO + replay
        # gate), and keeping it would diverge from replicas that saw the
        # dot before the key died
        self._retire_child_horizons(child)
        self._flush_deferred()

    def _retire_child_horizons(self, child) -> None:
        dfr = getattr(child, "deferred", None)
        if not dfr:
            return
        clock = self.clock
        for m in list(dfr):
            d = dfr[m]
            for a in [a for a, c in d.items() if c <= clock.get(a)]:
                del d[a]
            if not d:
                del dfr[m]

    def _apply_rm(self, op: RmOp) -> None:
        if self.clock.descends(op.ctx):
            self._rm_now(op.ctx, op.keys)
        else:
            self._defer(op.ctx, op.keys)

    def _rm_now(self, ctx: VClock, keys) -> None:
        for key in keys:
            birth = self.births.get(key)
            child = self.vals.get(key)
            if birth is None and child is None:
                continue
            if birth is not None:
                for a in [a for a, c in birth.items() if c <= ctx.get(a)]:
                    del birth[a]
            if child is not None:
                child.reset_remove(ctx)
            if not birth:
                self.births.pop(key, None)
                # the child may hold RESIDUE the key's death must not
                # erase: remove horizons citing dots this replica has not
                # seen (delivery is per-actor FIFO, not causal — an
                # arriving update's child sub-ops can reference actors
                # the key-remover never saw).  Without the residue,
                # replicas that got the remove first would resurrect
                # state that replicas who saw the update first killed.
                if child is not None and not self._child_residue(child):
                    self.vals.pop(key, None)

    def _child_residue(self, child) -> bool:
        return child.to_obj() != self._child_type()[0]().to_obj()

    def _defer(self, ctx: VClock, keys) -> None:
        tag = codec.pack(ctx.to_obj())
        slot = self.deferred.get(tag)
        if slot is None:
            self.deferred[tag] = (ctx.copy(), set(keys))
        else:
            slot[1].update(keys)

    def _flush_deferred(self) -> None:
        """Fire every pending remove whose cited history has now fully
        arrived (called after each clock advance and after merges)."""
        if not self.deferred:
            return
        for tag in [
            t for t, (ctx, _) in self.deferred.items()
            if self.clock.descends(ctx)
        ]:
            ctx, keys = self.deferred.pop(tag)
            self._rm_now(ctx, keys)

    # -- CvRDT -------------------------------------------------------------
    #
    # The survivor rule everywhere below relies on global dot uniqueness:
    # a dot (actor, counter) names ONE map update, which targeted ONE key
    # — so "dot covered by the other side's MAP clock, yet absent from
    # the other side's state" can only mean observed-removed.  Child
    # state therefore merges against the MAP clocks, not the children's
    # own clocks (a remover's child forgot the removed dots via
    # reset_remove, so its own clock cannot testify about them).
    def merge(self, other: "CrdtMap") -> None:
        if self.child != other.child:
            raise ValueError("cannot merge maps with different child types")
        self._mut += 1
        keys = (
            set(self.births) | set(other.births)
            | set(self.vals) | set(other.vals)  # residue-only keys too
        )
        cls = self._child_type()[0]
        new_births: dict = {}
        new_vals: dict = {}
        for key in keys:
            ba = self.births.get(key, {})
            bb = other.births.get(key, {})
            merged: dict = {}
            for a in set(ba) | set(bb):
                c = self._surv2(
                    ba.get(a, 0), bb.get(a, 0),
                    self.clock.get(a), other.clock.get(a),
                )
                if c:
                    merged[a] = c
            va = self.vals.get(key)
            vb = other.vals.get(key)
            child = self._merge_child_ctx(
                va if va is not None else cls(),
                vb if vb is not None else cls(),
                self.clock, other.clock,
            )
            if merged:
                new_births[key] = merged
                new_vals[key] = child
            elif self._child_residue(child):
                new_vals[key] = child  # dead key, live residue

        # pending removes union (keys union per identical context)
        for tag, (ctx, rm_keys) in other.deferred.items():
            slot = self.deferred.get(tag)
            if slot is None:
                self.deferred[tag] = (ctx.copy(), set(rm_keys))
            else:
                slot[1].update(rm_keys)

        self.clock.merge(other.clock)
        self.births = new_births
        self.vals = new_vals
        # pending removes whose cited history is now complete fire on the
        # merged state
        self._flush_deferred()

    @staticmethod
    def _surv2(xa: int, xb: int, ca_r: int, cb_r: int) -> int:
        """Per-actor survivor max: a side's value stands if both agree or
        it is beyond the other side's map clock (else observed-removed)."""
        surv_a = xa if (xa == xb or xa > cb_r) else 0
        surv_b = xb if (xa == xb or xb > ca_r) else 0
        return max(surv_a, surv_b)

    def _merge_child_ctx(self, va, vb, ca: VClock, cb: VClock):
        """Merge two child states under the MAP clocks (see merge())."""
        if self.child == b"orset":
            return self._merge_orset_ctx(va, vb, ca, cb)
        raise ValueError(f"unknown child CRDT type {self.child!r}")

    @classmethod
    def _merge_clock_ctx(cls, a: VClock, b: VClock, ca: VClock, cb: VClock) -> VClock:
        out = VClock()
        for r in set(a.counters) | set(b.counters):
            c = cls._surv2(a.get(r), b.get(r), ca.get(r), cb.get(r))
            if c:
                out.counters[r] = c
        return out

    @classmethod
    def _merge_orset_ctx(cls, va: ORSet, vb: ORSet, ca: VClock, cb: VClock) -> ORSet:
        out = ORSet()
        for m in set(va.entries) | set(vb.entries):
            ea, eb = va.entries.get(m, {}), vb.entries.get(m, {})
            merged = {}
            for r in set(ea) | set(eb):
                c = cls._surv2(ea.get(r, 0), eb.get(r, 0), ca.get(r), cb.get(r))
                if c:
                    merged[r] = c
            if merged:
                out.entries[m] = merged
        # remove horizons union by max…
        for src in (va.deferred, vb.deferred):
            for m, d in src.items():
                slot = out.deferred.setdefault(m, {})
                for r, c in d.items():
                    if c > slot.get(r, 0):
                        slot[r] = c
        out.clock = cls._merge_clock_ctx(va.clock, vb.clock, ca, cb)
        for m in list(set(out.entries) | set(out.deferred)):
            out._normalize_member(m)
        # …then retire any the merged MAP knowledge covers: a dot ≤ both
        # effective clocks can never re-enter this child (the map-level
        # survivor filter and replay gate both block it), and the fold
        # side retired the same horizons through the child clock the
        # map-level reset has since forgotten
        mapk = ca.copy()
        mapk.merge(cb)
        for m in list(out.deferred):
            d = out.deferred[m]
            for r in [r for r, c in d.items() if c <= mapk.get(r)]:
                del d[r]
            if not d:
                del out.deferred[m]
        return out

    # -- reads -------------------------------------------------------------
    def get(self, key):
        return self.vals.get(key)

    def keys(self) -> list:
        return sorted(self.births, key=codec.pack)

    def contains(self, key) -> bool:
        return key in self.births

    # -- wire --------------------------------------------------------------
    def op_to_obj(self, op):
        return op.to_obj(self._child_type()[2])

    def op_from_obj(self, obj):
        if isinstance(obj, (UpOp, RmOp)):
            return obj
        kind = obj[0]
        if kind == 0:
            return UpOp(
                Dot.from_obj(obj[1]), self._thaw_key(obj[2]),
                self._child_type()[1](obj[3]),
            )
        if kind == 1:
            return RmOp(
                VClock.from_obj(obj[1]),
                tuple(self._thaw_key(k) for k in obj[2]),
            )
        raise ValueError(f"bad CrdtMap op kind {kind!r}")

    @staticmethod
    def _thaw_key(key):
        if isinstance(key, (bytearray, memoryview)):
            return bytes(key)
        if isinstance(key, list):
            return tuple(key)
        return key

    def to_obj(self):
        all_keys = sorted(set(self.births) | set(self.vals), key=codec.pack)
        cls = self._child_type()[0]
        return [
            self.child,
            self.clock.to_obj(),
            [
                [
                    k,
                    {
                        a: c
                        for a, c in sorted(self.births.get(k, {}).items())
                    },
                    self.vals[k].to_obj() if k in self.vals else cls().to_obj(),
                ]
                for k in all_keys
            ],
            [
                [ctx.to_obj(), sorted(rm_keys, key=codec.pack)]
                for _, (ctx, rm_keys) in sorted(self.deferred.items())
            ],
        ]

    @classmethod
    def from_obj(cls, obj) -> "CrdtMap":
        child, clock, entries, deferred = obj
        m = cls(child=bytes(child))
        m.clock = VClock.from_obj(clock)
        ctype = m._child_type()[0]
        for k, birth, val in entries:
            k = cls._thaw_key(k)
            if birth:
                m.births[k] = {bytes(a): int(c) for a, c in birth.items()}
            m.vals[k] = ctype.from_obj(val)
        for ctx_obj, rm_keys in deferred:
            m._defer(
                VClock.from_obj(ctx_obj),
                [cls._thaw_key(k) for k in rm_keys],
            )
        return m

"""Per-CRDT delta codecs: cut a small lattice delta, apply it exactly.

A codec provides two pure functions over a CRDT type's state:

* ``diff(base, new) -> obj | None`` — the state change from ``base``
  to ``new`` as a msgpack-able object, or ``None`` when no delta
  smaller than the full state can be cut (the caller then seals no
  delta and consumers fall back to the snapshot path).
* ``apply(state, obj) -> None`` — fold the delta into ``state``.

**Correctness contract** (the differential tests and the adversarial
simulator both pin it byte-exactly): for any consumer state ``X`` that
has MERGED the base snapshot (``X ⊒ base`` in the CvRDT lattice, via
``merge(X0, base)`` — cursor coverage alone is NOT enough, see the
OR-Set note below), ``apply(X, diff(base, new))`` must leave ``X``
byte-identical (canonical form) to ``merge(X, new)``.  The core only
applies a delta when the base snapshot's content-addressed NAME is in
its ``read_states`` set, which is exactly the merged-the-base
precondition; anything weaker falls back to the full snapshot.

For join-semilattice states with cheap sub-elements (G-Counter,
PN-Counter, G-Set) the delta is literally a smaller element of the
same lattice and ``apply`` is ``merge`` — correct for ANY ``X``.  The
Orswot OR-Set is the interesting case: its clock doubles as the
tombstone set (``models/orset.py``), so a plain sub-state cannot
express removals without killing every surviving old entry.  The
Orswot delta here is the dotted-causal-context form restricted to the
window ``(base.clock, new.clock]``:

* ``e``  — surviving slots whose add-dot lies past ``base.clock``
  (the new adds; also the *confirmations* that keep a window dot
  alive on the consumer),
* ``x``  — base slots absent from ``new`` (removals of old entries;
  dot-exact, so a consumer's newer concurrent slot is untouched),
* ``t``  — remove horizons (``deferred``) raised past the base's,
* ``bc``/``c`` — both endpoint clocks, delimiting the kill window.

``apply`` kills a consumer slot iff it is dot-exactly removed by
``x``, or its dot falls in the window and ``e`` does not confirm it —
precisely the slots ``merge(X, new)`` would kill (``new`` saw those
dots and no longer holds them), and no others: dots at or below
``base.clock`` are protected (the consumer merged the base, so its
surviving old slots are the base's surviving old slots), and dots
past ``new.clock`` are unknown to ``new`` and survive any merge with
it.  Why cursor coverage is not enough for the precondition: Orswot
removes do not advance the clock, so a consumer whose *cursor*
descends the base's may still hold a pre-base dot alive that the base
had removed — only an actual merge of the base snapshot rules that
out.
"""

from __future__ import annotations

from ..models import GCounter, GSet, ORSet, PNCounter, VClock
from ..utils import codec as _codec


# --------------------------------------------------------------------- orset
def orset_delta_diff(base: ORSet, new: ORSet):
    """The Orswot window delta (module docs).  ``new`` must descend
    ``base`` (it is the same replica's state after more folding —
    slots only grow, killed dots stay dead)."""
    bc = base.clock
    adds: dict = {}
    for member, slots in new.entries.items():
        picked = {r: c for r, c in slots.items() if c > bc.get(r)}
        if picked:
            adds[member] = picked
    removed: dict = {}
    for member, slots in base.entries.items():
        new_slots = new.entries.get(member, {})
        gone = {r: c for r, c in slots.items() if not new_slots.get(r, 0)}
        if gone:
            removed[member] = gone
    horizons: dict = {}
    for member, hs in new.deferred.items():
        base_hs = base.deferred.get(member, {})
        raised = {
            r: h
            for r, h in hs.items()
            if h > base_hs.get(r, 0) and h > new.clock.get(r)
        }
        if raised:
            horizons[member] = raised
    return {
        b"bc": bc.to_obj(),
        b"c": new.clock.to_obj(),
        b"e": adds,
        b"x": removed,
        b"t": horizons,
    }


def orset_delta_from_rows(
    rows, *, members, replicas, row_width, base_clock, new_clock
):
    """Build the Orswot window delta from DEVICE-CUT diff rows instead
    of the host dict walk: ``rows`` is the (idx, code, add_base,
    add_new, rm_new) tuple :func:`ops.orset.orset_plane_diff_rows`
    gathered (already D2H, plain integer arrays), ``members`` /
    ``replicas`` are the shared vocab item lists the planes were
    indexed by, ``row_width`` is the padded replica width the flat
    indices were raveled with, and the clocks are the dense base/new
    clock rows.  Emits byte-for-byte the object
    :func:`orset_delta_diff` would (the canonical packer sorts map
    keys, so insertion order never reaches the sealed bytes); the
    differential tests pin that identity per storage backend and mesh
    shape."""
    from ..ops.orset import DIFF_ADD, DIFF_HORIZON, DIFF_REMOVED

    idx, code, add_b, add_n, rm_n = rows
    adds: dict = {}
    removed: dict = {}
    horizons: dict = {}
    for i in range(len(idx)):
        k = int(code[i])
        if not k:
            continue  # sentinel slot past the real diff count
        e, r = divmod(int(idx[i]), row_width)
        member = members[e]
        rep = replicas[r]
        if k & DIFF_ADD:
            adds.setdefault(member, {})[rep] = int(add_n[i])
        if k & DIFF_REMOVED:
            removed.setdefault(member, {})[rep] = int(add_b[i])
        if k & DIFF_HORIZON:
            horizons.setdefault(member, {})[rep] = int(rm_n[i])
    return {
        b"bc": {
            replicas[r]: int(c) for r, c in enumerate(base_clock) if c
        },
        b"c": {
            replicas[r]: int(c) for r, c in enumerate(new_clock) if c
        },
        b"e": adds,
        b"x": removed,
        b"t": horizons,
    }


def orset_delta_apply(state: ORSet, obj) -> None:
    """Fold one Orswot window delta into ``state`` (module docs)."""
    bc = VClock.from_obj(obj.get(b"bc"))
    nc = VClock.from_obj(obj.get(b"c"))
    adds = {m: {bytes(r): int(c) for r, c in v.items()}
            for m, v in (obj.get(b"e") or {}).items()}
    removed = {m: {bytes(r): int(c) for r, c in v.items()}
               for m, v in (obj.get(b"x") or {}).items()}
    horizons = {m: {bytes(r): int(c) for r, c in v.items()}
                for m, v in (obj.get(b"t") or {}).items()}
    state._mut += 1  # device plane caches key on the mutation epoch
    touched = set(adds) | set(removed) | set(horizons)

    # 1) kill pass: dot-exact removals, then the causal window.  When
    #    the window is empty (a remove-only delta: Orswot removes never
    #    advance the clock) only explicitly named members need a look.
    window = any(nc.get(r) > bc.get(r) for r in nc.counters)
    scan = list(state.entries) if window else [
        m for m in removed if m in state.entries
    ]
    for member in scan:
        slots = state.entries.get(member)
        if not slots:
            continue
        gone = removed.get(member, {})
        confirm = adds.get(member, {})
        for r in list(slots):
            c = slots[r]
            if gone.get(r, 0) == c:
                del slots[r]  # the base slot new explicitly dropped
            elif bc.get(r) < c <= nc.get(r) and confirm.get(r, 0) != c:
                # new saw this dot and no longer holds it: dead
                del slots[r]
                touched.add(member)
        if not slots:
            state.entries.pop(member, None)

    # 2) raised remove horizons: kill what they cover, defer the rest
    for member, hs in horizons.items():
        state._apply_rm(member, VClock(dict(hs)))

    # 3) new adds: unseen dots land, seen-and-dead dots stay dead
    for member, slots in adds.items():
        for r, c in slots.items():
            cur = state.entries.get(member, {}).get(r, 0)
            if cur >= c:
                continue  # consumer already holds this dot or newer
            if c <= state.clock.get(r):
                continue  # seen and killed locally: stays dead
            if state.deferred.get(member, {}).get(r, 0) >= c:
                continue  # a deferred remove already observed it
            state.entries.setdefault(member, {})[r] = c

    # 4) causal advance + canonical normalization of touched members
    state.clock.merge(nc)
    for member in touched:
        state._normalize_member(member)


class _OrsetCodec:
    state_type = ORSet
    diff = staticmethod(orset_delta_diff)
    apply = staticmethod(orset_delta_apply)


# ------------------------------------------------------------------ counters
class _GCounterCodec:
    """A G-Counter delta is a sub-clock: the per-actor counters that
    moved past the base.  ``apply`` is the lattice join itself, so the
    merged-base precondition is not even needed here."""

    state_type = GCounter

    @staticmethod
    def diff(base: GCounter, new: GCounter):
        return {
            r: c
            for r, c in new.clock.counters.items()
            if c > base.clock.get(r)
        }

    @staticmethod
    def apply(state: GCounter, obj) -> None:
        state.clock.merge(VClock.from_obj(obj))


class _PNCounterCodec:
    state_type = PNCounter

    @staticmethod
    def diff(base: PNCounter, new: PNCounter):
        return [
            _GCounterCodec.diff(base.p, new.p),
            _GCounterCodec.diff(base.n, new.n),
        ]

    @staticmethod
    def apply(state: PNCounter, obj) -> None:
        p, n = obj
        _GCounterCodec.apply(state.p, p)
        _GCounterCodec.apply(state.n, n)


class _GSetCodec:
    state_type = GSet

    @staticmethod
    def diff(base: GSet, new: GSet):
        added = [m for m in new.members if m not in base.members]
        added.sort(key=_codec.pack)
        return added

    @staticmethod
    def apply(state: GSet, obj) -> None:
        for m in obj or []:
            state.apply(m)


# ------------------------------------------------------------------ registry
# adapter name (CrdtAdapter.name) → codec.  The composed resettable
# counter (delta/compose.py) rides the OR-Set codec unchanged: its
# state IS an ORSet — the same composition law that lets it ride the
# OR-Set device kernels.
_CODECS = {
    b"orset": _OrsetCodec,
    b"rcounter": _OrsetCodec,
    b"gcounter": _GCounterCodec,
    b"pncounter": _PNCounterCodec,
    b"gset": _GSetCodec,
}


def codec_for(adapter_name: bytes):
    """The delta codec registered for an adapter name, or ``None`` —
    the caller falls back to the full-snapshot path (types without a
    codec simply never seal deltas)."""
    return _CODECS.get(bytes(adapter_name))

"""Composed adapters via semidirect products — new CRDT types, zero
new device kernels.

"Composing and Decomposing Op-Based CRDTs with Semidirect Products"
(arXiv:2004.04303) builds richer types as a product ``A ⋊ B`` where
``B``'s operations *act on* ``A``'s: the composed op set is the union,
and a ``B`` op rewrites the effect of every concurrent-or-prior ``A``
op it observed.  The resettable counter is the canonical instance —
increments (``A``) composed with resets (``B``) whose action cancels
every increment the reset observed, while concurrent unobserved
increments survive.

That action law — "cancel what you observed, spare what you didn't" —
is exactly the observed-remove discipline the Orswot OR-Set already
implements with its causal clock (``models/orset.py``).  So the
composition here is *representational*: a resettable counter state IS
an OR-Set whose members are **increment tokens** (one unique token per
increment, carrying its amount), and the composed ops ARE OR-Set ops:

* ``inc(amount)``   → ``AddOp(token, dot)`` — the token's dot is the
  increment's identity in the product;
* ``reset()``       → one ``RmOp`` per live token (the semidirect
  action: remove-what-you-observed);
* ``value()``       → sum of live tokens' amounts;
* ``undo(token)``   → ``RmOp`` for that single token.

Because the state is a real :class:`~crdt_enc_tpu.models.ORSet`, the
whole existing stack serves it unchanged: the TPU columnar fold
kernels, the fold sessions, the multi-tenant mega-folds, the warm
plane caches, the packed checkpoints, and the delta codec
(``delta/codec.py`` registers ``b"rcounter"`` onto the OR-Set codec).
The adapter below differs from ``orset_adapter`` only in name — the
name is the contract (it selects codecs and tells fsck what to
decode), the kernels are shared.

**Undo scope** — "The Only Undoable CRDTs are Counters"
(arXiv:2006.10494) proves that exact, order-agnostic undo exists only
for commutative-monoid effects (counters): un-incrementing is adding
the inverse.  Accordingly :meth:`ResettableCounter.undo` undoes
*increments* (token removal is the exact inverse, and it commutes),
and **resets are not undoable**: un-removing an Orswot token would
need a fresh dot, which is a new event, not an inverse — concurrent
peers could have observed the reset and the "undo" would resurrect
state some replicas legitimately dropped.  ``undo`` on a reset (or on
an already-cancelled token) raises :class:`UndoError` instead of
guessing.
"""

from __future__ import annotations

from ..models import ORSet
from ..models.orset import AddOp, RmOp, op_from_obj as orset_op_from_obj
from ..models.vclock import Actor
from ..utils import codec as _codec


class UndoError(Exception):
    """The requested undo is outside the honest undo scope: the target
    increment is no longer observable (already reset/undone/unseen),
    or the op kind (reset) admits no inverse (arXiv:2006.10494)."""


def _token(actor: Actor, counter: int, amount: int) -> bytes:
    """One increment token: unique per (actor, dot counter), carrying
    its amount.  Packed canonically so tokens sort deterministically
    in the OR-Set's member table."""
    return _codec.pack([b"inc", bytes(actor), int(counter), int(amount)])


def _token_amount(member) -> int | None:
    try:
        kind, _actor, _counter, amount = _codec.unpack(bytes(member))
    except Exception:
        return None
    if bytes(kind) != b"inc":
        return None
    return int(amount)


class ResettableCounter:
    """Op builders + reads over an OR-Set-typed state.  Stateless —
    every method takes the live state (use them inside
    ``core.with_state`` / ``core.update`` sections, where the LockBox
    discipline holds)."""

    # -- ops ---------------------------------------------------------------
    @staticmethod
    def inc(state: ORSet, actor: Actor, amount: int = 1) -> AddOp:
        """One increment as a composed op: a unique valued token added
        with the next dot.  Returns the ``AddOp`` (apply via the core's
        normal op path); the op's ``member`` is the undo handle."""
        if amount == 0:
            raise ValueError("amount must be non-zero")
        dot = state.clock.inc(actor)
        return AddOp(_token(dot.actor, dot.counter, amount), dot)

    @staticmethod
    def reset(state: ORSet) -> list[RmOp]:
        """The semidirect action: cancel every increment this replica
        has observed.  Concurrent increments it has NOT observed
        survive the reset — the add-wins window the product
        construction prescribes."""
        return [state.rm_ctx(m) for m in state.members()]

    @staticmethod
    def undo(state: ORSet, op) -> RmOp:
        """Undo one observed increment (its exact inverse).  Raises
        :class:`UndoError` when ``op`` is not an increment or its token
        is no longer live (already reset or undone — there is nothing
        left to invert)."""
        if isinstance(op, RmOp):
            raise UndoError(
                "resets are not undoable: un-removing would mint a new "
                "event, not an inverse (arXiv:2006.10494)"
            )
        member = op.member if isinstance(op, AddOp) else op
        if _token_amount(member) is None:
            raise UndoError(f"not an increment token: {member!r}")
        if not state.contains(member):
            raise UndoError("increment no longer observable (reset/undone)")
        return state.rm_ctx(member)

    # -- reads -------------------------------------------------------------
    @staticmethod
    def value(state: ORSet) -> int:
        total = 0
        for member in state.entries:
            amount = _token_amount(member)
            if amount is not None:
                total += amount
        return total

    @staticmethod
    def tokens(state: ORSet) -> list[tuple[bytes, int]]:
        """Live (token, amount) pairs — the auditable increment
        history the undo API addresses."""
        out = []
        for member in state.members():
            amount = _token_amount(member)
            if amount is not None:
                out.append((bytes(member), amount))
        return out


def rcounter_adapter():
    """The composed resettable counter as a Core adapter: OR-Set state,
    OR-Set wire, OR-Set kernels — only the name (and therefore the
    codec/fsck dispatch) differs.  Proof-of-law for ROADMAP item 3:
    a new user-facing CRDT type with no new device kernel."""
    from ..core.adapters import CrdtAdapter

    return CrdtAdapter(
        name=b"rcounter",
        new=ORSet,
        state_from_obj=ORSet.from_obj,
        op_from_obj=orset_op_from_obj,
    )

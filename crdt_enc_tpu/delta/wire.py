"""The sealed delta payload — one wire form, shared by core and fsck.

A delta file travels the same three-layer wire as every other object
(``core.open_sealed_blob``); this module owns only the decrypted inner
object.  Every field is load-bearing for the fallback discipline:

* ``base`` / ``new`` — the content-addressed NAMES of the two endpoint
  snapshots.  Names are fingerprints (SHA3 of the sealed bytes), so
  "has the consumer merged exactly this base?" is a set-membership
  test against ``read_states`` — any doubt (unknown base, renamed
  snapshot, adapter mismatch) falls back to the full snapshot.
* ``bcur`` / ``ncur`` — the op-log cursors of the two snapshots; a
  consumer that applies the delta advances its ingest cursor exactly
  as if it had merged the new snapshot.
* ``s`` — the sealer's actor id: the cursor-matrix row this delta
  teaches (obs/replication.py), and the log directory it must be
  filed under (fsck cross-checks; a mismatch is a misfiled orphan).
* ``wm`` — the sealer's causal stability watermark at seal time
  (PR-6 cursor-matrix math): the causal tag anchoring the chain — a
  reader can see how far behind fleet-stable the chain base was.
* ``a`` — the adapter name; selects the delta codec.
* ``d`` — the codec delta object (delta/codec.py).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..models.vclock import VClock

DELTA_WIRE_VERSION = 1


@dataclass
class DeltaRecord:
    base_name: str  # "" when the sealer had no base (no delta is sealed then)
    new_name: str
    base_cursor: VClock
    new_cursor: VClock
    sealer: bytes
    adapter: bytes
    watermark: dict  # actor -> stable version at seal time
    delta_obj: object


def build_delta_obj(rec: DeltaRecord) -> dict:
    return {
        b"v": DELTA_WIRE_VERSION,
        b"base": rec.base_name.encode(),
        b"new": rec.new_name.encode(),
        b"bcur": rec.base_cursor.to_obj(),
        b"ncur": rec.new_cursor.to_obj(),
        b"s": rec.sealer,
        b"a": rec.adapter,
        b"wm": {bytes(a): int(c) for a, c in sorted(rec.watermark.items())},
        b"d": rec.delta_obj,
    }


def parse_delta_obj(obj) -> DeltaRecord:
    """Decode + validate one delta payload.  Raises ``ValueError`` on
    any malformed field — the consumer counts it as a fallback, fsck
    reports it as an error row."""
    if not isinstance(obj, dict):
        raise ValueError("delta payload is not a map")
    v = obj.get(b"v")
    if v != DELTA_WIRE_VERSION:
        raise ValueError(f"unsupported delta wire version {v!r}")
    sealer = obj.get(b"s")
    if not isinstance(sealer, (bytes, bytearray, memoryview)) or len(sealer) != 16:
        raise ValueError("delta sealer id is not 16 bytes")
    adapter = obj.get(b"a")
    if not isinstance(adapter, (bytes, bytearray, memoryview)) or not adapter:
        raise ValueError("delta adapter name missing")
    new_name = obj.get(b"new")
    if not isinstance(new_name, (bytes, bytearray, memoryview)) or not new_name:
        raise ValueError("delta target snapshot name missing")
    base_name = obj.get(b"base", b"")
    if not isinstance(base_name, (bytes, bytearray, memoryview)):
        raise ValueError("delta base snapshot name malformed")
    wm = obj.get(b"wm")
    if not isinstance(wm, dict):
        raise ValueError("delta base watermark missing")
    bcur, ncur = obj.get(b"bcur"), obj.get(b"ncur")
    if not isinstance(bcur, dict) or not isinstance(ncur, dict):
        raise ValueError("delta cursors missing")
    if b"d" not in obj:
        raise ValueError("delta body missing")
    return DeltaRecord(
        base_name=bytes(base_name).decode(),
        new_name=bytes(new_name).decode(),
        base_cursor=VClock.from_obj(bcur),
        new_cursor=VClock.from_obj(ncur),
        sealer=bytes(sealer),
        adapter=bytes(adapter),
        watermark={bytes(a): int(c) for a, c in wm.items()},
        delta_obj=obj[b"d"],
    )

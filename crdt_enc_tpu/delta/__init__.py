"""Delta-state replication (docs/delta.md, ROADMAP item 3).

Full-state snapshots make the remote the fan-in bottleneck: every
consumer re-downloads O(state) bytes even when only a handful of ops
landed since its last read.  This package seals, alongside each
compacted snapshot, an encrypted **delta snapshot** — the state change
since the sealer's previous snapshot, causally tagged with both
endpoint cursors and the sealer's PR-6 stability watermark — so an
incremental consumer folds ``full-at-base + delta chain`` instead of
re-reading the full snapshot, with automatic fallback to the snapshot
path on any gap, GC'd link, or fingerprint doubt (traced via the
``delta_fallbacks`` counter, never silent).

* :mod:`~crdt_enc_tpu.delta.codec` — per-CRDT-type delta codecs:
  ``diff(base, new)`` cuts a lattice delta whose consumer-side
  ``apply`` is provably equal to merging the full new snapshot, for
  any consumer that has merged the base (the delta-state CRDT
  property; Almeida et al.'s delta-mutators specialized to this
  repo's columnar state planes).
* :mod:`~crdt_enc_tpu.delta.wire` — the sealed delta payload: base /
  new snapshot names (content addresses — the chain's fingerprints),
  both op-log cursors, the sealer id, the watermark tag, and the
  codec delta object.
* :mod:`~crdt_enc_tpu.delta.compose` — composed adapters via the
  semidirect-product construction (arXiv:2004.04303): the resettable
  counter (and its scoped undo per arXiv:2006.10494) expressed over
  the existing OR-Set columnar kernels — new CRDT types without new
  device kernels.

Deltas live in a per-sealer versioned log (``remote/deltas/
<actor-hex>/<N>``, the op-log idiom) so GC is the op-file rule:
consumed prefixes are removed at compaction, own logs are bounded at
:data:`MAX_CHAIN` links, and anything missing simply falls back to
the snapshot path.
"""

from __future__ import annotations

# longest own delta chain a sealer keeps: a consumer more than
# MAX_CHAIN compactions behind re-reads the full snapshot once and
# rejoins the chain — bounding both remote clutter and the worst-case
# chain a reader walks
MAX_CHAIN = 16

from .codec import codec_for, orset_delta_diff, orset_delta_apply  # noqa: E402
from .wire import DeltaRecord, build_delta_obj, parse_delta_obj  # noqa: E402
from .compose import (  # noqa: E402
    ResettableCounter,
    UndoError,
    rcounter_adapter,
)

__all__ = [
    "MAX_CHAIN",
    "codec_for",
    "orset_delta_diff",
    "orset_delta_apply",
    "DeltaRecord",
    "build_delta_obj",
    "parse_delta_obj",
    "ResettableCounter",
    "UndoError",
    "rcounter_adapter",
]

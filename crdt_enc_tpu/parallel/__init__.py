from .accel import TpuAccelerator
from .distributed import (
    global_op_batch,
    initialize,
    make_multihost_mesh,
    replicate,
)
from .mesh import (
    gcounter_fold_sharded,
    lww_fold_sharded,
    make_mesh,
    orset_fold_sharded,
    orset_merge_sharded,
    pad_rows_for_mesh,
    pncounter_fold_sharded,
    sharded_fold_cap,
)

__all__ = [
    "TpuAccelerator",
    "gcounter_fold_sharded",
    "global_op_batch",
    "initialize",
    "lww_fold_sharded",
    "make_mesh",
    "make_multihost_mesh",
    "orset_fold_sharded",
    "orset_merge_sharded",
    "pad_rows_for_mesh",
    "sharded_fold_cap",
    "pncounter_fold_sharded",
    "replicate",
]

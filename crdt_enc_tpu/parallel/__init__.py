from .accel import TpuAccelerator
from .mesh import (
    make_mesh,
    orset_fold_sharded,
    orset_merge_sharded,
    pad_rows_for_mesh,
)

__all__ = [
    "TpuAccelerator",
    "make_mesh",
    "orset_fold_sharded",
    "orset_merge_sharded",
    "pad_rows_for_mesh",
]

from .accel import TpuAccelerator
from .distributed import (
    global_op_batch,
    initialize,
    make_multihost_mesh,
    replicate,
)
from .mesh import (
    make_mesh,
    orset_fold_sharded,
    orset_merge_sharded,
    pad_rows_for_mesh,
)

__all__ = [
    "TpuAccelerator",
    "global_op_batch",
    "initialize",
    "make_mesh",
    "make_multihost_mesh",
    "orset_fold_sharded",
    "orset_merge_sharded",
    "pad_rows_for_mesh",
    "replicate",
]

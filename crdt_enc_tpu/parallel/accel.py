"""TPU accelerator: the drop-in replacement for the core's host fold/merge.

Plugs into ``OpenOptions.accelerator`` (crdt_enc_tpu/core/adapters.py
defines the interface + the host reference implementation).  Each call
converts sparse host state ↔ dense planes around one jitted kernel; the
conversion cost is amortized over whole op batches, which is exactly the
compaction shape (thousands of files → one fold).  Small batches fall back
to the host loop — dispatch overhead would dominate.

Shapes are bucket-padded (powers of two) so repeated compactions reuse
compiled programs (SURVEY.md §7 hard part 3).
"""

from __future__ import annotations

import os

import numpy as np

from ..core.adapters import HostAccelerator
from ..models import GCounter, LWWMap, ORSet, PNCounter
from ..models.counters import NEG, POS
from ..models.vclock import Dot, VClock
from ..obs import runtime as obs_runtime
from ..utils import trace
from .. import ops as K

MIN_DEVICE_BATCH = 256  # below this the host loop wins


def _bucket(n: int, floor: int = 8) -> int:
    b = floor
    while b < n:
        b *= 2
    return b


class _OrsetPlaneCache:
    """Device-resident ORSet state planes carried between folds.

    After a dense fold writes its result back to the sparse host state,
    the very planes it computed — already on device, already normalized,
    byte-equal to the state — are kept here so the NEXT fold on the same
    un-mutated state skips the state→planes walk and the full-state H2D
    re-upload (repeated ``read_remote``/``compact`` rounds in one
    process).  Validity is (object identity via weakref) × (the state's
    ``_mut`` mutation epoch recorded at writeback): any host mutation —
    per-op apply, CvRDT merge, another accelerator path's writeback —
    bumps the epoch and the entry silently expires.  The vocabularies
    are the fold vocabs of the caching round; later batches remap onto
    them (value-collision-guarded, exactly like the fold sessions)."""

    __slots__ = ("ref", "token", "members", "replicas", "planes", "canon")

    def __init__(self, ref, token, members, replicas, planes, canon):
        self.ref = ref
        self.token = token
        self.members = members
        self.replicas = replicas
        self.planes = planes  # (clock, add, rm) device arrays
        self.canon = canon  # member slot -> canonical packed bytes


class TpuAccelerator(HostAccelerator):
    """Accelerates ORSet / G-Counter / PN-Counter / LWW-Map folds and
    ORSet / MVReg merges; anything else (EmptyCrdt, custom types — and
    any batch too small to beat dispatch overhead) falls back to the
    host loops.

    ``mesh``: an optional ``jax.sharding.Mesh`` with ``(dp, mp)`` axes
    (``parallel.mesh.make_mesh`` / ``distributed.make_multihost_mesh``).
    With more than one device, every fold and merge routes through the
    sharded SPMD kernels — op rows over ``dp``, state planes over ``mp`` —
    so ``Core.compact`` executes multi-chip, not on device 0 of a pod."""

    def __init__(
        self,
        min_device_batch: int = MIN_DEVICE_BATCH,
        mesh=None,
        sparse_device: bool = False,
        map_fold_impl: str | None = None,
        sharded_stream: bool | None = None,
        stream_producers: int = 0,
        plane_reuse: bool | None = None,
        bucket_vocab: bool | None = None,
    ):
        self.min_device_batch = min_device_batch
        self.mesh = mesh
        # vocabulary-axis bucketing (None = env CRDT_BUCKET_VOCAB, default
        # OFF): lift the member/replica plane dims — and merge stack
        # heights — to power-of-two classes (zero padding; sliced back at
        # writeback).  Row counts are always bucketed; this extends the
        # same recompilation bound to E/R/S, so many small states with
        # churning vocabularies (the simulator's population shape) share
        # one compiled program set instead of compiling per vocab size.
        if bucket_vocab is None:
            bucket_vocab = os.environ.get(
                "CRDT_BUCKET_VOCAB", ""
            ).strip().lower() in ("1", "true", "on", "yes", "enabled")
        self.bucket_vocab = bool(bucket_vocab)
        # device-resident plane reuse across fold rounds (None = auto-on;
        # CRDT_PLANE_REUSE=0 opts out).  Single-device only: the sharded
        # fold keeps planes mp-distributed and re-builds per round.
        if plane_reuse is None:
            plane_reuse = os.environ.get(
                "CRDT_PLANE_REUSE", ""
            ).strip().lower() not in ("0", "false", "off", "no", "disabled")
        self.plane_reuse = bool(plane_reuse)
        self._plane_cache: _OrsetPlaneCache | None = None
        # persistent XLA compilation cache (CRDT_JIT_CACHE=<dir> or =1
        # for the default cache dir): short-lived compaction processes
        # stop re-paying first-compile cost for shapes any prior process
        # on this host already compiled
        jit_cache = os.environ.get("CRDT_JIT_CACHE", "").strip()
        if jit_cache and jit_cache.lower() not in (
            "0", "false", "off", "no", "disabled",
        ):
            import crdt_enc_tpu

            crdt_enc_tpu.enable_compilation_cache(
                None
                if jit_cache.lower() in ("1", "true", "on", "yes", "enabled")
                else jit_cache
            )
        # mesh-sharded streaming fold (parallel/session.py
        # _device_feed_sharded): None = auto — ON whenever the mesh is
        # active, so a pod compaction streams through the SPMD kernels
        # instead of buffering the whole row batch host-side.
        # CRDT_SHARDED_STREAM=0/1 overrides the auto default; an
        # unrecognized value keeps the auto default (never a silent
        # opt-in from a typo'd opt-out).
        if sharded_stream is None:
            env = os.environ.get("CRDT_SHARDED_STREAM", "").strip().lower()
            if env in ("0", "false", "off", "no", "disabled"):
                sharded_stream = False
            elif env in ("1", "true", "on", "yes", "enabled") or not env:
                sharded_stream = True
            else:
                import warnings

                warnings.warn(
                    f"CRDT_SHARDED_STREAM={env!r} not recognized; "
                    "keeping the auto default (on with an active mesh)",
                    stacklevel=2,
                )
                sharded_stream = True
        self.sharded_stream = bool(sharded_stream) and self._mesh_active()
        # ingest fan-out width for fold_encrypted_stream and the core's
        # pipelined bulk ingest: 0 = auto (ops.stream.stream_producer_count
        # — env CRDT_STREAM_PRODUCERS, else cpu_count-derived)
        self.stream_producers = stream_producers
        # every XLA backend compile around the jitted/Pallas folds bumps
        # the jax_compiles counter — steady-state growth is the ADVICE-r5
        # unbounded-recompile bug class, now mechanically visible
        # (default-on; an explicit operator track_recompiles(False) wins)
        obs_runtime.ensure_recompile_tracking()
        # CrdtMap scatter phase: "host" (numpy reference), "device"
        # (ops/map_device.py jit), or None = device for batches past
        # min_device_batch
        self.map_fold_impl = map_fold_impl
        # sparse-regime folds default to the vectorized host sort (numpy
        # lexsort beats the TPU's bitonic sort ~25× at these shapes and no
        # planes exist to ship — see orset_fold_sparse_host).  Opt in to
        # the device COO kernel where that trade flips: columns already
        # device-resident, or hosts much slower than this one.
        self.sparse_device = sparse_device

    def _mesh_active(self) -> bool:
        return self.mesh is not None and self.mesh.size > 1

    def _dp(self) -> int:
        return self.mesh.shape["dp"] if self._mesh_active() else 1

    @staticmethod
    def _round_to(n: int, mult: int) -> int:
        return -(-n // mult) * mult

    # ------------------------------------------------------------- fold_ops
    def fold_ops(self, state, ops: list):
        if len(ops) < self.min_device_batch:
            return super().fold_ops(state, ops)
        if isinstance(state, ORSet):
            return self._fold_orset(state, ops)
        if isinstance(state, PNCounter):
            return self._fold_pncounter(state, ops)
        if isinstance(state, GCounter):
            return self._fold_gcounter(state, ops)
        if isinstance(state, LWWMap):
            return self._fold_lww(state, ops)
        return super().fold_ops(state, ops)

    def _fold_orset(self, state: ORSet, ops: list) -> ORSet:
        members, replicas = K.Vocab(), K.Vocab()
        cols = K.orset_ops_to_columns(ops, members, replicas)
        return self._fold_orset_columns(
            state, cols.kind, cols.member, cols.actor, cols.counter,
            members, replicas,
        )

    # Above this many plane cells per batch row the dense scatter target's
    # HBM init/sweep dominates (measured: E·R ≈ 500·N cost 46s/fold at the
    # 100k-replica streaming scale) — the sorted-COO sparse fold wins.
    SPARSE_CELLS_PER_ROW = 64
    # …and below this many cells the dense planes are trivially cheap.
    SPARSE_MIN_CELLS = 1 << 22
    # Dense batches beyond this many rows fold blockwise (ops/stream.py) so
    # device memory stays at one chunk + planes however big the ingest.
    STREAM_CHUNK_ROWS = 1 << 22

    def _use_sparse(self, E: int, R: int, n_rows: int) -> bool:
        cells = E * R
        return cells >= self.SPARSE_MIN_CELLS and cells > (
            self.SPARSE_CELLS_PER_ROW * max(n_rows, 1)
        )

    def _plane_cache_for(self, state: ORSet) -> _OrsetPlaneCache | None:
        """The live cache entry for ``state``, or None (no entry, entry
        for another object, or the state mutated since it was filled)."""
        if not self.plane_reuse or self._mesh_active():
            return None
        c = self._plane_cache
        if c is None or c.ref() is not state:
            return None
        if c.token != getattr(state, "_mut", None):
            self._plane_cache = None  # stale: free the device planes
            return None
        return c

    @staticmethod
    def _remap_to_cache(cache: _OrsetPlaneCache, member, actor,
                        members, replicas):
        """Remap batch columns from their batch-local vocabs onto the
        cache's vocabs (growing them), or None when a member value
        collision (1 == True, 0.0 == -0.0) makes the dense planes
        unrepresentable — the caller then takes the uncached path."""
        from ..utils import codec

        if (len(member) and int(np.max(member)) >= len(members.items)) or (
            len(actor) and int(np.max(actor)) >= len(replicas.items)
        ):
            return None  # sentinel/padded columns: not plain vocab indices
        mt = np.empty(len(members.items), np.int32)
        canon = cache.canon
        for i, obj in enumerate(members.items):
            gid = cache.members.intern(obj)
            pk = codec.pack(obj)
            prev = canon.get(gid)
            if prev is None:
                stored = cache.members.items[gid]
                prev = pk if stored is obj else codec.pack(stored)
                canon[gid] = prev
            if prev != pk:
                return None
            mt[i] = gid
        rt = np.empty(len(replicas.items), np.int32)
        for i, a in enumerate(replicas.items):
            rt[i] = cache.replicas.intern(a)
        member = mt[member] if len(member) else np.asarray(member, np.int32)
        actor = rt[actor] if len(actor) else np.asarray(actor, np.int32)
        return member, actor

    @staticmethod
    def _cached_planes_padded(cache: _OrsetPlaneCache, E: int, R: int):
        """The cached device planes grown (on device — no host transfer)
        to the post-remap vocab sizes."""
        import jax.numpy as jnp

        clock, add, rm = cache.planes
        E0, R0 = add.shape
        if R > R0:
            clock = jnp.pad(clock, (0, R - R0))
            add = jnp.pad(add, ((0, 0), (0, R - R0)))
            rm = jnp.pad(rm, ((0, 0), (0, R - R0)))
        if E > E0:
            add = jnp.pad(add, ((0, E - E0), (0, 0)))
            rm = jnp.pad(rm, ((0, E - E0), (0, 0)))
        return clock, add, rm

    def _install_plane_cache(
        self, state: ORSet, members, replicas, dev_planes, canon
    ) -> None:
        """Record the fold's device planes as the state's resume planes.
        The writeback bump happens HERE so the recorded token is the
        post-writeback epoch.  The weakref finalizer drops the entry the
        moment the state dies — plane-sized device buffers must not
        outlive the replica they cache (the accelerator itself is held
        weakly in the callback, so nothing keeps anything alive)."""
        state._mut += 1
        if not self.plane_reuse or self._mesh_active():
            return
        import weakref

        accel_ref = weakref.ref(self)

        def _drop(dead_ref):
            accel = accel_ref()
            if accel is not None:
                c = accel._plane_cache
                if c is not None and c.ref is dead_ref:
                    accel._plane_cache = None

        self._plane_cache = _OrsetPlaneCache(
            weakref.ref(state, _drop), state._mut, members, replicas,
            dev_planes, canon if canon is not None else {},
        )

    def _note_orset_writeback(self, state: ORSet) -> None:
        """A non-caching path rewrote ``state``: bump its epoch and drop
        any device planes held for it."""
        state._mut += 1
        c = self._plane_cache
        if c is not None and c.ref() is state:
            self._plane_cache = None

    def _fold_orset_columns(
        self, state: ORSet, kind, member, actor, counter, members, replicas
    ) -> ORSet:
        """Shared tail: state → planes, pad, jit fold, planes → state.
        Sparse batches over huge vocabularies take the sorted-COO kernel
        instead — same semantics, no dense plane materialization.  With
        ``plane_reuse`` on and an unmutated state, the dense branch
        reuses the previous round's device-resident planes instead of
        re-walking the state and re-issuing the full-state H2D upload."""
        n_rows = len(kind)
        cache = self._plane_cache_for(state)
        if cache is not None:
            remapped = self._remap_to_cache(
                cache, member, actor, members, replicas
            )
            if remapped is None:
                cache = None
            else:
                member, actor = remapped
                members, replicas = cache.members, cache.replicas
        if cache is None:
            with trace.span("fold.vocab"):
                K.orset_scan_vocab(state, members, replicas)
        E, R = len(members), len(replicas)
        if E == 0 or R == 0:
            return state
        # vocab-axis compile classes (bucket_vocab): fold at the padded
        # (Ep, Rp) and slice back at writeback.  Zero rows/columns are
        # inert through the whole kernel — no op references a padded
        # member, padded replica columns carry zero clocks and zero
        # cells, and the sentinel row mask keys on ``actor >= Rp``.
        bucketed = (
            self.bucket_vocab
            and not self._mesh_active()
            and n_rows <= self.STREAM_CHUNK_ROWS
        )
        Ep = _bucket(E) if bucketed else E
        Rp = _bucket(R) if bucketed else R
        if self._mesh_active():
            # SPMD fold: rows shard over dp, planes over mp.  The mp axis is
            # also what makes huge (E, R) planes tractable — each device
            # holds E/mp rows — so the single-device sparse escape hatch
            # does not apply here.
            return self._fold_orset_sharded(
                state, kind, member, actor, counter, members, replicas
            )
        if self._use_sparse(E, R, n_rows):
            if self.sparse_device and 2 * E * R < 2**31:
                folded = self._fold_orset_coo_device(
                    state, kind, member, actor, counter, members, replicas
                )
            else:
                # vectorized host fold: in the N ≪ E·R regime the work is
                # one sort, where numpy beats the TPU's bitonic sort ~25x
                # and no dense planes exist to ship (see
                # orset_fold_sparse_host docs).  No bucket padding — that
                # exists only to bound jit recompilation, and this path
                # never compiles anything.
                folded = K.orset_fold_sparse_host(
                    state, kind, member, actor, counter, members, replicas
                )
            c = self._plane_cache
            if c is not None and c.ref() is state:
                self._plane_cache = None  # sparse writeback: planes stale
            return folded
        if self.bucket_vocab and not bucketed:
            # the streaming fold runs at true (E, R); cached planes from a
            # bucketed round may be padded past it, so rebuild from state
            cache = None
        if cache is not None:
            clock0, add0, rm0 = self._cached_planes_padded(cache, Ep, Rp)
        else:
            with trace.span("fold.planes"):
                clock0, add0, rm0 = K.orset_state_to_planes(
                    state, members, replicas, scanned=True
                )
            if (Ep, Rp) != (E, R):
                clock0 = np.pad(clock0, (0, Rp - R))
                add0 = np.pad(add0, ((0, Ep - E), (0, Rp - R)))
                rm0 = np.pad(rm0, ((0, Ep - E), (0, Rp - R)))
        with trace.span("fold.device"):
            if n_rows > self.STREAM_CHUNK_ROWS:
                if cache is not None:
                    # the blockwise stream stages planes from host (its
                    # own H2D rides under the first fold) — pull once
                    clock0, add0, rm0 = (
                        np.asarray(x) for x in (clock0, add0, rm0)
                    )
                # blockwise fold with donated plane buffers: bounded device
                # memory for arbitrarily large ingests (ops/stream.py).
                # Chunks route through the Pallas MXU fold when eligible —
                # the streaming path must run the same flagship kernel the
                # dense path does (chunk size == MAX_ROWS, so the row
                # bound holds by construction here).
                from ..ops import pallas_fold as PF
                from ..ops.stream import ChunkPool

                stream_kw = {}
                if self._pallas_eligible(counter):
                    stream_kw = dict(
                        impl="pallas", tile_cap=PF.fold_cap(member, E)
                    )
                # double-buffered staging: chunk k+1 columnarizes into a
                # recycled pool buffer and its H2D transfer rides under
                # chunk k's fold (ops/stream.py fold_chunks_overlapped)
                pool = ChunkPool(self.STREAM_CHUNK_ROWS, depth=2)
                dev_planes = K.orset_fold_stream(
                    clock0, add0, rm0,
                    K.iter_orset_chunks(
                        kind, member, actor, counter,
                        self.STREAM_CHUNK_ROWS, R, pool=pool,
                    ),
                    num_members=E, num_replicas=R, pool=pool, **stream_kw,
                )
            else:
                if cache is None:
                    # the full-state upload the plane cache exists to
                    # elide — counted at issue, like the streaming paths
                    # (the stream branch above counts its own)
                    trace.add(
                        "h2d_bytes",
                        clock0.nbytes + add0.nbytes + rm0.nbytes,
                    )
                cols = K.OrsetColumns(kind, member, actor, counter, members, replicas)
                K.pad_orset_rows(cols, _bucket(len(cols.kind)), Rp)
                fold = self._pick_dense_fold(cols, Ep, Rp)
                dev_planes = fold(
                    clock0,
                    add0,
                    rm0,
                    cols.kind,
                    cols.member,
                    cols.actor,
                    cols.counter,
                )
            clock, add, rm = (np.asarray(x) for x in dev_planes)
            if (Ep, Rp) != (E, R):
                clock, add, rm = clock[:R], add[:E, :R], rm[:E, :R]
        obs_runtime.sample_device_memory()  # fold boundary
        with trace.span("fold.writeback"):
            folded = K.orset_planes_to_state(clock, add, rm, members, replicas)
        state.clock = folded.clock
        state.entries = folded.entries
        state.deferred = folded.deferred
        # the planes just computed ARE the new state, already on device:
        # keep them for the next round (epoch recorded post-writeback)
        self._install_plane_cache(
            state, members, replicas, dev_planes,
            cache.canon if cache is not None else None,
        )
        return state

    @staticmethod
    def _lww_pallas_eligible(num_values, ts_hi, n_rows: int) -> bool:
        """Pallas LWW winner-fold precondition: real TPU, a packed
        (actor, value) rank (num_values set — its +1 present-offset is
        the only one the kernel applies, and it cannot wrap under the
        packed-rank bound), rows inside the sort working set."""
        import jax

        from ..ops import pallas_lww as PL

        return (
            jax.default_backend() == "tpu"
            and num_values is not None
            and n_rows <= PL.MAX_ROWS
        )

    @staticmethod
    def _pallas_eligible(counter) -> bool:
        """Shared Pallas-fold precondition: real TPU hardware and every
        counter inside the kernel's 7-bit-limb bound.  Row-count limits
        are the caller's concern (the dense path checks MAX_ROWS, the
        streaming path chunks at exactly that size)."""
        import jax

        from ..ops import pallas_fold as PF

        return (
            jax.default_backend() == "tpu"
            and int(np.max(counter, initial=0)) < PF.MAX_COUNTER
        )

    def _pick_dense_fold(self, cols, E: int, R: int):
        """The dense single-device fold kernel: the Pallas MXU fold when
        eligible on real TPU hardware (counters inside the 7-bit-limb
        bound, batch inside the sort working set — the same routing the
        bench publishes), else the XLA scatter fold.  The product ingest
        and the benchmark must run the same machinery."""
        from ..ops import pallas_fold as PF

        eligible = (
            len(cols.kind) <= PF.MAX_ROWS
            and self._pallas_eligible(cols.counter)
        )
        if eligible:
            tile_cap = PF.fold_cap(cols.member, E)
            # all-small counters skip the hi-limb matmul statically —
            # half the MXU work and no per-chunk max/branch at all
            hi_mode = (
                "skip"
                if int(np.max(cols.counter, initial=0)) < 128 else "cond"
            )

            def fold(c, a, r, kind, member, actor, counter):
                return PF.orset_fold_pallas(
                    c, a, r, kind, member, actor, counter,
                    num_members=E, num_replicas=R, tile_cap=tile_cap,
                    hi_mode=hi_mode,
                )

            return fold

        def fold(c, a, r, kind, member, actor, counter):
            return K.orset_fold(
                c, a, r, kind, member, actor, counter,
                num_members=E, num_replicas=R,
            )

        return fold

    def _fold_orset_coo_device(
        self, state: ORSet, kind, member, actor, counter, members, replicas
    ) -> ORSet:
        """Sparse-regime device fold: the sorted-COO kernel aggregates the
        batch on device without dense planes; the sparse state writeback
        shares ``orset_apply_coo`` with the host twin, so the two paths
        cannot drift."""
        # dense clock FIRST: it may intern clock actors into `replicas`,
        # and the kernel's segment keys are encoded modulo the final R
        clock0 = K.vclock_to_dense(state.clock, replicas)
        E, R = len(members), len(replicas)
        cols = K.OrsetColumns(
            np.asarray(kind, np.int8),
            np.asarray(member, np.int32),
            np.asarray(actor, np.int32),
            np.asarray(counter, np.int32),
            members,
            replicas,
        )
        K.pad_orset_rows(cols, _bucket(len(cols.kind)), R)
        clock, skey, smax, is_max = K.orset_fold_coo(
            clock0, cols.kind, cols.member, cols.actor, cols.counter,
            num_members=E, num_replicas=R,
        )
        return K.orset_apply_coo(
            state, np.asarray(clock), np.asarray(skey), np.asarray(smax),
            np.asarray(is_max), members, replicas,
        )

    def _fold_orset_sharded(
        self, state: ORSet, kind, member, actor, counter, members, replicas
    ) -> ORSet:
        """Multi-device tail: pad rows to the dp axis and the plane member
        axis to the mp axis, run the shard_map fold, write planes back."""
        from . import mesh as pmesh

        mesh = self.mesh
        dp, mp = mesh.shape["dp"], mesh.shape["mp"]
        E, R = len(members), len(replicas)
        clock0, add0, rm0 = K.orset_state_to_planes(
            state, members, replicas, scanned=True
        )
        E_pad = self._round_to(E, mp)
        if E_pad != E:
            z = np.zeros((E_pad - E, R), add0.dtype)
            add0 = np.concatenate([add0, z])
            rm0 = np.concatenate([rm0, z])
        cols = K.OrsetColumns(
            np.asarray(kind, np.int8),
            np.asarray(member, np.int32),
            np.asarray(actor, np.int32),
            np.asarray(counter, np.int32),
            members,
            replicas,
        )
        K.pad_orset_rows(
            cols, self._round_to(_bucket(len(cols.kind)), dp), R
        )
        # each shard runs the flagship Pallas scatter when eligible — a
        # mesh compaction must execute the same kernel a single chip does
        fold_kw = {}
        from ..ops import pallas_fold as PF

        # int32 segment-key bound for the per-shard ablk kernel (the
        # single-chip front door switches layouts past this; the sharded
        # route has only the ablk layout, so it must stay on XLA there)
        if (
            self._pallas_eligible(cols.counter)
            and len(cols.kind) // dp <= PF.MAX_ROWS
            and PF.ablk_key_space_fits(E_pad // mp, R)
        ):
            fold_kw = dict(
                impl="pallas",
                tile_cap=pmesh.sharded_fold_cap(cols.member, E_pad, dp, mp),
            )
        clock, add, rm = pmesh.orset_fold_sharded(
            mesh, clock0, add0, rm0,
            cols.kind, cols.member, cols.actor, cols.counter, **fold_kw,
        )
        folded = K.orset_planes_to_state(
            np.asarray(clock), np.asarray(add)[:E], np.asarray(rm)[:E],
            members, replicas,
        )
        state.clock = folded.clock
        state.entries = folded.entries
        state.deferred = folded.deferred
        self._note_orset_writeback(state)
        return state

    # ------------------------------------------------------- fold sessions
    def can_open_fold_session(self, state) -> bool:
        """Cheap predicate twin of :meth:`open_fold_session` (no session
        construction): the core checks it before spinning up pipeline
        machinery whose cost only pays off when a session exists."""
        from .session import session_supported

        return session_supported(state)

    def open_fold_session(self, state, actors_hint=()):
        """A chunked fold session for the core's pipelined bulk ingest
        (parallel/session.py), or None for CRDT types without a columnar
        chunk path — the core then uses the legacy whole-batch flow."""
        from .session import open_fold_session

        return open_fold_session(self, state, actors_hint)

    # -------------------------------------------------------- fold_payloads
    def fold_payloads(self, state, payloads: list, actors_hint=()) -> bool:
        """Bulk front end: decrypted op-file payloads → native columnar
        decode → jit fold.  Handles ORSet and the two counters; anything
        else (or any payload the native decoder declines) falls back to
        the per-op path."""
        if isinstance(state, (GCounter, PNCounter)):
            return self._fold_counter_payloads(state, payloads, actors_hint)
        from ..models.crdtmap import CrdtMap

        if isinstance(state, CrdtMap):
            return self._fold_map_payloads(state, payloads, actors_hint)
        from ..models import GSet, LWWReg, MVReg, MerkleReg, SeqList

        if isinstance(state, GSet):
            return self._fold_gset_payloads(state, payloads)
        if isinstance(state, LWWReg):
            return self._fold_lwwreg_payloads(state, payloads)
        if isinstance(state, MVReg):
            return self._fold_mvreg_payloads(state, payloads)
        if isinstance(state, SeqList):
            return self._fold_seqlist_payloads(state, payloads)
        if isinstance(state, MerkleReg):
            return self._fold_merklereg_payloads(state, payloads)
        if not isinstance(state, ORSet):
            return False
        from ..ops.native_decode import decode_orset_payload_batch

        actors_sorted = self._orset_actor_table(state, actors_hint)
        with trace.span("fold.decode"):
            decoded = decode_orset_payload_batch(payloads, actors_sorted)
        if decoded is None:
            return False
        return self._fold_orset_decoded(state, decoded, actors_sorted)

    def fold_encrypted_stream(
        self, state, key: bytes, blobs: list, *, actors_hint=(),
        chunk_blobs: int = 0, n_chunks: int = 8, depth: int = 0,
        n_threads: int = 0, n_producers: int = 0,
    ) -> bool:
        """The full overlapped streaming-compaction front end (BASELINE
        config #5 shape): encrypted op-file blobs in → folded ``state``
        out, with the host stages running CONCURRENTLY with the fold.

        ``n_producers`` worker threads (0 = the accelerator's configured
        ``stream_producers``, itself 0 = auto from the core count) claim
        **file-granular stripes** off one unified work queue
        (ops/stream.py ``run_striped_ingest_pipeline``): each stripe is
        a byte-bounded file subrange of a chunk, decrypted natively
        single-threaded (the old per-chunk decrypt thread pool is gone —
        parallelism lives entirely in the pool, never threads ×
        threads), and the worker landing a chunk's last stripe runs its
        columnar decode, while this thread columnarizes and folds
        completed chunks through a fold session (parallel/session.py —
        BUFFER / HOST_REDUCE / DEVICE_STREAM by regime; the device mode
        issues chunk H2D under the in-flight donated fold, mesh-sharded
        when the accelerator's ``sharded_stream`` route is active).  A
        sequencer re-emits chunks in chunk-index order, so the folded
        bytes are identical at any producer count and any stripe split.
        Backpressure bounds live host memory to ``depth`` chunks (0 =
        producers + 1).  On a single-core host with one producer the
        pipeline runs inline (no threads — byte-identical, minus the
        queue overhead).  Per-stage trace spans (``stream.decrypt`` /
        ``stream.decode`` / ``stream.stripe`` / ``stream.ingest`` /
        ``stream.reduce`` / ``stream.finish``, plus the fan-out's
        ``stream.producer.wait`` / ``stream.sequence`` and the
        ``stream_producers`` gauge) make the overlap auditable;
        ``bench.py --e2e-streaming`` publishes them.

        Returns False — with ``state`` untouched (sessions mutate only
        at finish) — when no session exists for this CRDT type or the
        native decoder declines; the caller replays its own copy of the
        blobs down another path.  Crypto failures (AeadError) and
        pipeline faults raise.
        """
        from ..backends.xchacha import decrypt_blobs, decrypt_blobs_packed
        from ..ops.stream import (
            run_striped_ingest_pipeline, stream_producer_count,
        )
        from .session import SessionDeclined

        session = self.open_fold_session(state, actors_hint=actors_hint)
        if session is None:
            return False
        n = len(blobs)
        if n == 0:
            return True
        if chunk_blobs <= 0:
            chunk_blobs = max(1, -(-n // max(n_chunks, 1)))
        spans = [blobs[i : i + chunk_blobs] for i in range(0, n, chunk_blobs)]

        producers = stream_producer_count(
            n_producers if n_producers > 0 else self.stream_producers
        )
        # with N > 1 every decrypt call is single-threaded: the
        # parallelism lives entirely in the producer pool's
        # file-granular stripe claiming — N cooperating decrypt lanes
        # on one unified queue, never threads × threads.  A SINGLE
        # producer keeps the native batch call's own thread pool (0 =
        # auto from the core count) — one whole-chunk stripe with no
        # pool of its own would strand a multicore box's idle cores.
        stripe_threads = n_threads if n_threads else (
            0 if producers == 1 else 1
        )

        accepts_packed = getattr(session, "accepts_packed", False)

        def split(span, k):
            """File-granular stripes: with several producers a chunk
            splits at byte boundaries so one giant op file forms its own
            stripe (one worker) while its peers decrypt the rest — a
            whole-chunk lane can no longer serialize behind it."""
            if producers == 1 or len(span) <= 1:
                return [span] if span else []
            budget = max(1, sum(len(b) for b in span) // producers)
            stripes, cur, cur_bytes = [], [], 0
            for b in span:
                cur.append(b)
                cur_bytes += len(b)
                if cur_bytes >= budget:
                    stripes.append(cur)
                    cur, cur_bytes = [], 0
            if cur:
                stripes.append(cur)
            return stripes

        def stripe(files, k, s):
            with trace.span("stream.decrypt", meta=k):
                packed = decrypt_blobs_packed(key, files, stripe_threads)
                if packed is None:
                    packed = decrypt_blobs(key, files, stripe_threads)
                # counted only AFTER the stripe's decrypt succeeded
                # (AeadError raises above) — the attribution marginals
                # must never claim bytes a failed batch never opened
                trace.add(
                    "bytes_decrypted", sum(len(b) for b in files)
                )
                return packed

        def assemble(parts, span, k):
            if not accepts_packed:
                # span-decoder-less sessions (counters, maps) take
                # per-blob views of the shared cleartext buffers
                payloads: list = []
                for part in parts:
                    if isinstance(part, tuple):
                        out, offs = part
                        view = memoryview(out)
                        lo_hi = offs.tolist()
                        payloads.extend(
                            view[int(lo_hi[i]) : int(lo_hi[i + 1])]
                            for i in range(len(lo_hi) - 1)
                        )
                    else:
                        payloads.extend(part)
                with trace.span("stream.decode", meta=k):
                    return session.decode_chunk(payloads)
            with trace.span("stream.decode", meta=k):
                # thread-safe by contract: decode never mutates the
                # session (parallel/session.py); multi-part decode
                # combines the per-stripe cleartext buffers zero-copy
                return session.decode_chunk_parts(parts)

        def reduce(decoded, k):
            session.reduce_chunk(decoded)

        try:
            run_striped_ingest_pipeline(
                spans, split, stripe, assemble, reduce,
                depth=depth, producers=producers,
            )
            with trace.span("stream.finish"):
                session.finish()
        except SessionDeclined:
            return False
        except K.PipelineError as e:
            if isinstance(e.__cause__, SessionDeclined):
                return False
            raise e.__cause__ from None
        return True

    def fold_payload_stream(self, state, chunks, actors_hint=()) -> bool:
        """ORSet bulk front end over an *iterator* of decrypted-payload
        chunks (e.g. ``xchacha.decrypt_blobs_chunked``): each chunk
        decodes while the producer decrypts the next, then all rows fold
        once.  On False the stream is closed (a generator's pending
        lookahead is cancelled at its next yield) and the caller replays
        its own copy of the payloads down the per-op path."""
        stream = self.open_payload_stream(state, actors_hint=actors_hint)
        if stream is None:
            return False
        try:
            for chunk in chunks:
                if not stream.feed(chunk):
                    return False
        finally:
            close = getattr(chunks, "close", None)
            if close is not None:
                close()
        return stream.finish()

    def open_payload_stream(self, state, actors_hint=()):
        """Incremental bulk front end: returns a stream with
        ``feed(payloads) -> bool`` (decodes one chunk; False = declined,
        nothing folded) and ``finish() -> bool`` (one combined fold into
        ``state``), or None when ``state`` has no columnar bulk path.
        ``feed`` only decodes — callers overlap it with their own decrypt
        of the next chunk (the native calls release the GIL); ``state``
        mutates only inside ``finish``.  Caller-serialized, like the fold
        sessions (parallel/session.py)."""
        if not isinstance(state, ORSet):
            return None
        return _OrsetPayloadStream(self, state, actors_hint)

    def _orset_actor_table(self, state: ORSet, actors_hint) -> list:
        """Sorted actor table for the native decoder (it binary-searches):
        the caller's hint plus every actor the state mentions.

        Callers usually pass an already-sorted hint (storage listings
        are sorted) covering every state actor; detecting that case
        skips re-sorting a set-scrambled copy — at 100k replicas the
        n·log n byte-string sort cost more than the decrypt phase."""
        import operator
        from itertools import islice

        def strictly_sorted(seq):
            # C-level pairwise compare: ~3ms at 100k vs ~10ms for an
            # index-based genexp — this sits ahead of every bulk ingest
            return all(map(operator.lt, seq, islice(seq, 1, None)))

        if (
            not state.clock.counters
            and not state.entries
            and not state.deferred
        ):
            # fresh replica (the streaming shape): the hint IS the table —
            # no set union to build, just the sorted-unique check
            hint = list(actors_hint)
            if strictly_sorted(hint):
                return hint
            return sorted(set(hint))
        actor_set = set(actors_hint)
        n_hint = len(actor_set)
        actor_set.update(state.clock.counters)
        for entry in state.entries.values():
            actor_set.update(entry)
        for dfr in state.deferred.values():
            actor_set.update(dfr)
        if len(actor_set) == n_hint and len(actors_hint) == n_hint:
            hint = list(actors_hint)
            if strictly_sorted(hint):
                return hint
        return sorted(actor_set)

    def _fold_orset_decoded(self, state: ORSet, decoded, actors_sorted) -> bool:
        kind, member_idx, actor_idx, counter, member_objs = decoded
        if len(kind) == 0:
            return True
        # vocabs: replicas in the decoder's sorted order (strictly sorted
        # ⇒ unique — skip the 100k-key eager index build); members in the
        # decoder's intern order (state members appended by planes builder)
        members = K.Vocab(member_objs)
        replicas = K.Vocab.presorted_unique(actors_sorted)
        # Vocab interning hashes member *objects*; distinct canonical bytes
        # can still collide as Python values (1 == True, 0.0 == -0.0).  A
        # collapsed vocab would leave member_idx out of range and scatter
        # ops onto the wrong member — bail to the per-op path instead.
        if len(members) != len(member_objs):
            return False
        self._fold_orset_columns(
            state, kind, member_idx, actor_idx, counter, members, replicas
        )
        return True

    def _fold_map_payloads(self, state, payloads: list, actors_hint=()) -> bool:
        """CrdtMap<orset> bulk path: native four-family decode → the
        vectorized columnar fold (ops/map_columnar.py).  Declines (per-op
        fallback) for other child types, non-shared-dot payloads, or any
        decode surprise."""
        if state.child != b"orset":
            return False
        from ..ops.map_columnar import crdtmap_fold_host, decode_map_payload_batch

        actor_set = set(actors_hint)
        actor_set.update(state.clock.counters)
        for birth in state.births.values():
            actor_set.update(birth)
        for ctx, _rm_keys in state.deferred.values():
            actor_set.update(ctx.counters)
        for child in state.vals.values():
            actor_set.update(child.clock.counters)
            for entry in child.entries.values():
                actor_set.update(entry)
            for dfr in child.deferred.values():
                actor_set.update(dfr)
        actors_sorted = sorted(actor_set)
        with trace.span("fold.map_decode"):
            decoded = decode_map_payload_batch(payloads, actors_sorted)
        if decoded is None:
            return False
        B, A, Rm, Kk, key_objs, member_objs = decoded
        keys = K.Vocab(key_objs)
        members = K.Vocab(member_objs)
        # vocab value-collision guard (1 == True etc.), as in the ORSet path
        if len(keys) != len(key_objs) or len(members) != len(member_objs):
            return False
        replicas = K.Vocab(actors_sorted)
        impl = self.map_fold_impl
        if impl is None and self._mesh_active():
            impl = "device"  # SPMD scatter phase over the mesh
        elif impl is None:
            n_rows = (
                len(B["actor"]) + len(A["actor"]) + len(Rm["actor"])
                + len(Kk["actor"])
            )
            impl = "device" if n_rows >= self.min_device_batch else "host"
        with trace.span("fold.map"):
            crdtmap_fold_host(
                state, B, A, Rm, Kk, keys, members, replicas, fold_impl=impl,
                mesh=self.mesh
                if impl == "device" and self._mesh_active()
                else None,
            )
        return True

    # -------------------------------------------- catalogue bulk front ends
    def _fold_gset_payloads(self, state, payloads: list) -> bool:
        """G-Set bulk: one msgpack unpack per file, one set update.  No
        device path — the fold IS deduplication of opaque values, which
        is exactly what hashing them into the host set does; there is no
        arithmetic to put on the MXU/VPU (docs/PARITY.md row 14)."""
        from ..utils import codec

        frozen = state._freeze
        state.members.update(
            frozen(op) for p in payloads for op in codec.unpack(p)
        )
        return True

    def _fold_lwwreg_payloads(self, state, payloads: list) -> bool:
        """LWW-Register bulk: the LWW-map cascade at K=1 — one device
        ``lww_fold`` over all writes, winner resolved against the slot
        with the host tie-break (identical total order: the columns are
        rank-interned so integer compare ≡ bytes compare)."""
        from ..models.lwwmap import LWWOp
        from ..utils import codec

        rows = [op for p in payloads for op in codec.unpack(p)]
        if not rows:
            return True
        if len(rows) < self.min_device_batch:
            for o in rows:
                state.apply(o)
            return True
        ops = [
            LWWOp(None, int(o[0]), bytes(o[1]), o[2], False) for o in rows
        ]
        cols = K.lww_ops_to_columns(ops)
        V = len(cols.values_sorted)
        num_values = V if len(cols.actors_sorted) * V < 2**31 else None
        m_hi, m_lo, m_actor, m_value, present = K.lww_fold(
            cols.key, cols.ts_hi, cols.ts_lo, cols.actor, cols.value,
            num_keys=1, num_values=num_values,
        )
        if not bool(np.asarray(present)[0]):
            return True
        ts = (int(np.asarray(m_hi)[0]) << 31) | int(np.asarray(m_lo)[0])
        actor = cols.actors_sorted[int(np.asarray(m_actor)[0])]
        value = cols.values_sorted[int(np.asarray(m_value)[0])]
        state._take(ts, actor, value)
        return True

    def _fold_mvreg_payloads(self, state, payloads: list) -> bool:
        """MVReg bulk fold: ops are (clock, value) candidates; iterated
        strict-dominance apply equals the global anti-chain (dominance is
        transitive), so one ``mvreg_dominance_keep`` call replaces the
        per-op loop — the same argument ``_merge_mvregs`` documents."""
        from ..models.vclock import VClock as VC
        from ..utils import codec

        pairs = list(state.vals)
        n_ops = 0
        for p in payloads:
            for obj in codec.unpack(p):
                pairs.append((VC.from_obj(obj[0]), obj[1]))
                n_ops += 1
        if n_ops == 0:
            return True
        if n_ops + len(state.vals) < self.min_device_batch:
            from ..models.mvreg import MVRegOp

            for c, v in pairs[len(state.vals):]:
                state.apply(MVRegOp(c, v))
            return True
        self._mvreg_antichain(state, pairs)
        return True

    def _fold_seqlist_payloads(self, state, payloads: list) -> bool:
        """SeqList bulk: whole-file unpack, vectorized-enough host apply.
        No device kernel: the state is an order-keyed tree of opaque
        idents (Logoot paths) — resolving it is pointer/compare work on
        variable-length paths with no dense tensor shape
        (docs/PARITY.md row 14)."""
        from ..models.seqlist import op_from_obj
        from ..utils import codec

        for p in payloads:
            for obj in codec.unpack(p):
                state.apply(op_from_obj(obj))
        return True

    def _fold_merklereg_payloads(self, state, payloads: list) -> bool:
        """MerkleReg bulk: whole-file unpack + apply.  No device kernel:
        the fold is hash-DAG bookkeeping (parent links, head set), not
        arithmetic (docs/PARITY.md row 14)."""
        from ..models.merkle_reg import MerkleNode
        from ..utils import codec

        for p in payloads:
            for obj in codec.unpack(p):
                state.apply(MerkleNode.from_obj(obj))
        return True

    def _fold_counter_payloads(self, state, payloads: list, actors_hint=()) -> bool:
        """Counter bulk path: native decode straight to (sign, actor,
        counter) columns, one segment-max fold.  Dots are monotone per
        actor, so max-folding whole files at once equals per-op apply."""
        from ..ops.native_decode import decode_counter_payload_batch

        clocks = (
            (state.p.clock, state.n.clock)
            if isinstance(state, PNCounter)
            else (state.clock,)
        )
        actor_set = set(actors_hint)
        for c in clocks:
            actor_set.update(c.counters)
        actors_sorted = sorted(actor_set)
        decoded = decode_counter_payload_batch(payloads, actors_sorted)
        if decoded is None:
            return False
        sign, actor_idx, counter = decoded
        if len(sign) == 0:
            return True
        if isinstance(state, GCounter) and np.any(sign != POS):
            return False  # PN-shaped rows in a G-Counter state
        self._fold_counter_dense(
            state, K.CounterColumns(sign, actor_idx, counter, K.Vocab(actors_sorted))
        )
        return True

    def _pad_counter_cols(self, cols, num_replicas: int):
        n = len(cols.sign)
        padn = self._round_to(_bucket(n), self._dp()) - n
        if padn:
            cols.sign = np.concatenate([cols.sign, np.zeros(padn, np.int8)])
            cols.actor = np.concatenate(
                [cols.actor, np.full(padn, num_replicas, np.int32)]
            )
            cols.counter = np.concatenate([cols.counter, np.zeros(padn, np.int32)])
        return cols

    def _fold_counter_dense(self, state, cols):
        """Shared tail for every counter fold: fix the replica vocab (state
        actors included), pad the columns, run the kernel, write the dense
        clocks back to the sparse state."""
        replicas = cols.replicas
        clocks = (
            (state.p.clock, state.n.clock)
            if isinstance(state, PNCounter)
            else (state.clock,)
        )
        for c in clocks:
            for a in c.counters:
                replicas.intern(a)
        R = len(replicas)
        if R == 0:
            return state
        self._pad_counter_cols(cols, R)
        sharded = self._mesh_active()
        if sharded:
            from . import mesh as pmesh
        if isinstance(state, PNCounter):
            p0 = K.vclock_to_dense(state.p.clock, replicas)
            n0 = K.vclock_to_dense(state.n.clock, replicas)
            if sharded:
                p, n, _ = pmesh.pncounter_fold_sharded(
                    self.mesh, p0, n0, cols.sign, cols.actor, cols.counter
                )
            else:
                p, n, _ = K.pncounter_fold(
                    p0, n0, cols.sign, cols.actor, cols.counter, num_replicas=R
                )
            state.p.clock = K.dense_to_vclock(np.asarray(p), replicas)
            state.n.clock = K.dense_to_vclock(np.asarray(n), replicas)
        else:
            clock0 = K.vclock_to_dense(state.clock, replicas)
            if sharded:
                clock, _ = pmesh.gcounter_fold_sharded(
                    self.mesh, clock0, cols.actor, cols.counter
                )
            else:
                clock, _ = K.gcounter_fold(
                    clock0, cols.actor, cols.counter, num_replicas=R
                )
            state.clock = K.dense_to_vclock(np.asarray(clock), replicas)
        return state

    def _fold_gcounter(self, state: GCounter, ops: list) -> GCounter:
        return self._fold_counter_dense(state, K.counter_ops_to_columns(ops))

    def _fold_pncounter(self, state: PNCounter, ops: list) -> PNCounter:
        return self._fold_counter_dense(state, K.counter_ops_to_columns(ops))

    def _fold_lww(self, state: LWWMap, ops: list) -> LWWMap:
        cols = K.lww_ops_to_columns(ops)
        Kn = len(cols.keys)
        if Kn == 0:
            return state
        n = len(cols.key)
        padn = self._round_to(_bucket(n), self._dp()) - n
        key_col, hi, lo, actor_col, value_col = (
            cols.key,
            cols.ts_hi,
            cols.ts_lo,
            cols.actor,
            cols.value,
        )
        if padn:
            key_col = np.concatenate([key_col, np.full(padn, Kn, np.int32)])
            hi = np.concatenate([hi, np.zeros(padn, np.int32)])
            lo = np.concatenate([lo, np.zeros(padn, np.int32)])
            actor_col = np.concatenate([actor_col, np.zeros(padn, np.int32)])
            value_col = np.concatenate([value_col, np.zeros(padn, np.int32)])
        if self._mesh_active():
            from . import mesh as pmesh

            m_hi, m_lo, m_actor, m_value, present = pmesh.lww_fold_sharded(
                self.mesh, key_col, hi, lo, actor_col, value_col, num_keys=Kn
            )
        else:
            # pack (actor, value) into one cascade when the rank product fits
            V = len(cols.values_sorted)
            num_values = V if len(cols.actors_sorted) * V < 2**31 else None
            if self._lww_pallas_eligible(num_values, hi, len(key_col)):
                from ..ops.pallas_lww import (
                    lww_column_maxima, lww_fold_pallas, lww_limbs,
                    lww_tile_cap,
                )

                # maxima on the UNPADDED columns, computed once (the pad
                # rows are zeros and cannot raise them); the limb counts
                # are quantized to their 1-4 range, so varying batches
                # draw from ≤ 64 static tuples — recompiles stay bounded
                maxima = lww_column_maxima(
                    cols.ts_hi, cols.ts_lo, cols.actor, num_values
                )
                m_hi, m_lo, m_actor, m_value, present = lww_fold_pallas(
                    key_col, hi, lo, actor_col, value_col,
                    num_keys=Kn, num_values=num_values,
                    tile_cap=lww_tile_cap(key_col, Kn),
                    # static limb counts from the batch's host-side maxima:
                    # the in-kernel per-chunk limb conds measured 4x slower
                    limbs=lww_limbs(hi, lo, actor_col, num_values,
                                    maxima=maxima),
                )
            else:
                m_hi, m_lo, m_actor, m_value, present = K.lww_fold(
                    key_col, hi, lo, actor_col, value_col,
                    num_keys=Kn, num_values=num_values,
                )
        m_hi = np.asarray(m_hi)
        m_lo = np.asarray(m_lo)
        m_actor = np.asarray(m_actor)
        m_value = np.asarray(m_value)
        present = np.asarray(present)
        # winner rows → tombstone lookup (vectorized over the batch)
        ki = cols.key
        win = (
            (cols.ts_hi == m_hi[ki])
            & (cols.ts_lo == m_lo[ki])
            & (cols.actor == m_actor[ki])
            & (cols.value == m_value[ki])
        )
        tomb_by_key = np.zeros(Kn, bool)
        np.maximum.at(tomb_by_key, ki[win], cols.tombstone[win])

        # vectorized writeback: materialize all winner entries in bulk
        # (batched .tolist() conversions, no per-key state.apply / LWWOp),
        # then resolve against existing entries — the host tie-break runs
        # only on actual key collisions
        from ..models.lwwmap import _wins

        idx = np.flatnonzero(present)
        ts64 = (m_hi[idx].astype(np.int64) << 31) | m_lo[idx]
        items = cols.keys.items
        actors, values = cols.actors_sorted, cols.values_sorted
        tombs = tomb_by_key[idx].tolist()
        new_entries = {
            items[k]: [
                t,
                actors[a],
                None if tomb else values[v],
                tomb,
            ]
            for k, t, a, v, tomb in zip(
                idx.tolist(),
                ts64.tolist(),
                m_actor[idx].tolist(),
                m_value[idx].tolist(),
                tombs,
            )
        }
        entries = state.entries
        if not entries:
            state.entries = new_entries
        else:
            for key_obj, new in new_entries.items():
                cur = entries.get(key_obj)
                if cur is None or _wins(*new, *cur):
                    entries[key_obj] = new
        return state

    # --------------------------------------------------------- merge_states
    def merge_states(self, state, others: list):
        if not others:
            return state
        if isinstance(state, ORSet):
            if self._mesh_active():
                return self._merge_orsets_sharded(state, others)
            if len(others) + 1 >= 3:
                return self._merge_orsets(state, others)
        from ..models import MVReg

        if isinstance(state, MVReg):
            total = len(state.vals) + sum(len(o.vals) for o in others)
            if total >= self.min_device_batch:
                return self._merge_mvregs(state, others)
        return super().merge_states(state, others)

    def _merge_mvregs(self, state, others: list):
        """Batched MVReg snapshot merge: the global anti-chain of every
        candidate (clock, value) pair via ONE dominance-filter kernel
        call, instead of S sequential pairwise merges.  Equivalent
        because each input register is already an anti-chain and
        domination is transitive, so iterated pairwise merging and the
        global filter both keep exactly the pairs no other pair strictly
        dominates; identical duplicates never dominate each other
        (strict filter) and collapse in canonicalization."""
        pairs = list(state.vals)
        for o in others:
            pairs.extend(o.vals)
        return self._mvreg_antichain(state, pairs)

    def _mvreg_antichain(self, state, pairs: list):
        """Write the global strict-dominance anti-chain of ``pairs`` into
        ``state`` via one ``mvreg_dominance_keep`` kernel call."""
        replicas = K.Vocab()
        for c, _ in pairs:
            for a in c.counters:
                replicas.intern(a)
        R, V = len(replicas), len(pairs)
        if R == 0 or V <= 1:  # empty clocks: dedup is all there is
            state.vals = pairs
            state._canonicalize()
            return state
        # bucket-pad both axes so repeated merges reuse the compiled
        # program: zero rows are masked out via `valid`, zero columns are
        # inert (elementwise comparisons on equal zeros)
        Vp = self._round_to(_bucket(V), self._dp())
        clocks = np.zeros((Vp, _bucket(R)), np.int32)
        for i, (c, _) in enumerate(pairs):
            for a, n in c.counters.items():
                clocks[i, replicas.intern(a)] = n
        valid = np.zeros(len(clocks), bool)
        valid[:V] = True
        if self._mesh_active():
            from . import mesh as pmesh

            keep = np.asarray(
                pmesh.mvreg_keep_sharded(self.mesh, clocks, valid)
            )
        else:
            keep = np.asarray(K.mvreg_dominance_keep(clocks, valid))
        state.vals = [pairs[i] for i in np.flatnonzero(keep[:V])]
        state._canonicalize()
        return state

    def _merge_orsets_sharded(self, state: ORSet, others: list) -> ORSet:
        """Pairwise SPMD merges with planes sharded over mp — elementwise
        work only, so each pair is one shard_map with no collectives."""
        from . import mesh as pmesh

        mesh = self.mesh
        mp = mesh.shape["mp"]
        members, replicas = K.Vocab(), K.Vocab()
        all_states = [state] + list(others)
        for s in all_states:
            K.orset_scan_vocab(s, members, replicas)
        E, R = len(members), len(replicas)
        if E == 0 or R == 0:
            return super().merge_states(state, others)
        E_pad = self._round_to(E, mp)

        def planes(s):
            clock, add, rm = K.orset_state_to_planes(
                s, members, replicas, scanned=True
            )
            if E_pad != E:
                z = np.zeros((E_pad - E, R), add.dtype)
                add = np.concatenate([add, z])
                rm = np.concatenate([rm, z])
            return clock, add, rm

        acc = planes(state)
        for other in others:
            acc = pmesh.orset_merge_sharded(mesh, *acc, *planes(other))
        clock, add, rm = (np.asarray(x) for x in acc)
        merged = K.orset_planes_to_state(
            clock, add[:E], rm[:E], members, replicas
        )
        state.clock = merged.clock
        state.entries = merged.entries
        state.deferred = merged.deferred
        self._note_orset_writeback(state)
        return state

    def _merge_orsets(self, state: ORSet, others: list) -> ORSet:
        members, replicas = K.Vocab(), K.Vocab()
        all_states = [state] + list(others)
        for s in all_states:
            K.orset_scan_vocab(s, members, replicas)  # cheap vocab-only pass
        if len(members) == 0 or len(replicas) == 0:
            return state
        planes = [
            K.orset_state_to_planes(s, members, replicas, scanned=True)
            for s in all_states
        ]
        clocks = np.stack([p[0] for p in planes])
        adds = np.stack([p[1] for p in planes])
        rms = np.stack([p[2] for p in planes])
        E, R = len(members), len(replicas)
        if self.bucket_vocab:
            # merge at power-of-two (S, E, R) classes: all-zero states are
            # the merge identity and zero vocab lanes are inert, so the
            # padded tree merge is byte-equal after the slice back — and a
            # population of small states shares one compiled merge set
            S = len(all_states)
            Sp, Ep, Rp = _bucket(S, 2), _bucket(E), _bucket(R)
            if (Sp, Ep, Rp) != (S, E, R):
                pad = ((0, Sp - S), (0, Ep - E), (0, Rp - R))
                clocks = np.pad(clocks, (pad[0], pad[2]))
                adds = np.pad(adds, pad)
                rms = np.pad(rms, pad)
        clock, add, rm = K.orset_merge_many(clocks, adds, rms)
        clock = np.asarray(clock)[:R]
        add = np.asarray(add)[:E, :R]
        rm = np.asarray(rm)[:E, :R]
        merged = K.orset_planes_to_state(clock, add, rm, members, replicas)
        state.clock = merged.clock
        state.entries = merged.entries
        state.deferred = merged.deferred
        self._note_orset_writeback(state)
        return state


class _OrsetPayloadStream:
    """Incremental ORSet bulk front end (``TpuAccelerator.open_payload_
    stream``): per-chunk native span decode, one combined intern + fold at
    ``finish``.  The product's bulk ingest feeds chunks as its decrypt
    lookahead lands (core.py ``_read_remote_ops_bulk``); the state is
    untouched until ``finish`` returns True, so a declined or abandoned
    stream leaves the replica exactly as it was."""

    def __init__(self, accel: TpuAccelerator, state: ORSet, actors_hint=()):
        self.accel = accel
        self.state = state
        self.actors_sorted = accel._orset_actor_table(state, actors_hint)
        self.parts: list = []
        self.declined = False
        self._finished = False
        # actor-table + native hash index, built once per stream (the
        # table is fixed for the stream's life) and reused across feeds
        self._decode_cache: dict = {}

    def feed(self, payloads: list) -> bool:
        """Decode one chunk of decrypted payloads.  False = the native
        decoder declined (unknown actor, non-canonical encoding); the
        stream is dead and the caller replays through the per-op path."""
        from ..ops.native_decode import decode_orset_payload_spans

        assert not self._finished, "stream already finished"
        if self.declined:
            return False
        if not payloads:
            return True
        with trace.span("fold.decode"):
            part = decode_orset_payload_spans(
                payloads, self.actors_sorted, cache=self._decode_cache
            )
        if part is None:
            self.declined = True
            return False
        self.parts.append(part)
        return True

    def finish(self) -> bool:
        """Combine every fed chunk and fold into the state (the only
        mutation).  False = vocab collision; state untouched."""
        from ..ops.native_decode import combine_orset_spans

        assert not self._finished, "stream already finished"
        assert not self.declined, "stream was declined"
        self._finished = True
        if not self.parts:
            return True
        with trace.span("fold.decode"):
            decoded = combine_orset_spans(self.parts)
        self.parts = []
        return self.accel._fold_orset_decoded(
            self.state, decoded, self.actors_sorted
        )

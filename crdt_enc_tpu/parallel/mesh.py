"""Distributed fold/merge: SPMD over a device mesh.

The scale-out story (SURVEY.md §2.3): op batches shard across the ``dp``
axis (each device folds its slice of the flattened op rows) and the state
planes shard across the ``mp`` axis (each device owns a contiguous member
range of the (E, R) matrices — the "tensor parallel" analogue).  Because the
fold is an elementwise-max semigroup, cross-device combination is a single
``jax.lax.pmax`` over ``dp`` riding ICI — no parameter servers, no NCCL,
exactly XLA collectives (the reference has no distributed backend at all;
its transport is the synced filesystem, which this keeps untouched).

Works on any mesh JAX can build: the one real TPU chip (1×1), a virtual
8-CPU-device mesh in tests, or a multi-host TPU slice (devices spanning
hosts — ``jax.distributed`` handles DCN bootstrap; the collectives here are
oblivious to the host boundary).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import ops as K
from ..ops.columnar import KIND_ADD, KIND_RM
from ..ops.counters import sum_wide
from ..utils import trace

# jax < 0.5 ships shard_map under experimental only, with the replication
# check named check_rep instead of check_vma; this module-local shim (the
# only shard_map entry point in the repo) translates — without patching
# the jax namespace, which other libraries feature-detect.
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _experimental_sm

    def _shard_map(f, **kw):
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        return _experimental_sm(f, **kw)


def parse_mesh_spec(spec: str) -> tuple[int, int]:
    """``"dp=N[,mp=M]"`` → ``(dp, mp)``.  The ONE parser behind every
    ``--mesh`` CLI flag (bench.py, tools/daemon) — raises ``ValueError``
    on malformed specs, non-positive axes, or a single-device mesh
    (``dp·mp < 2``: a size-1 "mesh" silently degrades to the unsharded
    path, which a flag asking for sharding must never do)."""
    dp, mp = 1, 1
    for part in spec.split(","):
        k, _, v = part.partition("=")
        if k == "dp":
            dp = int(v)
        elif k == "mp":
            mp = int(v)
        else:
            raise ValueError(f"unknown mesh axis {k!r} (want dp=N[,mp=M])")
    if dp < 1 or mp < 1 or dp * mp < 2:
        raise ValueError(
            f"mesh wants positive axes and at least 2 devices, got "
            f"dp={dp},mp={mp}"
        )
    return dp, mp


def make_mesh(shape: tuple[int, int] = None, devices=None) -> Mesh:
    """A (dp, mp) mesh over the available devices; defaults to all devices
    on the dp axis."""
    devices = devices if devices is not None else jax.devices()
    if shape is None:
        shape = (len(devices), 1)
    dp, mp = shape
    arr = np.asarray(devices[: dp * mp]).reshape(dp, mp)
    return Mesh(arr, axis_names=("dp", "mp"))


def mesh_for_population(n_lanes: int, devices=None) -> Mesh | None:
    """The population runner's mesh (sim/population.py): schedule×tenant
    lanes ride the ``dp`` axis — a lane's tenants are just more rows in
    the PR-14 tenant mega-fold, since schedules never interact — and the
    replica planes ride ``mp``.  dp gets the device majority (lanes
    outnumber the per-tenant replica-plane width in every population
    shape), mp takes what cleanly remains: dp = min(n_lanes, D) and
    mp = D // dp when that divides, else a flat (D, 1).  Returns None on
    a single-device host — the unsharded path IS the single-chip layout,
    and a size-1 mesh must not pretend otherwise (parse_mesh_spec
    enforces the same rule for explicit specs)."""
    devices = devices if devices is not None else jax.devices()
    n_dev = len(devices)
    if n_dev < 2:
        return None
    dp = max(1, min(int(n_lanes), n_dev))
    mp = n_dev // dp if n_dev % dp == 0 else 1
    return make_mesh((dp, mp), devices=devices[: dp * mp])


def _local_fold(clock0, add0, rm0, kind, member, actor, counter, member_lo, R,
                impl="xla", tile_cap=0, interpret=False, retire_rm=True):
    """Per-device body: fold this device's op rows into its member slice.

    ``member_lo`` is the first global member index of this device's slice;
    rows outside the slice are masked (they belong to a different mp shard).
    ``add0``/``rm0`` arrive as this device's (E_local, R) slice.

    ``impl="pallas"`` runs the scatter phase through the flagship ablk
    kernel (ops/pallas_fold.py orset_scatter_pallas) — a mesh compaction
    then executes the same kernel a single chip does; the dp-pmax
    combine and normalize tail are identical either way.

    ``retire_rm=False`` keeps remove horizons un-retired, exactly as in
    ``ops.orset.orset_fold``: required when the planes are a PARTIAL
    reduction (the sharded streaming fold) combined with a pre-existing
    state later — a horizon retired against the batch-local clock would
    lose its kill-effect on state entries it never met.
    """
    E_local = add0.shape[0]
    pad = actor >= R
    local_member = member - member_lo
    in_slice = (local_member >= 0) & (local_member < E_local)
    is_add = (kind == KIND_ADD) & ~pad & in_slice
    is_rm = (kind == KIND_RM) & ~pad & in_slice
    actor_ix = jnp.minimum(actor, R - 1)
    member_ix = jnp.clip(local_member, 0, E_local - 1)

    if impl == "pallas":
        from ..ops.pallas_fold import orset_scatter_pallas

        # out-of-slice rows become padding for this shard's kernel
        shard_actor = jnp.where(in_slice & ~pad, actor, R)
        add_new, rm_new = orset_scatter_pallas(
            kind, member_ix, shard_actor, counter,
            num_members=E_local, num_replicas=R, tile_cap=tile_cap,
            interpret=interpret,
        )
    else:
        seg = member_ix * R + actor_ix
        add_new = jax.ops.segment_max(
            jnp.where(is_add, counter, 0), seg, num_segments=E_local * R
        )
        rm_new = jax.ops.segment_max(
            jnp.where(is_rm, counter, 0), seg, num_segments=E_local * R
        )
        add_new = jnp.maximum(add_new, 0).reshape(E_local, R)
        rm_new = jnp.maximum(rm_new, 0).reshape(E_local, R)
    # cell-level replay gate (≡ row gating by per-actor dot monotonicity;
    # see ops/orset.py) — avoids a per-row clock gather on every shard
    add_new = jnp.where(add_new > clock0[None, :], add_new, 0)
    clock_new = jnp.maximum(
        jax.ops.segment_max(
            jnp.where((kind == KIND_ADD) & ~pad, counter, 0),
            actor_ix,
            num_segments=R,
        ),
        0,
    )

    # combine partials across the dp axis: max is the whole merge
    add_new = jax.lax.pmax(add_new, "dp")
    rm_new = jax.lax.pmax(rm_new, "dp")
    clock_new = jax.lax.pmax(clock_new, "dp")

    clock = jnp.maximum(clock0, clock_new)
    add = jnp.maximum(add0, add_new)
    rm = jnp.maximum(rm0, rm_new)
    add = jnp.where(add > rm, add, 0)
    if retire_rm:
        rm = jnp.where(rm > clock[None, :], rm, 0)
    return clock, add, rm


def orset_fold_sharded(
    mesh: Mesh,
    clock0,
    add0,
    rm0,
    kind,
    member,
    actor,
    counter,
    impl: str = "xla",
    tile_cap: int = 0,
    interpret: bool = False,
    retire_rm: bool = True,
):
    """Sharded ORSet fold.

    Layout: op rows sharded over ``dp`` (row count must divide by dp —
    bucket-pad first); state planes sharded over ``mp`` on the member axis
    (E must divide by mp); the clock is replicated (it is O(R) and every
    shard updates it).  Returns (clock, add, rm) with the same shardings.

    ``impl="pallas"``: each shard's scatter phase runs the flagship ablk
    kernel (pass ``tile_cap`` from ``fold_cap`` over the WHOLE member
    column — it bounds every shard's tiles).

    ``retire_rm=False``: partial-reduction mode for the sharded
    streaming fold (see :func:`_local_fold`).
    """
    dp = mesh.shape["dp"]
    mp = mesh.shape["mp"]
    E, R = add0.shape
    if len(kind) % dp or E % mp:
        raise ValueError(
            f"pad first: rows {len(kind)} % dp {dp} or members {E} % mp {mp}"
        )
    if impl == "pallas" and not tile_cap:
        raise ValueError(
            "impl='pallas' requires tile_cap (fold_cap over the whole "
            "member column)"
        )
    E_local = E // mp

    def body(clock0, add0, rm0, kind, member, actor, counter, member_lo):
        return _local_fold(
            clock0, add0, rm0, kind, member, actor, counter, member_lo[0], R,
            impl=impl, tile_cap=tile_cap, interpret=interpret,
            retire_rm=retire_rm,
        )

    # each mp shard needs its global member offset
    member_lo = np.arange(mp, dtype=np.int32) * E_local

    # op rows sharded over dp; plane member-axis sharded over mp
    fold = _shard_map(
        body,
        mesh=mesh,
        in_specs=(
            P(),
            P("mp", None),
            P("mp", None),
            P("dp"),
            P("dp"),
            P("dp"),
            P("dp"),
            P("mp"),
        ),
        out_specs=(P(), P("mp", None), P("mp", None)),
        check_vma=False,
    )
    return fold(clock0, add0, rm0, kind, member, actor, counter, member_lo)


def orset_merge_sharded(mesh: Mesh, clock_a, add_a, rm_a, clock_b, add_b, rm_b):
    """Pairwise state merge with planes sharded over mp — pure elementwise,
    so the spec is trivial; exists to keep compaction fully SPMD."""

    merge = _shard_map(
        K.orset_merge,
        mesh=mesh,
        in_specs=(P(), P("mp", None), P("mp", None), P(), P("mp", None), P("mp", None)),
        out_specs=(P(), P("mp", None), P("mp", None)),
        check_vma=False,
    )
    return merge(clock_a, add_a, rm_a, clock_b, add_b, rm_b)


def sharded_fold_cap(member, E_pad: int, dp: int, mp: int) -> int:
    """``tile_cap`` for the pallas-sharded fold: the max op-row count over
    any (dp shard, mp slice)-local 8-member tile, bucketed to a power of
    two.  A global ``fold_cap`` does NOT bound this when ``E_pad/mp`` is
    not a multiple of 8 (shard-local tiles straddle global ones), so the
    count runs over the actual shard decomposition — dp row blocks are
    contiguous, mp slices are contiguous member ranges."""
    m = np.asarray(member, np.int64)
    if len(m) % dp:
        # padding AFTER computing the cap would shift the contiguous dp
        # block boundaries and silently undercount a shard's tiles
        raise ValueError(
            f"pad rows to a dp={dp} multiple BEFORE computing the cap "
            f"(got {len(m)})"
        )
    rows_per = max(len(m) // dp, 1)
    E_local = E_pad // mp
    T = max(-(-E_local // 8), 1)
    # one pass: composite (dp block, mp slice, local tile) key per row
    s = np.minimum(m // E_local, mp - 1)
    tile = np.minimum((m - s * E_local) // 8, T - 1)
    d = np.arange(len(m)) // rows_per
    key = (d * mp + s) * T + tile
    need = int(np.bincount(key).max(initial=0)) if len(m) else 0
    cap = 256
    while cap < need:
        cap *= 2
    return cap


def pad_rows_for_mesh(cols, dp: int, num_replicas: int):
    """Pad flattened op columns so the row count divides the dp axis."""
    n = len(cols.kind)
    target = ((n + dp - 1) // dp) * dp
    return K.pad_orset_rows(cols, target, num_replicas)


# ---- sharded streaming fold ------------------------------------------------


def stream_sharding(mesh: Mesh):
    """The (rows, clock, planes) shardings of the streaming fold: op-row
    chunks over ``dp``, the clock replicated, the (E, R) planes over
    ``mp`` on the member axis."""
    return (
        NamedSharding(mesh, P("dp")),
        NamedSharding(mesh, P()),
        NamedSharding(mesh, P("mp", None)),
    )


def sharded_stream_planes(mesh: Mesh, E_pad: int, R: int):
    """Zero-seeded accumulator planes for the sharded streaming fold,
    placed with :func:`stream_sharding` (clock replicated, planes
    mp-sharded).  ``E_pad`` must divide the mp axis."""
    _, clock_s, plane_s = stream_sharding(mesh)
    clock0 = np.zeros(max(R, 1), np.int32)
    add0 = np.zeros((E_pad, R), np.int32)
    rm0 = np.zeros((E_pad, R), np.int32)
    # counted HERE, at issue (OBS001) — callers must not count again
    trace.add("h2d_bytes", clock0.nbytes + add0.nbytes + rm0.nbytes)
    clock = jax.device_put(clock0, clock_s)
    add = jax.device_put(add0, plane_s)
    rm = jax.device_put(rm0, plane_s)
    return clock, add, rm


# One compiled step per (mesh, kernel route): the streaming session calls
# this per promotion/growth, and repeated compactions over the same mesh
# must reuse the compiled program (the jax_compiles invariant) — jit
# caches per function object, so the function object itself is cached.
# BOUNDED LRU, not a weak dict: the step closure must capture the mesh
# (shard_map needs it at trace time), so a weak key would be pinned by
# its own value; eviction caps what a mesh-churning process can retain.
_STREAM_STEP_CACHE: dict = {}
_STREAM_STEP_CACHE_MAX = 8


def sharded_stream_fold_step(
    mesh: Mesh, impl: str = "xla", tile_cap: int = 0, interpret: bool = False
):
    """A donated ``(clock, add, rm), chunk → (clock, add, rm)`` step for
    the sharded streaming fold: one jitted :func:`orset_fold_sharded`
    with ``retire_rm=False`` (partial-reduction mode — the session's
    finish retires once against the true merged clock, exactly like the
    single-chip stream).  The planes are donated, so device memory stays
    at one dp-sharded chunk + one mp-sharded set of planes however long
    the stream runs."""
    key = (mesh, impl, tile_cap, interpret)
    step = _STREAM_STEP_CACHE.pop(key, None)
    if step is None:

        @partial(jax.jit, donate_argnums=(0, 1, 2))
        def step(clock, add, rm, kind, member, actor, counter):
            return orset_fold_sharded(
                mesh, clock, add, rm, kind, member, actor, counter,
                impl=impl, tile_cap=tile_cap, interpret=interpret,
                retire_rm=False,
            )

    _STREAM_STEP_CACHE[key] = step  # re-insert = mark most-recently-used
    while len(_STREAM_STEP_CACHE) > _STREAM_STEP_CACHE_MAX:
        _STREAM_STEP_CACHE.pop(next(iter(_STREAM_STEP_CACHE)))
    return step


# ---- sharded multi-tenant mega-folds --------------------------------------
#
# The serving layer's tenant batch (ops/orset.orset_fold_tenants — the
# vmapped mega-fold) as a MESH axis: tenant lanes partition over ``dp``
# (each device folds its slice of the fleet, tenants never interact so
# no cross-dp collective exists at all) and each tenant's member planes
# partition over ``mp`` (rows replicate across mp and mask to the local
# member slice — the one cross-device value, the per-tenant clock, is a
# single ``pmax`` over mp).  One multi-chip pod then serves the
# many-small-tenants shape the solo ``orset_fold_sharded`` was never
# built for: a whole bucket of tenants per dispatch, every chip busy.


def _tenant_local_fold(clock0, add0, rm0, kind, member, actor, counter,
                       member_lo, E_local, R):
    """One tenant's fold against this device's member slice.

    ``add0``/``rm0`` arrive as the tenant's (E_local, R) mp-slice; the
    tenant's op rows arrive WHOLE (replicated over mp — the tenant lives
    on one dp shard), so rows outside the slice mask out of the scatter
    but still feed the clock, exactly as in ``ops.orset.orset_fold``
    where the clock is the column max over every live add."""
    pad = actor >= R
    local_member = member - member_lo
    in_slice = (local_member >= 0) & (local_member < E_local)
    is_add = (kind == KIND_ADD) & ~pad
    is_rm = (kind == KIND_RM) & ~pad & in_slice
    actor_ix = jnp.minimum(actor, R - 1)
    member_ix = jnp.clip(local_member, 0, E_local - 1)
    seg = member_ix * R + actor_ix
    add_new = jax.ops.segment_max(
        jnp.where(is_add & in_slice, counter, 0), seg,
        num_segments=E_local * R,
    )
    rm_new = jax.ops.segment_max(
        jnp.where(is_rm, counter, 0), seg, num_segments=E_local * R
    )
    add_new = jnp.maximum(add_new, 0).reshape(E_local, R)
    rm_new = jnp.maximum(rm_new, 0).reshape(E_local, R)
    # cell-level stale-add gate (≡ ops.orset.orset_fold's)
    add_new = jnp.where(add_new > clock0[None, :], add_new, 0)
    # the clock sees EVERY live add, in-slice or not (each mp shard has
    # all the tenant's rows) — but gated against clock0 exactly as the
    # solo kernel's post-gate column max is
    clock_new = jnp.maximum(
        jax.ops.segment_max(
            jnp.where(is_add, counter, 0), actor_ix, num_segments=R
        ),
        0,
    )
    clock_new = jnp.where(clock_new > clock0, clock_new, 0)
    # combine the per-shard clocks over mp: each shard computed the full
    # clock already (rows replicate over mp), so this pmax is a no-op at
    # mp=1 and pure agreement insurance otherwise
    clock_new = jax.lax.pmax(clock_new, "mp")
    clock = jnp.maximum(clock0, clock_new)
    add = jnp.maximum(add0, add_new)
    rm = jnp.maximum(rm0, rm_new)
    add = jnp.where(add > rm, add, 0)
    rm = jnp.where(rm > clock[None, :], rm, 0)
    return clock, add, rm


def orset_fold_tenants_sharded(
    mesh: Mesh,
    clock0,  # (T, R) int32 — per-tenant state clocks
    add0,  # (T, E, R) int32 — per-tenant state planes
    rm0,  # (T, E, R) int32
    kind,  # (T, N) int8 — per-tenant op rows
    member,  # (T, N) int32
    actor,  # (T, N) int32  (== num_replicas ⇒ padding row)
    counter,  # (T, N) int32
):
    """Mesh-sharded twin of ``ops.orset.orset_fold_tenants``.

    Layout: the tenant axis shards over ``dp`` (T must divide dp — the
    serve planner quantizes bucket slots to dp multiples), each tenant's
    member axis over ``mp`` (E must divide mp — the planner lifts E
    classes to mp multiples), op rows replicated across mp.  Per-tenant
    results are byte-identical to the vmapped single-device mega-fold —
    pinned by the differential tests on the virtual 8-device mesh."""
    dp = mesh.shape["dp"]
    mp = mesh.shape["mp"]
    T, E, R = add0.shape
    if T % dp or E % mp:
        raise ValueError(
            f"pad first: tenants {T} % dp {dp} or members {E} % mp {mp}"
        )
    E_local = E // mp

    def body(c0, a0, r0, k, m, ac, ct, lo):
        def one(c, a, r, kk, mm, aa, cc):
            return _tenant_local_fold(
                c, a, r, kk, mm, aa, cc, lo[0], E_local, R
            )

        return jax.vmap(one)(c0, a0, r0, k, m, ac, ct)

    member_lo = np.arange(mp, dtype=np.int32) * E_local
    fold = _shard_map(
        body,
        mesh=mesh,
        in_specs=(
            P("dp", None),
            P("dp", "mp", None),
            P("dp", "mp", None),
            P("dp", None),
            P("dp", None),
            P("dp", None),
            P("dp", None),
            P("mp"),
        ),
        out_specs=(P("dp", None), P("dp", "mp", None), P("dp", "mp", None)),
        check_vma=False,
    )
    return fold(clock0, add0, rm0, kind, member, actor, counter, member_lo)


def gcounter_fold_tenants_sharded(
    mesh: Mesh,
    clock0,  # (T, R) int32 — per-tenant clocks
    actor,  # (T, N) int32  (== num_replicas ⇒ padding row)
    counter,  # (T, N) int32
):
    """Mesh-sharded twin of ``ops.counters.gcounter_fold_tenants``:
    tenant lanes over ``dp``, the tiny (R,) planes shard-local (they
    replicate over mp — counter tenants are plane-light by definition).
    T must divide dp."""
    from ..ops.counters import gcounter_fold

    dp = mesh.shape["dp"]
    T, R = clock0.shape
    if T % dp:
        raise ValueError(f"pad first: tenants {T} % dp {dp}")

    def body(c0, a, ct):
        def one(c, aa, cc):
            clock, _value = gcounter_fold(c, aa, cc, num_replicas=R)
            return clock

        return jax.vmap(one)(c0, a, ct)

    fold = _shard_map(
        body,
        mesh=mesh,
        in_specs=(P("dp", None), P("dp", None), P("dp", None)),
        out_specs=P("dp", None),
        check_vma=False,
    )
    return fold(clock0, actor, counter)


def tenant_plane_diff_sharded(
    mesh: Mesh,
    clock_b,  # (T, R) int32 — per-tenant BASE clocks (last sealed)
    add_b,  # (T, E, R) int32 — per-tenant BASE planes
    rm_b,  # (T, E, R) int32
    clock_n,  # (T, R) int32 — per-tenant post-fold clocks
    add_n,  # (T, E, R) int32 — per-tenant post-fold planes
    rm_n,  # (T, E, R) int32
):
    """Mesh-sharded twin of ``ops.orset.orset_plane_diff_tenants`` for
    the device-cut delta seal (docs/delta.md): tenant lanes over ``dp``,
    member slices over ``mp`` — the SAME layout the fold twin just left
    the planes in, so the diff dispatch reads them where they already
    live.  The per-cell code is embarrassingly shard-local (every bit
    condition reads one cell plus the replicated clock rows); only the
    per-tenant count crosses shards, as one ``psum`` over mp.  Same
    bucket-class law as the fold: shapes are planner-quantized, so churn
    never recompiles."""
    dp = mesh.shape["dp"]
    mp = mesh.shape["mp"]
    T, E, R = add_n.shape
    if T % dp or E % mp:
        raise ValueError(
            f"pad first: tenants {T} % dp {dp} or members {E} % mp {mp}"
        )

    def body(cb, ab, rb, cn, an, rn):
        code, count = jax.vmap(K.orset_plane_diff)(cb, ab, rb, cn, an, rn)
        return code, jax.lax.psum(count, "mp")

    diff = _shard_map(
        body,
        mesh=mesh,
        in_specs=(
            P("dp", None),
            P("dp", "mp", None),
            P("dp", "mp", None),
            P("dp", None),
            P("dp", "mp", None),
            P("dp", "mp", None),
        ),
        out_specs=(P("dp", "mp", None), P("dp")),
        check_vma=False,
    )
    return diff(clock_b, add_b, rm_b, clock_n, add_n, rm_n)


# One compiled step pair per mesh, same bounded-LRU discipline (and the
# same pinning rationale) as _STREAM_STEP_CACHE below: the serve layer
# calls these per bucket, and shape variation is already quantized by
# the planner, so jit's own shape cache stays bounded per step.
_TENANT_STEP_CACHE: dict = {}
_TENANT_STEP_CACHE_MAX = 8


def tenant_fold_steps(mesh: Mesh):
    """The jitted ``(orset_step, gcounter_step)`` pair for one mesh —
    shapes are the only statics (derived inside the trace), so a fixed
    bucket-class set compiles a fixed program set."""
    steps = _TENANT_STEP_CACHE.pop(mesh, None)
    if steps is None:

        @jax.jit
        def orset_step(clock0, add0, rm0, kind, member, actor, counter):
            return orset_fold_tenants_sharded(
                mesh, clock0, add0, rm0, kind, member, actor, counter
            )

        @jax.jit
        def gcounter_step(clock0, actor, counter):
            return gcounter_fold_tenants_sharded(mesh, clock0, actor, counter)

        steps = (orset_step, gcounter_step)
    _TENANT_STEP_CACHE[mesh] = steps  # re-insert = mark most-recently-used
    while len(_TENANT_STEP_CACHE) > _TENANT_STEP_CACHE_MAX:
        _TENANT_STEP_CACHE.pop(next(iter(_TENANT_STEP_CACHE)))
    return steps


def tenant_diff_step(mesh: Mesh):
    """The jitted plane-diff step for one mesh — same bounded-LRU cache
    and bucket-class pinning as :func:`tenant_fold_steps` (the two share
    the dict; diff entries key on ``(mesh, "diff")``)."""
    key = (mesh, "diff")
    step = _TENANT_STEP_CACHE.pop(key, None)
    if step is None:

        @jax.jit
        def diff_step(clock_b, add_b, rm_b, clock_n, add_n, rm_n):
            return tenant_plane_diff_sharded(
                mesh, clock_b, add_b, rm_b, clock_n, add_n, rm_n
            )

        step = diff_step
    _TENANT_STEP_CACHE[key] = step
    while len(_TENANT_STEP_CACHE) > _TENANT_STEP_CACHE_MAX:
        _TENANT_STEP_CACHE.pop(next(iter(_TENANT_STEP_CACHE)))
    return step


# ---- counters -------------------------------------------------------------


def pncounter_fold_sharded(mesh: Mesh, p0, n0, sign, actor, counter):
    """PN-Counter fold with op rows sharded over ``dp`` (pad row count to
    a dp multiple with ``actor == R`` sentinels first).  The (R,) planes
    are replicated — they are tiny next to the batch — and the cross-
    device combine is one ``pmax``, the same shape as the ORSet fold's."""
    R = len(p0)
    dp = mesh.shape["dp"]
    if len(sign) % dp:
        raise ValueError(f"pad first: rows {len(sign)} % dp {dp}")

    def body(p0, n0, sign, actor, counter):
        p, n, _ = K.pncounter_fold(
            jnp.zeros_like(p0), jnp.zeros_like(n0), sign, actor, counter,
            num_replicas=R,
        )
        p = jnp.maximum(p0, jax.lax.pmax(p, "dp"))
        n = jnp.maximum(n0, jax.lax.pmax(n, "dp"))
        return p, n, sum_wide(p) - sum_wide(n)

    fold = _shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), P(), P("dp"), P("dp"), P("dp")),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    return fold(p0, n0, sign, actor, counter)


def gcounter_fold_sharded(mesh: Mesh, clock0, actor, counter):
    """G-Counter fold sharded over ``dp`` (see pncounter_fold_sharded)."""
    sign = np.zeros(len(actor), np.int8)
    p, _, total = pncounter_fold_sharded(
        mesh, clock0, jnp.zeros_like(clock0), sign, actor, counter
    )
    return p, total  # n-plane is zero, so the pn value IS the sum


# ---- LWW ------------------------------------------------------------------


def lww_fold_sharded(mesh: Mesh, key, ts_hi, ts_lo, actor, value, *, num_keys: int):
    """LWW-map fold with write rows sharded over ``dp``.

    Each device selects its shard's per-key winners (``lww_fold``), then
    the winner tables combine across ``dp`` with the same lexicographic
    order evaluated **elementwise** on an ``all_gather`` of the (K,)-sized
    tables (``lww_table_merge``) — dense per-key state moves once, rows
    never do, and the cross-shard combine never touches the scatter path.
    Row count must divide dp (pad with ``key == num_keys`` sentinel
    rows)."""
    Kk = num_keys
    dp = mesh.shape["dp"]
    if len(key) % dp:
        raise ValueError(f"pad first: rows {len(key)} % dp {dp}")

    def body(key, ts_hi, ts_lo, actor, value):
        local = K.lww_fold(key, ts_hi, ts_lo, actor, value, num_keys=Kk)
        # gather every shard's winner table ((dp, K) per column) and
        # lex-reduce across the dp axis — pure VPU work, no re-scatter
        g = tuple(jax.lax.all_gather(x, "dp") for x in local)
        acc = tuple(x[0] for x in g)
        for i in range(1, dp):
            acc = K.lww_table_merge(tuple(x[i] for x in g), acc)
        return acc

    fold = _shard_map(
        body,
        mesh=mesh,
        in_specs=(P("dp"),) * 5,
        out_specs=(P(),) * 5,
        check_vma=False,
    )
    return fold(key, ts_hi, ts_lo, actor, value)


# ---- CrdtMap --------------------------------------------------------------


def crdtmap_scatter_sharded(
    mesh: Mesh,
    clock0, births0, cclk0, cadd0, crm0, key_of_pair,
    b_rows, k_rows, a_rows, r_rows,
    *, num_groups: int,
):
    """Sharded CrdtMap scatter phase: the four row families shard over
    ``dp`` (each padded to a dp multiple with ``actor == R`` sentinels);
    the key/pair planes are replicated — map workloads are row-heavy and
    plane-light (NK·R and NP·R are bounded by the touched vocabulary,
    not the batch), the opposite regime from the ORSet fold's mp axis.
    Each scatter combines across dp with one ``pmax`` (``pmin`` for the
    remove-group gate) inside ops/map_device.crdtmap_scatter_phase."""
    from ..ops.map_device import crdtmap_scatter_phase

    dp = mesh.shape["dp"]
    for fam in (b_rows, k_rows, a_rows, r_rows):
        if len(fam[0]) % dp:
            raise ValueError(f"pad row families to dp={dp} multiples first")
    NK, R = births0.shape
    NP = cadd0.shape[0]

    def body(c0, b0, cc0, ca0, cr0, kop, *rows):
        b = rows[0:3]
        k = rows[3:7]
        a = rows[7:11]
        r = rows[11:16]
        return crdtmap_scatter_phase(
            c0, b0, cc0, ca0, cr0, kop, *b, *k, *a, *r,
            num_keys=NK, num_pairs=NP, num_replicas=R,
            num_groups=num_groups, axis_name="dp",
        )

    n_rows = 3 + 4 + 4 + 5
    fold = _shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), P(), P()) + (P("dp"),) * n_rows,
        out_specs=(P(),) * 6,
        check_vma=False,
    )
    return fold(
        clock0, births0, cclk0, cadd0, crm0, key_of_pair,
        *b_rows, *k_rows, *a_rows, *r_rows,
    )


# ---- MVReg ----------------------------------------------------------------


def mvreg_keep_sharded(mesh: Mesh, clocks, valid):
    """Sharded MVReg dominance filter: candidate rows shard over ``dp``
    (pad V to a dp multiple with invalid rows); each device all_gathers
    the full candidate set (V·R is small — clocks, not payloads) and
    filters its slice, so the O(V²R) compare matrix is split V/dp ways.
    Same contract as ops/mvreg.mvreg_dominance_keep."""
    dp = mesh.shape["dp"]
    V, R = clocks.shape
    if V % dp:
        raise ValueError(f"pad candidates {V} to a dp={dp} multiple first")

    def body(c_slice, v_slice):
        full_c = jax.lax.all_gather(c_slice, "dp", tiled=True)  # (V, R)
        full_v = jax.lax.all_gather(v_slice, "dp", tiled=True)  # (V,)
        ge = jnp.all(full_c[:, None, :] >= c_slice[None, :, :], axis=-1)
        gt = jnp.any(full_c[:, None, :] > c_slice[None, :, :], axis=-1)
        dominated = jnp.any((ge & gt) & full_v[:, None], axis=0)
        return v_slice & ~dominated

    keep = _shard_map(
        body,
        mesh=mesh,
        in_specs=(P("dp", None), P("dp")),
        out_specs=P("dp"),
        check_vma=False,
    )
    return keep(clocks, valid)

"""Chunked fold sessions: bounded-memory, transfer-aware bulk ingestion.

A session consumes decrypted op-file payloads chunk by chunk (fed by the
core's pipelined reader, core.py ``_read_remote_ops_pipelined``) and folds
them into one CRDT state with memory bounded by the chunk size — the
restructuring of the reference's consumer path (crdt-enc/src/lib.rs:471-547)
that SURVEY.md §7 hard part 3 calls for.

Three execution modes, chosen adaptively because the dominant cost changes
with regime (measured on v5e via the tunnel — see BASELINE.md):

* **BUFFER** — small ingests accumulate columns and fold once at finish
  through the accelerator's existing regime-picking tail (sparse host /
  dense device / mesh).  Promotion out of BUFFER happens the moment the
  accumulated column bytes exceed ``BUFFER_BYTES``, so memory stays small.
* **HOST_REDUCE** — when the dense state planes are small relative to the
  row stream (``3·E·R·4 ≪ N·13``), shipping every row to the device is
  pure transfer cost (the fold itself is a segment-max the host can run at
  memory bandwidth).  Each chunk reduces into persistent host planes with
  ``np.maximum.at``; ONE tiny device pass applies the batch planes to the
  state planes at finish.  This is a hierarchical fold: host does the leaf
  level on data it necessarily already holds (it just decrypted it),
  device does the combine — bytes over the interconnect drop from
  ``N·13`` to ``6·E·R·4``.
* **DEVICE_STREAM** — when the planes themselves are large (E·R beyond
  ``HOST_PLANE_CELLS``), host reduction thrashes caches and the planes,
  not the rows, dominate transfer; the planes live on device (donated
  between chunks, ops/stream.py) and fixed-shape row chunks stream
  through the compiled fold — device memory stays at one chunk + planes.
  Under an ACTIVE MESH (and the accelerator's ``sharded_stream`` toggle,
  auto-on) this mode goes SPMD (``_device_feed_sharded``): chunks
  dp-sharded, donated planes mp-sharded, per-chunk ``orset_fold_sharded``
  in partial-reduction mode — a pod compaction streams through the same
  kernels the whole-batch sharded fold runs, instead of buffering the
  entire row batch host-side.

Exactness: every mode reproduces the one-big-``orset_fold`` semantics.
HOST_REDUCE masks stale adds against the state clock captured at session
start (exactly the kernel's ``seen`` mask); DEVICE_STREAM's carried clock
only ever rejects true replays under the core's per-actor version ordering
(ops/stream.py module docs).  Byte equality vs the host loop is pinned in
tests/test_fold_session.py across all modes.
"""

from __future__ import annotations

import numpy as np

from .. import ops as K
from ..models import GCounter, ORSet, PNCounter
from ..models.counters import POS
from ..obs import runtime as obs_runtime
from ..ops.columnar import KIND_ADD, KIND_RM
from ..utils import trace

BUFFER_BYTES = 4 << 20  # promote out of BUFFER beyond this many column bytes
# host-reduce planes up to E·R = 128M cells (~1.5GB for 3 int32 planes):
# np.maximum.at runs at memory bandwidth and the combine is elementwise, so
# host reduction wins until the planes threaten host RAM — only beyond that
# is the donated-buffer device stream (bounded device memory) the answer
HOST_PLANE_CELLS = 1 << 27
DEVICE_CHUNK_ROWS = 1 << 20  # device-stream row bucket (one compile)

# Tests only: pin the DEVICE_STREAM fold's kernel choice (None = the
# backend-driven default — the Pallas route engages on real TPU).  With
# a forced True on a host backend the kernel runs in interpret mode.
FORCE_PALLAS_STREAM: bool | None = None


def _bucket(n: int, floor: int = 8) -> int:
    b = floor
    while b < n:
        b *= 2
    return b


class SessionDeclined(Exception):
    """The native decoder cannot represent this chunk (non-canonical
    encoding, vocab collision); the caller must fold it another way."""


def apply_batch_planes_host(clock0, add0, rm0, add_b, rm_b):
    """numpy mirror of :func:`crdt_enc_tpu.ops.orset.orset_apply_batch_planes`
    for small planes, where a device round-trip is pure latency.  The two
    must never diverge — tests/test_fold_session.py pins them equal on
    randomized inputs."""
    add_b = np.where(add_b > clock0[None, :], add_b, 0)
    clock = np.maximum(clock0, add_b.max(axis=0, initial=0))
    add = np.maximum(add0, add_b)
    rm = np.maximum(rm0, rm_b)
    add = np.where(add > rm, add, 0)
    rm = np.where(rm > clock[None, :], rm, 0)
    return clock, add, rm


class OrsetFoldSession:
    """Fold ORSet op-file payloads chunk by chunk into ``state``.

    Protocol: ``feed(payloads)`` per chunk (raises :class:`SessionDeclined`
    with the chunk unconsumed if the native decoder declines), then
    ``finish()`` exactly once — only finish mutates ``state``.
    """

    # decode_chunk accepts the packed ``(buffer, offsets)`` cleartext pair
    # straight from decrypt_blobs_packed (the zero-object-materialization
    # shape); sessions without span decoders take per-blob payload lists
    accepts_packed = True

    def __init__(self, accel, state: ORSet, actors_hint=()):
        from ..ops.columnar import strictly_sorted

        self.accel = accel
        self.state = state
        clock_counters = state.clock.counters
        fresh = (
            not clock_counters and not state.entries and not state.deferred
        )
        if fresh and strictly_sorted(actors_hint):
            # the streaming shape — a FRESH replica whose actor hint is
            # already the sorted table (storage listings are sorted):
            # the hint IS actors_sorted, the clock is all zeros, and the
            # Vocab index builds lazily.  The general path below cost
            # ~77ms of a ~150ms e2e streaming wall at the config-5
            # shape (100k-actor set union + sort + a 100k-iteration
            # Python clock loop + two eager index builds) — all of it
            # provably no-ops on an empty state.
            self.actors_sorted = list(actors_hint)
            self.replicas = K.Vocab.presorted_unique(self.actors_sorted)
            member_list: list = []
        else:
            # one pass over the state builds BOTH vocabularies: actors
            # via C-level set.update per entry dict, members in
            # first-appearance order (entries, then deferred) — a
            # per-dot intern walk here cost ~0.5s of every warm-open
            # tail ingest at 1M-dot states
            actor_set = set(actors_hint)
            actor_set.update(clock_counters)
            member_list = []
            for m, entry in state.entries.items():
                member_list.append(m)
                actor_set.update(entry)
            for m, dfr in state.deferred.items():
                member_list.append(m)
                actor_set.update(dfr)
            self.actors_sorted = sorted(actor_set)
            # sorted set ⇒ unique: skip the eager index build too
            self.replicas = K.Vocab.presorted_unique(self.actors_sorted)
        self.members = K.Vocab()
        for m in member_list:
            self.members.intern(m)
        self._state_members = len(self.members)
        self.R = len(self.replicas)
        # the kernel's stale-add mask is evaluated against the clock as of
        # session start for EVERY chunk — one-big-batch semantics.  Only
        # actors the clock actually mentions are visited (zeros
        # elsewhere), and none are on the fresh fast path.
        self._clock0 = np.zeros(max(self.R, 1), np.int32)
        if clock_counters:
            index = self.replicas.index
            for a, c in clock_counters.items():
                self._clock0[index[a]] = c
        self.mode = "buffer"
        self._buffered: list[tuple] = []
        self._buffered_bytes = 0
        self._member_canon: dict[int, bytes] = {}
        self._member_ids: dict[bytes, int] = {}  # wire bytes → member gid
        # actor-table flattening + native hash index, built once per
        # session and reused across chunk decodes (rebuilding per chunk
        # at 100k actors costs more than the decode itself); entries are
        # immutable, so concurrent decode_chunk threads can share it —
        # a racing double-build just writes the same value twice
        self._decode_cache: dict = {}
        self.rows_fed = 0
        # HOST_REDUCE accumulators (allocated at promotion)
        self._h_add = self._h_rm = None
        # DEVICE_STREAM carry (allocated at promotion); _d_sharded marks
        # the mesh route (planes mp-sharded, chunks dp-sharded)
        self._d_planes = None
        self._d_E = 0
        self._d_sharded = False
        self._finished = False

    # ------------------------------------------------------------------ feed
    def decode_chunk(self, payloads: list):
        """Stage 1, thread-safe (no session mutation): native columnar
        decode of one chunk's payloads.  The ctypes call releases the GIL,
        so the core decodes chunk i+1 while chunk i reduces."""
        return self.decode_chunk_parts([payloads])

    def decode_chunk_parts(self, parts: list):
        """Multi-part twin of :meth:`decode_chunk`: each element of
        ``parts`` is one stripe's cleartext — a packed ``(buffer,
        offsets)`` pair or a payload list — decoded in place and
        combined zero-copy (the striped pipeline's per-stripe decrypt
        buffers never re-join).  Thread-safe like ``decode_chunk``."""
        from ..ops.native_decode import (
            combine_orset_spans, decode_orset_payload_spans,
        )

        if len(parts) == 1 and isinstance(parts[0], tuple):
            from ..ops.device_decode import (
                decode_adds_device, device_decode_enabled,
            )

            if device_decode_enabled():
                # the CRDT_DEVICE_DECODE=1 experiment: fixed-stride
                # add-only chunks bit-twiddle on device after bulk AEAD;
                # anything else (removes, wide ints) falls through to
                # the native host decoder below (ops/device_decode.py)
                dd = decode_adds_device(parts[0], self.actors_sorted)
                if dd is not None:
                    return dd

        with trace.span("session.decode"):
            decoded_parts = []
            for payloads in parts:
                part = decode_orset_payload_spans(
                    payloads, self.actors_sorted, cache=self._decode_cache
                )
                if part is None:
                    raise SessionDeclined(
                        "native decoder declined the chunk"
                    )
                decoded_parts.append(part)
            decoded = combine_orset_spans(decoded_parts, with_bytes=True)
        return decoded

    def reduce_chunk(self, decoded) -> None:
        """Stage 2, serialized by the caller (mutates vocab + planes)."""
        assert not self._finished, "session already finished"
        member_bytes = None
        if len(decoded) == 6:
            kind, member_idx, actor_idx, counter, member_objs, \
                member_bytes = decoded
        else:
            kind, member_idx, actor_idx, counter, member_objs = decoded
        if len(kind) == 0:
            return
        with trace.span("session.remap"):
            member_global = self._remap_members(
                member_idx, member_objs, member_bytes
            )
        self.rows_fed += len(kind)
        cols = (kind, member_global, actor_idx, counter)
        if self.mode == "buffer":
            self._buffered.append(cols)
            self._buffered_bytes += len(kind) * 13
            if self._buffered_bytes > BUFFER_BYTES:
                self._promote()
        elif self.mode == "host_reduce":
            self._host_reduce(*cols)
        else:
            self._device_feed(*cols)

    def feed(self, payloads: list) -> None:
        """decode + reduce in one call (single-threaded convenience)."""
        self.reduce_chunk(self.decode_chunk(payloads))

    def _remap_members(self, member_idx, member_objs, member_bytes=None):
        """Chunk-local member interning → the session-global vocabulary.

        With ``member_bytes`` (the decoder's unique wire spans) a seen
        span is ONE bytes-dict hit — no object hashing, no re-pack: the
        per-chunk Python work drops from one intern + canonical pack per
        distinct member (measured ~30ms across the config-5 chunks) to
        effectively zero after the first chunk.  A new span pays one
        intern + pack exactly like the legacy path.

        Collision guard (both paths): distinct canonical bytes can still
        collide as Python values (1 == True, 0.0 == -0.0) — including
        ACROSS chunks or against members already in the state.  The
        dense planes cannot represent that, so each vocab slot remembers
        the canonical bytes it was first interned under and any mismatch
        declines the chunk (the per-op path then matches the host dict
        semantics exactly).  A NON-canonical wire alias of the same
        value (e.g. uint8-encoded 5) is accepted and cached per wire
        span, exactly as the legacy re-pack accepted it."""
        from ..utils import codec

        canon = self._member_canon
        if member_bytes is not None:
            # member_objs may be None (lazy mode): a new span decodes
            # HERE, once per distinct member per stream
            table = np.empty(len(member_bytes), np.int32)
            ids = self._member_ids
            for i, pk in enumerate(member_bytes):
                gid = ids.get(pk)
                if gid is None:
                    obj = (
                        codec.unpack(pk) if member_objs is None
                        else member_objs[i]
                    )
                    gid = self.members.intern(obj)
                    prev = canon.get(gid)
                    if prev is None:
                        stored = self.members.items[gid]
                        prev = codec.pack(stored)
                        canon[gid] = prev
                    if prev != pk and codec.pack(obj) != prev:
                        raise SessionDeclined("member vocab collision")
                    ids[pk] = gid
                table[i] = gid
            return table[member_idx]
        table = np.empty(len(member_objs), np.int32)
        for i, obj in enumerate(member_objs):
            gid = self.members.intern(obj)
            table[i] = gid
            pk = codec.pack(obj)
            prev = canon.get(gid)
            if prev is None:
                stored = self.members.items[gid]
                prev = pk if stored is obj else codec.pack(stored)
                canon[gid] = prev
            if prev != pk:
                raise SessionDeclined("member vocab collision")
        return table[member_idx]

    # ------------------------------------------------------------- promotion
    def _promote(self) -> None:
        """Leave BUFFER mode: pick the cheap representation for this regime
        and replay the buffered chunks through it."""
        mesh_on = getattr(self.accel, "_mesh_active", lambda: False)()
        sharded_ok = mesh_on and getattr(self.accel, "sharded_stream", False)
        if sharded_ok:
            import jax

            if jax.process_count() > 1:
                # the stream's growth and finish combine pull the
                # mp-sharded planes to host (np.asarray), which only
                # addresses LOCAL shards — on a multi-host pod that
                # raises, so those meshes keep the buffered whole-batch
                # sharded fold until a process_allgather combine lands
                sharded_ok = False
        if mesh_on and not sharded_ok:
            # mesh ingests without the sharded streaming route finish
            # through the whole-batch sharded fold — stay buffered
            # (multi-chip compaction trades host memory for SPMD
            # execution; the sharded_stream toggle removes the trade)
            return
        E_est = _bucket(max(len(self.members), 1))
        if not mesh_on and E_est * self.R <= HOST_PLANE_CELLS:
            self.mode = "host_reduce"
            self._h_add = np.zeros((E_est, self.R), np.int32)
            self._h_rm = np.zeros((E_est, self.R), np.int32)
            for cols in self._buffered:
                self._host_reduce(*cols)
        else:
            self.mode = "device_stream"
            self._d_sharded = mesh_on
            # overshoot the member capacity: every growth step recompiles
            # the donated fold for the new static shape, so fewer, larger
            # steps (the compile cache then amortizes across runs)
            self._d_E = _bucket(max(len(self.members), 1) * 4)
            # the device planes seed from ZERO, not from the state: the
            # streamed fold is a pure reduction of the op batch, combined
            # into the live state at finish with op-APPLY semantics
            # (apply_batch_planes_host — NOT the CvRDT merge, whose
            # survivor rule would misread the batch clock as state
            # history), and never reading the state here keeps this
            # thread-safe against concurrent applies — this code runs off
            # the event loop (core drain_one → to_thread)
            import jax

            if mesh_on:
                # mp-sharded planes: each device owns E_pad/mp member rows
                from . import mesh as pmesh

                mp = self.accel.mesh.shape["mp"]
                self._d_E = -(-self._d_E // mp) * mp
                # h2d_bytes counted inside sharded_stream_planes, at issue
                self._d_planes = pmesh.sharded_stream_planes(
                    self.accel.mesh, self._d_E, self.R
                )
            else:
                # the zero accumulator planes materialize ON device (an
                # XLA fill — no host buffer exists, so there is no
                # full-plane device_put to issue or count): repeated
                # read_remote rounds in one process stop re-uploading
                # plane-sized zero buffers (ISSUE-4 plane reuse)
                import jax.numpy as jnp

                self._d_planes = (
                    jnp.zeros(max(self.R, 1), jnp.int32),
                    jnp.zeros((self._d_E, self.R), jnp.int32),
                    jnp.zeros((self._d_E, self.R), jnp.int32),
                )
            for cols in self._buffered:
                self._device_feed(*cols)
        self._buffered = []
        self._buffered_bytes = 0

    def _state_planes(self, E_pad: int):
        clock0, add0, rm0 = K.orset_state_to_planes(
            self.state, self.members, self.replicas, scanned=True
        )
        E = add0.shape[0]
        if E_pad > E:
            # column count follows the CURRENT replica vocab — it may have
            # grown past self.R if a concurrent apply introduced an actor
            z = np.zeros((E_pad - E, len(self.replicas)), np.int32)
            add0 = np.concatenate([add0, z])
            rm0 = np.concatenate([rm0, z])
        return clock0, add0, rm0

    # ------------------------------------------------- host-reduce internals
    def _grow_host_planes(self) -> None:
        E_new = _bucket(len(self.members))
        if E_new * self.R > 2 * HOST_PLANE_CELLS:
            # a member-skewed stream outgrew the promotion-time estimate;
            # declining (before any mutation) keeps the bounded-memory
            # contract — the core folds the rest per-op, chunk by chunk
            raise SessionDeclined(
                "member vocabulary outgrew the host reduction planes"
            )
        grow = E_new - self._h_add.shape[0]
        if grow > 0:
            z = np.zeros((grow, self.R), np.int32)
            self._h_add = np.concatenate([self._h_add, z])
            self._h_rm = np.concatenate([self._h_rm, z])

    def _host_reduce(self, kind, member, actor, counter) -> None:
        """The leaf-level fold on host: exactly orset_fold's masked
        scatter-max (ops/orset.py:84-131).  One native linear pass
        (np.maximum.at is a buffered ufunc, ~10× slower at these scales);
        the numpy form remains as fallback."""
        if len(self.members) > self._h_add.shape[0]:
            self._grow_host_planes()
        with trace.span("session.host_reduce"):
            try:
                from .. import native

                lib = native.load()
                import ctypes

                i32p = ctypes.POINTER(ctypes.c_int32)
                i8p = ctypes.POINTER(ctypes.c_int8)
                kind_c = np.ascontiguousarray(kind, np.int8)
                member_c = np.ascontiguousarray(member, np.int32)
                actor_c = np.ascontiguousarray(actor, np.int32)
                counter_c = np.ascontiguousarray(counter, np.int32)
                clock_c = np.ascontiguousarray(self._clock0, np.int32)
                oob = lib.orset_host_reduce(
                    kind_c.ctypes.data_as(i8p),
                    member_c.ctypes.data_as(i32p),
                    actor_c.ctypes.data_as(i32p),
                    counter_c.ctypes.data_as(i32p),
                    len(kind_c),
                    clock_c.ctypes.data_as(i32p),
                    self.R,
                    self._h_add.shape[0],
                    self._h_add.ctypes.data_as(i32p),
                    self._h_rm.ctypes.data_as(i32p),
                )
                if oob:
                    raise AssertionError(
                        f"{oob} rows outside the host planes (sizing bug)"
                    )
                return
            except RuntimeError:  # native lib unavailable: numpy fallback
                pass
            valid = actor < self.R
            seen = counter <= self._clock0[np.minimum(actor, self.R - 1)]
            live_add = (kind == KIND_ADD) & valid & ~seen
            is_rm = (kind == KIND_RM) & valid
            np.maximum.at(
                self._h_add,
                (member[live_add], actor[live_add]),
                counter[live_add],
            )
            np.maximum.at(
                self._h_rm, (member[is_rm], actor[is_rm]), counter[is_rm]
            )

    # ------------------------------------------------ device-stream internals
    def _grow_device_planes(self) -> None:
        E_new = _bucket(len(self.members) * 4)  # overshoot (see _promote)
        if self._d_sharded:
            from . import mesh as pmesh

            mp = self.accel.mesh.shape["mp"]
            E_new = -(-E_new // mp) * mp
            if E_new <= self._d_E:
                return
            # growth is rare (4× overshoot): a host round-trip keeps the
            # mp re-shard trivial instead of a resharding pad program
            _, clock_s, plane_s = pmesh.stream_sharding(self.accel.mesh)
            import jax

            clock, add, rm = (np.asarray(x) for x in self._d_planes)
            z = np.zeros((E_new - self._d_E, add.shape[1]), np.int32)
            # the growth re-upload is a real transfer the plane gauges
            # would otherwise miss (OBS001)
            trace.add(
                "h2d_bytes", clock.nbytes + 2 * (add.nbytes + z.nbytes)
            )
            self._d_planes = (
                jax.device_put(clock, clock_s),
                jax.device_put(np.concatenate([add, z]), plane_s),
                jax.device_put(np.concatenate([rm, z]), plane_s),
            )
            self._d_E = E_new
            return
        if E_new > self._d_E:
            import jax.numpy as jnp

            clock, add, rm = self._d_planes
            pad = E_new - self._d_E
            add = jnp.pad(add, ((0, pad), (0, 0)))
            rm = jnp.pad(rm, ((0, pad), (0, 0)))
            self._d_planes = (clock, add, rm)
            self._d_E = E_new

    def _device_feed_sharded(self, kind, member, actor, counter) -> None:
        """DEVICE_STREAM over the accelerator's mesh: the SPMD twin of
        :meth:`_device_feed`.  Rows pad to the dp axis
        (``pad_rows_for_mesh``) and stream as dp-sharded fixed-shape
        chunks through the donated ``orset_fold_sharded`` step
        (``retire_rm=False`` — partial-reduction mode, identical combine
        discipline to the single-chip stream); the accumulator planes
        stay mp-sharded on device between chunks, and chunk k+1's
        sharded ``device_put`` is still issued under chunk k's in-flight
        fold (``fold_chunks_overlapped`` with a sharded ``put``).  The
        per-shard scatter runs the XLA segment-max kernel — the
        per-shard Pallas route needs a shard-local tile cap per chunk,
        which would recompile per chunk; the whole-batch sharded fold
        keeps that kernel."""
        import jax

        from ..ops.stream import fold_chunks_overlapped, iter_orset_chunks
        from . import mesh as pmesh

        mesh = self.accel.mesh
        dp = mesh.shape["dp"]
        if len(self.members) > self._d_E:
            self._grow_device_planes()
        cols = K.OrsetColumns(
            np.asarray(kind, np.int8),
            np.asarray(member, np.int32),
            np.asarray(actor, np.int32),
            np.asarray(counter, np.int32),
            self.members,
            self.replicas,
        )
        pmesh.pad_rows_for_mesh(cols, dp, self.R)
        rows = min(DEVICE_CHUNK_ROWS, _bucket(len(cols.kind)))
        rows = -(-rows // dp) * dp  # the fixed chunk shape must divide dp
        step = pmesh.sharded_stream_fold_step(mesh)
        row_s, _, _ = pmesh.stream_sharding(mesh)

        def put(x):
            # h2d_bytes counted by fold_chunks_overlapped at chunk issue
            return jax.device_put(x, row_s)  # lint: disable=OBS001

        def fold_step(planes, chunk):
            return step(*planes, *chunk)

        with trace.span("session.device_fold"):
            self._d_planes = fold_chunks_overlapped(
                self._d_planes,
                iter_orset_chunks(
                    cols.kind, cols.member, cols.actor, cols.counter,
                    rows, self.R,
                ),
                fold_step,
                put=put,
            )

    def _device_feed(self, kind, member, actor, counter) -> None:
        if self._d_sharded:
            return self._device_feed_sharded(kind, member, actor, counter)
        import jax

        from ..ops import pallas_fold as PF
        from ..ops.stream import (
            _fold_donated, _fold_donated_pallas, fold_chunks_overlapped,
            iter_orset_chunks,
        )

        if len(self.members) > self._d_E:
            self._grow_device_planes()
        # the flagship Pallas scatter serves the streaming-plane regime
        # too when eligible — the SAME predicate as the dense/sharded
        # routes (accel._pallas_eligible) plus the ablk key-space bound
        # (this route has no wide-layout fallback)
        use_pallas = bool(
            len(counter)
            and self.accel._pallas_eligible(counter)
            and PF.ablk_key_space_fits(self._d_E, self.R)
        )
        interpret = False
        if FORCE_PALLAS_STREAM is not None:  # tests pin the branch
            use_pallas = FORCE_PALLAS_STREAM
            interpret = jax.default_backend() != "tpu"
        tile_cap = PF.fold_cap(member, self._d_E) if use_pallas else 0

        # retire_rm=False: a horizon retired against the batch-local
        # clock would lose its kill-effect on pre-existing state
        # entries; finish() retires once against the true merged clock
        def fold_step(planes, chunk):
            if use_pallas:
                return _fold_donated_pallas(
                    *planes, *chunk,
                    num_members=self._d_E, num_replicas=self.R,
                    tile_cap=tile_cap, retire_rm=False,
                    interpret=interpret,
                )
            return _fold_donated(
                *planes, *chunk,
                num_members=self._d_E, num_replicas=self.R,
                impl="fused", small_counters=False, retire_rm=False,
            )

        with trace.span("session.device_fold"):
            rows = min(DEVICE_CHUNK_ROWS, _bucket(len(kind)))
            # overlapped consumer loop: chunk k+1's H2D transfer is
            # issued while chunk k's donated fold is in flight; the
            # final fold stays un-blocked — jax dispatch is async, so
            # the next chunk's decrypt and decode overlap the device
            # work (ops/stream.py fold_chunks_overlapped)
            self._d_planes = fold_chunks_overlapped(
                self._d_planes,
                iter_orset_chunks(kind, member, actor, counter, rows, self.R),
                fold_step,
            )

    # ---------------------------------------------------------------- finish
    def finish(self) -> ORSet:
        """Fold everything fed into ``state`` (the only state mutation).

        Concurrency-correct by construction: the state is re-read HERE, in
        one sync section, so applies or state merges that landed while
        chunks were in flight are honored — both modes re-evaluate the
        stale mask against the current clock inside the op-apply combine
        (``apply_batch_planes_host``; batch planes are reductions of OPS,
        never CvRDT states — see the device_finish comment)."""
        assert not self._finished, "session already finished"
        self._finished = True
        state = self.state
        if self.mode == "buffer":
            if not self._buffered:
                return state
            kind = np.concatenate([c[0] for c in self._buffered])
            member = np.concatenate([c[1] for c in self._buffered])
            actor = np.concatenate([c[2] for c in self._buffered])
            counter = np.concatenate([c[3] for c in self._buffered])
            self._buffered = []
            if len(self.members) == 0 or self.R == 0:
                return state
            return self.accel._fold_orset_columns(
                state, kind, member, actor, counter, self.members, self.replicas
            )
        # concurrent applies may have introduced members (never actors —
        # feeds only ever index the fixed actors_sorted columns, and new
        # actors' dots live in the state planes, re-read below)
        K.orset_scan_vocab(state, self.members, self.replicas)
        E = len(self.members)
        R_final = len(self.replicas)
        if self.mode == "host_reduce":
            with trace.span("session.combine"):
                E_pad = max(self._h_add.shape[0], _bucket(max(E, 1)))
                clock0, add0, rm0 = self._state_planes(E_pad)
                add_b = self._pad_batch(self._h_add, E_pad, R_final)
                rm_b = self._pad_batch(self._h_rm, E_pad, R_final)
                # the combine is one elementwise pass — the host runs it at
                # memory bandwidth on planes it already holds, so shipping
                # them to an accelerator is pure interconnect cost at ANY
                # size (the jit twin orset_apply_batch_planes exists for
                # callers whose planes are already device-resident, and
                # tests pin the two equal)
                clock, add, rm = apply_batch_planes_host(
                    clock0, add0, rm0, add_b, rm_b
                )
        else:
            obs_runtime.sample_device_memory()  # planes still resident
            with trace.span("session.device_finish"):
                # op-APPLY semantics, exactly as HOST_REDUCE: the streamed
                # planes are a fold of OPS from a zero clock, NOT a valid
                # CvRDT state — their clock (per-actor add maxima) covers
                # every older dot of those actors, so the CvRDT merge's
                # survivor rule would delete pre-existing entries the
                # batch never touched (confirmed data loss; regression in
                # tests/test_fold_session.py)
                _, d_add, d_rm = (np.asarray(x) for x in self._d_planes)
                E_pad = max(self._d_E, _bucket(max(E, 1)))
                clock0, add0, rm0 = self._state_planes(E_pad)
                d_add = self._pad_batch(d_add, E_pad, R_final)
                d_rm = self._pad_batch(d_rm, E_pad, R_final)
                clock, add, rm = apply_batch_planes_host(
                    clock0, add0, rm0, d_add, d_rm
                )
        with trace.span("session.writeback"):
            folded = K.orset_planes_to_state(
                clock, add[:E], rm[:E], self.members, self.replicas
            )
        state.clock = folded.clock
        state.entries = folded.entries
        state.deferred = folded.deferred
        # bump the mutation epoch (and drop the accelerator's device
        # plane cache if it holds this state) — the combine ran on host
        note = getattr(self.accel, "_note_orset_writeback", None)
        if note is not None:
            note(state)
        else:
            state._mut += 1
        return state

    @staticmethod
    def _pad_batch(plane, E_pad: int, R_final: int):
        e, r = plane.shape
        if e == E_pad and r == R_final:
            return plane
        out = np.zeros((E_pad, R_final), np.int32)
        out[:e, :r] = plane
        return out

    @staticmethod
    def _pad_clock(clock, R_final: int):
        if len(clock) == R_final:
            return clock
        out = np.zeros(R_final, np.int32)
        out[: len(clock)] = clock
        return out


class CounterFoldSession:
    """Chunked G/PN-Counter ingestion: per-actor maxima reduce on host per
    chunk (the planes are O(R) — transfer and scatter are both trivial),
    one device combine at finish."""

    def __init__(self, accel, state, actors_hint=()):
        self.accel = accel
        self.state = state
        self.is_pn = isinstance(state, PNCounter)
        clocks = (
            (state.p.clock, state.n.clock) if self.is_pn else (state.clock,)
        )
        actor_set = set(actors_hint)
        for c in clocks:
            actor_set.update(c.counters)
        self.actors_sorted = sorted(actor_set)
        self.replicas = K.Vocab(self.actors_sorted)
        self.R = len(self.replicas)
        self._p = np.zeros(max(self.R, 1), np.int32)
        self._n = np.zeros(max(self.R, 1), np.int32)
        self.rows_fed = 0
        self._finished = False

    def decode_chunk(self, payloads: list):
        from ..ops.native_decode import decode_counter_payload_batch

        decoded = decode_counter_payload_batch(payloads, self.actors_sorted)
        if decoded is None:
            raise SessionDeclined("native decoder declined the chunk")
        sign = decoded[0]
        if len(sign) and isinstance(self.state, GCounter) and np.any(sign != POS):
            raise SessionDeclined("PN-shaped rows in a G-Counter state")
        return decoded

    def reduce_chunk(self, decoded) -> None:
        assert not self._finished, "session already finished"
        sign, actor_idx, counter = decoded
        if len(sign) == 0:
            return
        self.rows_fed += len(sign)
        pos = sign == POS
        np.maximum.at(self._p, actor_idx[pos], counter[pos])
        np.maximum.at(self._n, actor_idx[~pos], counter[~pos])

    def feed(self, payloads: list) -> None:
        self.reduce_chunk(self.decode_chunk(payloads))

    def finish(self):
        assert not self._finished, "session already finished"
        self._finished = True
        state = self.state
        if self.R == 0 or self.rows_fed == 0:
            return state
        # concurrent applies may have introduced actors since init: rescan
        # the state clocks (fed rows only ever index the original columns)
        clocks = (
            (state.p.clock, state.n.clock) if self.is_pn else (state.clock,)
        )
        for c in clocks:
            for a in c.counters:
                self.replicas.intern(a)
        R_final = len(self.replicas)
        p = self._pad(self._p, R_final)
        n = self._pad(self._n, R_final)
        if self.is_pn:
            p0 = K.vclock_to_dense(state.p.clock, self.replicas)
            n0 = K.vclock_to_dense(state.n.clock, self.replicas)
            state.p.clock = K.dense_to_vclock(np.maximum(p0, p), self.replicas)
            state.n.clock = K.dense_to_vclock(np.maximum(n0, n), self.replicas)
        else:
            c0 = K.vclock_to_dense(state.clock, self.replicas)
            state.clock = K.dense_to_vclock(np.maximum(c0, p), self.replicas)
        return state

    @staticmethod
    def _pad(arr, R_final: int):
        if len(arr) == R_final:
            return arr
        out = np.zeros(R_final, np.int32)
        out[: len(arr)] = arr
        return out


class MapFoldSession:
    """Chunked CrdtMap<orset> ingestion: each chunk decodes to the four
    row families natively (validation up front — ``SessionDeclined``
    fires at reduce time, never at finish) and interns its key/member
    spans into running vocabularies; finish concatenates the remapped
    families and runs the columnar map fold once against the state read
    AT FINISH (``crdtmap_fold_host``), so applies that landed while
    chunks were in flight are honored exactly like the whole-batch
    path."""

    def __init__(self, accel, state, actors_hint=()):
        from ..ops.columnar import Vocab

        self.accel = accel
        self.state = state
        actor_set = set(actors_hint)
        actor_set.update(state.clock.counters)
        for birth in state.births.values():
            actor_set.update(birth)
        for ctx, _rm_keys in state.deferred.values():
            actor_set.update(ctx.counters)
        for child in state.vals.values():
            actor_set.update(child.clock.counters)
            for entry in child.entries.values():
                actor_set.update(entry)
            for dfr in child.deferred.values():
                actor_set.update(dfr)
        self.actors_sorted = sorted(actor_set)
        self.keys = Vocab()
        self.members = Vocab()
        self._fams: list = []  # (B, A, Rm, K) with vocab-global indices
        self._n_groups = 0
        self.rows_fed = 0
        self._finished = False

    def decode_chunk(self, payloads: list):
        from ..ops.map_columnar import decode_map_payload_batch

        decoded = decode_map_payload_batch(payloads, self.actors_sorted)
        if decoded is None:
            raise SessionDeclined("native map decoder declined the chunk")
        return decoded

    def _remap(self, vocab, objs):
        """Chunk-local object table → running-vocab indices; declines on
        a value collision (1 == True etc. — distinct canonical spans
        interning to one slot would scatter rows onto the wrong row)."""
        idx = np.fromiter(
            (vocab.intern(o) for o in objs), np.int32, count=len(objs)
        )
        if len(objs) and len(np.unique(idx)) != len(objs):
            raise SessionDeclined("vocab value collision in map chunk")
        return idx

    def reduce_chunk(self, decoded) -> None:
        assert not self._finished, "session already finished"
        B, A, Rm, Kk, key_objs, member_objs = decoded
        kmap = self._remap(self.keys, key_objs)
        mmap = self._remap(self.members, member_objs)

        def rekey(fam, with_member):
            out = dict(fam)
            if len(fam["key"]):
                out["key"] = kmap[fam["key"]]
            if with_member and len(fam.get("member", ())):
                out["member"] = mmap[fam["member"]]
            return out

        B2, A2, Rm2 = rekey(B, False), rekey(A, True), rekey(Rm, True)
        K2 = rekey(Kk, False)
        if len(K2["group"]):
            K2["group"] = K2["group"] + self._n_groups
            self._n_groups += int(Kk["group"].max()) + 1
        self._fams.append((B2, A2, Rm2, K2))
        self.rows_fed += (
            len(B2["actor"]) + len(A2["actor"]) + len(Rm2["actor"])
            + len(K2["actor"])
        )

    def feed(self, payloads: list) -> None:
        self.reduce_chunk(self.decode_chunk(payloads))

    def finish(self):
        from ..ops.columnar import Vocab
        from ..ops.map_columnar import crdtmap_fold_host

        assert not self._finished, "session already finished"
        self._finished = True
        state = self.state
        if not self._fams:
            return state

        def cat(ix, names):
            return {
                n: np.concatenate([f[ix].get(n, np.zeros(0, np.int32))
                                   for f in self._fams])
                for n in names
            }

        B = cat(0, ("key", "actor", "ctr"))
        A = cat(1, ("key", "member", "actor", "ctr"))
        Rm = cat(2, ("key", "member", "actor", "ctr", "mactor", "mctr"))
        Kk = cat(3, ("key", "actor", "ctr", "group"))
        self._fams = []
        # concurrent applies may have introduced actors since open: the
        # fed rows only ever index the original sorted prefix, so new
        # actors intern AFTER it and the row indices stay valid
        replicas = Vocab(self.actors_sorted)
        state_actors = set(state.clock.counters)
        for birth in state.births.values():
            state_actors.update(birth)
        for ctx, _rm_keys in state.deferred.values():
            state_actors.update(ctx.counters)
        for child in state.vals.values():
            state_actors.update(child.clock.counters)
            for entry in child.entries.values():
                state_actors.update(entry)
            for dfr in child.deferred.values():
                state_actors.update(dfr)
        for a in sorted(state_actors):
            replicas.intern(a)
        impl = self.accel.map_fold_impl
        mesh_on = getattr(self.accel, "_mesh_active", lambda: False)()
        if impl is None and mesh_on:
            impl = "device"
        elif impl is None:
            impl = (
                "device"
                if self.rows_fed >= self.accel.min_device_batch
                else "host"
            )
        crdtmap_fold_host(
            state, B, A, Rm, Kk, self.keys, self.members,
            replicas, fold_impl=impl,
            mesh=self.accel.mesh if impl == "device" and mesh_on else None,
        )
        return state


def session_supported(state) -> bool:
    """Cheap type predicate for :func:`open_fold_session` — True iff a
    chunked columnar session exists for ``state``'s type.  Costs one
    isinstance chain, no session construction (whose state scans are the
    expensive part) — callers use it to decide whether to spin up
    pipeline machinery at all."""
    from ..models.crdtmap import CrdtMap

    if isinstance(state, (ORSet, GCounter, PNCounter)):
        return True
    return isinstance(state, CrdtMap) and state.child == b"orset"


def open_fold_session(accel, state, actors_hint=()):
    """A fold session for ``state``, or None when no chunked columnar path
    exists for its type (the caller folds chunks through the per-op path)."""
    if not session_supported(state):
        return None
    if isinstance(state, ORSet):
        return OrsetFoldSession(accel, state, actors_hint)
    if isinstance(state, (GCounter, PNCounter)):
        return CounterFoldSession(accel, state, actors_hint)
    return MapFoldSession(accel, state, actors_hint)

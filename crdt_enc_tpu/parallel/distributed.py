"""Multi-host execution: DCN bootstrap + host-aware meshes + global batches.

The reference has no distributed backend at all — its transport is a synced
filesystem (SURVEY.md §2.3).  This module is the TPU-native scale-out layer
the rebuild adds on top: many hosts, each with a slice of TPU chips, jointly
folding one op batch with XLA collectives.  Three pieces:

* :func:`initialize` — one-call ``jax.distributed`` bootstrap (idempotent,
  env-var driven, a no-op for single-process runs), the moral equivalent of
  the NCCL/MPI rendezvous other frameworks need — except after it returns
  there is nothing else to manage: collectives are compiled into the
  program by XLA.
* :func:`make_multihost_mesh` — a ``(dp, mp)`` mesh with **hosts on the
  ``dp`` axis and each host's chips on ``mp``**.  Why this way around: op
  rows shard over ``dp`` (parallel/mesh.py), so each host folds ONLY the
  rows it decoded locally — raw op data never crosses a host boundary.
  The fold's single collective, the ``pmax`` of folded partial planes over
  ``dp`` (mesh.py:79-81), is the one thing that must cross DCN and is
  exactly the data-parallel all-reduce pattern: dense partial state, moved
  once.  ``mp`` (the member-sharded plane axis) carries no fold-time
  collectives and stays on ICI inside each host.
* :func:`global_op_batch` — assemble the globally-``dp``-sharded op batch
  from each process's *local* rows
  (``jax.make_array_from_process_local_data``): host i's rows ARE dp shard
  i, so no host ever materializes the full batch.

Typical multi-host compaction::

    distributed.initialize()                    # env/TPU-pod autodetected
    mesh = distributed.make_multihost_mesh()
    batch = distributed.global_op_batch(mesh, kind, member, actor, counter,
                                        num_replicas=R)
    clock, add, rm = pmesh.orset_fold_sharded(mesh, clock0, add0, rm0, *batch)

Validated single-process on a virtual 8-device CPU mesh in
tests/test_distributed.py; the device placement logic is exercised by
faking process boundaries in the device list.
"""

from __future__ import annotations

import logging
import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import ops as K
from ..utils import trace

logger = logging.getLogger("crdt_enc_tpu.distributed")

_INITIALIZED = False


def _backend_untouched() -> bool | None:
    """Whether the XLA backend is still uninitialized: True/False when the
    probe works, None when it cannot tell.  Probes private jax internals —
    no public API exposes this without initializing the backend as a side
    effect — so a jax release that moves them degrades to None rather than
    crashing; callers decide how to act on uncertainty."""
    bridge = getattr(getattr(jax, "_src", None), "xla_bridge", None)
    backends = getattr(bridge, "_backends", None)
    if backends is None:
        return None
    return not backends


def _already_initialized() -> bool:
    """Probe the distributed client WITHOUT touching the XLA backend
    (``jax.process_count()`` would initialize it, after which
    ``jax.distributed.initialize`` refuses to run)."""
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        return bool(is_init())
    from jax._src import distributed as _dist  # fallback for older jax

    return getattr(_dist.global_state, "client", None) is not None


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    **kwargs,
) -> bool:
    """Bootstrap ``jax.distributed`` for a multi-host run.

    Arguments default to the standard env vars (``JAX_COORDINATOR_ADDRESS``,
    ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``).  With explicit configuration
    (args or env) the bootstrap is mandatory: failures propagate — a
    misconfigured pod must die loudly, not degrade to a single-process run
    while its peers block in the rendezvous.  With no configuration at all,
    pod auto-detection is attempted if (and only if) the XLA backend is
    still untouched; "no cluster detected" is logged and treated as a plain
    single-process run.  Returns True iff the distributed runtime is
    initialized after the call.  Safe to call more than once.
    """
    global _INITIALIZED
    if _INITIALIZED or _already_initialized():
        _INITIALIZED = True
        return True
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])
    explicit = (
        coordinator_address is not None
        or num_processes is not None
        or process_id is not None
    )
    if explicit:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **kwargs,
        )
        _INITIALIZED = True
        return True
    if _backend_untouched() is False:
        return False  # backend provably up — too late to auto-detect; no-op
    # backend untouched (or unknowable on this jax version): attempt
    # auto-detection — the call itself degrades gracefully either way
    try:
        jax.distributed.initialize(**kwargs)
    except Exception as e:  # no pod metadata → plain single-process run
        logger.info("no cluster auto-detected (%s); running single-process", e)
        return False
    _INITIALIZED = True
    return True


def make_multihost_mesh(devices=None, local_count: int | None = None) -> Mesh:
    """A ``(dp, mp)`` mesh with hosts along ``dp`` and each host's chips
    along ``mp``.

    Op rows shard over ``dp``, so each host folds only its locally-decoded
    rows; the ``pmax`` of folded partial planes over ``dp`` is the single
    cross-host (DCN) collective — dense partial state moved once, the
    data-parallel all-reduce shape.  ``mp`` shards the state planes on the
    member axis with no fold-time collectives, riding ICI within a host.

    ``devices`` defaults to all global devices in process order (JAX's
    guarantee: ``jax.devices()`` groups by process).  ``local_count``
    overrides devices-per-host for testing (fake process boundaries).
    On one host this degrades to ``(1, n_chips)`` — all chips plane-sharded;
    use :func:`crdt_enc_tpu.parallel.make_mesh` instead when you want a
    custom single-host split.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    if local_count is None:
        local_count = (
            jax.local_device_count()
            if jax.process_count() > 1
            else len(devices)
        )
    n = len(devices)
    if n % local_count:
        raise ValueError(
            f"{n} devices do not split into hosts of {local_count}"
        )
    hosts = n // local_count
    # process-major device order ⇒ row i of (hosts, local) is host i's chips
    arr = np.asarray(devices).reshape(hosts, local_count)
    return Mesh(arr, axis_names=("dp", "mp"))


def global_op_batch(
    mesh: Mesh,
    kind,
    member,
    actor,
    counter,
    num_replicas: int,
    rows_per_host: int | None = None,
):
    """Assemble globally-``dp``-sharded op columns from process-local rows.

    Each process passes ONLY the rows it decoded locally; the returned
    ``jax.Array``s are global views sharded ``P("dp")`` — host i's rows are
    dp shard i, so no host gathers the whole batch.  All hosts must
    contribute the same row count for the global array to be rectangular:
    rows are sentinel-padded (``ops.pad_orset_rows``) up to ``rows_per_host``
    — computed collectively (max over hosts, one tiny allgather) when not
    given.  Single-process this degrades to a sharded ``device_put`` over
    the dp axis — the same downstream code path, so tests exercise it
    without a cluster.
    """
    cols = K.OrsetColumns(
        np.asarray(kind, np.int8),
        np.asarray(member, np.int32),
        np.asarray(actor, np.int32),
        np.asarray(counter, np.int32),
    )
    dp = mesh.shape["dp"]
    procs = jax.process_count()
    n_local = len(cols.kind)
    if rows_per_host is not None:
        # capacity check: single-process the bucket spans all dp shards,
        # multi-process it holds just this host's rows
        capacity = rows_per_host * dp if procs == 1 else rows_per_host
        if capacity < n_local:
            raise ValueError(
                f"rows_per_host={rows_per_host} cannot hold {n_local} rows"
            )
    if procs == 1:
        # whole batch is local: pad so the row count divides dp (or fills
        # the explicit per-shard bucket) and shard over the dp axis
        target = (
            rows_per_host * dp
            if rows_per_host is not None
            else -(-len(cols.kind) // dp) * dp
        )
        K.pad_orset_rows(cols, target, num_replicas)
        sharding = NamedSharding(mesh, P("dp"))
        columns = (cols.kind, cols.member, cols.actor, cols.counter)
        trace.add("h2d_bytes", sum(x.nbytes for x in columns))
        return tuple(jax.device_put(x, sharding) for x in columns)
    if dp != procs:
        raise ValueError(
            f"multi-process batches need the dp axis ({dp}) to equal the "
            f"process count ({procs}): one dp shard per host "
            "(make_multihost_mesh builds exactly this)"
        )
    if rows_per_host is None:
        from jax.experimental import multihost_utils

        counts = multihost_utils.process_allgather(
            np.asarray([len(cols.kind)], np.int64)
        )
        rows_per_host = int(np.max(counts))
    K.pad_orset_rows(cols, rows_per_host, num_replicas)
    sharding = NamedSharding(mesh, P("dp"))
    columns = (cols.kind, cols.member, cols.actor, cols.counter)
    # this host's shard of the global batch, counted at issue like the
    # single-process branch (each process counts its own contribution)
    trace.add("h2d_bytes", sum(x.nbytes for x in columns))
    return tuple(
        jax.make_array_from_process_local_data(sharding, x)
        for x in columns
    )


def replicate(mesh: Mesh, *arrays):
    """Place arrays fully replicated over the mesh (clocks, initial planes
    that are not member-sharded)."""
    sharding = NamedSharding(mesh, P())
    host = tuple(np.asarray(a) for a in arrays)
    trace.add("h2d_bytes", sum(a.nbytes for a in host))
    out = tuple(jax.device_put(a, sharding) for a in host)
    return out if len(out) != 1 else out[0]

"""Remote integrity checker — failure detection for the synced directory.

The replication substrate is a passively synced directory written by many
replicas (reference README.md:3-11); the failure modes that matter are
sync-tool damage and bit rot: torn/truncated blobs, tampered ciphertext,
content-addressed files whose name no longer matches their bytes, op-log
gaps that stall every consumer's dense scan, and key metadata that no
longer decodes.  The crash-safety ORDERING is by construction
(write-new-before-delete-old); this tool detects what ordering cannot
prevent.

``fsck_remote`` walks one remote through the SAME plugin stack a replica
uses (storage + cryptor + key cryptor), verifies every object family, and
returns a structured report; the CLI prints it.  Read-only — never
repairs, because the right repair is re-sync or restore of immutable
content-addressed files.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..backends.memory import content_name
from ..core.core import RemoteMeta, snapshot_sealer
from ..core.key_cryptor import Keys
from ..utils import VersionBytes, codec, trace
from ..utils.versions import SUPPORTED_CONTAINER_VERSIONS


@dataclass
class Issue:
    severity: str  # "error" | "warn"
    family: str  # "meta" | "states" | "ops" | "keys"
    obj: str  # file name / actor:version
    problem: str

    def __str__(self) -> str:
        return f"[{self.severity}] {self.family} {self.obj}: {self.problem}"


@dataclass
class FsckReport:
    meta_files: int = 0
    state_files: int = 0
    op_files: int = 0
    op_actors: int = 0
    ops_decoded: int = 0
    delta_files: int = 0
    keys_found: int = 0
    issues: list = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not any(i.severity == "error" for i in self.issues)

    def add(self, severity, family, obj, problem):
        self.issues.append(Issue(severity, family, obj, problem))

    def summary(self) -> str:
        errors = sum(1 for i in self.issues if i.severity == "error")
        warns = len(self.issues) - errors
        deltas = f", {self.delta_files} deltas" if self.delta_files else ""
        return (
            f"{'OK' if self.ok else 'DAMAGED'}: {self.meta_files} meta, "
            f"{self.state_files} states, {self.op_files} op files across "
            f"{self.op_actors} actors ({self.ops_decoded} ops){deltas}, "
            f"{self.keys_found} data keys; {errors} error(s), {warns} warning(s)"
        )


class _KeyCollector:
    """Just enough CoreSubHandle surface for a key cryptor — collects the
    decoded key set; never writes the remote.  Shared by ``fsck_remote``
    and ``verify_checkpoint``."""

    actor_id = b"\x00" * 16

    def __init__(self):
        self.keys = Keys()

    def set_keys(self, keys):
        self.keys = keys

    async def set_remote_meta_key_cryptor(self, reg):
        pass  # read-only: never write the remote


async def fsck_remote(storage, cryptor, key_cryptor, *, deep: bool = True) -> FsckReport:
    """Verify one remote.  ``deep=True`` additionally decrypts every state
    and op file (auth check) and parses the cleartext framing; ``False``
    checks structure and names only.

    The key cryptor receives the remote's converged key register exactly
    as a replica would (``set_remote_meta``); decrypting then uses a core
    stub that only collects keys — no replica state is created anywhere.
    """
    report = FsckReport()

    collector = _KeyCollector()
    await key_cryptor.init(collector)

    # ---- meta family -----------------------------------------------------
    meta = RemoteMeta()
    with trace.span("fsck.meta"):
        names = await storage.list_remote_meta_names()
        loaded = dict(await storage.load_remote_metas(names))
    for name in names:
        raw = loaded.get(name)
        if raw is None:
            report.add("warn", "meta", name, "listed but unreadable (racing GC?)")
            continue
        report.meta_files += 1
        if content_name(raw) != name:
            report.add("error", "meta", name, "content does not match its address")
            continue
        try:
            vb = VersionBytes.deserialize(raw).ensure_versions(
                SUPPORTED_CONTAINER_VERSIONS
            )
            meta.merge(RemoteMeta.from_obj(codec.unpack(vb.content)))
        except Exception as e:
            report.add("error", "meta", name, f"malformed: {e}")
    try:
        await key_cryptor.set_remote_meta(meta.key_cryptor)
    except Exception as e:
        report.add("error", "keys", "register", f"key metadata does not decode: {e}")
    keys = collector.keys
    report.keys_found = len(keys.keys.entries)
    latest_ok = False
    try:
        latest_ok = keys.latest_key() is not None
    except Exception as e:  # e.g. DanglingLatestKey: id survives, material lost
        report.add("error", "keys", "latest", f"latest key unresolvable: {e}")
        latest_ok = True  # already reported — not also "no resolvable key"

    from ..core.core import open_sealed_blob

    async def open_sealed(raw: bytes):
        # the shared wire-contract implementation (core.open_sealed_blob);
        # the app's inner data-version set is unknown here, so that one
        # check is skipped
        clear_obj = await open_sealed_blob(keys, cryptor, raw)
        return clear_obj

    # ---- states ----------------------------------------------------------
    with trace.span("fsck.states"):
        names = await storage.list_state_names()
        loaded = dict(await storage.load_states(names))
        for name in names:
            raw = loaded.get(name)
            if raw is None:
                report.add(
                    "warn", "states", name, "listed but unreadable (racing GC?)"
                )
                continue
            report.state_files += 1
            if content_name(raw) != name:
                report.add(
                    "error", "states", name, "content does not match its address"
                )
                continue
            if not deep:
                continue
            try:
                obj = await open_sealed(raw)
                # [state, cursor] or [state, cursor, sealer] — the
                # replication-obs layer appends the sealing replica's
                # actor id (StateWrapper's wire note in core/core.py)
                if not (isinstance(obj, (list, tuple)) and len(obj) in (2, 3)):
                    raise ValueError(
                        "state wrapper is not [state, cursor(, sealer)]"
                    )
                # same wire rule core ingest applies — but where core
                # silently drops a malformed sealer, fsck reports it
                if len(obj) == 3 and obj[2] and snapshot_sealer(obj) is None:
                    raise ValueError("snapshot sealer id is not 16 bytes")
            except Exception as e:
                report.add("error", "states", name, f"{e}")

    # ---- op logs ---------------------------------------------------------
    with trace.span("fsck.ops"):
        actors = await storage.list_op_actors()
        report.op_actors = len(actors)
        for actor in actors:
            hexa = actor.hex()
            versions = await _list_op_versions(storage, actor)
            if versions is None:
                report.add(
                    "warn", "ops", hexa,
                    "storage backend cannot enumerate op versions; "
                    "gap detection skipped",
                )
                if deep:
                    files = await storage.load_ops([(actor, 1)])
                    report.op_files += len(files)
                    await _deep_check_ops(report, open_sealed, hexa, files)
                continue
            report.op_files += len(versions)
            if not versions:
                continue
            # dense from the FLOOR — compaction legitimately GCs a prefix,
            # so a log starting at N+1 is healthy; only holes with files
            # beyond them strand data (every consumer's scan stops at the
            # hole)
            floor = versions[0]
            expected = set(range(floor, floor + len(versions)))
            missing = sorted(expected - set(versions))
            if missing:
                report.add(
                    "error", "ops", hexa,
                    f"gap at version {missing[0]}: "
                    f"{sum(1 for v in versions if v > missing[0])} file(s) "
                    "beyond it are unreachable by the dense scan",
                )
            if deep:
                files = await storage.load_ops([(actor, floor)])
                await _deep_check_ops(report, open_sealed, hexa, files)
    # ---- delta snapshots -------------------------------------------------
    await _check_deltas(report, storage, open_sealed, deep=deep)

    trace.add("fsck_ops_decoded", report.ops_decoded)
    if not latest_ok and (
        report.meta_files or report.keys_found
        or report.state_files or report.op_files
    ):
        report.add(
            "error", "keys", "latest",
            "no resolvable latest data key (key metadata lost?)",
        )
    return report


def _adapter_for_name(name: bytes):
    """Adapter instance for a delta payload's adapter name, or None —
    the refold check is skipped for types this build cannot decode."""
    key = bytes(name).decode(errors="replace")
    if key == "rcounter":
        from ..delta.compose import rcounter_adapter

        return rcounter_adapter()
    ctor = ADAPTERS.get(key)
    if ctor is None:
        return None
    from ..core import adapters as _adapters

    return getattr(_adapters, ctor)()


async def _check_deltas(report, storage, open_sealed, *, deep: bool) -> None:
    """Validate the delta file family (docs/delta.md):

    * **broken chains** — interior version gaps in a sealer's log
      (logs are append-only and GC removes only prefixes, so a hole
      with links beyond it is damage), and payloads missing the base
      watermark / cursors / names (malformed) — error rows;
    * **orphan deltas** — a link filed under one sealer's log whose
      payload names a different sealer: misfiled by the sync tool,
      unusable and misleading — error row;
    * **delta-vs-refold byte divergence** — whenever BOTH endpoint
      snapshots are still present, the base state + delta must refold
      byte-identically to the target snapshot's state — error row;
    * anchoring looseness is WARNED, not failed: a link's base may
      legitimately be an *earlier* anchor than its predecessor's
      target (a stale-checkpoint reopen re-anchors the chain), and a
      chain head may target a snapshot a superseding compactor GC'd —
      consumers holding the base name still apply such links, everyone
      else falls back.
    """
    if not getattr(storage, "has_deltas", False):
        return
    from ..delta import codec_for, wire

    with trace.span("fsck.deltas"):
        try:
            actors = await storage.list_delta_actors()
        except Exception as e:
            report.add("error", "deltas", "listing", f"unlistable: {e}")
            return
        state_names = set(await storage.list_state_names())
        for actor in actors:
            hexa = actor.hex()
            versions = await _list_delta_versions(storage, actor)
            if versions is None:
                report.add(
                    "warn", "deltas", hexa,
                    "storage backend cannot enumerate delta versions; "
                    "gap detection skipped",
                )
                versions = []
            if versions:
                floor = versions[0]
                expected = set(range(floor, floor + len(versions)))
                missing = sorted(expected - set(versions))
                if missing:
                    report.add(
                        "error", "deltas", hexa,
                        f"broken chain: gap at version {missing[0]} "
                        "(GC removes only prefixes — an interior hole "
                        "is damage)",
                    )
            if not deep:
                report.delta_files += len(versions)
                continue
            files = await storage.load_deltas([(actor, 1)])
            report.delta_files += len(files)
            records: list[tuple] = []  # (version, record) that parsed
            for _, version, raw in files:
                try:
                    obj = await open_sealed(raw)
                    rec = wire.parse_delta_obj(obj)
                except Exception as e:
                    report.add(
                        "error", "deltas", f"{hexa}:{version}", f"{e}"
                    )
                    continue
                if rec.sealer != actor:
                    report.add(
                        "error", "deltas", f"{hexa}:{version}",
                        "orphan delta: payload sealer "
                        f"{rec.sealer.hex()} does not own this log",
                    )
                    continue
                records.append((version, rec))
            # base anchoring: a link need not chain from its IMMEDIATE
            # predecessor (a stale-checkpoint reopen legitimately
            # re-anchors at an earlier own snapshot), but its base must
            # resolve SOMEWHERE — an earlier link's target or a listed
            # state.  Unresolvable is a warning (the anchor may have
            # been GC'd after consumers learned it), never silent.
            targets = {rec.new_name for _, rec in records}
            for version, rec in records:
                if (
                    rec.base_name not in targets
                    and rec.base_name not in state_names
                    and records[0][0] != version  # oldest link's base
                    # is routinely a GC'd predecessor target
                ):
                    report.add(
                        "warn", "deltas", f"{hexa}:{version}",
                        f"unanchored chain link: base "
                        f"{rec.base_name[:16]}… resolves to no listed "
                        "snapshot or log target",
                    )
                if rec.base_name in state_names and rec.new_name in state_names:
                    await _check_delta_refold(
                        report, storage, open_sealed, hexa, version, rec,
                        codec_for(rec.adapter),
                    )
            if records and records[-1][1].new_name not in state_names:
                report.add(
                    "warn", "deltas", f"{hexa}:{records[-1][0]}",
                    "chain head targets a GC'd snapshot; consumers "
                    "holding the base still apply it, everyone else "
                    "falls back",
                )


async def _check_delta_refold(
    report, storage, open_sealed, hexa, version, rec, codec_cls
) -> None:
    adapter = _adapter_for_name(rec.adapter)
    if adapter is None or codec_cls is None:
        report.add(
            "warn", "deltas", f"{hexa}:{version}",
            f"adapter {rec.adapter!r} unknown here; refold check skipped",
        )
        return
    loaded = dict(await storage.load_states([rec.base_name, rec.new_name]))
    if len(loaded) < 2:
        return  # racing GC; both were listed a moment ago
    try:
        base_obj = await open_sealed(loaded[rec.base_name])
        new_obj = await open_sealed(loaded[rec.new_name])
        base_state = adapter.state_from_obj(base_obj[0])
        codec_cls.apply(base_state, rec.delta_obj)
        refolded = codec.pack(adapter.state_to_obj(base_state))
        target = codec.pack(adapter.state_to_obj(
            adapter.state_from_obj(new_obj[0])
        ))
    except Exception as e:
        report.add(
            "error", "deltas", f"{hexa}:{version}", f"refold failed: {e}"
        )
        return
    if refolded != target:
        report.add(
            "error", "deltas", f"{hexa}:{version}",
            f"delta-vs-refold divergence: base+delta ({len(refolded)}B "
            f"canonical) != target snapshot ({len(target)}B canonical)",
        )


async def _list_delta_versions(storage, actor) -> list[int] | None:
    """Sorted delta versions for one sealer without reading bytes, or
    None when the backend cannot enumerate them."""
    deltas_dir = getattr(storage, "_deltas_dir", None)
    if deltas_dir is not None:
        import os

        try:
            names = os.listdir(deltas_dir(actor))
        except FileNotFoundError:
            return []
        return sorted(int(n) for n in names if n.isdigit())
    table = getattr(storage, "remote", None)
    deltas = getattr(table, "deltas", None)
    if isinstance(deltas, dict):  # MemoryRemote: {actor: {version: bytes}}
        return sorted(int(v) for v in deltas.get(actor, {}))
    return None


async def _deep_check_ops(report, open_sealed, hexa: str, files: list) -> None:
    for _, version, raw in files:
        try:
            ops = await open_sealed(raw)
            if not isinstance(ops, (list, tuple)):
                raise ValueError("op payload is not an array")
            report.ops_decoded += len(ops)
        except Exception as e:
            report.add("error", "ops", f"{hexa}:{version}", f"{e}")


async def verify_checkpoint(
    local_storage, storage, cryptor, key_cryptor, *, adapter=None
) -> FsckReport:
    """Verify a replica's local fold checkpoint against its remote: load
    and decrypt the checkpoint, then REFOLD the remote (state snapshots
    whose cursors it covers, plus op files up to the checkpoint cursor —
    the same ingestion order a cold open runs) and byte-compare the two
    canonical serializations.  Divergence is an error row (non-zero CLI
    exit); a remote whose op logs no longer reach the cursor reports the
    refold as unverifiable (warn) rather than passing silently.

    ``adapter`` decodes generic-format checkpoints and replayed ops
    (default: the OR-Set adapter)."""
    from ..core.adapters import orset_adapter
    from ..core.core import open_sealed_blob, unpack_checkpoint_state
    from ..models.vclock import VClock

    if adapter is None:
        adapter = orset_adapter()
    report = FsckReport()
    raw = await local_storage.load_local_checkpoint()
    if raw is None:
        report.add(
            "warn", "checkpoint", "local", "no local checkpoint to verify"
        )
        return report

    # keys from the remote's converged metadata, exactly as a replica
    # would read them (the same collector stub fsck_remote uses)
    collector = _KeyCollector()
    await key_cryptor.init(collector)
    meta = RemoteMeta()
    names = await storage.list_remote_meta_names()
    for name, blob in await storage.load_remote_metas(names):
        try:
            vb = VersionBytes.deserialize(blob).ensure_versions(
                SUPPORTED_CONTAINER_VERSIONS
            )
            meta.merge(RemoteMeta.from_obj(codec.unpack(vb.content)))
        except Exception as e:
            report.add("error", "meta", name, f"malformed: {e}")
    try:
        await key_cryptor.set_remote_meta(meta.key_cryptor)
    except Exception as e:
        report.add(
            "error", "keys", "register", f"key metadata does not decode: {e}"
        )
        return report
    keys = collector.keys

    async def open_sealed(blob: bytes):
        return await open_sealed_blob(keys, cryptor, blob)

    with trace.span("checkpoint.verify"):
        try:
            obj = await open_sealed(raw)
            fmt = int(obj[b"fmt"])
            cursor = VClock.from_obj(obj[b"cursor"])
            ck_state = unpack_checkpoint_state(adapter, fmt, obj[b"state"])
        except Exception as e:
            report.add("error", "checkpoint", "local", f"unreadable: {e}")
            return report

        refold = adapter.new()
        folded_cursor = VClock()
        state_names = await storage.list_state_names()
        for name, blob in sorted(await storage.load_states(state_names)):
            try:
                sobj = await open_sealed(blob)
                sc = VClock.from_obj(sobj[1])
            except Exception as e:
                report.add("error", "states", name, f"{e}")
                continue
            if any(c > cursor.get(a) for a, c in sc.counters.items()):
                report.add(
                    "warn", "checkpoint", name,
                    "snapshot exceeds the checkpoint cursor "
                    "(a later compaction); skipped from the refold",
                )
                continue
            refold.merge(adapter.state_from_obj(sobj[0]))
            folded_cursor.merge(sc)
            report.state_files += 1
        from contextlib import aclosing

        unverifiable = []
        for actor in sorted(cursor.counters):
            last = cursor.get(actor)
            v = folded_cursor.get(actor) + 1
            # chunked read, stopped at the cursor: the remote may hold a
            # long post-checkpoint tail this verification must not load
            done = False
            async with aclosing(
                storage.iter_op_chunks([(actor, v)])
            ) as chunks:
                async for files in chunks:
                    for _, version, blob in files:
                        if version > last:
                            done = True  # a tail the checkpoint never folded
                            break
                        try:
                            ops = await open_sealed(blob)
                        except Exception as e:
                            report.add(
                                "error", "ops",
                                f"{actor.hex()}:{version}", f"{e}",
                            )
                            return report
                        for o in ops:
                            refold.apply(adapter.op_from_obj(o))
                            report.ops_decoded += 1
                        report.op_files += 1
                        v = version + 1
                    if done:
                        break
            if v <= last:
                unverifiable.append((actor, v, last))
        if unverifiable:
            for actor, v, last in unverifiable:
                report.add(
                    "warn", "checkpoint", actor.hex(),
                    f"op files v{v}..v{last} are gone from the remote "
                    "and no snapshot covers them; refold incomplete — "
                    "checkpoint unverifiable",
                )
            return report
        ck_bytes = codec.pack(adapter.state_to_obj(ck_state))
        rf_bytes = codec.pack(adapter.state_to_obj(refold))
        if ck_bytes != rf_bytes:
            report.add(
                "error", "checkpoint", "local",
                f"checkpointed state ({len(ck_bytes)}B canonical) diverges "
                f"from the remote refold ({len(rf_bytes)}B canonical)",
            )
    return report


ADAPTERS = {
    "orset": "orset_adapter",
    "gcounter": "gcounter_adapter",
    "pncounter": "pncounter_adapter",
    "lwwmap": "lwwmap_adapter",
    "mvreg": "mvreg_adapter",
    "gset": "gset_adapter",
    "lwwreg": "lwwreg_adapter",
    "merklereg": "merklereg_adapter",
    "list": "list_adapter",
    "map": "map_adapter",
}

# composed adapters (delta/compose.py) resolve through _adapter_for_name,
# which special-cases them; they are CLI-selectable like the rest
CLI_ADAPTERS = sorted(ADAPTERS) + ["rcounter"]


async def _list_op_versions(storage, actor) -> list[int] | None:
    """Sorted op-file versions for one actor WITHOUT reading file bytes,
    or None when the backend cannot enumerate them (no fs directory and
    no in-memory table)."""
    ops_dir = getattr(storage, "_ops_dir", None)
    if ops_dir is not None:
        import os

        try:
            names = os.listdir(ops_dir(actor))
        except FileNotFoundError:
            return []
        return sorted(int(n) for n in names if n.isdigit())
    table = getattr(storage, "remote", None)
    ops = getattr(table, "ops", None)
    if isinstance(ops, dict):  # MemoryRemote: {actor: {version: bytes}}
        return sorted(int(v) for v in ops.get(actor, {}))
    return None


def main(argv=None) -> int:
    """CLI: ``python -m crdt_enc_tpu.tools.fsck REMOTE [--shallow]
    [--passphrase …]`` — checks a remote written with the XChaCha cryptor
    and the plain (or passphrase) key cryptor."""
    import argparse
    import asyncio
    import tempfile

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("remote", help="remote directory to verify (read-only)")
    ap.add_argument("--shallow", action="store_true",
                    help="skip decrypt/auth; structure and names only")
    ap.add_argument("--passphrase", help="passphrase-sealed key metadata")
    ap.add_argument("--obs", action="store_true",
                    help="print the fsck phase table (and append a "
                    "snapshot to CRDT_OBS_SINK if set)")
    ap.add_argument("--verify-checkpoint", metavar="LOCAL_DIR",
                    help="additionally verify LOCAL_DIR's fold checkpoint: "
                    "refold the remote up to the checkpoint cursor and "
                    "byte-compare (error row + exit 1 on divergence)")
    ap.add_argument("--adapter", default="orset", choices=CLI_ADAPTERS,
                    help="CRDT adapter for checkpoint/op decoding "
                    "(--verify-checkpoint only; default orset)")
    args = ap.parse_args(argv)

    from ..backends import (
        FsStorage,
        PassphraseKeyCryptor,
        PlainKeyCryptor,
        XChaChaCryptor,
    )

    def make_kc():
        return (
            PassphraseKeyCryptor(args.passphrase)
            if args.passphrase
            else PlainKeyCryptor()
        )

    async def go():
        with tempfile.TemporaryDirectory() as scratch:
            storage = FsStorage(scratch, args.remote)
            report = await fsck_remote(
                storage, XChaChaCryptor(), make_kc(), deep=not args.shallow
            )
            if args.verify_checkpoint:
                local = FsStorage(args.verify_checkpoint, args.remote)
                vc = await verify_checkpoint(
                    local, storage, XChaChaCryptor(), make_kc(),
                    adapter=_adapter_for_name(args.adapter.encode()),
                )
                report.issues.extend(vc.issues)
        for issue in report.issues:
            print(issue)
        print(report.summary())
        if args.obs:
            import sys

            from ..obs import sink as obs_sink

            print(trace.report(), file=sys.stderr)
            obs_sink.maybe_write("fsck", meta={"remote": args.remote})
        return 0 if report.ok else 1

    return asyncio.run(go())


if __name__ == "__main__":
    raise SystemExit(main())

"""``python -m crdt_enc_tpu.tools.analyze`` — static-analysis CLI.

Thin entry point over :mod:`crdt_enc_tpu.analysis.cli`; see
docs/static_analysis.md for the rule registry, pragma and baseline
formats.
"""

from __future__ import annotations

from ..analysis.cli import main

if __name__ == "__main__":
    raise SystemExit(main())

"""One-shot exporter to the reference implementation's remote format.

The inverse of :mod:`import_reference`: takes a replica of THIS framework
and writes a remote directory the reference (chpio/crdt-enc) can read —
for migrating back, escaping to the reference in a disaster, or feeding a
mixed deployment during a staged migration.  Layer-exact to the same
in-tree citations the importer pins (op dirs named by the actor UUID's
Display form with files from version **0**, crdt-enc-tokio/src/
lib.rs:249-257; three nested layers with NO key id in the outer layer,
crdt-enc/src/lib.rs:670-695; msgpack ``EncBox`` cipher envelope,
crdt-enc-xchacha20poly1305/src/lib.rs:59-68) and validated as the
importer's byte-level inverse by round-trip tests.

Two modes:

* **state** (default) — fold the source replica (``read_remote``), then
  write its state as synthetic op files under one fresh export actor.
  Correct for any CmRDT: applying the state's constituent ops converges
  a reference replica to the same state.  Works regardless of how much
  of the source history was compacted away.
* **log** — translate the per-actor op logs 1:1 (our version N file →
  reference version N-1), preserving actor attribution and causal
  history.  Refused when the source has compacted (a state snapshot
  exists or a log does not start at version 1): the reference's dense
  from-0 scan would silently see nothing of a shifted log, and a
  snapshot's history has no op-file form — use state mode instead.

Key boundary (same as the importer's): the reference's key metadata is
the external ``crdts`` crate's serde encoding, which is not pinned by any
in-tree source — so this tool does not fabricate reference ``meta``
files.  The operator supplies the 32-byte data key here and configures
the same key on the reference side (whose shipped key backend is an
identity stub anyway — crdt-enc-gpgme/src/lib.rs:95-98).
"""

from __future__ import annotations

import logging
import os
import secrets
import uuid as uuidm
from dataclasses import dataclass, field

from ..models import MVReg, MVRegOp
from ..utils import codec
from .import_reference import (
    KEY_LEN,
    NONCE_LEN,
    REF_CIPHER_DATA_VERSION,
    REF_CONTAINER_VERSION,
    ReferenceFormatError,
)

logger = logging.getLogger("crdt_enc_tpu.export_reference")


def seal_reference_blob(key: bytes, payload: bytes, data_version: bytes) -> bytes:
    """Seal ``payload`` exactly as the reference writes an op file: inner
    raw ``VersionBytes(data_version)`` → XChaCha20-Poly1305 → named-map
    ``EncBox`` → msgpack cipher envelope → outer raw ``VersionBytes``
    with the reference container version (and no key id)."""
    from ..backends import xchacha

    if len(key) != KEY_LEN:
        raise ReferenceFormatError(f"data key must be {KEY_LEN} bytes")
    if len(data_version) != 16:
        raise ReferenceFormatError("app data version must be a 16-byte UUID")
    inner = bytes(data_version) + bytes(payload)
    nonce = secrets.token_bytes(NONCE_LEN)
    enc_box = codec.pack(
        {"nonce": nonce, "enc_data": xchacha.seal_raw(key, nonce, inner)}
    )
    middle = codec.pack([REF_CIPHER_DATA_VERSION, enc_box])
    return REF_CONTAINER_VERSION + middle


def _ref_vclock(clock) -> dict:
    """crdts ``VClock`` named-map serde form: ``{"dots": {bin16: u64}}``."""
    return {"dots": {bytes(a): int(c) for a, c in clock.counters.items()}}


def mvreg_op_untranslator(op: MVRegOp):
    """``MVRegOp`` → the crdts v7 ``mvreg::Op { clock, val }`` named-map
    encoding (the exact form :func:`import_reference.mvreg_translator`
    parses back)."""
    return {"clock": _ref_vclock(op.clock), "val": op.value}


def mvreg_state_untranslator(state: MVReg) -> list:
    """An MVReg state is exactly its surviving ``(clock, value)`` pairs;
    each is a valid ``mvreg::Op`` — applying them all reconstructs the
    state on any replica."""
    return [
        {"clock": _ref_vclock(c), "val": v} for c, v in state.vals
    ]


@dataclass
class ExportStats:
    actors: int = 0
    op_files: int = 0
    ops: int = 0
    mode: str = "state"
    export_actor: bytes | None = None
    data_version: bytes = b""
    skipped: list = field(default_factory=list)


def _write_ref_op_file(
    dest_remote: str, actor: bytes, ref_version: int, blob: bytes
) -> None:
    d = os.path.join(dest_remote, "ops", str(uuidm.UUID(bytes=actor)))
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, str(ref_version))
    # the reference's own create_new discipline: immutable files, no
    # silent overwrite (crdt-enc-tokio lib.rs:326-346)
    with open(path, "xb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())


async def export_reference_state(
    src,
    dest_remote: str | os.PathLike,
    key: bytes,
    data_version: bytes,
    state_untranslator=mvreg_state_untranslator,
    export_actor: bytes | None = None,
) -> ExportStats:
    """Fold the source replica and write its state as ONE synthetic op
    file under a fresh export actor (reference version 0).  ``src`` is an
    opened ``Core``; the source remote is never written to."""
    dest = os.fspath(dest_remote)
    await src.read_remote()
    ref_ops = src.with_state(state_untranslator)
    actor = export_actor if export_actor is not None else uuidm.uuid4().bytes
    stats = ExportStats(
        mode="state", export_actor=actor, data_version=bytes(data_version)
    )
    if not ref_ops:
        logger.warning("source state is empty; nothing exported")
        return stats
    blob = seal_reference_blob(key, codec.pack(ref_ops), data_version)
    _write_ref_op_file(dest, actor, 0, blob)
    stats.actors = 1
    stats.op_files = 1
    stats.ops = len(ref_ops)
    return stats


async def export_reference_log(
    src,
    dest_remote: str | os.PathLike,
    key: bytes,
    data_version: bytes,
    op_untranslator=mvreg_op_untranslator,
) -> ExportStats:
    """Translate the source remote's per-actor op logs 1:1 into reference
    layout (our dense-from-1 versions → the reference's dense-from-0).

    Refuses a compacted source: a state snapshot's history has no op-file
    form, and a GC'd log starting beyond version 1 would be invisible to
    the reference's from-0 scan — silent data loss, so fail loudly and
    point at state mode.
    """
    dest = os.fspath(dest_remote)
    stats = ExportStats(mode="log", data_version=bytes(data_version))

    state_names = await src.storage.list_state_names()
    if state_names:
        raise ReferenceFormatError(
            f"source remote holds {len(state_names)} state snapshot(s); "
            "compacted history has no reference op-file form — "
            "use state mode"
        )
    actors = await src.storage.list_op_actors()
    if not actors:
        raise ReferenceFormatError("source remote has no op logs to export")
    from .fsck import _list_op_versions

    for actor in sorted(actors):
        files = await src.storage.load_ops([(actor, 1)])
        if not files:
            raise ReferenceFormatError(
                f"actor {actor.hex()}'s log does not start at version 1 "
                "(GC'd prefix?): the reference's dense from-0 scan would "
                "see none of it — use state mode"
            )
        # a mid-log hole with files beyond it would silently truncate the
        # export (load_ops scans densely and stops at the hole) — refuse,
        # exactly as the importer refuses a gapped source
        versions = await _list_op_versions(src.storage, actor)
        if versions is not None and len(versions) > len(files):
            raise ReferenceFormatError(
                f"actor {actor.hex()}'s log has a gap at version "
                f"{files[-1][1] + 1} with {len(versions) - len(files)} "
                "file(s) stranded beyond it — refusing a partial export "
                "(run tools.fsck for the damage report)"
            )
        stats.actors += 1
        for _, version, raw in files:
            # same tool↔core pairing the importer uses with dest._seal:
            # the shared wire contract lives in core.open_sealed_blob
            objs = await src._open_sealed(raw)
            ops = [src.adapter.op_from_obj(o) for o in objs]
            payload = codec.pack([op_untranslator(op) for op in ops])
            blob = seal_reference_blob(key, payload, data_version)
            _write_ref_op_file(dest, actor, version - 1, blob)
            stats.op_files += 1
            stats.ops += len(ops)
    return stats


def main(argv=None) -> int:
    """CLI: ``python -m crdt_enc_tpu.tools.export_reference SRC_LOCAL
    SRC_REMOTE DEST_REF_REMOTE --key-hex <64 hex> --data-version-uuid
    <uuid> [--mode state|log]``.  The source opens with the XChaCha
    cryptor + plain key cryptor and the MVReg adapter (the reference
    example's state type); other deployments drive the async API with
    their own adapter and untranslators."""
    import argparse
    import asyncio

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("src_local", help="source replica's local dir")
    ap.add_argument("src_remote", help="source remote directory (read-only)")
    ap.add_argument("dest_remote", help="reference remote directory to create")
    ap.add_argument(
        "--key-hex", required=True,
        help="32-byte data key for the reference deployment, hex-encoded",
    )
    ap.add_argument(
        "--data-version-uuid", required=True,
        help="app data version UUID the reference deployment expects "
        "(its OpenOptions.supported_data_versions)",
    )
    ap.add_argument("--mode", choices=("state", "log"), default="state")
    args = ap.parse_args(argv)

    from ..backends import FsStorage, PlainKeyCryptor, XChaChaCryptor
    from ..core import Core, OpenOptions, mvreg_adapter
    from ..utils.versions import DEFAULT_DATA_VERSION_1

    key = bytes.fromhex(args.key_hex)
    data_version = uuidm.UUID(args.data_version_uuid).bytes

    async def go():
        src = await Core.open(OpenOptions(
            storage=FsStorage(args.src_local, args.src_remote),
            cryptor=XChaChaCryptor(),
            key_cryptor=PlainKeyCryptor(),
            adapter=mvreg_adapter(),
            supported_data_versions=(DEFAULT_DATA_VERSION_1,),
            current_data_version=DEFAULT_DATA_VERSION_1,
            create=False,
        ))
        if args.mode == "state":
            stats = await export_reference_state(
                src, args.dest_remote, key, data_version
            )
        else:
            stats = await export_reference_log(
                src, args.dest_remote, key, data_version
            )
        print(
            f"exported {stats.ops} ops in {stats.op_files} files "
            f"({stats.mode} mode, {stats.actors} actor(s))"
        )

    asyncio.run(go())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
